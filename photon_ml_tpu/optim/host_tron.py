"""Host-driven TRON for objectives that cannot be traced into jit.

The in-jit optimizer (optim.tron.minimize_tron) compiles the whole
trust-region while_loop — impossible when each (value, gradient) or
Hessian-vector evaluation performs host IO (the streaming >RAM input
path, io/streaming.py). This variant drives the SAME math from Python:
LIBLINEAR eta/sigma trust-region rules, Steihaug truncated CG (<=20
iterations, one streamed Hv pass per step — exactly the reference's
one-cluster-aggregate-per-CG-step loop,
HessianVectorAggregator.scala:137-152 + TRON.scala:259-341), and the
shared convergence rules (Optimizer.scala:156-170).

Readback discipline (PERF_NOTES round 10): control scalars come back
BATCHED through the counted ``overlap.device_get`` seam — per CG step
one residual-norm check plus one (d·Hd, d·d, s·d, s·s) batch (the
boundary norm ‖s+αd‖ derives from those on host, so the old separate
norm pull is gone), and per outer iteration ONE batch carrying the
step/model scalars (g·s, s·r, f_new, ‖s‖, ‖g_new‖, the projection flag
and the device-computed convergence reason)."""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.optim.common import (
    BoxConstraints,
    GRADIENT_WITHIN_TOLERANCE,
    MAX_ITERATIONS,
    NOT_CONVERGED,
    OptResult,
    Tracker,
    check_convergence,
)
from photon_ml_tpu.parallel import overlap

Array = jnp.ndarray
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]

# LIBLINEAR trust-region constants (TRON.scala / tron.cpp) — identical to
# optim.tron so the two drivers walk the same iterate sequence.
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


def _truncated_cg_host(hvp, g, delta, *, max_cg: int, cg_tol_factor=0.1,
                       g_norm: Optional[float] = None):
    """Steihaug truncated CG, host-driven: each iteration costs ONE hvp
    call (= one streamed pass) plus two batched scalar fetches. Returns
    (s, r) with r = -g - H s, the tron.cpp prered trick.

    ``g_norm``: the caller's already-fetched ‖g‖ (skips a pull)."""
    if g_norm is None:
        g_norm = float(overlap.device_get(jnp.linalg.norm(g)))
    cg_tol = cg_tol_factor * g_norm
    s = jnp.zeros_like(g)
    r = -g
    d = r
    rtr = g_norm * g_norm
    for _ in range(max_cg):
        if np.sqrt(max(rtr, 0.0)) <= cg_tol:
            break
        hd = hvp(d)
        # ONE batch: curvature + the boundary-geometry scalars (the old
        # separate ‖s+αd‖ pull derives from these on host)
        dhd, dd, sd, ss = (
            float(v) for v in overlap.device_get((
                jnp.vdot(d, hd), jnp.vdot(d, d),
                jnp.vdot(s, d), jnp.vdot(s, s),
            ))
        )
        alpha = rtr / dhd if dhd > 0 else 0.0
        s_new_sq = ss + 2.0 * alpha * sd + alpha * alpha * dd
        hit = dhd <= 0 or np.sqrt(max(s_new_sq, 0.0)) >= delta
        if hit:
            # walk to the trust-region boundary and stop
            rad = np.sqrt(max(sd * sd + dd * (delta * delta - ss), 0.0))
            tau = (-sd + rad) / max(dd, 1e-30)
            s = s + tau * d
            r = r - tau * hd
            break
        s = s + alpha * d
        r = r - alpha * hd
        rtr_new = float(overlap.device_get(jnp.vdot(r, r)))
        beta = rtr_new / max(rtr, 1e-30)
        d = r + beta * d
        rtr = rtr_new
    return s, r


def minimize_tron_host(
    value_and_grad_fn: ValueAndGrad,
    hvp_fn: Callable[[Array, Array], Array],
    w0: Array,
    *,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_cg: int = 20,
    max_improvement_failures: int = 16,
    box: Optional[BoxConstraints] = None,
    hvp_factory=None,
    track_coefficients: bool = False,
) -> OptResult:
    """Trust-region Newton whose evaluations run host-side code.

    ``hvp_fn(w, d) -> H(w) @ d``; ``hvp_factory(w) -> (d -> H(w) @ d)``
    lets the caller cache the w-only pieces (margins, d2 coefficients)
    once per outer iteration — with streamed data that saves one full
    disk/cache pass per CG step. Defaults mirror TRON.scala:260-265."""
    w = jnp.asarray(w0, jnp.float32)
    if box is not None:
        w = box.project(w)
    f_dev, g = value_and_grad_fn(w)
    # one batched fetch for the initial control scalars
    f, g0_norm = (
        float(v) for v in overlap.device_get((f_dev, jnp.linalg.norm(g)))
    )
    f0 = f
    g_norm = g0_norm
    delta = g0_norm
    tracker = Tracker.create(
        max_iter + 1,
        coef_dim=w.shape[0] if track_coefficients else None,
    ).record(f, jnp.float32(g0_norm), w if track_coefficients else None)
    reason = (
        GRADIENT_WITHIN_TOLERANCE if g0_norm == 0.0 else NOT_CONVERGED
    )
    it = 0
    failures = 0
    while reason == NOT_CONVERGED:
        hvp = (
            hvp_factory(w)
            if hvp_factory is not None
            else (lambda d, _w=w: hvp_fn(_w, d))
        )
        s, r = _truncated_cg_host(
            hvp, g, delta, max_cg=max_cg, g_norm=g_norm
        )
        w_trial = w + s
        s_raw = s
        if box is not None:
            w_trial = box.project(w_trial)
            s = w_trial - w
        f_new_dev, g_new = value_and_grad_fn(w_trial)
        # the OUTER iteration's batch: every step/model control scalar
        # plus the device-computed convergence reason, in ONE fetch
        gs, s_r, f_new, snorm, g_norm_new, projected_any, reason_new = (
            overlap.device_get((
                jnp.vdot(g, s),
                jnp.vdot(s, r),
                f_new_dev,
                jnp.linalg.norm(s),
                jnp.linalg.norm(g_new),
                (
                    jnp.any(s != s_raw)
                    if box is not None else jnp.bool_(False)
                ),
                check_convergence(
                    jnp.int32(it + 1), jnp.float32(f), f_new_dev,
                    jnp.linalg.norm(g_new), jnp.float32(f0),
                    jnp.float32(g0_norm), max_iter=max_iter, tol=tol,
                ),
            ))
        )
        gs, f_new, snorm = float(gs), float(f_new), float(snorm)
        if bool(projected_any):
            # the CG residual r belongs to the UNPROJECTED step; with an
            # active box constraint the quadratic model must be re-
            # evaluated at the projected s (one extra Hv pass) or the
            # actred/prered trust-region test compares incompatible
            # models near the boundary
            prered = -(
                gs + 0.5 * float(overlap.device_get(jnp.vdot(s, hvp(s))))
            )
        else:
            prered = -0.5 * (gs - float(s_r))
        actred = f - f_new

        denom = f_new - f - gs
        alpha = _SIGMA3 if denom <= 0 else max(_SIGMA1, -0.5 * (gs / denom))
        if actred < _ETA0 * prered:
            delta = min(max(alpha, _SIGMA1) * snorm, _SIGMA2 * delta)
        elif actred < _ETA1 * prered:
            delta = max(_SIGMA1 * delta, min(alpha * snorm, _SIGMA2 * delta))
        elif actred < _ETA2 * prered:
            delta = max(_SIGMA1 * delta, min(alpha * snorm, _SIGMA3 * delta))
        else:
            delta = max(delta, min(alpha * snorm, _SIGMA3 * delta))

        accept = actred > _ETA0 * prered and np.isfinite(f_new)
        it += 1
        if accept:
            failures = 0
            g_norm = float(g_norm_new)
            reason = int(reason_new)
            w, f, g = w_trial, f_new, g_new
            tracker = tracker.record(
                f, jnp.float32(g_norm), w if track_coefficients else None
            )
        else:
            failures += 1
            if it >= max_iter or failures >= max_improvement_failures:
                reason = MAX_ITERATIONS
    return OptResult(
        coefficients=w,
        value=jnp.float32(f),
        grad_norm=jnp.linalg.norm(g),
        iterations=jnp.int32(it),
        reason=jnp.int32(reason),
        tracker=tracker,
    )
