"""Host-driven TRON for objectives that cannot be traced into jit.

The in-jit optimizer (optim.tron.minimize_tron) compiles the whole
trust-region while_loop — impossible when each (value, gradient) or
Hessian-vector evaluation performs host IO (the streaming >RAM input
path, io/streaming.py). This variant drives the SAME math from Python:
LIBLINEAR eta/sigma trust-region rules, Steihaug truncated CG (<=20
iterations, one streamed Hv pass per step — exactly the reference's
one-cluster-aggregate-per-CG-step loop,
HessianVectorAggregator.scala:137-152 + TRON.scala:259-341), and the
shared convergence rules (Optimizer.scala:156-170).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.optim.common import (
    BoxConstraints,
    GRADIENT_WITHIN_TOLERANCE,
    MAX_ITERATIONS,
    NOT_CONVERGED,
    OptResult,
    Tracker,
    check_convergence,
)

Array = jnp.ndarray
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]

# LIBLINEAR trust-region constants (TRON.scala / tron.cpp) — identical to
# optim.tron so the two drivers walk the same iterate sequence.
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


def _truncated_cg_host(hvp, g, delta, *, max_cg: int, cg_tol_factor=0.1):
    """Steihaug truncated CG, host-driven: each iteration costs ONE hvp
    call (= one streamed pass). Returns (s, r) with r = -g - H s, the
    tron.cpp prered trick."""
    cg_tol = cg_tol_factor * float(jnp.linalg.norm(g))
    s = jnp.zeros_like(g)
    r = -g
    d = r
    rtr = float(jnp.vdot(r, r))
    for _ in range(max_cg):
        if np.sqrt(rtr) <= cg_tol:
            break
        hd = hvp(d)
        dhd = float(jnp.vdot(d, hd))
        alpha = rtr / dhd if dhd > 0 else 0.0
        s_new = s + alpha * d
        hit = dhd <= 0 or float(jnp.linalg.norm(s_new)) >= delta
        if hit:
            # walk to the trust-region boundary and stop
            dd = float(jnp.vdot(d, d))
            sd = float(jnp.vdot(s, d))
            ss = float(jnp.vdot(s, s))
            rad = np.sqrt(max(sd * sd + dd * (delta * delta - ss), 0.0))
            tau = (-sd + rad) / max(dd, 1e-30)
            s = s + tau * d
            r = r - tau * hd
            break
        s = s_new
        r = r - alpha * hd
        rtr_new = float(jnp.vdot(r, r))
        beta = rtr_new / max(rtr, 1e-30)
        d = r + beta * d
        rtr = rtr_new
    return s, r


def minimize_tron_host(
    value_and_grad_fn: ValueAndGrad,
    hvp_fn: Callable[[Array, Array], Array],
    w0: Array,
    *,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_cg: int = 20,
    max_improvement_failures: int = 16,
    box: Optional[BoxConstraints] = None,
    hvp_factory=None,
    track_coefficients: bool = False,
) -> OptResult:
    """Trust-region Newton whose evaluations run host-side code.

    ``hvp_fn(w, d) -> H(w) @ d``; ``hvp_factory(w) -> (d -> H(w) @ d)``
    lets the caller cache the w-only pieces (margins, d2 coefficients)
    once per outer iteration — with streamed data that saves one full
    disk/cache pass per CG step. Defaults mirror TRON.scala:260-265."""
    w = jnp.asarray(w0, jnp.float32)
    if box is not None:
        w = box.project(w)
    f, g = value_and_grad_fn(w)
    f0 = float(f)
    g0_norm = float(jnp.linalg.norm(g))
    delta = g0_norm
    tracker = Tracker.create(
        max_iter + 1,
        coef_dim=w.shape[0] if track_coefficients else None,
    ).record(f, jnp.float32(g0_norm), w if track_coefficients else None)
    reason = (
        GRADIENT_WITHIN_TOLERANCE if g0_norm == 0.0 else NOT_CONVERGED
    )
    it = 0
    failures = 0
    while reason == NOT_CONVERGED:
        hvp = (
            hvp_factory(w)
            if hvp_factory is not None
            else (lambda d, _w=w: hvp_fn(_w, d))
        )
        s, r = _truncated_cg_host(hvp, g, delta, max_cg=max_cg)
        w_trial = w + s
        projected = False
        if box is not None:
            w_trial = box.project(w_trial)
            s_proj = w_trial - w
            projected = bool(jnp.any(s_proj != s))
            s = s_proj
        f_new, g_new = value_and_grad_fn(w_trial)
        gs = float(jnp.vdot(g, s))
        if projected:
            # the CG residual r belongs to the UNPROJECTED step; with an
            # active box constraint the quadratic model must be re-
            # evaluated at the projected s (one extra Hv pass) or the
            # actred/prered trust-region test compares incompatible
            # models near the boundary
            prered = -(gs + 0.5 * float(jnp.vdot(s, hvp(s))))
        else:
            prered = -0.5 * (gs - float(jnp.vdot(s, r)))
        actred = float(f) - float(f_new)
        snorm = float(jnp.linalg.norm(s))

        denom = float(f_new) - float(f) - gs
        alpha = _SIGMA3 if denom <= 0 else max(_SIGMA1, -0.5 * (gs / denom))
        if actred < _ETA0 * prered:
            delta = min(max(alpha, _SIGMA1) * snorm, _SIGMA2 * delta)
        elif actred < _ETA1 * prered:
            delta = max(_SIGMA1 * delta, min(alpha * snorm, _SIGMA2 * delta))
        elif actred < _ETA2 * prered:
            delta = max(_SIGMA1 * delta, min(alpha * snorm, _SIGMA3 * delta))
        else:
            delta = max(delta, min(alpha * snorm, _SIGMA3 * delta))

        accept = actred > _ETA0 * prered and np.isfinite(float(f_new))
        it += 1
        if accept:
            failures = 0
            g_norm = float(jnp.linalg.norm(g_new))
            reason = int(check_convergence(
                jnp.int32(it), f, f_new, jnp.float32(g_norm),
                jnp.float32(f0), jnp.float32(g0_norm),
                max_iter=max_iter, tol=tol,
            ))
            w, f, g = w_trial, f_new, g_new
            tracker = tracker.record(
                f, jnp.float32(g_norm), w if track_coefficients else None
            )
        else:
            failures += 1
            if it >= max_iter or failures >= max_improvement_failures:
                reason = MAX_ITERATIONS
    return OptResult(
        coefficients=w,
        value=jnp.float32(float(f)),
        grad_norm=jnp.linalg.norm(g),
        iterations=jnp.int32(it),
        reason=jnp.int32(reason),
        tracker=tracker,
    )
