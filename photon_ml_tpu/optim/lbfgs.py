"""L-BFGS and OWL-QN as single-jit ``lax.while_loop`` programs.

Reference: photon-ml .../optimization/LBFGS.scala (Breeze adapter, defaults
maxIter=100 m=10 tol=1e-7, box-constraint projection at :77) and
OWLQN.scala:43-91 (L1/elastic-net path with mutable l1RegWeight).

TPU-native design notes:
- The optimizer is *data-free*: it sees only ``value_and_grad(w)``. Run it
  under ``shard_map`` with a psum-ing objective → distributed fixed-effect
  training; ``jax.vmap`` it over a coefficient bank with batched objectives →
  millions of per-entity random-effect solves in one XLA program (the
  reference's RandomEffectCoordinate mapValues loop collapses into one
  vmapped while_loop).
- L-BFGS memory is a fixed [m, d] circular buffer; the two-loop recursion is
  a ``fori_loop`` over static m with validity masking — no dynamic shapes.
- Line search is projected Armijo backtracking plus cautious memory updates
  (skip pairs with y.s <= eps); Breeze's strong-Wolfe search is replaced by
  this while_loop-friendly equivalent.
- OWL-QN follows Andrew & Gao: pseudo-gradient, orthant-aligned direction,
  orthant projection of trial points; memory pairs use smooth gradients.
  L1 weight is a *runtime scalar* so one compilation serves a whole
  regularization path (the reference mutates `l1RegWeight` similarly).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (
    BoxConstraints,
    GRADIENT_WITHIN_TOLERANCE,
    LINE_SEARCH_STALLED,
    NOT_CONVERGED,
    OptResult,
    Tracker,
    ValueAndGrad,
    backtracking_line_search,
    check_convergence,
)

Array = jnp.ndarray


class _Memory(NamedTuple):
    s: Array  # [m, d]
    y: Array  # [m, d]
    rho: Array  # [m]
    length: Array  # int32 number of valid pairs
    ptr: Array  # int32 next write slot


def _empty_memory(m: int, d: int, dtype) -> _Memory:
    return _Memory(
        s=jnp.zeros((m, d), dtype),
        y=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        length=jnp.zeros((), jnp.int32),
        ptr=jnp.zeros((), jnp.int32),
    )


def make_global_prims(axis_name: Optional[str]):
    """(vdot, norm, vsum) primitives — mesh-global when ``axis_name`` is
    set (psum over that axis), plain otherwise. Passing these through the
    optimizer makes the SAME L-BFGS program run over feature-sharded
    coefficient blocks: vectors stay device-local, only scalars cross the
    mesh (the reduce-scatter recipe of SURVEY §2.3's coefficient
    parallelism)."""
    if axis_name is None:
        return jnp.vdot, jnp.linalg.norm, jnp.sum

    def vdot(a, b):
        return lax.psum(jnp.vdot(a, b), axis_name)

    def norm(a):
        return jnp.sqrt(jnp.maximum(vdot(a, a), 0.0))

    def vsum(a):
        return lax.psum(jnp.sum(a), axis_name)

    return vdot, norm, vsum


def _two_loop_direction(g: Array, mem: _Memory, vdot=jnp.vdot) -> Array:
    """Classic two-loop recursion over the circular buffer; returns -H~ g."""
    m = mem.s.shape[0]
    alphas = jnp.zeros((m,), g.dtype)

    def backward(i, carry):
        q, alphas = carry
        idx = jnp.mod(mem.ptr - 1 - i, m)
        valid = i < mem.length
        a = jnp.where(valid, mem.rho[idx] * vdot(mem.s[idx], q), 0.0)
        q = q - a * mem.y[idx]
        return q, alphas.at[idx].set(a)

    q, alphas = lax.fori_loop(0, m, backward, (g, alphas))

    last = jnp.mod(mem.ptr - 1, m)
    ys = vdot(mem.s[last], mem.y[last])
    yy = vdot(mem.y[last], mem.y[last])
    gamma = jnp.where(mem.length > 0, ys / jnp.maximum(yy, 1e-30), 1.0)
    r = gamma * q

    def forward(i, r):
        idx = jnp.mod(mem.ptr - mem.length + i, m)
        valid = i < mem.length
        b = jnp.where(valid, mem.rho[idx] * vdot(mem.y[idx], r), 0.0)
        return r + jnp.where(valid, alphas[idx] - b, 0.0) * mem.s[idx]

    r = lax.fori_loop(0, m, forward, r)
    return -r


def _update_memory(mem: _Memory, s: Array, y: Array, vdot=jnp.vdot) -> _Memory:
    """Cautious update: store the pair only when y.s > eps (keeps H~ PD)."""
    ys = vdot(y, s)
    ok = ys > 1e-10
    ptr = mem.ptr
    new = _Memory(
        s=mem.s.at[ptr].set(s),
        y=mem.y.at[ptr].set(y),
        rho=mem.rho.at[ptr].set(1.0 / jnp.maximum(ys, 1e-30)),
        length=jnp.minimum(mem.length + 1, mem.s.shape[0]),
        ptr=jnp.mod(ptr + 1, mem.s.shape[0]),
    )
    return jax.tree.map(lambda a, b: jnp.where(ok, a, b), new, mem)


class _LoopState(NamedTuple):
    w: Array
    f: Array
    g: Array  # smooth gradient
    mem: _Memory
    iteration: Array
    reason: Array
    tracker: Tracker


def minimize_lbfgs(
    value_and_grad_fn: ValueAndGrad,
    w0: Array,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history: int = 10,
    box: Optional[BoxConstraints] = None,
    ls_max_steps: int = 24,
    axis_name: Optional[str] = None,
    track_coefficients: bool = False,
) -> OptResult:
    """Minimize a smooth objective. jit/vmap/shard_map-safe.

    Defaults mirror LBFGS.scala:152-156 (maxIter=100, m=10, tol=1e-7).

    Under ``jax.vmap`` (the batched λ-grid path, problem.run_grid) the
    batching rule of ``lax.while_loop`` active-masks the carry per
    member: ``cond`` is this member's ``reason == NOT_CONVERGED``, so a
    converged member's whole state — coefficients, memory, tracker,
    reason — is selected UNCHANGED on every further trip and the loop
    exits when all members are done. The grid tests pin that freeze
    bitwise (test_grid_batched.py::TestFreezeSemantics); keep ``cond``
    a pure per-member predicate or the batched path loses it.

    ``axis_name``: run over a FEATURE-SHARDED coefficient block inside
    shard_map — w0 (and every state vector) is this device's block, and
    all inner products / norms psum over the axis, so the optimizer is
    numerically identical to its replicated self with fully sharded state.
    """
    vdot, norm, _ = make_global_prims(axis_name)
    project = (lambda w: box.project(w)) if box is not None else None
    w0 = w0 if project is None else project(w0)
    f0, g0 = value_and_grad_fn(w0)
    g0_norm = norm(g0)

    def cond(st: _LoopState):
        return st.reason == NOT_CONVERGED

    def body(st: _LoopState):
        d = _two_loop_direction(st.g, st.mem, vdot)
        # Fall back to steepest descent if d is not a descent direction.
        descent = vdot(d, st.g) < 0
        d = jnp.where(descent, d, -st.g)
        t0 = jnp.where(
            st.mem.length > 0,
            jnp.ones((), st.f.dtype),
            1.0 / jnp.maximum(norm(d), 1.0),
        )
        ls = backtracking_line_search(
            value_and_grad_fn, st.w, st.f, st.g, d, t0,
            max_steps=ls_max_steps, project=project, vdot=vdot,
        )
        mem = _update_memory(st.mem, ls.w - st.w, ls.g - st.g, vdot)
        it = st.iteration + 1
        g_norm = norm(ls.g)
        # A failed line search means no further progress is possible; check
        # BEFORE the function-change test (a stalled search has Δf == 0 and
        # would otherwise masquerade as convergence).
        reason = jnp.where(
            ls.ok,
            check_convergence(
                it, st.f, ls.f, g_norm, f0, g0_norm, max_iter=max_iter, tol=tol
            ),
            LINE_SEARCH_STALLED,
        ).astype(jnp.int32)
        return _LoopState(
            w=ls.w, f=ls.f, g=ls.g, mem=mem, iteration=it, reason=reason,
            tracker=st.tracker.record(
                ls.f, g_norm, ls.w if track_coefficients else None
            ),
        )

    init = _LoopState(
        w=w0,
        f=f0,
        g=g0,
        mem=_empty_memory(history, w0.shape[0], w0.dtype),
        iteration=jnp.zeros((), jnp.int32),
        reason=jnp.where(
            g0_norm == 0.0, GRADIENT_WITHIN_TOLERANCE, NOT_CONVERGED
        ).astype(jnp.int32),
        tracker=Tracker.create(
            max_iter + 1, w0.dtype,
            coef_dim=w0.shape[0] if track_coefficients else None,
        ).record(f0, g0_norm, w0 if track_coefficients else None),
    )
    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.w,
        value=final.f,
        grad_norm=norm(final.g),
        iterations=final.iteration,
        reason=final.reason,
        tracker=final.tracker,
    )


# ---------------------------------------------------------------------------
# OWL-QN
# ---------------------------------------------------------------------------


def _pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """Andrew & Gao pseudo-gradient of f(w) + l1 * ||w||_1."""
    right = g + l1
    left = g - l1
    at_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(w > 0, right, jnp.where(w < 0, left, at_zero))


def minimize_owlqn(
    value_and_grad_fn: ValueAndGrad,
    w0: Array,
    l1_weight,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history: int = 10,
    l1_mask: Optional[Array] = None,
    box: Optional[BoxConstraints] = None,
    ls_max_steps: int = 24,
    axis_name: Optional[str] = None,
    track_coefficients: bool = False,
) -> OptResult:
    """Minimize smooth(w) + l1_weight * ||w||_1 (OWL-QN).

    ``l1_weight`` is a runtime scalar — a whole elastic-net path reuses one
    compilation (the reference mutates OWLQN.l1RegWeight the same way,
    OWLQN.scala:43-91). ``l1_mask`` optionally exempts slots (the intercept)
    from the penalty. ``axis_name``: run over a feature-sharded coefficient
    block (see minimize_lbfgs) — the L1 term and pseudo-gradient are
    elementwise, so only the scalar reductions psum.

    ``box``: project every trial point into the hypercube AFTER the orthant
    projection — the reference's OWLQN subclasses LBFGS and inherits its
    line-search projection (OWLQN.scala:43-91, LBFGS.scala:77), so
    constrained elastic-net is a supported combination.
    """
    vdot, norm, vsum = make_global_prims(axis_name)
    if box is not None:
        w0 = box.project(w0)
    l1w = jnp.asarray(l1_weight, dtype=w0.dtype)
    mask = jnp.ones_like(w0) if l1_mask is None else l1_mask.astype(w0.dtype)
    l1_vec = l1w * mask

    def total(w, fsmooth):
        return fsmooth + vsum(l1_vec * jnp.abs(w))

    f0s, g0 = value_and_grad_fn(w0)
    pg0 = _pseudo_gradient(w0, g0, l1_vec)
    f0 = total(w0, f0s)
    g0_norm = norm(pg0)

    def cond(st: _LoopState):
        return st.reason == NOT_CONVERGED

    def body(st: _LoopState):
        pg = _pseudo_gradient(st.w, st.g, l1_vec)
        d = _two_loop_direction(pg, st.mem, vdot)
        # Constrain direction to the descent orthant of the pseudo-gradient.
        d = jnp.where(d * pg < 0, d, 0.0)
        orthant = jnp.where(st.w != 0, jnp.sign(st.w), jnp.sign(-pg))

        def project_orthant(w_t):
            w_t = jnp.where(jnp.sign(w_t) == orthant, w_t, 0.0)
            return w_t if box is None else box.project(w_t)

        def vg_total(w_t):
            fs, gs = value_and_grad_fn(w_t)
            return total(w_t, fs), gs  # returns SMOOTH gradient

        f_cur_total = total(st.w, st.f)
        t0 = jnp.where(
            st.mem.length > 0,
            jnp.ones((), st.f.dtype),
            1.0 / jnp.maximum(norm(d), 1.0),
        )
        ls = backtracking_line_search(
            vg_total, st.w, f_cur_total, pg, d, t0,
            max_steps=ls_max_steps, project=project_orthant, vdot=vdot,
        )
        # ls.f is the total value; recover smooth value for state/memory.
        f_smooth_new = ls.f - vsum(l1_vec * jnp.abs(ls.w))
        mem = _update_memory(st.mem, ls.w - st.w, ls.g - st.g, vdot)
        it = st.iteration + 1
        pg_new = _pseudo_gradient(ls.w, ls.g, l1_vec)
        pg_norm = norm(pg_new)
        # Stalled line search reports LINE_SEARCH_STALLED, not convergence.
        reason = jnp.where(
            ls.ok,
            check_convergence(
                it, f_cur_total, ls.f, pg_norm, f0, g0_norm,
                max_iter=max_iter, tol=tol,
            ),
            LINE_SEARCH_STALLED,
        ).astype(jnp.int32)
        return _LoopState(
            w=ls.w, f=f_smooth_new, g=ls.g, mem=mem, iteration=it,
            reason=reason, tracker=st.tracker.record(
                ls.f, pg_norm, ls.w if track_coefficients else None
            ),
        )

    init = _LoopState(
        w=w0,
        f=f0s,
        g=g0,
        mem=_empty_memory(history, w0.shape[0], w0.dtype),
        iteration=jnp.zeros((), jnp.int32),
        reason=jnp.where(
            g0_norm == 0.0, GRADIENT_WITHIN_TOLERANCE, NOT_CONVERGED
        ).astype(jnp.int32),
        tracker=Tracker.create(
            max_iter + 1, w0.dtype,
            coef_dim=w0.shape[0] if track_coefficients else None,
        ).record(f0, g0_norm, w0 if track_coefficients else None),
    )
    final = lax.while_loop(cond, body, init)
    pg_final = _pseudo_gradient(final.w, final.g, l1_vec)
    return OptResult(
        coefficients=final.w,
        value=total(final.w, final.f),
        grad_norm=norm(pg_final),
        iterations=final.iteration,
        reason=final.reason,
        tracker=final.tracker,
    )
