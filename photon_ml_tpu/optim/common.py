"""Shared optimizer machinery: convergence, line search, tracking, projection.

Reference: photon-ml .../optimization/Optimizer.scala (template method +
convergence checks at 156-170), OptimizerState.scala,
OptimizationStatesTracker.scala, OptimizationUtils (hypercube projection).

Everything is functional and statically shaped: optimizers are
``lax.while_loop`` programs whose state is a NamedTuple of arrays, so they
jit once, vmap over entity banks (the random-effect path) and run unchanged
under ``shard_map`` (the fixed-effect path, where the objective psums).

Convergence reasons mirror the reference's ``ConvergenceReason``:
  MAX_ITERATIONS         — hit the iteration budget
  FUNCTION_VALUES_WITHIN_TOLERANCE — |f_k - f_{k-1}| <= tol * |f_0|
  GRADIENT_WITHIN_TOLERANCE        — ||g_k|| <= tol * ||g_0||
(Optimizer.scala:156-170; relative-to-initial-state semantics kept exactly
so warm starts behave like `isReusingPreviousInitialState`.)
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax.numpy as jnp
from jax import lax

Array = jnp.ndarray

# Convergence reason codes (int32 so they live in jit state).
NOT_CONVERGED = 0
MAX_ITERATIONS = 1
FUNCTION_VALUES_WITHIN_TOLERANCE = 2
GRADIENT_WITHIN_TOLERANCE = 3
# A backtracking line search found no decreasing step (Breeze's
# LineSearchFailed / ObjectiveNotImproving analog) — distinct from
# hitting the iteration cap.
LINE_SEARCH_STALLED = 4

CONVERGENCE_REASON_NAMES = {
    NOT_CONVERGED: "NotConverged",
    MAX_ITERATIONS: "MaxIterations",
    FUNCTION_VALUES_WITHIN_TOLERANCE: "FunctionValuesWithinTolerance",
    GRADIENT_WITHIN_TOLERANCE: "GradientWithinTolerance",
    LINE_SEARCH_STALLED: "LineSearchStalled",
}


class BoxConstraints(NamedTuple):
    """Per-coefficient [lower, upper] box (OptimizationUtils'
    projectCoefficientsToHypercube analog). Use +-inf for unconstrained."""

    lower: Array  # [d]
    upper: Array  # [d]

    def project(self, w: Array) -> Array:
        return jnp.clip(w, self.lower, self.upper)


def project_coefficients_to_hypercube(w: Array, box: Optional[BoxConstraints]) -> Array:
    return w if box is None else box.project(w)


class Tracker(NamedTuple):
    """Per-iteration optimization trace, fixed-capacity stacked arrays.

    The TPU-native OptimizationStatesTracker: slot i holds (value, ||g||,
    elapsed-iteration marker) for iteration i; ``count`` marks the filled
    prefix. ``coefs`` (the ModelTracker analog) optionally stacks the
    coefficient vector per iteration — enabled by the optimizers'
    ``track_coefficients`` flag; None keeps the while_loop state small for
    the common case (and for vmapped entity banks).
    """

    values: Array  # [cap]
    grad_norms: Array  # [cap]
    count: Array  # int32
    coefs: Optional[Array] = None  # [cap, d] when tracking models

    @staticmethod
    def create(
        capacity: int, dtype=jnp.float32, coef_dim: Optional[int] = None
    ) -> "Tracker":
        return Tracker(
            values=jnp.zeros((capacity,), dtype),
            grad_norms=jnp.zeros((capacity,), dtype),
            count=jnp.zeros((), jnp.int32),
            coefs=(
                None
                if coef_dim is None
                else jnp.zeros((capacity, coef_dim), dtype)
            ),
        )

    def record(
        self, value: Array, grad_norm: Array, coef: Optional[Array] = None
    ) -> "Tracker":
        i = jnp.minimum(self.count, self.values.shape[0] - 1)
        return Tracker(
            values=self.values.at[i].set(value),
            grad_norms=self.grad_norms.at[i].set(grad_norm),
            count=self.count + 1,
            coefs=(
                self.coefs
                if self.coefs is None or coef is None
                else self.coefs.at[i].set(coef)
            ),
        )


class OptResult(NamedTuple):
    """Result of one optimize() call."""

    coefficients: Array
    value: Array
    grad_norm: Array
    iterations: Array  # int32
    reason: Array  # int32 convergence reason code
    tracker: Tracker

    @property
    def reason_name(self) -> str:  # host-side convenience
        return CONVERGENCE_REASON_NAMES.get(int(self.reason), "?")


def check_convergence(
    iteration: Array,
    f_prev: Array,
    f_cur: Array,
    g_norm: Array,
    f0: Array,
    g0_norm: Array,
    *,
    max_iter: int,
    tol: float,
) -> Array:
    """Return the convergence-reason code (0 if not converged).

    Mirrors Optimizer.scala:156-170: relative function-change and relative
    gradient-norm tests against the *initial* state.
    """
    reason = jnp.where(
        jnp.abs(f_cur - f_prev) <= tol * jnp.abs(f0),
        FUNCTION_VALUES_WITHIN_TOLERANCE,
        NOT_CONVERGED,
    )
    reason = jnp.where(g_norm <= tol * g0_norm, GRADIENT_WITHIN_TOLERANCE, reason)
    reason = jnp.where(
        (reason == NOT_CONVERGED) & (iteration >= max_iter), MAX_ITERATIONS, reason
    )
    return reason.astype(jnp.int32)


ValueAndGrad = Callable[[Array], Tuple[Array, Array]]


class LineSearchResult(NamedTuple):
    step: Array
    w: Array
    f: Array
    g: Array
    ok: Array  # bool: sufficient decrease achieved


def backtracking_line_search(
    vg: ValueAndGrad,
    w: Array,
    f: Array,
    g: Array,
    direction: Array,
    t0: Array,
    *,
    c1: float = 1e-4,
    shrink: float = 0.5,
    max_steps: int = 24,
    project: Optional[Callable[[Array], Array]] = None,
    vdot: Callable[[Array, Array], Array] = jnp.vdot,
) -> LineSearchResult:
    """Armijo backtracking, optionally projecting each trial point.

    ``vdot`` may be a mesh-global dot (psum over a model axis) so the same
    search runs over sharded coefficient blocks.

    The reference delegates to Breeze's StrongWolfeLineSearch; here a
    projected-backtracking search plus a cautious-update rule in the L-BFGS
    memory (skip pairs with y.s <= eps) gives the same robustness with
    while_loop-friendly control flow (no data-dependent Python branching).
    """
    proj = project if project is not None else (lambda x: x)

    def trial(t):
        w_t = proj(w + t * direction)
        f_t, g_t = vg(w_t)
        return w_t, f_t, g_t

    def armijo_ok(w_t, f_t):
        # Armijo on the projected point: f_t <= f + c1 * g.(w_t - w)
        # (for unconstrained this reduces to the usual f + c1 t g.d).
        return (f_t <= f + c1 * vdot(g, w_t - w)) & jnp.isfinite(f_t)

    # The Armijo test lives in `cond` (pure arithmetic) so each loop trip
    # costs exactly ONE objective evaluation — the accepted unit step pays
    # a single value_and_grad call, which is the dominant cost when the
    # objective psums over a mesh.
    def cond(state):
        _, w_t, f_t, _, k = state
        return (~armijo_ok(w_t, f_t)) & (k < max_steps)

    def body(state):
        t, _, _, _, k = state
        t_next = t * shrink
        w_n, f_n, g_n = trial(t_next)
        return (t_next, w_n, f_n, g_n, k + 1)

    w1, f1, g1 = trial(t0)
    t, w_t, f_t, g_t, _ = lax.while_loop(
        cond, body, (t0, w1, f1, g1, jnp.zeros((), jnp.int32))
    )
    ok = armijo_ok(w_t, f_t)
    # If the search never succeeded, keep the original point.
    w_out = jnp.where(ok, w_t, w)
    f_out = jnp.where(ok, f_t, f)
    g_out = jnp.where(ok, g_t, g)
    return LineSearchResult(step=t, w=w_out, f=f_out, g=g_out, ok=ok)
