"""TRON: trust-region Newton with truncated conjugate gradient.

Reference: photon-ml .../optimization/TRON.scala (in-tree LIBLINEAR port:
outer trust-region loop with eta/sigma update rules at 103-256, inner
truncated CG calling hessianVector per step, <=20 CG iterations, defaults
maxIter=15 tol=1e-5; improvement-failure tolerance at 69-75).

On TPU every CG step's Hessian-vector product is one fused psum-ing kernel
(photon_ml_tpu.ops.objective.GLMObjective.hessian_vector) instead of a
cluster round-trip — the reference's hottest distributed loop becomes a
while_loop of matmul+psum. The whole optimizer is one jit program and vmaps
over entity banks like L-BFGS.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (
    BoxConstraints,
    GRADIENT_WITHIN_TOLERANCE,
    MAX_ITERATIONS,
    NOT_CONVERGED,
    OptResult,
    Tracker,
    ValueAndGrad,
    check_convergence,
)

Array = jnp.ndarray

# LIBLINEAR trust-region constants (TRON.scala / tron.cpp).
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


class _CGState(NamedTuple):
    s: Array
    r: Array
    d: Array
    rtr: Array
    iters: Array
    done: Array


def _truncated_cg(
    hvp: Callable[[Array], Array],
    g: Array,
    delta: Array,
    *,
    max_cg: int,
    cg_tol_factor: float = 0.1,
    vdot=jnp.vdot,
    norm=jnp.linalg.norm,
):
    """Steihaug truncated CG: approximately solve H s = -g, ||s|| <= delta.

    Mirrors TRON.scala:259-341 (trustRegionConjugateGradientMethod).
    Returns ``(s, r)`` with r = -g - H s maintained through boundary exits,
    so the caller computes prered = -0.5*(g.s - s.r) without an extra
    Hessian-vector product (the tron.cpp trick).
    """
    cg_tol = cg_tol_factor * norm(g)

    def boundary_tau(s, d, delta):
        # tau >= 0 with ||s + tau d|| = delta
        dd = vdot(d, d)
        sd = vdot(s, d)
        ss = vdot(s, s)
        rad = jnp.sqrt(jnp.maximum(sd * sd + dd * (delta * delta - ss), 0.0))
        return (-sd + rad) / jnp.maximum(dd, 1e-30)

    def cond(st: _CGState):
        return (~st.done) & (st.iters < max_cg) & (jnp.sqrt(st.rtr) > cg_tol)

    def body(st: _CGState):
        hd = hvp(st.d)
        dhd = vdot(st.d, hd)
        # Negative curvature or radius hit: walk to the boundary and stop.
        alpha = st.rtr / jnp.where(dhd > 0, dhd, 1.0)
        s_new = st.s + alpha * st.d
        hit = (norm(s_new) >= delta) | (dhd <= 0)
        step = jnp.where(hit, boundary_tau(st.s, st.d, delta), alpha)
        s_out = st.s + step * st.d
        r_new = st.r - step * hd
        rtr_new = vdot(r_new, r_new)
        beta = rtr_new / jnp.maximum(st.rtr, 1e-30)
        d_new = r_new + beta * st.d
        return _CGState(
            s=s_out,
            r=r_new,
            d=jnp.where(hit, st.d, d_new),
            rtr=rtr_new,
            iters=st.iters + 1,
            done=st.done | hit,
        )

    r0 = -g
    init = _CGState(
        s=jnp.zeros_like(g),
        r=r0,
        d=r0,
        rtr=vdot(r0, r0),
        iters=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), bool),
    )
    final = lax.while_loop(cond, body, init)
    return final.s, final.r


class _TronState(NamedTuple):
    w: Array
    f: Array
    g: Array
    delta: Array
    iteration: Array
    reason: Array
    failures: Array  # consecutive improvement failures
    tracker: Tracker


def minimize_tron(
    value_and_grad_fn: ValueAndGrad,
    hvp_fn: Callable[[Array, Array], Array],
    w0: Array,
    *,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_cg: int = 20,
    max_improvement_failures: int = 16,
    box: Optional[BoxConstraints] = None,
    track_coefficients: bool = False,
    axis_name: Optional[str] = None,
    hvp_factory=None,
) -> OptResult:
    """Trust-region Newton. ``hvp_fn(w, d) -> H(w) @ d``.

    ``hvp_factory(w) -> (d -> H(w) @ d)``: alternative to ``hvp_fn`` that
    lets the caller compute the w-only pieces of the Hessian (margins,
    second-derivative coefficients) ONCE per outer iteration instead of
    once per CG step — the HessianVectorAggregator caching analog. When
    given, ``hvp_fn`` is ignored (pass None).

    Defaults mirror TRON.scala:260-265 (maxIter=15, tol=1e-5, <=20 CG).

    ``axis_name``: run over a FEATURE-SHARDED coefficient block inside
    shard_map — every inner product / norm (outer loop AND truncated CG)
    psums over the axis, so the optimizer is numerically identical to its
    replicated self with fully sharded state (same contract as
    minimize_lbfgs).

    Under ``jax.vmap`` (the batched λ-grid path) both while_loops — the
    outer trust-region loop and the truncated CG — are carry-masked per
    member by the batching rule, so converged members freeze bit-stable
    while stragglers iterate (see minimize_lbfgs's note; pinned by the
    grid tests). Keep the ``cond``s pure per-member predicates.
    """
    from photon_ml_tpu.optim.lbfgs import make_global_prims

    vdot, norm, _ = make_global_prims(axis_name)
    if box is not None:
        w0 = box.project(w0)
    f0, g0 = value_and_grad_fn(w0)
    g0_norm = norm(g0)

    def cond(st: _TronState):
        return st.reason == NOT_CONVERGED

    def body(st: _TronState):
        hvp_local = (
            hvp_factory(st.w)
            if hvp_factory is not None
            else (lambda d: hvp_fn(st.w, d))
        )
        s, r = _truncated_cg(
            hvp_local, st.g, st.delta, max_cg=max_cg,
            vdot=vdot, norm=norm,
        )
        w_trial = st.w + s
        if box is not None:
            w_trial = box.project(w_trial)
            s = w_trial - st.w
        f_new, g_new = value_and_grad_fn(w_trial)
        gs = vdot(st.g, s)
        # r = -g - H s from CG, so s.Hs = -s.(g + r) and
        # prered = -(g.s + 0.5 s.Hs) = -0.5 (g.s - s.r).
        prered = -0.5 * (gs - vdot(s, r))
        actred = st.f - f_new
        snorm = norm(s)

        # Step-size estimate for the radius update (tron.cpp alpha rule).
        denom = f_new - st.f - gs
        alpha = jnp.where(
            denom <= 0, _SIGMA3, jnp.maximum(_SIGMA1, -0.5 * (gs / denom))
        )
        delta = st.delta
        delta = jnp.where(
            actred < _ETA0 * prered,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * snorm, _SIGMA2 * delta),
            jnp.where(
                actred < _ETA1 * prered,
                jnp.maximum(_SIGMA1 * delta, jnp.minimum(alpha * snorm, _SIGMA2 * delta)),
                jnp.where(
                    actred < _ETA2 * prered,
                    jnp.maximum(_SIGMA1 * delta, jnp.minimum(alpha * snorm, _SIGMA3 * delta)),
                    jnp.maximum(delta, jnp.minimum(alpha * snorm, _SIGMA3 * delta)),
                ),
            ),
        )

        accept = (actred > _ETA0 * prered) & jnp.isfinite(f_new)
        w2 = jnp.where(accept, w_trial, st.w)
        f2 = jnp.where(accept, f_new, st.f)
        g2 = jnp.where(accept, g_new, st.g)
        failures = jnp.where(accept, 0, st.failures + 1).astype(jnp.int32)

        it = st.iteration + 1
        g_norm = norm(g2)
        reason = check_convergence(
            it, st.f, f2, g_norm, f0, g0_norm, max_iter=max_iter, tol=tol
        )
        # Rejected steps should not trip the function-change test.
        reason = jnp.where(
            accept, reason, jnp.where(it >= max_iter, MAX_ITERATIONS, NOT_CONVERGED)
        )
        reason = jnp.where(
            (reason == NOT_CONVERGED) & (failures >= max_improvement_failures),
            MAX_ITERATIONS,
            reason,
        ).astype(jnp.int32)
        return _TronState(
            w=w2, f=f2, g=g2, delta=delta, iteration=it, reason=reason,
            failures=failures, tracker=st.tracker.record(
                f2, g_norm, w2 if track_coefficients else None
            ),
        )

    init = _TronState(
        w=w0,
        f=f0,
        g=g0,
        delta=g0_norm,
        iteration=jnp.zeros((), jnp.int32),
        reason=jnp.where(
            g0_norm == 0.0, GRADIENT_WITHIN_TOLERANCE, NOT_CONVERGED
        ).astype(jnp.int32),
        failures=jnp.zeros((), jnp.int32),
        tracker=Tracker.create(
            max_iter + 1, w0.dtype,
            coef_dim=w0.shape[0] if track_coefficients else None,
        ).record(f0, g0_norm, w0 if track_coefficients else None),
    )
    final = lax.while_loop(cond, body, init)
    return OptResult(
        coefficients=final.w,
        value=final.f,
        grad_norm=norm(final.g),
        iterations=final.iteration,
        reason=final.reason,
        tracker=final.tracker,
    )
