"""Optimizer / regularization configuration.

Reference: photon-ml .../optimization/OptimizerType.scala,
RegularizationType.scala, RegularizationContext.scala:?-90 (lambda split
l1 = alpha*lambda, l2 = (1-alpha)*lambda),
GLMOptimizationConfiguration.scala:39-89 (string DSL
``maxIter,tol,regWeight,downSamplingRate,optimizer,regType``) and
OptimizerConfig.scala.

The TPU build uses typed dataclasses natively and keeps the CLI string
format as a parsing shim for parity.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Tuple


class OptimizerType(enum.Enum):
    LBFGS = "LBFGS"
    TRON = "TRON"

    @classmethod
    def parse(cls, s: str) -> "OptimizerType":
        return cls(s.strip().upper())


class RegularizationType(enum.Enum):
    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"

    @classmethod
    def parse(cls, s: str) -> "RegularizationType":
        return cls(s.strip().upper())


@dataclass(frozen=True)
class RegularizationContext:
    """Splits a total regularization weight into (l1, l2) parts.

    ELASTIC_NET with mixing alpha: l1 = alpha*lambda, l2 = (1-alpha)*lambda
    (RegularizationContext.scala).
    """

    reg_type: RegularizationType = RegularizationType.NONE
    elastic_net_alpha: Optional[float] = None

    def __post_init__(self):
        if self.reg_type == RegularizationType.ELASTIC_NET:
            a = self.elastic_net_alpha
            if a is None or not (0.0 <= a <= 1.0):
                raise ValueError(
                    f"ELASTIC_NET requires alpha in [0,1], got {a}"
                )
        elif self.elastic_net_alpha is not None:
            raise ValueError(
                f"alpha is only valid for ELASTIC_NET, got {self.reg_type}"
            )

    def split(self, reg_weight: float) -> Tuple[float, float]:
        """-> (l1_weight, l2_weight)."""
        t = self.reg_type
        if t == RegularizationType.NONE:
            return 0.0, 0.0
        if t == RegularizationType.L1:
            return reg_weight, 0.0
        if t == RegularizationType.L2:
            return 0.0, reg_weight
        a = self.elastic_net_alpha
        return a * reg_weight, (1.0 - a) * reg_weight

    @property
    def has_l1(self) -> bool:
        return self.reg_type in (
            RegularizationType.L1,
            RegularizationType.ELASTIC_NET,
        )


@dataclass(frozen=True)
class OptimizerConfig:
    """Optimizer budget + tolerances (OptimizerConfig.scala defaults:
    LBFGS maxIter=100/tol=1e-7, TRON maxIter=15/tol=1e-5)."""

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iter: int = 100
    tolerance: float = 1e-7
    lbfgs_history: int = 10
    tron_max_cg: int = 20

    @staticmethod
    def default_for(optimizer_type: OptimizerType) -> "OptimizerConfig":
        if optimizer_type == OptimizerType.TRON:
            return OptimizerConfig(OptimizerType.TRON, max_iter=15, tolerance=1e-5)
        return OptimizerConfig(OptimizerType.LBFGS, max_iter=100, tolerance=1e-7)


@dataclass(frozen=True)
class GLMOptimizationConfiguration:
    """One coordinate's optimization settings; parses the reference's
    CLI string ``maxIter,tol,regWeight,downSamplingRate,optimizer,regType``
    (GLMOptimizationConfiguration.scala:39-89)."""

    optimizer_config: OptimizerConfig = field(default_factory=OptimizerConfig)
    regularization: RegularizationContext = field(
        default_factory=RegularizationContext
    )
    reg_weight: float = 0.0
    down_sampling_rate: float = 1.0

    @classmethod
    def parse(cls, s: str) -> "GLMOptimizationConfiguration":
        parts = [p.strip() for p in s.split(",")]
        if len(parts) != 6:
            raise ValueError(
                "expected 'maxIter,tol,regWeight,downSamplingRate,"
                f"optimizer,regType', got {s!r}"
            )
        max_iter = int(parts[0])
        tol = float(parts[1])
        reg_weight = float(parts[2])
        rate = float(parts[3])
        opt_type = OptimizerType.parse(parts[4])
        reg_type = RegularizationType.parse(parts[5])
        if max_iter <= 0:
            raise ValueError(f"maxIter must be positive: {max_iter}")
        if tol <= 0:
            raise ValueError(f"tolerance must be positive: {tol}")
        if reg_weight < 0:
            raise ValueError(f"regWeight must be non-negative: {reg_weight}")
        if not (0 < rate <= 1):
            raise ValueError(f"downSamplingRate must be in (0,1]: {rate}")
        base = OptimizerConfig.default_for(opt_type)
        return cls(
            optimizer_config=OptimizerConfig(
                optimizer_type=opt_type, max_iter=max_iter, tolerance=tol,
                lbfgs_history=base.lbfgs_history, tron_max_cg=base.tron_max_cg,
            ),
            regularization=RegularizationContext(reg_type),
            reg_weight=reg_weight,
            down_sampling_rate=rate,
        )

    def render(self) -> str:
        oc = self.optimizer_config
        return (
            f"{oc.max_iter},{oc.tolerance},{self.reg_weight},"
            f"{self.down_sampling_rate},{oc.optimizer_type.value},"
            f"{self.regularization.reg_type.value}"
        )
