"""GLM optimization problems: optimizer + objective + model construction.

Reference: photon-ml .../optimization/GeneralizedLinearOptimizationProblem.
scala (run at :112-121, coefficient de-normalization at :89-95),
DistributedOptimizationProblem.scala (variance computation 1/(Hdiag+eps) at
:79-93, updateRegularizationWeight at :59-70, runWithSampling at :112-124)
and SingleNodeOptimizationProblem.scala.

The Distributed/SingleNode split disappears on TPU: the same problem object
runs single-chip or under shard_map depending on the objective's
``axis_name``; "single node" per-entity solves are the vmapped variant
(photon_ml_tpu.game.random_effect).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.data.sampler import down_sample
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel, create_model
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.normalization import NormalizationContext, identity_context
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.common import BoxConstraints, OptResult
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.optim.factory import make_optimizer
from photon_ml_tpu.task import TaskType

Array = jnp.ndarray

# Reference adds a small epsilon when inverting the Hessian diagonal
# (DistributedOptimizationProblem.scala:79-93).
_VARIANCE_EPSILON = 1e-12

# Jitted fit programs shared by equal problems (see _get_fit);
# FIFO-bounded so long-lived processes constructing many distinct
# problems don't pin executables forever.
_FIT_CACHE: dict = {}
_FIT_CACHE_MAX = 32


def _row_axis(mesh) -> str:
    """The mesh axis example rows shard over: the data axis when the
    mesh has one; on the unified (grid, entity) mesh rows ride the
    entity axis (the pod row convention — residual currency and the
    two-hop exchange stay entity-aligned); else the first axis."""
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, ENTITY_AXIS

    names = tuple(mesh.axis_names)
    if DATA_AXIS in names:
        return DATA_AXIS
    if ENTITY_AXIS in names:
        return ENTITY_AXIS
    return names[0]


@dataclass(frozen=True)
class GLMOptimizationProblem:
    """One (task, optimizer, regularization) training problem over a
    coefficient dimension. Reusable across a whole lambda grid: the
    regularization weight is a runtime argument."""

    task: "TaskType"
    objective: GLMObjective
    config: OptimizerConfig = field(default_factory=OptimizerConfig)
    regularization: RegularizationContext = field(
        default_factory=RegularizationContext
    )
    compute_variances: bool = False
    box: Optional[BoxConstraints] = None
    intercept_index: Optional[int] = None

    def _l1_mask(self) -> Optional[Array]:
        if self.intercept_index is None:
            return None
        return jnp.ones((self.objective.dim,)).at[self.intercept_index].set(0.0)

    # photon: entropy(id(mesh)-keyed jit-program memo; in-memory only)
    def _get_fit(self, track_models: bool, mesh=None, axis: str = "",
                 grid: bool = False, with_offsets: bool = False):
        """Jitted fit program (optionally shard_mapped over ``mesh``),
        cached so repeat `run`/`run_grid` calls skip re-tracing the
        optimizer while_loop.

        Tracing the L-BFGS while_loop over the tiled objective costs
        seconds of host time (the schedules are ~16.7M-entry pytrees);
        without caching EVERY `run` call pays it — once per lambda-grid
        entry per driver stage, and once per coordinate-descent iteration
        in GAME. Cache key: the problem's config tuple (module-level, so
        equal problems share; FIFO-bounded) with an instance-local
        fallback when a field (e.g. box-constraint arrays) is unhashable.
        reg weights stay TRACED arguments, so a whole lambda grid is one
        compile. The cache entry pins the mesh so an id-recycled mesh
        cannot alias a stale program.

        ``grid`` builds the GRID variant: ``fit(w0_bank, batch, l1_vec,
        l2_vec)`` runs ``vmap(minimize_lbfgs/owlqn/tron)`` over a [G, d]
        coefficient bank — the whole λ grid as ONE XLA program (1
        compile, 1 optimizer loop, 1 dispatch for G solves). Per-member
        convergence is active-masked by the batched ``lax.while_loop``
        itself: jax's batching rule selects each member's carry only
        while its own cond holds, so a converged λ's state
        (coefficients, reason, tracker) is frozen bit-stable while the
        loop runs on for the stragglers, and the loop exits when all G
        are done. The objective's data pass evaluates the whole bank
        fused: the scatter objective batches into one
        (n×d)@(d×G)-shaped gather/contract under vmap, and the tiled
        objective's Pallas passes swap in the flat fused grid pass via
        custom_vmap (ops.tiled_sparse._bilinear_pass_auto) — one
        schedule walk for the whole grid. With ``with_offsets`` the
        grid program takes a fifth [G, n] per-member offsets bank
        (row-sharded under a mesh) and each member solves against
        ``batch._replace(offsets=...)`` — the unified-mesh GAME trainer's
        residual currency.
        """
        import jax

        key = (
            "grid" if grid else "fit",
            with_offsets,
            self.objective,
            self.config,
            self.regularization,
            self.box,
            self.intercept_index,
            track_models,
            id(mesh) if mesh is not None else None,
            axis,
        )
        try:
            hash(key)
            cache = _FIT_CACHE
        except TypeError:
            if "_local_fit_cache" not in self.__dict__:
                object.__setattr__(self, "_local_fit_cache", {})
            cache = self._local_fit_cache
            key = (
                "grid" if grid else "fit", with_offsets, track_models,
                id(mesh) if mesh is not None else None, axis,
            )
        hit = cache.get(key)
        if hit is not None:
            return hit[0]
        optimize = make_optimizer(
            self.config,
            self.regularization,
            loss_has_hessian=self.objective.loss.has_hessian,
            box=self.box,
            l1_mask=self._l1_mask(),
            track_coefficients=track_models,
        )
        needs_hvp = self.config.optimizer_type == OptimizerType.TRON
        objective = (
            self.objective if mesh is None else self.objective.with_axis(axis)
        )

        def solve_one(w0, batch, l1, l2):
            def vg(w):
                return objective.value_and_gradient(w, batch, l2)

            def hvp(w, d):
                return objective.hessian_vector(w, d, batch, l2)

            return optimize(
                vg, w0, l1_weight=l1, hvp_fn=hvp if needs_hvp else None
            )

        if not grid:
            fit = solve_one
        elif with_offsets:

            def fit(w0_bank, batch, l1_vec, l2_vec, off_bank):
                def run_one(w0, l1, l2, off):
                    return solve_one(
                        w0, batch._replace(offsets=off), l1, l2
                    )

                return jax.vmap(run_one)(w0_bank, l1_vec, l2_vec, off_bank)

        else:

            def fit(w0_bank, batch, l1_vec, l2_vec):
                def run_one(w0, l1, l2):
                    return solve_one(w0, batch, l1, l2)

                return jax.vmap(run_one)(w0_bank, l1_vec, l2_vec)

        if mesh is not None:
            from functools import partial as _partial

            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            in_specs = (P(), P(axis), P(), P())
            if grid and with_offsets:
                in_specs = in_specs + (P(None, axis),)
            # photon: sharding(axes=[data], in=?, out=[r])
            fit = _partial(
                shard_map,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=P(),
                check_vma=False,
            )(fit)
        fit = jax.jit(fit)

        while len(cache) >= _FIT_CACHE_MAX:
            cache.pop(next(iter(cache)))
        cache[key] = (fit, mesh)
        return fit

    # photon: entropy(id(mesh)-keyed jit-program memo; in-memory only)
    def _get_hdiag(self, mesh=None, axis: str = "", grid: bool = False,
                   with_offsets: bool = False):
        """Jitted Hessian-diagonal pass (variance computation), cached
        like :meth:`_get_fit` — one builder for all four call sites
        (single/grid × replicated/sharded). Grid signature:
        ``hdiag(w_bank, batch, l2_vec[, off_bank])``."""
        import jax

        key = (
            "hdiag", grid, with_offsets, self.objective,
            id(mesh) if mesh is not None else None, axis,
        )
        try:
            hash(key)
            cache = _FIT_CACHE
        except TypeError:
            if "_local_fit_cache" not in self.__dict__:
                object.__setattr__(self, "_local_fit_cache", {})
            cache = self._local_fit_cache
            key = (
                "hdiag", grid, with_offsets,
                id(mesh) if mesh is not None else None, axis,
            )
        hit = cache.get(key)
        if hit is not None:
            return hit[0]
        objective = (
            self.objective if mesh is None else self.objective.with_axis(axis)
        )

        def one(w, batch, l2):
            return objective.hessian_diagonal(w, batch, l2)

        if not grid:
            hdiag = one
        elif with_offsets:

            def hdiag(w_bank, batch, l2_vec, off_bank):
                return jax.vmap(
                    lambda w, l2, off: one(
                        w, batch._replace(offsets=off), l2
                    )
                )(w_bank, l2_vec, off_bank)

        else:

            def hdiag(w_bank, batch, l2_vec):
                return jax.vmap(lambda w, l2: one(w, batch, l2))(
                    w_bank, l2_vec
                )

        if mesh is not None:
            from functools import partial as _partial

            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            in_specs = (P(), P(axis), P())
            if grid and with_offsets:
                in_specs = in_specs + (P(None, axis),)
            # photon: sharding(axes=[data], in=?, out=[r])
            hdiag = _partial(
                shard_map,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=P(),
                check_vma=False,
            )(hdiag)
        hdiag = jax.jit(hdiag)

        while len(cache) >= _FIT_CACHE_MAX:
            cache.pop(next(iter(cache)))
        cache[key] = (hdiag, mesh)
        return hdiag

    def run_grid(
        self,
        batch: Batch,
        reg_weights,
        initial: Optional[Array] = None,
        mesh=None,
        track_models: bool = False,
        offsets_bank: Optional[Array] = None,
    ):
        """Solve the whole λ grid in ONE batched program.

        ``reg_weights`` is the (deduplicated, ordered) λ sequence;
        ``initial`` is either a [d] vector broadcast to every member or a
        [G, d] bank. Returns ``(variances_bank, OptResult)`` where every
        OptResult field carries a leading grid axis (slice i belongs to
        reg_weights[i]); ``variances_bank`` is None unless
        ``compute_variances`` (the Hdiag pass is a second program — the
        1-compile contract covers the fit itself).

        ``offsets_bank`` ([G, n]) gives each member its OWN row offsets
        (``batch.offsets`` is ignored): the unified-mesh GAME trainer's
        per-member residual currency, where member g's fixed effect
        solves against base offsets + its own residual. Columns short of
        the (padded) batch row count are zero-extended.

        Unlike :meth:`run` driven sequentially, members do NOT warm-start
        from each other — every λ starts from ``initial`` (see the README
        "Regularization paths" discussion of when that trade wins).
        """
        weights = [float(w) for w in reg_weights]
        G = len(weights)
        splits = [self.regularization.split(w) for w in weights]
        l1_vec = jnp.asarray([s[0] for s in splits], jnp.float32)
        l2_vec = jnp.asarray([s[1] for s in splits], jnp.float32)
        if initial is None:
            w0_bank = jnp.zeros((G, self.objective.dim), jnp.float32)
        else:
            w0 = jnp.asarray(initial, jnp.float32)
            w0_bank = (
                w0 if w0.ndim == 2 else jnp.broadcast_to(
                    w0, (G, self.objective.dim)
                )
            )
        with_offsets = offsets_bank is not None

        def _pad_offsets(rows: int) -> Array:
            off = jnp.asarray(offsets_bank, jnp.float32)
            if off.shape[1] < rows:
                off = jnp.concatenate(
                    [off, jnp.zeros((off.shape[0], rows - off.shape[1]),
                                    jnp.float32)],
                    axis=1,
                )
            return off

        if mesh is None:
            from photon_ml_tpu.data.batch import SparseBatch
            from photon_ml_tpu.ops.tiled_sparse import (
                TiledGLMObjective,
                ensure_tiled,
            )

            if isinstance(self.objective, TiledGLMObjective) and isinstance(
                batch, SparseBatch
            ):
                batch = ensure_tiled(batch, self.objective.dim)
            fit = self._get_fit(
                track_models, grid=True, with_offsets=with_offsets
            )
            extras = (
                (_pad_offsets(int(batch.offsets.shape[0])),)
                if with_offsets else ()
            )
            result = fit(w0_bank, batch, l1_vec, l2_vec, *extras)
            variances = None
            if self.compute_variances:
                hdiag = self._get_hdiag(
                    grid=True, with_offsets=with_offsets
                )(result.coefficients, batch, l2_vec, *extras)
                variances = 1.0 / (hdiag + _VARIANCE_EPSILON)
            return variances, result

        from photon_ml_tpu.parallel.mesh import ensure_data_sharded

        axis = _row_axis(mesh)
        from photon_ml_tpu.ops.tiled_sparse import (
            TiledGLMObjective,
            ensure_tiled_sharded,
        )

        if isinstance(self.objective, TiledGLMObjective):
            sharded = ensure_tiled_sharded(batch, self.objective.dim, mesh, axis)
        else:
            sharded = ensure_data_sharded(batch, mesh, axis)
        fit = self._get_fit(
            track_models, mesh=mesh, axis=axis, grid=True,
            with_offsets=with_offsets,
        )
        extras = ()
        if with_offsets:
            from jax import device_put
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            off = _pad_offsets(int(sharded.offsets.shape[0]))
            extras = (
                device_put(off, NamedSharding(mesh, P(None, axis))),
            )
        result = fit(w0_bank, sharded, l1_vec, l2_vec, *extras)
        variances = None
        if self.compute_variances:
            hdiag = self._get_hdiag(
                mesh=mesh, axis=axis, grid=True, with_offsets=with_offsets
            )(result.coefficients, sharded, l2_vec, *extras)
            variances = 1.0 / (hdiag + _VARIANCE_EPSILON)
        return variances, result

    def run(
        self,
        batch: Batch,
        initial: Optional[Array] = None,
        reg_weight: float = 0.0,
        mesh=None,
        track_models: bool = False,
    ) -> Tuple[Coefficients, OptResult]:
        """Optimize and build coefficients (+ variances if requested).

        ``track_models`` stacks the coefficient vector per iteration into
        ``result.tracker.coefs`` (the ModelTracker analog backing
        validate-per-iteration, Driver.scala:329-372).

        Mirrors GeneralizedLinearOptimizationProblem.run:112-121.

        With ``mesh`` set, the ENTIRE optimize loop runs inside one
        shard_map program: the batch is row-padded and sharded over the
        mesh's "data" axis, coefficients are replicated, and the
        objective psums its partials — the treeAggregate analog
        (ValueAndGradientAggregator.scala:235-250), but with per-iteration
        reductions riding ICI instead of one cluster round-trip per Breeze
        evaluation.
        """
        w0 = (
            jnp.zeros((self.objective.dim,), jnp.float32)
            if initial is None
            else jnp.asarray(initial)
        )
        l1, l2 = self.regularization.split(reg_weight)

        if mesh is None:
            from photon_ml_tpu.data.batch import SparseBatch
            from photon_ml_tpu.ops.tiled_sparse import (
                TiledGLMObjective,
                ensure_tiled,
            )

            if isinstance(self.objective, TiledGLMObjective) and isinstance(
                batch, SparseBatch
            ):
                # identity-cached conversion: a CD loop re-wrapping the
                # same columns with fresh offsets reuses the schedules
                batch = ensure_tiled(batch, self.objective.dim)
            fit = self._get_fit(track_models)
            result = fit(w0, batch, jnp.float32(l1), jnp.float32(l2))
            variances = None
            if self.compute_variances:
                hdiag = self.objective.hessian_diagonal(
                    result.coefficients, batch, l2
                )
                variances = 1.0 / (hdiag + _VARIANCE_EPSILON)
            return Coefficients(result.coefficients, variances), result

        from photon_ml_tpu.parallel.mesh import ensure_data_sharded

        axis = _row_axis(mesh)
        from photon_ml_tpu.ops.tiled_sparse import TiledGLMObjective, ensure_tiled_sharded

        if isinstance(self.objective, TiledGLMObjective):
            # fast kernel AND mesh together: per-shard tiled schedules
            # (ValueAndGradientAggregator.scala:235-250 runs distributed at
            # full speed; so do we — no scatter fallback)
            sharded = ensure_tiled_sharded(batch, self.objective.dim, mesh, axis)
        else:
            sharded = ensure_data_sharded(batch, mesh, axis)
        _fit = self._get_fit(track_models, mesh=mesh, axis=axis)
        result = _fit(w0, sharded, jnp.float32(l1), jnp.float32(l2))

        variances = None
        if self.compute_variances:
            hdiag = self._get_hdiag(mesh=mesh, axis=axis)(
                result.coefficients, sharded, jnp.float32(l2)
            )
            variances = 1.0 / (hdiag + _VARIANCE_EPSILON)
        return Coefficients(result.coefficients, variances), result

    def run_with_sampling(
        self,
        batch: Batch,
        key: Array,
        down_sampling_rate: float,
        initial: Optional[Array] = None,
        reg_weight: float = 0.0,
        mesh=None,
        track_models: bool = False,
    ) -> Tuple[Coefficients, OptResult]:
        """Apply the task's down-sampler first (runWithSampling:112-124)."""
        if down_sampling_rate < 1.0:
            batch = down_sample(key, batch, down_sampling_rate, self.task)
        return self.run(
            batch, initial, reg_weight, mesh=mesh, track_models=track_models
        )

    def create_model(
        self,
        coefficients: Coefficients,
        norm: Optional[NormalizationContext] = None,
    ) -> GeneralizedLinearModel:
        """Build the model, de-normalizing coefficients back to the raw
        feature space (GeneralizedLinearOptimizationProblem.scala:89-95)."""
        norm = norm if norm is not None else identity_context()
        if not norm.is_identity:
            means = norm.model_to_original_space(coefficients.means)
            if self.intercept_index is not None:
                # The intercept absorbs -shift.(factor*w'); its own slot has
                # factor 1 / shift 0 by construction in build_normalization.
                means = means.at[self.intercept_index].add(
                    norm.intercept_adjustment(coefficients.means)
                )
            coefficients = Coefficients(means, coefficients.variances)
        return create_model(self.task, coefficients)


def resolve_kernel(kernel: str, batch=None) -> str:
    """Resolve the objective-kernel choice: "scatter" | "tiled" | "auto".

    "auto" picks the tiled Pallas kernel pair (7x the scatter throughput,
    PERF_NOTES.md) when running on TPU with sparse data; the kernels are
    Mosaic (TPU-only), so every other backend — CPU, GPU — gets scatter.
    """
    if kernel not in ("auto", "tiled", "scatter"):
        raise ValueError(
            f"unknown kernel {kernel!r}; expected auto | tiled | scatter"
        )
    if kernel != "auto":
        return kernel
    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.utils.backend import effective_platform

    on_tpu = effective_platform() == "tpu"
    sparse_ok = batch is None or isinstance(batch, SparseBatch)
    return "tiled" if (on_tpu and sparse_ok) else "scatter"


def create_glm_problem(
    task,
    dim: int,
    *,
    config: Optional[OptimizerConfig] = None,
    regularization: Optional[RegularizationContext] = None,
    norm: Optional[NormalizationContext] = None,
    axis_name: Optional[str] = None,
    compute_variances: bool = False,
    box: Optional[BoxConstraints] = None,
    intercept_index: Optional[int] = None,
    kernel: str = "scatter",
) -> GLMOptimizationProblem:
    """Convenience factory mirroring DistributedGLMLossFunction.create +
    DistributedOptimizationProblem.create (ModelTraining.scala:123-169).

    ``kernel`` selects the objective implementation: "scatter" (gather/
    scatter GLMObjective, any Batch type) or "tiled" (TiledGLMObjective
    over a TiledSparseBatch — see photon_ml_tpu.ops.tiled_sparse). Both
    share the same method contract, so the rest of the problem layer is
    agnostic.
    """
    norm_ctx = norm if norm is not None else identity_context()
    if kernel == "tiled":
        from photon_ml_tpu.ops.tiled_sparse import TiledGLMObjective
        from photon_ml_tpu.utils.backend import effective_platform

        # Mosaic kernels cannot lower to CPU: an explicit tiled request
        # there runs in interpret mode (slow, for tests/debugging).
        objective = TiledGLMObjective(
            loss_for_task(task), dim, norm_ctx, axis_name,
            interpret=effective_platform() == "cpu",
        )
    else:
        objective = GLMObjective(
            loss_for_task(task), dim, norm_ctx, axis_name
        )
    return GLMOptimizationProblem(
        task=task,
        objective=objective,
        config=config if config is not None else OptimizerConfig(),
        regularization=(
            regularization if regularization is not None else RegularizationContext()
        ),
        compute_variances=compute_variances,
        box=box,
        intercept_index=intercept_index,
    )
