"""Host-driven L-BFGS for objectives that cannot be traced into jit.

The in-jit optimizer (optim.lbfgs.minimize_lbfgs) compiles the whole
while_loop — correct for device-resident data, impossible when each
objective evaluation performs host IO (the streaming >RAM input path,
io/streaming.py). This variant drives the SAME math from Python:
two-loop recursion, cautious memory updates (skip pairs with y.s <= eps),
steepest-descent fallback, Armijo backtracking with the same constants,
and the reference's convergence rules (Optimizer.scala:156-170 via
optim.common.check_convergence).

Readback discipline (PERF_NOTES round 10; the round-9 baseline debt):
ONLY the scalars that gate host control flow come back, and they come
back BATCHED through the counted ``overlap.device_get`` seam — one fetch
for the direction setup, one per line-search trial (the trial's
accept/F-value pair; inherently serial, each trial depends on the
previous decision), one for the iteration's convergence batch
(y.s, ‖g‖, reason). The two-loop recursion itself stays entirely on
device — its α/ρ/γ scalars only feed arithmetic, never branches, so the
round-9 grandfathered per-pair ``float()`` pulls are simply gone.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.optim.common import (
    BoxConstraints,
    GRADIENT_WITHIN_TOLERANCE,
    LINE_SEARCH_STALLED,
    NOT_CONVERGED,
    OptResult,
    Tracker,
    check_convergence,
)
from photon_ml_tpu.parallel import overlap

Array = jnp.ndarray
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]

_MEM_EPS = 1e-10  # cautious-update threshold, matches optim.lbfgs


def _direction(g: Array, s_list: List[Array], y_list: List[Array]) -> Array:
    """Two-loop recursion over the host-side (s, y) history — all
    arithmetic on DEVICE scalars (α/ρ/γ never gate control flow, so
    nothing here needs a readback)."""
    q = -g
    alphas = []
    rhos = [1.0 / jnp.vdot(y, s) for s, y in zip(s_list, y_list)]
    for s, y, rho in zip(reversed(s_list), reversed(y_list), reversed(rhos)):
        a = rho * jnp.vdot(s, q)
        q = q - a * y
        alphas.append((a, rho))
    if s_list:
        s, y = s_list[-1], y_list[-1]
        gamma = jnp.vdot(s, y) / jnp.maximum(jnp.vdot(y, y), 1e-30)
        q = q * gamma
    for (a, rho), s, y in zip(reversed(alphas), s_list, y_list):
        b = rho * jnp.vdot(y, q)
        q = q + (a - b) * s
    return q


def minimize_lbfgs_host(
    value_and_grad_fn: ValueAndGrad,
    w0: Array,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history: int = 10,
    box: Optional[BoxConstraints] = None,
    ls_max_steps: int = 24,
    ls_c1: float = 1e-4,
    ls_shrink: float = 0.5,
    track_coefficients: bool = False,
) -> OptResult:
    """Minimize a smooth objective whose evaluations run host-side code.

    Same defaults and convergence semantics as minimize_lbfgs
    (LBFGS.scala:152-156; Optimizer.scala:156-170), including the
    hypercube projection of trial points (LBFGS.scala:77) when ``box``
    is given and the per-iteration coefficient stack (ModelTracker
    analog) when ``track_coefficients``."""
    w = jnp.asarray(w0, jnp.float32)
    if box is not None:
        w = box.project(w)
    f_dev, g = value_and_grad_fn(w)
    # one batched fetch for the initial state's control scalars
    f, g0_norm = (
        float(v) for v in overlap.device_get((f_dev, jnp.linalg.norm(g)))
    )
    f0 = f
    g_norm = g0_norm
    tracker = Tracker.create(
        max_iter + 1,
        coef_dim=w.shape[0] if track_coefficients else None,
    ).record(f, g0_norm, w if track_coefficients else None)

    s_list: List[Array] = []
    y_list: List[Array] = []
    reason = (
        GRADIENT_WITHIN_TOLERANCE if g0_norm == 0.0 else NOT_CONVERGED
    )
    it = 0
    while reason == NOT_CONVERGED:
        d = _direction(g, s_list, y_list)
        # ONE fetch for the direction's control scalars (descent test +
        # the Armijo slope + the first-step scaling norm)
        gd, d_norm = (
            float(v) for v in overlap.device_get(
                (jnp.vdot(d, g), jnp.linalg.norm(d))
            )
        )
        if gd >= 0:  # not a descent direction: steepest-descent fallback
            d = -g
            gd = -(g_norm * g_norm)
            d_norm = g_norm
        t = 1.0 if s_list else 1.0 / max(d_norm, 1.0)
        ok = False
        f_new, g_new, w_new = f, g, w
        for _ in range(ls_max_steps):
            w_t = w + t * d
            if box is not None:
                w_t = box.project(w_t)
            f_t, g_t = value_and_grad_fn(w_t)
            # one fetch per trial: the Armijo accept flag and the trial
            # value together (the decision is inherently sequential —
            # each trial's step size depends on the previous verdict)
            ok_t, f_t_host = overlap.device_get((
                (f_t <= f + ls_c1 * t * gd) & jnp.isfinite(f_t), f_t,
            ))
            if bool(ok_t):
                ok = True
                w_new, f_new, g_new = w_t, float(f_t_host), g_t
                break
            t *= ls_shrink
        it += 1
        if ok:
            s = w_new - w
            y = g_new - g
            # the iteration's convergence batch: memory-update gate,
            # gradient norm and the convergence reason in ONE fetch
            ys, g_norm_new, reason_new = overlap.device_get((
                jnp.vdot(y, s),
                jnp.linalg.norm(g_new),
                check_convergence(
                    jnp.int32(it), jnp.float32(f), jnp.float32(f_new),
                    jnp.linalg.norm(g_new), jnp.float32(f0),
                    jnp.float32(g0_norm), max_iter=max_iter, tol=tol,
                ),
            ))
            if float(ys) > _MEM_EPS:  # cautious update
                s_list.append(s)
                y_list.append(y)
                if len(s_list) > history:
                    s_list.pop(0)
                    y_list.pop(0)
            g_norm = float(g_norm_new)
            reason = int(reason_new)
            w, f, g = w_new, f_new, g_new
            tracker = tracker.record(
                f, g_norm, w if track_coefficients else None
            )
        else:
            # stalled line search: no decreasing step exists from here —
            # report it as such, not as an iteration-cap stop
            reason = LINE_SEARCH_STALLED
    return OptResult(
        coefficients=w,
        value=jnp.float32(f),
        grad_norm=jnp.linalg.norm(g),
        iterations=jnp.int32(it),
        reason=jnp.int32(reason),
        tracker=tracker,
    )


def minimize_owlqn_host(
    value_and_grad_fn: ValueAndGrad,
    w0: Array,
    l1_weight,
    *,
    max_iter: int = 100,
    tol: float = 1e-7,
    history: int = 10,
    l1_mask: Optional[Array] = None,
    box: Optional[BoxConstraints] = None,
    ls_max_steps: int = 24,
    ls_c1: float = 1e-4,
    ls_shrink: float = 0.5,
    track_coefficients: bool = False,
) -> OptResult:
    """Host-driven OWL-QN: minimize smooth(w) + l1 * ||w||_1 where each
    smooth evaluation runs host-side code (the streaming >RAM path's
    elastic-net). Same Andrew & Gao rules as optim.lbfgs.minimize_owlqn —
    pseudo-gradient, orthant-constrained direction, orthant projection of
    trial points, memory pairs on SMOOTH gradients — driven from Python
    like minimize_lbfgs_host, with the same batched-fetch discipline.
    ``value_and_grad_fn`` returns the SMOOTH (value, gradient)."""
    from photon_ml_tpu.optim.lbfgs import _pseudo_gradient

    w = jnp.asarray(w0, jnp.float32)
    if box is not None:
        w = box.project(w)
    l1_vec = jnp.float32(l1_weight) * (
        jnp.ones_like(w) if l1_mask is None else jnp.asarray(l1_mask)
    )

    def total_dev(w_t, f_smooth):
        return f_smooth + jnp.sum(l1_vec * jnp.abs(w_t))

    f_s, g = value_and_grad_fn(w)
    pg = _pseudo_gradient(w, g, l1_vec)
    # one batched fetch for the initial control scalars
    f_tot, g0_norm = (
        float(v) for v in overlap.device_get(
            (total_dev(w, f_s), jnp.linalg.norm(pg))
        )
    )
    f0 = f_tot
    pg_norm = g0_norm
    tracker = Tracker.create(
        max_iter + 1,
        coef_dim=w.shape[0] if track_coefficients else None,
    ).record(
        jnp.float32(f_tot), jnp.float32(g0_norm),
        w if track_coefficients else None,
    )

    s_list: List[Array] = []
    y_list: List[Array] = []
    reason = (
        GRADIENT_WITHIN_TOLERANCE if g0_norm == 0.0 else NOT_CONVERGED
    )
    it = 0
    while reason == NOT_CONVERGED:
        pg = _pseudo_gradient(w, g, l1_vec)
        d = _direction(pg, s_list, y_list)
        # constrain to the descent orthant of the pseudo-gradient
        d = jnp.where(d * pg < 0, d, 0.0)
        # ONE fetch for the direction's control scalars
        dpg, d_norm = (
            float(v) for v in overlap.device_get(
                (jnp.vdot(d, pg), jnp.linalg.norm(d))
            )
        )
        if dpg >= 0:
            d = -pg
            d_norm = pg_norm
        orthant = jnp.where(w != 0, jnp.sign(w), jnp.sign(-pg))
        t = 1.0 if s_list else 1.0 / max(d_norm, 1.0)
        ok = False
        w_new, f_new_tot, g_new = w, f_tot, g
        for _ in range(ls_max_steps):
            w_t = jnp.where(jnp.sign(w + t * d) == orthant, w + t * d, 0.0)
            if box is not None:
                # hypercube projection AFTER the orthant projection, the
                # inherited LBFGS.scala:77 semantics (same as the in-jit
                # minimize_owlqn)
                w_t = box.project(w_t)
            f_t_s, g_t = value_and_grad_fn(w_t)
            f_t_tot_dev = total_dev(w_t, f_t_s)
            # Armijo on the projected point against the pseudo-gradient:
            # one fetch per trial (flag + total value together)
            ok_t, f_t_tot = overlap.device_get((
                (f_t_tot_dev <= f_tot + ls_c1 * jnp.vdot(pg, w_t - w))
                & jnp.isfinite(f_t_tot_dev),
                f_t_tot_dev,
            ))
            if bool(ok_t) and np.isfinite(float(f_t_tot)):
                ok = True
                w_new, f_new_tot, g_new = w_t, float(f_t_tot), g_t
                break
            t *= ls_shrink
        it += 1
        if ok:
            s = w_new - w
            y = g_new - g  # smooth gradients, per Andrew & Gao
            pg_new = _pseudo_gradient(w_new, g_new, l1_vec)
            # the iteration's convergence batch in ONE fetch
            ys, pg_norm_new, reason_new = overlap.device_get((
                jnp.vdot(y, s),
                jnp.linalg.norm(pg_new),
                check_convergence(
                    jnp.int32(it), jnp.float32(f_tot),
                    jnp.float32(f_new_tot), jnp.linalg.norm(pg_new),
                    jnp.float32(f0), jnp.float32(g0_norm),
                    max_iter=max_iter, tol=tol,
                ),
            ))
            if float(ys) > _MEM_EPS:
                s_list.append(s)
                y_list.append(y)
                if len(s_list) > history:
                    s_list.pop(0)
                    y_list.pop(0)
            pg_norm = float(pg_norm_new)
            reason = int(reason_new)
            w, f_tot, g = w_new, f_new_tot, g_new
            tracker = tracker.record(
                jnp.float32(f_tot), jnp.float32(pg_norm),
                w if track_coefficients else None,
            )
        else:
            reason = LINE_SEARCH_STALLED
    return OptResult(
        coefficients=w,
        value=jnp.float32(f_tot),
        grad_norm=jnp.linalg.norm(_pseudo_gradient(w, g, l1_vec)),
        iterations=jnp.int32(it),
        reason=jnp.int32(reason),
        tracker=tracker,
    )
