"""Optimizer selection rules.

Reference: photon-ml .../optimization/OptimizerFactory.scala:49-86 —
(LBFGS, L1/ELASTIC_NET) -> OWLQN; (LBFGS, L2/NONE) -> LBFGS;
(TRON, L2/NONE) -> TRON; TRON + any L1 rejected. Additionally the
smoothed-hinge loss has no Hessian, so TRON is rejected for it
(Params.validate in the reference, Params.scala:200-222).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from photon_ml_tpu.optim.common import BoxConstraints, OptResult, ValueAndGrad
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
)
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs, minimize_owlqn
from photon_ml_tpu.optim.tron import minimize_tron

Array = jnp.ndarray


def validate_optimizer_choice(
    config: OptimizerConfig,
    regularization: RegularizationContext,
    *,
    loss_has_hessian: bool = True,
) -> None:
    if config.optimizer_type == OptimizerType.TRON:
        if regularization.has_l1:
            raise ValueError(
                "TRON does not support L1/ELASTIC_NET regularization "
                "(OptimizerFactory.scala:49-86)"
            )
        if not loss_has_hessian:
            raise ValueError(
                "TRON requires a twice-differentiable loss; the smoothed "
                "hinge loss is only once-differentiable"
            )


def make_optimizer(
    config: OptimizerConfig,
    regularization: RegularizationContext,
    *,
    loss_has_hessian: bool = True,
    box: Optional[BoxConstraints] = None,
    l1_mask: Optional[Array] = None,
    track_coefficients: bool = False,
) -> Callable[..., OptResult]:
    """Build ``optimize(value_and_grad_fn, w0, l1_weight=0.0, hvp_fn=None)``.

    The returned callable has a uniform signature across LBFGS/OWLQN/TRON so
    problem layers stay optimizer-agnostic; l1/l2 weights are runtime values
    (one compilation per lambda-grid).
    """
    validate_optimizer_choice(config, regularization, loss_has_hessian=loss_has_hessian)
    use_owlqn = regularization.has_l1

    def optimize(
        value_and_grad_fn: ValueAndGrad,
        w0: Array,
        *,
        l1_weight=0.0,
        hvp_fn=None,
    ) -> OptResult:
        if config.optimizer_type == OptimizerType.TRON:
            if hvp_fn is None:
                raise ValueError("TRON requires hvp_fn")
            return minimize_tron(
                value_and_grad_fn,
                hvp_fn,
                w0,
                max_iter=config.max_iter,
                tol=config.tolerance,
                max_cg=config.tron_max_cg,
                box=box,
                track_coefficients=track_coefficients,
            )
        if use_owlqn:
            return minimize_owlqn(
                value_and_grad_fn,
                w0,
                l1_weight,
                max_iter=config.max_iter,
                tol=config.tolerance,
                history=config.lbfgs_history,
                l1_mask=l1_mask,
                box=box,
                track_coefficients=track_coefficients,
            )
        return minimize_lbfgs(
            value_and_grad_fn,
            w0,
            max_iter=config.max_iter,
            tol=config.tolerance,
            history=config.lbfgs_history,
            box=box,
            track_coefficients=track_coefficients,
        )

    return optimize
