"""Optimizers: L-BFGS / OWL-QN / TRON as jit-once, vmap-able while_loop
programs. See individual modules for reference citations."""

from photon_ml_tpu.optim.common import (
    BoxConstraints,
    CONVERGENCE_REASON_NAMES,
    FUNCTION_VALUES_WITHIN_TOLERANCE,
    GRADIENT_WITHIN_TOLERANCE,
    LINE_SEARCH_STALLED,
    MAX_ITERATIONS,
    NOT_CONVERGED,
    OptResult,
    Tracker,
    project_coefficients_to_hypercube,
)
from photon_ml_tpu.optim.config import (
    GLMOptimizationConfiguration,
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optim.factory import make_optimizer, validate_optimizer_choice
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs, minimize_owlqn
from photon_ml_tpu.optim.tron import minimize_tron

__all__ = [
    "BoxConstraints",
    "CONVERGENCE_REASON_NAMES",
    "FUNCTION_VALUES_WITHIN_TOLERANCE",
    "GRADIENT_WITHIN_TOLERANCE",
    "LINE_SEARCH_STALLED",
    "MAX_ITERATIONS",
    "NOT_CONVERGED",
    "OptResult",
    "Tracker",
    "project_coefficients_to_hypercube",
    "GLMOptimizationConfiguration",
    "OptimizerConfig",
    "OptimizerType",
    "RegularizationContext",
    "RegularizationType",
    "make_optimizer",
    "validate_optimizer_choice",
    "minimize_lbfgs",
    "minimize_owlqn",
    "minimize_tron",
]
