"""Input data validation.

Reference: photon-ml .../data/DataValidators.scala:139 — per-task row
validators (finite features/offsets/labels, binary labels for
classification, non-negative labels for Poisson) run at
``VALIDATE_FULL`` / ``VALIDATE_SAMPLE`` / ``VALIDATE_DISABLED`` levels
(sanity checks fail the job with a summary of violations).

Device-side: each check is a vectorized reduction over the batch; the
driver raises with counts instead of per-row messages.
"""

from __future__ import annotations

import enum
from typing import Dict

import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch, SparseBatch
from photon_ml_tpu.task import TaskType

Array = jnp.ndarray


class DataValidationType(enum.Enum):
    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"

    @classmethod
    def parse(cls, s: str) -> "DataValidationType":
        return cls(s.strip().upper())


class DataValidationError(ValueError):
    pass


def _sample(batch: Batch, fraction: float = 0.1) -> Batch:
    """Deterministic head-sample (the reference samples a fraction for
    VALIDATE_SAMPLE; determinism matters more than randomness here)."""
    n = max(8, int(batch.weights.shape[0] * fraction))
    import jax

    return jax.tree.map(lambda a: a[:n], batch)


def validation_failures(batch: Batch, task: TaskType) -> Dict[str, int]:
    """-> {check name: violation count}, empty when clean."""
    real = batch.weights > 0
    failures: Dict[str, int] = {}

    if isinstance(batch, SparseBatch):
        row_bad_features = jnp.any(~jnp.isfinite(batch.values), axis=-1)
    else:
        row_bad_features = jnp.any(~jnp.isfinite(batch.features), axis=-1)
    checks = {
        "features_finite": row_bad_features,
        "offsets_finite": ~jnp.isfinite(batch.offsets),
        "labels_finite": ~jnp.isfinite(batch.labels),
        "weights_finite": ~jnp.isfinite(batch.weights),
    }
    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        checks["labels_binary"] = ~(
            (batch.labels == 0.0) | (batch.labels == 1.0)
        )
    if task == TaskType.POISSON_REGRESSION:
        checks["labels_non_negative"] = batch.labels < 0
    from photon_ml_tpu.parallel import overlap

    # ONE batched counted fetch for every check's count — was one
    # synchronous int() readback per check (PL001)
    counts = overlap.device_get(
        jnp.stack([jnp.sum(bad & real) for bad in checks.values()])
    )
    for name, count in zip(checks, counts):
        if int(count):
            failures[name] = int(count)
    return failures


def sanity_check_data(
    batch: Batch,
    task: TaskType,
    level: DataValidationType = DataValidationType.VALIDATE_FULL,
) -> None:
    """Raise DataValidationError listing violated checks
    (DataValidators.sanityCheckData)."""
    if level == DataValidationType.VALIDATE_DISABLED:
        return
    if level == DataValidationType.VALIDATE_SAMPLE:
        batch = _sample(batch)
    failures = validation_failures(batch, task)
    if failures:
        desc = ", ".join(f"{k}: {v} rows" for k, v in sorted(failures.items()))
        raise DataValidationError(f"input data failed validation ({desc})")
