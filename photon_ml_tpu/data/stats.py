"""Per-feature summary statistics, computed on device from padded batches.

Reference: photon-ml .../stat/BasicStatistics.scala:42 (wraps MLlib
Statistics.colStats) and BasicStatisticalSummary.scala:80 (mean/variance/
count/numNonzeros/max/min/normL1/normL2/meanAbs with NaN-variance repair at
:94-120). These feed NormalizationContext factories and the feature
summarization output.

Sparse batches accumulate with scatter-adds over (row, nnz) pairs; weights
gate padding rows. Unweighted counts follow the reference (MLlib colStats
is unweighted; weights only enter training objectives).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch, SparseBatch

Array = jnp.ndarray


class BasicStatisticalSummary(NamedTuple):
    mean: Array  # [d]
    variance: Array  # [d]
    count: Array  # scalar: number of (real) examples
    num_nonzeros: Array  # [d]
    max: Array  # [d]
    min: Array  # [d]
    norm_l1: Array  # [d]
    norm_l2: Array  # [d]
    mean_abs: Array  # [d]

    @property
    def max_magnitude(self) -> Array:
        return jnp.maximum(jnp.abs(self.max), jnp.abs(self.min))

    @property
    def std(self) -> Array:
        return jnp.sqrt(self.variance)


def sparse_moments(batch: SparseBatch, dim: int):
    """Accumulable raw moments of one sparse batch: (n, s1, s2, l1, nnz,
    mx, mn) with mx/mn over NONZERO entries only (+-inf when untouched).
    Chunked/streaming summaries sum the first five and max/min the last
    two across chunks, then call :func:`finalize_summary` ONCE — the
    implicit-zero fold needs the global n and nnz."""
    real = (batch.weights > 0).astype(jnp.float32)
    n = jnp.sum(real)
    flat_ix = batch.indices.reshape(-1)
    row_real = jnp.repeat(real, batch.indices.shape[1])
    v = batch.values.reshape(-1) * row_real
    nz = ((batch.values.reshape(-1) != 0) & (row_real > 0)).astype(jnp.float32)
    s1 = jnp.zeros((dim,), jnp.float32).at[flat_ix].add(v)
    s2 = jnp.zeros((dim,), jnp.float32).at[flat_ix].add(v * v)
    l1 = jnp.zeros((dim,), jnp.float32).at[flat_ix].add(jnp.abs(v))
    nnz = jnp.zeros((dim,), jnp.float32).at[flat_ix].add(nz)
    # Per-feature max/min over NONZERO entries (padding slots carry
    # index 0 / value 0 and must not pollute feature 0).
    big = jnp.float32(jnp.inf)
    nonzero_slot = (row_real > 0) & (batch.values.reshape(-1) != 0)
    mx = jnp.full((dim,), -big).at[flat_ix].max(
        jnp.where(nonzero_slot, batch.values.reshape(-1), -big)
    )
    mn = jnp.full((dim,), big).at[flat_ix].min(
        jnp.where(nonzero_slot, batch.values.reshape(-1), big)
    )
    return n, s1, s2, l1, nnz, mx, mn


def finalize_summary(n, s1, s2, l1, nnz, mx, mn) -> BasicStatisticalSummary:
    """Raw (possibly chunk-accumulated) moments -> summary, with the
    implicit-zero fold (zeros — explicit or implicit — enter max/min via
    the nnz < n test, contributing the same 0) and the NaN-variance
    repair of BasicStatisticalSummary.scala:94-120."""
    has_implicit_zero = nnz < n
    mx = jnp.where(has_implicit_zero, jnp.maximum(mx, 0.0), mx)
    mn = jnp.where(has_implicit_zero, jnp.minimum(mn, 0.0), mn)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
    mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
    safe_n = jnp.maximum(n, 1.0)
    mean = s1 / safe_n
    var = (s2 - safe_n * mean * mean) / jnp.maximum(safe_n - 1.0, 1.0)
    var = jnp.where(jnp.isfinite(var) & (var >= 0), var, 1.0)
    return BasicStatisticalSummary(
        mean=mean,
        variance=var,
        count=n,
        num_nonzeros=nnz,
        max=mx,
        min=mn,
        norm_l1=l1,
        norm_l2=jnp.sqrt(s2),
        mean_abs=l1 / safe_n,
    )


def compute_summary(batch: Batch, dim: int) -> BasicStatisticalSummary:
    """colStats analog. Implicit zeros count toward mean/variance/min/max
    exactly as in MLlib's sparse colStats."""
    real = (batch.weights > 0).astype(jnp.float32)
    n = jnp.sum(real)

    if isinstance(batch, SparseBatch):
        return finalize_summary(*sparse_moments(batch, dim))
    else:
        f = batch.features * real[:, None]
        s1 = jnp.sum(f, axis=0)
        s2 = jnp.sum(f * f, axis=0)
        l1 = jnp.sum(jnp.abs(f), axis=0)
        nnz = jnp.sum((f != 0).astype(jnp.float32), axis=0)
        masked_max = jnp.where(real[:, None] > 0, batch.features, -jnp.inf)
        masked_min = jnp.where(real[:, None] > 0, batch.features, jnp.inf)
        mx = jnp.max(masked_max, axis=0)
        mn = jnp.min(masked_min, axis=0)
        mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
        mn = jnp.where(jnp.isfinite(mn), mn, 0.0)

    safe_n = jnp.maximum(n, 1.0)
    mean = s1 / safe_n
    # Unbiased variance with NaN/negative repair (BasicStatisticalSummary
    # :94-120 replaces pathological variances with 1.0).
    var = (s2 - safe_n * mean * mean) / jnp.maximum(safe_n - 1.0, 1.0)
    var = jnp.where(jnp.isfinite(var) & (var >= 0), var, 1.0)
    return BasicStatisticalSummary(
        mean=mean,
        variance=var,
        count=n,
        num_nonzeros=nnz,
        max=mx,
        min=mn,
        norm_l1=l1,
        norm_l2=jnp.sqrt(s2),
        mean_abs=l1 / safe_n,
    )
