"""Down-samplers as on-device stateless-RNG weight masking.

Reference: photon-ml .../sampler/DownSampler.scala,
BinaryClassificationDownSampler.scala:89-109 (negative-only down-sampling
with weight rescale 1/rate), DefaultDownSampler.scala (uniform sampling for
regression tasks).

Instead of materializing a smaller RDD, rows are masked in place: a dropped
row gets weight 0 (padding semantics — contributes nothing to any
reduction) and kept rows get their weight rescaled by 1/rate so the
objective stays an unbiased estimate. Shapes stay static — no recompilation,
and the mask composes with sharding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.task import TaskType

Array = jnp.ndarray


def default_down_sample(key: Array, batch: Batch, rate) -> Batch:
    """Uniform row down-sampling with 1/rate weight rescale."""
    keep = jax.random.bernoulli(key, rate, batch.weights.shape)
    new_w = jnp.where(keep, batch.weights / rate, 0.0)
    return batch._replace(weights=new_w)


def binary_classification_down_sample(key: Array, batch: Batch, rate) -> Batch:
    """Keep all positives; keep negatives with probability ``rate`` and
    rescale their weight by 1/rate (BinaryClassificationDownSampler)."""
    keep_draw = jax.random.bernoulli(key, rate, batch.weights.shape)
    is_positive = batch.labels > 0.5
    new_w = jnp.where(
        is_positive,
        batch.weights,
        jnp.where(keep_draw, batch.weights / rate, 0.0),
    )
    return batch._replace(weights=new_w)


def down_sample(key: Array, batch: Batch, rate, task: TaskType) -> Batch:
    """Task-dispatching sampler (DownSampler factory semantics)."""
    if task == TaskType.LOGISTIC_REGRESSION or task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        return binary_classification_down_sample(key, batch, rate)
    return default_down_sample(key, batch, rate)
