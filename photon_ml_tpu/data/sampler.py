"""Down-samplers as on-device stateless-RNG weight masking.

Reference: photon-ml .../sampler/DownSampler.scala,
BinaryClassificationDownSampler.scala:89-109 (negative-only down-sampling
with weight rescale 1/rate), DefaultDownSampler.scala (uniform sampling for
regression tasks).

Instead of materializing a smaller RDD, rows are masked in place: a dropped
row gets weight 0 (padding semantics — contributes nothing to any
reduction) and kept rows get their weight rescaled by 1/rate so the
objective stays an unbiased estimate. Shapes stay static — no recompilation,
and the mask composes with sharding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.task import TaskType

Array = jnp.ndarray


def default_down_sample(key: Array, batch: Batch, rate) -> Batch:
    """Uniform row down-sampling with 1/rate weight rescale."""
    return batch._replace(
        weights=_default_weights(key, batch.weights, rate)
    )


def binary_classification_down_sample(key: Array, batch: Batch, rate) -> Batch:
    """Keep all positives; keep negatives with probability ``rate`` and
    rescale their weight by 1/rate (BinaryClassificationDownSampler)."""
    return batch._replace(
        weights=_binary_weights(key, batch.labels, batch.weights, rate)
    )


def _default_weights(key: Array, weights: Array, rate) -> Array:
    keep = jax.random.bernoulli(key, rate, weights.shape)
    return jnp.where(keep, weights / rate, 0.0)


def _binary_weights(key: Array, labels: Array, weights: Array, rate) -> Array:
    keep_draw = jax.random.bernoulli(key, rate, weights.shape)
    is_positive = labels > 0.5
    return jnp.where(
        is_positive,
        weights,
        jnp.where(keep_draw, weights / rate, 0.0),
    )


def down_sample_weights(
    key: Array, labels: Array, weights: Array, rate, task: TaskType
) -> Array:
    """The samplers' WEIGHT transform alone: [n] -> [n], identical draws
    to :func:`down_sample` for the same key/shape. The feature-sharded
    fixed effect re-weights its cached sharded layout with this (the
    mask is a traced argument — the layout, schedules and compiled fit
    survive every draw), so sampled-sharded reproduces sampled-replicated
    bit-for-bit on the sampling side."""
    if task in (
        TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM
    ):
        return _binary_weights(key, labels, weights, rate)
    return _default_weights(key, weights, rate)


def down_sample(key: Array, batch: Batch, rate, task: TaskType) -> Batch:
    """Task-dispatching sampler (DownSampler factory semantics)."""
    return batch._replace(
        weights=down_sample_weights(
            key, batch.labels, batch.weights, rate, task
        )
    )
