"""Statically-shaped example batches — the TPU-native `RDD[LabeledPoint]`.

Reference data model: photon-ml .../data/LabeledPoint.scala (label, Breeze
sparse/dense features, offset, weight; margin = features . coef + offset).

On TPU everything must be static-shape, so a batch of sparse examples is a
padded gather-format block ("padded COO rows", ELL-like):

- ``indices[n, k]`` int32 — feature ids per row, padded with 0
- ``values[n, k]`` float — feature values per row, padded with 0.0
  (a padded slot contributes ``0.0 * w[0] = 0`` to every reduction)
- ``labels/offsets/weights[n]`` — padded ROWS carry ``weight == 0``, which
  zeroes their contribution to loss/gradient/Hessian and to weighted metrics.

Dense batches (small feature dims, MF latent factors) use a plain matrix and
ride the MXU.

Both are NamedTuples, hence pytrees: they jit, vmap, shard (batch axis = axis
0) and donate cleanly.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np
import jax.numpy as jnp

Array = jnp.ndarray


class SparseBatch(NamedTuple):
    """Padded sparse example block. Row i: sum_j values[i,j] * w[indices[i,j]]."""

    indices: Array  # int32 [n, k]
    values: Array  # float  [n, k]
    labels: Array  # float  [n]
    offsets: Array  # float [n]
    weights: Array  # float [n] ; 0 for padding rows

    @property
    def num_rows(self) -> int:
        return self.indices.shape[0]

    @property
    def nnz_per_row(self) -> int:
        return self.indices.shape[1]


class DenseBatch(NamedTuple):
    """Dense example block. Row i: features[i] . w."""

    features: Array  # [n, d]
    labels: Array
    offsets: Array
    weights: Array

    @property
    def num_rows(self) -> int:
        return self.features.shape[0]


Batch = Union[SparseBatch, DenseBatch]


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def make_sparse_batch(
    rows: Sequence[Tuple[Sequence[int], Sequence[float]]],
    labels: Sequence[float],
    offsets: Optional[Sequence[float]] = None,
    weights: Optional[Sequence[float]] = None,
    *,
    pad_rows_to: int = 8,
    pad_nnz_to: int = 8,
    max_nnz: Optional[int] = None,
    dtype=np.float32,
) -> SparseBatch:
    """Build a padded SparseBatch from per-row (indices, values) lists.

    ``pad_rows_to`` / ``pad_nnz_to`` round shapes up to multiples so XLA sees
    a small set of distinct shapes (recompilation control) and tiles align
    with the (8, 128) float32 TPU layout.
    """
    n = len(rows)
    if n == 0:
        raise ValueError("empty batch")
    k = max((len(ix) for ix, _ in rows), default=1)
    if max_nnz is not None:
        k = min(k, max_nnz)
    k = max(_round_up(max(k, 1), pad_nnz_to), pad_nnz_to)
    n_pad = max(_round_up(n, pad_rows_to), pad_rows_to)

    indices = np.zeros((n_pad, k), dtype=np.int32)
    values = np.zeros((n_pad, k), dtype=dtype)
    for i, (ix, vs) in enumerate(rows):
        m = min(len(ix), k)
        indices[i, :m] = np.asarray(ix[:m], dtype=np.int32)
        values[i, :m] = np.asarray(vs[:m], dtype=dtype)

    lab = np.zeros((n_pad,), dtype=dtype)
    lab[:n] = np.asarray(labels, dtype=dtype)
    off = np.zeros((n_pad,), dtype=dtype)
    if offsets is not None:
        off[:n] = np.asarray(offsets, dtype=dtype)
    wgt = np.zeros((n_pad,), dtype=dtype)
    wgt[:n] = 1.0 if weights is None else np.asarray(weights, dtype=dtype)

    return SparseBatch(
        indices=jnp.asarray(indices),
        values=jnp.asarray(values),
        labels=jnp.asarray(lab),
        offsets=jnp.asarray(off),
        weights=jnp.asarray(wgt),
    )


def make_dense_batch(
    features: np.ndarray,
    labels: Sequence[float],
    offsets: Optional[Sequence[float]] = None,
    weights: Optional[Sequence[float]] = None,
    *,
    pad_rows_to: int = 8,
    dtype=np.float32,
) -> DenseBatch:
    features = np.asarray(features, dtype=dtype)
    n, d = features.shape
    n_pad = max(_round_up(n, pad_rows_to), pad_rows_to)
    f = np.zeros((n_pad, d), dtype=dtype)
    f[:n] = features
    lab = np.zeros((n_pad,), dtype=dtype)
    lab[:n] = np.asarray(labels, dtype=dtype)
    off = np.zeros((n_pad,), dtype=dtype)
    if offsets is not None:
        off[:n] = np.asarray(offsets, dtype=dtype)
    wgt = np.zeros((n_pad,), dtype=dtype)
    wgt[:n] = 1.0 if weights is None else np.asarray(weights, dtype=dtype)
    return DenseBatch(
        features=jnp.asarray(f),
        labels=jnp.asarray(lab),
        offsets=jnp.asarray(off),
        weights=jnp.asarray(wgt),
    )


def sparse_dot(batch: SparseBatch, w_eff: Array) -> Array:
    """Per-row sparse dot product: [n]. The hot gather of the whole library."""
    return jnp.sum(batch.values * jnp.take(w_eff, batch.indices, axis=0), axis=-1)


def sparse_scatter_add(batch: SparseBatch, row_coef: Array, dim: int) -> Array:
    """Accumulate sum_i row_coef[i] * x_i into a dense [dim] vector.

    The TPU-native analog of the reference's per-datum
    ``axpy(coef, features, vectorSum)`` accumulation
    (ValueAndGradientAggregator.scala:133-154): one scatter-add over the
    flattened (row, nnz) pairs.
    """
    contrib = (batch.values * row_coef[:, None]).reshape(-1)
    flat_ix = batch.indices.reshape(-1)
    return jnp.zeros((dim,), dtype=batch.values.dtype).at[flat_ix].add(contrib)
