"""Declarative SLOs with multi-window burn-rate alerting (ISSUE 15).

An :class:`SLOSpec` names an objective — availability ("99% of
requests reach a good outcome") or a latency percentile ("99% of
requests complete within 25ms") — and the :class:`SLOEngine` evaluates
it over the live metrics registry: availability over a (bad, total)
counter pair, latency over a registry *histogram* (the good fraction
is the cumulative bucket count at the threshold bound, so the
threshold must be one of the histogram's bounds — approximating
between bounds would silently move the objective).

**Burn rate**, not raw error fraction: ``burn = error_rate /
(1 - objective)`` — the rate at which the error *budget* is being
spent. 1.0 means the budget lasts exactly the SLO period; 10 means a
tenth of that. Alerting is **multi-window** (the SRE-workbook shape):
an alert fires only when BOTH a short and a long window burn past the
threshold — the short window makes detection fast and makes the alert
RESET fast once the burst ends, the long window keeps one transient
blip from paging. Both windows are measured over the same cumulative
counters by differencing a ring of periodic samples, so the engine
never needs per-request state.

Alerts are filed as flight-recorder events (``slo.alert`` /
``slo.clear`` — they ride the ring into every dump) and exposed as
registry gauges (``slo_burn_rate{slo=...,window=...}``,
``slo_alert{slo=...}``), so both the post-mortem and the live scrape
see them. The serving watcher's health window can consume the alert
state instead of raw error fractions via
:meth:`SLOEngine.any_alert_active` (wired as ``burn_gate`` on
``RegistryWatcher``).

Host arithmetic only: nothing in obs/ touches a jax value (pinned by
``tests/test_lint_clean.py``).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "SLOSpec",
    "parse_slo_specs",
    "default_serving_slos",
    "default_router_slos",
    "SLOEngine",
]

KINDS = ("availability", "latency")


@dataclass(frozen=True)
class SLOSpec:
    """One declarative objective.

    ``kind="availability"``: ``metric`` names the TOTAL counter and
    ``bad_metric`` the bad-event counter (both registry counters,
    summed over label sets). ``kind="latency"``: ``metric`` names a
    registry histogram and ``latency_threshold_s`` one of its bucket
    bounds; an observation above the bound is a budget-burning event.

    ``burn_threshold`` is the budget-spend multiple that pages (e.g.
    2.0 = the budget would be gone in half the SLO period); the alert
    fires only when BOTH windows burn past it.
    """

    name: str
    objective: float
    kind: str = "availability"
    metric: str = ""
    bad_metric: str = ""
    latency_threshold_s: float = 0.0
    short_window_s: float = 60.0
    long_window_s: float = 720.0
    burn_threshold: float = 2.0

    def validate(self) -> "SLOSpec":
        if not self.name:
            raise ValueError("SLOSpec needs a name")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: objective must be in (0, 1), got "
                f"{self.objective}"
            )
        if self.kind not in KINDS:
            raise ValueError(
                f"SLO {self.name!r}: kind must be one of {KINDS}, got "
                f"{self.kind!r}"
            )
        if not self.metric:
            raise ValueError(f"SLO {self.name!r}: metric is required")
        if self.kind == "availability" and not self.bad_metric:
            raise ValueError(
                f"SLO {self.name!r}: availability needs bad_metric"
            )
        if self.kind == "latency" and self.latency_threshold_s <= 0:
            raise ValueError(
                f"SLO {self.name!r}: latency needs latency_threshold_s"
            )
        if not 0 < self.short_window_s < self.long_window_s:
            raise ValueError(
                f"SLO {self.name!r}: need 0 < short_window_s < "
                f"long_window_s, got {self.short_window_s}/"
                f"{self.long_window_s}"
            )
        if self.burn_threshold <= 0:
            raise ValueError(
                f"SLO {self.name!r}: burn_threshold must be > 0"
            )
        return self

    def as_dict(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "objective": self.objective,
            "kind": self.kind,
            "metric": self.metric,
            "bad_metric": self.bad_metric,
            "latency_threshold_s": self.latency_threshold_s,
            "short_window_s": self.short_window_s,
            "long_window_s": self.long_window_s,
            "burn_threshold": self.burn_threshold,
        }


def parse_slo_specs(text: str) -> List[SLOSpec]:
    """``--slo`` grammar: inline JSON (one object or a list), ``@path``
    to a JSON file, or the literal ``default`` (the serving specs).
    Unknown keys are rejected — a typo'd window must not silently
    become the default."""
    text = (text or "").strip()
    if not text:
        raise ValueError("empty SLO spec")
    if text == "default":
        return default_serving_slos()
    if text.startswith("@"):
        with open(text[1:]) as f:
            payload = json.load(f)
    else:
        payload = json.loads(text)
    if isinstance(payload, Mapping):
        payload = [payload]
    specs: List[SLOSpec] = []
    fields = set(SLOSpec.__dataclass_fields__)
    for obj in payload:
        unknown = set(obj) - fields
        if unknown:
            raise ValueError(
                f"unknown SLO spec key(s) {sorted(unknown)}; known: "
                f"{sorted(fields)}"
            )
        specs.append(SLOSpec(**obj).validate())
    if len({s.name for s in specs}) != len(specs):
        raise ValueError("duplicate SLO spec names")
    return specs


def default_serving_slos(
    *, latency_threshold_s: float = 0.025
) -> List[SLOSpec]:
    """The single-server serving plane's instruments (see
    ``ServingMetrics.bind_registry``)."""
    return [
        SLOSpec(
            name="serving-availability",
            objective=0.99,
            kind="availability",
            metric="serving_requests_total",
            bad_metric="serving_bad_total",
        ).validate(),
        SLOSpec(
            name="serving-latency",
            objective=0.99,
            kind="latency",
            metric="serving_latency_seconds",
            latency_threshold_s=latency_threshold_s,
        ).validate(),
    ]


def default_router_slos() -> List[SLOSpec]:
    """The routed plane's instruments (``RouterMetrics.bind_registry``)."""
    return [
        SLOSpec(
            name="router-availability",
            objective=0.99,
            kind="availability",
            metric="router_requests_total",
            bad_metric="router_bad_total",
        ).validate(),
        SLOSpec(
            name="router-latency",
            objective=0.99,
            kind="latency",
            metric="router_latency_seconds",
            latency_threshold_s=0.25,
        ).validate(),
    ]


class SLOEngine:
    """Evaluates SLO specs over a metrics registry on a tick cadence.

    ``tick(now)`` is deterministic (tests drive it with a synthetic
    clock); :meth:`start`/:meth:`stop` run it on a background thread.
    Each tick samples every spec's cumulative (bad, total), appends to
    a bounded per-spec ring, differences the ring at the short and long
    window edges, and updates gauges + alert state. All registry /
    recorder calls happen OUTSIDE the engine's own lock.
    """

    def __init__(
        self,
        registry,
        specs: Sequence[SLOSpec],
        *,
        recorder=None,
        sources: Optional[
            Mapping[str, Callable[[], Tuple[float, float]]]
        ] = None,
        max_samples: int = 4096,
    ):
        self.registry = registry
        self.specs = [s.validate() for s in specs]
        self.recorder = recorder
        self.sources = dict(sources or {})
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._samples: Dict[str, deque] = {
            s.name: deque(maxlen=self.max_samples) for s in self.specs
        }
        self._active: Dict[str, bool] = {s.name: False for s in self.specs}
        self._last_eval: Dict[str, Dict[str, object]] = {}
        self._alerts_fired = 0  # photon: guarded-by(_lock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # single-writer publish: start() sets it before the thread runs
        self._period_s = 1.0  # photon: guarded-by(atomic)
        # gauges are created once up front (get-or-create is idempotent)
        self._g_burn = registry.gauge(
            "slo_burn_rate",
            "error-budget burn rate per SLO and window",
        )
        self._g_alert = registry.gauge(
            "slo_alert",
            "1 while the SLO's multi-window burn-rate alert is active",
        )
        self._g_err = registry.gauge(
            "slo_error_rate", "windowed bad/total per SLO (short window)"
        )

    # -- sampling -------------------------------------------------------------

    def _counts(self, spec: SLOSpec) -> Tuple[float, float]:
        """Cumulative (bad, total) for one spec. Resolution order:
        an explicit source callable, then registry instruments."""
        src = self.sources.get(spec.metric)
        if src is not None:
            bad, total = src()
            return float(bad), float(total)
        if spec.kind == "availability":
            total = self.registry.counter(spec.metric).total()
            bad = self.registry.counter(spec.bad_metric).total()
            return float(bad), float(total)
        hist = self.registry.histogram(spec.metric)
        idx = None
        for i, b in enumerate(hist.bounds):
            if abs(b - spec.latency_threshold_s) <= 1e-12:
                idx = i
                break
        if idx is None:
            raise ValueError(
                f"SLO {spec.name!r}: threshold "
                f"{spec.latency_threshold_s} is not a bucket bound of "
                f"{spec.metric!r} (bounds: {hist.bounds})"
            )
        good = 0.0
        total = 0.0
        for cell in hist.series().values():
            total += cell["count"]
            good += sum(cell["buckets"][: idx + 1])
        return total - good, total

    @staticmethod
    def _window_delta(samples, now: float, window_s: float, bad, total):
        """Difference the cumulative counters against the newest sample
        at least ``window_s`` old (or the oldest available — a short
        history reports over what it has, with the actual span)."""
        edge = None
        for t, b, n in samples:  # oldest -> newest
            if t <= now - window_s:
                edge = (t, b, n)
            else:
                break
        if edge is None and samples:
            edge = samples[0]
        if edge is None:
            return 0.0, 0.0, 0.0
        t0, b0, n0 = edge
        return bad - b0, total - n0, now - t0

    def tick(self, now: Optional[float] = None) -> Dict[str, Dict]:
        """One evaluation pass; returns per-spec verdicts."""
        now = time.monotonic() if now is None else float(now)
        out: Dict[str, Dict] = {}
        transitions: List[Tuple[SLOSpec, bool, Dict]] = []
        for spec in self.specs:
            bad, total = self._counts(spec)
            with self._lock:
                ring = self._samples[spec.name]
                ring.append((now, bad, total))
                samples = list(ring)
            budget = 1.0 - spec.objective
            burns = {}
            rates = {}
            for label, w in (
                ("short", spec.short_window_s),
                ("long", spec.long_window_s),
            ):
                d_bad, d_total, span = self._window_delta(
                    samples, now, w, bad, total
                )
                rate = (d_bad / d_total) if d_total > 0 else 0.0
                rates[label] = rate
                burns[label] = rate / budget
            active = (
                burns["short"] > spec.burn_threshold
                and burns["long"] > spec.burn_threshold
            )
            verdict = {
                "kind": spec.kind,
                "objective": spec.objective,
                "burn_short": round(burns["short"], 6),
                "burn_long": round(burns["long"], 6),
                "error_rate_short": round(rates["short"], 6),
                "burn_threshold": spec.burn_threshold,
                "alert": active,
                "bad": bad,
                "total": total,
            }
            out[spec.name] = verdict
            # read-modify-write of the alert state in ONE critical
            # section: the transition decision is made on the value
            # read under this lock, never on a stale pre-compute peek
            with self._lock:
                was_active = self._active[spec.name]
                self._active[spec.name] = active
                self._last_eval[spec.name] = verdict
                if active and not was_active:
                    self._alerts_fired += 1
            # gauges + flight events outside the engine lock
            self._g_burn.set(burns["short"], slo=spec.name, window="short")
            self._g_burn.set(burns["long"], slo=spec.name, window="long")
            self._g_err.set(rates["short"], slo=spec.name)
            self._g_alert.set(1.0 if active else 0.0, slo=spec.name)
            if active != was_active:
                transitions.append((spec, active, verdict))
        if self.recorder is not None:
            for spec, active, verdict in transitions:
                self.recorder.record(
                    "slo.alert" if active else "slo.clear",
                    slo=spec.name,
                    objective=spec.objective,
                    burn_short=verdict["burn_short"],
                    burn_long=verdict["burn_long"],
                    burn_threshold=spec.burn_threshold,
                )
        return out

    # -- state ----------------------------------------------------------------

    def alert_active(self, name: str) -> bool:
        with self._lock:
            return bool(self._active.get(name))

    def any_alert_active(self) -> bool:
        """The registry watcher's ``burn_gate``: "is ANY declared SLO
        burning its budget past threshold on both windows right now" —
        burn-rate semantics in place of the raw error fraction."""
        with self._lock:
            return any(self._active.values())

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "specs": [s.as_dict() for s in self.specs],
                "alerts_active": sorted(
                    n for n, a in self._active.items() if a
                ),
                "alerts_fired": self._alerts_fired,
                "last_eval": {
                    k: dict(v) for k, v in self._last_eval.items()
                },
            }

    # -- background cadence ---------------------------------------------------

    def start(self, period_s: float = 1.0) -> "SLOEngine":
        self._period_s = max(float(period_s), 0.02)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="photon-slo-engine", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self._period_s):
            try:
                self.tick()
            except ValueError:
                # a spec referencing a not-yet-populated histogram must
                # not kill the cadence; it resolves once traffic flows
                continue

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
