"""Unified telemetry plane (ISSUE 13): request/training tracing, a
process-wide metrics registry with live wire exposition, and the
crash/rollback flight recorder.

Four pieces, all host-arithmetic-only (obs code never touches a jax
value — pinned by ``tests/test_lint_clean.py``):

- :mod:`photon_ml_tpu.obs.trace` — lightweight spans with trace ids
  minted at the frontend, carried on the wire, propagated through
  router -> shard -> batcher dispatch and the training loops; exported
  as Chrome trace-event JSON next to ``jax.profiler`` device traces.
- :mod:`photon_ml_tpu.obs.registry` — counters/gauges/bounded
  histograms with capped label cardinality, plus views over the
  existing subsystem accumulators (ServingMetrics, RouterMetrics, host
  timings, reliability accounting); served live by the frontend's
  ``{"op": "metrics"}`` and snapshotted periodically under
  ``--obs-dir``.
- :mod:`photon_ml_tpu.obs.flight_recorder` — a bounded ring of
  structured protocol events (swap/rollback/shed/circuit/fault/lease)
  with monotone conservation counters and atomic dumps on SIGTERM,
  rollback, and operator request; ``check_conservation()`` is the
  every-request-reaches-a-named-outcome invariant the chaos arms call.
- :mod:`photon_ml_tpu.obs.events` — the folded typed-event emitter
  (ONE structured-event path; ``photon_ml_tpu.events`` is a compat
  shim over it).

:class:`ObsSession` is the drivers' one-call wiring: ``--obs-dir``
enables tracing, arms the flight recorder's auto-dump, starts the
periodic snapshot writer, and ``finish()`` exports ``trace.json`` +
``flight.json`` + the final snapshot.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from photon_ml_tpu.obs.events import (  # noqa: F401
    Event,
    EventEmitter,
    EventListener,
    PhotonOptimizationLogEvent,
    PhotonSetupEvent,
    ScheduleCacheEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.obs.flight_recorder import (  # noqa: F401
    FlightRecorder,
    flight_recorder,
    install_signal_dump,
    reset_flight_recorder,
)
from photon_ml_tpu.obs.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    SnapshotWriter,
    default_registry,
    reset_default_registry,
)
from photon_ml_tpu.obs.trace import (  # noqa: F401
    PARENT_KEY,
    TRACE_KEY,
    Span,
    Tracer,
    chrome_trace_events,
    epoch,
    epoch_now,
    export_chrome_trace,
    new_trace_id,
    record_span,
    reset_tracer,
    set_tracing,
    span,
    start_span,
    tracer,
    tracing_enabled,
    tracing_scope,
    wire_context,
)

# Fleet-scale observability (ISSUE 15): imported lazily by consumers —
# photon_ml_tpu.obs.fleet (FleetCollector, stitch/verify/export,
# fleet_check_conservation, the post-hoc CLI) and photon_ml_tpu.obs.slo
# (SLOSpec, SLOEngine, parse_slo_specs) are deliberately NOT imported
# here: the serving hot path imports this package and must not pay for
# the collector/engine machinery it never uses.

__all__ = ["ObsSession"]


class ObsSession:
    """Driver-side wiring for ``--obs-dir``: one constructor call at
    startup, one ``finish()`` at exit.

    On construction (when ``obs_dir`` is set): tracing flips on, the
    process flight recorder arms its transition auto-dump at
    ``<obs_dir>/flight.json``, standard process views (host timings,
    reliability accounting, readback count, flight counters) register
    with the process registry, and the periodic snapshot writer starts.
    ``finish()`` stops the writer (final snapshot included), exports
    the span ring as Chrome trace-event JSON, and dumps the flight ring
    — all through atomic writers. A driver without ``--obs-dir``
    constructs this with ``obs_dir=None`` and every method no-ops.
    """

    def __init__(
        self,
        obs_dir: Optional[str],
        *,
        snapshot_period_s: float = 5.0,
        signal_dump: bool = True,
        extra_views: Optional[Dict[str, object]] = None,
    ):
        self.obs_dir = obs_dir or None
        self.registry: Optional[MetricsRegistry] = None
        self.recorder: Optional[FlightRecorder] = None
        self._writer: Optional[SnapshotWriter] = None
        self._finished = False
        if self.obs_dir is None:
            return
        os.makedirs(self.obs_dir, exist_ok=True)
        set_tracing(True)
        self.recorder = flight_recorder()
        self.recorder.set_auto_dump(self.flight_path)
        if signal_dump:
            install_signal_dump(self.flight_path)
        self.registry = default_registry()
        self._register_process_views()
        for name, fn in (extra_views or {}).items():
            self.registry.register_view(name, fn)
        self._writer = SnapshotWriter(
            self.registry, self.obs_dir, period_s=snapshot_period_s
        ).start()

    @property
    def enabled(self) -> bool:
        return self.obs_dir is not None

    @property
    def flight_path(self) -> str:
        return os.path.join(self.obs_dir or "", "flight.json")

    @property
    def trace_path(self) -> str:
        return os.path.join(self.obs_dir or "", "trace.json")

    def _register_process_views(self) -> None:
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.reliability import reliability_metrics
        from photon_ml_tpu.utils.profiling import host_timings

        reg = self.registry
        reg.register_view("host_timings", host_timings)
        reg.register_view("reliability", reliability_metrics)
        reg.register_view(
            "readbacks", lambda: {"device_get_calls": overlap.readback_stats()}
        )
        rec = self.recorder
        reg.register_view(
            "flight",
            lambda: {
                "recorded": rec.snapshot()["recorded"],
                "conservation": rec.check_conservation(),
            },
        )

    def register_view(self, name: str, fn) -> None:
        if self.registry is not None:
            self.registry.register_view(name, fn)

    def record(self, kind: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.record(kind, **fields)

    def finish(self, *, reason: str = "exit") -> Optional[Dict[str, object]]:
        """Flush everything; idempotent. Returns a summary block for
        metrics.json (paths + conservation verdict) or None when
        disabled."""
        if self.obs_dir is None or self._finished:
            return None
        self._finished = True
        if self._writer is not None:
            self._writer.stop()
        n_spans = export_chrome_trace(self.trace_path)
        self.recorder.dump(self.flight_path, reason=reason)
        conservation = self.recorder.check_conservation()
        return {
            "obs_dir": self.obs_dir,
            "trace_path": self.trace_path,
            "trace_events": n_spans,
            "flight_path": self.flight_path,
            "conservation": conservation,
        }
