"""Fleet-scale observability (ISSUE 15): cross-process trace stitching
into ONE timeline, a live fleet telemetry collector, and fleet-wide
score conservation.

PR 13's telemetry plane is strictly per-process: each of the router,
N shard-servers and the registry watcher dumps its OWN trace ring,
metrics snapshot and flight recorder, and ``check_conservation()``
balances one process's books. The trace ids already cross the wire
(``trace_id`` / ``parent_span`` on every sub-request) — this module is
the layer that stitches them:

- :class:`FleetCollector` polls every fleet member over FRESH control
  connections (never the multiplexed data plane) using the incremental
  ``{"op": "trace"}`` drain op: cursor/seq-keyed, so polls never
  duplicate a span and never silently drop one (ring evictions between
  polls are counted, per member, into the artifact). A SIGKILLed
  shard's spans survive in the COLLECTOR — everything polled before
  the kill joins the fleet timeline.

- **Clock-skew normalization.** Spans carry ``perf_counter`` pairs
  mapped onto the wall clock through one per-process ``(wall, perf)``
  epoch (``obs/trace.py``). Each poll runs one NTP-style exchange
  against that SAME mapping: the collector stamps its epoch-time
  before (``c0``) and after (``c1``) the request, the member answers
  with its epoch-mapped "now"; ``offset = member_now - (c0 + c1)/2``
  with uncertainty ``(c1 - c0)/2`` (half the round trip). The
  lowest-uncertainty estimate seen so far wins, every member's offset
  and uncertainty ride the artifact, and
  :func:`verify_fleet_trace` uses the summed uncertainties as the
  tolerance for its parent→child monotonicity check — the accuracy
  envelope is explicit, never assumed.

- **Stitching** (:func:`stitch_spans`): per-process span ids are
  namespaced ``<member>:<span_id>`` in the merged artifact (the
  source-side pid+nonce prefixes make collisions vanishingly rare, but
  a fleet merge must not DEPEND on that — collisions are counted and
  surfaced), parent references are remapped through the global id map
  (wire-carried parents cross processes by design), and the batch-level
  dispatch spans expand into their per-request ``serving.score`` leaves
  exactly like the single-process exporter does.

- **Fleet-wide conservation**
  (:func:`fleet_check_conservation`): router admitted == Σ
  shard-attributed terminals + router-local outcomes (sheds, NO_SHARD
  refusals, hot-cache hits that fan out to zero shards, FE-only
  degraded), joined against each shard's own per-generation terminal
  split. A shard whose book is a mid-flight snapshot (SIGKILLed — its
  last transition auto-dump is all there is) is joined advisorily,
  never counted as a failure; a CLEANLY drained shard must balance
  exactly and must have served at least every sub-request the router
  attributed to it.

- **Post-hoc merge**: ``python -m photon_ml_tpu.obs.fleet <obs-dir>...``
  merges already-dumped ``trace.json`` / ``flight.json`` artifacts into
  one ``fleet_trace.json`` (flight-ring events join the timeline as
  instant events, so a chaos run's SIGKILLed-process rings are still
  visible in it) and re-checks conservation from the dumped books.

Host arithmetic only: nothing in obs/ touches a jax value (pinned by
``tests/test_lint_clean.py``).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.obs.trace import TRACES_ATTR

__all__ = [
    "FleetCollector",
    "stitch_spans",
    "verify_fleet_trace",
    "fleet_chrome_events",
    "export_fleet_trace",
    "fleet_check_conservation",
    "spans_from_chrome_export",
    "load_obs_dump",
    "main",
]

# Control ops run on fresh connections; a trace drain can carry many
# thousands of spans in one JSON line.
CONTROL_TIMEOUT_S = 30.0
DEFAULT_POLL_S = 1.0
# Per-member span accumulation cap: the collector is itself bounded
# (old spans fall off, counted), so a week-long fleet watch cannot grow
# host memory.
DEFAULT_MAX_SPANS_PER_MEMBER = 1 << 17


def _request_line(
    host: str, port: int, obj: Mapping, timeout_s: float
) -> Dict:
    """One JSON-lines control request on a FRESH connection — staging
    or a slow member must never stall a shared data-plane reader."""
    with socket.create_connection(
        (host, int(port)), timeout=timeout_s
    ) as sock:
        sock.settimeout(timeout_s)
        sock.sendall((json.dumps(obj) + "\n").encode("utf-8"))
        buf = b""
        while b"\n" not in buf:
            chunk = sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("EOF before response line")
            buf += chunk
    return json.loads(buf.split(b"\n", 1)[0].decode("utf-8"))


def _request_wire(
    host: str, port: int, obj: Mapping, timeout_s: float
) -> Dict:
    """The binary twin of :func:`_request_line`: one MSG_JSON request
    frame on a fresh connection (the frontend sniffs the magic byte),
    one decoded response frame back. The trace drain's span batches —
    the collector's bulk transfer — ride photon-wire's raw float
    buffers instead of per-float JSON text. Imported lazily so the obs
    plane stays importable without the serving stack."""
    from photon_ml_tpu.serving import wire as wirefmt

    out = bytearray()
    wirefmt.append_json(out, dict(obj))
    decoder = wirefmt.FrameDecoder(wirefmt.resolve_max_frame_bytes())
    with socket.create_connection(
        (host, int(port)), timeout=timeout_s
    ) as sock:
        sock.settimeout(timeout_s)
        sock.sendall(out)
        while True:
            chunk = sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("EOF before response frame")
            frames = decoder.feed(chunk)
            if frames:
                return wirefmt.decode_message(*frames[0])


class _MemberState:
    """One fleet member's collector-side book. Every field is guarded
    by the owning collector's ``_lock``; the poll path reads the cursor
    under the lock, does its socket IO with NO lock held, and publishes
    the results back under the lock."""

    __slots__ = (
        "name", "host", "port", "local", "cursor", "spans",
        "ring_dropped", "merge_dropped", "epoch_wall", "epoch_perf",
        "pid", "offset_s", "offset_unc_s", "polls", "errors",
        "enabled", "last_error", "uid_seq",
    )

    def __init__(self, name: str, host: Optional[str], port: int):
        self.name = str(name)
        self.host = host
        self.port = int(port)
        self.local = host is None
        self.cursor = 0
        self.spans: List[Dict] = []
        self.ring_dropped = 0   # evicted at the member between polls
        self.merge_dropped = 0  # evicted here past the collector cap
        self.epoch_wall: Optional[float] = None
        self.epoch_perf: Optional[float] = None
        self.pid: Optional[int] = None
        self.offset_s = 0.0
        self.offset_unc_s: Optional[float] = None  # None = never synced
        self.polls = 0
        self.errors = 0
        self.enabled: Optional[bool] = None
        self.last_error = ""
        self.uid_seq = 0


class FleetCollector:
    """Polls every fleet member's ``{"op": "trace"}`` drain (plus the
    local process tracer when ``local_name`` is set — the router's own
    spans join the same timeline) and merges the result into one
    skew-corrected Chrome trace.

    ``members`` is a sequence of ``(name, host, port)``. Polling runs
    either on the background thread (:meth:`start` / :meth:`stop`) or
    deterministically via :meth:`poll_once` — chaos arms and tests
    drive the latter. A member that cannot be reached costs one counted
    error, never a crash: a SIGKILLed shard simply stops contributing
    new spans while everything already collected stays merged.
    """

    def __init__(
        self,
        members: Sequence[Tuple[str, str, int]],
        *,
        local_name: Optional[str] = None,
        poll_s: float = DEFAULT_POLL_S,
        connect_timeout_s: float = 5.0,
        max_spans_per_member: int = DEFAULT_MAX_SPANS_PER_MEMBER,
        wire: str = "json",
    ):
        self.wire = str(wire)
        if self.wire not in ("json", "binary"):
            raise ValueError(
                f"unknown wire protocol {wire!r} (json | binary)"
            )
        self.poll_s = max(float(poll_s), 0.02)
        self.connect_timeout_s = float(connect_timeout_s)
        self.max_spans_per_member = int(max_spans_per_member)
        self._lock = threading.Lock()
        # serializes whole polls (background thread vs an explicit
        # poll_once vs the stop-time final poll): two concurrent polls
        # of one member would read the same cursor and duplicate spans
        self._poll_serial = threading.Lock()
        self._members: List[_MemberState] = [
            _MemberState(name, host, port) for name, host, port in members
        ]
        if local_name is not None:
            self._members.append(_MemberState(local_name, None, 0))
        names = [m.name for m in self._members]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate member names: {names}")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- polling --------------------------------------------------------------

    def _poll_member(self, m: _MemberState) -> None:
        with self._lock:
            cursor = m.cursor
        if m.local:
            # the collector's own process: read the tracer directly —
            # same cursor contract, offset zero by construction
            spans, new_cursor, dropped = obs_trace.tracer().read_since(
                cursor
            )
            ew, ep = obs_trace.epoch()
            payload = {
                "spans": [s.to_dict() for s in spans],
                "cursor": new_cursor,
                "dropped": dropped,
                "epoch_wall": ew,
                "epoch_perf": ep,
                "pid": os.getpid(),
                "enabled": obs_trace.tracing_enabled(),
            }
            offset, unc = 0.0, 0.0
        else:
            # NTP-style exchange against the member's span-time epoch:
            # both c0/c1 are THIS process's epoch-mapped now, so the
            # derived offset lands every member on the collector's own
            # span timeline
            ask = _request_wire if self.wire == "binary" else _request_line
            c0 = obs_trace.epoch_now()
            payload = ask(
                m.host, m.port,
                {"op": "trace", "cursor": cursor, "uid": self._uid(m)},
                self.connect_timeout_s,
            )
            c1 = obs_trace.epoch_now()
            if payload.get("status") != "ok":
                raise ConnectionError(
                    f"trace op refused: {payload.get('error')}"
                )
            member_now = payload["epoch_wall"] + (
                payload["now_perf"] - payload["epoch_perf"]
            )
            offset = member_now - 0.5 * (c0 + c1)
            unc = 0.5 * (c1 - c0)
        with self._lock:
            m.polls += 1
            m.cursor = int(payload["cursor"])
            m.ring_dropped += int(payload.get("dropped") or 0)
            m.epoch_wall = float(payload["epoch_wall"])
            m.epoch_perf = float(payload["epoch_perf"])
            m.pid = payload.get("pid")
            m.enabled = payload.get("enabled")
            if m.offset_unc_s is None or unc < m.offset_unc_s:
                # keep the tightest estimate: uncertainty is half the
                # round trip, so the fastest exchange wins
                m.offset_s, m.offset_unc_s = offset, unc
            m.spans.extend(payload["spans"])
            overflow = len(m.spans) - self.max_spans_per_member
            if overflow > 0:
                del m.spans[:overflow]
                m.merge_dropped += overflow

    def _uid(self, m: _MemberState) -> str:
        with self._lock:
            m.uid_seq += 1
            return f"fleet-{m.name}-{m.uid_seq}"

    def poll_once(self) -> Dict[str, bool]:
        """One deterministic poll of every member; returns name -> ok.
        Serialized against the background thread, so a test (or the
        stop-time final poll) can interleave with it safely."""
        out: Dict[str, bool] = {}
        with self._poll_serial:
            for m in list(self._members):
                try:
                    self._poll_member(m)
                    out[m.name] = True
                except (OSError, ValueError, KeyError, TypeError) as e:
                    with self._lock:
                        m.errors += 1
                        m.last_error = str(e)
                    out[m.name] = False
        return out

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.poll_s):
            self.poll_once()

    def start(self) -> "FleetCollector":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="photon-fleet-collector", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 10.0, *, final_poll: bool = True):
        """Join the poll thread, then (by default) drain each member's
        ring one last time so the artifact holds the tail."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        if final_poll:
            self.poll_once()

    # -- the fleet flight/conservation plane ----------------------------------

    def collect_flight(self) -> Dict[str, Dict]:
        """Fetch every member's flight ring + conservation book over a
        fresh ``{"op": "flight"}`` each (the local member reads the
        process recorder). Unreachable members are reported with an
        ``error`` entry — the fleet check treats them as incomplete."""
        from photon_ml_tpu.obs.flight_recorder import flight_recorder

        out: Dict[str, Dict] = {}
        for m in list(self._members):
            if m.local:
                rec = flight_recorder()
                out[m.name] = {
                    "conservation": rec.check_conservation(),
                    "events": rec.events(),
                    "complete": True,
                }
                continue
            try:
                resp = _request_line(
                    m.host, m.port,
                    {"op": "flight", "uid": self._uid(m)},
                    self.connect_timeout_s,
                )
                out[m.name] = {
                    "conservation": resp["conservation"],
                    "events": resp["flight"]["events"],
                    "complete": True,
                }
            except (OSError, ValueError, KeyError) as e:
                out[m.name] = {"error": str(e), "complete": False}
        return out

    # -- merge ----------------------------------------------------------------

    def member_status(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            return {
                m.name: {
                    "pid": m.pid,
                    "polls": m.polls,
                    "errors": m.errors,
                    "spans": len(m.spans),
                    "cursor": m.cursor,
                    "ring_dropped": m.ring_dropped,
                    "merge_dropped": m.merge_dropped,
                    "clock_offset_s": m.offset_s,
                    "clock_offset_uncertainty_s": m.offset_unc_s,
                    "tracing_enabled": m.enabled,
                    "last_error": m.last_error,
                }
                for m in self._members
            }

    def _payloads(self) -> List[Dict]:
        with self._lock:
            return [
                {
                    "name": m.name,
                    "pid": m.pid,
                    "spans": list(m.spans),
                    "epoch_wall": m.epoch_wall,
                    "epoch_perf": m.epoch_perf,
                    "offset_s": m.offset_s,
                    "offset_unc_s": m.offset_unc_s,
                    "wall_mapped": False,
                }
                for m in self._members
                if m.spans
            ]

    def stitched_spans(self) -> List[Dict]:
        return stitch_spans(self._payloads())

    def export(self, path: str, *, extra: Optional[Dict] = None) -> int:
        """Write the merged, skew-corrected fleet timeline as ONE
        Chrome trace-event JSON. Returns the event count."""
        stitched = self.stitched_spans()
        status = self.member_status()
        return export_fleet_trace(
            path, stitched, member_status=status, extra=extra
        )


# -- stitching ------------------------------------------------------------------


def _expand_wire_span(s: Dict) -> List[Dict]:
    """The wire twin of ``trace.expand_spans``: a dispatch span dict
    carrying per-request trace contexts expands into its
    ``serving.score`` leaves (leaf ids derive from the dispatch span's
    own id, so they stay unique after namespacing)."""
    out = [s]
    traces = (s.get("attrs") or {}).get(TRACES_ATTR)
    if not traces:
        return out
    for k, entry in enumerate(traces):
        trace_id, parent_id, degraded = entry[0], entry[1], entry[2]
        out.append({
            "name": "serving.score",
            "trace_id": trace_id,
            "span_id": f"{s['span_id']}#{k}",
            "parent_id": parent_id,
            "t0": s["t0"],
            "t1": s["t1"],
            "tid": s.get("tid"),
            "seq": s.get("seq"),
            "attrs": {
                "degraded": bool(degraded),
                "dispatch_span": s["span_id"],
                **{
                    k2: v for k2, v in (s.get("attrs") or {}).items()
                    if k2 in ("generation", "shape")
                },
            },
        })
    return out


def stitch_spans(payloads: Sequence[Mapping]) -> List[Dict]:
    """Merge per-member span payloads into ONE namespaced, parent-
    linked, skew-corrected span list.

    Each payload: ``{name, pid, spans, epoch_wall, epoch_perf,
    offset_s, offset_unc_s, wall_mapped}`` — ``spans`` hold raw
    ``perf_counter`` times unless ``wall_mapped`` (the post-hoc path,
    whose exporter already applied the epoch). Output spans carry
    ``member``, ``pid``, a namespaced ``span_id``, a remapped
    ``parent_id`` (left verbatim when the parent was never collected —
    e.g. minted by a process outside the fleet), wall-clock ``t0``/
    ``t1`` seconds on the collector's timeline, and the member's
    offset uncertainty (``unc``) for tolerance-aware checks."""
    # pass 1: wall-map + expand each member's spans, build the global
    # id map (original id -> namespaced id)
    per_member: List[Tuple[Mapping, List[Dict]]] = []
    id_map: Dict[str, str] = {}
    collisions = 0
    for p in payloads:
        expanded: List[Dict] = []
        for s in p["spans"]:
            expanded.extend(_expand_wire_span(s))
        for s in expanded:
            sid = s["span_id"]
            nsid = f"{p['name']}:{sid}"
            if sid in id_map:
                collisions += 1
            else:
                id_map[sid] = nsid
        per_member.append((p, expanded))
    # pass 2: emit namespaced spans with remapped parents
    out: List[Dict] = []
    for p, expanded in per_member:
        offset = float(p.get("offset_s") or 0.0)
        unc = p.get("offset_unc_s")
        wall_mapped = bool(p.get("wall_mapped"))
        ew = p.get("epoch_wall")
        ep = p.get("epoch_perf")

        def to_wall(t, _ew=ew, _ep=ep, _off=offset, _wm=wall_mapped):
            if t is None:
                return None
            if _wm:
                return float(t) - _off
            return float(_ew) + (float(t) - float(_ep)) - _off

        for s in expanded:
            parent = s.get("parent_id")
            attrs = dict(s.get("attrs") or {})
            dispatch = attrs.get("dispatch_span")
            if dispatch is not None:
                attrs["dispatch_span"] = id_map.get(
                    str(dispatch), str(dispatch)
                )
            out.append({
                "name": s["name"],
                "member": p["name"],
                "pid": p.get("pid"),
                "tid": s.get("tid"),
                "trace_id": s.get("trace_id"),
                "span_id": id_map.get(s["span_id"], s["span_id"]),
                "parent_id": (
                    None if parent is None
                    else id_map.get(str(parent), str(parent))
                ),
                "t0": to_wall(s.get("t0")),
                "t1": to_wall(s.get("t1")),
                "unc": unc,
                "attrs": attrs,
                "id_collisions": collisions or None,
            })
    return out


def verify_fleet_trace(stitched: Sequence[Mapping]) -> Dict[str, object]:
    """The fleet-timeline contract, machine-checked:

    - every ``router.subrequest`` parents under a ``router.request``;
    - every ``frontend.request`` that joins a routed trace parents
      under a ``router.subrequest``;
    - every ``serving.score`` leaf parents under its ``frontend.request``
      AND its ``dispatch_span`` resolves to a ``serving.dispatch`` span
      of the SAME member (the request's trace joins the device dispatch
      that served it);
    - skew-corrected timestamps are monotone parent -> child within
      every trace, to the summed clock-sync uncertainty of the two
      members involved.
    """
    by_id = {s["span_id"]: s for s in stitched}
    violations: List[str] = []
    checked_edges = 0

    def tol(a: Mapping, b: Mapping) -> float:
        return (a.get("unc") or 0.0) + (b.get("unc") or 0.0)

    want_parent = {
        "router.subrequest": ("router.request",),
        "serving.score": ("frontend.request",),
    }
    n_sub = n_front = n_score = 0
    for s in stitched:
        parent = by_id.get(s.get("parent_id") or "")
        name = s["name"]
        if name == "router.subrequest":
            n_sub += 1
        elif name == "frontend.request":
            n_front += 1
        elif name == "serving.score":
            n_score += 1
        expect = want_parent.get(name)
        if expect is not None:
            if parent is None:
                violations.append(
                    f"{name} {s['span_id']}: parent "
                    f"{s.get('parent_id')!r} not in the merged trace"
                )
                continue
            if parent["name"] not in expect:
                violations.append(
                    f"{name} {s['span_id']}: parent is "
                    f"{parent['name']}, expected one of {expect}"
                )
        if name == "frontend.request" and parent is not None:
            # a frontend span with a collected parent must hang off a
            # router sub-request (bare client traffic stays parentless)
            if parent["name"] != "router.subrequest":
                violations.append(
                    f"frontend.request {s['span_id']}: parent is "
                    f"{parent['name']}, expected router.subrequest"
                )
        if name == "serving.score":
            d = (s.get("attrs") or {}).get("dispatch_span")
            dspan = by_id.get(str(d)) if d is not None else None
            if dspan is None or dspan["name"] != "serving.dispatch":
                violations.append(
                    f"serving.score {s['span_id']}: dispatch_span "
                    f"{d!r} does not resolve to a serving.dispatch span"
                )
            elif dspan["member"] != s["member"]:
                violations.append(
                    f"serving.score {s['span_id']}: dispatch span "
                    f"belongs to member {dspan['member']!r}, leaf to "
                    f"{s['member']!r}"
                )
        # monotonicity along every resolvable edge, skew-aware
        if parent is not None and s.get("t0") is not None:
            checked_edges += 1
            slack = tol(parent, s)
            if s["t0"] + slack < parent["t0"]:
                violations.append(
                    f"{name} {s['span_id']} starts "
                    f"{(parent['t0'] - s['t0']) * 1e3:.3f}ms before its "
                    f"parent {parent['name']} (tolerance "
                    f"{slack * 1e3:.3f}ms)"
                )
    return {
        "ok": not violations,
        "spans": len(stitched),
        "edges_checked": checked_edges,
        "router_subrequests": n_sub,
        "frontend_requests": n_front,
        "score_leaves": n_score,
        "violations": violations[:50],
    }


# -- chrome export ----------------------------------------------------------------


def fleet_chrome_events(
    stitched: Sequence[Mapping],
    *,
    flight_events: Optional[Mapping[str, Sequence[Mapping]]] = None,
    flight_offsets: Optional[Mapping[str, float]] = None,
) -> List[Dict]:
    """Chrome trace events with one pid LANE per member (synthetic,
    deterministic lane ids — real pids can collide across hosts), plus
    the members' flight-ring events as instant markers so protocol
    transitions (and a SIGKILLed process's last recorded acts) sit on
    the same timeline as the spans."""
    members: List[str] = []
    for s in stitched:
        if s["member"] not in members:
            members.append(s["member"])
    for name in (flight_events or {}):
        if name not in members:
            members.append(name)
    lane = {name: i + 1 for i, name in enumerate(sorted(members))}
    events: List[Dict] = []
    for name, pid_lane in sorted(lane.items()):
        real = next(
            (s.get("pid") for s in stitched if s["member"] == name), None
        )
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid_lane,
            "args": {
                "name": f"{name}" + (f" (pid {real})" if real else "")
            },
        })
    for s in stitched:
        if s.get("t1") is None or s.get("t0") is None:
            continue
        args: Dict[str, object] = {
            "member": s["member"],
            "trace_id": s.get("trace_id"),
            "span_id": s["span_id"],
        }
        if s.get("parent_id") is not None:
            args["parent_span"] = s["parent_id"]
        for k, v in (s.get("attrs") or {}).items():
            if k == TRACES_ATTR:
                args["traced_requests"] = len(v)
                continue
            args[k] = v if isinstance(v, (int, float, bool, str)) else str(v)
        events.append({
            "name": s["name"],
            "cat": s["name"].split(".", 1)[0],
            "ph": "X",
            "ts": s["t0"] * 1e6,
            "dur": max((s["t1"] - s["t0"]) * 1e6, 0.001),
            "pid": lane[s["member"]],
            "tid": s.get("tid") or 0,
            "args": args,
        })
    for name, evs in (flight_events or {}).items():
        off = float((flight_offsets or {}).get(name, 0.0))
        for e in evs:
            events.append({
                "name": str(e.get("kind")),
                "cat": "flight",
                "ph": "i",
                "s": "p",
                "ts": (float(e["t"]) - off) * 1e6,
                "pid": lane[name],
                "tid": 0,
                "args": {
                    "seq": e.get("seq"),
                    **{
                        k: (v if isinstance(v, (int, float, bool, str))
                            else str(v))
                        for k, v in (e.get("fields") or {}).items()
                    },
                },
            })
    return events


def export_fleet_trace(
    path: str,
    stitched: Sequence[Mapping],
    *,
    member_status: Optional[Mapping] = None,
    flight_events: Optional[Mapping[str, Sequence[Mapping]]] = None,
    flight_offsets: Optional[Mapping[str, float]] = None,
    extra: Optional[Dict] = None,
) -> int:
    """Atomically write ONE merged fleet timeline. The per-member clock
    offsets/uncertainties and drop accounting ride ``otherData``."""
    from photon_ml_tpu.reliability import atomic_write_json

    events = fleet_chrome_events(
        stitched,
        flight_events=flight_events,
        flight_offsets=flight_offsets,
    )
    verification = verify_fleet_trace(stitched)
    atomic_write_json(path, {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "members": dict(member_status or {}),
            "verification": verification,
            **(extra or {}),
        },
    })
    return len(events)


# -- fleet-wide conservation -------------------------------------------------------


def fleet_check_conservation(
    router_book: Mapping,
    shard_books: Mapping[str, Mapping],
) -> Dict[str, object]:
    """Balance the WHOLE fleet's request ledger.

    ``router_book`` is the router process's ``check_conservation()``
    dict — every terminal attributed (``shard:<i>`` / ``cache`` /
    ``degraded`` / ``no_shard`` / ``shed``). ``shard_books`` maps member
    name -> ``{"conservation": <dict>, "complete": bool,
    "shard_indices": [i, ...]}``.

    Checks, in order:

    1. the router's own books balance: admitted == Σ terminals, with
       the per-generation split re-summing;
    2. the attribution table re-sums to the terminal total (every
       admitted request landed in exactly one bucket — a dropped
       response is a hole HERE);
    3. every CLEANLY-drained shard book balances internally, its
       per-generation split re-sums, and it served at least every
       sub-request the router attributed to it (hedges / abandoned-but-
       served sub-requests make the shard side >=, never ==);
    4. a shard whose book is a mid-flight snapshot (SIGKILLed: the
       auto-dumped transition ring is all that survives) is joined
       advisorily — reported, never failed.
    """
    attr = dict(router_book.get("terminal_by_attribution") or {})
    attr_total = sum(attr.values())
    terminal_total = int(router_book.get("terminal_total") or 0)
    by_gen = router_book.get("terminal_by_generation") or {}
    router_ok = bool(router_book.get("ok"))
    attribution_ok = attr_total == terminal_total
    gen_ok = sum(by_gen.values()) == terminal_total
    ok = router_ok and attribution_ok and gen_ok
    shards_out: Dict[str, Dict[str, object]] = {}
    for name, book in sorted(shard_books.items()):
        cons = book.get("conservation") or {}
        complete = bool(book.get("complete", True))
        indices = list(book.get("shard_indices") or [])
        attributed = sum(
            v for k, v in attr.items()
            if k.startswith("shard:")
            and k.split(":", 1)[1].isdigit()
            and int(k.split(":", 1)[1]) in indices
        ) if indices else None
        served_ok = int((cons.get("terminal") or {}).get("ok") or 0)
        entry: Dict[str, object] = {
            "complete": complete,
            "book_ok": bool(cons.get("ok")),
            "admitted": cons.get("admitted"),
            "served_ok": served_ok,
            "router_attributed": attributed,
            "terminal_by_generation": cons.get("terminal_by_generation"),
        }
        if not complete:
            # last-transition snapshot: requests served after the dump
            # are invisible, so neither direction of the join is sound
            entry["join_ok"] = None
        else:
            join_ok = bool(cons.get("ok"))
            if attributed is not None:
                join_ok = join_ok and served_ok >= attributed
            entry["join_ok"] = join_ok
            ok = ok and join_ok
        shards_out[name] = entry
    return {
        "ok": ok,
        "router_ok": router_ok,
        "attribution_ok": attribution_ok,
        "generation_split_ok": gen_ok,
        "admitted": router_book.get("admitted"),
        "terminal_total": terminal_total,
        "terminal_by_attribution": attr,
        "terminal_by_generation": dict(by_gen),
        "shards": shards_out,
    }


# -- post-hoc merge ------------------------------------------------------------------


def spans_from_chrome_export(data: Mapping) -> List[Dict]:
    """Normalize an already-exported per-process ``trace.json`` back
    into span dicts (wall-mapped; ``serving.score`` leaves were already
    expanded by the exporter)."""
    out: List[Dict] = []
    for e in data.get("traceEvents") or []:
        if e.get("ph") != "X":
            continue
        args = dict(e.get("args") or {})
        out.append({
            "name": e.get("name"),
            "trace_id": args.pop("trace_id", None),
            "span_id": args.pop("span_id", None),
            "parent_id": args.pop("parent_span", None),
            "t0": float(e["ts"]) / 1e6,
            "t1": (float(e["ts"]) + float(e.get("dur") or 0.0)) / 1e6,
            "tid": e.get("tid"),
            "seq": None,
            "attrs": args,
        })
    return out


def load_obs_dump(obs_dir: str, *, name: Optional[str] = None) -> Dict:
    """Read one ``--obs-dir``: the exported ``trace.json`` (if the
    process lived to export one) and the ``flight.json`` ring/book (the
    auto-dump survives a SIGKILL). ``complete`` reflects whether the
    flight book was written by a clean drain/exit — anything else is a
    mid-flight snapshot."""
    out: Dict[str, object] = {
        "dir": obs_dir,
        "name": name or os.path.basename(os.path.normpath(obs_dir)),
        "spans": [],
        "pid": None,
        "flight": None,
        "conservation": None,
        "complete": False,
    }
    trace_path = os.path.join(obs_dir, "trace.json")
    if os.path.exists(trace_path):
        with open(trace_path) as f:
            data = json.load(f)
        out["spans"] = spans_from_chrome_export(data)
        out["pid"] = (data.get("otherData") or {}).get("pid")
    flight_path = os.path.join(obs_dir, "flight.json")
    if os.path.exists(flight_path):
        with open(flight_path) as f:
            flight = json.load(f)
        out["flight"] = flight
        out["conservation"] = flight.get("conservation")
        out["pid"] = out["pid"] or flight.get("pid")
        out["complete"] = flight.get("reason") in ("exit", "drain")
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m photon_ml_tpu.obs.fleet <obs-dir>... [-o OUT]`` —
    merge post-hoc per-process dumps into one fleet timeline + a fleet
    conservation verdict. Exit 0 when the merged trace verifies and
    conservation balances, 1 otherwise."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.obs.fleet",
        description="merge per-process --obs-dir dumps into one "
        "fleet_trace.json + fleet_conservation.json",
    )
    ap.add_argument("obs_dirs", nargs="+", help="per-process obs dirs")
    ap.add_argument(
        "-o", "--out", default=".",
        help="output directory (default: cwd)",
    )
    ap.add_argument(
        "--router", default=None,
        help="member name holding the ROUTER conservation book "
        "(default: auto-detected by its attribution table)",
    )
    ns = ap.parse_args(argv)
    dumps = [load_obs_dump(d) for d in ns.obs_dirs]
    names = [d["name"] for d in dumps]
    if len(set(names)) != len(names):
        # disambiguate duplicate basenames by position
        for i, d in enumerate(dumps):
            d["name"] = f"{d['name']}#{i}"
    payloads = [
        {
            "name": d["name"],
            "pid": d["pid"],
            "spans": d["spans"],
            "epoch_wall": None,
            "epoch_perf": None,
            "offset_s": 0.0,
            "offset_unc_s": None,  # post-hoc: no live exchange to sync
            "wall_mapped": True,
        }
        for d in dumps
    ]
    stitched = stitch_spans(payloads)
    verification = verify_fleet_trace(stitched)
    flight_events = {
        d["name"]: (d["flight"] or {}).get("events") or []
        for d in dumps
        if d.get("flight")
    }
    os.makedirs(ns.out, exist_ok=True)
    trace_out = os.path.join(ns.out, "fleet_trace.json")
    n_events = export_fleet_trace(
        trace_out,
        stitched,
        flight_events=flight_events,
        member_status={
            d["name"]: {
                "dir": d["dir"],
                "pid": d["pid"],
                "spans": len(d["spans"]),
                "complete": d["complete"],
                "clock_offset_s": 0.0,
                "clock_offset_uncertainty_s": None,
            }
            for d in dumps
        },
        extra={"mode": "post-hoc", "merged_at": time.time()},
    )
    # conservation: the router book is the one whose terminals carry a
    # full attribution table (or the named one)
    router_dump = None
    if ns.router is not None:
        router_dump = next(
            (d for d in dumps if d["name"] == ns.router), None
        )
        if router_dump is None:
            print(f"no obs dir named {ns.router!r}", flush=True)
            return 2
    else:
        for d in dumps:
            cons = d.get("conservation") or {}
            attr = cons.get("terminal_by_attribution") or {}
            if attr and sum(attr.values()) == cons.get("terminal_total"):
                router_dump = d
                break
    conservation = None
    if router_dump is not None and router_dump.get("conservation"):
        shard_books = {
            d["name"]: {
                "conservation": d.get("conservation") or {},
                "complete": d["complete"],
                "shard_indices": None,  # unknown post-hoc: internal-only
            }
            for d in dumps
            if d is not router_dump and d.get("conservation")
        }
        conservation = fleet_check_conservation(
            router_dump["conservation"], shard_books
        )
        from photon_ml_tpu.reliability import atomic_write_json

        atomic_write_json(
            os.path.join(ns.out, "fleet_conservation.json"), conservation
        )
    ok = verification["ok"] and (
        conservation is None or conservation["ok"]
    )
    print(json.dumps({  # photon: entropy(operator-facing merge report; carries live merge wall-time by design)
        "fleet_trace": trace_out,
        "events": n_events,
        "members": len(dumps),
        "verification_ok": verification["ok"],
        "violations": verification["violations"][:5],
        "conservation_ok": (
            None if conservation is None else conservation["ok"]
        ),
    }, indent=2), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
