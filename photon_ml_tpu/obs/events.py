"""Typed structured events + emitter — the ONE structured-event path.

This is the former ``photon_ml_tpu.events`` module (the reference's
EventEmitter.scala shape: typed events, registered listeners,
synchronized fan-out) folded into the obs plane: every ``send`` now
ALSO files the event into the process flight recorder as
``event.<ClassName>``, so driver-level lifecycle events (setup,
training start/finish, per-λ optimization logs, schedule-cache stats)
land on the same ordered timeline as swap/rollback/fault transitions
instead of living in a parallel, listener-only world.

``photon_ml_tpu.events`` remains as a thin compat shim re-exporting
everything here — existing emit sites and tests work unchanged.

Reference: photon-ml .../event/Event.scala:27-64,
EventEmitter.scala:88-130, EventListener.scala; listeners injected by
class name via ``--event-listeners`` (Driver.scala:110-119).
"""

from __future__ import annotations

import importlib
import threading
from dataclasses import dataclass, field, fields, is_dataclass
from typing import Any, Dict, List

__all__ = [
    "Event",
    "PhotonSetupEvent",
    "TrainingStartEvent",
    "TrainingFinishEvent",
    "PhotonOptimizationLogEvent",
    "ScheduleCacheEvent",
    "EventListener",
    "EventEmitter",
]


@dataclass(frozen=True)
class Event:
    pass


@dataclass(frozen=True)
class PhotonSetupEvent(Event):
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class TrainingStartEvent(Event):
    job_name: str = ""


@dataclass(frozen=True)
class TrainingFinishEvent(Event):
    job_name: str = ""


@dataclass(frozen=True)
class PhotonOptimizationLogEvent(Event):
    reg_weight: float = 0.0
    iterations: int = 0
    convergence_reason: str = ""
    final_value: float = 0.0
    metrics: Dict[str, float] = field(default_factory=dict)


@dataclass(frozen=True)
class ScheduleCacheEvent(Event):
    """Tile-schedule cache outcome for one training stage: hit/miss/build
    counters plus the host-side build/load/store timers
    (ops/schedule_cache.py). Emitted by the drivers after training so
    listeners can track cold-vs-warm schedule cost per run."""

    stats: Dict[str, float] = field(default_factory=dict)


class EventListener:
    def on_event(self, event: Event) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


def _event_fields(event: Event) -> Dict[str, object]:
    """Shallow field view for the flight-recorder record: scalars pass
    through, containers degrade to their repr at dump time (the
    recorder dumps with ``default=str``)."""
    if not is_dataclass(event):
        return {}
    return {f.name: getattr(event, f.name) for f in fields(event)}


class EventEmitter:
    """Thread-safe fan-out of events to registered listeners, with the
    flight recorder as the always-on structural listener."""

    def __init__(self):
        self._listeners: List[EventListener] = []
        self._lock = threading.Lock()

    def register(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def register_by_name(self, class_path: str) -> None:
        """Instantiate `pkg.module.Class` by name (--event-listeners)."""
        module_name, _, cls_name = class_path.rpartition(".")
        cls = getattr(importlib.import_module(module_name), cls_name)
        self.register(cls())

    def send(self, event: Event) -> None:
        from photon_ml_tpu.obs.flight_recorder import flight_recorder

        flight_recorder().record(
            f"event.{type(event).__name__}", **_event_fields(event)
        )
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            listener.on_event(event)

    def close(self) -> None:
        with self._lock:
            listeners = list(self._listeners)
            self._listeners.clear()
        for listener in listeners:
            listener.close()
