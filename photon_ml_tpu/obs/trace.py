"""End-to-end request + training tracing: lightweight host-side spans
with wire propagation and Chrome trace-event export.

The unified-telemetry half the ``jax.profiler`` device timelines cannot
give us: WHERE a request (or a CD iteration) spent its wall time across
the fleet — frontend accept, router scatter, shard-server dispatch,
micro-batch execution — correlated by one trace id minted at the edge
and carried on the wire in the request/response JSON
(``trace_id`` / ``parent_span`` keys; see :data:`TRACE_KEY`).

Design constraints, in priority order:

- **Host arithmetic only.** Nothing in this module (or anywhere in
  ``photon_ml_tpu/obs/``) may touch a jax value — telemetry must never
  add a device sync, a lowering, or a readback. Pinned by
  ``tests/test_lint_clean.py`` (no ``jax`` import anywhere in obs/).
- **No locks on the dispatch hot path.** Span ids come from
  ``itertools.count`` (atomic at the C level) and finished spans land
  in a bounded ``collections.deque`` (``maxlen`` ring — atomic append
  under the GIL). Recording a span acquires NO lock, so tracing can
  stay on in production without adding a contention point to the
  batcher's device section. ``drain()`` swaps the ring under the
  tracer's own lock (never taken by ``record``/``end``).
- **Off by default, free when off.** ``tracing_enabled()`` is one
  module-global read; every instrumentation site calls ``span()`` /
  ``start_span()`` which return the no-op singleton when disabled —
  the A/B in ``dev-scripts/bench_obs.sh`` prices the enabled path
  (<2% request-path overhead gate) and the disabled path is a branch.

Timestamps are ``time.perf_counter()`` pairs mapped onto the wall clock
through one (wall, perf) epoch captured at import, so spans from one
process share a consistent timeline and export directly as Chrome
trace-event JSON (``ph: "X"`` complete events) that loads in Perfetto /
``chrome://tracing`` NEXT TO a ``--profile-dir`` device trace captured
in the same run.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterable, List, Mapping, Optional

__all__ = [
    "TRACE_KEY",
    "PARENT_KEY",
    "Span",
    "Tracer",
    "tracer",
    "reset_tracer",
    "tracing_enabled",
    "set_tracing",
    "tracing_scope",
    "span",
    "start_span",
    "record_span",
    "traced",
    "expand_spans",
    "TRACES_ATTR",
    "new_trace_id",
    "wire_context",
    "epoch",
    "epoch_now",
    "chrome_trace_events",
    "export_chrome_trace",
]

# Wire keys: a request JSON object carrying these joins the sender's
# trace; responses echo TRACE_KEY so the client can stitch both sides.
TRACE_KEY = "trace_id"
PARENT_KEY = "parent_span"

DEFAULT_MAX_SPANS = 1 << 16


def _env_int(name: str, default: int) -> int:
    """Ring bound from the environment (PHOTON_TRACE_SPANS /
    PHOTON_FLIGHT_EVENTS, mirroring the PHOTON_TRACE switch)."""
    try:
        return max(int(os.environ.get(name, "")), 1)
    except ValueError:
        return default


# One (wall, perf) epoch per process: every span's perf_counter pair
# maps onto the wall clock through it, so cross-process traces line up
# to clock-sync accuracy without per-span time.time() calls. The fleet
# collector's NTP-style skew estimation measures a remote process's
# "now" through THIS mapping (see epoch_now), so the offset it derives
# corrects exactly the timeline the spans are exported on.
_EPOCH_WALL = time.time()  # photon: entropy(per-boot span-epoch anchor; the wall/perf pair IS the timeline contract)
_EPOCH_PERF = time.perf_counter()  # photon: entropy(per-boot span-epoch anchor; paired with _EPOCH_WALL)


def epoch() -> tuple:
    """This process's (wall, perf) epoch — the span-time -> wall-clock
    mapping, served on the wire by the ``{"op": "trace"}`` control op."""
    return (_EPOCH_WALL, _EPOCH_PERF)


def epoch_now() -> float:
    """"Now" as the span timeline sees it: the wall clock REACHED BY
    the epoch mapping (not a fresh time.time(), which may have drifted
    from it) — what the fleet collector's skew estimate must target."""
    return _EPOCH_WALL + (time.perf_counter() - _EPOCH_PERF)


# Id mints. itertools.count.__next__ is atomic (implemented in C), so
# minting needs no lock; the pid + boot-nonce prefix keeps ids unique
# across a multi-process (and multi-HOST — pids alone can collide
# across boxes) fleet whose spans are merged into one timeline.
_TRACE_IDS = itertools.count(1)
_SPAN_IDS = itertools.count(1)
_PROC_NONCE = os.urandom(3).hex()  # photon: entropy(boot nonce; id uniqueness across hosts REQUIRES per-process randomness)
_TRACE_PREFIX = f"t{os.getpid():x}.{_PROC_NONCE}-"  # photon: entropy(pid+nonce id prefix; cross-process uniqueness, not content)
_SPAN_PREFIX = f"s{os.getpid():x}.{_PROC_NONCE}-"  # photon: entropy(pid+nonce id prefix; cross-process uniqueness, not content)

# Enablement is a single module global: the disabled fast path is one
# read + branch. set_tracing is the only writer (driver startup / test
# scopes) — a torn read is impossible for a bool.
_ENABLED = os.environ.get("PHOTON_TRACE", "").strip().lower() in (
    "1", "true", "yes"
)


def tracing_enabled() -> bool:
    return _ENABLED


def set_tracing(enabled: bool) -> None:
    global _ENABLED
    _ENABLED = bool(enabled)


@contextmanager
def tracing_scope(enabled: bool):
    """Temporarily force tracing on/off (tests, A/B benches)."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(enabled)
    try:
        yield
    finally:
        _ENABLED = prev


def new_trace_id() -> str:
    return _TRACE_PREFIX + str(next(_TRACE_IDS))


def _new_span_id() -> str:
    return _SPAN_PREFIX + str(next(_SPAN_IDS))


class Span:
    """One timed operation. ``end()`` stamps the close time and files
    the span with its tracer — exactly once; a double end is a no-op."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "t0", "t1", "tid", "attrs", "seq", "_tracer",
    )

    def __init__(
        self,
        tracer_obj: "Tracer",
        name: str,
        trace_id: Optional[str],
        parent_id: Optional[str],
        attrs: Optional[Dict[str, object]],
        t0: Optional[float] = None,
    ):
        self.name = name
        self.trace_id = trace_id or new_trace_id()
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        self.t1: Optional[float] = None
        self.tid = threading.get_ident()
        self.attrs = dict(attrs) if attrs else {}
        self.seq = 0  # stamped by Tracer._file when the span is filed
        self._tracer = tracer_obj

    def end(self, t1: Optional[float] = None, **attrs) -> "Span":
        if self.t1 is not None:
            return self  # already filed
        self.t1 = time.perf_counter() if t1 is None else float(t1)
        if attrs:
            self.attrs.update(attrs)
        self._tracer._file(self)
        return self

    @property
    def duration_s(self) -> float:
        return (self.t1 if self.t1 is not None else self.t0) - self.t0

    def to_dict(self) -> Dict[str, object]:
        # The span's wire shape — the trace-drain op ships exactly this
        # dict. The binary drain (serving/wire.py MSG_TRACE_RESPONSE)
        # relies on two invariants pinned here: ``t0``/``t1`` are the
        # ONLY float timestamp fields (they ride a raw f64 buffer,
        # everything else rides the JSON header), and ``t1`` is None
        # exactly when the span is unfinished (NaN-encoded in flight).
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t0": self.t0,
            "t1": self.t1,
            "tid": self.tid,
            "seq": self.seq,
            "attrs": dict(self.attrs),
        }


class _NullSpan:
    """The disabled path: every method a no-op, one shared instance."""

    __slots__ = ()
    name = ""
    trace_id = None
    span_id = None
    parent_id = None
    t0 = 0.0
    t1 = 0.0
    tid = 0
    seq = 0
    attrs: Dict[str, object] = {}
    duration_s = 0.0

    def end(self, t1=None, **attrs):
        return self

    def to_dict(self):
        return {}


NULL_SPAN = _NullSpan()


class Tracer:
    """Bounded collector of finished spans.

    ``_file`` (the record side) is a lock-free ring append; the lock
    exists only for the drain/snapshot side, where it serializes the
    ring SWAP — a dump concurrent with span emission sees a consistent
    prefix, never a torn iteration (``deque`` mutation during iteration
    raises, so snapshots take the whole ring by swap instead).
    """

    def __init__(self, max_spans: Optional[int] = None):
        # ring bound: explicit arg > PHOTON_TRACE_SPANS > default. The
        # chosen bound rides every export's otherData so post-hoc drop
        # accounting is interpretable.
        self.max_spans = (
            int(max_spans)
            if max_spans is not None
            else _env_int("PHOTON_TRACE_SPANS", DEFAULT_MAX_SPANS)
        )
        # single-writer-per-append ring; appends are GIL-atomic. The
        # reference itself is swapped only under _lock (drain).
        self._ring = deque(maxlen=self.max_spans)  # photon: guarded-by(atomic)
        self._lock = threading.Lock()
        # total spans ever filed: the counter bump is C-level-atomic
        # (itertools.count), the published value a plain reference
        # assignment — drops derive as filed - retained, so a capped
        # export is visibly capped without a lock on the record path
        self._counter = itertools.count(1)  # photon: guarded-by(atomic)
        self._filed = 0  # photon: guarded-by(atomic)

    @property
    def dropped(self) -> int:
        return max(0, self._filed - len(self._ring))

    def _file(self, s: Span) -> None:
        # seq stamps a process-monotone order onto the ring so the
        # {"op": "trace"} drain can be cursor-keyed: a poll never
        # duplicates (seq > cursor filter) and never silently drops
        # (gaps in the seq line are counted eviction). Still lock-free:
        # the counter bump is C-atomic, the append GIL-atomic.
        s.seq = next(self._counter)
        self._filed = s.seq
        self._ring.append(s)

    def start(
        self,
        name: str,
        *,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, object]] = None,
        t0: Optional[float] = None,
    ) -> Span:
        return Span(self, name, trace_id, parent_id, attrs, t0=t0)

    def record(
        self,
        name: str,
        t0: float,
        t1: float,
        *,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, object]] = None,
    ) -> Span:
        """A span whose window already elapsed (the batcher stamps its
        dispatch window after the device section, off the locked path).
        This is the request-path fast path: the Span is assembled
        directly (no re-stamping, no attrs copy) and ring-appended —
        one object allocation plus one GIL-atomic append."""
        s = Span.__new__(Span)
        s.name = name
        s.trace_id = trace_id if trace_id is not None else new_trace_id()
        s.span_id = _new_span_id()
        s.parent_id = parent_id
        s.t0 = t0
        s.t1 = t1
        s.tid = threading.get_ident()
        s.attrs = attrs if attrs is not None else {}
        s._tracer = self
        self._file(s)
        return s

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def read_since(self, cursor: int):
        """Incremental, cursor-keyed read for the ``{"op": "trace"}``
        drain: returns ``(spans, new_cursor, dropped)`` where ``spans``
        are the finished spans with ``seq > cursor`` in a CONTIGUOUS
        seq run, ``new_cursor`` is the last returned seq (pass it back
        on the next poll), and ``dropped`` counts spans filed after the
        cursor but already evicted from the ring before this poll could
        read them.

        Two subtleties make the contract exact:

        - a span whose seq is minted but whose ring append has not yet
          landed (the record path is lock-free) would leave a MID-run
          gap; the run stops there and the next poll picks it up —
          never skipped, never duplicated;
        - a cursor AHEAD of the filed count means the ring was reset
          (drain()/clear()/process restart): the read restarts from the
          beginning rather than silently returning nothing forever.
        """
        cursor = int(cursor)
        with self._lock:
            if cursor > self._filed:
                cursor = 0
            fresh = sorted(
                (s for s in self._ring if s.seq > cursor),
                key=lambda s: s.seq,
            )
            if not fresh:
                return [], cursor, 0
            # front gap = spans evicted between polls (ring wrapped)
            dropped = fresh[0].seq - cursor - 1
            out = [fresh[0]]
            for s in fresh[1:]:
                if s.seq != out[-1].seq + 1:
                    break  # mid gap: a span is mid-file; resume next poll
                out.append(s)
            return out, out[-1].seq, max(dropped, 0)

    def drain(self) -> List[Span]:
        with self._lock:
            ring, self._ring = self._ring, deque(maxlen=self.max_spans)
            self._counter = itertools.count(1)
            self._filed = 0
            return list(ring)

    def clear(self) -> None:
        with self._lock:
            self._ring = deque(maxlen=self.max_spans)
            self._counter = itertools.count(1)
            self._filed = 0

    def __len__(self) -> int:
        return len(self._ring)


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process-wide tracer every instrumentation site files into."""
    return _TRACER


def reset_tracer() -> Tracer:
    """Fresh process-wide tracer, re-reading PHOTON_TRACE_SPANS (tests
    / driver re-entry). Spans already handed out keep filing into the
    old ring — a reset mid-traffic loses them, so call it quiescent."""
    global _TRACER
    _TRACER = Tracer()
    return _TRACER


def start_span(
    name: str,
    *,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    **attrs,
):
    """Open a span on the process tracer (no-op singleton when tracing
    is off — the call sites never branch themselves). Assembled
    directly: the ``**attrs`` dict is freshly built for this call, so
    the span owns it without the defensive copy ``Span.__init__``
    makes — this is the request-path open (router request/sub-request,
    frontend request), priced by dev-scripts/bench_fleet_obs.sh."""
    if not _ENABLED:
        return NULL_SPAN
    s = Span.__new__(Span)
    s.name = name
    s.trace_id = trace_id if trace_id is not None else new_trace_id()
    s.span_id = _new_span_id()
    s.parent_id = parent_id
    s.t0 = time.perf_counter()
    s.t1 = None
    s.tid = threading.get_ident()
    s.attrs = attrs
    s.seq = 0
    s._tracer = _TRACER
    return s


def record_span(
    name: str,
    t0: float,
    t1: float,
    *,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    **attrs,
) -> None:
    if not _ENABLED:
        return
    _TRACER.record(
        name, t0, t1,
        trace_id=trace_id, parent_id=parent_id, attrs=attrs or None,
    )


@contextmanager
def span(
    name: str,
    *,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    **attrs,
):
    """``with span("cd.iteration", iteration=3):`` — times the block.
    Yields the open span so callers can attach result attrs."""
    s = start_span(name, trace_id=trace_id, parent_id=parent_id, **attrs)
    try:
        yield s
    finally:
        s.end()


def traced(name: str, **span_attrs):
    """Decorator: the whole call becomes one span (streaming scan/stage
    passes and other coarse phases). Zero overhead when tracing is off
    beyond one flag read."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _ENABLED:
                return fn(*args, **kwargs)
            with span(name, **span_attrs):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def wire_context(record: Mapping) -> tuple:
    """(trace_id, parent_span_id) carried on a wire request, or
    (None, None) — the frontend mints a fresh trace for bare requests."""
    t = record.get(TRACE_KEY)
    p = record.get(PARENT_KEY)
    return (None if t is None else str(t), None if p is None else str(p))


# The dispatch hot path records ONE span per batch; the per-request
# leaves are synthesized from this attr at export time (constant work
# per dispatch on the request path, per-request work only when someone
# actually looks at the trace).
TRACES_ATTR = "traces"


def expand_spans(spans: Iterable[Span]) -> List[Span]:
    """Materialize per-request child spans from batch-level spans.

    A span carrying ``attrs[TRACES_ATTR] = [(trace_id, parent_span,
    degraded), ...]`` (the batcher's dispatch span) expands into one
    ``serving.score`` child per entry, sharing the batch's dispatch
    window and parented under each request's own wire span — the leaf
    that connects a routed request's trace to the device dispatch that
    served it. Returns originals + synthesized children; the originals'
    attrs are untouched."""
    out: List[Span] = []
    for s in spans:
        out.append(s)
        traces = s.attrs.get(TRACES_ATTR) if s.attrs else None
        if not traces:
            continue
        for entry in traces:
            trace_id, parent_id, degraded = entry
            child = Span.__new__(Span)
            child.name = "serving.score"
            child.trace_id = trace_id
            child.span_id = _new_span_id()
            child.parent_id = parent_id
            child.t0 = s.t0
            child.t1 = s.t1
            child.tid = s.tid
            child.seq = s.seq
            child.attrs = {
                "degraded": bool(degraded),
                "dispatch_span": s.span_id,
                **{
                    k: v for k, v in s.attrs.items()
                    if k in ("generation", "shape")
                },
            }
            child._tracer = s._tracer
            out.append(child)
    return out


# -- export -------------------------------------------------------------------


def _wall_us(perf_t: float) -> float:
    return (_EPOCH_WALL + (perf_t - _EPOCH_PERF)) * 1e6


def chrome_trace_events(spans: Iterable[Span]) -> List[Dict[str, object]]:
    """Chrome trace-event "complete" (``ph: "X"``) records: what
    Perfetto and chrome://tracing load, and the same container the
    ``jax.profiler`` device trace exports to — host spans and device
    timelines open side by side. Batch-level spans expand into their
    per-request leaves here (see :func:`expand_spans`)."""
    pid = os.getpid()
    out: List[Dict[str, object]] = []
    for s in expand_spans(spans):
        if s.t1 is None:
            continue
        args: Dict[str, object] = {
            "trace_id": s.trace_id,
            "span_id": s.span_id,
        }
        if s.parent_id is not None:
            args["parent_span"] = s.parent_id
        for k, v in s.attrs.items():
            if k == TRACES_ATTR:
                args["traced_requests"] = len(v)
                continue
            args[k] = v if isinstance(v, (int, float, bool, str)) else str(v)
        out.append({
            "name": s.name,
            "cat": s.name.split(".", 1)[0],
            "ph": "X",
            "ts": _wall_us(s.t0),
            "dur": max((s.t1 - s.t0) * 1e6, 0.001),
            "pid": pid,
            "tid": s.tid,
            "args": args,
        })
    return out


def export_chrome_trace(  # photon: entropy(trace artifact; pid + boot epoch attribute the timeline to its process by design)
    path: str,
    spans: Optional[Iterable[Span]] = None,
    *,
    extra: Optional[Dict[str, object]] = None,
) -> int:
    """Atomically write the spans (default: the process tracer's current
    ring) as one Chrome trace-event JSON file. Returns the event count."""
    from photon_ml_tpu.reliability import atomic_write_json

    spans = _TRACER.snapshot() if spans is None else list(spans)
    events = chrome_trace_events(spans)
    payload: Dict[str, object] = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "pid": os.getpid(),
            "dropped_spans": _TRACER.dropped,
            # the configured ring bound (PHOTON_TRACE_SPANS) rides the
            # artifact so drop accounting is interpretable post-hoc
            "max_spans": _TRACER.max_spans,
            "epoch_wall": _EPOCH_WALL,
            "epoch_perf": _EPOCH_PERF,
            **(extra or {}),
        },
    }
    atomic_write_json(path, payload)
    return len(events)
