"""Process-wide metrics registry: counters / gauges / bounded
histograms with capped label cardinality, plus read-only VIEWS over the
subsystem accumulators that already exist.

The pre-obs state was per-subsystem snapshots that only materialize in
``metrics.json`` at exit — ``ServingMetrics``, ``RouterMetrics``, the
``utils/profiling`` host-timing buckets, the reliability accounting.
This registry makes them ONE live surface without rewriting any of
them: a subsystem registers a zero-arg ``view`` callable (its existing
``snapshot()``), and :meth:`MetricsRegistry.snapshot` merges every view
next to the registry's own instruments. The frontend's
``{"op": "metrics"}`` control op serves that merged snapshot live
(JSON, or Prometheus-style text via ``{"format": "prometheus"}``), and
:class:`SnapshotWriter` persists it periodically under ``--obs-dir``
through the reliability layer's atomic writers.

Concurrency discipline (PL008–PL010): every mutable structure in this
module is guarded by its owner's single ``_lock``; instrument updates
are one short critical section with no foreign calls inside. Label
cardinality is CAPPED — past ``max_label_sets`` distinct label tuples,
updates collapse into one ``__overflow__`` series (counted), so a
label leak (e.g. a uid smuggled into a label) degrades resolution, not
host memory. Everything here is host arithmetic: obs code never
touches a jax value (pinned by ``tests/test_lint_clean.py``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SnapshotWriter",
    "default_registry",
    "reset_default_registry",
]

DEFAULT_MAX_LABEL_SETS = 64
DEFAULT_HISTOGRAM_BOUNDS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
OVERFLOW = ("__overflow__",)


def _label_key(labels: Mapping[str, str]) -> Tuple[str, ...]:
    return tuple(f"{k}={labels[k]}" for k in sorted(labels))


class _Instrument:
    """Shared label-cardinality plumbing. Subclasses hold per-label
    values in ``self._values`` under ``self._lock``."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, max_label_sets: int):
        self.name = name
        self.help = help_text
        self._max_label_sets = int(max_label_sets)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], object] = {}
        self._overflowed = 0  # photon: guarded-by(_lock)

    def _slot(self, labels: Optional[Mapping[str, str]]) -> Tuple[str, ...]:  # photon: guarded-by(_lock)
        key = _label_key(labels) if labels else ()
        if key not in self._values and len(self._values) >= self._max_label_sets:
            self._overflowed += 1
            return OVERFLOW
        return key

    def series(self) -> Dict[Tuple[str, ...], object]:
        with self._lock:
            return dict(self._values)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {
                "|".join(k) if k else "": v for k, v in self._values.items()
            }
            if self._overflowed:
                out["__overflow_updates__"] = self._overflowed
            return out


class Counter(_Instrument):
    """Monotone counter, optionally labelled: ``c.inc(3, shard="1")``."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        with self._lock:
            key = self._slot(labels)
            self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._values.get(_label_key(labels) if labels else (), 0))

    def total(self) -> float:
        with self._lock:
            return float(sum(
                v for k, v in self._values.items() if k != OVERFLOW
            ) + (self._values.get(OVERFLOW) or 0))


class Gauge(_Instrument):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[self._slot(labels)] = float(v)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            v = self._values.get(_label_key(labels) if labels else ())
            return None if v is None else float(v)


class Histogram(_Instrument):
    """Fixed-bound bucketed histogram (cumulative on export, like the
    Prometheus convention): bounded memory regardless of traffic."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_text: str,
        max_label_sets: int,
        bounds: Sequence[float] = DEFAULT_HISTOGRAM_BOUNDS,
    ):
        super().__init__(name, help_text, max_label_sets)
        self.bounds = tuple(sorted(float(b) for b in bounds))

    def observe(self, v: float, **labels) -> None:
        v = float(v)
        with self._lock:
            key = self._slot(labels)
            cell = self._values.get(key)
            if cell is None:
                cell = {
                    "count": 0,
                    "sum": 0.0,
                    "buckets": [0] * (len(self.bounds) + 1),
                }
                self._values[key] = cell
            cell["count"] += 1
            cell["sum"] += v
            for i, b in enumerate(self.bounds):
                if v <= b:
                    cell["buckets"][i] += 1
                    break
            else:
                cell["buckets"][-1] += 1

    def count(self, **labels) -> int:
        with self._lock:
            cell = self._values.get(_label_key(labels) if labels else ())
            return 0 if cell is None else int(cell["count"])


class MetricsRegistry:
    """Name -> instrument map plus the subsystem views.

    ``counter``/``gauge``/``histogram`` are get-or-create (same name ->
    same instrument; a kind clash raises — two subsystems silently
    sharing a name with different types is a bug, not a merge).
    """

    def __init__(self, *, max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        self._lock = threading.Lock()
        self._max_label_sets = int(max_label_sets)
        self._instruments: Dict[str, _Instrument] = {}
        self._views: Dict[str, Callable[[], object]] = {}

    def _get_or_create(self, name: str, factory, kind: str):  # photon: guarded-by(_lock)
        inst = self._instruments.get(name)
        if inst is None:
            inst = factory()
            self._instruments[name] = inst
        elif inst.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {inst.kind}, "
                f"requested {kind}"
            )
        return inst

    def counter(self, name: str, help_text: str = "") -> Counter:
        with self._lock:
            return self._get_or_create(
                name,
                lambda: Counter(name, help_text, self._max_label_sets),
                "counter",
            )

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        with self._lock:
            return self._get_or_create(
                name,
                lambda: Gauge(name, help_text, self._max_label_sets),
                "gauge",
            )

    def histogram(
        self,
        name: str,
        help_text: str = "",
        bounds: Sequence[float] = DEFAULT_HISTOGRAM_BOUNDS,
    ) -> Histogram:
        with self._lock:
            return self._get_or_create(
                name,
                lambda: Histogram(
                    name, help_text, self._max_label_sets, bounds
                ),
                "histogram",
            )

    def register_view(self, name: str, fn: Callable[[], object]) -> None:
        """Attach a zero-arg callable whose result is merged into every
        snapshot under ``name`` — how ServingMetrics / RouterMetrics /
        host timings / reliability accounting join the live surface
        without being rewritten. Re-registering a name replaces it."""
        with self._lock:
            self._views[name] = fn

    def unregister_view(self, name: str) -> None:
        with self._lock:
            self._views.pop(name, None)

    def _parts(self):
        with self._lock:
            return list(self._instruments.values()), dict(self._views)

    def snapshot(self) -> Dict[str, object]:  # photon: entropy(live metrics surface; ts is the scrape timestamp by contract)
        """The live merged surface: registry instruments + every view.
        A failing view reports its error in place — one wedged
        subsystem must not take down the metrics op."""
        instruments, views = self._parts()
        out: Dict[str, object] = {
            "ts": time.time(),
            "metrics": {
                inst.name: {
                    "kind": inst.kind,
                    "values": inst.snapshot(),
                }
                for inst in sorted(instruments, key=lambda i: i.name)
            },
        }
        for name in sorted(views):
            try:
                out[name] = views[name]()
            except Exception as e:
                out[name] = {"error": str(e)}
        return out

    # -- Prometheus-style text exposition ------------------------------------

    def prometheus(self) -> str:
        """Flat ``# TYPE`` + sample lines for the registry's own
        instruments plus every view's NUMERIC leaves (nested view dicts
        flatten to ``view_key_subkey`` names) — enough for a scrape
        without a client-library dependency."""
        instruments, views = self._parts()
        lines: List[str] = []

        def sanitize(name: str) -> str:
            return "".join(
                c if c.isalnum() or c == "_" else "_" for c in name
            )

        def label_parts(key) -> List[str]:
            if key and key != ("",):
                return [part.replace("=", '="', 1) + '"' for part in key]
            return []

        def sample(name, key, value, extra: str = ""):
            parts = label_parts(key)
            if extra:
                parts.append(extra)
            if parts:
                lines.append(f"{name}{{{','.join(parts)}}} {value}")
            else:
                lines.append(f"{name} {value}")

        for inst in sorted(instruments, key=lambda i: i.name):
            name = sanitize(inst.name)
            lines.append(f"# TYPE {name} {inst.kind}")
            for key, v in sorted(inst.series().items()):
                if inst.kind == "histogram":
                    sample(f"{name}_count", key, v["count"])
                    sample(f"{name}_sum", key, v["sum"])
                    # _bucket lines merge the series' label set with
                    # le, exactly like sample() renders it — two label
                    # sets of one histogram must never emit colliding
                    # unlabeled {le=...} samples
                    cum = 0
                    for b, n in zip(inst.bounds, v["buckets"]):
                        cum += n
                        sample(
                            f"{name}_bucket", key, cum,
                            extra=f'le="{b}"',
                        )
                    cum += v["buckets"][-1]
                    sample(
                        f"{name}_bucket", key, cum, extra='le="+Inf"'
                    )
                else:
                    sample(name, key, v)

        def flatten(prefix: str, obj) -> None:
            if isinstance(obj, Mapping):
                for k in sorted(obj):
                    flatten(f"{prefix}_{sanitize(str(k))}", obj[k])
            elif isinstance(obj, bool):
                lines.append(f"{prefix} {int(obj)}")
            elif isinstance(obj, (int, float)) and obj == obj:
                lines.append(f"{prefix} {obj}")

        for vname in sorted(views):
            try:
                payload = views[vname]()
            except Exception:
                lines.append(f"# view {sanitize(vname)} failed")
                continue
            flatten(sanitize(vname), payload)
        return "\n".join(lines) + "\n"


class SnapshotWriter:
    """Periodic ``--obs-dir`` snapshot thread: every ``period_s`` (and
    once at :meth:`stop`) the merged registry snapshot lands atomically
    in ``<obs_dir>/metrics_snapshot.json`` — a crash leaves the previous
    complete snapshot, never a torn one."""

    def __init__(
        self,
        registry: MetricsRegistry,
        obs_dir: str,
        *,
        period_s: float = 5.0,
        filename: str = "metrics_snapshot.json",
    ):
        self.registry = registry
        self.path = os.path.join(obs_dir, filename)
        self.period_s = max(float(period_s), 0.05)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.writes = 0  # photon: guarded-by(_lock)
        self.write_errors = 0  # photon: guarded-by(_lock)
        self._thread: Optional[threading.Thread] = None

    def _write_once(self) -> None:
        from photon_ml_tpu.reliability import atomic_write_json

        try:
            atomic_write_json(self.path, self.registry.snapshot())
            with self._lock:
                self.writes += 1
        except OSError:
            # a full/unwritable obs dir must never take down the
            # process it observes; the error count is itself visible
            with self._lock:
                self.write_errors += 1

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.period_s):
            self._write_once()

    def start(self) -> "SnapshotWriter":
        self._thread = threading.Thread(
            target=self._loop, name="photon-obs-snapshot", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        """Final snapshot + join: the exit-time file is always current."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        self._write_once()


_DEFAULT_LOCK = threading.Lock()
_DEFAULT: Optional[MetricsRegistry] = None


def default_registry() -> MetricsRegistry:
    """The process-wide registry (created on first use). Drivers wire
    their subsystem views into THIS one so one ``{"op": "metrics"}``
    answers for the whole process."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


def reset_default_registry() -> MetricsRegistry:
    """Fresh process-wide registry (tests / driver re-entry)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = MetricsRegistry()
        return _DEFAULT
