"""Crash/rollback flight recorder: a bounded ring of structured events
plus monotone conservation counters, dumped atomically on SIGTERM,
rollback, and operator request.

When a serving replica rolls back (or a chaos arm SIGKILLs a shard
mid-flood), the question is always "what was the exact sequence?" —
swap staged where, committed when, which circuit opened first, which
fault seam fired. ``metrics.json`` answers "how much"; the flight
recorder answers "in what order": every structured event carries a
process-monotone sequence number and a wall timestamp, the ring is
bounded (old events fall off; the counters do not), and dumps go
through the reliability layer's atomic writer so a dump racing a crash
leaves the previous complete file, never a torn one.

Event sources (each a one-line hook at the subsystem):

- ``swap.stage`` / ``swap.commit`` / ``swap.abort`` / ``swap.rollback``
  — the serving generation protocol (``serving/swap.py``);
- ``watcher.rollback`` / ``watcher.promote`` — registry-driven swaps;
- ``request.shed`` / ``request.deadline`` — overload outcomes;
- ``circuit.open`` / ``circuit.close`` — router shard breakers;
- ``fault.crossing`` — every TRIGGERED injection at a reliability seam;
- ``registry.lease`` / ``registry.publish`` — publication transitions;
- ``event.*`` — the folded :mod:`photon_ml_tpu.obs.events` emitter
  (the ONE structured-event path; the legacy ``photon_ml_tpu.events``
  module is a compat shim over it).

**Conservation.** The recorder also keeps monotone counters fed by the
micro-batcher: ``admitted`` (requests that entered the queue) and
``terminal[outcome]`` (every future resolution, keyed by outcome name
and by the generation that served it). :meth:`check_conservation` is
the end-to-end invariant the ROADMAP's scenario-factory item names —
*every admitted request reaches exactly one named terminal outcome,
conserved across generation swaps* — and the chaos arms call it at
every quiescent point. Counter feeds happen on the submit/resolve
paths (which already take the metrics lock today), never inside the
batcher's locked device section, so the 1-readback / 0-lowering /
no-new-hot-path-locks contract is untouched.

Host arithmetic only: nothing in obs/ touches a jax value (pinned by
``tests/test_lint_clean.py``).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "flight_recorder",
    "reset_flight_recorder",
    "install_signal_dump",
]

DEFAULT_CAPACITY = 4096


def _env_capacity() -> int:
    """Ring bound from PHOTON_FLIGHT_EVENTS (mirroring PHOTON_TRACE /
    PHOTON_TRACE_SPANS); the chosen bound rides every snapshot/dump as
    ``capacity`` so drop accounting is interpretable post-hoc."""
    try:
        return max(int(os.environ.get("PHOTON_FLIGHT_EVENTS", "")), 1)
    except ValueError:
        return DEFAULT_CAPACITY

# Event kinds whose arrival auto-dumps the ring when an auto-dump path
# is armed: low-frequency protocol transitions. A SIGKILLed process
# cannot run an exit handler, but its last swap/rollback transition
# already persisted the ring — which is exactly what the post-mortem
# needs (dev-scripts/chaos_matrix.py reads these dumps).
AUTO_DUMP_KINDS = (
    "swap.", "watcher.", "registry.", "rollback", "fault.",
)


class FlightRecorder:
    """Bounded structured-event ring + conservation counters. One
    instance per process (module singleton below); every method is
    thread-safe under the recorder's single lock — including dumps, so
    a dump concurrent with event emission is never torn."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = (
            int(capacity) if capacity is not None else _env_capacity()
        )
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0  # photon: guarded-by(_lock)
        self._recorded = 0  # photon: guarded-by(_lock)
        self._admitted = 0  # photon: guarded-by(_lock)
        self._terminal: Dict[str, int] = {}  # photon: guarded-by(_lock)
        self._terminal_by_gen: Dict[str, int] = {}  # photon: guarded-by(_lock)
        self._terminal_by_attr: Dict[str, int] = {}  # photon: guarded-by(_lock)
        self._auto_dump_path: Optional[str] = None  # photon: guarded-by(_lock)
        self._dumps = 0  # photon: guarded-by(_lock)
        self._dump_errors = 0  # photon: guarded-by(_lock)

    # -- event side -----------------------------------------------------------

    def record(self, kind: str, **fields) -> int:
        """File one structured event; returns its sequence number.
        Fields must be JSON-representable scalars/containers (enforced
        at dump time via ``default=str`` — a bad field degrades to its
        repr, never a lost dump)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._recorded += 1
            self._ring.append({
                "seq": seq,
                "t": time.time(),
                "kind": str(kind),
                **({"fields": fields} if fields else {}),
            })
            auto = self._auto_dump_path
        if auto is not None and any(
            str(kind).startswith(p) for p in AUTO_DUMP_KINDS
        ):
            self.dump(auto)
        return seq

    def events(self, kind_prefix: str = "") -> List[Dict[str, object]]:
        with self._lock:
            evs = list(self._ring)
        if kind_prefix:
            evs = [e for e in evs if str(e["kind"]).startswith(kind_prefix)]
        return evs

    # -- conservation counters ------------------------------------------------

    def note_admitted(self, n: int = 1) -> None:
        with self._lock:
            self._admitted += int(n)

    def note_terminal(
        self,
        outcome: str,
        *,
        generation: Optional[int] = None,
        attribution: Optional[str] = None,
        n: int = 1,
    ) -> None:
        """One (or n) named terminal outcome(s). ``attribution`` is the
        fleet-conservation split: the router stamps every terminal with
        WHO terminated it (``shard:<i>`` for a wire-served gather keyed
        by the FE-providing shard, ``cache`` for a zero-fan-out hot-
        cache hit, ``degraded`` for FE-only outcomes, ``no_shard`` /
        ``shed`` for refusals), so fleet_check_conservation can balance
        router admitted == Σ shard-attributed + router-local books."""
        with self._lock:
            self._terminal[outcome] = self._terminal.get(outcome, 0) + int(n)
            gen_key = "none" if generation is None else str(generation)
            self._terminal_by_gen[gen_key] = (
                self._terminal_by_gen.get(gen_key, 0) + int(n)
            )
            if attribution is not None:
                self._terminal_by_attr[attribution] = (
                    self._terminal_by_attr.get(attribution, 0) + int(n)
                )

    def check_conservation(self) -> Dict[str, object]:
        """``admitted == sum(terminal outcomes)`` — SLO accounting
        conserved across swaps (the per-generation split must re-sum to
        the same total). ``in_flight`` is the difference; the invariant
        holds at any quiescent point (drained batcher, completed
        flood), so chaos arms assert ``ok`` there."""
        with self._lock:
            terminal_total = sum(self._terminal.values())
            by_gen_total = sum(self._terminal_by_gen.values())
            return {
                "ok": (
                    self._admitted == terminal_total
                    and by_gen_total == terminal_total
                ),
                "admitted": self._admitted,
                "terminal_total": terminal_total,
                "in_flight": self._admitted - terminal_total,
                "terminal": dict(sorted(self._terminal.items())),
                "terminal_by_generation": dict(
                    sorted(self._terminal_by_gen.items())
                ),
                "terminal_by_attribution": dict(
                    sorted(self._terminal_by_attr.items())
                ),
            }

    # -- dumps ----------------------------------------------------------------

    def set_auto_dump(self, path: Optional[str]) -> None:
        """Arm (or disarm with None) dump-on-transition: every
        swap/rollback/registry event persists the ring to ``path``, so
        even a SIGKILLed process leaves its last protocol transition on
        disk. The SIGTERM path dumps via :func:`install_signal_dump`."""
        with self._lock:
            self._auto_dump_path = path

    def snapshot(self) -> Dict[str, object]:  # photon: entropy(live telemetry snapshot; pid attributes the dump to its process)
        with self._lock:
            return {
                "pid": os.getpid(),
                "capacity": self.capacity,
                "recorded": self._recorded,
                "retained": len(self._ring),
                "dropped": self._recorded - len(self._ring),
                "events": list(self._ring),
                "dumps": self._dumps,
                "dump_errors": self._dump_errors,
            }

    def dump(self, path: str, *, reason: str = "") -> Optional[str]:
        """Atomically persist ring + counters + conservation verdict.
        Returns the path, or None when the write failed (counted — the
        recorder must never take down the process it records)."""
        import json

        from photon_ml_tpu.reliability import atomic_write_text

        payload = {
            **self.snapshot(),
            "reason": reason,
            "conservation": self.check_conservation(),
        }
        try:
            # default=str: a non-JSON event field degrades to its repr,
            # never a lost dump
            atomic_write_text(
                path, json.dumps(payload, indent=2, default=str)
            )
        except OSError:
            with self._lock:
                self._dump_errors += 1
            return None
        with self._lock:
            self._dumps += 1
        return path

    def reset(self) -> None:
        with self._lock:
            self._ring = deque(maxlen=self.capacity)
            self._seq = 0
            self._recorded = 0
            self._admitted = 0
            self._terminal = {}
            self._terminal_by_gen = {}
            self._terminal_by_attr = {}
            self._dumps = 0
            self._dump_errors = 0


_SINGLETON_LOCK = threading.Lock()
_RECORDER: Optional[FlightRecorder] = None


def flight_recorder() -> FlightRecorder:
    """The process-wide recorder every hook files into."""
    global _RECORDER
    with _SINGLETON_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def reset_flight_recorder(
    capacity: Optional[int] = None,
) -> FlightRecorder:
    """Fresh process-wide recorder (tests / driver re-entry); the
    default capacity re-reads PHOTON_FLIGHT_EVENTS."""
    global _RECORDER
    with _SINGLETON_LOCK:
        _RECORDER = FlightRecorder(capacity)
        return _RECORDER


def install_signal_dump(
    path: str, signals=(signal.SIGTERM,)
) -> None:
    """Chain a flight-recorder dump onto the given signals' existing
    handlers (main thread only; a non-main-thread caller is a no-op —
    the driver's own drain path still dumps explicitly). The previous
    handler runs AFTER the dump, so the drain protocol is unchanged."""
    rec = flight_recorder()
    for sig in signals:
        try:
            prev = signal.getsignal(sig)
        except (ValueError, OSError):
            continue

        def _handler(signum, frame, _prev=prev):
            rec.record("signal", signum=signum)
            rec.dump(path, reason=f"signal {signum}")
            if callable(_prev):
                _prev(signum, frame)
            elif _prev == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        try:
            signal.signal(sig, _handler)
        except ValueError:
            return  # not the main thread
