"""Evaluator family: typed metrics with string parsing and comparison
direction.

Reference: photon-ml .../evaluation/Evaluator.scala:47-56 (join scores with
(label, offset, weight), compute metric, `betterThan`),
EvaluatorType.scala:63-77 (string forms incl. ``precision@5:queryId`` and
``AUC:documentId`` sharded variants), RMSEEvaluator, the loss evaluators,
ShardedPrecisionAtKEvaluator.scala, plus Evaluation.scala's MetricsMap for
plain GLM validation.

On TPU an evaluator is a pure function over device arrays; the "join" is
gone because scores/labels/weights live in one aligned batch, and sharded
metrics use segmented reductions over dense group ids prepared by the data
layer.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from photon_ml_tpu.evaluation import metrics as M
from photon_ml_tpu.ops.losses import (
    LOGISTIC,
    LINEAR,
    POISSON,
    SMOOTHED_HINGE,
)

Array = jnp.ndarray

_LOSS_BY_NAME = {
    "LOGISTIC_LOSS": LOGISTIC,
    "SQUARED_LOSS": LINEAR,
    "POISSON_LOSS": POISSON,
    "SMOOTHED_HINGE_LOSS": SMOOTHED_HINGE,
}

_PRECISION_RE = re.compile(r"^PRECISION@(\d+):(.+)$", re.IGNORECASE)
_SHARDED_AUC_RE = re.compile(r"^AUC:(.+)$", re.IGNORECASE)


@dataclass(frozen=True)
class EvaluatorType:
    """name in {AUC, AUPR, RMSE, *_LOSS, PRECISION_AT_K}; sharded metrics
    carry the id column name (``id_type``)."""

    name: str
    k: Optional[int] = None
    id_type: Optional[str] = None  # e.g. "queryId" — set => sharded

    @property
    def is_sharded(self) -> bool:
        return self.id_type is not None

    @property
    def maximize(self) -> bool:
        return self.name in ("AUC", "AUPR", "PRECISION_AT_K")

    def better_than(self, a: float, b: float) -> bool:
        return a > b if self.maximize else a < b

    @classmethod
    def parse(cls, s: str) -> "EvaluatorType":
        t = s.strip()
        m = _PRECISION_RE.match(t)
        if m:
            return cls("PRECISION_AT_K", k=int(m.group(1)), id_type=m.group(2))
        m = _SHARDED_AUC_RE.match(t)
        if m:
            return cls("AUC", id_type=m.group(1))
        u = t.upper()
        if u in ("AUC", "AUPR", "RMSE"):
            return cls(u)
        if u in _LOSS_BY_NAME:
            return cls(u)
        raise ValueError(f"unrecognized evaluator type: {s!r}")

    def render(self) -> str:
        if self.name == "PRECISION_AT_K":
            return f"PRECISION@{self.k}:{self.id_type}"
        if self.id_type is not None:
            return f"{self.name}:{self.id_type}"
        return self.name


@dataclass(frozen=True)
class Evaluator:
    """Computes one metric over (scores, labels, weights[, group_ids]).

    ``scores`` must already include offsets (the GAME residual currency) —
    callers pass margins, and mean-space metrics (RMSE) apply the mean
    function first themselves.
    """

    etype: EvaluatorType
    num_groups: Optional[int] = None  # required for sharded metrics

    def evaluate(
        self,
        scores: Array,
        labels: Array,
        weights: Array,
        group_ids: Optional[Array] = None,
    ) -> Array:
        et = self.etype
        if et.is_sharded:
            if group_ids is None or self.num_groups is None:
                raise ValueError(
                    f"{et.render()} requires group_ids and num_groups"
                )
            if et.name == "AUC":
                return M.sharded_auc(
                    group_ids, scores, labels, weights, self.num_groups
                )
            return M.sharded_precision_at_k(
                et.k, group_ids, scores, labels, weights, self.num_groups
            )
        if et.name == "AUC":
            return M.area_under_roc_curve(scores, labels, weights)
        if et.name == "AUPR":
            return M.area_under_precision_recall_curve(scores, labels, weights)
        if et.name == "RMSE":
            return M.root_mean_squared_error(scores, labels, weights)
        loss = _LOSS_BY_NAME[et.name]
        return M.mean_pointwise_loss(loss, scores, labels, weights)

    def better_than(self, a: float, b: float) -> bool:
        return self.etype.better_than(a, b)


def select_best_model(models_by_lambda, evaluate_fn, maximize: bool):
    """Pick (lambda, model, metric) with the best validation metric.

    Reference: ModelSelection.scala:36-63 (selectBestLinearClassifier by
    AUC, selectBestRegressionModel by RMSE, selectBestPoissonRegressionModel
    by log-likelihood).
    """
    best = None
    for lam, model in models_by_lambda.items():
        metric = float(evaluate_fn(model))
        if (
            best is None
            or (maximize and metric > best[2])
            or (not maximize and metric < best[2])
        ):
            best = (lam, model, metric)
    return best
