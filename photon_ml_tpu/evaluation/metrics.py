"""Device-side evaluation metrics: AUC, AUPR, RMSE, weighted losses,
precision@k — all weighted, padding-aware (weight 0 rows vanish), jit-safe.

Reference: photon-ml Evaluation.scala:54-125 (MetricsMap: AUC/AUPR/RMSE/
log-likelihood/AIC via Spark MLlib BinaryClassificationMetrics),
evaluation/AreaUnderROCCurveLocalEvaluator.scala:1-65,
PrecisionAtKLocalEvaluator.scala, RMSEEvaluator.scala and the loss
evaluators (LogisticLossEvaluator.scala etc).

The MLlib sort-and-sweep becomes one device sort + cumulative sums with
exact tie handling (average-rank / trapezoidal semantics, matching MLlib's
grouped-by-threshold curves).
"""

from __future__ import annotations


import jax.numpy as jnp

from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jnp.ndarray


def _tie_groups(sorted_keys: Array) -> Array:
    """Group index per element of a sorted array; equal keys share a group."""
    new_group = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]]
    )
    return jnp.cumsum(new_group) - 1  # int, in [0, n)


def area_under_roc_curve(scores: Array, labels: Array, weights: Array) -> Array:
    """Weighted AUC with exact tie handling (Mann-Whitney U / total mass).

    AUC = sum_pos w_p * (W_neg_below(p) + 0.5 * W_neg_tied(p)) / (Wp * Wn).
    Returns NaN when either class has zero weight (reference returns NaN via
    MLlib on degenerate input).
    """
    n = scores.shape[0]
    order = jnp.argsort(scores)
    s, y, w = scores[order], labels[order], weights[order]
    pos_w = w * (y > 0.5)
    neg_w = w * (y <= 0.5)
    g = _tie_groups(s)
    group_neg = jnp.zeros((n,), w.dtype).at[g].add(neg_w)
    excl_cum_neg = jnp.cumsum(group_neg) - group_neg  # neg mass strictly below group
    credit = excl_cum_neg[g] + 0.5 * group_neg[g]
    u = jnp.sum(pos_w * credit)
    wp = jnp.sum(pos_w)
    wn = jnp.sum(neg_w)
    return u / (wp * wn)


def area_under_precision_recall_curve(
    scores: Array, labels: Array, weights: Array
) -> Array:
    """Weighted AUPR with threshold-grouped points and linear interpolation
    between recall levels (MLlib PRCurve semantics: one point per distinct
    score, area by trapezoid with first point (0, p@max))."""
    n = scores.shape[0]
    order = jnp.argsort(-scores)  # descending
    s, y, w = scores[order], labels[order], weights[order]
    pos_w = w * (y > 0.5)
    g = _tie_groups(s)
    group_pos = jnp.zeros((n,), w.dtype).at[g].add(pos_w)
    group_tot = jnp.zeros((n,), w.dtype).at[g].add(w)
    # Per tie-group cumulative (inclusive) true positives / predicted mass.
    cum_pos_g = jnp.cumsum(group_pos)
    cum_tot_g = jnp.cumsum(group_tot)
    wp = jnp.sum(pos_w)
    is_real_group = group_tot > 0  # empty trailing group slots
    precision = jnp.where(cum_tot_g > 0, cum_pos_g / jnp.maximum(cum_tot_g, 1e-30), 0.0)
    recall = jnp.where(wp > 0, cum_pos_g / jnp.maximum(wp, 1e-30), 0.0)
    # Trapezoid over (recall, precision) points, prepending (0, P_first).
    prev_recall = jnp.concatenate([jnp.zeros((1,), recall.dtype), recall[:-1]])
    prev_precision = jnp.concatenate([precision[:1], precision[:-1]])
    seg_area = jnp.where(
        is_real_group,
        (recall - prev_recall) * 0.5 * (precision + prev_precision),
        0.0,
    )
    return jnp.sum(seg_area)


def root_mean_squared_error(
    predictions: Array, labels: Array, weights: Array
) -> Array:
    d = predictions - labels
    return jnp.sqrt(jnp.sum(weights * d * d) / jnp.maximum(jnp.sum(weights), 1e-30))


def mean_pointwise_loss(
    loss: PointwiseLoss,
    margins: Array,
    labels: Array,
    weights: Array,
) -> Array:
    """Weighted mean of a pointwise loss over margins (the reference's
    per-datum loss evaluators divide by total weight)."""
    total = jnp.sum(weights * loss.value(margins, labels))
    return total / jnp.maximum(jnp.sum(weights), 1e-30)


def total_pointwise_loss(
    loss: PointwiseLoss, margins: Array, labels: Array, weights: Array
) -> Array:
    return jnp.sum(weights * loss.value(margins, labels))


def akaike_information_criterion(
    log_likelihood_total: Array, num_parameters: Array
) -> Array:
    """AIC = 2k - 2 ln L; the reference feeds total log-loss as -ln L
    (Evaluation.scala)."""
    return 2.0 * num_parameters + 2.0 * log_likelihood_total


def precision_at_k(
    k: int, scores: Array, labels: Array, weights: Array
) -> Array:
    """Unweighted precision@k over one group: fraction of positives among
    the top-k scored items (PrecisionAtKLocalEvaluator; ranking is by score
    descending, weights only gate row validity)."""
    valid = weights > 0
    masked = jnp.where(valid, scores, -jnp.inf)
    order = jnp.argsort(-masked)
    topk = order[:k]
    hits = (labels[topk] > 0.5) & valid[topk]
    denom = jnp.minimum(jnp.sum(valid), k)
    return jnp.sum(hits) / jnp.maximum(denom, 1)


def f1_score(
    predictions: Array, labels: Array, weights: Array
) -> Array:
    """Weighted F1 for binary 0/1 predictions."""
    tp = jnp.sum(weights * (predictions > 0.5) * (labels > 0.5))
    fp = jnp.sum(weights * (predictions > 0.5) * (labels <= 0.5))
    fn = jnp.sum(weights * (predictions <= 0.5) * (labels > 0.5))
    return 2.0 * tp / jnp.maximum(2.0 * tp + fp + fn, 1e-30)


# ---------------------------------------------------------------------------
# Sharded (grouped-by-id) metrics — the reference's ShardedEvaluator family.
# ---------------------------------------------------------------------------


def sharded_auc(
    group_ids: Array,
    scores: Array,
    labels: Array,
    weights: Array,
    num_groups: int,
) -> Array:
    """Mean per-group AUC over groups that have both classes.

    Reference: evaluation/ShardedAreaUnderROCCurveEvaluator — groupBy
    document id, local AUC per group, unweighted average. Here the groupBy
    is a lexsort + segmented cumulative sums; ``group_ids`` must be dense
    ints in [0, num_groups).
    """
    n = scores.shape[0]
    order = jnp.lexsort((scores, group_ids))
    gid, s, y, w = group_ids[order], scores[order], labels[order], weights[order]
    pos_w = w * (y > 0.5)
    neg_w = w * (y <= 0.5)
    # Tie groups keyed by (group, score): new group when either changes.
    new_group = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            (gid[1:] != gid[:-1]) | (s[1:] != s[:-1]),
        ]
    )
    tg = jnp.cumsum(new_group) - 1
    group_neg = jnp.zeros((n,), w.dtype).at[tg].add(neg_w)
    glob_excl = jnp.cumsum(group_neg) - group_neg
    # Per-id segment totals and their exclusive prefix (base at segment start).
    seg_neg_total = jnp.zeros((num_groups,), w.dtype).at[gid].add(neg_w)
    seg_base = jnp.cumsum(seg_neg_total) - seg_neg_total
    # Which id-segment each tie-group belongs to.
    tg_seg = jnp.zeros((n,), gid.dtype).at[tg].max(gid)
    within_excl = glob_excl - seg_base[tg_seg]
    credit = within_excl[tg] + 0.5 * group_neg[tg]
    seg_u = jnp.zeros((num_groups,), w.dtype).at[gid].add(pos_w * credit)
    seg_pos = jnp.zeros((num_groups,), w.dtype).at[gid].add(pos_w)
    valid = (seg_pos > 0) & (seg_neg_total > 0)
    auc = jnp.where(
        valid, seg_u / jnp.maximum(seg_pos * seg_neg_total, 1e-30), 0.0
    )
    return jnp.sum(auc) / jnp.maximum(jnp.sum(valid), 1)


def sharded_precision_at_k(
    k: int,
    group_ids: Array,
    scores: Array,
    labels: Array,
    weights: Array,
    num_groups: int,
) -> Array:
    """Mean per-group precision@k (ShardedPrecisionAtKEvaluator)."""
    n = scores.shape[0]
    valid_row = weights > 0
    masked = jnp.where(valid_row, scores, -jnp.inf)
    order = jnp.lexsort((-masked, group_ids))
    gid, y, v = group_ids[order], labels[order], valid_row[order]
    # Rank within group = position - first position of the group.
    pos = jnp.arange(n)
    is_first = jnp.concatenate([jnp.ones((1,), bool), gid[1:] != gid[:-1]])
    seg_start = jnp.full((num_groups,), n, pos.dtype).at[gid].min(
        jnp.where(is_first, pos, n)
    )
    rank = pos - seg_start[gid]
    in_topk = (rank < k) & v
    seg_hits = jnp.zeros((num_groups,), jnp.float32).at[gid].add(
        (in_topk & (y > 0.5)).astype(jnp.float32)
    )
    seg_count = jnp.zeros((num_groups,), jnp.float32).at[gid].add(
        v.astype(jnp.float32)
    )
    denom = jnp.minimum(seg_count, float(k))
    group_exists = seg_count > 0
    prec = jnp.where(group_exists, seg_hits / jnp.maximum(denom, 1.0), 0.0)
    return jnp.sum(prec) / jnp.maximum(jnp.sum(group_exists), 1)
