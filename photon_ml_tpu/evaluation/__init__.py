"""Evaluation: device-side metrics, evaluator types, model selection."""

from photon_ml_tpu.evaluation.evaluator import (
    Evaluator,
    EvaluatorType,
    select_best_model,
)
from photon_ml_tpu.evaluation.streaming import (
    StreamingAUC,
    StreamingMeanLoss,
    StreamingRMSE,
    finalize_metrics,
    glm_streaming_metrics,
    update_glm_metrics,
)
from photon_ml_tpu.evaluation.metrics import (
    akaike_information_criterion,
    area_under_precision_recall_curve,
    area_under_roc_curve,
    f1_score,
    mean_pointwise_loss,
    precision_at_k,
    root_mean_squared_error,
    sharded_auc,
    sharded_precision_at_k,
    total_pointwise_loss,
)

__all__ = [
    "Evaluator",
    "EvaluatorType",
    "select_best_model",
    "akaike_information_criterion",
    "area_under_precision_recall_curve",
    "area_under_roc_curve",
    "f1_score",
    "mean_pointwise_loss",
    "precision_at_k",
    "root_mean_squared_error",
    "sharded_auc",
    "sharded_precision_at_k",
    "total_pointwise_loss",
    "StreamingAUC",
    "StreamingMeanLoss",
    "StreamingRMSE",
    "finalize_metrics",
    "glm_streaming_metrics",
    "update_glm_metrics",
]
