"""Streaming (bounded-memory) evaluation accumulators.

Reference: the reference evaluates validation data as one more pass over
an RDD (Driver.scala:329-413, Evaluation.scala:54-125) — nothing is ever
materialized on the driver. The in-memory evaluators here
(evaluation/metrics.py) instead sort the WHOLE score vector on device,
which caps validation at host/device RAM. These accumulators restore the
pass-over-chunks shape: the validate directory streams through
``io.streaming.iter_chunks`` and each metric folds one chunk at a time.

- RMSE and the pointwise losses are EXACT (weighted sums commute).
- AUC uses a fixed-bin histogram over the sigmoid-squashed margin
  (AUC is invariant under strictly monotone transforms, so binning
  sigma(z) in (0, 1) loses only within-bin orderings). With the default
  4096 bins the error against the exact sort-based AUC is well under
  1e-3 on realistic score distributions; ties within a bin get the same
  0.5 credit the exact evaluator gives exact ties.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from photon_ml_tpu.ops.losses import PointwiseLoss


class StreamingAUC:
    """Weighted AUC from per-class histograms over sigmoid(margin) bins.

    AUC = sum_b pos_b * (neg_below_b + 0.5 * neg_b) / (P * N): the exact
    Mann-Whitney statistic computed as if every score were rounded to its
    bin center — the fixed-bin-merge analog of MLlib's grouped-by-
    threshold curve. Histograms merge across chunks (and hosts) by
    addition.
    """

    def __init__(self, num_bins: int = 4096):
        self.num_bins = int(num_bins)
        self.pos = np.zeros(self.num_bins, np.float64)
        self.neg = np.zeros(self.num_bins, np.float64)

    def update(self, margins, labels, weights) -> None:
        s = np.asarray(margins, np.float64)
        y = np.asarray(labels, np.float64)
        w = np.asarray(weights, np.float64)
        real = w > 0
        if not real.any():
            return
        s, y, w = s[real], y[real], w[real]
        # monotone squash to (0, 1); stable for |z| large
        p = np.where(s >= 0, 1.0 / (1.0 + np.exp(-s)),
                     np.exp(np.minimum(s, 0)) / (1.0 + np.exp(np.minimum(s, 0))))
        b = np.clip((p * self.num_bins).astype(np.int64), 0, self.num_bins - 1)
        np.add.at(self.pos, b, np.where(y > 0.5, w, 0.0))
        np.add.at(self.neg, b, np.where(y <= 0.5, w, 0.0))

    def merge(self, other: "StreamingAUC") -> "StreamingAUC":
        assert other.num_bins == self.num_bins
        self.pos += other.pos
        self.neg += other.neg
        return self

    def result(self) -> float:
        wp = self.pos.sum()
        wn = self.neg.sum()
        if wp <= 0 or wn <= 0:
            return float("nan")  # degenerate input, like the exact path
        neg_below = np.cumsum(self.neg) - self.neg
        u = np.sum(self.pos * (neg_below + 0.5 * self.neg))
        return float(u / (wp * wn))


class StreamingRMSE:
    """Exact weighted RMSE over mean-space predictions, chunk by chunk."""

    def __init__(self):
        self.sq_sum = 0.0
        self.w_sum = 0.0

    def update(self, predictions, labels, weights) -> None:
        p = np.asarray(predictions, np.float64)
        y = np.asarray(labels, np.float64)
        w = np.asarray(weights, np.float64)
        d = p - y
        self.sq_sum += float(np.sum(w * d * d))
        self.w_sum += float(np.sum(w))

    def result(self) -> float:
        return float(np.sqrt(self.sq_sum / max(self.w_sum, 1e-30)))


class StreamingMeanLoss:
    """Exact weighted mean pointwise loss (margins in, like the
    evaluators in metrics.py)."""

    def __init__(self, loss: PointwiseLoss):
        self.loss = loss
        self.loss_sum = 0.0
        self.w_sum = 0.0

    def update(self, margins, labels, weights) -> None:
        import jax.numpy as jnp

        from photon_ml_tpu.parallel import overlap

        w = jnp.asarray(weights)
        total = jnp.sum(w * self.loss.value(jnp.asarray(margins),
                                            jnp.asarray(labels)))
        # counted seam: one fetch per chunk (the streaming accumulator
        # is host-resident by design; the discipline test still sees it)
        self.loss_sum += float(overlap.device_get(total))
        self.w_sum += float(np.sum(np.asarray(weights, np.float64)))

    def result(self) -> float:
        return float(self.loss_sum / max(self.w_sum, 1e-30))


def glm_streaming_metrics(task, loss: PointwiseLoss):
    """The GLM driver's metric set (driver._metrics_for) as streaming
    accumulators: {metric_name: (accumulator, space)} where space is
    "margin" or "mean" — the caller feeds margins and mean-space
    predictions per chunk via :func:`update_glm_metrics`."""
    from photon_ml_tpu.task import TaskType

    accs: Dict[str, object] = {f"{loss.name}_loss": StreamingMeanLoss(loss)}
    if task in (TaskType.LOGISTIC_REGRESSION,
                TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        accs["AUC"] = StreamingAUC()
    if task in (TaskType.LINEAR_REGRESSION, TaskType.POISSON_REGRESSION):
        accs["RMSE"] = StreamingRMSE()
    return accs


def update_glm_metrics(accs: Dict[str, object], loss: PointwiseLoss,
                       margins, labels, weights) -> None:
    """Fold one chunk into every accumulator of a glm_streaming_metrics
    set. Mean-space metrics (RMSE) apply the loss mean function here, the
    same transform the in-memory driver applies before evaluating."""
    for name, acc in accs.items():
        if isinstance(acc, StreamingRMSE):
            import jax.numpy as jnp

            acc.update(loss.mean(jnp.asarray(margins)), labels, weights)
        else:
            acc.update(margins, labels, weights)


def finalize_metrics(accs: Dict[str, object]) -> Dict[str, float]:
    return {name: acc.result() for name, acc in accs.items()}
