"""Feature normalization as (shift, factor) algebra — never densifying data.

Reference: photon-ml .../normalization/NormalizationContext.scala:119-157 and
NormalizationType.java {NONE, SCALE_WITH_STANDARD_DEVIATION,
SCALE_WITH_MAX_MAGNITUDE, STANDARDIZATION}.

The key trick preserved from the reference (ValueAndGradientAggregator.
scala:36-80): normalization ``x -> (x - shift) * factor`` is applied
*algebraically inside the objective kernels*, so sparse data is never
transformed or densified:

    margin      = x . (factor * w) - shift . (factor * w)
    grad        = factor * (sum_i c_i x_i  -  shift * sum_i c_i)

with ``c_i = weight_i * dzLoss_i``. The intercept column (if any) has
``shift = 0, factor = 1``.
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Optional

import jax.numpy as jnp

Array = jnp.ndarray


class NormalizationType(enum.Enum):
    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


class NormalizationContext(NamedTuple):
    """Optional shift/factor vectors; None means identity (no-op).

    A pytree — flows freely through jit/shard_map; replicated on the mesh.
    """

    factor: Optional[Array] = None  # [d] or None
    shift: Optional[Array] = None  # [d] or None

    @property
    def is_identity(self) -> bool:
        return self.factor is None and self.shift is None

    def effective_coefficients(self, coef: Array) -> Array:
        """w_eff = factor * w (margin side)."""
        return coef if self.factor is None else coef * self.factor

    def shift_dot(self, coef_eff: Array) -> Array:
        """shift . w_eff, the scalar subtracted from every margin."""
        if self.shift is None:
            return jnp.zeros((), dtype=coef_eff.dtype)
        return jnp.dot(self.shift, coef_eff)

    def unshift_gradient(self, vector_sum: Array, prefactor_sum: Array) -> Array:
        """Driver-side un-shifting: (vectorSum - shift*prefactor) * factor.

        Mirrors ValueAndGradientAggregator.scala:199-221.
        """
        g = vector_sum
        if self.shift is not None:
            g = g - self.shift * prefactor_sum
        if self.factor is not None:
            g = g * self.factor
        return g

    def model_to_original_space(self, coef: Array) -> Array:
        """De-normalize trained coefficients back to the raw-feature space.

        If training saw x' = (x - shift)*factor, then w_orig = factor * w'
        and the intercept absorbs ``- (shift*factor) . w'``
        (NormalizationContext.scala:72-84). Intercept handling is done by the
        caller, which knows the intercept slot.
        """
        return coef if self.factor is None else coef * self.factor

    def intercept_adjustment(self, coef: Array) -> Array:
        """Amount to add to the intercept when mapping back to original space."""
        if self.shift is None:
            return jnp.zeros((), dtype=coef.dtype)
        eff = self.effective_coefficients(coef)
        return -jnp.dot(self.shift, eff)


def identity_context() -> NormalizationContext:
    return NormalizationContext(None, None)


def build_normalization(
    norm_type: NormalizationType,
    *,
    mean: Array,
    std: Array,
    max_magnitude: Array,
    intercept_index: Optional[int] = None,
) -> NormalizationContext:
    """Build shift/factor from feature summary stats.

    Mirrors NormalizationContext.scala:119-157; the intercept slot is kept
    untouched (factor 1, shift 0).
    """
    mean = jnp.asarray(mean)
    std = jnp.asarray(std)
    max_magnitude = jnp.asarray(max_magnitude)
    one = jnp.ones_like(mean)

    safe_std = jnp.where(std > 0, std, 1.0)
    safe_max = jnp.where(max_magnitude > 0, max_magnitude, 1.0)

    if norm_type == NormalizationType.NONE:
        return identity_context()
    if norm_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factor, shift = one / safe_std, None
    elif norm_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factor, shift = one / safe_max, None
    elif norm_type == NormalizationType.STANDARDIZATION:
        factor, shift = one / safe_std, mean
    else:  # pragma: no cover
        raise ValueError(norm_type)

    if intercept_index is not None:
        factor = factor.at[intercept_index].set(1.0)
        if shift is not None:
            shift = shift.at[intercept_index].set(0.0)
    return NormalizationContext(factor=factor, shift=shift)
