"""GLM objective kernels: value / gradient / Hessian-vector / Hessian-diagonal.

This is the TPU-native replacement for the reference's aggregator layer
(photon-ml .../function/ValueAndGradientAggregator.scala:133-250,
HessianVectorAggregator.scala:137-152, HessianDiagonalAggregator.scala) and
its Distributed/SingleNode objective wrappers
(DistributedGLMLossFunction.scala:63-136, SingleNodeGLMLossFunction.scala).

Design:
- One fused pass per evaluation: margins (gather or matmul) -> pointwise loss
  derivatives -> weighted reductions (scatter-add or matmul). XLA fuses the
  elementwise work into the reductions; no per-datum loop exists.
- Distribution is a *parameter*, not a subclass: if ``axis_name`` is set the
  per-shard partial sums are combined with ``jax.lax.psum`` — run the same
  method under ``shard_map`` over a mesh and it becomes the treeAggregate
  analog (partials ride ICI instead of netty).
- Normalization is applied algebraically via NormalizationContext (shift /
  factor), never materialized (reference trick, ValueAndGradientAggregator.
  scala:36-80).
- Objective semantics match the reference: total = sum_i weight_i * loss_i
  (no 1/n), L2 term = lambda/2 * ||w||^2 added once after the psum.
  L1 is NOT part of the objective — it lives in OWLQN (reference:
  function/L2Regularization.scala comment; OWLQN.scala).

Everything here is jit-, grad-, vmap- and shard_map-safe; ``l2_weight`` is a
dynamic argument so a whole regularization path reuses one compilation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import (
    Batch,
    SparseBatch,
    sparse_dot,
    sparse_scatter_add,
)
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.normalization import NormalizationContext, identity_context

Array = jnp.ndarray


@dataclass(frozen=True)
class GLMObjective:
    """A (possibly distributed) weighted GLM objective over one batch type.

    Attributes:
      loss: pointwise loss kernel triple.
      dim: coefficient dimension.
      norm: normalization context (shift/factor), identity by default.
      axis_name: if set, reductions are psum'ed over this mesh axis
        (use inside shard_map / pjit with a sharded batch).
    """

    loss: PointwiseLoss
    dim: int
    norm: NormalizationContext = field(default_factory=identity_context)
    axis_name: Optional[str] = None

    # -- reductions --------------------------------------------------------

    def _psum(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.psum(x, self.axis_name)

    # -- margins -----------------------------------------------------------

    def margins(self, coef: Array, batch: Batch) -> Array:
        """z_i = x_eff_i . w_eff + offset_i (normalized-space margin)."""
        w_eff = self.norm.effective_coefficients(coef)
        if isinstance(batch, SparseBatch):
            raw = sparse_dot(batch, w_eff)
        else:
            raw = batch.features @ w_eff
        return raw - self.norm.shift_dot(w_eff) + batch.offsets

    # -- scatter helpers ---------------------------------------------------

    def _weighted_feature_sum(self, batch: Batch, row_coef: Array) -> Array:
        """sum_i row_coef[i] * x_i  as a dense [dim] vector."""
        if isinstance(batch, SparseBatch):
            return sparse_scatter_add(batch, row_coef, self.dim)
        return batch.features.T @ row_coef

    # -- value / gradient --------------------------------------------------

    def value(self, coef: Array, batch: Batch, l2_weight=0.0) -> Array:
        z = self.margins(coef, batch)
        val = jnp.sum(batch.weights * self.loss.value(z, batch.labels))
        val = self._psum(val)
        return val + 0.5 * l2_weight * jnp.dot(coef, coef)

    def value_and_gradient(
        self, coef: Array, batch: Batch, l2_weight=0.0
    ) -> Tuple[Array, Array]:
        """One fused pass for (value, gradient) — the LBFGS hot path.

        Accumulates the reference's three partials (valueSum, vectorSum,
        vectorShiftPrefactorSum), psums them, then un-shifts:
        grad = factor * (vectorSum - shift * prefactorSum) + lambda * w.
        """
        z = self.margins(coef, batch)
        lv = self.loss.value(z, batch.labels)
        ld = self.loss.d1(z, batch.labels)
        c = batch.weights * ld
        value_sum = jnp.sum(batch.weights * lv)
        vector_sum = self._weighted_feature_sum(batch, c)
        prefactor_sum = jnp.sum(c)
        value_sum, vector_sum, prefactor_sum = self._psum(
            (value_sum, vector_sum, prefactor_sum)
        )
        grad = self.norm.unshift_gradient(vector_sum, prefactor_sum)
        value = value_sum + 0.5 * l2_weight * jnp.dot(coef, coef)
        grad = grad + l2_weight * coef
        return value, grad

    def gradient(self, coef: Array, batch: Batch, l2_weight=0.0) -> Array:
        return self.value_and_gradient(coef, batch, l2_weight)[1]

    # -- second order ------------------------------------------------------

    def hessian_vector(
        self, coef: Array, direction: Array, batch: Batch, l2_weight=0.0
    ) -> Array:
        """H(w) @ d, one psum round — the TRON/CG hot path.

        Mirrors HessianVectorAggregator.scala:137-152:
        Hv = factor * (sum_i w_i l''_i (x_eff_i . d_eff) x_i
                       - shift * sum_i w_i l''_i (x_eff_i . d_eff)) + lambda d
        """
        w_eff = self.norm.effective_coefficients(coef)
        d_eff = self.norm.effective_coefficients(direction)
        if isinstance(batch, SparseBatch):
            z_raw = sparse_dot(batch, w_eff)
            zd_raw = sparse_dot(batch, d_eff)
        else:
            z_raw = batch.features @ w_eff
            zd_raw = batch.features @ d_eff
        z = z_raw - self.norm.shift_dot(w_eff) + batch.offsets
        zd = zd_raw - self.norm.shift_dot(d_eff)
        c = batch.weights * self.loss.d2(z, batch.labels) * zd
        vector_sum = self._weighted_feature_sum(batch, c)
        prefactor_sum = jnp.sum(c)
        vector_sum, prefactor_sum = self._psum((vector_sum, prefactor_sum))
        hv = self.norm.unshift_gradient(vector_sum, prefactor_sum)
        return hv + l2_weight * direction

    def hessian_diagonal(self, coef: Array, batch: Batch, l2_weight=0.0) -> Array:
        """diag(H), used for per-coefficient variances 1/(Hdiag + eps)
        (reference: DistributedOptimizationProblem.scala:79-93,
        HessianDiagonalAggregator.scala).

        With x_eff = (x - shift) * factor:
          diag_j = factor_j^2 * ( S2_j - 2 shift_j S1_j + shift_j^2 S0 )
        where c_i = weight_i l''_i, S2 = sum c x^2, S1 = sum c x, S0 = sum c.
        All three accumulate sparsely.
        """
        z = self.margins(coef, batch)
        c = batch.weights * self.loss.d2(z, batch.labels)
        if isinstance(batch, SparseBatch):
            flat_ix = batch.indices.reshape(-1)
            cv = (batch.values * c[:, None]).reshape(-1)
            cv2 = (batch.values**2 * c[:, None]).reshape(-1)
            s1 = jnp.zeros((self.dim,), batch.values.dtype).at[flat_ix].add(cv)
            s2 = jnp.zeros((self.dim,), batch.values.dtype).at[flat_ix].add(cv2)
        else:
            s1 = batch.features.T @ c
            s2 = (batch.features**2).T @ c
        s0 = jnp.sum(c)
        s0, s1, s2 = self._psum((s0, s1, s2))
        diag = s2
        if self.norm.shift is not None:
            diag = diag - 2.0 * self.norm.shift * s1 + (self.norm.shift**2) * s0
        if self.norm.factor is not None:
            diag = diag * self.norm.factor**2
        return diag + l2_weight

    # -- convenience -------------------------------------------------------

    def with_axis(self, axis_name: Optional[str]) -> "GLMObjective":
        return GLMObjective(self.loss, self.dim, self.norm, axis_name)


# A pytree: the normalization vectors are leaves, everything else static
# aux. The objective then passes straight through jit as an ARGUMENT, so
# the module-level partial programs below (and any future jitted
# consumer) share ONE persistent compile cache across instances — two
# streaming objectives over the same chunk shape hit the same executable
# instead of each holding a private jit(lambda).
jax.tree_util.register_dataclass(
    GLMObjective,
    data_fields=["norm"],
    meta_fields=["loss", "dim", "axis_name"],
)


# -- shared per-chunk partial programs ---------------------------------------
#
# The streaming objectives (io/streaming.py, game/streaming.py) evaluate
# l2=0 partials chunk by chunk and fold on device; these module-level jits
# replace their constructor-time ``jit(lambda)``s (PERF_NOTES round 9's
# "noted, not attempted" item): one compile cache for the whole process,
# keyed by jit on the objective's static structure + chunk shapes.


@jax.jit
def partial_value_and_gradient(objective, coef: Array, batch: Batch):
    """(value, gradient) at l2=0 — the streamed per-chunk partial."""
    return objective.value_and_gradient(coef, batch, 0.0)


@jax.jit
def partial_hessian_vector(
    objective, coef: Array, direction: Array, batch: Batch
):
    """H(w) @ d at l2=0 — the streamed per-chunk TRON/CG partial."""
    return objective.hessian_vector(coef, direction, batch, 0.0)


@jax.jit
def partial_hessian_diagonal(objective, coef: Array, batch: Batch):
    """diag(H) at l2=0 — the streamed per-chunk variance partial."""
    return objective.hessian_diagonal(coef, batch, 0.0)
