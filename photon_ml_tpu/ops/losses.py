"""Pointwise GLM loss kernels: ``(margin, label) -> (loss, d/dz loss, d2/dz2 loss)``.

These are the scalar kernels at the bottom of every objective evaluation.
Reference: photon-ml .../function/glm/PointwiseLossFunction.scala:36-54
(`lossAndDzLoss`, `DzzLoss`) and its implementations
LogisticLossFunction.scala:122-141, SquaredLossFunction.scala,
PoissonLossFunction.scala, and .../function/svm/SmoothedHingeLossFunction.scala.

All functions are elementwise over arrays of margins/labels, jit- and
vmap-safe, and written for numerical stability in float32 (the reference gets
float64 for free on the JVM; here stable forms matter).

Label conventions match the reference:
- logistic: labels in {0, 1}; margin is the log-odds.
- squared/poisson: real / non-negative labels.
- smoothed hinge: labels in {0, 1}, internally mapped to {-1, +1}
  (reference: SmoothedHingeLossFunction.scala maps via 2*y - 1).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax.numpy as jnp

from photon_ml_tpu.task import TaskType

Array = jnp.ndarray


class PointwiseLoss(NamedTuple):
    """A pointwise loss: value, first and second derivative w.r.t. margin.

    ``d2`` (the reference's `DzzLoss`) powers Hessian-vector products and
    Hessian diagonals; losses that are only once-differentiable (smoothed
    hinge) set ``has_hessian=False`` and their ``d2`` must not be trusted.
    """

    name: str
    value: Callable[[Array, Array], Array]
    d1: Callable[[Array, Array], Array]
    d2: Callable[[Array, Array], Array]
    # mean function: margin -> E[y]  (GeneralizedLinearModel.computeMean)
    mean: Callable[[Array], Array]
    has_hessian: bool = True


def _sigmoid(z: Array) -> Array:
    return jnp.where(
        z >= 0,
        1.0 / (1.0 + jnp.exp(-z)),
        jnp.exp(z) / (1.0 + jnp.exp(z)),
    )


def _log1pexp(z: Array) -> Array:
    """log(1 + exp(z)), stable for large |z|."""
    return jnp.where(z > 0, z + jnp.log1p(jnp.exp(-z)), jnp.log1p(jnp.exp(z)))


# --- logistic --------------------------------------------------------------
# loss(z, y) = log(1 + e^z) - y z      (y in {0,1})
# d1 = sigmoid(z) - y ;  d2 = sigmoid(z) (1 - sigmoid(z))
# Stable form mirrors LogisticLossFunction.scala:122-141.

def _logistic_value(z: Array, y: Array) -> Array:
    return _log1pexp(z) - y * z


def _logistic_d1(z: Array, y: Array) -> Array:
    return _sigmoid(z) - y


def _logistic_d2(z: Array, y: Array) -> Array:
    s = _sigmoid(z)
    return s * (1.0 - s)


LOGISTIC = PointwiseLoss(
    name="logistic",
    value=_logistic_value,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean=_sigmoid,
)


# --- squared ---------------------------------------------------------------
# loss = 0.5 (z - y)^2  (SquaredLossFunction.scala)

def _squared_value(z: Array, y: Array) -> Array:
    d = z - y
    return 0.5 * d * d


LINEAR = PointwiseLoss(
    name="squared",
    value=_squared_value,
    d1=lambda z, y: z - y,
    d2=lambda z, y: jnp.ones_like(z),
    mean=lambda z: z,
)


# --- poisson ---------------------------------------------------------------
# loss = e^z - y z  (negative Poisson log-likelihood up to const,
# PoissonLossFunction.scala)

POISSON = PointwiseLoss(
    name="poisson",
    value=lambda z, y: jnp.exp(z) - y * z,
    d1=lambda z, y: jnp.exp(z) - y,
    d2=lambda z, y: jnp.exp(z),
    mean=lambda z: jnp.exp(z),
)


# --- smoothed hinge (Rennie) ----------------------------------------------
# With t = (2y - 1) z:
#   t >= 1: 0 ;  t <= 0: 0.5 - t ;  else 0.5 (1 - t)^2
# (SmoothedHingeLossFunction.scala; only once-differentiable, so TRON is
# rejected for this task by OptimizerFactory — same rule enforced in
# photon_ml_tpu.optim.factory.)

def _hinge_t(z: Array, y: Array) -> Array:
    return (2.0 * y - 1.0) * z


def _smoothed_hinge_value(z: Array, y: Array) -> Array:
    t = _hinge_t(z, y)
    return jnp.where(t >= 1.0, 0.0, jnp.where(t <= 0.0, 0.5 - t, 0.5 * (1.0 - t) ** 2))


def _smoothed_hinge_d1(z: Array, y: Array) -> Array:
    s = 2.0 * y - 1.0
    t = s * z
    dt = jnp.where(t >= 1.0, 0.0, jnp.where(t <= 0.0, -1.0, t - 1.0))
    return s * dt


SMOOTHED_HINGE = PointwiseLoss(
    name="smoothed_hinge",
    value=_smoothed_hinge_value,
    d1=_smoothed_hinge_d1,
    d2=lambda z, y: jnp.zeros_like(z),
    mean=lambda z: z,  # raw margin score (classification threshold applied later)
    has_hessian=False,
)


LOSSES_BY_TASK = {
    TaskType.LOGISTIC_REGRESSION: LOGISTIC,
    TaskType.LINEAR_REGRESSION: LINEAR,
    TaskType.POISSON_REGRESSION: POISSON,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SMOOTHED_HINGE,
}


def loss_for_task(task: TaskType) -> PointwiseLoss:
    return LOSSES_BY_TASK[task]
