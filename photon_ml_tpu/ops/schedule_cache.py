"""Persistent content-addressed cache for tiled sparse schedules.

WHY: the tiled Pallas kernels (ops/tiled_sparse.py) sit at ~0.99x their
dispatched-step roofline (BENCH_r05), so the remaining cold-training host
cost is the SCHEDULE BUILD — ~4.3 s per dataset at the ads shape, repaid
on every process start and every sweep whose in-memory cache missed. The
schedule is a pure function of (entry coordinates/values, tile params,
output-block count): exactly the static layout work Photon ML amortizes
once per dataset via its off-heap PalDB feature index (PAPER.md), and
what veScale argues an SPMD system must cache rather than recompute per
run (PAPERS.md). This module is that tier: a versioned on-disk artifact
per built schedule, keyed by a content hash of the inputs, loaded back
as zero-copy ``np.load(mmap_mode='r')`` views with cheap integrity
checks and automatic fallback-to-rebuild on any mismatch.

Layout on disk (one directory per schedule)::

    <cache_dir>/v<VERSION>/<key>/
        meta.json          # version, key, per-array dtype/shape/nbytes/spot
        step_out.npy ... spill_vals.npy   # the 9 schedule arrays

Integrity: each ``.npy`` carries a SPOT digest (blake2b over the first
and last 64 KiB of the file plus its size) recorded in meta.json. That
catches truncation, header damage and version skew in O(1) IO — a full
checksum would force reading every page and forfeit the mmap win; the
content-addressed key already ties the artifact to its exact inputs.

Multi-host: the coordinator (process 0) builds and writes; other
processes wait-and-read its artifacts (poll with a deadline, then fall
back to a local build without storing). Stores are atomic (temp dir +
rename), so a reader never sees a half-written artifact and concurrent
writers race benignly.

Configuration precedence: ``cache_scope`` (innermost) > ``configure`` >
``PHOTON_TILE_CACHE_DIR`` env var > off. Unset means OFF — tier-1 tests
stay hermetic by default.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import asdict, dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# Bump whenever the schedule array layout or builder semantics change:
# the version is part of both the artifact path and meta.json, so old
# artifacts simply miss and are rebuilt.
SCHEDULE_CACHE_VERSION = 1

ENV_CACHE_DIR = "PHOTON_TILE_CACHE_DIR"
ENV_WAIT_S = "PHOTON_TILE_CACHE_WAIT_S"
ENV_WRITER = "PHOTON_TILE_CACHE_WRITER"

SCHEDULE_ARRAY_NAMES = (
    "step_out", "step_in", "step_init", "out_pos", "in_pos", "vals",
    "spill_out", "spill_in", "spill_vals",
)

_SPOT_BYTES = 64 * 1024

# -- configuration -----------------------------------------------------------

_configured: Optional[str] = None
_configured_set = False
_scoped: list = []  # innermost-last stack of explicit cache dirs
_lock = threading.Lock()


def configure(cache_dir: Optional[str]) -> None:
    """Process-wide cache directory (drivers call this from
    ``--tile-cache-dir``). ``configure(None)`` restores the env-var
    default; ``configure("")`` disables the cache outright."""
    global _configured, _configured_set
    _configured = cache_dir
    _configured_set = cache_dir is not None


@contextmanager
def cache_scope(cache_dir: Optional[str]):
    """Scoped override for library callers (training.py / streaming.py)
    that thread an explicit ``tile_cache_dir`` argument. ``None`` is a
    no-op passthrough (outer configuration still applies)."""
    if cache_dir is None:
        yield
        return
    with _lock:
        _scoped.append(cache_dir)
    try:
        yield
    finally:
        with _lock:
            _scoped.pop()


def resolve_cache_dir() -> Optional[str]:
    """The active cache directory, or None when the cache is off."""
    with _lock:
        if _scoped:
            return _scoped[-1] or None
    if _configured_set:
        return _configured or None
    return os.environ.get(ENV_CACHE_DIR) or None


# -- stats (the observable seam: hit/miss/build counters + timers) ----------


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    builds: int = 0  # schedules actually built (disk hit skips this)
    corrupt: int = 0  # artifacts rejected (version/checksum/shape)
    quarantined: int = 0  # rejected artifacts renamed to *.corrupt
    stores: int = 0
    hash_s: float = 0.0
    load_s: float = 0.0
    store_s: float = 0.0
    build_s: float = 0.0
    wait_s: float = 0.0

    def as_dict(self) -> Dict[str, float]:
        return asdict(self)


_stats = CacheStats()
_stats_lock = threading.Lock()


def stats() -> CacheStats:
    """Snapshot of the process-wide cache counters."""
    with _stats_lock:
        return CacheStats(**asdict(_stats))


def reset_stats() -> None:
    global _stats
    with _stats_lock:
        _stats = CacheStats()


def _bump(counter: str, n: int = 1) -> None:
    with _stats_lock:
        setattr(_stats, counter, getattr(_stats, counter) + n)


def _add_time(bucket: str, seconds: float) -> None:
    with _stats_lock:
        setattr(_stats, bucket, getattr(_stats, bucket) + seconds)
    from photon_ml_tpu.utils.profiling import record_host_timing

    record_host_timing(f"schedule_cache.{bucket}", seconds)


def record_build_seconds(seconds: float) -> None:
    """Called by the schedule builder so build time lands in the same
    stats/profiling stream as the cache's own load/store timers."""
    _bump("builds")
    _add_time("build_s", seconds)


# -- content addressing ------------------------------------------------------


def content_digest(*arrays: np.ndarray, extra: str = "") -> str:
    """blake2b hex digest over the arrays' dtype/shape/bytes (+ a free-
    form discriminator). Arrays are hashed on worker threads — hashlib
    releases the GIL for large buffers, so the three COO columns digest
    in parallel at ~memory speed."""
    import hashlib
    from concurrent.futures import ThreadPoolExecutor

    t0 = time.perf_counter()

    def one(a: np.ndarray) -> bytes:
        a = np.ascontiguousarray(a)
        h = hashlib.blake2b(digest_size=16)
        h.update(str((a.dtype.str, a.shape)).encode())
        h.update(memoryview(a).cast("B"))
        return h.digest()

    arrays = tuple(arrays)
    if len(arrays) > 1:
        with ThreadPoolExecutor(len(arrays)) as pool:
            parts = list(pool.map(one, arrays))
    else:
        parts = [one(a) for a in arrays]
    h = hashlib.blake2b(digest_size=16)
    for p in parts:
        h.update(p)
    h.update(extra.encode())
    out = h.hexdigest()
    _add_time("hash_s", time.perf_counter() - t0)
    return out


def schedule_key(
    digest: str,
    params,
    sort_by_feature_block: bool,
    num_out_blocks: int,
) -> str:
    """Cache key for one built schedule: the entry-content digest plus
    everything else the build depends on (tile params incl. the RESOLVED
    chunk, pass direction, output-block count, layout version)."""
    import hashlib

    canon = "|".join(
        (
            f"v{SCHEDULE_CACHE_VERSION}",
            digest,
            repr(params),
            f"feat_sorted={int(bool(sort_by_feature_block))}",
            f"out_blocks={int(num_out_blocks)}",
        )
    )
    return hashlib.blake2b(canon.encode(), digest_size=16).hexdigest()


# -- multi-host roles --------------------------------------------------------


def is_cache_writer() -> bool:
    """Process 0 writes; everyone else waits-and-reads. Overridable with
    PHOTON_TILE_CACHE_WRITER=0|1 (tests / external orchestration)."""
    forced = os.environ.get(ENV_WRITER)
    if forced is not None:
        return forced.strip() not in ("0", "false", "no", "")
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def _wait_deadline_s() -> float:
    try:
        return float(os.environ.get(ENV_WAIT_S, "300"))
    except ValueError:
        return 300.0


# -- disk artifacts ----------------------------------------------------------


def _artifact_dir(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, f"v{SCHEDULE_CACHE_VERSION}", key)


def _spot_digest(path: str) -> str:
    """Cheap integrity fingerprint: blake2b over the first and last
    64 KiB of the file plus its size — O(1) IO regardless of artifact
    size, catches truncation and header/tail damage."""
    import hashlib

    size = os.path.getsize(path)
    h = hashlib.blake2b(digest_size=16)
    h.update(str(size).encode())
    with open(path, "rb") as f:
        h.update(f.read(_SPOT_BYTES))
        if size > _SPOT_BYTES:
            f.seek(max(size - _SPOT_BYTES, 0))
            h.update(f.read(_SPOT_BYTES))
    return h.hexdigest()


def store_schedule(
    cache_dir: str, key: str, arrays: Sequence[np.ndarray]
) -> bool:
    """Write one schedule artifact atomically (temp dir + rename).
    Returns False (without raising) on any IO failure — the cache is an
    accelerator, never a correctness dependency."""
    if len(arrays) != len(SCHEDULE_ARRAY_NAMES):
        raise ValueError(
            f"expected {len(SCHEDULE_ARRAY_NAMES)} schedule arrays, "
            f"got {len(arrays)}"
        )
    t0 = time.perf_counter()
    final = _artifact_dir(cache_dir, key)
    tmp = f"{final}.{os.getpid()}.{threading.get_ident()}.tmp"

    def _store_once() -> bool:
        if os.path.isdir(final):
            return True  # already stored (concurrent writer won)
        os.makedirs(tmp, exist_ok=True)
        from concurrent.futures import ThreadPoolExecutor

        def write_one(item: Tuple[str, np.ndarray]) -> Tuple[str, dict]:
            name, a = item
            a = np.ascontiguousarray(a)
            path = os.path.join(tmp, f"{name}.npy")
            np.save(path, a)
            return name, {
                "dtype": a.dtype.str,
                "shape": list(a.shape),
                "nbytes": int(a.nbytes),
                "spot": _spot_digest(path),
            }

        with ThreadPoolExecutor(min(4, len(arrays))) as pool:
            meta_arrays = dict(
                pool.map(write_one, zip(SCHEDULE_ARRAY_NAMES, arrays))
            )
        meta = {
            "version": SCHEDULE_CACHE_VERSION,
            "key": key,
            "arrays": meta_arrays,
        }
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        try:
            os.rename(tmp, final)
        except OSError:
            # another writer renamed first — theirs is equivalent
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
        _bump("stores")
        return True

    try:
        # cache_store seam: transient write errors retry into a fresh
        # temp-dir attempt (the temp+rename protocol is idempotent)
        from photon_ml_tpu.reliability.retry import io_call

        return io_call("cache_store", _store_once, detail=final)
    except Exception as e:  # disk full, permissions, retry budget spent
        logger.warning("tile-schedule cache store failed (%s): %s", key, e)
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        return False
    finally:
        _add_time("store_s", time.perf_counter() - t0)


def _quarantine_artifact_dir(d: str, key: str, why: str) -> None:
    """A rejected artifact must not fail every future run: rename the
    whole artifact directory to ``*.corrupt`` (accounted in both the
    cache stats and the reliability quarantine list) so the next run
    rebuilds and re-stores a clean copy instead of re-tripping on the
    poison forever."""
    from photon_ml_tpu.reliability.retry import quarantine_artifact

    dst = quarantine_artifact(d, "cache_load")
    if dst is not None:
        _bump("quarantined")
        logger.warning(
            "tile-schedule cache artifact %s quarantined to %s (%s)",
            key, dst, why,
        )


def load_schedule(
    cache_dir: str, key: str
) -> Optional[Tuple[np.ndarray, ...]]:
    """Load one schedule artifact as mmap-backed read-only arrays, or
    None on miss / version skew / corruption (callers rebuild). Runs
    behind the ``cache_load`` seam: transient IO errors retry; an
    artifact still failing (or failing integrity checks) is QUARANTINED
    (renamed ``*.corrupt``) so it cannot poison future runs."""
    from photon_ml_tpu.reliability.retry import SeamFailure, io_call

    t0 = time.perf_counter()
    d = _artifact_dir(cache_dir, key)
    meta_path = os.path.join(d, "meta.json")

    def _load_once() -> Optional[Tuple[np.ndarray, ...]]:
        with open(meta_path) as f:
            meta = json.load(f)
        if meta.get("version") != SCHEDULE_CACHE_VERSION or meta.get(
            "key"
        ) != key:
            raise ValueError("version/key mismatch")
        out = []
        for name in SCHEDULE_ARRAY_NAMES:
            spec = meta["arrays"][name]
            path = os.path.join(d, f"{name}.npy")
            if _spot_digest(path) != spec["spot"]:
                raise ValueError(f"spot checksum mismatch for {name}")
            a = np.load(path, mmap_mode="r")
            if a.dtype.str != spec["dtype"] or list(a.shape) != list(
                spec["shape"]
            ):
                raise ValueError(f"dtype/shape mismatch for {name}")
            out.append(a)
        return tuple(out)

    try:
        if not os.path.isfile(meta_path):
            _bump("misses")
            return None
        out = io_call("cache_load", _load_once, detail=d)
        _bump("hits")
        return out
    except (ValueError, KeyError, json.JSONDecodeError) as e:
        # artifact damage: re-reading yields the same bytes — quarantine
        _bump("corrupt")
        _bump("misses")
        _quarantine_artifact_dir(d, key, str(e))
        return None
    except (SeamFailure, OSError) as e:
        # persistent IO trouble on this artifact: same quarantine path
        # (the cache is an accelerator, never a correctness dependency)
        logger.warning(
            "tile-schedule cache artifact %s unreadable, rebuilding: %s",
            key, e,
        )
        _bump("corrupt")
        _bump("misses")
        _quarantine_artifact_dir(d, key, str(e))
        return None
    finally:
        _add_time("load_s", time.perf_counter() - t0)


def wait_and_load(
    cache_dir: str, key: str, timeout_s: Optional[float] = None
) -> Optional[Tuple[np.ndarray, ...]]:
    """Non-writer processes: poll for the coordinator's artifact until
    the deadline, then give up (caller builds locally, without storing).
    The store is atomic, so the first successful load is complete."""
    deadline = time.monotonic() + (
        timeout_s if timeout_s is not None else _wait_deadline_s()
    )
    t0 = time.perf_counter()
    try:
        while True:
            if os.path.isfile(
                os.path.join(_artifact_dir(cache_dir, key), "meta.json")
            ):
                return load_schedule(cache_dir, key)
            if time.monotonic() >= deadline:
                logger.warning(
                    "timed out waiting for tile-schedule artifact %s; "
                    "building locally", key,
                )
                return None
            time.sleep(0.05)
    finally:
        _add_time("wait_s", time.perf_counter() - t0)


# -- bounded in-memory LRU (the two tiers in front of the disk cache) --------


class ScheduleLRU:
    """Small bounded LRU for converted batches: a hit refreshes recency,
    inserts evict the LEAST recently used entry. One instance each for
    the tiled and sharded conversions (ops/tiled_sparse.py), so the two
    call sites can no longer thrash each other out of a shared dict
    (ADVICE.md round 5)."""

    def __init__(self, maxsize: int):
        from collections import OrderedDict

        if maxsize < 1:
            raise ValueError("maxsize must be >= 1")
        self.maxsize = maxsize
        self._d = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            if key not in self._d:
                return None
            self._d.move_to_end(key)
            return self._d[key]

    def put(self, key, value) -> None:
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
            self._d[key] = value
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)

    def pop(self, key) -> None:
        with self._lock:
            self._d.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def keys(self):
        with self._lock:
            return list(self._d.keys())
