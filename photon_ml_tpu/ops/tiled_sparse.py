"""Tiled sparse GLM kernels: gather/scatter-free margins and gradients.

WHY: on TPU, XLA lowers random gather/scatter to ~7ns/element serial loops
(measured — PERF_NOTES.md), so the reference's two hot loops (margin
accumulation and gradient axpy, ValueAndGradientAggregator.scala:133-154)
are 100x slower than the hardware's streaming rate. This module replaces
both with a STATIC TILED layout + two Pallas kernels whose only per-entry
operations are VPU compares and MXU matmuls:

- Entries are binned into (row-window x feature-window) tiles; windows are
  R_WIN = F_WIN = S_HI * S_LO positions wide.
- A window-local index idx in [0, WIN) decomposes as hi*S_LO + lo; the
  gather w[idx] becomes the bilinear form onehot_hi @ w2d . onehot_lo with
  w2d = w_window reshaped [S_HI, S_LO] — ONE small matmul per chunk plus
  elementwise masks, no scatter/gather anywhere.
- The z-pass streams chunks sorted by row-block (output revisiting is
  monotone -> pallas accumulates the z window in VMEM); the grad-pass
  streams the same entries sorted by feature-block.

The schedule (tile assignment, chunking, window-local index packing) is
computed ONCE on host per dataset — full-batch GLM training re-evaluates
the same static structure hundreds of times, so the build cost amortizes
to zero. Schedules and per-row arrays are pytree leaves: pass the
TiledSparseBatch *as a jit argument* (exactly like SparseBatch), never a
closure constant — at ads scale the schedule is hundreds of MB and baking
it into the executable blows up compilation.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.normalization import NormalizationContext, identity_context

Array = jnp.ndarray


@dataclass(frozen=True)
class TileParams:
    # Defaults from an on-chip sweep at the ads shape (262k x 64nnz x 1M,
    # PERF_NOTES.md "tile sweep"): chunk 2048 cut the full fused eval
    # 36.8 -> 28.9 ms vs chunk 1024 (fewer grid steps amortize per-step
    # scalar/DMA overhead; tile-boundary padding grew only ~25%), while
    # window-shape changes (s_hi=s_lo=128, or 64/128) were net losses.
    s_hi: int = 128
    s_lo: int = 64
    chunk: int = 2048  # entries per grid step

    @property
    def window(self) -> int:
        return self.s_hi * self.s_lo


class _Schedule(NamedTuple):
    """One pass's static schedule: chunked entries sorted by output block.

    All fields are arrays (the NamedTuple is a pytree — jit-argument safe).
    Entry blocks are 2-D rows [G, L]: TPU HBM tiling pads the trailing two
    dims to (8, 128), so [G, L, 1] would waste 128x HBM (observed: 54 GB
    for a 528 MB schedule) and [G, 1, L] 8x, while [G, L] is compact. In
    the kernel each [1, L] row broadcasts against sublane-iota; a
    [8, L//8] -> [L] reshape would be an unsupported Mosaic relayout.
    """

    step_out: Array  # int32 [G] output block id per step
    step_in: Array  # int32 [G] input-window block id per step
    step_init: Array  # int32 [G] 1 iff first step of its output block
    out_pos: Array  # int32 [G, L] window-local OUTPUT index in [0, WIN)
    in_pos: Array  # int32 [G, L] window-local INPUT index in [0, WIN)
    vals: Array  # float32 [G, L] entry values (0 for padding slots)

    @property
    def num_steps(self) -> int:
        return self.step_out.shape[0]


def _build_schedule(
    rows: np.ndarray,
    feats: np.ndarray,
    vals: np.ndarray,
    *,
    params: TileParams,
    sort_by_feature_block: bool,
    num_out_blocks: int,
) -> _Schedule:
    win = params.window
    L = params.chunk
    rb = rows // win
    fb = feats // win
    if sort_by_feature_block:
        order = np.lexsort((rb, fb))
        out_blocks, in_blocks = fb[order], rb[order]
        out_pos, in_pos = feats[order] % win, rows[order] % win
    else:
        order = np.lexsort((fb, rb))
        out_blocks, in_blocks = rb[order], fb[order]
        out_pos, in_pos = rows[order] % win, feats[order] % win
    v = vals[order]

    steps = []  # (entry_start, entry_end, out_block) ; start==end: zero step
    if len(v):
        # tile boundaries: chunk entries so no chunk crosses a tile boundary
        tile_key = (
            out_blocks.astype(np.int64) * (int(in_blocks.max()) + 1)
            + in_blocks
        )
        boundaries = np.nonzero(
            np.concatenate([[True], tile_key[1:] != tile_key[:-1]])
        )[0]
        tile_starts = boundaries
        tile_ends = np.concatenate([boundaries[1:], [len(v)]])
        for s, e in zip(tile_starts, tile_ends):
            for cs in range(s, e, L):
                steps.append((cs, min(cs + L, e), int(out_blocks[s])))
    # Every output block needs at least one step: the kernel only writes
    # blocks named by step_out (out_ref starts as UNINITIALIZED memory on
    # TPU — interpret mode zero-fills, hiding this), so an output window
    # with no entries would otherwise return garbage. Insert zero-entry
    # init steps for the missing blocks, keeping out-block order sorted so
    # VMEM accumulation stays monotone.
    present = {ob for (_, _, ob) in steps}
    for ob in range(num_out_blocks):
        if ob not in present:
            steps.append((0, 0, ob))
    steps.sort(key=lambda t: t[2])

    G = len(steps)
    step_out = np.zeros(G, np.int32)
    step_in = np.zeros(G, np.int32)
    step_init = np.zeros(G, np.int32)
    o_pos = np.zeros((G, L), np.int32)
    i_pos = np.zeros((G, L), np.int32)
    sv = np.zeros((G, L), np.float32)
    prev_out = -1
    for g, (cs, ce, ob) in enumerate(steps):
        m = ce - cs
        step_out[g] = ob
        step_in[g] = in_blocks[cs] if m else 0
        step_init[g] = 1 if ob != prev_out else 0
        prev_out = ob
        if m:
            o_pos[g, :m] = out_pos[cs:ce]
            i_pos[g, :m] = in_pos[cs:ce]
            sv[g, :m] = v[cs:ce]
    # pad the step axis to a multiple of 8: the kernel reads entry rows in
    # (8, L) blocks (sublane tiling); padded rows are never executed
    G8 = ((G + 7) // 8) * 8
    if G8 != G:
        o_pos = np.concatenate([o_pos, np.zeros((G8 - G, L), np.int32)])
        i_pos = np.concatenate([i_pos, np.zeros((G8 - G, L), np.int32)])
        sv = np.concatenate([sv, np.zeros((G8 - G, L), np.float32)])
    return _Schedule(
        jnp.asarray(step_out),
        jnp.asarray(step_in),
        jnp.asarray(step_init),
        jnp.asarray(o_pos),
        jnp.asarray(i_pos),
        jnp.asarray(sv),
    )


class TiledSparseBatch(NamedTuple):
    """Statically tiled sparse batch (replaces SparseBatch on the hot path).

    Row space is padded to num_row_blocks * window; feature space to
    num_feat_blocks * window. ``labels/offsets/weights`` live in padded row
    space (weight 0 padding). A NamedTuple pytree: ints are leaves too, but
    they are concrete python ints, so jit sees them as static weak-typed
    scalars only if hashable — instead we keep them in ``meta`` as a static
    aux via the _TiledMeta wrapper below.
    """

    meta: "_TiledMeta"
    z_sched: _Schedule
    g_sched: _Schedule
    g_vals_sq: Array  # [G2, L] squared values for hessian_diagonal
    labels: Array
    offsets: Array
    weights: Array

    # convenience passthroughs (static python ints)
    @property
    def params(self) -> TileParams:
        return self.meta.params

    @property
    def num_rows(self) -> int:
        return self.meta.num_rows

    @property
    def dim(self) -> int:
        return self.meta.dim

    @property
    def num_real_rows(self) -> int:
        return self.meta.num_real_rows

    @property
    def real_dim(self) -> int:
        return self.meta.real_dim

    @property
    def num_row_blocks(self) -> int:
        return self.meta.num_rows // self.meta.params.window

    @property
    def num_feat_blocks(self) -> int:
        return self.meta.dim // self.meta.params.window


@jax.tree_util.register_static
@dataclass(frozen=True)
class _TiledMeta:
    """Static (hashable) shape metadata for TiledSparseBatch."""

    params: TileParams
    num_rows: int  # padded
    dim: int  # padded
    num_real_rows: int
    real_dim: int


def build_tiled_batch(
    rows: np.ndarray,
    feats: np.ndarray,
    vals: np.ndarray,
    labels: np.ndarray,
    offsets: np.ndarray,
    weights: np.ndarray,
    dim: int,
    *,
    params: TileParams = TileParams(),
) -> TiledSparseBatch:
    """COO triples + per-row arrays -> tiled batch. Entries with zero value
    are dropped (they contribute nothing)."""
    nz = vals != 0
    rows, feats, vals = rows[nz], feats[nz], vals[nz]
    win = params.window
    n = labels.shape[0]
    n_pad = max(((n + win - 1) // win) * win, win)
    d_pad = max(((dim + win - 1) // win) * win, win)

    z_sched = _build_schedule(
        rows, feats, vals, params=params, sort_by_feature_block=False,
        num_out_blocks=n_pad // win,
    )
    g_sched = _build_schedule(
        rows, feats, vals, params=params, sort_by_feature_block=True,
        num_out_blocks=d_pad // win,
    )
    lab = np.zeros(n_pad, np.float32)
    lab[:n] = labels
    off = np.zeros(n_pad, np.float32)
    off[:n] = offsets
    wgt = np.zeros(n_pad, np.float32)
    wgt[:n] = weights
    return TiledSparseBatch(
        meta=_TiledMeta(
            params=params,
            num_rows=n_pad,
            dim=d_pad,
            num_real_rows=n,
            real_dim=dim,
        ),
        z_sched=z_sched,
        g_sched=g_sched,
        g_vals_sq=g_sched.vals**2,
        labels=jnp.asarray(lab),
        offsets=jnp.asarray(off),
        weights=jnp.asarray(wgt),
    )


def tiled_batch_from_sparse(batch, dim: int, *, params: TileParams = TileParams()):
    """Convenience: SparseBatch (padded ELL) -> TiledSparseBatch."""
    indices = np.asarray(batch.indices)
    values = np.asarray(batch.values)
    weights = np.asarray(batch.weights)
    n, k = indices.shape
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    feats = indices.reshape(-1).astype(np.int64)
    vals = values.reshape(-1).astype(np.float32)
    # rows with weight 0 are padding — drop their entries
    vals = np.where(np.repeat(weights > 0, k), vals, 0.0)
    return build_tiled_batch(
        rows, feats, vals,
        np.asarray(batch.labels), np.asarray(batch.offsets), weights,
        dim, params=params,
    )


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _bilinear_pass_kernel(
    # scalar prefetch
    step_out_ref, step_in_ref, step_init_ref,
    # per-step entry blocks [1, L]
    in_pos_ref, out_pos_ref, vals_ref,
    # gathered-from window [1, S_HI, S_LO] (w2d for z-pass, c2d for grad)
    src_ref,
    # output window accumulator [1, S_HI, S_LO]
    out_ref,
    *,
    s_hi: int,
    s_lo: int,
    chunk: int,
    mxu: str,
):
    """One grid step: expand src at in_pos, multiply by vals,
    bilinear-scatter into the out_pos output window.

    Entries live on LANES ([1, L] rows); one-hots are sublane-iota
    compares, so each one-hot is [S, L] with the entry axis last and both
    matmuls contract without any transpose relayout.
    """
    g = pl.program_id(0)
    L = chunk
    # Entry blocks are [8, L] (8 steps' rows — sublane dim must tile by 8);
    # select this step's row with a sublane one-hot mask + reduce (dynamic
    # sublane slicing would relayout; the mask is cheap VPU work).
    r = jax.lax.rem(g, 8)
    row_sel = (
        jax.lax.broadcasted_iota(jnp.int32, (8, L), 0) == r
    )
    ip = jnp.sum(
        jnp.where(row_sel, in_pos_ref[...], 0), axis=0, keepdims=True
    )  # [1, L] int32, window-local = hi * s_lo + lo
    op = jnp.sum(
        jnp.where(row_sel, out_pos_ref[...], 0), axis=0, keepdims=True
    )
    v = jnp.sum(
        jnp.where(row_sel, vals_ref[...], 0.0), axis=0, keepdims=True
    )  # [1, L] float32

    ih = ip // s_lo
    il = ip - ih * s_lo
    oh = op // s_lo
    ol = op - oh * s_lo

    hi_iota = jax.lax.broadcasted_iota(jnp.int32, (s_hi, L), 0)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (s_lo, L), 0)
    dims_in = (((0,), (0,)), ((), ()))
    dims_out = (((1,), (1,)), ((), ()))

    def _split(x):
        # hi + lo bf16 terms of an f32 array (~16 mantissa bits kept);
        # shared by both bf16 variants — keep their numerics identical
        hi_part = x.astype(jnp.bfloat16)
        lo_part = (x - hi_part.astype(jnp.float32)).astype(jnp.bfloat16)
        return hi_part, lo_part

    if mxu == "bf16x2w":
        # Same hi+lo bf16 data split as "bf16x2", but each pass's TWO
        # half-width matmuls fuse into ONE full-width matmul by packing
        # the hi and lo terms into the otherwise idle half of the MXU
        # tile (s_lo = 64 uses 64 of 128 sublanes/lanes): identical MAC
        # count at ~2x the effective utilization.
        oh_in_hi = (ih == hi_iota).astype(jnp.bfloat16)  # [S_HI, L]

        # gather: pack [hi | lo] along the lane axis -> [S_HI, 2*S_LO]
        s1, s2 = _split(src_ref[0])
        src_cat = jnp.concatenate([s1, s2], axis=1)
        a_cat = jax.lax.dot_general(
            src_cat, oh_in_hi, dims_in, preferred_element_type=jnp.float32
        )  # [2*S_LO, L]: rows [0,S_LO) = hi terms, [S_LO,2*S_LO) = lo
        # fold the halves first (sublane slice at a multiple of 8) so the
        # mask-reduce runs at [S_LO, L] instead of [2*S_LO, L]
        a = a_cat[:s_lo] + a_cat[s_lo:]
        oh_in_lo = (il == lo_iota).astype(jnp.float32)
        src_g = jnp.sum(a * oh_in_lo, axis=0, keepdims=True)  # [1, L]
        contrib = v * src_g
        lo2_iota = jax.lax.broadcasted_iota(jnp.int32, (2 * s_lo, L), 0)

        # scatter: RHS rows [0,S_LO) carry onehot*c_hi, [S_LO,2*S_LO)
        # carry onehot*c_lo -> one [S_HI, 2*S_LO] product; the two lane
        # halves fold with an exact VPU add
        c1, c2 = _split(contrib)
        oh_out_hi = (oh == hi_iota).astype(jnp.bfloat16)
        oh_out_lo2 = (ol == jax.lax.rem(lo2_iota, s_lo)).astype(jnp.bfloat16)
        # arithmetic blend instead of jnp.where: Mosaic cannot relayout
        # the lane-replicated i1 mask against the sublane-replicated
        # c-rows; the float blend is exact (half is 0/1)
        half = (lo2_iota >= s_lo).astype(jnp.bfloat16)  # [2*S_LO, L]
        csel = c1 * (jnp.bfloat16(1) - half) + c2 * half
        update_wide = jax.lax.dot_general(
            oh_out_hi, oh_out_lo2 * csel, dims_out,
            preferred_element_type=jnp.float32,
        )  # [S_HI, 2*S_LO]
        update = update_wide[:, :s_lo] + update_wide[:, s_lo:]
    elif mxu == "bf16x2":
        # One-hot matrices are 0/1 — EXACT in bf16. Only the data operand
        # carries mantissa, so instead of Precision.HIGHEST (6 bf16 MXU
        # passes for f32 x f32) we split the data side into two bf16 terms
        # (hi + lo, ~16 mantissa bits, ~1e-5 rel error) and run 2
        # single-pass bf16 matmuls — 3x the MXU throughput at
        # GLM-sufficient precision.
        oh_in_hi = (ih == hi_iota).astype(jnp.bfloat16)  # [S_HI, L]
        oh_in_lo = (il == lo_iota).astype(jnp.float32)  # [S_LO, L]

        # gather: src_g[p] = src2d[ih[p], il[p]]
        s1, s2 = _split(src_ref[0])
        a = jax.lax.dot_general(
            s1, oh_in_hi, dims_in, preferred_element_type=jnp.float32
        ) + jax.lax.dot_general(
            s2, oh_in_hi, dims_in, preferred_element_type=jnp.float32
        )  # [S_LO, L]
        src_g = jnp.sum(a * oh_in_lo, axis=0, keepdims=True)  # [1, L]
        contrib = v * src_g  # [1, L]

        oh_out_hi = (oh == hi_iota).astype(jnp.bfloat16)
        oh_out_lo = (ol == lo_iota).astype(jnp.bfloat16)
        # A @ B^T via lane/entry contraction. oh_out_lo is 0/1 and the
        # contrib terms are already bf16, so each product below is exact.
        c1, c2 = _split(contrib)
        update = jax.lax.dot_general(
            oh_out_hi, oh_out_lo * c1, dims_out,
            preferred_element_type=jnp.float32,
        ) + jax.lax.dot_general(
            oh_out_hi, oh_out_lo * c2, dims_out,
            preferred_element_type=jnp.float32,
        )  # [S_HI, S_LO]
    else:  # "highest": full f32 emulation, ~3x slower, ~1e-7 rel error
        oh_in_hi = (ih == hi_iota).astype(jnp.float32)
        oh_in_lo = (il == lo_iota).astype(jnp.float32)
        a = jax.lax.dot_general(
            src_ref[0], oh_in_hi, dims_in,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        src_g = jnp.sum(a * oh_in_lo, axis=0, keepdims=True)
        contrib = v * src_g
        oh_out_hi = (oh == hi_iota).astype(jnp.float32)
        oh_out_lo = (ol == lo_iota).astype(jnp.float32)
        update = jax.lax.dot_general(
            oh_out_hi, oh_out_lo * contrib, dims_out,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )

    @pl.when(step_init_ref[g] == 1)
    def _():
        out_ref[0] = update

    @pl.when(step_init_ref[g] != 1)
    def _():
        out_ref[0] = out_ref[0] + update


def _run_bilinear_pass(
    sched: _Schedule,
    src: Array,  # [num_in_blocks, S_HI, S_LO]
    num_out_blocks: int,
    params: TileParams,
    *,
    vals: Optional[Array] = None,
    interpret: bool = False,
    mxu: str = "bf16x2w",
) -> Array:
    """-> [num_out_blocks, S_HI, S_LO] accumulated output."""
    G = sched.num_steps
    L = params.chunk
    kernel = partial(
        _bilinear_pass_kernel,
        s_hi=params.s_hi,
        s_lo=params.s_lo,
        chunk=L,
        mxu=mxu,
    )
    entry_spec = pl.BlockSpec((8, L), lambda g, so, si, st: (g // 8, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(G,),
        in_specs=[
            entry_spec,  # in_pos
            entry_spec,  # out_pos
            entry_spec,  # vals
            pl.BlockSpec(
                (1, params.s_hi, params.s_lo),
                lambda g, so, si, st: (si[g], 0, 0),
            ),  # src window
        ],
        out_specs=pl.BlockSpec(
            (1, params.s_hi, params.s_lo),
            lambda g, so, si, st: (so[g], 0, 0),
        ),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (num_out_blocks, params.s_hi, params.s_lo), jnp.float32
        ),
        interpret=interpret,
    )(
        sched.step_out,
        sched.step_in,
        sched.step_init,
        sched.in_pos,
        sched.out_pos,
        sched.vals if vals is None else vals,
        src,
    )
    return out


@dataclass(frozen=True)
class TiledGLMObjective:
    """GLMObjective-compatible fused objective over TiledSparseBatch data.

    Same math and signature contract as
    photon_ml_tpu.ops.objective.GLMObjective (sum-weighted loss, L2 added
    once, lazy shift/factor normalization, psum over ``axis_name`` if set),
    with the margins/gradient passes running the tiled Pallas kernels
    instead of gather/scatter. Methods take the batch as an argument (pass
    it through jit — it is a pytree).
    """

    loss: object
    dim: int  # real (unpadded) coefficient dimension
    norm: NormalizationContext = None
    axis_name: Optional[str] = None
    interpret: bool = False
    # "bf16x2w" (default): hi+lo bf16 data split with both half-width
    # matmuls fused into one full-width MXU tile (~1e-5 rel err, fastest);
    # "bf16x2": the two-matmul variant; "highest" (~1e-7, 2.5x slower).
    mxu: str = "bf16x2w"

    def __post_init__(self):
        if self.norm is None:
            object.__setattr__(self, "norm", identity_context())
        if self.mxu not in ("bf16x2w", "bf16x2", "highest"):
            # a typo must not silently fall through to the "highest"
            # branch (2.5x slower, different numerics)
            raise ValueError(f"unknown mxu variant {self.mxu!r}")

    def _psum(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.psum(x, self.axis_name)

    def _pad(self, w: Array, batch: TiledSparseBatch) -> Array:
        if w.shape[0] == batch.dim:
            return w
        return jnp.zeros((batch.dim,), w.dtype).at[: w.shape[0]].set(w)

    def _z_pass(self, w_padded: Array, batch: TiledSparseBatch) -> Array:
        """raw row-sums [num_rows] of the tiled bilinear product."""
        b = batch
        p = b.params
        w2d = w_padded.reshape((b.num_feat_blocks, p.s_hi, p.s_lo))
        return _run_bilinear_pass(
            b.z_sched, w2d, b.num_row_blocks, p,
            interpret=self.interpret, mxu=self.mxu,
        ).reshape(-1)

    def _grad_pass(
        self, c_rows: Array, batch: TiledSparseBatch,
        vals: Optional[Array] = None,
    ) -> Array:
        b = batch
        p = b.params
        c2d = c_rows.reshape((b.num_row_blocks, p.s_hi, p.s_lo))
        return _run_bilinear_pass(
            b.g_sched, c2d, b.num_feat_blocks, p,
            vals=vals, interpret=self.interpret, mxu=self.mxu,
        ).reshape(-1)

    # -- margins -----------------------------------------------------------

    def margins(self, coef: Array, batch: TiledSparseBatch) -> Array:
        """z_i = x_eff_i . w_eff + offset_i in padded row space."""
        w_eff = self.norm.effective_coefficients(coef)
        raw = self._z_pass(self._pad(w_eff, batch), batch)
        return raw - self.norm.shift_dot(w_eff) + batch.offsets

    # -- value / gradient --------------------------------------------------

    def value(self, coef: Array, batch: TiledSparseBatch, l2_weight=0.0) -> Array:
        z = self.margins(coef, batch)
        val = jnp.sum(batch.weights * self.loss.value(z, batch.labels))
        val = self._psum(val)
        return val + 0.5 * l2_weight * jnp.dot(coef, coef)

    def value_and_gradient(
        self, coef: Array, batch: TiledSparseBatch, l2_weight=0.0
    ) -> Tuple[Array, Array]:
        d_in = coef.shape[0]
        z = self.margins(coef, batch)
        lv = self.loss.value(z, batch.labels)
        ld = self.loss.d1(z, batch.labels)
        c = batch.weights * ld
        value_sum = jnp.sum(batch.weights * lv)
        vector_sum = self._grad_pass(c, batch)[:d_in]
        prefactor_sum = jnp.sum(c)
        value_sum, vector_sum, prefactor_sum = self._psum(
            (value_sum, vector_sum, prefactor_sum)
        )
        grad = self.norm.unshift_gradient(vector_sum, prefactor_sum)
        value = value_sum + 0.5 * l2_weight * jnp.dot(coef, coef)
        return value, grad + l2_weight * coef

    def gradient(self, coef: Array, batch: TiledSparseBatch, l2_weight=0.0) -> Array:
        return self.value_and_gradient(coef, batch, l2_weight)[1]

    # -- second order ------------------------------------------------------

    def hessian_vector(
        self, coef: Array, direction: Array, batch: TiledSparseBatch,
        l2_weight=0.0,
    ) -> Array:
        d_in = coef.shape[0]
        w_eff = self.norm.effective_coefficients(coef)
        d_eff = self.norm.effective_coefficients(direction)
        z = (
            self._z_pass(self._pad(w_eff, batch), batch)
            - self.norm.shift_dot(w_eff) + batch.offsets
        )
        zd = (
            self._z_pass(self._pad(d_eff, batch), batch)
            - self.norm.shift_dot(d_eff)
        )
        c = batch.weights * self.loss.d2(z, batch.labels) * zd
        vector_sum = self._grad_pass(c, batch)[:d_in]
        prefactor_sum = jnp.sum(c)
        vector_sum, prefactor_sum = self._psum((vector_sum, prefactor_sum))
        hv = self.norm.unshift_gradient(vector_sum, prefactor_sum)
        return hv + l2_weight * direction

    def hessian_diagonal(
        self, coef: Array, batch: TiledSparseBatch, l2_weight=0.0
    ) -> Array:
        d_in = coef.shape[0]
        z = self.margins(coef, batch)
        c = batch.weights * self.loss.d2(z, batch.labels)
        s2 = self._grad_pass(c, batch, vals=batch.g_vals_sq)[:d_in]
        if self.norm.shift is not None:
            # shifted space needs S1 = sum c x and S0 = sum c as well
            s1 = self._grad_pass(c, batch)[:d_in]
            s0 = jnp.sum(c)
            s0, s1, s2 = self._psum((s0, s1, s2))
            diag = s2 - 2.0 * self.norm.shift * s1 + (self.norm.shift**2) * s0
        else:
            diag = self._psum(s2)
        if self.norm.factor is not None:
            diag = diag * self.norm.factor**2
        return diag + l2_weight

    # -- convenience -------------------------------------------------------

    def with_axis(self, axis_name: Optional[str]) -> "TiledGLMObjective":
        return TiledGLMObjective(
            self.loss, self.dim, self.norm, axis_name, self.interpret,
            self.mxu,
        )
