"""Tiled sparse GLM kernels: gather/scatter-free margins and gradients.

WHY: on TPU, XLA lowers random gather/scatter to ~7ns/element serial loops
(measured — PERF_NOTES.md), so the reference's two hot loops (margin
accumulation and gradient axpy, ValueAndGradientAggregator.scala:133-154)
are 100x slower than the hardware's streaming rate. This module replaces
both with a STATIC TILED layout + two Pallas kernels whose only per-entry
operations are VPU compares and MXU matmuls:

- Entries are binned into (row-window x feature-window) tiles; windows are
  R_WIN = F_WIN = S_HI * S_LO positions wide.
- A window-local index idx in [0, WIN) decomposes as hi*S_LO + lo; the
  gather w[idx] becomes the bilinear form onehot_hi @ w2d . onehot_lo with
  w2d = w_window reshaped [S_HI, S_LO] — ONE small matmul per chunk plus
  elementwise masks, no scatter/gather anywhere.
- The z-pass streams chunks sorted by row-block (output revisiting is
  monotone -> pallas accumulates the z window in VMEM); the grad-pass
  streams the same entries sorted by feature-block.

The schedule (tile assignment, chunking, window-local index packing) is
computed ONCE on host per dataset — full-batch GLM training re-evaluates
the same static structure hundreds of times, so the build cost amortizes
to zero. Schedules and per-row arrays are pytree leaves: pass the
TiledSparseBatch *as a jit argument* (exactly like SparseBatch), never a
closure constant — at ads scale the schedule is hundreds of MB and baking
it into the executable blows up compilation.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.normalization import NormalizationContext, identity_context
from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

Array = jnp.ndarray


@dataclass(frozen=True)
class TileParams:
    # Defaults from on-chip sweeps at the ads shape (262k x 64nnz x 1M,
    # PERF_NOTES.md "tile sweep"): window-shape changes (s_hi=s_lo=128, or
    # 64/128) were net losses. ``chunk=None`` sizes the grid-step width
    # from the dataset's average tile occupancy at build time (pow2 of the
    # mean entries per tile, clamped to [1024, 4096]) — at the ads shape
    # that picks 4096, which with the bf16x2w full-width matmuls measured
    # 23.9 ms vs 25.8 ms for the old fixed 2048 (fewer grid steps, ~99.5%
    # slot fill because the mean tile holds ~4078 entries).
    s_hi: int = 128
    s_lo: int = 64
    chunk: Optional[int] = None  # entries per grid step; None = auto
    # Independent compute chains per grid step (chunk lane-sliced into
    # `split` sub-chunks with no data dependency). Measured on-chip:
    # Mosaic does NOT overlap the chains (split=2 cost ~1.3-1.7 ms at
    # every chunk size), so the default stays 1; the knob remains for
    # kernel experiments. chunk must be divisible by split * 128.
    split: int = 1
    # Spill-to-scatter threshold: a tile whose entry count modulo the
    # chunk leaves a remainder <= spill_cap routes that remainder to a
    # small XLA gather/scatter path instead of paying a nearly-empty
    # grid step (and a tile with <= spill_cap entries total spills
    # entirely). Break-even (measured, ads shape): one grid step costs
    # ~3.9 us while a spilled entry costs ~15 ns of serialized
    # gather+scatter, so the cap defaults to chunk // 16 (~260 at chunk
    # 4096). None = default; 0 disables spilling.
    spill_cap: Optional[int] = None

    @property
    def window(self) -> int:
        return self.s_hi * self.s_lo

    def resolved_spill_cap(self) -> int:
        if self.spill_cap is not None:
            return self.spill_cap
        return max(0, (self.chunk or 0) // 16)

    def resolved(self, n_entries: int, n_tiles_hint: int) -> "TileParams":
        """Fix ``chunk=None`` from dataset statistics. Tiny-window test
        configs (window < 1024) fall back to the window size so toy
        schedules stay small.

        With spilling enabled and tiles in the single-chunk regime, the
        chunk is mean + 2*sqrt(mean) rounded up to a lane multiple: tile
        occupancy concentrates around the mean (Poisson-ish), so a chunk
        just past the +2-sigma tail holds ~98% of tiles in ONE ~97%-full
        step and spills only the far tail. Measured at the ads shape
        (mean 4078): chunk 4224 -> 16.5 ms/eval vs 18.6 at pow2 4096
        (104k spills -> 2.3k) vs 23.1 without spilling. Multi-chunk
        tiles (mean > 4096) keep the pow2 rule — the remainder logic
        already spills or pads their tails."""
        if self.chunk is not None:
            return self
        import dataclasses

        avg = max(1, n_entries // max(n_tiles_hint, 1))
        lo = min(1024, self.window)
        spilling = self.spill_cap is None or self.spill_cap > 0
        # lane slices in the kernel are chunk // split wide, so the
        # resolved chunk must divide by split * 128
        align = 128 * max(1, self.split)
        if spilling and avg <= 4096:
            c = int(-(-int(avg + 2.0 * np.sqrt(avg)) // align) * align)
            c = max(lo, min(-(-4608 // align) * align, c))
        else:
            c = 1 << int(np.round(np.log2(avg)))
            c = max(lo, min(4096, c))
        return dataclasses.replace(self, chunk=c)


class _Schedule(NamedTuple):
    """One pass's static schedule: chunked entries sorted by output block.

    All fields are arrays (the NamedTuple is a pytree — jit-argument safe).
    Entry blocks are 2-D rows [G, L]: TPU HBM tiling pads the trailing two
    dims to (8, 128), so [G, L, 1] would waste 128x HBM (observed: 54 GB
    for a 528 MB schedule) and [G, 1, L] 8x, while [G, L] is compact. In
    the kernel each [1, L] row broadcasts against sublane-iota; a
    [8, L//8] -> [L] reshape would be an unsupported Mosaic relayout.

    ``spill_*``: the tile remainders routed around the kernel (see
    TileParams.spill_cap) as SCHEDULE-LOCAL flat coordinates (output /
    input position in this pass's padded out/in space). Zero-padded to a
    lane multiple; padding slots carry val 0 at coordinate 0 — inert.
    """

    step_out: Array  # int32 [G] output block id per step
    step_in: Array  # int32 [G] input-window block id per step
    step_init: Array  # int32 [G] 1 iff first step of its output block
    out_pos: Array  # int32 [G, L] window-local OUTPUT index in [0, WIN)
    in_pos: Array  # int32 [G, L] window-local INPUT index in [0, WIN)
    vals: Array  # float32 [G, L] entry values (0 for padding slots)
    spill_out: Array  # int32 [S] flat output coordinate
    spill_in: Array  # int32 [S] flat input coordinate
    spill_vals: Array  # float32 [S] (0 for padding)

    @property
    def num_steps(self) -> int:
        return self.step_out.shape[0]

    def apply_spill(
        self, out_flat: Array, src_flat: Array,
        vals: Optional[Array] = None,
    ) -> Array:
        """out_flat[spill_out] += spill_vals * src_flat[spill_in] — the
        scatter cleanup completing the kernel's chunked partial sums.
        ``vals`` overrides the entry values (the hessian-diagonal pass
        squares them)."""
        if self.spill_vals.shape[0] == 0:
            return out_flat
        v = self.spill_vals if vals is None else vals
        contrib = v * jnp.take(src_flat, self.spill_in)
        return out_flat.at[self.spill_out].add(contrib)


import threading as _threading

_TILE_LIB_LOCK = _threading.Lock()
_tile_lib_handle = None  # None = untried, False = unavailable


def _tile_lib():
    """ctypes handle to native/tile_schedule.cpp (compiled on demand like
    io/native_avro.py); False when the toolchain/library is unavailable —
    callers fall back to the numpy builder."""
    global _tile_lib_handle
    if _tile_lib_handle is not None:
        return _tile_lib_handle
    import ctypes
    import os
    import subprocess

    with _TILE_LIB_LOCK:
        if _tile_lib_handle is not None:
            return _tile_lib_handle
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        src = os.path.join(root, "native", "tile_schedule.cpp")
        lib_dir = os.path.join(root, "native", "build")
        lib_path = os.path.join(lib_dir, "libtile_schedule.so")
        try:
            if not (
                os.path.isfile(lib_path)
                and os.path.getmtime(lib_path) >= os.path.getmtime(src)
            ):
                os.makedirs(lib_dir, exist_ok=True)
                # compile to a temp path + atomic rename so another
                # process never dlopens a half-written .so
                tmp_path = f"{lib_path}.{os.getpid()}.tmp"
                subprocess.run(
                    [
                        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                        src, "-o", tmp_path,
                    ],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp_path, lib_path)
            lib = ctypes.CDLL(lib_path)
            i64 = ctypes.c_int64
            p_i64 = ctypes.POINTER(i64)
            p_i32 = ctypes.POINTER(ctypes.c_int32)
            p_f32 = ctypes.POINTER(ctypes.c_float)
            lib.ts_plan.restype = i64
            lib.ts_plan.argtypes = [
                p_i64, p_i64, i64, i64, i64, i64, i64, p_i64, p_i64,
            ]
            lib.ts_fill.restype = i64
            lib.ts_fill.argtypes = [
                p_i64, p_i64, p_f32, i64, i64, i64, i64, i64, i64, i64,
                p_i32, p_i32, p_i32, p_i32, p_i32, p_f32,
                p_i32, p_i32, p_f32,
            ]
            _tile_lib_handle = lib
        except Exception:
            _tile_lib_handle = False
    return _tile_lib_handle


def _build_schedule_native(
    rows: np.ndarray,
    feats: np.ndarray,
    vals: np.ndarray,
    *,
    params: TileParams,
    sort_by_feature_block: bool,
    num_out_blocks: int,
) -> Optional[Tuple[np.ndarray, ...]]:
    """Counting-sort schedule build in C++ (~0.3 s vs ~4 s numpy at the ads
    shape; ctypes releases the GIL, so the z/grad passes overlap for real).
    Returns None when the native library is unavailable or the tile space
    is too large for counting sort."""
    lib = _tile_lib()
    if not lib:
        return None
    import ctypes

    if sort_by_feature_block:
        oc, ic = feats, rows
    else:
        oc, ic = rows, feats
    oc = np.ascontiguousarray(oc, dtype=np.int64)
    ic = np.ascontiguousarray(ic, dtype=np.int64)
    v = np.ascontiguousarray(vals, dtype=np.float32)
    n = oc.shape[0]
    L = params.chunk
    win = params.window

    def p(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    i64, i32, f32 = ctypes.c_int64, ctypes.c_int32, ctypes.c_float
    cap = params.resolved_spill_cap()
    # flat spill coordinates must fit int32 — same (conservative,
    # block-rounded) bound as the numpy builder so both produce
    # identically shaped schedules
    if cap and n and (
        (int(oc.max()) // win) * win + win >= 2**31
        or (int(ic.max()) // win) * win + win >= 2**31
    ):
        cap = 0
    steps_out = ctypes.c_int64()
    spilled_out = ctypes.c_int64()
    rc = lib.ts_plan(
        p(oc, i64), p(ic, i64), n, win, L, cap, num_out_blocks,
        ctypes.byref(steps_out), ctypes.byref(spilled_out),
    )
    if rc != 0:
        return None
    G = steps_out.value
    S = spilled_out.value
    G8 = ((G + 7) // 8) * 8
    step_out = np.zeros(G, np.int32)
    step_in = np.zeros(G, np.int32)
    step_init = np.zeros(G, np.int32)
    o_pos = np.zeros((G8, L), np.int32)
    i_pos = np.zeros((G8, L), np.int32)
    sv = np.zeros((G8, L), np.float32)
    sp_out = np.zeros(S, np.int32)
    sp_in = np.zeros(S, np.int32)
    sp_vals = np.zeros(S, np.float32)
    rc = lib.ts_fill(
        p(oc, i64), p(ic, i64), p(v, f32), n, win, L, cap,
        num_out_blocks, G, S,
        p(step_out, i32), p(step_in, i32), p(step_init, i32),
        p(o_pos, i32), p(i_pos, i32), p(sv, f32),
        p(sp_out, i32), p(sp_in, i32), p(sp_vals, f32),
    )
    if rc != 0:
        return None
    sp_out, sp_in, sp_vals = _pad_spill_np(sp_out, sp_in, sp_vals)
    return (
        step_out, step_in, step_init, o_pos, i_pos, sv,
        sp_out, sp_in, sp_vals,
    )


def _build_schedule_np(
    rows: np.ndarray,
    feats: np.ndarray,
    vals: np.ndarray,
    *,
    params: TileParams,
    sort_by_feature_block: bool,
    num_out_blocks: int,
    digest: Optional[str] = None,
) -> Tuple[np.ndarray, ...]:
    """Schedule build -> (step_out, step_in, step_init, o_pos, i_pos, sv)
    numpy arrays. Tries the persistent content-addressed disk cache first
    (ops/schedule_cache.py — a hit returns mmap-backed arrays and skips
    the build entirely), then the native counting-sort builder; the numpy
    path below is the fallback oracle (vectorized repeat/cumsum/scatter —
    no per-entry Python loops; the round-2 loop version cost 17-77 s at the
    ads shape, this is ~8 s, the native builder ~0.3 s).

    ``digest``: precomputed content digest of (rows, feats, vals) so
    callers building BOTH passes from one triple hash it once."""
    from photon_ml_tpu.ops import schedule_cache as _sc

    cache_dir = _sc.resolve_cache_dir()
    cache_key = None
    if cache_dir is not None:
        if digest is None:
            digest = _sc.content_digest(rows, feats, vals)
        cache_key = _sc.schedule_key(
            digest, params, sort_by_feature_block, num_out_blocks
        )
        cached = _sc.load_schedule(cache_dir, cache_key)
        if cached is None and not _sc.is_cache_writer():
            # multi-host: the coordinator builds and writes; everyone
            # else waits for its artifact (local build only on timeout)
            cached = _sc.wait_and_load(cache_dir, cache_key)
        if cached is not None:
            return cached
    import time as _time

    t_build = _time.perf_counter()
    native = _build_schedule_native(
        rows, feats, vals, params=params,
        sort_by_feature_block=sort_by_feature_block,
        num_out_blocks=num_out_blocks,
    )
    if native is not None:
        return _finish_schedule_build(native, t_build, cache_dir, cache_key)
    win = params.window
    L = params.chunk
    # int32 entry coordinates when they fit (half the sort/gather traffic);
    # feature ids can exceed int32 at the 10B-coefficient scale
    if len(rows) and int(rows.max()) < 2**31 and int(feats.max()) < 2**31:
        rows = rows.astype(np.int32, copy=False)
        feats = feats.astype(np.int32, copy=False)
    rb = rows // win
    fb = feats // win
    # Single combined-key stable argsort (numpy uses radix sort for ints —
    # ~2x faster than the equivalent two-key lexsort at 16.7M entries).
    if sort_by_feature_block:
        key = fb.astype(np.int64) * (int(rb.max(initial=0)) + 1) + rb
        order = np.argsort(key, kind="stable")
        out_blocks, in_blocks = fb[order], rb[order]
        out_pos, in_pos = feats[order] % win, rows[order] % win
    else:
        key = rb.astype(np.int64) * (int(fb.max(initial=0)) + 1) + fb
        order = np.argsort(key, kind="stable")
        out_blocks, in_blocks = rb[order], fb[order]
        out_pos, in_pos = rows[order] % win, feats[order] % win
    v = vals[order]
    n_ent = len(v)
    cap = params.resolved_spill_cap()
    sp_out = np.zeros(0, np.int32)
    sp_in = np.zeros(0, np.int32)
    sp_vals = np.zeros(0, np.float32)

    if n_ent:
        # tile boundaries: chunk entries so no chunk crosses a tile
        # boundary; the sort key IS the tile id, already ordered
        tile_key = key[order]
        tile_starts = np.nonzero(
            np.concatenate([[True], tile_key[1:] != tile_key[:-1]])
        )[0]
        tile_ends = np.concatenate([tile_starts[1:], [n_ent]])
        sizes_t = tile_ends - tile_starts
        if cap and (
            int(out_blocks.max(initial=0)) * win + win >= 2**31
            or int(in_blocks.max(initial=0)) * win + win >= 2**31
        ):
            cap = 0  # flat spill coordinates must fit int32
        # spill rule (see TileParams.spill_cap): whole tiny tiles spill;
        # otherwise a small remainder past the last full chunk spills —
        # the spilled entries are each tile's TAIL in stable order
        full = sizes_t // L
        rem = sizes_t % L
        spill_all = sizes_t <= cap
        spill_tail = (~spill_all) & (rem > 0) & (rem <= cap) & (full >= 1)
        n_spill_t = np.where(
            spill_all, sizes_t, np.where(spill_tail, rem, 0)
        )
        kept_t = sizes_t - n_spill_t
        n_chunks = -(-kept_t // L)  # 0 for fully spilled tiles
        if int(n_spill_t.sum()):
            pos_in_tile = np.arange(n_ent) - np.repeat(tile_starts, sizes_t)
            is_spill = pos_in_tile >= np.repeat(kept_t, sizes_t)
            sp_out = (
                out_blocks[is_spill].astype(np.int64) * win
                + out_pos[is_spill]
            ).astype(np.int32)
            sp_in = (
                in_blocks[is_spill].astype(np.int64) * win
                + in_pos[is_spill]
            ).astype(np.int32)
            sp_vals = v[is_spill].astype(np.float32)
            keep = ~is_spill
            out_blocks, in_blocks = out_blocks[keep], in_blocks[keep]
            out_pos, in_pos, v = out_pos[keep], in_pos[keep], v[keep]
            n_ent = len(v)
            tile_starts = np.concatenate(
                [[0], np.cumsum(kept_t)[:-1]]
            ).astype(tile_starts.dtype)
            tile_ends = tile_starts + kept_t
        live = n_chunks > 0
        tile_starts, tile_ends = tile_starts[live], tile_ends[live]
        n_chunks = n_chunks[live]
        G_data = int(n_chunks.sum())
        rep_start = np.repeat(tile_starts, n_chunks)
        rep_end = np.repeat(tile_ends, n_chunks)
        first = np.concatenate([[0], np.cumsum(n_chunks)[:-1]])
        ordinal = np.arange(G_data) - np.repeat(first, n_chunks)
        chunk_start = rep_start + ordinal * L
        chunk_end = np.minimum(chunk_start + L, rep_end)
        so_data = out_blocks[rep_start].astype(np.int32)
        si_data = in_blocks[chunk_start].astype(np.int32)
        sizes = chunk_end - chunk_start
        entry_step = np.repeat(np.arange(G_data), sizes)
        slot = np.arange(n_ent) - np.repeat(chunk_start, sizes)
    else:
        G_data = 0
        so_data = np.zeros(0, np.int32)
        si_data = np.zeros(0, np.int32)

    # Every output block needs at least one step: the kernel only writes
    # blocks named by step_out (out_ref starts as UNINITIALIZED memory on
    # TPU — interpret mode zero-fills, hiding this), so an output window
    # with no entries would otherwise return garbage. Append zero-entry
    # init steps for the missing blocks; the stable sort below slots them
    # into out-block order so VMEM accumulation stays monotone. Data steps
    # are already out-block-sorted (entries were lexsorted by out block),
    # so the stable merge preserves their entry order.
    present = np.zeros(num_out_blocks, bool)
    if G_data:
        present[so_data] = True
    missing = np.nonzero(~present)[0].astype(np.int32)

    G = G_data + len(missing)
    so_all = np.concatenate([so_data, missing])
    si_all = np.concatenate([si_data, np.zeros(len(missing), np.int32)])
    perm = np.argsort(so_all, kind="stable")
    step_out = so_all[perm]
    step_in = si_all[perm]
    step_init = np.ones(G, np.int32)
    step_init[1:] = (step_out[1:] != step_out[:-1]).astype(np.int32)

    # pad the entry-row axis to a multiple of 8: the kernel reads entry
    # rows in (8, L) blocks (sublane tiling); padded rows never execute
    G8 = ((G + 7) // 8) * 8
    o_pos = np.zeros((G8, L), np.int32)
    i_pos = np.zeros((G8, L), np.int32)
    sv = np.zeros((G8, L), np.float32)
    if n_ent:
        inv = np.empty(G_data, np.int64)
        inv[perm[perm < G_data].astype(np.int64)] = np.nonzero(
            perm < G_data
        )[0]
        dest_row = inv[entry_step]
        o_pos[dest_row, slot] = out_pos
        i_pos[dest_row, slot] = in_pos
        sv[dest_row, slot] = v
    sp_out, sp_in, sp_vals = _pad_spill_np(sp_out, sp_in, sp_vals)
    return _finish_schedule_build(
        (
            step_out, step_in, step_init, o_pos, i_pos, sv,
            sp_out, sp_in, sp_vals,
        ),
        t_build, cache_dir, cache_key,
    )


def _finish_schedule_build(arrays, t0, cache_dir, key):
    """Record the build in the cache stats/profiling stream and persist
    the artifact (writer process only) when the disk tier is active."""
    import time as _time

    from photon_ml_tpu.ops import schedule_cache as _sc

    _sc.record_build_seconds(_time.perf_counter() - t0)
    if key is not None and _sc.is_cache_writer():
        _sc.store_schedule(cache_dir, key, arrays)
    return arrays


def _pad_spill_np(sp_out, sp_in, sp_vals, pad_to: Optional[int] = None):
    """Zero-pad spill arrays to a lane multiple (or exactly ``pad_to``);
    padding entries carry val 0 at coordinate 0 — inert adds."""
    s = len(sp_vals)
    target = ((s + 127) // 128) * 128 if pad_to is None else pad_to
    if target < s:
        raise ValueError(f"pad_to={target} < spill size {s}")
    if target != s:
        sp_out = np.concatenate([sp_out, np.zeros(target - s, np.int32)])
        sp_in = np.concatenate([sp_in, np.zeros(target - s, np.int32)])
        sp_vals = np.concatenate(
            [sp_vals, np.zeros(target - s, np.float32)]
        )
    return sp_out, sp_in, sp_vals


def _pad_schedule_np(
    arrs: Tuple[np.ndarray, ...], pad_steps_to: int, num_out_blocks: int,
    pad_spill_to: Optional[int] = None,
) -> Tuple[np.ndarray, ...]:
    """Pad a schedule's step axis to ``pad_steps_to`` with inert zero-entry
    steps on the LAST output block (keeps out-block order monotone; the
    last block always exists — init steps guarantee every block has one)
    and its spill axis to ``pad_spill_to``. Needed so per-device-shard
    schedules share one static shape under shard_map."""
    (
        step_out, step_in, step_init, o_pos, i_pos, sv,
        sp_out, sp_in, sp_vals,
    ) = arrs
    G = step_out.shape[0]
    if pad_steps_to < G:
        raise ValueError(f"pad_steps_to={pad_steps_to} < num steps {G}")
    extra = pad_steps_to - G
    if extra:
        step_out = np.concatenate(
            [step_out, np.full(extra, num_out_blocks - 1, np.int32)]
        )
        step_in = np.concatenate([step_in, np.zeros(extra, np.int32)])
        step_init = np.concatenate([step_init, np.zeros(extra, np.int32)])
    G8 = ((pad_steps_to + 7) // 8) * 8
    L = o_pos.shape[1]
    if G8 > o_pos.shape[0]:
        pad_rows = G8 - o_pos.shape[0]
        o_pos = np.concatenate([o_pos, np.zeros((pad_rows, L), np.int32)])
        i_pos = np.concatenate([i_pos, np.zeros((pad_rows, L), np.int32)])
        sv = np.concatenate([sv, np.zeros((pad_rows, L), np.float32)])
    if pad_spill_to is not None:
        sp_out, sp_in, sp_vals = _pad_spill_np(
            sp_out, sp_in, sp_vals, pad_to=pad_spill_to
        )
    return (
        step_out, step_in, step_init, o_pos, i_pos, sv,
        sp_out, sp_in, sp_vals,
    )


def _build_schedule(
    rows: np.ndarray,
    feats: np.ndarray,
    vals: np.ndarray,
    *,
    params: TileParams,
    sort_by_feature_block: bool,
    num_out_blocks: int,
    digest: Optional[str] = None,
) -> _Schedule:
    return _Schedule(*map(jnp.asarray, _build_schedule_np(
        rows, feats, vals, params=params,
        sort_by_feature_block=sort_by_feature_block,
        num_out_blocks=num_out_blocks, digest=digest,
    )))


class TiledSparseBatch(NamedTuple):
    """Statically tiled sparse batch (replaces SparseBatch on the hot path).

    Row space is padded to num_row_blocks * window; feature space to
    num_feat_blocks * window. ``labels/offsets/weights`` live in padded row
    space (weight 0 padding). A NamedTuple pytree: ints are leaves too, but
    they are concrete python ints, so jit sees them as static weak-typed
    scalars only if hashable — instead we keep them in ``meta`` as a static
    aux via the _TiledMeta wrapper below.
    """

    meta: "_TiledMeta"
    z_sched: _Schedule
    g_sched: _Schedule
    g_vals_sq: Array  # [G2, L] squared values for hessian_diagonal
    labels: Array
    offsets: Array
    weights: Array

    # convenience passthroughs (static python ints)
    @property
    def params(self) -> TileParams:
        return self.meta.params

    @property
    def num_rows(self) -> int:
        return self.meta.num_rows

    @property
    def dim(self) -> int:
        return self.meta.dim

    @property
    def num_real_rows(self) -> int:
        return self.meta.num_real_rows

    @property
    def real_dim(self) -> int:
        return self.meta.real_dim

    @property
    def num_row_blocks(self) -> int:
        return self.meta.num_rows // self.meta.params.window

    @property
    def num_feat_blocks(self) -> int:
        return self.meta.dim // self.meta.params.window


@jax.tree_util.register_static
@dataclass(frozen=True)
class _TiledMeta:
    """Static (hashable) shape metadata for TiledSparseBatch.

    ``data_shards > 1`` marks a mesh layout: every array leaf carries
    ``data_shards`` per-shard segments concatenated along axis 0 (all
    per-shard shapes equal), and the shape fields describe ONE shard —
    the view each device sees inside shard_map with the batch's leaves
    split over the data axis. Such a batch is only meaningful under that
    shard_map; single-device code must use ``data_shards == 1`` batches.
    """

    params: TileParams
    num_rows: int  # padded (per data shard)
    dim: int  # padded
    num_real_rows: int  # global real row count
    real_dim: int
    data_shards: int = 1


def build_tiled_batch(
    rows: np.ndarray,
    feats: np.ndarray,
    vals: np.ndarray,
    labels: np.ndarray,
    offsets: np.ndarray,
    weights: np.ndarray,
    dim: int,
    *,
    params: TileParams = TileParams(),
) -> TiledSparseBatch:
    """COO triples + per-row arrays -> tiled batch. Entries with zero value
    are dropped (they contribute nothing)."""
    nz = vals != 0
    rows, feats, vals = rows[nz], feats[nz], vals[nz]
    win = params.window
    n = labels.shape[0]
    n_pad = max(((n + win - 1) // win) * win, win)
    d_pad = max(((dim + win - 1) // win) * win, win)
    params = params.resolved(len(vals), (n_pad // win) * (d_pad // win))

    # the two passes are independent and numpy's sorts/gathers release the
    # GIL — overlap them (halves the dominant host cost of cold training)
    from concurrent.futures import ThreadPoolExecutor

    from photon_ml_tpu.ops import schedule_cache as _sc

    # both passes key off the same COO triple: hash it once, up front
    digest = (
        _sc.content_digest(rows, feats, vals)
        if _sc.resolve_cache_dir() is not None else None
    )
    with ThreadPoolExecutor(2) as pool:
        fz = pool.submit(
            _build_schedule, rows, feats, vals, params=params,
            sort_by_feature_block=False, num_out_blocks=n_pad // win,
            digest=digest,
        )
        fg = pool.submit(
            _build_schedule, rows, feats, vals, params=params,
            sort_by_feature_block=True, num_out_blocks=d_pad // win,
            digest=digest,
        )
        z_sched = fz.result()
        g_sched = fg.result()
    lab = np.zeros(n_pad, np.float32)
    lab[:n] = labels
    off = np.zeros(n_pad, np.float32)
    off[:n] = offsets
    wgt = np.zeros(n_pad, np.float32)
    wgt[:n] = weights
    return TiledSparseBatch(
        meta=_TiledMeta(
            params=params,
            num_rows=n_pad,
            dim=d_pad,
            num_real_rows=n,
            real_dim=dim,
        ),
        z_sched=z_sched,
        g_sched=g_sched,
        g_vals_sq=g_sched.vals**2,
        labels=jnp.asarray(lab),
        offsets=jnp.asarray(off),
        weights=jnp.asarray(wgt),
    )


def tiled_batch_from_sparse(batch, dim: int, *, params: TileParams = TileParams()):
    """Convenience: SparseBatch (padded ELL) -> TiledSparseBatch."""
    rows, feats, vals, _ = _sparse_coo(batch)
    return build_tiled_batch(
        rows, feats, vals,
        np.asarray(batch.labels), np.asarray(batch.offsets),
        np.asarray(batch.weights),
        dim, params=params,
    )


def _sparse_coo(batch) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """SparseBatch -> filtered COO triples (+ real row count): zero values
    and weight-0 (padding) rows dropped."""
    indices = np.asarray(batch.indices)
    values = np.asarray(batch.values)
    weights = np.asarray(batch.weights)
    n, k = indices.shape
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    feats = indices.reshape(-1).astype(np.int64)
    vals = values.reshape(-1).astype(np.float32)
    vals = np.where(np.repeat(weights > 0, k), vals, 0.0)
    nz = vals != 0
    return rows[nz], feats[nz], vals[nz], n


def _padded_row_meta(batch, total: int, n: int):
    lab = np.zeros(total, np.float32)
    lab[:n] = np.asarray(batch.labels)
    off = np.zeros(total, np.float32)
    off[:n] = np.asarray(batch.offsets)
    wgt = np.zeros(total, np.float32)
    wgt[:n] = np.asarray(batch.weights)
    return jnp.asarray(lab), jnp.asarray(off), jnp.asarray(wgt)


def _concat_cell_schedules(
    local_rows: np.ndarray,
    local_feats: np.ndarray,
    vals: np.ndarray,
    cell_of: np.ndarray,
    n_cells: int,
    *,
    params: TileParams,
    z_out_blocks: int,
    g_out_blocks: int,
) -> Tuple[_Schedule, _Schedule, np.ndarray]:
    """Per-cell z/grad schedules padded to ONE static shape and
    concatenated along the step axis (cells in ``cell_of`` order) so a
    shard_map split hands each device its own schedule. Returns
    (z_sched, g_sched, g_vals numpy) — callers square g_vals for the
    hessian-diagonal pass."""
    from concurrent.futures import ThreadPoolExecutor

    def _cell_pair(c):
        m = cell_of == c
        lr, lf, vl = local_rows[m], local_feats[m], vals[m]
        return (
            _build_schedule_np(
                lr, lf, vl, params=params, sort_by_feature_block=False,
                num_out_blocks=z_out_blocks,
            ),
            _build_schedule_np(
                lr, lf, vl, params=params, sort_by_feature_block=True,
                num_out_blocks=g_out_blocks,
            ),
        )

    with ThreadPoolExecutor(min(8, n_cells)) as pool:
        pairs = list(pool.map(_cell_pair, range(n_cells)))
        z_parts = [p[0] for p in pairs]
        g_parts = [p[1] for p in pairs]
        gz = max(p[0].shape[0] for p in z_parts)
        gg = max(p[0].shape[0] for p in g_parts)
        sz = max(p[8].shape[0] for p in z_parts)
        sg = max(p[8].shape[0] for p in g_parts)
        # the per-cell pad-to-common-shape copies were the last serial
        # stretch of the sharded build — numpy concatenate releases the
        # GIL, so they overlap on the same pool
        z_parts = list(pool.map(
            lambda p: _pad_schedule_np(p, gz, z_out_blocks, sz), z_parts
        ))
        g_parts = list(pool.map(
            lambda p: _pad_schedule_np(p, gg, g_out_blocks, sg), g_parts
        ))
    z_sched = _Schedule(*(
        jnp.asarray(np.concatenate([p[i] for p in z_parts]))
        for i in range(9)
    ))
    g_sched = _Schedule(*(
        jnp.asarray(np.concatenate([p[i] for p in g_parts]))
        for i in range(9)
    ))
    return z_sched, g_sched, np.concatenate([p[5] for p in g_parts])


# photon: sharding(axes=[data], in=?, out=[data])
def build_sharded_tiled_batch(
    batch,
    dim: int,
    n_shards: int,
    *,
    params: TileParams = TileParams(),
    mesh=None,
    axis: Optional[str] = None,
) -> TiledSparseBatch:
    """SparseBatch -> mesh-layout TiledSparseBatch: the fast kernel AND
    data parallelism simultaneously (the reference's hot loop property,
    ValueAndGradientAggregator.scala:235-250).

    Rows split into ``n_shards`` contiguous ranges (each padded to the tile
    window); each range gets its OWN z/grad schedule built in its local row
    space; all schedules are padded to one static shape and concatenated
    along axis 0. Under shard_map with the batch's leaves split over the
    data axis, every device then sees exactly a single-shard
    TiledSparseBatch (the meta describes the per-shard view) and runs the
    unmodified Pallas kernels; the objective's ``axis_name`` psums do the
    cross-device reduction. With ``mesh`` given, leaves are placed with
    rows/steps sharded over ``axis`` (default "data").
    """
    win = params.window
    rows, feats, vals, n = _sparse_coo(batch)
    rows_per = -(-n // n_shards)
    R = max(((rows_per + win - 1) // win) * win, win)
    d_pad = max(((dim + win - 1) // win) * win, win)
    params = params.resolved(
        len(vals), n_shards * (R // win) * (d_pad // win)
    )
    shard_of = rows // R
    local_rows = rows - shard_of * R

    z_sched, g_sched, g_vals = _concat_cell_schedules(
        local_rows, feats, vals, shard_of, n_shards,
        params=params, z_out_blocks=R // win, g_out_blocks=d_pad // win,
    )
    g_vals_sq = jnp.asarray(g_vals**2)
    lab, off, wgt = _padded_row_meta(batch, n_shards * R, n)
    out = TiledSparseBatch(
        meta=_TiledMeta(
            params=params, num_rows=R, dim=d_pad, num_real_rows=n,
            real_dim=dim, data_shards=n_shards,
        ),
        z_sched=z_sched,
        g_sched=g_sched,
        g_vals_sq=g_vals_sq,
        labels=lab,
        offsets=off,
        weights=wgt,
    )
    if mesh is not None:
        out = _place_data_sharded(out, mesh, axis or DATA_AXIS)
    return out


@jax.tree_util.register_static
@dataclass(frozen=True)
class _FeatureShardedTiledMeta:
    """Static metadata for FeatureShardedTiledBatch: shapes describe ONE
    (data shard x feature block) cell — the per-device view."""

    params: TileParams
    rows_per_shard: int  # padded rows per data shard
    block_dim: int  # padded features per model block (multiple of window)
    num_real_rows: int
    real_dim: int
    data_shards: int
    model_shards: int


class FeatureShardedTiledBatch(NamedTuple):
    """The 10B-coefficient layout on the FAST kernel: a SparseBatch
    re-laid-out for a 2-D (data x model) mesh with one tiled schedule per
    (data shard, feature block) cell.

    Each cell's z-schedule produces that feature block's PARTIAL margins
    for its row shard (psum over "model" completes them); its g-schedule
    produces the block-local gradient (psum over "data" completes it) —
    same collective pattern as parallel.distributed's scatter-based sparse
    layout, but running the Pallas bilinear kernels instead of
    ~7ns/element gather/scatter loops.

    Schedule leaves concatenate cells along axis 0 in data-major,
    model-minor order, all cells padded to one static shape, so shard_map
    splits them with ``P((data, model))``. Row metadata is sharded over
    "data" only (replicated across feature blocks). Global feature id
    f lives at w[(f // block_dim) * block_dim + f % block_dim] — blocks
    are contiguous ranges, so w[:real_dim] are the real coefficients.
    """

    meta: _FeatureShardedTiledMeta
    z_sched: _Schedule
    g_sched: _Schedule
    labels: Array
    offsets: Array
    weights: Array


# photon: sharding(axes=[data,model], in=?, out=[data+model])
def feature_shard_tiled_batch(
    batch,
    dim: int,
    data_shards: int,
    model_shards: int,
    *,
    params: TileParams = TileParams(),
    mesh=None,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
) -> Tuple[FeatureShardedTiledBatch, int]:
    """SparseBatch -> (FeatureShardedTiledBatch, block_dim).

    ``block_dim`` (features per model block) is rounded up to a multiple of
    the tile window so every block's local feature space is tile-aligned;
    the sharded coefficient vector has length model_shards * block_dim.
    With ``mesh`` given, leaves are placed with schedules sharded over
    (data, model) and row metadata over data.
    """
    win = params.window
    rows, feats, vals, n = _sparse_coo(batch)
    rows_per = -(-n // data_shards)
    R = max(((rows_per + win - 1) // win) * win, win)
    block_dim = -(-dim // model_shards)
    block_dim = max(((block_dim + win - 1) // win) * win, win)
    params = params.resolved(
        len(vals),
        data_shards * model_shards * (R // win) * (block_dim // win),
    )

    ds_of = rows // R
    local_rows = rows - ds_of * R
    mb_of = feats // block_dim
    local_feats = feats - mb_of * block_dim
    cell_of = ds_of * model_shards + mb_of

    z_sched, g_sched, _ = _concat_cell_schedules(
        local_rows, local_feats, vals, cell_of,
        data_shards * model_shards, params=params,
        z_out_blocks=R // win, g_out_blocks=block_dim // win,
    )
    lab, off, wgt = _padded_row_meta(batch, data_shards * R, n)
    out = FeatureShardedTiledBatch(
        meta=_FeatureShardedTiledMeta(
            params=params, rows_per_shard=R, block_dim=block_dim,
            num_real_rows=n, real_dim=dim, data_shards=data_shards,
            model_shards=model_shards,
        ),
        z_sched=z_sched,
        g_sched=g_sched,
        labels=lab,
        offsets=off,
        weights=wgt,
    )
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        cell_sh = NamedSharding(mesh, P((data_axis, model_axis)))
        row_sh = NamedSharding(mesh, P(data_axis))
        out = FeatureShardedTiledBatch(
            meta=out.meta,
            z_sched=_Schedule(*(
                jax.device_put(a, cell_sh) for a in out.z_sched
            )),
            g_sched=_Schedule(*(
                jax.device_put(a, cell_sh) for a in out.g_sched
            )),
            labels=jax.device_put(out.labels, row_sh),
            offsets=jax.device_put(out.offsets, row_sh),
            weights=jax.device_put(out.weights, row_sh),
        )
    return out, block_dim


def tiled_block_local_vg(loss, batch: FeatureShardedTiledBatch,
                         data_axis: str, model_axis: str, l2,
                         *, shift=None, factor=None,
                         interpret: bool = False, mxu: str = "bf16x2w"):
    """Block-local (value, grad) closure over ONE device's cell of a
    FeatureShardedTiledBatch (call inside shard_map). The distributed.py
    fit entry points wrap this with the unmodified L-BFGS/OWL-QN.

    ``shift``/``factor``: this feature block's slice of the lazy
    normalization vectors (NormalizationContext.scala:119-157 applied
    inside the aggregator): margins use w_eff = factor * w and subtract
    the psum'd shift.w_eff scalar; gradients un-shift with the data-psum'd
    prefactor — normalization shards trivially along the feature axis."""
    meta = batch.meta
    p = meta.params
    win = p.window

    def vg(w_block):
        w_eff = w_block if factor is None else w_block * factor
        w2d = w_eff.reshape((meta.block_dim // win, p.s_hi, p.s_lo))
        z_partial = _bilinear_pass_auto(
            batch.z_sched, w2d, meta.rows_per_shard // win, p,
            interpret=interpret, mxu=mxu,
        ).reshape(-1)
        z_partial = batch.z_sched.apply_spill(z_partial, w_eff)
        if shift is not None:
            z_partial = z_partial - jnp.vdot(shift, w_eff)
        z = jax.lax.psum(z_partial, model_axis) + batch.offsets
        c = batch.weights * loss.d1(z, batch.labels)
        value = jax.lax.psum(
            jnp.sum(batch.weights * loss.value(z, batch.labels)), data_axis
        )
        c2d = c.reshape((meta.rows_per_shard // win, p.s_hi, p.s_lo))
        g_local = _bilinear_pass_auto(
            batch.g_sched, c2d, meta.block_dim // win, p,
            interpret=interpret, mxu=mxu,
        ).reshape(-1)
        g_local = batch.g_sched.apply_spill(g_local, c)
        grad_block = jax.lax.psum(g_local, data_axis)
        if shift is not None or factor is not None:
            prefactor = jax.lax.psum(jnp.sum(c), data_axis)
            if shift is not None:
                grad_block = grad_block - shift * prefactor
            if factor is not None:
                grad_block = grad_block * factor
        w_sq = jax.lax.psum(jnp.vdot(w_block, w_block), model_axis)
        return value + 0.5 * l2 * w_sq, grad_block + l2 * w_block

    return vg


def tiled_block_local_hvp_factory(
    loss, batch: FeatureShardedTiledBatch,
    data_axis: str, model_axis: str, l2,
    *, shift=None, factor=None,
    interpret: bool = False, mxu: str = "bf16x2w",
):
    """Block-local Hessian-vector FACTORY over one device's cell of a
    FeatureShardedTiledBatch (call inside shard_map) — the tiled twin of
    parallel.distributed._sparse_block_hvp_factory
    (HessianVectorAggregator.scala:137-152). The Hv pass reuses the
    z-schedule for the direction expansion and the g-schedule for the
    accumulation — same static layout, different contraction — so the
    reference's hottest distributed loop (one Hv per CG step,
    TRON.scala:259-341) runs at full kernel speed. The w-only pieces
    (margins psum, second-derivative coefficients) are computed once per
    outer TRON iteration."""
    meta = batch.meta
    p = meta.params
    win = p.window

    def _z(x_block):
        # x_block is already in EFFECTIVE space (callers apply factor);
        # the shift correction is one block-local scalar folded into the
        # model-axis psum
        x2d = x_block.reshape((meta.block_dim // win, p.s_hi, p.s_lo))
        part = _bilinear_pass_auto(
            batch.z_sched, x2d, meta.rows_per_shard // win, p,
            interpret=interpret, mxu=mxu,
        ).reshape(-1)
        part = batch.z_sched.apply_spill(part, x_block)
        if shift is not None:
            part = part - jnp.vdot(shift, x_block)
        return part

    def _eff(x_block):
        return x_block if factor is None else x_block * factor

    def factory(w_block):
        z = jax.lax.psum(_z(_eff(w_block)), model_axis) + batch.offsets
        d2c = batch.weights * loss.d2(z, batch.labels)

        def hvp(d_block):
            zd = jax.lax.psum(_z(_eff(d_block)), model_axis)
            c = d2c * zd
            c2d = c.reshape((meta.rows_per_shard // win, p.s_hi, p.s_lo))
            h_local = _bilinear_pass_auto(
                batch.g_sched, c2d, meta.block_dim // win, p,
                interpret=interpret, mxu=mxu,
            ).reshape(-1)
            h_local = batch.g_sched.apply_spill(h_local, c)
            h_block = jax.lax.psum(h_local, data_axis)
            if shift is not None or factor is not None:
                prefactor = jax.lax.psum(jnp.sum(c), data_axis)
                if shift is not None:
                    h_block = h_block - shift * prefactor
                if factor is not None:
                    h_block = h_block * factor
            return h_block + l2 * d_block

        return hvp

    return factory


def tiled_block_local_hdiag(
    loss, batch: FeatureShardedTiledBatch,
    data_axis: str, model_axis: str, l2,
    *, shift=None, factor=None,
    interpret: bool = False, mxu: str = "bf16x2w",
):
    """Block-local Hessian-DIAGONAL closure over one device's cell — the
    variance computation of DistributedOptimizationProblem.scala:79-93 on
    the feature-sharded layout. Hdiag is block-local by construction
    (diag_j only touches feature j's entries), so it shards trivially:
    one g-pass with squared values psum'd over "data" (plus the S1/S0
    shifted-space terms when normalization is active)."""
    meta = batch.meta
    p = meta.params
    win = p.window

    def hdiag(w_block):
        w_eff = w_block if factor is None else w_block * factor
        w2d = w_eff.reshape((meta.block_dim // win, p.s_hi, p.s_lo))
        z_partial = _bilinear_pass_auto(
            batch.z_sched, w2d, meta.rows_per_shard // win, p,
            interpret=interpret, mxu=mxu,
        ).reshape(-1)
        z_partial = batch.z_sched.apply_spill(z_partial, w_eff)
        if shift is not None:
            z_partial = z_partial - jnp.vdot(shift, w_eff)
        z = jax.lax.psum(z_partial, model_axis) + batch.offsets
        c = batch.weights * loss.d2(z, batch.labels)
        c2d = c.reshape((meta.rows_per_shard // win, p.s_hi, p.s_lo))

        def g_pass(vals, spill_vals):
            out = _bilinear_pass_auto(
                batch.g_sched, c2d, meta.block_dim // win, p,
                vals=vals, interpret=interpret, mxu=mxu,
            ).reshape(-1)
            return batch.g_sched.apply_spill(out, c, vals=spill_vals)

        s2 = jax.lax.psum(
            g_pass(batch.g_sched.vals**2, batch.g_sched.spill_vals**2),
            data_axis,
        )
        if shift is not None:
            s1 = jax.lax.psum(g_pass(None, None), data_axis)
            s0 = jax.lax.psum(jnp.sum(c), data_axis)
            diag = s2 - 2.0 * shift * s1 + (shift**2) * s0
        else:
            diag = s2
        if factor is not None:
            diag = diag * factor**2
        return diag + l2

    return hdiag


def _place_data_sharded(batch: TiledSparseBatch, mesh, axis: str):
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


# In-memory conversion caches for ensure_tiled / ensure_tiled_sharded: a
# caller that wraps the SAME indices/values/weights arrays in a fresh
# SparseBatch per call (the GAME coordinate-descent pattern — only
# offsets change between sweeps) must not pay the multi-second schedule
# rebuild + host pull every call. Keyed by array identity; LRU-bounded
# because each entry pins a tiled batch in HBM. TWO separate caches (one
# per conversion flavor, ADVICE.md round 5): a process interleaving
# single-device and sharded conversions — GAME with several FE shards
# plus a GLM grid — previously thrashed one shared 2-entry dict and
# silently rebuilt every sweep. Both sit in front of the persistent disk
# tier (ops/schedule_cache.py), which absorbs genuine evictions and
# process restarts.
from photon_ml_tpu.ops.schedule_cache import ScheduleLRU as _ScheduleLRU

_TILED_CACHE_MAX = 2
_SHARDED_CACHE_MAX = 2
_TILED_CACHE = _ScheduleLRU(_TILED_CACHE_MAX)
_SHARDED_CACHE = _ScheduleLRU(_SHARDED_CACHE_MAX)


def ensure_tiled(  # photon: entropy(id-keyed tiling memo; weakref-pinned, never serialized)
    batch,
    dim: int,
    *,
    params: Optional[TileParams] = None,
) -> TiledSparseBatch:
    """Idempotent single-device tiled conversion with the same
    identity-keyed LRU pattern as ensure_tiled_sharded (but its OWN
    bounded cache, so the two conversion flavors cannot evict each
    other): a SparseBatch sharing indices/values/weights with a previous
    call (the GAME coordinate-descent pattern — only offsets change
    between sweeps) reuses the cached schedules and only re-pads the row
    metadata."""
    if isinstance(batch, TiledSparseBatch):
        return batch
    key = (
        id(batch.indices), id(batch.values), id(batch.weights),
        dim, params,
    )
    hit = _TILED_CACHE.get(key)
    if hit is not None:
        (ix_ref, v_ref, w_ref), cached = hit
        if (
            ix_ref is batch.indices
            and v_ref is batch.values
            and w_ref is batch.weights
        ):
            meta = cached.meta
            lab, off, wgt = _padded_row_meta(
                batch, meta.num_rows, meta.num_real_rows
            )
            return cached._replace(labels=lab, offsets=off, weights=wgt)
        _TILED_CACHE.pop(key)  # stale id collision
    out = tiled_batch_from_sparse(
        batch, dim, params=params or TileParams()
    )
    _TILED_CACHE.put(
        key, ((batch.indices, batch.values, batch.weights), out),
    )
    return out


# photon: sharding(axes=[data], in=?, out=[data])
def ensure_tiled_sharded(  # photon: entropy(id-keyed tiling memo; weakref-pinned, never serialized)
    batch,
    dim: int,
    mesh,
    axis: str = DATA_AXIS,
    *,
    params: Optional[TileParams] = None,
) -> TiledSparseBatch:
    """Idempotent mesh-layout conversion (the tiled analog of
    parallel.mesh.ensure_data_sharded): SparseBatch -> sharded tiled build;
    an already-matching TiledSparseBatch passes through (so a lambda grid
    or coordinate-descent loop pays the schedule build + transfer once).
    A SparseBatch sharing indices/values/weights with a previous call
    reuses the cached schedules — only the row metadata (labels/offsets/
    weights, the parts a CD sweep changes) is re-padded and re-placed."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = int(mesh.shape[axis])
    if isinstance(batch, TiledSparseBatch):
        if batch.meta.data_shards != n:
            raise ValueError(
                f"TiledSparseBatch was laid out for {batch.meta.data_shards} "
                f"data shard(s) but the mesh's {axis!r} axis has {n}; "
                "rebuild from the SparseBatch with build_sharded_tiled_batch"
            )
        if getattr(batch.labels, "sharding", None) == NamedSharding(mesh, P(axis)):
            return batch
        return _place_data_sharded(batch, mesh, axis)
    key = (
        id(batch.indices), id(batch.values), id(batch.weights),
        dim, n, id(mesh), axis, params,
    )
    hit = _SHARDED_CACHE.get(key)
    if hit is not None:
        (ix_ref, v_ref, w_ref), cached = hit
        if (
            ix_ref is batch.indices
            and v_ref is batch.values
            and w_ref is batch.weights
        ):
            meta = cached.meta
            lab, off, wgt = _padded_row_meta(
                batch, meta.data_shards * meta.num_rows, meta.num_real_rows
            )
            row_sh = NamedSharding(mesh, P(axis))
            return cached._replace(
                labels=jax.device_put(lab, row_sh),
                offsets=jax.device_put(off, row_sh),
                weights=jax.device_put(wgt, row_sh),
            )
        _SHARDED_CACHE.pop(key)  # stale id collision
    out = build_sharded_tiled_batch(
        batch, dim, n, params=params or TileParams(), mesh=mesh, axis=axis
    )
    _SHARDED_CACHE.put(
        key, ((batch.indices, batch.values, batch.weights), out),
    )
    return out


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _bilinear_pass_kernel(
    # scalar prefetch
    step_out_ref, step_in_ref, step_init_ref,
    # per-step entry blocks [1, L]
    in_pos_ref, out_pos_ref, vals_ref,
    # gathered-from window [1, S_HI, S_LO] (w2d for z-pass, c2d for grad)
    src_ref,
    # output window accumulator [1, S_HI, S_LO]
    out_ref,
    *,
    s_hi: int,
    s_lo: int,
    chunk: int,
    mxu: str,
    split: int = 1,
    onehot: str = "compare",
):
    """One grid step: expand src at in_pos, multiply by vals,
    bilinear-scatter into the out_pos output window.

    Entries live on LANES ([1, L] rows); one-hots are sublane-iota
    compares, so each one-hot is [S, L] with the entry axis last and both
    matmuls contract without any transpose relayout.
    """
    g = pl.program_id(0)
    L = chunk
    # Entry blocks are [8, L] (8 steps' rows — sublane dim must tile by 8);
    # select this step's row with a sublane one-hot mask + reduce (dynamic
    # sublane slicing would relayout; the mask is cheap VPU work).
    r = jax.lax.rem(g, 8)
    row_sel = (
        jax.lax.broadcasted_iota(jnp.int32, (8, L), 0) == r
    )
    ip_full = jnp.sum(
        jnp.where(row_sel, in_pos_ref[...], 0), axis=0, keepdims=True
    )  # [1, L] int32, window-local = hi * s_lo + lo
    op_full = jnp.sum(
        jnp.where(row_sel, out_pos_ref[...], 0), axis=0, keepdims=True
    )
    v_full = jnp.sum(
        jnp.where(row_sel, vals_ref[...], 0.0), axis=0, keepdims=True
    )  # [1, L] float32

    def _split(x):
        # hi + lo bf16 terms of an f32 array (~16 mantissa bits kept);
        # shared by both bf16 variants — keep their numerics identical
        hi_part = x.astype(jnp.bfloat16)
        lo_part = (x - hi_part.astype(jnp.float32)).astype(jnp.bfloat16)
        return hi_part, lo_part

    def _expand(idx, s, width, dt):
        """Positional expansion: [1, width] window-local indices ->
        [s, width] one-hot rows.

        ``onehot="compare"`` (default): sublane-iota equality compare —
        the round-2 build, one [s, width] VPU compare + select chain.

        ``onehot="mxu"``: the round-3 "pack the one-hot build itself
        onto the MXU" lever. 1 - (i - ix)^2 comes from ONE tiny
        [s, 3] x [3, width] matmul over packed features [1, ix, ix^2]
        (lhs rows [1 - i^2, 2i, -1]); a single relu blends it to the
        exact 0/1 indicator, since integer mismatches give d >= 1.
        f32 HIGHEST keeps ix^2 exact (< 2^14 << 2^24 mantissa range) —
        one-hot EXACTNESS, which the bf16 split relies on, survives.
        Trades the [s, width] compare chain for a matmul + one
        elementwise pass; whether Mosaic schedules it better than the
        compare is the A/B bench.py carries (PERF_NOTES round 6)."""
        if onehot == "mxu":
            i_col = jax.lax.broadcasted_iota(jnp.float32, (s, 1), 0)
            lhs = jnp.concatenate(
                [1.0 - i_col * i_col, 2.0 * i_col, -jnp.ones_like(i_col)],
                axis=1,
            )  # [s, 3]
            idx_f = idx.astype(jnp.float32)
            rhs = jnp.concatenate(
                [jnp.ones_like(idx_f), idx_f, idx_f * idx_f], axis=0
            )  # [3, width]
            d = jax.lax.dot_general(
                lhs, rhs, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )  # [s, width] = 1 - (i - ix)^2
            return jnp.maximum(d, 0.0).astype(dt)
        iota = jax.lax.broadcasted_iota(jnp.int32, (s, width), 0)
        return (idx == iota).astype(dt)

    def _chain(ip, op, v, width):
        """One independent gather->contrib->scatter chain over ``width``
        entry lanes -> update [S_HI, S_LO]."""
        ih = ip // s_lo
        il = ip - ih * s_lo
        oh = op // s_lo
        ol = op - oh * s_lo
        dims_in = (((0,), (0,)), ((), ()))
        dims_out = (((1,), (1,)), ((), ()))

        if mxu == "bf16x2w":
            # Same hi+lo bf16 data split as "bf16x2", but each pass's TWO
            # half-width matmuls fuse into ONE full-width matmul by packing
            # the hi and lo terms into the otherwise idle half of the MXU
            # tile (s_lo = 64 uses 64 of 128 sublanes/lanes): identical MAC
            # count at ~2x the effective utilization.
            oh_in_hi = _expand(ih, s_hi, width, jnp.bfloat16)  # [S_HI, w]

            # gather: pack [hi | lo] along the lane axis -> [S_HI, 2*S_LO]
            s1, s2 = _split(src_ref[0])
            src_cat = jnp.concatenate([s1, s2], axis=1)
            a_cat = jax.lax.dot_general(
                src_cat, oh_in_hi, dims_in,
                preferred_element_type=jnp.float32,
            )  # [2*S_LO, w]: rows [0,S_LO) = hi terms, [S_LO,2*S_LO) = lo
            # fold the halves first (sublane slice at a multiple of 8) so
            # the mask-reduce runs at [S_LO, w] instead of [2*S_LO, w]
            a = a_cat[:s_lo] + a_cat[s_lo:]
            oh_in_lo = _expand(il, s_lo, width, jnp.float32)
            src_g = jnp.sum(a * oh_in_lo, axis=0, keepdims=True)  # [1, w]
            contrib = v * src_g

            # scatter: RHS rows [0,S_LO) carry onehot*c_hi, [S_LO,2*S_LO)
            # carry onehot*c_lo -> one [S_HI, 2*S_LO] product; the two lane
            # halves fold with an exact VPU add. The RHS is built from ONE
            # [S_LO, w] one-hot compare + a sublane concat (round 2 used a
            # [2*S_LO, w] compare + arithmetic 0/1 blend — twice the VPU
            # compare work for the same matrix).
            c1, c2 = _split(contrib)
            oh_out_hi = _expand(oh, s_hi, width, jnp.bfloat16)
            oh_out_lo = _expand(ol, s_lo, width, jnp.bfloat16)
            rhs = jnp.concatenate(
                [oh_out_lo * c1, oh_out_lo * c2], axis=0
            )  # [2*S_LO, w]
            update_wide = jax.lax.dot_general(
                oh_out_hi, rhs, dims_out,
                preferred_element_type=jnp.float32,
            )  # [S_HI, 2*S_LO]
            return update_wide[:, :s_lo] + update_wide[:, s_lo:]
        elif mxu == "bf16x2":
            # One-hot matrices are 0/1 — EXACT in bf16. Only the data
            # operand carries mantissa, so instead of Precision.HIGHEST (6
            # bf16 MXU passes for f32 x f32) we split the data side into
            # two bf16 terms (hi + lo, ~16 mantissa bits, ~1e-5 rel error)
            # and run 2 single-pass bf16 matmuls — 3x the MXU throughput
            # at GLM-sufficient precision.
            oh_in_hi = _expand(ih, s_hi, width, jnp.bfloat16)  # [S_HI, w]
            oh_in_lo = _expand(il, s_lo, width, jnp.float32)  # [S_LO, w]

            # gather: src_g[p] = src2d[ih[p], il[p]]
            s1, s2 = _split(src_ref[0])
            a = jax.lax.dot_general(
                s1, oh_in_hi, dims_in, preferred_element_type=jnp.float32
            ) + jax.lax.dot_general(
                s2, oh_in_hi, dims_in, preferred_element_type=jnp.float32
            )  # [S_LO, w]
            src_g = jnp.sum(a * oh_in_lo, axis=0, keepdims=True)  # [1, w]
            contrib = v * src_g  # [1, w]

            oh_out_hi = _expand(oh, s_hi, width, jnp.bfloat16)
            oh_out_lo = _expand(ol, s_lo, width, jnp.bfloat16)
            # A @ B^T via lane/entry contraction. oh_out_lo is 0/1 and the
            # contrib terms are already bf16, so each product is exact.
            c1, c2 = _split(contrib)
            return jax.lax.dot_general(
                oh_out_hi, oh_out_lo * c1, dims_out,
                preferred_element_type=jnp.float32,
            ) + jax.lax.dot_general(
                oh_out_hi, oh_out_lo * c2, dims_out,
                preferred_element_type=jnp.float32,
            )  # [S_HI, S_LO]
        else:  # "highest": full f32 emulation, ~3x slower, ~1e-7 rel error
            oh_in_hi = _expand(ih, s_hi, width, jnp.float32)
            oh_in_lo = _expand(il, s_lo, width, jnp.float32)
            a = jax.lax.dot_general(
                src_ref[0], oh_in_hi, dims_in,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            src_g = jnp.sum(a * oh_in_lo, axis=0, keepdims=True)
            contrib = v * src_g
            oh_out_hi = _expand(oh, s_hi, width, jnp.float32)
            oh_out_lo = _expand(ol, s_lo, width, jnp.float32)
            return jax.lax.dot_general(
                oh_out_hi, oh_out_lo * contrib, dims_out,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )

    # `split` independent chains over lane slices of the chunk: no data
    # dependency between them, so the scheduler can overlap one chain's
    # VPU one-hot build with another's MXU passes.
    w = L // split
    update = _chain(
        ip_full[:, :w], op_full[:, :w], v_full[:, :w], w
    )
    for h in range(1, split):
        update = update + _chain(
            ip_full[:, h * w:(h + 1) * w],
            op_full[:, h * w:(h + 1) * w],
            v_full[:, h * w:(h + 1) * w],
            w,
        )

    @pl.when(step_init_ref[g] == 1)
    def _():
        out_ref[0] = update

    @pl.when(step_init_ref[g] != 1)
    def _():
        out_ref[0] = out_ref[0] + update


# Mosaic compiler-params experiment hook (None = defaults). Sweeps set
# this to probe e.g. dimension_semantics / vmem_limit_bytes; production
# leaves it None.
_COMPILER_PARAMS = None


def _grid_bilinear_pass(
    sched: _Schedule,
    src_bank: Array,  # [G, num_in_blocks, S_HI, S_LO]
    num_out_blocks: int,
    params: TileParams,
    vals: Optional[Array] = None,
) -> Array:
    """Grid-batched schedule application: ONE fused data pass serves every
    grid member (the λ-grid batching lever, ISSUE 5 / Podracer-style
    batched while_loops, arxiv 2104.06272).

    The per-member bilinear kernel computes out = A @ src where A is the
    sparse operator the schedule encodes; with a coefficient BANK the
    (n×d) sparse matvec becomes the (n×d) @ (d×G) blocked product. Here
    that product is one flat gather + one segment scatter-add over the
    schedule's flat (block*window + pos) coordinates with the grid axis
    riding the trailing (lane) dimension — every entry's schedule lookup,
    the dominant traffic, is paid once for the whole grid instead of once
    per λ. Flat coordinates must fit int32 (same bound the spill router
    enforces); the grid path's memory-budget gate keeps d_pad far below
    that.

    Returns [G, num_out_blocks, S_HI, S_LO]; spill entries are applied by
    the caller's ``apply_spill`` (take + scatter-add, which batches
    natively under vmap).
    """
    win = params.window
    S = sched.num_steps
    G = src_bank.shape[0]
    flat_in = (
        sched.step_in[:, None] * win + sched.in_pos[:S]
    ).reshape(-1)
    flat_out = (
        sched.step_out[:, None] * win + sched.out_pos[:S]
    ).reshape(-1)
    v = (sched.vals if vals is None else vals)[:S].reshape(-1)
    src_flat = src_bank.reshape(G, -1).T  # [num_in_blocks * win, G]
    contrib = v[:, None] * jnp.take(src_flat, flat_in, axis=0)
    out = jnp.zeros((num_out_blocks * win, G), src_flat.dtype)
    out = out.at[flat_out].add(contrib)
    return out.T.reshape(G, num_out_blocks, params.s_hi, params.s_lo)


def _bilinear_pass_auto(
    sched: _Schedule,
    src: Array,
    num_out_blocks: int,
    params: TileParams,
    *,
    vals: Optional[Array] = None,
    interpret: bool = False,
    mxu: str = "bf16x2w",
    onehot: str = "compare",
) -> Array:
    """:func:`_run_bilinear_pass` that stays ``jax.vmap``-able.

    Unbatched calls lower to the Pallas kernel unchanged. Under vmap
    (the batched λ-grid path vmaps the optimizers over a coefficient
    bank) a ``custom_vmap`` rule swaps in :func:`_grid_bilinear_pass`:
    one fused pass for the whole bank instead of per-member kernel
    launches — pallas_call's scalar-prefetch grid has no batching rule,
    and even if it did, G separate passes is exactly what the grid path
    exists to avoid. Only the ``src`` operand may be batched; the
    schedule and entry values are shared across the grid by construction.
    """
    import jax.custom_batching

    @jax.custom_batching.custom_vmap
    def run(sched_, src_, vals_):
        return _run_bilinear_pass(
            sched_, src_, num_out_blocks, params, vals=vals_,
            interpret=interpret, mxu=mxu, onehot=onehot,
        )

    @run.def_vmap
    def _rule(axis_size, in_batched, sched_, src_, vals_):
        sched_b, src_b, vals_b = in_batched
        if any(jax.tree_util.tree_leaves(sched_b)) or vals_b:
            raise NotImplementedError(
                "grid batching supports a batched coefficient/row operand "
                "only; the tile schedule is shared across the grid"
            )
        if not src_b:
            out = run(sched_, src_, vals_)
            return (
                jnp.broadcast_to(out, (axis_size,) + out.shape), True
            )
        return (
            _grid_bilinear_pass(
                sched_, src_, num_out_blocks, params, vals=vals_
            ),
            True,
        )

    return run(sched, src, sched.vals if vals is None else vals)


def _run_bilinear_pass(
    sched: _Schedule,
    src: Array,  # [num_in_blocks, S_HI, S_LO]
    num_out_blocks: int,
    params: TileParams,
    *,
    vals: Optional[Array] = None,
    interpret: bool = False,
    mxu: str = "bf16x2w",
    onehot: str = "compare",
) -> Array:
    """-> [num_out_blocks, S_HI, S_LO] accumulated output."""
    G = sched.num_steps
    L = params.chunk
    if L % max(params.split, 1) != 0:
        # a non-dividing split would silently drop the remainder lanes
        raise ValueError(
            f"chunk {L} is not divisible by split {params.split}"
        )
    entry_spec = pl.BlockSpec((8, L), lambda g, so, si, st: (g // 8, 0))
    src_spec = pl.BlockSpec(
        (1, params.s_hi, params.s_lo), lambda g, so, si, st: (si[g], 0, 0)
    )
    out_spec = pl.BlockSpec(
        (1, params.s_hi, params.s_lo), lambda g, so, si, st: (so[g], 0, 0)
    )
    kernel = partial(
        _bilinear_pass_kernel,
        s_hi=params.s_hi,
        s_lo=params.s_lo,
        chunk=L,
        mxu=mxu,
        split=params.split,
        onehot=onehot,
    )
    in_specs = [entry_spec, entry_spec, entry_spec, src_spec]
    operands = (
        sched.step_out, sched.step_in, sched.step_init,
        sched.in_pos, sched.out_pos,
        sched.vals if vals is None else vals,
        src,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(G,),
        in_specs=in_specs,
        out_specs=out_spec,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (num_out_blocks, params.s_hi, params.s_lo), jnp.float32
        ),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS,
    )(*operands)
    return out


@dataclass(frozen=True)
class TiledGLMObjective:
    """GLMObjective-compatible fused objective over TiledSparseBatch data.

    Same math and signature contract as
    photon_ml_tpu.ops.objective.GLMObjective (sum-weighted loss, L2 added
    once, lazy shift/factor normalization, psum over ``axis_name`` if set),
    with the margins/gradient passes running the tiled Pallas kernels
    instead of gather/scatter. Methods take the batch as an argument (pass
    it through jit — it is a pytree).
    """

    loss: object
    dim: int  # real (unpadded) coefficient dimension
    norm: NormalizationContext = None
    axis_name: Optional[str] = None
    interpret: bool = False
    # "bf16x2w" (default): hi+lo bf16 data split with both half-width
    # matmuls fused into one full-width MXU tile (~1e-5 rel err, fastest);
    # "bf16x2": the two-matmul variant; "highest" (~1e-7, 2.5x slower).
    mxu: str = "bf16x2w"
    # Positional-expansion algorithm: "compare" (sublane-iota equality,
    # the round-2 build) or "mxu" (squared-distance matmul + relu — the
    # round-3 "pack the one-hot build onto the MXU" lever; exact 0/1
    # output either way, see _bilinear_pass_kernel._expand).
    onehot: str = "compare"

    def __post_init__(self):
        if self.norm is None:
            object.__setattr__(self, "norm", identity_context())
        if self.mxu not in ("bf16x2w", "bf16x2", "highest"):
            # a typo must not silently fall through to the "highest"
            # branch (2.5x slower, different numerics)
            raise ValueError(f"unknown mxu variant {self.mxu!r}")
        if self.onehot not in ("compare", "mxu"):
            raise ValueError(f"unknown onehot variant {self.onehot!r}")

    def _psum(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.psum(x, self.axis_name)

    def _pad(self, w: Array, batch: TiledSparseBatch) -> Array:
        if w.shape[0] == batch.dim:
            return w
        return jnp.zeros((batch.dim,), w.dtype).at[: w.shape[0]].set(w)

    def _z_pass(self, w_padded: Array, batch: TiledSparseBatch) -> Array:
        """raw row-sums [num_rows] of the tiled bilinear product."""
        b = batch
        p = b.params
        w2d = w_padded.reshape((b.num_feat_blocks, p.s_hi, p.s_lo))
        raw = _bilinear_pass_auto(
            b.z_sched, w2d, b.num_row_blocks, p,
            interpret=self.interpret, mxu=self.mxu, onehot=self.onehot,
        ).reshape(-1)
        return b.z_sched.apply_spill(raw, w_padded)

    def _grad_pass(
        self, c_rows: Array, batch: TiledSparseBatch,
        vals: Optional[Array] = None,
        spill_vals: Optional[Array] = None,
    ) -> Array:
        b = batch
        p = b.params
        c2d = c_rows.reshape((b.num_row_blocks, p.s_hi, p.s_lo))
        g = _bilinear_pass_auto(
            b.g_sched, c2d, b.num_feat_blocks, p,
            vals=vals, interpret=self.interpret, mxu=self.mxu, onehot=self.onehot,
        ).reshape(-1)
        return b.g_sched.apply_spill(g, c_rows, vals=spill_vals)

    # -- margins -----------------------------------------------------------

    def margins(self, coef: Array, batch: TiledSparseBatch) -> Array:
        """z_i = x_eff_i . w_eff + offset_i in padded row space."""
        w_eff = self.norm.effective_coefficients(coef)
        raw = self._z_pass(self._pad(w_eff, batch), batch)
        return raw - self.norm.shift_dot(w_eff) + batch.offsets

    # -- value / gradient --------------------------------------------------

    def value(self, coef: Array, batch: TiledSparseBatch, l2_weight=0.0) -> Array:
        z = self.margins(coef, batch)
        val = jnp.sum(batch.weights * self.loss.value(z, batch.labels))
        val = self._psum(val)
        return val + 0.5 * l2_weight * jnp.dot(coef, coef)

    def value_and_gradient(
        self, coef: Array, batch: TiledSparseBatch, l2_weight=0.0
    ) -> Tuple[Array, Array]:
        d_in = coef.shape[0]
        z = self.margins(coef, batch)
        lv = self.loss.value(z, batch.labels)
        ld = self.loss.d1(z, batch.labels)
        c = batch.weights * ld
        value_sum = jnp.sum(batch.weights * lv)
        vector_sum = self._grad_pass(c, batch)[:d_in]
        prefactor_sum = jnp.sum(c)
        value_sum, vector_sum, prefactor_sum = self._psum(
            (value_sum, vector_sum, prefactor_sum)
        )
        grad = self.norm.unshift_gradient(vector_sum, prefactor_sum)
        value = value_sum + 0.5 * l2_weight * jnp.dot(coef, coef)
        return value, grad + l2_weight * coef

    def gradient(self, coef: Array, batch: TiledSparseBatch, l2_weight=0.0) -> Array:
        return self.value_and_gradient(coef, batch, l2_weight)[1]

    # -- second order ------------------------------------------------------

    def hessian_vector(
        self, coef: Array, direction: Array, batch: TiledSparseBatch,
        l2_weight=0.0,
    ) -> Array:
        d_in = coef.shape[0]
        w_eff = self.norm.effective_coefficients(coef)
        d_eff = self.norm.effective_coefficients(direction)
        z = (
            self._z_pass(self._pad(w_eff, batch), batch)
            - self.norm.shift_dot(w_eff) + batch.offsets
        )
        zd = (
            self._z_pass(self._pad(d_eff, batch), batch)
            - self.norm.shift_dot(d_eff)
        )
        c = batch.weights * self.loss.d2(z, batch.labels) * zd
        vector_sum = self._grad_pass(c, batch)[:d_in]
        prefactor_sum = jnp.sum(c)
        vector_sum, prefactor_sum = self._psum((vector_sum, prefactor_sum))
        hv = self.norm.unshift_gradient(vector_sum, prefactor_sum)
        return hv + l2_weight * direction

    def hessian_diagonal(
        self, coef: Array, batch: TiledSparseBatch, l2_weight=0.0
    ) -> Array:
        d_in = coef.shape[0]
        z = self.margins(coef, batch)
        c = batch.weights * self.loss.d2(z, batch.labels)
        s2 = self._grad_pass(
            c, batch, vals=batch.g_vals_sq,
            spill_vals=batch.g_sched.spill_vals**2,
        )[:d_in]
        if self.norm.shift is not None:
            # shifted space needs S1 = sum c x and S0 = sum c as well
            s1 = self._grad_pass(c, batch)[:d_in]
            s0 = jnp.sum(c)
            s0, s1, s2 = self._psum((s0, s1, s2))
            diag = s2 - 2.0 * self.norm.shift * s1 + (self.norm.shift**2) * s0
        else:
            diag = self._psum(s2)
        if self.norm.factor is not None:
            diag = diag * self.norm.factor**2
        return diag + l2_weight

    # -- convenience -------------------------------------------------------

    def with_axis(self, axis_name: Optional[str]) -> "TiledGLMObjective":
        return TiledGLMObjective(
            self.loss, self.dim, self.norm, axis_name, self.interpret,
            self.mxu, self.onehot,
        )


# A pytree: the normalization vectors are leaves, everything else static
# aux — so the objective passes straight through jit as an ARGUMENT and
# equal-structure objectives share one persistent compile cache (the
# shared module-level jits in io/streaming.py ride on this).
jax.tree_util.register_dataclass(
    TiledGLMObjective,
    data_fields=["norm"],
    meta_fields=["loss", "dim", "axis_name", "interpret", "mxu", "onehot"],
)
