"""Tiled sparse GLM kernels: gather/scatter-free margins and gradients.

WHY: on TPU, XLA lowers random gather/scatter to ~7ns/element serial loops
(measured — PERF_NOTES.md), so the reference's two hot loops (margin
accumulation and gradient axpy, ValueAndGradientAggregator.scala:133-154)
are 100x slower than the hardware's streaming rate. This module replaces
both with a STATIC TILED layout + two Pallas kernels whose only per-entry
operations are VPU compares and MXU matmuls:

- Entries are binned into (row-window x feature-window) tiles; windows are
  R_WIN = F_WIN = S_HI * S_LO positions wide.
- A window-local index idx in [0, WIN) decomposes as hi*S_LO + lo; the
  gather w[idx] becomes the bilinear form onehot_hi @ w2d . onehot_lo with
  w2d = w_window reshaped [S_HI, S_LO] — ONE small matmul per chunk plus
  elementwise masks, no scatter/gather anywhere.
- The z-pass streams chunks sorted by row-block (output revisiting is
  monotone -> pallas accumulates the z window in VMEM); the grad-pass
  streams the same entries sorted by feature-block.

The schedule (tile assignment, chunking, one-hot index splits) is computed
ONCE on host per dataset — full-batch GLM training re-evaluates the same
static structure hundreds of times, so the build cost amortizes to zero.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jnp.ndarray


@dataclass(frozen=True)
class TileParams:
    s_hi: int = 128
    s_lo: int = 64
    chunk: int = 1024  # entries per grid step

    @property
    def window(self) -> int:
        return self.s_hi * self.s_lo


@dataclass
class _Schedule:
    """One pass's static schedule: chunked entries sorted by output block."""

    step_out: np.ndarray  # [G] output block id per step
    step_in: np.ndarray  # [G] input-window block id per step
    step_init: np.ndarray  # [G] 1 iff first step of its output block
    out_hi: np.ndarray  # [G, L] one-hot hi index into the OUTPUT window
    out_lo: np.ndarray  # [G, L]
    in_hi: np.ndarray  # [G, L] one-hot hi index into the INPUT window
    in_lo: np.ndarray  # [G, L]
    vals: np.ndarray  # [G, L] entry values (0 for padding slots)

    @property
    def num_steps(self) -> int:
        return self.step_out.shape[0]


def _build_schedule(
    rows: np.ndarray,
    feats: np.ndarray,
    vals: np.ndarray,
    *,
    params: TileParams,
    sort_by_feature_block: bool,
) -> _Schedule:
    win = params.window
    L = params.chunk
    rb = rows // win
    fb = feats // win
    if sort_by_feature_block:
        order = np.lexsort((rb, fb))
        out_blocks, in_blocks = fb[order], rb[order]
        out_pos, in_pos = feats[order] % win, rows[order] % win
    else:
        order = np.lexsort((fb, rb))
        out_blocks, in_blocks = rb[order], fb[order]
        out_pos, in_pos = rows[order] % win, feats[order] % win
    v = vals[order]

    # tile boundaries: chunk entries so no chunk crosses a tile boundary
    tile_key = out_blocks.astype(np.int64) * (in_blocks.max() + 1) + in_blocks
    boundaries = np.nonzero(
        np.concatenate([[True], tile_key[1:] != tile_key[:-1]])
    )[0]
    tile_starts = boundaries
    tile_ends = np.concatenate([boundaries[1:], [len(v)]])

    steps = []
    for s, e in zip(tile_starts, tile_ends):
        for cs in range(s, e, L):
            steps.append((s, cs, min(cs + L, e)))
    G = len(steps)
    step_out = np.zeros(G, np.int32)
    step_in = np.zeros(G, np.int32)
    step_init = np.zeros(G, np.int32)
    o_hi = np.zeros((G, L), np.int32)
    o_lo = np.zeros((G, L), np.int32)
    i_hi = np.zeros((G, L), np.int32)
    i_lo = np.zeros((G, L), np.int32)
    sv = np.zeros((G, L), np.float32)
    prev_out = -1
    for g, (tile_start, cs, ce) in enumerate(steps):
        m = ce - cs
        step_out[g] = out_blocks[cs]
        step_in[g] = in_blocks[cs]
        step_init[g] = 1 if out_blocks[cs] != prev_out else 0
        prev_out = out_blocks[cs]
        o_hi[g, :m] = out_pos[cs:ce] // params.s_lo
        o_lo[g, :m] = out_pos[cs:ce] % params.s_lo
        i_hi[g, :m] = in_pos[cs:ce] // params.s_lo
        i_lo[g, :m] = in_pos[cs:ce] % params.s_lo
        sv[g, :m] = v[cs:ce]
    return _Schedule(step_out, step_in, step_init, o_hi, o_lo, i_hi, i_lo, sv)


@dataclass
class TiledSparseBatch:
    """Statically tiled sparse batch (replaces SparseBatch on the hot path).

    Row space is padded to num_row_blocks * window; feature space to
    num_feat_blocks * window. ``labels/offsets/weights`` live in padded row
    space (weight 0 padding).
    """

    params: TileParams
    num_rows: int  # padded
    dim: int  # padded
    num_real_rows: int
    real_dim: int
    z_sched: _Schedule
    g_sched: _Schedule
    g_vals_sq: np.ndarray  # [G2, L] squared values for hessian_diagonal
    labels: Array
    offsets: Array
    weights: Array

    @property
    def num_row_blocks(self) -> int:
        return self.num_rows // self.params.window

    @property
    def num_feat_blocks(self) -> int:
        return self.dim // self.params.window


def build_tiled_batch(
    rows: np.ndarray,
    feats: np.ndarray,
    vals: np.ndarray,
    labels: np.ndarray,
    offsets: np.ndarray,
    weights: np.ndarray,
    dim: int,
    *,
    params: TileParams = TileParams(),
) -> TiledSparseBatch:
    """COO triples + per-row arrays -> tiled batch. Entries with zero value
    are dropped (they contribute nothing)."""
    nz = vals != 0
    rows, feats, vals = rows[nz], feats[nz], vals[nz]
    win = params.window
    n = labels.shape[0]
    n_pad = max(((n + win - 1) // win) * win, win)
    d_pad = max(((dim + win - 1) // win) * win, win)

    z_sched = _build_schedule(
        rows, feats, vals, params=params, sort_by_feature_block=False
    )
    g_sched = _build_schedule(
        rows, feats, vals, params=params, sort_by_feature_block=True
    )
    lab = np.zeros(n_pad, np.float32)
    lab[:n] = labels
    off = np.zeros(n_pad, np.float32)
    off[:n] = offsets
    wgt = np.zeros(n_pad, np.float32)
    wgt[:n] = weights
    return TiledSparseBatch(
        params=params,
        num_rows=n_pad,
        dim=d_pad,
        num_real_rows=n,
        real_dim=dim,
        z_sched=z_sched,
        g_sched=g_sched,
        g_vals_sq=g_sched.vals**2,
        labels=jnp.asarray(lab),
        offsets=jnp.asarray(off),
        weights=jnp.asarray(wgt),
    )


def tiled_batch_from_sparse(batch, dim: int, *, params: TileParams = TileParams()):
    """Convenience: SparseBatch (padded ELL) -> TiledSparseBatch."""
    indices = np.asarray(batch.indices)
    values = np.asarray(batch.values)
    weights = np.asarray(batch.weights)
    n, k = indices.shape
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    feats = indices.reshape(-1).astype(np.int64)
    vals = values.reshape(-1).astype(np.float32)
    # rows with weight 0 are padding — drop their entries
    vals = np.where(np.repeat(weights > 0, k), vals, 0.0)
    return build_tiled_batch(
        rows, feats, vals,
        np.asarray(batch.labels), np.asarray(batch.offsets), weights,
        dim, params=params,
    )


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _bilinear_pass_kernel(
    # scalar prefetch
    step_out_ref, step_in_ref, step_init_ref,
    # per-step entry blocks [1, L]
    in_hi_ref, in_lo_ref, out_hi_ref, out_lo_ref, vals_ref,
    # gathered-from window [1, S_HI, S_LO] (w2d for z-pass, c2d for grad)
    src_ref,
    # output window accumulator [1, S_HI, S_LO]
    out_ref,
    *,
    s_hi: int,
    s_lo: int,
    chunk: int,
):
    """One grid step: expand src at (in_hi, in_lo), multiply by vals,
    bilinear-scatter into the (out_hi, out_lo) output window."""
    g = pl.program_id(0)
    L = chunk
    # entry blocks are stored [G, 8, L//8] to satisfy TPU (8, 128) tiling
    ih = in_hi_ref[0].reshape(L)
    il = in_lo_ref[0].reshape(L)
    oh = out_hi_ref[0].reshape(L)
    ol = out_lo_ref[0].reshape(L)
    v = vals_ref[0].reshape(L)

    hi_iota = jax.lax.broadcasted_iota(jnp.int32, (L, s_hi), 1)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (L, s_lo), 1)
    oh_in_hi = (ih[:, None] == hi_iota).astype(jnp.float32)  # [L, S_HI]
    oh_in_lo = (il[:, None] == lo_iota).astype(jnp.float32)  # [L, S_LO]

    # gather: src_g[p] = src2d[ih[p], il[p]]
    a = jnp.dot(oh_in_hi, src_ref[0], preferred_element_type=jnp.float32)
    src_g = jnp.sum(a * oh_in_lo, axis=1)  # [L]
    contrib = v * src_g

    oh_out_hi = (oh[:, None] == hi_iota).astype(jnp.float32)
    oh_out_lo = (ol[:, None] == lo_iota).astype(jnp.float32)
    update = jnp.dot(
        (oh_out_hi * contrib[:, None]).T, oh_out_lo,
        preferred_element_type=jnp.float32,
    )  # [S_HI, S_LO]

    @pl.when(step_init_ref[g] == 1)
    def _():
        out_ref[0] = update

    @pl.when(step_init_ref[g] != 1)
    def _():
        out_ref[0] = out_ref[0] + update


def _run_bilinear_pass(
    sched: _Schedule,
    src: Array,  # [num_in_blocks, S_HI, S_LO]
    num_out_blocks: int,
    params: TileParams,
    *,
    vals: Optional[Array] = None,
    interpret: bool = False,
) -> Array:
    """-> [num_out_blocks, S_HI, S_LO] accumulated output."""
    G = sched.num_steps
    L = params.chunk
    kernel = partial(
        _bilinear_pass_kernel,
        s_hi=params.s_hi,
        s_lo=params.s_lo,
        chunk=L,
    )
    assert L % 1024 == 0 or L in (8, 32), f"chunk {L} must tile (8,128)"
    eb = (1, 8, L // 8) if L % 1024 == 0 else (1, 1, L)
    def eshape(a):
        return jnp.asarray(a).reshape((G,) + eb[1:])
    entry_spec = pl.BlockSpec(eb, lambda g, so, si, st: (g, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(G,),
        in_specs=[
            entry_spec,  # in_hi
            entry_spec,  # in_lo
            entry_spec,  # out_hi
            entry_spec,  # out_lo
            entry_spec,  # vals
            pl.BlockSpec(
                (1, params.s_hi, params.s_lo),
                lambda g, so, si, st: (si[g], 0, 0),
            ),  # src window
        ],
        out_specs=pl.BlockSpec(
            (1, params.s_hi, params.s_lo),
            lambda g, so, si, st: (so[g], 0, 0),
        ),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (num_out_blocks, params.s_hi, params.s_lo), jnp.float32
        ),
        interpret=interpret,
    )(
        jnp.asarray(sched.step_out),
        jnp.asarray(sched.step_in),
        jnp.asarray(sched.step_init),
        eshape(sched.in_hi),
        eshape(sched.in_lo),
        eshape(sched.out_hi),
        eshape(sched.out_lo),
        eshape(sched.vals if vals is None else vals),
        src,
    )
    return out


class TiledGLMObjective:
    """GLMObjective-compatible fused objective over a TiledSparseBatch.

    Same math contract as photon_ml_tpu.ops.objective.GLMObjective
    (sum-weighted loss, L2 added once, psum over ``axis_name`` if set), with
    the margins/gradient passes running the tiled Pallas kernels instead of
    gather/scatter.
    """

    def __init__(self, loss, batch: TiledSparseBatch, *, axis_name=None,
                 interpret: bool = False):
        self.loss = loss
        self.batch = batch
        self.axis_name = axis_name
        self.interpret = interpret
        p = batch.params
        self._w_shape = (batch.num_feat_blocks, p.s_hi, p.s_lo)
        self._c_shape = (batch.num_row_blocks, p.s_hi, p.s_lo)

    def _psum(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.psum(x, self.axis_name)

    def _margins(self, w_padded: Array) -> Array:
        """z [num_rows] = tiled row-sums + offsets."""
        b = self.batch
        w2d = w_padded.reshape(self._w_shape)
        z = _run_bilinear_pass(
            b.z_sched, w2d, b.num_row_blocks, b.params,
            interpret=self.interpret,
        ).reshape(-1)
        return z + b.offsets

    def _grad_pass(self, c_rows: Array, vals: Optional[Array] = None) -> Array:
        b = self.batch
        c2d = c_rows.reshape(self._c_shape)
        g = _run_bilinear_pass(
            b.g_sched, c2d, b.num_feat_blocks, b.params,
            vals=vals, interpret=self.interpret,
        ).reshape(-1)
        return g

    def _pad_w(self, w: Array) -> Array:
        b = self.batch
        if w.shape[0] == b.dim:
            return w
        return jnp.zeros((b.dim,), w.dtype).at[: w.shape[0]].set(w)

    def value_and_gradient(self, w: Array, l2_weight=0.0) -> Tuple[Array, Array]:
        b = self.batch
        d_in = w.shape[0]
        wp = self._pad_w(w)
        z = self._margins(wp)
        lv = self.loss.value(z, b.labels)
        ld = self.loss.d1(z, b.labels)
        c = b.weights * ld
        value = self._psum(jnp.sum(b.weights * lv))
        grad = self._psum(self._grad_pass(c))[:d_in]
        value = value + 0.5 * l2_weight * jnp.vdot(w, w)
        return value, grad + l2_weight * w

    def value(self, w: Array, l2_weight=0.0) -> Array:
        b = self.batch
        z = self._margins(self._pad_w(w))
        value = self._psum(jnp.sum(b.weights * self.loss.value(z, b.labels)))
        return value + 0.5 * l2_weight * jnp.vdot(w, w)

    def hessian_vector(self, w: Array, direction: Array, l2_weight=0.0) -> Array:
        b = self.batch
        d_in = w.shape[0]
        z = self._margins(self._pad_w(w))
        zd = self._margins(self._pad_w(direction)) - b.offsets
        c = b.weights * self.loss.d2(z, b.labels) * zd
        hv = self._psum(self._grad_pass(c))[:d_in]
        return hv + l2_weight * direction

    def hessian_diagonal(self, w: Array, l2_weight=0.0) -> Array:
        b = self.batch
        d_in = w.shape[0]
        z = self._margins(self._pad_w(w))
        c = b.weights * self.loss.d2(z, b.labels)
        diag = self._psum(
            self._grad_pass(c, vals=jnp.asarray(b.g_vals_sq))
        )[:d_in]
        return diag + l2_weight
