"""Driver-side diagnostics orchestration + HTML report.

Reference: photon-ml Driver.scala:525-552 (diagnose stage: per-model
diagnostics over the lambda grid) and :618-638 (model-diagnostic HTML
report written to <output>/model-diagnostics).
"""

from __future__ import annotations

import os

import numpy as np

from photon_ml_tpu.diagnostics.diagnostics import (
    bootstrap_training_diagnostic,
    feature_importance_diagnostic,
    fitting_diagnostic,
    hosmer_lemeshow_diagnostic,
    kendall_tau_diagnostic,
)
from photon_ml_tpu.diagnostics.reporting import (
    Chapter,
    Document,
    LinePlot,
    Section,
    Table,
    Text,
    write_html_report,
    write_text_report,
)
from photon_ml_tpu.task import TaskType


def run_glm_diagnostics(driver) -> None:
    """Diagnose the trained lambda grid and write the HTML report.
    ``driver`` is a GLMDriver after train() (and validate(), if a
    validation dir was configured)."""
    from photon_ml_tpu.cli.glm_driver import DiagnosticMode

    p = driver.params
    data = driver._data
    summary = driver._summary
    # Streaming runs carry no in-memory train batch; row-level sections
    # (calibration/Kendall fallback, bootstrap, fitting curves) run on
    # the bounded uniform reservoir sample collected during the
    # streamed-summary pass instead — the bounded-memory stand-in for
    # the reference's RDD-wide diagnose passes (Driver.scala:525-552).
    batch = (
        data.batch
        if data.batch is not None
        else getattr(driver, "_stream_sample", None)
    )
    if batch is None:
        raise ValueError(
            "diagnostics need an in-memory batch or a streamed reservoir "
            "sample; run preprocess() with a diagnostic mode set"
        )
    vdata = getattr(driver, "_validation_data", None)
    doc = Document(title=f"Photon ML TPU diagnostics — {p.job_name}")

    # -- per-lambda model diagnostics -------------------------------------
    for lam, model in driver.models.items():
        chapter = Chapter(title=f"Model lambda={lam}")

        imp = feature_importance_diagnostic(
            model,
            np.asarray(summary.mean),
            np.asarray(summary.variance),
        )
        def feature_name(i: int) -> str:
            key = data.index_map.get_feature_name(i)
            return key.replace("\t", " / ") if key else str(i)
        chapter.sections.append(
            Section(
                "Feature importance",
                [
                    Table(
                        ["feature", "|w * E[x]|"],
                        [[feature_name(i), f"{v:.5g}"] for i, v in imp.expected_magnitude[:10]],
                        caption="expected-magnitude importance",
                    ),
                    Table(
                        ["feature", "|w| * sd(x)"],
                        [[feature_name(i), f"{v:.5g}"] for i, v in imp.variance_magnitude[:10]],
                        caption="variance importance",
                    ),
                ],
            )
        )

        eval_batch = vdata.batch if vdata is not None else batch
        if p.task == TaskType.LOGISTIC_REGRESSION:
            hl = hosmer_lemeshow_diagnostic(model, eval_batch)
            chapter.sections.append(
                Section(
                    "Hosmer-Lemeshow calibration",
                    [
                        Text(
                            f"chi^2 = {hl.chi_square:.4g} with "
                            f"{hl.degrees_of_freedom} dof, p = {hl.p_value:.4g}"
                        ),
                        Table(
                            ["bin count", "observed+", "expected+", "mean p"],
                            [
                                [f"{b['count']:.0f}", f"{b['observed_pos']:.1f}",
                                 f"{b['expected_pos']:.1f}", f"{b['mean_prob']:.3f}"]
                                for b in hl.bins
                            ],
                        ),
                        LinePlot(
                            x=[b["mean_prob"] for b in hl.bins],
                            series=[
                                ("observed rate",
                                 [b["observed_pos"] / max(b["count"], 1e-9) for b in hl.bins]),
                                ("expected rate",
                                 [b["expected_pos"] / max(b["count"], 1e-9) for b in hl.bins]),
                            ],
                            title="calibration", x_label="predicted", y_label="rate",
                        ),
                    ],
                )
            )

        kt = kendall_tau_diagnostic(model, eval_batch)
        chapter.sections.append(
            Section(
                "Prediction-error independence (Kendall tau)",
                [Text(f"tau = {kt.tau:.4g}, p = {kt.p_value:.4g}: {kt.message}")],
            )
        )
        doc.chapters.append(chapter)

    # -- bootstrap + fitting on the selected model ------------------------
    best_lambda = driver.best_lambda if driver.best_lambda is not None else (
        sorted(driver.models)[0]
    )

    def train_fn(b):
        from photon_ml_tpu.training import train_generalized_linear_model

        models, _ = train_generalized_linear_model(
            b, p.task, data.num_features,
            optimizer_type=p.optimizer_type,
            regularization_type=p.regularization_type,
            regularization_weights=[best_lambda],
            elastic_net_alpha=p.elastic_net_alpha,
            max_iter=p.max_num_iterations,
            tolerance=p.tolerance,
            normalization=driver._norm,
            intercept_index=data.intercept_index,
        )
        return models[best_lambda]

    def metrics_fn(model, b=None):
        return driver._metrics_for(model, b if b is not None else batch)

    boot = bootstrap_training_diagnostic(
        batch, train_fn, lambda m: metrics_fn(m), num_samples=5
    )
    boot_chapter = Chapter("Bootstrap analysis")
    boot_chapter.sections.append(
        Section(
            f"Bootstrap ({boot.num_samples} resamples, lambda={best_lambda})",
            [
                Table(
                    ["metric", "mean", "std"],
                    [[k, f"{m:.5g}", f"{s:.3g}"]
                     for k, (m, s) in boot.metrics_distribution.items()],
                ),
                Table(
                    ["feature", "coef mean", "coef std"],
                    [[data.index_map.get_feature_name(i) or str(i),
                      f"{m:.5g}", f"{s:.3g}"]
                     for i, m, s in boot.important_features],
                    caption="top coefficients across bootstrap replicates",
                ),
            ],
        )
    )
    doc.chapters.append(boot_chapter)

    if vdata is not None:
        fit = fitting_diagnostic(
            batch, vdata.batch, train_fn, lambda m, b: metrics_fn(m, b),
            num_portions=5,
        )
        metric0 = next(iter(fit.train_metrics))
        doc.chapters.append(
            Chapter(
                "Fitting analysis",
                [
                    Section(
                        "Learning curves",
                        [
                            Text(fit.message),
                            LinePlot(
                                x=fit.portions,
                                series=[
                                    (f"train {metric0}", fit.train_metrics[metric0]),
                                    (f"test {metric0}", fit.test_metrics[metric0]),
                                ],
                                title=f"{metric0} vs training portion",
                                x_label="portion", y_label=metric0,
                            ),
                        ],
                    )
                ],
            )
        )

    out = os.path.join(p.output_dir, "model-diagnostics", "report.html")
    write_html_report(doc, out)
    # text render strategy alongside (reference reporting/text/**)
    write_text_report(
        doc, os.path.join(p.output_dir, "model-diagnostics", "report.txt")
    )
    driver.logger.info("diagnostics report written to %s", out)
