"""Model diagnostics + HTML reporting."""

from photon_ml_tpu.diagnostics.diagnostics import (
    BootstrapReport,
    FeatureImportanceReport,
    FittingReport,
    HosmerLemeshowReport,
    KendallTauReport,
    bootstrap_training_diagnostic,
    feature_importance_diagnostic,
    fitting_diagnostic,
    hosmer_lemeshow_diagnostic,
    kendall_tau_diagnostic,
)
from photon_ml_tpu.diagnostics.reporting import (
    Chapter,
    Document,
    LinePlot,
    Section,
    Table,
    Text,
    render_html,
    write_html_report,
)

__all__ = [
    "BootstrapReport",
    "FeatureImportanceReport",
    "FittingReport",
    "HosmerLemeshowReport",
    "KendallTauReport",
    "bootstrap_training_diagnostic",
    "feature_importance_diagnostic",
    "fitting_diagnostic",
    "hosmer_lemeshow_diagnostic",
    "kendall_tau_diagnostic",
    "Chapter",
    "Document",
    "LinePlot",
    "Section",
    "Table",
    "Text",
    "render_html",
    "write_html_report",
]
