"""Logical report tree -> HTML rendering (stdlib only).

Reference: photon-ml .../diagnostics/reporting/** — logical reports
(document/chapter/section with text, tables, plots) transformed to a
physical report and rendered by a strategy (html/HTMLRenderStrategy.scala
:1-73 uses scala.xml + xchart/batik rasterized plots). Here plots are
hand-rolled inline SVG (no plotting dependency in the image).
"""

from __future__ import annotations

import html
import os
from dataclasses import dataclass, field
from typing import List, Tuple, Union


@dataclass
class Text:
    body: str


@dataclass
class Table:
    header: List[str]
    rows: List[List[str]]
    caption: str = ""


@dataclass
class LinePlot:
    """Simple multi-series line plot rendered as inline SVG."""

    x: List[float]
    series: List[Tuple[str, List[float]]]
    title: str = ""
    x_label: str = ""
    y_label: str = ""


@dataclass
class Section:
    title: str
    items: List[Union[Text, Table, LinePlot]] = field(default_factory=list)


@dataclass
class Chapter:
    title: str
    sections: List[Section] = field(default_factory=list)


@dataclass
class Document:
    title: str
    chapters: List[Chapter] = field(default_factory=list)


_PALETTE = ["#3366cc", "#dc3912", "#ff9900", "#109618", "#990099"]


def _svg_line_plot(plot: LinePlot, width: int = 560, height: int = 320) -> str:
    pad = 48
    xs = list(plot.x)
    all_y = [y for _, ys in plot.series for y in ys if y == y]
    if not xs or not all_y:
        return "<p>(empty plot)</p>"
    x_min, x_max = min(xs), max(xs)
    y_min, y_max = min(all_y), max(all_y)
    if x_max == x_min:
        x_max = x_min + 1.0
    if y_max == y_min:
        y_max = y_min + 1.0

    def sx(v):
        return pad + (v - x_min) / (x_max - x_min) * (width - 2 * pad)

    def sy(v):
        return height - pad - (v - y_min) / (y_max - y_min) * (height - 2 * pad)

    parts = [
        f'<svg width="{width}" height="{height}" '
        'xmlns="http://www.w3.org/2000/svg" style="background:#fff">'
    ]
    if plot.title:
        parts.append(
            f'<text x="{width/2}" y="18" text-anchor="middle" '
            f'font-size="14">{html.escape(plot.title)}</text>'
        )
    # axes
    parts.append(
        f'<line x1="{pad}" y1="{height-pad}" x2="{width-pad}" '
        f'y2="{height-pad}" stroke="#333"/>'
    )
    parts.append(
        f'<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{height-pad}" stroke="#333"/>'
    )
    for frac in (0.0, 0.5, 1.0):
        xv = x_min + frac * (x_max - x_min)
        yv = y_min + frac * (y_max - y_min)
        parts.append(
            f'<text x="{sx(xv)}" y="{height-pad+16}" text-anchor="middle" '
            f'font-size="10">{xv:.3g}</text>'
        )
        parts.append(
            f'<text x="{pad-6}" y="{sy(yv)+4}" text-anchor="end" '
            f'font-size="10">{yv:.3g}</text>'
        )
    if plot.x_label:
        parts.append(
            f'<text x="{width/2}" y="{height-8}" text-anchor="middle" '
            f'font-size="11">{html.escape(plot.x_label)}</text>'
        )
    if plot.y_label:
        parts.append(
            f'<text x="14" y="{height/2}" text-anchor="middle" font-size="11" '
            f'transform="rotate(-90 14 {height/2})">{html.escape(plot.y_label)}</text>'
        )
    for si, (name, ys) in enumerate(plot.series):
        color = _PALETTE[si % len(_PALETTE)]
        pts = " ".join(
            f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys) if y == y
        )
        parts.append(
            f'<polyline fill="none" stroke="{color}" stroke-width="2" '
            f'points="{pts}"/>'
        )
        parts.append(
            f'<text x="{width-pad+4}" y="{pad + 14*si}" font-size="11" '
            f'fill="{color}">{html.escape(name)}</text>'
        )
    parts.append("</svg>")
    return "".join(parts)


def render_html(doc: Document) -> str:
    out = [
        "<!DOCTYPE html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(doc.title)}</title>",
        "<style>body{font-family:sans-serif;margin:32px;max-width:960px}"
        "table{border-collapse:collapse;margin:12px 0}"
        "td,th{border:1px solid #ccc;padding:4px 10px;font-size:13px}"
        "th{background:#f0f0f0}h2{border-bottom:2px solid #3366cc}"
        "caption{font-size:12px;color:#555}</style></head><body>",
        f"<h1>{html.escape(doc.title)}</h1>",
    ]
    for ch in doc.chapters:
        out.append(f"<h2>{html.escape(ch.title)}</h2>")
        for sec in ch.sections:
            out.append(f"<h3>{html.escape(sec.title)}</h3>")
            for item in sec.items:
                if isinstance(item, Text):
                    out.append(f"<p>{html.escape(item.body)}</p>")
                elif isinstance(item, Table):
                    out.append("<table>")
                    if item.caption:
                        out.append(f"<caption>{html.escape(item.caption)}</caption>")
                    out.append(
                        "<tr>" + "".join(f"<th>{html.escape(h)}</th>" for h in item.header) + "</tr>"
                    )
                    for row in item.rows:
                        out.append(
                            "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in row) + "</tr>"
                        )
                    out.append("</table>")
                elif isinstance(item, LinePlot):
                    out.append(_svg_line_plot(item))
    out.append("</body></html>")
    return "".join(out)


def write_html_report(doc: Document, path: str) -> None:
    from photon_ml_tpu.reliability.artifacts import atomic_writer

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with atomic_writer(path, encoding="utf-8") as f:
        f.write(render_html(doc))


def render_text(doc: Document) -> str:
    """Plain-text render strategy (the reference's
    diagnostics/reporting/text/** StringRenderStrategy analog): chapters
    and sections as underlined headings, tables column-aligned, plots
    summarized as their series' (min, max, last) since text cannot carry
    an image."""
    lines: List[str] = [doc.title, "=" * len(doc.title), ""]
    for chapter in doc.chapters:
        lines += [chapter.title, "-" * len(chapter.title), ""]
        for section in chapter.sections:
            lines += [f"## {section.title}", ""]
            for item in section.items:
                if isinstance(item, Text):
                    lines += [item.body, ""]
                elif isinstance(item, Table):
                    # tolerate ragged rows like render_html does — both
                    # shorter AND longer than the header
                    def cell(row, c):
                        return str(row[c]) if c < len(row) else ""

                    ncols = max(
                        [len(item.header)] + [len(r) for r in item.rows]
                    )
                    widths = [
                        max(
                            len(cell(item.header, c)),
                            *(len(cell(r, c)) for r in item.rows),
                        )
                        if item.rows
                        else len(cell(item.header, c))
                        for c in range(ncols)
                    ]

                    def fmt(row):
                        return "  ".join(
                            cell(row, c).ljust(w)
                            for c, w in enumerate(widths)
                        ).rstrip()

                    if item.caption:
                        lines.append(item.caption)
                    lines.append(fmt(item.header))
                    lines.append("  ".join("-" * w for w in widths))
                    lines += [fmt(r) for r in item.rows]
                    lines.append("")
                elif isinstance(item, LinePlot):
                    lines.append(f"[plot] {item.title or 'line plot'}")
                    for name, ys in item.series:
                        finite = [y for y in ys if y == y]  # NaN filter,
                        # matching _svg_line_plot's guard
                        if finite:
                            lines.append(
                                f"  {name}: min={min(finite):.6g} "
                                f"max={max(finite):.6g} "
                                f"last={finite[-1]:.6g} "
                                f"({len(ys)} points)"
                            )
                    lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def write_text_report(doc: Document, path: str) -> None:
    from photon_ml_tpu.reliability.artifacts import atomic_writer

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with atomic_writer(path, encoding="utf-8") as f:
        f.write(render_text(doc))
