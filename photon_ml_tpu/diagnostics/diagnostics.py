"""Model diagnostics: bootstrap CIs, Hosmer-Lemeshow, Kendall-tau,
feature importance, fitting curves.

Reference: photon-ml .../diagnostics/** —
- bootstrap/BootstrapTrainingDiagnostic.scala:1-149 + BootstrapTraining
  .scala:46-99 (resample + train + per-coefficient CoefficientSummary CIs),
- hl/HosmerLemeshowDiagnostic.scala:1-97 (decile-binned chi^2 calibration
  for logistic models),
- independence/KendallTauAnalysis.scala:1-131 (prediction/error rank
  independence),
- featureimportance/* (|w_j|-based mean/variance importance),
- fitting/FittingDiagnostic.scala:1-131 (learning curves on 10%%..100%%
  portions).

Each diagnostic returns a plain-python report dict consumed by
photon_ml_tpu.diagnostics.reporting (logical -> HTML).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

import numpy as np
from scipy import stats as scipy_stats

import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.models.glm import GeneralizedLinearModel, compute_means
from photon_ml_tpu.task import TaskType

Array = jnp.ndarray


# ---------------------------------------------------------------------------
# Bootstrap
# ---------------------------------------------------------------------------


@dataclass
class BootstrapReport:
    num_samples: int
    # per-coefficient: (mean, std, lo, hi) at the requested confidence
    coefficient_intervals: np.ndarray  # [d, 4]
    metrics_distribution: Dict[str, Tuple[float, float]]  # name -> (mean, std)
    important_features: List[Tuple[int, float, float]]  # (index, mean, std)


def bootstrap_training_diagnostic(
    batch: Batch,
    train_fn: Callable[[Batch], GeneralizedLinearModel],
    metrics_fn: Callable[[GeneralizedLinearModel], Dict[str, float]],
    *,
    num_samples: int = 10,
    confidence: float = 0.95,
    seed: int = 0,
    top_k: int = 10,
) -> BootstrapReport:
    """Resample rows WITH replacement (as weight multipliers — static
    shapes), retrain, aggregate per-coefficient summaries
    (BootstrapTrainingDiagnostic; resampling via multinomial row weights is
    the weighted-bootstrap equivalent of RDD.sample(true, 1.0))."""
    rng = np.random.default_rng(seed)
    n = batch.weights.shape[0]
    real = np.asarray(batch.weights) > 0
    coefs = []
    metric_values: Dict[str, List[float]] = {}
    for b in range(num_samples):
        counts = rng.multinomial(real.sum(), real / real.sum())
        w = np.asarray(batch.weights) * counts
        resampled = batch._replace(weights=jnp.asarray(w.astype(np.float32)))
        model = train_fn(resampled)
        coefs.append(np.asarray(model.means))
        for k, v in metrics_fn(model).items():
            metric_values.setdefault(k, []).append(v)
    coefs = np.stack(coefs)  # [B, d]
    alpha = (1.0 - confidence) / 2.0
    lo = np.quantile(coefs, alpha, axis=0)
    hi = np.quantile(coefs, 1.0 - alpha, axis=0)
    mean = coefs.mean(axis=0)
    std = coefs.std(axis=0, ddof=1) if num_samples > 1 else np.zeros_like(mean)
    intervals = np.stack([mean, std, lo, hi], axis=1)
    importance = np.abs(mean)
    order = np.argsort(-importance)[:top_k]
    return BootstrapReport(
        num_samples=num_samples,
        coefficient_intervals=intervals,
        metrics_distribution={
            k: (float(np.mean(v)), float(np.std(v)))
            for k, v in metric_values.items()
        },
        important_features=[(int(i), float(mean[i]), float(std[i])) for i in order],
    )


# ---------------------------------------------------------------------------
# Hosmer-Lemeshow
# ---------------------------------------------------------------------------


@dataclass
class HosmerLemeshowReport:
    chi_square: float
    degrees_of_freedom: int
    p_value: float
    bins: List[Dict[str, float]]  # per bin: count, expected_pos, observed_pos


def hosmer_lemeshow_diagnostic(
    model: GeneralizedLinearModel,
    batch: Batch,
    *,
    num_bins: int = 10,
) -> HosmerLemeshowReport:
    """Decile-of-risk calibration chi^2 for logistic models
    (HosmerLemeshowDiagnostic.scala:1-97)."""
    if model.task != TaskType.LOGISTIC_REGRESSION:
        raise ValueError("Hosmer-Lemeshow applies to logistic regression only")
    probs = np.asarray(model.mean(batch))
    labels = np.asarray(batch.labels)
    weights = np.asarray(batch.weights)
    real = weights > 0
    probs, labels, weights = probs[real], labels[real], weights[real]
    order = np.argsort(probs)
    probs, labels, weights = probs[order], labels[order], weights[order]
    cum_w = np.cumsum(weights)
    total = cum_w[-1]
    edges = np.searchsorted(cum_w, np.linspace(0, total, num_bins + 1)[1:-1])
    idx = np.split(np.arange(len(probs)), edges)
    chi2 = 0.0
    bins = []
    used_bins = 0
    for bucket in idx:
        if len(bucket) == 0:
            continue
        w = weights[bucket]
        cnt = w.sum()
        obs = (labels[bucket] * w).sum()
        exp = (probs[bucket] * w).sum()
        denom = exp * (1.0 - exp / max(cnt, 1e-12))
        if denom > 1e-12:
            chi2 += (obs - exp) ** 2 / denom
        used_bins += 1
        bins.append({
            "count": float(cnt),
            "observed_pos": float(obs),
            "expected_pos": float(exp),
            "mean_prob": float((probs[bucket] * w).sum() / max(cnt, 1e-12)),
        })
    dof = max(used_bins - 2, 1)
    p = float(scipy_stats.chi2.sf(chi2, dof))
    return HosmerLemeshowReport(
        chi_square=float(chi2), degrees_of_freedom=dof, p_value=p, bins=bins
    )


# ---------------------------------------------------------------------------
# Kendall tau
# ---------------------------------------------------------------------------


@dataclass
class KendallTauReport:
    tau: float
    p_value: float
    message: str


def kendall_tau_diagnostic(
    model: GeneralizedLinearModel,
    batch: Batch,
    *,
    max_samples: int = 2000,
    seed: int = 0,
) -> KendallTauReport:
    """Rank correlation between predictions and residual errors
    (KendallTauAnalysis.scala:1-131): material correlation flags a
    systematically mis-specified model."""
    preds = np.asarray(compute_means(model.task, model.means, batch))
    labels = np.asarray(batch.labels)
    real = np.asarray(batch.weights) > 0
    preds, labels = preds[real], labels[real]
    errors = labels - preds
    if len(preds) > max_samples:
        sel = np.random.default_rng(seed).choice(
            len(preds), size=max_samples, replace=False
        )
        preds, errors = preds[sel], errors[sel]
    tau, p = scipy_stats.kendalltau(preds, errors)
    msg = (
        "prediction/error ranks look independent"
        if p > 0.05
        else "prediction and error ranks are correlated — check model fit"
    )
    return KendallTauReport(tau=float(tau), p_value=float(p), message=msg)


# ---------------------------------------------------------------------------
# Feature importance
# ---------------------------------------------------------------------------


@dataclass
class FeatureImportanceReport:
    # (feature index, importance) sorted descending
    expected_magnitude: List[Tuple[int, float]]
    variance_magnitude: List[Tuple[int, float]]


def feature_importance_diagnostic(
    model: GeneralizedLinearModel,
    feature_means: np.ndarray,
    feature_variances: np.ndarray,
    *,
    top_k: int = 20,
) -> FeatureImportanceReport:
    """|w_j * E[x_j]| and |w_j| * sd(x_j) importances
    (featureimportance/ExpectedMagnitudeFeatureImportanceDiagnostic and
    VarianceFeatureImportanceDiagnostic)."""
    w = np.asarray(model.means)
    exp_imp = np.abs(w * feature_means)
    var_imp = np.abs(w) * np.sqrt(np.maximum(feature_variances, 0.0))
    def top(arr):
        order = np.argsort(-arr)[:top_k]
        return [(int(i), float(arr[i])) for i in order]
    return FeatureImportanceReport(top(exp_imp), top(var_imp))


# ---------------------------------------------------------------------------
# Fitting / learning curves
# ---------------------------------------------------------------------------


@dataclass
class FittingReport:
    portions: List[float]
    train_metrics: Dict[str, List[float]]
    test_metrics: Dict[str, List[float]]
    message: str


def fitting_diagnostic(
    batch: Batch,
    test_batch: Batch,
    train_fn: Callable[[Batch], GeneralizedLinearModel],
    metrics_fn: Callable[[GeneralizedLinearModel, Batch], Dict[str, float]],
    *,
    num_portions: int = 10,
    seed: int = 0,
) -> FittingReport:
    """Train on growing data portions, record train/test metric curves
    (FittingDiagnostic.scala:1-131). Portions are weight masks, keeping
    shapes static."""
    rng = np.random.default_rng(seed)
    w0 = np.asarray(batch.weights)
    real_idx = np.nonzero(w0 > 0)[0]
    perm = rng.permutation(real_idx)
    portions = [p / num_portions for p in range(1, num_portions + 1)]
    train_curves: Dict[str, List[float]] = {}
    test_curves: Dict[str, List[float]] = {}
    for p in portions:
        take = perm[: max(1, int(len(perm) * p))]
        mask = np.zeros_like(w0)
        mask[take] = 1.0
        sub = batch._replace(weights=jnp.asarray(w0 * mask))
        model = train_fn(sub)
        for k, v in metrics_fn(model, sub).items():
            train_curves.setdefault(k, []).append(v)
        for k, v in metrics_fn(model, test_batch).items():
            test_curves.setdefault(k, []).append(v)
    gaps = {
        k: abs(train_curves[k][-1] - test_curves[k][-1])
        for k in train_curves
        if k in test_curves
    }
    message = (
        "learning curves converge — more data unlikely to help"
        if all(g < 0.05 for g in gaps.values())
        else "train/test gap persists — consider more data or regularization"
    )
    return FittingReport(
        portions=portions,
        train_metrics=train_curves,
        test_metrics=test_curves,
        message=message,
    )
