"""Training task types.

Reference: photon-ml .../supervised/TaskType.scala (LINEAR_REGRESSION,
POISSON_REGRESSION, LOGISTIC_REGRESSION, SMOOTHED_HINGE_LOSS_LINEAR_SVM).
"""

import enum


class TaskType(enum.Enum):
    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"

    @property
    def is_classification(self) -> bool:
        return self in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )

    @classmethod
    def parse(cls, s: str) -> "TaskType":
        return cls(s.strip().upper())
