#!/usr/bin/env bash
# Twin-run determinism gate (chaos-style), runnable anywhere the
# package runs: every shipped artifact class is produced TWICE in
# fresh subprocesses under different PYTHONHASHSEEDs (0 vs 4242) and
# perturbed TZs (UTC vs Pacific/Kiritimati), then byte-diffed. Any
# divergence exits nonzero and names the first differing file + byte
# offset. The matrix (photon_ml_tpu/testing/determinism_targets.py):
#
#   metrics_json      run-summary / metrics JSON family
#   wire_frames       one frame per photon-wire message family
#   registry_publish  manifest + content signature + COMMIT marker
#   avro_container    Avro object container (deterministic sync marker)
#   sharding_md       SPMD contract inventory renderer
#   fleet_trace       merged fleet timeline
#
# This is the runtime twin of lint's determinism pass (PL015-PL018):
# lint proves no unordered iteration / undeclared ambient entropy
# reaches a writer; this gate proves the composed writers actually
# emit identical bytes. The per-class results + runtimes land in
# $OUT/determinism_gate.json for CI artifact upload.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${OUT:-/tmp/photon_determinism}"
rm -rf "$OUT"
mkdir -p "$OUT"

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m \
    photon_ml_tpu.testing.determinism \
    --matrix --out "$OUT" --report "$OUT/determinism_gate.json" "$@"
