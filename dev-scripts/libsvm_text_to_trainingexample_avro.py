#!/usr/bin/env python3
"""Convert a LibSVM text file into TrainingExampleAvro container files.

Parity analog of the reference's dataset-conversion helper
(photon-ml dev-scripts/libsvm_text_to_trainingexample_avro.py, used by the
README a1a tutorial at README.md:226-229): each feature's LibSVM index
token becomes the feature ``name`` verbatim (no re-indexing), the ``term``
is empty, and classification labels are mapped to {0, 1} (any label <= 0
becomes 0). With ``--regression`` the label is kept as-is.

Unlike the reference there is no output-schema-path argument: the
TrainingExampleAvro schema ships with the framework
(photon_ml_tpu.io.schemas) and is embedded in the container header, so the
output is readable by the reference's Avro input path and by
``photon_ml_tpu.cli.glm_driver --format TRAINING_EXAMPLE``.

Usage:
    python libsvm_text_to_trainingexample_avro.py INPUT OUTPUT [--regression]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from photon_ml_tpu.io.avro_codec import write_container  # noqa: E402
from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_AVRO  # noqa: E402


def libsvm_to_training_example_records(lines, *, regression: bool = False):
    """Iterate TrainingExampleAvro dicts over LibSVM text lines."""
    for line in lines:
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        tokens = line.split()
        if regression:
            label = float(tokens[0])
        else:
            label = 0.0 if float(tokens[0]) <= 0 else 1.0
        features = []
        for token in tokens[1:]:
            name, _, value = token.partition(":")
            features.append({"name": name, "term": "", "value": float(value)})
        yield {
            "uid": None,
            "label": label,
            "features": features,
            "metadataMap": None,
            "weight": None,
            "offset": None,
        }


def convert(input_path: str, output_path: str, *, regression: bool = False) -> int:
    """-> number of converted examples."""
    with open(input_path, "r", encoding="utf-8") as f:
        return write_container(
            output_path,
            TRAINING_EXAMPLE_AVRO,
            libsvm_to_training_example_records(f, regression=regression),
        )


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("input_path", help="LibSVM text input file")
    parser.add_argument("output_path", help="Avro container output file")
    parser.add_argument(
        "-r", "--regression", action="store_true",
        help="keep labels as-is instead of mapping to {0,1}",
    )
    args = parser.parse_args(argv)
    count = convert(args.input_path, args.output_path, regression=args.regression)
    print(f"converted {count} examples")


if __name__ == "__main__":
    main()
