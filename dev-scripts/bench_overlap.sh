#!/usr/bin/env bash
# Host-device overlap A/B (parallel/overlap.py): runs the config-5-shaped
# GAME coordinate-descent step with overlap OFF vs ON (bench.py
# --overlap-ab: deferred readbacks, prefetched host prep, async IO,
# pipelined streaming populate) and asserts the measured speedup plus the
# readback discipline and the streaming-populate wall bound.
#
# The speedup gate is host-class-aware, because the costs overlap removes
# are RELAY/ASYNC-DEVICE latencies (PERF_NOTES round 5: ~100 ms readback
# per bank update + ~125 ms host gaps between dispatches):
#   - accelerator attached -> the GAME step must be >= 1.15x faster
#     (PHOTON_OVERLAP_MIN_SPEEDUP overrides);
#   - single-core CPU-only host (this container when the tunnel is down)
#     -> compute/compute overlap is physically unavailable; the gate is
#     PARITY (overlap must not lose more than 5%) and the populate wall
#     must stay within the decode+consume sum bound. The >= 1.15x claim
#     is then carried by the next chip-attached round's BENCH artifact.
# Readback discipline is asserted unconditionally: 1 batched readback per
# CD iteration with overlap on, strictly more with it off.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-overlap-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

python bench.py --overlap-ab ${PHOTON_OVERLAP_FULL:+--full} | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
game = d["game_step"]
pop = d["streaming_populate"]
cpu_only_single_core = (d["host"]["cpu_count"] or 1) <= 1
print(json.dumps(r, indent=2))

# -- readback discipline (host-class independent) -----------------------
assert game["readbacks_per_step_on"] == 1, game
assert game["readbacks_per_step_off"] > 1, game

# -- GAME step speedup gate --------------------------------------------
default_gate = "0.95" if cpu_only_single_core else "1.15"
gate = float(os.environ.get("PHOTON_OVERLAP_MIN_SPEEDUP", default_gate))
sp = game["speedup"]
kind = "parity" if cpu_only_single_core else "speedup"
print(f"GAME CD step: off {game['step_s_overlap_off']}s -> "
      f"on {game['step_s_overlap_on']}s ({sp}x; {kind} gate >= {gate}x)")
assert sp >= gate, f"overlap speedup {sp}x below the {gate}x gate"

# -- streaming populate wall bound -------------------------------------
wall = pop["cold_populate_wall_s_pipelined"]
serial = pop["cold_populate_wall_s_serial"]
if cpu_only_single_core:
    # one core: decode cannot hide under consume, so the wall bound is
    # unattainable by physics; the gate is NO REGRESSION vs the serial
    # populate (the sum/max bound booleans stay recorded for the chip
    # rounds). 15%+50ms slack absorbs 1-core scheduler noise.
    assert wall <= serial * 1.15 + 0.05, pop
    print(f"populate wall {wall}s vs serial {serial}s "
          f"[single-core host: no-regression gate]")
else:
    assert pop["wall_within_max_bound"], pop
    print(f"populate wall {wall}s within max(decode, consume) bound "
          f"({pop['bound_max_decode_consume_s']}s)")
print("OK: overlap A/B gates passed")
EOF
