#!/usr/bin/env bash
# Out-of-core GAME coordinate-descent A/B (game/streaming.py): runs the
# streamed CD vs the in-memory CD on the same synthetic Avro files
# (bench.py --streaming-game) and gates the result.
#
# Host-class-aware gates, because what streaming trades is HOST work
# (per-pass Avro decode + python staging) that a multi-core host hides
# behind the solves but a single core pays serially:
#   - multi-core host -> streamed throughput must be >= 0.8x the
#     in-memory fit (PHOTON_STREAM_GAME_MIN_RATIO overrides);
#   - single-core CPU container (this image when the tunnel is down) ->
#     the gate is PARITY: the streamed objective must match the
#     in-memory objective (rel diff < 1e-3) — the machinery is correct
#     and the throughput claim is carried by the next multi-core round.
# The RSS assertion runs unconditionally: the streamed fit's RSS
# high-water delta must stay in the budget + interpreter/XLA slack
# class, NOT the dataset class (the strict subprocess-isolated bound is
# pinned in tests/test_streaming_game.py::TestStreamingGameBoundedMemory).
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-stream-game-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

python bench.py --streaming-game | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

assert d["num_chunks"] >= 3, f"A/B must stream >= 3 chunks: {d['num_chunks']}"

# -- objective parity (host-class independent) --------------------------
assert d["objective_rel_diff"] < 1e-3, d["objective_rel_diff"]

# -- RSS bound ----------------------------------------------------------
slack = 192 << 20  # interpreter + jit compile + model class
budget = d["memory_budget_bytes"]
assert d["rss_delta_bytes"] < budget + slack, (
    f"RSS delta {d['rss_delta_bytes']} exceeds budget {budget} + slack"
)
print(f"RSS delta {d['rss_delta_bytes'] >> 20} MiB within "
      f"budget {budget >> 20} MiB + {slack >> 20} MiB slack")

# -- throughput gate ----------------------------------------------------
single_core = (d["host"]["cpu_count"] or 1) <= 1
if single_core:
    print(f"single-core host: throughput ratio {d['throughput_ratio']}x "
          "recorded (parity gate only; >= 0.8x gate applies on "
          "multi-core hosts)")
else:
    gate = float(os.environ.get("PHOTON_STREAM_GAME_MIN_RATIO", "0.8"))
    ratio = d["throughput_ratio"]
    print(f"streamed {d['examples_per_s']} ex/s vs in-memory "
          f"{d['in_memory_examples_per_s']} ex/s ({ratio}x; gate >= {gate}x)")
    assert ratio >= gate, f"throughput ratio {ratio}x below {gate}x"

print("bench_streaming_game: PASS")
EOF
