#!/usr/bin/env bash
# Chaos harness (round 11, reliability layer; serving-under-fire arms
# added round 13): a tier-1-sized fault matrix — one injected fault per
# seam class (chunk read, spill write/read, cache load/store,
# checkpoint save, async IO worker, serving model-load/frontend-read/
# dispatch) — driven end-to-end through the GLM, GAME and serving
# drivers (replay, stdin deadline mix, the TCP front-end under
# flood + mid-flood swap + SIGTERM drain, and the shard-routed
# scatter/gather fleet under flood + two-step flip + SIGKILL),
# asserting:
#
#   1. every faulted run COMPLETES (transient faults retry; corrupt
#      cache artifacts quarantine to *.corrupt and rebuild);
#   2. faulted runs are BITWISE equal to their fault-free twins
#      (models-text, model containers, objective histories);
#   3. every injected fault / retry / quarantine is ACCOUNTED in the
#      run's metrics.json reliability block;
#   4. with injection disabled, the seam layer costs < 2% of the
#      spill-read hot path (bench.py --reliability);
#   5. (ISSUE 13) every fleet process's FLIGHT RECORDER captured the
#      injected sequence in order — the SIGKILLed shard's auto-dumped
#      ring survives the kill showing stage->commit, and
#      check_conservation() (admitted == named terminal outcomes)
#      holds across the mid-flood generation swap (arm 14's obs leg).
#
# CPU-only by design (JAX_PLATFORMS=cpu in the matrix): the seams under
# test are host-side IO; chip rounds inherit the same code path.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== chaos matrix (fault injection x both drivers) =="
python dev-scripts/chaos_matrix.py

echo "== interleaving matrix (deterministic schedules, ISSUE 11) =="
# the runtime twin of lint rules PL008-PL010: >=200 seeded cooperative
# schedules over submit/close/swap/rollback on the REAL serving/
# registry thread plane — every submitted request reaches exactly one
# terminal outcome, generations stay monotonic under concurrent swaps,
# at most one rollback per health regression, zero deadlocks. Failures
# name their seed; replay with InterleaveScheduler(seed=<seed>).
python dev-scripts/interleave_matrix.py --schedules "${PHOTON_INTERLEAVE_SCHEDULES:-200}"

echo "== reliability overhead gate (injection disabled) =="
OUT=$(mktemp -t photon-chaos-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT
JAX_PLATFORMS=cpu python bench.py --reliability | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
print(json.dumps(r, indent=2))
gate = float(os.environ.get("PHOTON_RELIABILITY_MAX_OVERHEAD", "0.02"))
frac = r["value"]
assert frac < gate, (
    f"reliability-layer overhead {frac:.4f} exceeds the {gate:.2%} gate "
    f"(per-call {r['detail']['per_call_overhead_us']} us x "
    f"{r['detail']['calls_per_sweep']} calls over a "
    f"{r['detail']['sweep_s']}s sweep)"
)
print(f"overhead {frac:.4%} < {gate:.2%} gate")
print("chaos: PASS")
EOF
