#!/usr/bin/env python
"""Interleaving arm of the chaos harness (ISSUE 11): drive the REAL
serving/registry thread plane — MicroBatcher dispatch, ServingModel
stage/flip, RegistryWatcher promote/rollback — through N seeded
deterministic schedules of submit/close/swap/rollback and assert the
invariants the static rules (PL008-PL010) protect:

  1. every submitted request reaches EXACTLY ONE terminal outcome
     (a score or a named ServingError) — no hung futures, ever;
  2. no schedule deadlocks or livelocks (the scheduler completes
     inside its step budget; a deadlock raises with the blocked
     thread set and the replayable seed);
  3. model generations are strictly monotonic across concurrent
     swaps and rollbacks (the swap-serialization contract);
  4. at most one rollback fires per health regression episode (the
     stale-window double-rollback defect stays dead).

Schedules are VIRTUAL-TIME (the harness owns the clock), so the whole
matrix runs in seconds. Every failure names its seed; replay it with
InterleaveScheduler(seed=<seed>) and the same scenario.

Usage:  python dev-scripts/interleave_matrix.py [--schedules N]
                                                [--base-seed S]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from photon_ml_tpu.testing.interleave import InterleaveScheduler  # noqa: E402


class StubPrograms:
    """Fixed-ladder scorer: virtual device time, zero scores."""

    ladder = (1, 4, 16)

    def score(self, bank, batch):
        time.sleep(0.002)  # virtual device time per dispatch
        return np.zeros(batch.offsets.shape[0], np.float32)

    def ensure_compiled(self, bank, partial=False):
        time.sleep(0.05)  # virtual warmup
        return 0

    def executable(self, spec, B, partial=False):
        return object()


class StubBank:
    def __init__(self, spec=("g",)):
        self.spec = spec
        self.arrays = {}
        self.generation = 1
        self.retired = False
        self.index_maps = {}
        self.shard_widths = {}
        self.used_shards = ()
        self.re_types = ()
        self.quarantined_re_types = frozenset()
        self.entity_rows = {}


class FakeGen:
    def __init__(self, generation, parent):
        self.generation = generation
        self.parent = parent
        self.model_dir = f"gen-{generation}"


class FakeRegistry:
    root = "<interleave-matrix>"

    def __init__(self, gens):
        self._gens = {g.generation: g for g in gens}
        self.quarantined = []

    def latest(self):
        live = [
            g for n, g in self._gens.items()
            if n not in self.quarantined
        ]
        return max(live, key=lambda g: g.generation) if live else None

    def generation(self, n):
        return self._gens.get(n)

    def lineage(self, n):
        out = []
        while n is not None and n in self._gens:
            out.append(n)
            n = self._gens[n].parent
        return out

    def quarantine_generation(self, n, reason=""):
        self.quarantined.append(n)
        return f"q-{n}"


class SwapAdapter:
    """RegistryWatcher speaks stage_and_swap(model_dir); route it onto
    the REAL ServingModel.swap_to_bank so the watcher's promote and
    rollback protocols exercise the real stage/flip locking."""

    def __init__(self, sm):
        self.sm = sm

    def stage_and_swap(self, model_dir, **kw):
        time.sleep(0.1)  # virtual artifact-load time
        return self.sm.swap_to_bank(StubBank(spec=(model_dir,)))


def one_schedule(seed: int) -> dict:
    """One deterministic schedule of submit/close/swap/rollback over
    the real thread plane. Returns a stats dict; raises on any
    invariant violation (the caller records the seed)."""
    import photon_ml_tpu.serving.swap as swap_mod
    from photon_ml_tpu.registry.watcher import (
        RegistryWatcher,
        RollbackPolicy,
    )
    from photon_ml_tpu.serving.admission import ServingError
    from photon_ml_tpu.serving.batcher import MicroBatcher, ScoreRequest
    from photon_ml_tpu.serving.metrics import ServingMetrics

    sched = InterleaveScheduler(seed=seed, max_steps=500_000)
    saved_place = swap_mod.place_on_device
    swap_mod.place_on_device = lambda arrays: arrays
    outcomes = []
    try:
        with sched.patched():
            sm = swap_mod.ServingModel(StubBank(), StubPrograms())
            metrics = ServingMetrics()
            batcher = MicroBatcher(
                sm.current, sm.programs, metrics, max_queue=8,
            )
            # the watcher drives swap AND rollback through the REAL
            # ServingModel via the adapter
            registry = FakeRegistry(
                [FakeGen(1, None), FakeGen(2, 1), FakeGen(3, 2)]
            )
            watcher = RegistryWatcher(
                registry, SwapAdapter(sm),
                poll_s=0.05,
                policy=RollbackPolicy(
                    window=8, min_requests=2, max_unhealthy_rate=0.4
                ),
            )
            watcher.start()

            def submitter(tag, n, deadline_ms):
                def body():
                    for i in range(n):
                        req = ScoreRequest(
                            uid=f"{tag}-{i}", indices={}, values={},
                            entity_ids={}, deadline_ms=deadline_ms,
                        )
                        try:
                            fut = batcher.submit(req)
                        except ServingError as e:
                            outcomes.append(("refused", type(e).__name__))
                            continue
                        outcomes.append(("admitted", fut))
                        time.sleep(0.003)
                return body

            def unhealthy_feed():
                # simulate a degraded post-swap window so the watcher's
                # auto-rollback (and ONLY one) fires
                for _ in range(200):
                    watcher.observe_outcome(degraded=True)
                    time.sleep(0.01)
                    if any(
                        r.action == "rollback" for r in watcher.history
                    ):
                        break
                for _ in range(4):  # stragglers: the double-rollback bait
                    watcher.observe_outcome(degraded=True)
                    time.sleep(0.01)

            def extra_swap():
                # a driver-style swap racing the watcher's promote
                time.sleep(0.05)
                sm.swap_to_bank(StubBank(spec=("driver-swap",)))

            def closer():
                time.sleep(3.0)
                watcher.stop(timeout_s=30.0)
                batcher.drain(timeout_s=30.0)

            sched.spawn(submitter("a", 6, None), name="submit-a")
            sched.spawn(submitter("b", 6, 25.0), name="submit-b")
            sched.spawn(unhealthy_feed, name="health-feed")
            sched.spawn(extra_swap, name="driver-swap")
            sched.spawn(closer, name="closer")
            sched.run()

        # -- invariants ------------------------------------------------------
        admitted = [o[1] for o in outcomes if o[0] == "admitted"]
        for fut in admitted:
            assert fut.done(), (
                f"seed {seed}: hung future after drain "
                f"(queue_depth={batcher.queue_depth()})"
            )
            # exactly-one-terminal-outcome: done() means result OR a
            # named error; anything else would have raised above
            exc = fut.exception(timeout=0)
            if exc is not None:
                assert isinstance(exc, ServingError), (
                    f"seed {seed}: anonymous failure {exc!r}"
                )
        gens = [r.generation for r in sm.swap_history if r.ok]
        assert gens == sorted(gens) and len(gens) == len(set(gens)), (
            f"seed {seed}: non-monotonic generations {gens}"
        )
        rollbacks = [
            r for r in watcher.history if r.action == "rollback"
        ]
        assert len(rollbacks) <= 1, (
            f"seed {seed}: {len(rollbacks)} rollbacks for one episode: "
            f"{[(r.action, r.registry_generation) for r in watcher.history]}"
        )
        assert not batcher.alive(), f"seed {seed}: dispatcher leaked"
        return {
            "admitted": len(admitted),
            "refused": sum(1 for o in outcomes if o[0] == "refused"),
            "swaps": len(gens),
            "rollbacks": len(rollbacks),
            "steps": sched.steps,
        }
    finally:
        swap_mod.place_on_device = saved_place


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--schedules", type=int, default=200)
    ap.add_argument("--base-seed", type=int, default=0)
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    totals = {"admitted": 0, "refused": 0, "swaps": 0, "rollbacks": 0,
              "steps": 0}
    failures = []
    for i in range(args.schedules):
        seed = args.base_seed + i
        try:
            stats = one_schedule(seed)
        except BaseException as e:
            failures.append(f"seed {seed}: {type(e).__name__}: {e}")
            if len(failures) >= 5:
                break
            continue
        for k in totals:
            totals[k] += stats[k]
    wall = time.monotonic() - t0
    print(
        f"interleave matrix: {args.schedules} schedule(s) in {wall:.1f}s "
        f"— admitted {totals['admitted']}, refused {totals['refused']}, "
        f"swaps {totals['swaps']}, rollbacks {totals['rollbacks']}, "
        f"{totals['steps']} scheduler steps"
    )
    if failures:
        print(
            f"INTERLEAVE VIOLATIONS ({len(failures)}):\n  "
            + "\n  ".join(failures),
            file=sys.stderr,
        )
        return 1
    # the matrix must actually exercise the plane, not vacuously pass
    assert totals["admitted"] > 0 and totals["swaps"] > 0, totals
    print("interleave matrix: PASS (zero invariant violations)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
