#!/usr/bin/env bash
# Static gates, runnable anywhere the package runs:
#   1. photon-lint — the project-specific JAX hot-path invariants
#      (readback seam, recompile hazards, spill/IO hygiene) PLUS the
#      whole-package concurrency pass (PL008 unguarded-shared-state,
#      PL009 lock-order-inversion, PL010 atomicity-hygiene) AND the
#      whole-package SPMD pass (PL011 mesh-axis-discipline, PL012
#      sharded-bank-host-gather, PL013 reduction-completeness, PL014
#      donation-hygiene) AND the whole-package determinism pass
#      (PL015 unordered-iteration-to-artifact, PL016 ambient-entropy-
#      in-artifact with the '# photon: entropy(<reason>)' declaration
#      grammar, PL017 float-accumulation-order, PL018 wire-contract
#      completeness), all ON BY DEFAULT (opt out per-invocation with
#      --no-concurrency / --no-spmd / --no-determinism); rules and
#      suppression/baseline mechanics in photon_ml_tpu/lint/. PL009,
#      PL012, PL016 and PL018 findings are never baseline-able. The
#      determinism pass's runtime twin is dev-scripts/determinism.sh
#      (hash-seed twin-run byte-diff over every artifact class).
#      The SPMD pass covers the unified-mesh plane (parallel/
#      unified_mesh.py, game/unified.py) at ZERO baseline and ZERO
#      allows — every grid-sharded program carries a machine-checked
#      '# photon: sharding(...)' contract like the pod plane.
#   2. SHARDING.md drift gate — the committed sharding-contract
#      inventory must match a fresh render of the SPMD pass's entry-
#      point scan (regenerate with --write-sharding-md). Skipped when
#      --no-spmd was passed.
#   3. ruff — generic hygiene (import order, unused imports/variables,
#      mutable default args; [tool.ruff] in pyproject.toml). Soft-skips
#      when ruff is not installed so minimal CI containers still gate
#      on photon-lint.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m photon_ml_tpu.lint photon_ml_tpu bench.py "$@"

skip_spmd=0
for arg in "$@"; do
    [ "$arg" = "--no-spmd" ] && skip_spmd=1
done
if [ "$skip_spmd" = 0 ]; then
    python -m photon_ml_tpu.lint photon_ml_tpu bench.py \
        --check-sharding-md SHARDING.md
fi

if command -v ruff >/dev/null 2>&1; then
    ruff check photon_ml_tpu bench.py tests dev-scripts
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check photon_ml_tpu bench.py tests dev-scripts
else
    echo "lint.sh: ruff not installed — skipping ruff check" >&2
fi
