#!/usr/bin/env bash
# Static gates, runnable anywhere the package runs:
#   1. photon-lint — the project-specific JAX hot-path invariants
#      (readback seam, recompile hazards, spill/IO hygiene) PLUS the
#      whole-package concurrency pass (PL008 unguarded-shared-state,
#      PL009 lock-order-inversion, PL010 atomicity-hygiene), which
#      runs BY DEFAULT (opt out per-invocation with --no-concurrency);
#      rules and suppression/baseline mechanics in photon_ml_tpu/lint/.
#      PL009 findings are never baseline-able.
#   2. ruff — generic hygiene (import order, unused imports/variables,
#      mutable default args; [tool.ruff] in pyproject.toml). Soft-skips
#      when ruff is not installed so minimal CI containers still gate
#      on photon-lint.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m photon_ml_tpu.lint photon_ml_tpu bench.py "$@"

if command -v ruff >/dev/null 2>&1; then
    ruff check photon_ml_tpu bench.py tests dev-scripts
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check photon_ml_tpu bench.py tests dev-scripts
else
    echo "lint.sh: ruff not installed — skipping ruff check" >&2
fi
