#!/usr/bin/env bash
# Planet-scale serving bench (photon_ml_tpu/serving/routing, ISSUE 12):
# runs bench.py --shard-routing — the scatter/gather router over REAL
# shard-server subprocesses at N in {1, 2, 4}, flooded with a zipf
# (head-skewed) open-loop replay, plus a SIGKILL-one-shard leg — and
# gates the routing contract.
#
# Host-class-aware gates:
#   - EVERYWHERE (the routing contract is host-independent):
#       * every submitted request reached exactly one terminal outcome
#         in EVERY fleet (terminal == submitted) — zero hangs, and the
#         kill leg too;
#       * per-request fan-out p99 bounded
#         (<= PHOTON_ROUTING_MAX_P99_MS; default 250 ms on CPU
#         containers, 50 ms chip-attached);
#       * hot-entity cache hit rate > 0 under the zipf replay (head
#         traffic MUST be absorbed; a zero rate means the cache plane
#         is dead);
#       * 0 request-path lowerings per shard-server
#         (cold_dispatch_compiles == 0 on every shard that drained);
#       * kill leg: the SIGKILLed shard's entities DEGRADE (FE-only,
#         counted > 0) with zero request errors — one dead shard is
#         never an outage;
#   - SCALING gate (aggregate QPS at N=4 >= PHOTON_ROUTING_MIN_SCALING
#     x the N=1 fleet, default 2.0): applied only when the host can
#     actually run 4 scorer processes concurrently (cpu_count >= 8 or
#     chip-attached) — on a 1-core container all fleets share one core
#     and the ratio is RECORDED, not gated.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-shard-routing-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

python bench.py --shard-routing | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

host = d["host"]

# -- exactly one terminal outcome per submitted request, every fleet ----
for n, f in sorted(d["fleets"].items()):
    assert f["terminal"] == f["submitted"], (n, f["terminal"], f["submitted"])
    errs = {k: v for k, v in f["outcomes"].items() if k.startswith("error")}
    assert not errs, (n, errs)
    print(f"fleet N={n}: {f['submitted']} submitted -> {f['terminal']} "
          f"terminal, qps {f['qps']}, fanout p99 {f['fanout_p99_ms']}ms, "
          f"cache hit rate {f['cache_hit_rate']}")

# -- fan-out latency stays bounded --------------------------------------
default_p99 = 50.0 if host["on_chip"] else 250.0
max_p99 = float(os.environ.get("PHOTON_ROUTING_MAX_P99_MS", default_p99))
for n, f in sorted(d["fleets"].items()):
    p99 = f["fanout_p99_ms"]
    assert p99 is not None and p99 <= max_p99, (
        f"fleet N={n}: fan-out p99 {p99}ms above {max_p99}ms"
    )
print(f"latency OK: every fleet's fan-out p99 <= {max_p99}ms")

# -- the hot-entity cache absorbs head traffic --------------------------
for n, f in sorted(d["fleets"].items()):
    assert f["cache_hit_rate"] > 0, (
        f"fleet N={n}: zero cache hits under a zipf replay — the "
        "hot-entity cache is not engaging"
    )
print("cache OK: hit rate > 0 under zipf replay in every fleet")

# -- fixed-shape contract per shard -------------------------------------
for n, f in sorted(d["fleets"].items()):
    for s in f["shards"]:
        assert s["cold_dispatch_compiles"] == 0, (n, s)
        assert s["dispatches"] > 0, (n, s)
print("contract OK: 0 request-path lowerings on every drained shard")

# -- one dead shard degrades, never an outage ---------------------------
k = d["kill_leg"]
assert k is not None, "kill leg missing"
assert k["terminal"] == k["submitted"], (k["terminal"], k["submitted"])
assert k["degraded"] > 0, (
    "SIGKILLed shard produced zero degraded outcomes — degradation is "
    "not engaging"
)
assert k["errors"] == 0, k
print(f"degradation OK: shard {k['killed_shard']} SIGKILLed -> "
      f"{k['degraded']} FE-only degraded, 0 errors, "
      f"{k['terminal']}/{k['submitted']} terminal")

# -- aggregate QPS scales with shard count (multi-core/chip only) -------
min_scaling = float(os.environ.get("PHOTON_ROUTING_MIN_SCALING", "2.0"))
scaling = d["scaling_4_over_1"]
can_gate = host["on_chip"] or (host["cpu_count"] or 1) >= 8
if can_gate:
    assert scaling >= min_scaling, (
        f"aggregate QPS at N=4 only {scaling}x the N=1 fleet "
        f"(gate {min_scaling}x)"
    )
    print(f"scaling OK: N=4 / N=1 = {scaling}x >= {min_scaling}x")
else:
    print(f"scaling recorded (not gated on {host['cpu_count']}-core "
          f"host): N=4 / N=1 = {scaling}x, N=2 / N=1 = "
          f"{d['scaling_2_over_1']}x")

print("bench_shard_routing: PASS")
EOF
