#!/usr/bin/env bash
# Fleet-observability overhead bench (photon_ml_tpu/obs/fleet, ISSUE
# 15): runs bench.py --fleet-obs — the SAME closed-loop routed request
# stream through a REAL 2-shard TCP fleet with the fleet-obs plane OFF
# (shipped default) vs ON (span tracing + the live FleetCollector
# draining every member's ring + router conservation attribution),
# alternating passes — and gates the result.
#
# Host-class-aware gates:
#   - EVERYWHERE (the request-path contract, host-independent):
#       * zero programs lowered on the request path in BOTH arms
#         (request_path_lowerings == 0 — the collector must never
#         compile anything);
#       * FLEET CONSERVATION: router admitted == Σ shard-attributed
#         terminals + router-local outcomes, joined against each
#         shard's own per-generation book;
#       * merge COMPLETENESS: every traced request's router.request
#         root reached the collector (router_request_roots ==
#         traced_requests), the stitched trace verifies (nesting +
#         skew tolerance), and the collector dropped nothing
#         (ring_dropped == 0, errors == 0);
#       * implied overhead < PHOTON_FLEET_OBS_MAX_OVERHEAD (default
#         2%): the plane's entire request-path addition (two
#         conservation notes + two span records per routed request)
#         measured deterministically in isolation over the measured
#         per-request wall — the noise-free twin of the A/B.
#   - MULTI-CORE / CHIP ONLY: the paired A/B itself < the same gate.
#     A 1-core container timeshares the collector thread WITH the
#     request loop, so its A/B is noise-dominated; recorded honestly,
#     bounded only by a loose ceiling.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-fleet-obs-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --fleet-obs | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

# -- request-path contract (host-independent) ---------------------------
assert d["request_path_lowerings"] == 0, d["request_path_lowerings"]
print("contract OK: 0 request-path lowerings across both arms")

# -- fleet conservation -------------------------------------------------
cons = d["conservation"]
assert cons["ok"], cons
assert cons["attribution_ok"], cons
for name, entry in cons["shards"].items():
    assert entry["join_ok"] is True, (name, entry)
print(
    f"fleet conservation OK: admitted {cons['admitted']} == "
    f"Σ attributed {sum(cons['terminal_by_attribution'].values())} "
    f"({cons['terminal_by_attribution']}), shard joins exact"
)

# -- merge completeness -------------------------------------------------
assert d["stitch_ok"], d["stitch_violations"]
assert d["router_request_roots"] == d["traced_requests"], (
    d["router_request_roots"], d["traced_requests"],
)
assert d["score_leaves"] > 0, d
assert d["collector"]["ring_dropped"] == 0, d["collector"]
assert d["collector"]["errors"] == 0, d["collector"]
print(
    f"completeness OK: {d['router_request_roots']} router.request "
    f"roots == {d['traced_requests']} traced requests; "
    f"{d['score_leaves']} dispatch-joined score leaves; collector "
    f"dropped 0 over {d['collector']['polls']} poll(s)"
)

# -- overhead gates -----------------------------------------------------
gate = float(os.environ.get("PHOTON_FLEET_OBS_MAX_OVERHEAD", "0.02"))
implied = d["implied_overhead_frac"]
assert implied < gate, (
    f"implied per-request overhead {implied:.4f} "
    f"({d['conservation_note_us']}us notes + {d['span_pair_us']}us "
    f"spans over {d['per_request_us']}us/request) exceeds the "
    f"{gate:.2%} gate"
)
print(
    f"implied overhead OK: {d['conservation_note_us']}us notes + "
    f"{d['span_pair_us']}us spans over {d['per_request_us']}us/request "
    f"= {implied:.4%} < {gate:.2%}"
)

multi_core = d["host"]["on_chip"] or (d["host"]["cpu_count"] or 1) > 1
ab = r["value"]
if multi_core:
    assert ab < gate, (
        f"paired A/B overhead {ab:.4f} exceeds the {gate:.2%} gate"
    )
    print(f"A/B overhead OK: {ab:.4%} < {gate:.2%}")
else:
    noise_ceiling = float(
        os.environ.get("PHOTON_FLEET_OBS_NOISE_CEILING", "0.30")
    )
    assert ab < noise_ceiling, (
        f"paired A/B overhead {ab:.4f} exceeds even the 1-core noise "
        f"ceiling {noise_ceiling:.2%} — that is an effect, not jitter"
    )
    print(
        f"A/B recorded (1-core container, collector timeshares the "
        f"request loop): {ab:.4%} (pairwise ratios "
        f"{d['pairwise_ratios']}); <{gate:.2%} gate applies on "
        "multi-core/chip hosts"
    )
print("bench_fleet_obs: PASS")
EOF
