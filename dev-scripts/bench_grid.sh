#!/usr/bin/env bash
# Batched λ-grid training A/B (training.train_grid_batched, ISSUE 5):
# runs the warm-started sequential regularization path vs the ONE
# vmapped grid program on the same synthetic data (bench.py
# --grid-batched) and gates the result.
#
# Host-class-aware gates, because what batching buys is PARALLELISM
# across the grid members' device work — a single core executes the
# vmapped program and the sequential loop as the same serial FLOPs:
#   - multi-core / chip-attached host -> batched warm wall-clock must be
#     >= 1.3x the sequential path at G >= 4
#     (PHOTON_GRID_MIN_SPEEDUP overrides);
#   - single-core CPU container (this image when the tunnel is down) ->
#     the gate is PARITY + the compile/readback contract; the measured
#     1-core speedup is recorded for the round artifact, not gated.
# Unconditional gates: per-λ objective parity (rel <= 2e-3, the
# PERF_NOTES LBFGS envelope class), the whole grid's scalars in ONE
# readback round, and the batched path lowering NO MORE jit programs
# than the sequential path (1 fused program serves the grid).
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-grid-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

python bench.py --grid-batched | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

assert d["G"] >= 4, f"A/B needs a G >= 4 grid: {d['G']}"

# -- per-λ objective parity (host-class independent) --------------------
assert d["objective_parity_rel_max"] <= 2e-3, d["objective_parity_rel_max"]
print(f"per-λ objective parity: rel max {d['objective_parity_rel_max']:.2e}")

# -- the 1-compile / 1-readback contract --------------------------------
assert d["batched"]["scalar_readback_rounds"] == 1, d["batched"]
# λ is a traced argument: a different grid of the same shape must lower
# ZERO new programs — ONE compiled program serves every grid
assert d["batched"]["jit_lowerings_regrid"] == 0, d["batched"]
print(
    f"re-grid lowerings: {d['batched']['jit_lowerings_regrid']} (one "
    f"program serves every same-shape grid); grid scalars in "
    f"{d['batched']['scalar_readback_rounds']} readback round"
)

# -- wall-clock gate ----------------------------------------------------
single_core = (d["host"]["cpu_count"] or 1) <= 1
if single_core:
    print(f"single-core host: warm speedup {d['speedup_warm']}x recorded "
          "(parity gate only; >= 1.3x gate applies on multi-core/chip "
          "hosts)")
else:
    gate = float(os.environ.get("PHOTON_GRID_MIN_SPEEDUP", "1.3"))
    print(f"batched warm {d['batched']['warm_s']}s vs sequential "
          f"{d['sequential']['warm_s']}s ({d['speedup_warm']}x; "
          f"gate >= {gate}x)")
    assert d["speedup_warm"] >= gate, (
        f"grid speedup {d['speedup_warm']}x below {gate}x"
    )

print("bench_grid: PASS")
EOF
