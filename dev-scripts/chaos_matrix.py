#!/usr/bin/env python
"""Chaos matrix: one injected fault per seam class, end-to-end through
both drivers, asserting completion + accounting + bitwise parity.

Driven by ``dev-scripts/chaos.sh``. Arms (each driver invocation is a
fresh subprocess, so fault plans and reliability counters are
per-arm):

1. **GLM clean** — streaming λ-grid (tiled kernel + tile cache +
   checkpoint dir + async summary write) with NO fault plan: the
   reference outputs.
2. **GLM faulted cold** — same args, fresh dirs, plan injecting one
   transient fault at chunk_read, spill_write, spill_read, io_worker,
   ckpt_save and cache_store. Must complete; ``models-text`` and the
   models container must be BITWISE equal to arm 1; metrics.json must
   account every injected fault and retry.
3. **GLM faulted warm** — rerun over arm 2's tile cache with a
   cache_load fault + a cache_load CORRUPT: the faulted artifact
   quarantines (``*.corrupt`` on disk, counted in metrics) and the run
   still completes bitwise-equal.
4. **GAME clean** — streamed GAME CD (chunks + RE segments + score
   stores + per-iteration CD snapshots).
5. **GAME faulted** — same args, fresh dirs, faults at chunk_read,
   spill_write, spill_read and ckpt_save. Completion + bitwise model
   parity + accounting.
6. **Serving clean** — the GAME best-model replayed through the online
   scoring driver (AOT ladder + micro-batcher): the reference scores.
7. **Serving faulted** — transient EIO at ``serving.model_load`` on the
   initial bank load: retried, completes, scores bitwise-equal arm 6.
8. **Serving swap-corrupt** — a hot swap staged mid-replay from a model
   copy whose load injects CORRUPT: the copy quarantines to
   ``*.corrupt``, the swap ROLLS BACK, the run completes on generation
   1 with scores bitwise-equal arm 6, and metrics.json accounts the
   quarantine + rollback.
9. **Serving overload (stdin deadlines)** — the same trace replayed as
   JSON lines with every 3rd request carrying an already-expired
   deadline: every request reaches exactly ONE terminal outcome
   (ok/deadline_exceeded, conserved), the admitted scores are bitwise
   arm 6's, and the dropped rows never reach the device.
10. **Frontend under fire** — the real TCP front-end flooded over a
    socket with injected read + dispatch faults, a mid-flood hot swap,
    expired-deadline requests, a malformed client, a stalled
    (half-line) slow client, and an operator RE quarantine — every
    request one terminal response, non-degraded scores bitwise arm 6,
    degraded scores bitwise the FE-only batch reference, and SIGTERM
    drains to exit 0 with zero hung futures and zero leaked
    connections.
11. **Kill-mid-publish (ISSUE 10)** — the GLM driver publishes into a
    model registry with a KILL planted at a ``registry.publish`` seam
    crossing (the stage->rename->commit protocol): after the SIGKILL
    the registry lists NOTHING (never a half-visible generation), and
    the re-run republishes a generation BITWISE equal to an
    uninterrupted publish on a twin registry.
12. **Gate refusal** — a retrain over label-flipped appended data
    fails its AUC gate against the parent generation: the driver
    exits 0 (a refusal is a terminal outcome, not a crash), the named
    verdict lands in metrics.json AND the registry's refusal record,
    and the candidate is absent from the loader listing.
13. **Post-swap auto-rollback** — the serving driver follows a
    registry (--registry-dir): generation 2 publishes and is promoted
    under live traffic; a post-swap health regression (degraded
    responses past the rollback window policy) flips serving BACK to
    generation 1 bitwise (scores equal the pre-swap clean scores),
    and the bad generation is quarantined in the registry so it is
    never re-promoted.
14. **Shard routing under fire (ISSUE 12)** — a 2-shard scatter/gather
    fleet (real serving_driver subprocesses in --shard-index mode)
    flooded through the router with a MID-FLOOD router-coordinated
    two-step generation flip, then a SIGKILL of shard 1 followed by a
    cache-missing variant flood: every request one terminal outcome,
    non-degraded routed scores bitwise the clean single-server arm on
    BOTH sides of the flip, the dead shard's entities degraded to the
    FE-only reference bitwise (its entities ONLY), and the surviving
    shard SIGTERM-drains to exit 0 with zero request-path compiles.

Every asserted invariant is printed; any failure exits non-zero.
"""

import filecmp
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GLM_PLAN_COLD = (
    "chunk_read:2:EIO,spill_write:2:EIO,spill_read:2:EIO,"
    "io_worker:1:EIO,ckpt_save:1:ENOSPC,cache_store:1:EIO"
)
GLM_PLAN_WARM = "cache_load:1:EIO,cache_load:3:CORRUPT"
GAME_PLAN = (
    "chunk_read:3:EIO,spill_write:4:EIO,spill_read:3:EIO,"
    "ckpt_save:2:ENOSPC"
)
SERVING_PLAN = "serving.model_load:1:EIO"
# crossing 1 = the initial bank load (clean), crossing 2 = the hot-swap
# staging read (corrupted -> quarantine + rollback)
SERVING_SWAP_PLAN = "serving.model_load:2:CORRUPT"


def log(msg):
    print(f"[chaos] {msg}", flush=True)


def run(cmd, stdin_text=None, **env):
    e = {**os.environ, "JAX_PLATFORMS": "cpu",
         "PHOTON_RETRY_BASE_S": "0.002", **env}
    r = subprocess.run(
        cmd, cwd=REPO, env=e, capture_output=True, text=True, timeout=900,
        input=stdin_text,
    )
    if r.returncode != 0:
        sys.exit(
            f"[chaos] FAILED: {' '.join(cmd)}\n--- stdout\n"
            f"{r.stdout[-4000:]}\n--- stderr\n{r.stderr[-4000:]}"
        )
    return r


# -- synthetic data -----------------------------------------------------------


def gen_glm_data(train_dir, *, n_files=3, rows=400, d=40, k=8, seed=0):
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container

    rng = np.random.default_rng(seed)
    os.makedirs(train_dir, exist_ok=True)
    w = rng.normal(size=d) * 0.5
    for fi in range(n_files):
        recs = []
        for i in range(rows):
            ix = rng.integers(0, d, size=k)
            vs = rng.normal(size=k)
            z = float((w[ix] * vs).sum())
            recs.append({
                "uid": f"{fi}-{i}",
                "label": float(1 / (1 + np.exp(-z)) > rng.uniform()),
                "features": [
                    {"name": str(int(j)), "term": "", "value": float(v)}
                    for j, v in zip(ix, vs)
                ],
                "offset": 0.0,
                "weight": 1.0,
            })
        write_container(
            os.path.join(train_dir, f"part-{fi:03d}.avro"),
            schemas.TRAINING_EXAMPLE_AVRO, recs,
        )


def gen_game_data(train_dir, *, n_files=3, rows=150, n_users=8, d_g=5,
                  d_u=3, seed=0):
    from photon_ml_tpu.io import schemas
    from photon_ml_tpu.io.avro_codec import write_container

    schema = {
        "name": "GameExample", "type": "record",
        "fields": [
            {"name": "uid", "type": ["null", "string"], "default": None},
            {"name": "response", "type": "double"},
            {"name": "metadataMap",
             "type": ["null", {"type": "map", "values": "string"}],
             "default": None},
            {"name": "features",
             "type": {"type": "array", "items": schemas.FEATURE_AVRO}},
            {"name": "userFeatures",
             "type": {"type": "array", "items": "FeatureAvro"}},
        ],
    }
    rng = np.random.default_rng(seed)
    os.makedirs(train_dir, exist_ok=True)
    w_g = np.linspace(-1, 1, d_g)
    w_u = np.random.default_rng(7).normal(size=(n_users, d_u))
    for fi in range(n_files):
        recs = []
        for i in range(rows):
            u = int(rng.integers(0, n_users))
            xg = rng.normal(size=d_g)
            xu = rng.normal(size=d_u)
            z = float(xg @ w_g + xu @ w_u[u])
            recs.append({
                "uid": f"f{fi}-{i}",
                "response": float(1 / (1 + np.exp(-z)) > rng.uniform()),
                "metadataMap": {"userId": f"user{u}"},
                "features": [
                    {"name": f"g{j}", "term": "", "value": float(xg[j])}
                    for j in range(d_g)
                ],
                "userFeatures": [
                    {"name": f"u{j}", "term": "", "value": float(xu[j])}
                    for j in range(d_u)
                ],
            })
        write_container(
            os.path.join(train_dir, f"part-{fi}.avro"), schema, recs
        )


# -- assertions ---------------------------------------------------------------


def assert_trees_bitwise_equal(a, b, label):
    diffs = []

    def walk(rel):
        da, db = os.path.join(a, rel), os.path.join(b, rel)
        ents_a = sorted(os.listdir(da))
        ents_b = sorted(os.listdir(db))
        if ents_a != ents_b:
            diffs.append(f"{rel}: {ents_a} != {ents_b}")
            return
        for e in ents_a:
            r = os.path.join(rel, e) if rel else e
            if os.path.isdir(os.path.join(a, r)):
                walk(r)
            elif not filecmp.cmp(
                os.path.join(a, r), os.path.join(b, r), shallow=False
            ):
                diffs.append(r)

    walk("")
    assert not diffs, f"{label}: files differ between arms: {diffs}"
    log(f"{label}: bitwise equal")


def assert_accounting(metrics_path, plan, label):
    m = json.load(open(metrics_path))
    rel = m["reliability"]
    injected = rel["faults"]["injected"]
    retries = rel["retries"]["retries"]
    assert rel["faults"]["plan"] == plan, (rel["faults"]["plan"], plan)
    planned_seams = {e.split(":")[0] for e in plan.split(",")}
    for seam in planned_seams:
        assert injected.get(seam, 0) >= 1, (
            f"{label}: planned fault at {seam} never fired "
            f"(seam not crossed?): injected={injected}"
        )
    # every transient (EIO/ENOSPC) injection must be visible as a retry
    transient = {
        e.split(":")[0] for e in plan.split(",")
        if e.split(":")[2] != "CORRUPT"
    }
    for seam in transient:
        assert retries.get(seam, 0) >= 1, (
            f"{label}: injected transient fault at {seam} not retried: "
            f"{retries}"
        )
    log(f"{label}: accounting OK — injected={injected} retries={retries}")
    return m


# -- arms ---------------------------------------------------------------------


def glm_args(train, out, ckpt, cache, plan=None):
    args = [
        sys.executable, "-m", "photon_ml_tpu.cli.glm_driver",
        "--training-data-directory", train,
        "--output-directory", out,
        "--task", "LOGISTIC_REGRESSION",
        "--regularization-weights", "10,1,0.1",
        "--num-iterations", "12",
        "--streaming", "true",
        "--stream-memory-budget", str(64 << 10),
        "--kernel", "tiled",
        "--tile-cache-dir", cache,
        "--checkpoint-dir", ckpt,
        "--summarization-output-dir", os.path.join(out, "summary"),
        "--normalization-type", "STANDARDIZATION",
        "--delete-output-dirs-if-exist", "true",
    ]
    if plan:
        args += ["--fault-plan", plan]
    return args


def game_args(train, out, ckpt, plan=None):
    args = [
        sys.executable, "-m", "photon_ml_tpu.cli.game_training_driver",
        "--train-input-dirs", train,
        "--output-dir", out,
        "--task-type", "LOGISTIC_REGRESSION",
        "--feature-shard-id-to-feature-section-keys-map",
        "globalShard:features|userShard:userFeatures",
        "--fixed-effect-data-configurations",
        "global:globalShard,1",
        "--fixed-effect-optimization-configurations",
        "global:20,1e-6,0.5,1,TRON,L2",
        "--random-effect-data-configurations",
        "per-user:userId,userShard,1,none,none,none,identity",
        "--random-effect-optimization-configurations",
        "per-user:20,1e-6,1.0,1,LBFGS,L2",
        "--num-iterations", "2",
        "--streaming", "true",
        "--stream-memory-budget", str(64 << 10),
        "--checkpoint-dir", ckpt,
        "--delete-output-dir-if-exists", "true",
    ]
    if plan:
        args += ["--fault-plan", plan]
    return args


def serving_args(train, model_dir, out, plan=None, swap_dir=None):
    args = [
        sys.executable, "-m", "photon_ml_tpu.cli.serving_driver",
        "--game-model-input-dir", model_dir,
        "--request-paths", train,
        "--output-dir", out,
        "--task-type", "LOGISTIC_REGRESSION",
        "--feature-shard-id-to-feature-section-keys-map",
        "globalShard:features|userShard:userFeatures",
        "--mode", "open",
        "--concurrency", "4",
        "--delete-output-dir-if-exists", "true",
    ]
    if swap_dir:
        args += ["--swap-model-dir", swap_dir,
                 "--swap-after-requests", "50"]
    if plan:
        args += ["--fault-plan", plan]
    return args


# -- serving-under-fire arms (ISSUE 8) ---------------------------------------

FRONTEND_PLAN = "serving.frontend.read:5:EIO,serving.dispatch:3:EIO"


def write_name_term_lists(nt_dir):
    """Prebuilt feature vocabularies for the stdin/front-end request
    sources (a request stream has no dataset to build maps from)."""
    from photon_ml_tpu.io.name_term_list import (
        save_name_and_term_feature_sets,
    )

    save_name_and_term_feature_sets(
        {
            "features": {f"g{j}\t" for j in range(5)},
            "userFeatures": {f"u{j}\t" for j in range(3)},
        },
        nt_dir,
    )


def trace_json_records(train_dir):
    from photon_ml_tpu.io.avro_codec import read_avro_records

    return [
        {
            k: r[k]
            for k in ("uid", "response", "metadataMap", "features",
                      "userFeatures")
        }
        for r in read_avro_records(train_dir)
    ]


def scores_by_uid(scores_dir):
    from photon_ml_tpu.io.avro_codec import read_avro_records

    return {
        r["uid"]: r["predictionScore"]
        for r in read_avro_records(scores_dir)
    }


def fe_only_model_copy(model_dir, dst):
    """The batch scorer's FE-only path, as an artifact: the same model
    with its random-effect coordinates removed."""
    shutil.copytree(model_dir, dst)
    shutil.rmtree(os.path.join(dst, "random-effect"))
    return dst


def stream_serving_args(model_dir, out, nt_dir):
    return [
        sys.executable, "-m", "photon_ml_tpu.cli.serving_driver",
        "--game-model-input-dir", model_dir,
        "--output-dir", out,
        "--feature-shard-id-to-feature-section-keys-map",
        "globalShard:features|userShard:userFeatures",
        "--feature-name-and-term-set-path", nt_dir,
        "--request-nnz-width", "globalShard:6|userShard:4",
        "--ladder", "1,8,64",
        "--delete-output-dir-if-exists", "true",
    ]


def serving_overload_arm(base, game_train, model_dir, nt_dir, clean_scores):
    """Arm 9: deadline-mixed stdin replay — exact outcome conservation,
    dropped-before-dispatch, bitwise-subset scores."""
    out = os.path.join(base, "serving-stdin-overload")
    records = trace_json_records(game_train)
    lines = []
    expired_uids = set()
    for i, obj in enumerate(records):
        if i % 3 == 2:
            # an already-expired client deadline: admission accepts it
            # (empty-queue prediction is 0) and the dispatcher MUST
            # drop it before the device sees it
            obj = {**obj, "deadline_ms": 1e-4}
            expired_uids.add(obj["uid"])
        lines.append(json.dumps(obj))
    args = stream_serving_args(model_dir, out, nt_dir)
    args += ["--request-paths", "-"]
    run(args, stdin_text="\n".join(lines) + "\n")
    log("serving overload (stdin deadlines) arm completed")
    m = json.load(open(os.path.join(out, "metrics.json")))
    outcomes = m["outcomes"]
    assert outcomes.get("deadline_exceeded", 0) == len(expired_uids), (
        outcomes, len(expired_uids)
    )
    assert outcomes.get("ok", 0) == len(records) - len(expired_uids), (
        outcomes
    )
    assert sum(outcomes.values()) == len(records), outcomes
    assert m["serving"]["deadline_expired"] == len(expired_uids)
    assert m["interrupted"] is False
    got = scores_by_uid(os.path.join(out, "scores"))
    assert set(got) == set(clean_scores) - expired_uids, (
        "admitted set must be exactly the non-expired trace rows"
    )
    mismatched = [u for u, s in got.items() if s != clean_scores[u]]
    assert not mismatched, f"admitted scores differ: {mismatched[:5]}"
    log(
        f"serving overload: {outcomes['ok']} ok bitwise-equal clean arm, "
        f"{outcomes['deadline_exceeded']} dropped before dispatch, "
        "outcomes conserved"
    )


class _Wire:
    """One JSON-lines client connection for the front-end arm."""

    def __init__(self, port, timeout=60.0):
        self.sock = socket.create_connection(
            ("127.0.0.1", port), timeout=timeout
        )
        self.reader = self.sock.makefile("rb")

    def send(self, obj_or_bytes):
        data = (
            obj_or_bytes if isinstance(obj_or_bytes, bytes)
            else (json.dumps(obj_or_bytes) + "\n").encode()
        )
        self.sock.sendall(data)

    def recv(self):
        line = self.reader.readline()
        return json.loads(line) if line else None

    def ask(self, obj):
        self.send(obj)
        return self.recv()

    def close(self):
        try:
            self.reader.close()
            self.sock.close()
        except OSError:
            pass


def frontend_under_fire_arm(
    base, game_train, model_dir, nt_dir, clean_scores, fe_scores
):
    """Arm 10: the TCP front-end under flood + faults + mid-flood swap
    + deadline drops + malformed/slow clients + RE quarantine, then a
    SIGTERM drain. See the module docstring for the invariants."""
    out = os.path.join(base, "serving-frontend-out")
    swap_copy = os.path.join(base, "frontend-swap-gen2")
    shutil.copytree(model_dir, swap_copy)
    args = stream_serving_args(model_dir, out, nt_dir) + [
        "--frontend-port", "0",
        "--drain-timeout", "20",
        "--swap-model-dir", swap_copy,
        "--swap-after-requests", "30",
        "--fault-plan", FRONTEND_PLAN,
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PHOTON_RETRY_BASE_S": "0.002"}
    proc = subprocess.Popen(
        args, cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        fj = os.path.join(out, "frontend.json")
        deadline = time.time() + 240
        while not os.path.exists(fj):
            assert proc.poll() is None, proc.communicate()[0][-4000:]
            assert time.time() < deadline, "front-end never came up"
            time.sleep(0.1)
        port = json.load(open(fj))["port"]

        records = trace_json_records(game_train)[:150]
        # line 5 (1-based) takes the planned read fault; every 10th
        # record (offset 7) carries an expired deadline
        fault_idx = 4
        deadline_idx = {
            i for i in range(len(records)) if i % 10 == 7
        } - {fault_idx}

        main_c = _Wire(port)
        n_ok = 0
        generations = set()
        for i, rec in enumerate(records):
            obj = (
                {**rec, "deadline_ms": 1e-4} if i in deadline_idx else rec
            )
            resp = main_c.ask(obj)
            if i == fault_idx:
                assert resp["status"] == "error", (i, resp)
                assert resp["error"] == "READ_FAULT", resp
            elif i in deadline_idx:
                assert resp["status"] == "deadline_exceeded", (i, resp)
            else:
                assert resp["status"] == "ok", (i, resp)
                assert resp["degraded"] is False, resp
                assert resp["score"] == clean_scores[rec["uid"]], (
                    i, resp["score"], clean_scores[rec["uid"]],
                )
                generations.add(resp["generation"])
                n_ok += 1
        log(
            f"frontend flood: {n_ok} ok bitwise-equal clean arm, "
            f"{len(deadline_idx)} deadline drops, 1 read fault, "
            f"generations {sorted(generations)}"
        )

        # the mid-flood swap ran in the background; wait for the flip,
        # then prove post-swap traffic is still bitwise (donated,
        # same-content generation 2)
        deadline = time.time() + 60
        while True:
            status = main_c.ask({"op": "status"})
            if status["generation"] == 2:
                break
            assert time.time() < deadline, (
                f"mid-flood swap never landed: {status}"
            )
            time.sleep(0.1)
        for rec in records[:3]:
            resp = main_c.ask(rec)
            assert resp["status"] == "ok" and resp["generation"] == 2
            assert resp["score"] == clean_scores[rec["uid"]], resp
        log("mid-flood swap: generation 2 serving, scores still bitwise")

        # malformed client: named error, no crash
        bad_c = _Wire(port)
        resp = bad_c.ask(b"this is not json\n")
        assert resp["status"] == "error" and resp["error"] == "BAD_REQUEST"
        bad_c.close()

        # slow client: half a line, never completed — must not wedge
        # the drain below
        slow_c = _Wire(port)
        slow_c.send(b'{"uid": "stalled')

        # operator quarantine: degraded responses, bitwise the batch
        # scorer's FE-only path
        resp = main_c.ask({"op": "quarantine_re", "re_type": "userId"})
        assert resp["status"] == "ok", resp
        degraded_uids = []
        for rec in records[:10]:
            resp = main_c.ask(rec)
            assert resp["status"] == "ok" and resp["degraded"] is True
            assert resp["score"] == fe_scores[rec["uid"]], (
                resp["score"], fe_scores[rec["uid"]],
            )
            degraded_uids.append(rec["uid"])
        log(
            f"quarantined RE: {len(degraded_uids)} degraded responses "
            "bitwise-equal the FE-only batch reference"
        )

        # SIGTERM: drained exit 0, zero hung futures, zero leaks
        proc.send_signal(signal.SIGTERM)
        stdout, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0, stdout[-4000:]
        assert main_c.recv() is None, "client must observe EOF"
        main_c.close()
        slow_c.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=60)

    m = json.load(open(os.path.join(out, "metrics.json")))
    assert m["interrupted"] is True
    assert m["leaked_connections"] == 0, m["leaked_connections"]
    assert m["drain"]["timed_out"] is False, m["drain"]
    srv = m["serving"]
    assert srv["frontend"]["malformed"] >= 1
    assert srv["frontend"]["read_faults"] == 1
    assert srv["frontend"]["connections_opened"] >= 3
    assert srv["deadline_expired"] == len(deadline_idx)
    assert srv["degraded_responses"] == len(degraded_uids)
    swaps = m["swap_history"]
    assert len(swaps) == 1 and swaps[0]["ok"] and swaps[0]["donated"], swaps
    rel = m["reliability"]
    assert rel["faults"]["injected"].get("serving.frontend.read", 0) == 1
    assert rel["faults"]["injected"].get("serving.dispatch", 0) >= 1
    assert rel["retries"]["retries"].get("serving.dispatch", 0) >= 1, (
        "the injected dispatch fault must be absorbed by a retry"
    )
    log(
        "frontend under fire: SIGTERM drained exit 0, 0 hung futures, "
        "0 leaked connections, dispatch fault retried bitwise, "
        "accounting complete"
    )


# -- planet-scale serving arm (ISSUE 12) -------------------------------------


def shard_routing_arm(
    base, game_train, model_dir, fe_model, nt_dir, clean_scores
):
    """Arm 14: scatter/gather routing under fire — a 2-shard fleet
    (real serving_driver subprocesses, each holding 1/2 of the RE
    banks) flooded through the router from concurrent threads, with a
    mid-flood router-coordinated TWO-STEP generation swap and a
    mid-flood SIGKILL of shard 1. Invariants:

    - every routed request reaches exactly one terminal outcome
      (conserved; 0 hung futures);
    - admitted NON-degraded scores are bitwise the clean single-server
      arm's — across BOTH generations of the swap (the staged gen-2 is
      a byte-copy, so bitwise equality must hold on either side of the
      flip and a mixed-generation gather would still be caught by the
      router's consistency check);
    - photon-wire leg (ISSUE 17): the flood rides the NEGOTIATED
      binary data plane (router wire="binary" against real subprocess
      shards), and a JSON-pinned cross-check router first reproduces
      the same reference bitwise — so binary == JSON == single-server
      scorer holds across the mid-flood flip and the SIGKILL;
    - after the SIGKILL, shard 1's entities answer DEGRADED with the
      FE-only reference score bitwise — shard 0's entities stay exact;
    - the surviving shard SIGTERM-drains to exit 0 with zero cold
      (request-path) compiles.

    Observability leg (ISSUE 13): every fleet process runs with
    --obs-dir, and the arm asserts each process's FLIGHT RECORDER
    captured the injected sequence in order — the SIGKILLed shard's
    auto-dumped ring shows stage -> commit (persisted at the
    transition, so it survives the uncatchable kill), the surviving
    shard's drain dump shows the same order plus a conservation
    verdict that holds ACROSS the mid-flood swap (admitted == terminal
    with terminals split over BOTH generations), and the router
    process's own recorder shows the fleet commit BEFORE the circuit
    breaker opened on the killed shard.
    """
    import threading

    from photon_ml_tpu.game.model_io import load_game_model
    from photon_ml_tpu.game.config import FeatureShardConfiguration
    from photon_ml_tpu.serving import (
        RoutingPolicy,
        ServingError,
        ShardRouter,
    )
    from photon_ml_tpu import ownership

    records = trace_json_records(game_train)
    swap_copy = os.path.join(base, "routing-swap-gen2")
    shutil.copytree(model_dir, swap_copy)
    # the post-SIGKILL flood uses a VARIANT trace (same entities,
    # deterministically perturbed feature values): its records miss the
    # hot-entity cache by construction, so the dead shard's entities
    # must go to the wire and degrade. References come from the same
    # single-server stdin path the other serving arms gate against.
    variants = []
    for r in records:
        v = json.loads(json.dumps(r))
        for bag in ("features", "userFeatures"):
            for f in v.get(bag) or []:
                f["value"] = float(f["value"]) * 1.25 + 0.125
        variants.append(v)

    def stdin_reference(md, out):
        lines = "\n".join(json.dumps(v) for v in variants) + "\n"
        run(
            stream_serving_args(md, out, nt_dir)
            + ["--request-paths", "-"],
            stdin_text=lines,
        )
        return scores_by_uid(os.path.join(out, "scores"))

    var_clean = stdin_reference(
        model_dir, os.path.join(base, "routing-var-clean")
    )
    var_fe = stdin_reference(
        fe_model, os.path.join(base, "routing-var-fe")
    )
    shard_cfgs = [
        FeatureShardConfiguration("globalShard", ["features"]),
        FeatureShardConfiguration("userShard", ["userFeatures"]),
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # router-process flight recorder: reset so the sequence assertions
    # below read THIS arm's transitions, not an earlier arm's
    from photon_ml_tpu.obs.flight_recorder import reset_flight_recorder
    from photon_ml_tpu.obs.trace import set_tracing, tracer

    router_recorder = reset_flight_recorder()
    # fleet-obs leg (ISSUE 15): the router process traces its own
    # spans while the live collector drains both shard subprocesses'
    # rings incrementally — merged + verified at the end of the arm
    set_tracing(True)
    tracer().clear()
    procs = []
    for s in range(2):
        out = os.path.join(base, f"routing-shard{s}")
        procs.append((out, subprocess.Popen(
            stream_serving_args(model_dir, out, nt_dir) + [
                "--frontend-port", "0",
                "--shard-index", str(s), "--shard-count", "2",
                "--obs-dir", os.path.join(out, "obs"),
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )))
    try:
        ports = []
        for out, p in procs:
            fj = os.path.join(out, "frontend.json")
            deadline = time.time() + 180
            while not os.path.exists(fj):
                assert p.poll() is None, p.stdout.read()[-3000:]
                assert time.time() < deadline, "shard boot timeout"
                time.sleep(0.2)
            meta = json.load(open(fj))
            ports.append(meta["port"])
            assert meta["shard"]["shard_index"] == len(ports) - 1
            assert meta["shard"]["rule"] == "entity_code % num_shards"
        loaded = load_game_model(model_dir)
        (_rt, _sid, per_entity), = loaded.random_effects.values()
        ids = sorted(per_entity)
        router = ShardRouter(
            [("127.0.0.1", pt) for pt in ports],
            entity_ids={"userId": ids},
            shard_configs=shard_cfgs,
            policy=RoutingPolicy(subrequest_timeout_s=5.0),
            wire="binary",
        )
        info = router.connect()
        # -- photon-wire leg (ISSUE 17): the whole flood below rides
        # the negotiated BINARY data plane against real subprocess
        # shards; a JSON-pinned router first reproduces the
        # single-server reference bitwise, so binary == JSON == batch
        # scorer transitively (phase 1 pins the binary side)
        assert info["wire"] == "binary", info
        assert router.status()["wire"]["negotiated"] == "binary", (
            router.status()["wire"]
        )
        router_json = ShardRouter(
            [("127.0.0.1", pt) for pt in ports],
            entity_ids={"userId": ids},
            shard_configs=shard_cfgs,
            policy=RoutingPolicy(subrequest_timeout_s=5.0),
            wire="json",
        )
        assert router_json.connect()["wire"] == "json"
        try:
            for rec in records:
                j = float(router_json.score_record(rec))
                assert j == clean_scores[rec["uid"]], (
                    rec["uid"], j, clean_scores[rec["uid"]],
                )
        finally:
            router_json.close()
        from photon_ml_tpu.obs.fleet import (
            FleetCollector,
            fleet_check_conservation,
            verify_fleet_trace,
        )

        # the collector drains both subprocess rings over BINARY
        # framing (MSG_TRACE_RESPONSE) — the chaos twin of the bench's
        # trace-drain leg, across a mid-flood swap + SIGKILL
        collector = FleetCollector(
            [
                ("shard0", "127.0.0.1", ports[0]),
                ("shard1", "127.0.0.1", ports[1]),
            ],
            local_name="router",
            poll_s=0.5,
            connect_timeout_s=15.0,
            wire="binary",
        ).start()
        owners = {
            r["uid"]: ownership.owner_of(
                ids.index((r.get("metadataMap") or {}).get("userId")), 2
            )
            for r in records
            if (r.get("metadataMap") or {}).get("userId") in ids
        }

        def flood(recs, passes):
            """Concurrent replay; returns (uid, outcome, score,
            degraded, generation) per request."""
            results = []
            res_lock = threading.Lock()
            it = iter([rec for _p in range(passes) for rec in recs])
            it_lock = threading.Lock()

            def worker():
                while True:
                    with it_lock:
                        rec = next(it, None)
                    if rec is None:
                        return
                    try:
                        out = router.score_record(rec)
                        entry = (rec["uid"], "ok", float(out),
                                 out.degraded, out.generation)
                    except ServingError as e:
                        entry = (rec["uid"], f"error:{e.code}", None,
                                 False, None)
                    with res_lock:
                        results.append(entry)

            threads = [
                threading.Thread(target=worker) for _ in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(results) == passes * len(recs), (
                len(results), passes * len(recs),
            )
            return results

        # -- phase 1: flood with a MID-FLOOD two-step swap: a swapper
        # thread stages + commits generation 2 on both shards while 4
        # workers keep scoring — in-flight gathers straddle the commit
        # wave (the router's consistency check re-scatters them) and
        # every score must stay bitwise the clean arm's on BOTH sides
        # of the flip
        swap_result = {}

        def swapper():
            swap_result.update(router.coordinate_swap(swap_copy))

        sw = threading.Thread(target=swapper)
        sw.start()
        # keep flooding until the swap lands, then one full pass more:
        # staging two real shard processes takes seconds, and the flood
        # must genuinely straddle the commit wave
        phase1 = []
        post_swap_passes = 0
        for _pass in range(500):
            swap_done_before = bool(swap_result)
            phase1 += flood(records, passes=1)
            if swap_done_before:
                post_swap_passes += 1
                if post_swap_passes >= 1:
                    break
        sw.join()
        assert swap_result.get("ok"), swap_result
        assert swap_result["generation"] == 2, swap_result
        gens = {g for _u, _o, _s, _d, g in phase1}
        assert gens >= {1, 2}, (
            f"the flood must straddle the two-step flip, saw {gens}"
        )
        for uid, outcome, score, degraded, _gen in phase1:
            assert outcome == "ok", (uid, outcome)
            assert not degraded, (uid, "no shard is down yet")
            assert score == clean_scores[uid], (
                uid, score, clean_scores[uid]
            )
        # -- phase 2: SIGKILL shard 1, then flood a VARIANT trace
        # (same entities, perturbed features -> cache misses by
        # construction): shard 1's entities MUST degrade to the
        # FE-only variant reference bitwise; shard 0's stay exact
        procs[1][1].send_signal(signal.SIGKILL)
        procs[1][1].wait(timeout=60)
        phase2 = flood(variants, passes=1)
        n_exact = n_deg = 0
        for uid, outcome, score, degraded, gen in phase2:
            assert outcome == "ok", (uid, outcome)
            assert gen == 2, (uid, gen)
            if degraded:
                n_deg += 1
                assert owners.get(uid) == 1, (
                    f"{uid}: only the SIGKILLed shard's entities may "
                    "degrade"
                )
                assert score == var_fe[uid], (uid, score, var_fe[uid])
            else:
                n_exact += 1
                assert owners.get(uid) != 1, (
                    f"{uid}: a dead shard's entity cannot score exact "
                    "without its bank"
                )
                assert score == var_clean[uid], (
                    uid, score, var_clean[uid]
                )
        assert n_deg > 0, "SIGKILL produced no degraded outcomes"
        assert n_exact > 0, "the surviving shard must keep scoring"
        n_ok = len(phase1) + n_exact
        # -- observability: the SIGKILLed shard's flight recorder was
        # auto-dumped at its swap transitions, so the ring SURVIVES the
        # uncatchable kill: complete JSON (atomic write — never torn),
        # stage -> commit in sequence order
        kill_dump = json.load(open(
            os.path.join(procs[1][0], "obs", "flight.json")
        ))
        swap_kinds = [
            e["kind"] for e in kill_dump["events"]
            if e["kind"].startswith("swap.")
        ]
        assert swap_kinds == ["swap.stage", "swap.commit"], swap_kinds
        seqs = [e["seq"] for e in kill_dump["events"]]
        assert seqs == sorted(seqs), seqs
        # -- fleet observability (ISSUE 15): stop the live collector
        # (one final drain poll against the survivor), merge all three
        # processes into ONE skew-corrected timeline, and verify the
        # stitching contract: every router sub-request parents under
        # its router request, every shard frontend span joins its
        # sub-request, every serving.score leaf joins its shard's
        # dispatch span, timestamps monotone parent->child within the
        # recorded clock-sync uncertainty. The SIGKILLed shard's spans
        # survive in the COLLECTOR (polled before the kill).
        collector.stop(final_poll=True)
        fleet_flight = collector.collect_flight()
        stitched = collector.stitched_spans()
        verdict = verify_fleet_trace(stitched)
        assert verdict["ok"], verdict["violations"][:5]
        assert verdict["router_subrequests"] > 0, verdict
        assert verdict["frontend_requests"] > 0, verdict
        assert verdict["score_leaves"] > 0, verdict
        assert {s["member"] for s in stitched} == {
            "router", "shard0", "shard1",
        }
        status = collector.member_status()
        assert status["shard1"]["spans"] > 0, (
            "the SIGKILLed shard's pre-kill spans must survive in the "
            "collector"
        )
        fleet_trace = os.path.join(base, "fleet_trace.json")
        n_events = collector.export(fleet_trace)
        assert n_events > 0
        # fleet conservation ACROSS the mid-flood two-step swap + the
        # SIGKILL: router admitted == Σ shard-attributed + router-local
        # outcomes; the survivor's live book joins exactly, the killed
        # shard's last-transition snapshot joins advisorily
        assert fleet_flight["shard0"]["complete"]
        assert not fleet_flight["shard1"]["complete"]
        fleet_cons = fleet_check_conservation(
            router_recorder.check_conservation(),
            {
                name: {
                    "conservation": fleet_flight[name].get(
                        "conservation"
                    ) or {},
                    "complete": fleet_flight[name]["complete"],
                    "shard_indices": [i],
                }
                for i, name in enumerate(("shard0", "shard1"))
            },
        )
        assert fleet_cons["ok"], fleet_cons
        assert set(fleet_cons["terminal_by_generation"]) >= {"1", "2"}, (
            fleet_cons
        )
        assert fleet_cons["terminal_by_attribution"].get(
            "degraded", 0
        ) >= n_deg, fleet_cons
        assert fleet_cons["shards"]["shard0"]["join_ok"] is True
        assert fleet_cons["shards"]["shard1"]["join_ok"] is None
        # surviving shard drains clean with 0 request-path compiles
        procs[0][1].send_signal(signal.SIGTERM)
        stdout, _ = procs[0][1].communicate(timeout=120)
        assert procs[0][1].returncode == 0, stdout[-3000:]
        m = json.load(open(os.path.join(procs[0][0], "metrics.json")))
        assert m["programs"]["cold_dispatch_compiles"] == 0
        assert m["leaked_connections"] == 0
        # -- observability: the surviving shard's drain dump shows the
        # same ordered two-step flip, and conservation holds ACROSS the
        # mid-flood swap — every admitted request reached exactly one
        # terminal outcome, split over BOTH generations
        cons = m["obs"]["conservation"]
        assert cons["ok"], cons
        assert set(cons["terminal_by_generation"]) >= {"1", "2"}, cons
        drain_dump = json.load(open(
            os.path.join(procs[0][0], "obs", "flight.json")
        ))
        swap_kinds = [
            e["kind"] for e in drain_dump["events"]
            if e["kind"].startswith("swap.")
        ]
        assert swap_kinds == ["swap.stage", "swap.commit"], swap_kinds
        assert os.path.exists(
            os.path.join(procs[0][0], "obs", "trace.json")
        )
        # -- observability: the router process's own ring orders the
        # fleet commit BEFORE the breaker opened on the killed shard
        router_events = router_recorder.events()
        kinds = [e["kind"] for e in router_events]
        assert "swap.fleet_commit" in kinds, kinds
        assert "circuit.open" in kinds, kinds
        assert (
            kinds.index("swap.fleet_commit") < kinds.index("circuit.open")
        ), kinds
        opened = [
            e for e in router_events if e["kind"] == "circuit.open"
        ]
        assert all(e["fields"]["shard"] == 1 for e in opened), opened
        log(
            f"shard routing: {n_ok} exact bitwise clean arm across "
            f"generations {sorted(g for g in gens if g)} (two-step "
            f"flip mid-flood), {n_deg} degraded bitwise FE-only after "
            "SIGKILL, outcomes conserved, surviving shard drained "
            "exit 0; flood rode the NEGOTIATED binary wire (JSON "
            "cross-check router bitwise-equal first), collector "
            "drained both rings over binary framing; flight recorders "
            "of all 3 processes captured "
            "stage->commit->kill->circuit-open in order, conservation "
            "held across the swap; fleet collector merged "
            f"{n_events} trace event(s) from all 3 processes into "
            "fleet_trace.json (nesting + skew verified) and "
            "fleet-wide conservation balanced router-admitted == "
            "Σ shard-attributed + router-local across swap + SIGKILL"
        )
    finally:
        set_tracing(False)
        for _out, p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate(timeout=60)


# -- continuous-retraining arms (ISSUE 10) ------------------------------------


def run_allow_kill(cmd, **env):
    """Like run(), but a SIGKILL exit (the planted registry KILL) is an
    expected outcome; any OTHER failure still aborts the matrix."""
    e = {**os.environ, "JAX_PLATFORMS": "cpu",
         "PHOTON_RETRY_BASE_S": "0.002", **env}
    r = subprocess.run(
        cmd, cwd=REPO, env=e, capture_output=True, text=True, timeout=900
    )
    if r.returncode not in (0, -9):
        sys.exit(
            f"[chaos] FAILED: {' '.join(cmd)} (rc={r.returncode})\n"
            f"--- stdout\n{r.stdout[-4000:]}\n--- stderr\n{r.stderr[-4000:]}"
        )
    return r


def glm_publish_args(train, val, out, registry, plan=None, extra=()):
    args = [
        sys.executable, "-m", "photon_ml_tpu.cli.glm_driver",
        "--training-data-directory", train,
        "--output-directory", out,
        "--validating-data-directory", val,
        "--task", "LOGISTIC_REGRESSION",
        "--regularization-weights", "1.0",
        "--num-iterations", "12",
        "--streaming", "true",
        "--retrain-from", registry,
        "--publish-registry", registry,
        "--gate-max-auc-drop", "0.5",
        "--delete-output-dirs-if-exist", "true",
        *extra,
    ]
    if plan:
        args += ["--fault-plan", plan]
    return args


def registry_generations(registry_dir):
    from photon_ml_tpu.registry import ModelRegistry

    return [g.generation for g in ModelRegistry(registry_dir).list_generations()]


def _retrain_val_dir(base):
    """Holdout for the retrain arms: SAME true model as gen_glm_data's
    training draw (w comes from seed 0), fresh example noise — the
    gates compare candidate vs parent on data they can both predict."""
    val = os.path.join(base, "glm-val")
    if os.path.isdir(val):
        return val
    import numpy as _np
    from photon_ml_tpu.io import schemas as _schemas
    from photon_ml_tpu.io.avro_codec import write_container as _wc

    d, k = 40, 8
    w = _np.random.default_rng(0).normal(size=d) * 0.5
    rng = _np.random.default_rng(11)
    recs = []
    for i in range(1200):
        ix = rng.integers(0, d, size=k)
        vs = rng.normal(size=k)
        z = float((w[ix] * vs).sum())
        recs.append({
            "uid": f"val-{i}",
            "label": float(1 / (1 + _np.exp(-z)) > rng.uniform()),
            "features": [
                {"name": str(int(j)), "term": "", "value": float(v)}
                for j, v in zip(ix, vs)
            ],
            "offset": 0.0, "weight": 1.0,
        })
    os.makedirs(val)
    _wc(os.path.join(val, "part-000.avro"),
        _schemas.TRAINING_EXAMPLE_AVRO, recs)
    return val


def kill_mid_publish_arm(base, glm_train):
    """Arm 11: KILL at a registry.publish crossing -> nothing visible;
    resume -> bitwise the uninterrupted publish."""
    val = _retrain_val_dir(base)
    reg_ref = os.path.join(base, "retrain-reg-ref")
    reg_kill = os.path.join(base, "retrain-reg-kill")
    run(glm_publish_args(glm_train, val, os.path.join(base, "pub-ref"),
                         reg_ref))
    assert registry_generations(reg_ref) == [1]
    # crossing 3 is the staging->final rename: the worst place to die
    r = run_allow_kill(
        glm_publish_args(glm_train, val, os.path.join(base, "pub-kill"),
                         reg_kill, plan="registry.publish:3:KILL")
    )
    assert r.returncode == -9, "planned KILL never fired"
    assert registry_generations(reg_kill) == [], (
        "a killed publish left a visible generation"
    )
    log("kill-mid-publish: SIGKILL at the rename crossing, registry empty")
    run(glm_publish_args(glm_train, val, os.path.join(base, "pub-resume"),
                         reg_kill))
    assert registry_generations(reg_kill) == [1]
    assert_trees_bitwise_equal(
        os.path.join(reg_ref, "generations", "g000001"),
        os.path.join(reg_kill, "generations", "g000001"),
        "kill-mid-publish resumed generation",
    )


def gate_refusal_arm(base, glm_train):
    """Arm 12: poisoned retrain -> named verdict, candidate never
    loadable, exit 0."""
    val = _retrain_val_dir(base)
    reg = os.path.join(base, "retrain-reg-gate")
    train = os.path.join(base, "glm-train-poisoned")
    shutil.copytree(glm_train, train)
    run(glm_publish_args(train, val, os.path.join(base, "gate-gen1"), reg))
    assert registry_generations(reg) == [1]
    # poison: a flood of label-flipped rows swamps the signal
    import numpy as _np
    from photon_ml_tpu.io import schemas as _schemas
    from photon_ml_tpu.io.avro_codec import write_container as _wc

    rng = _np.random.default_rng(3)
    d, k = 40, 8
    w = _np.random.default_rng(0).normal(size=d) * 0.5  # gen_glm_data's w
    recs = []
    for i in range(3000):
        ix = rng.integers(0, d, size=k)
        vs = rng.normal(size=k)
        z = float((-w[ix] * vs).sum())  # FLIPPED signal
        recs.append({
            "uid": f"poison-{i}",
            "label": float(1 / (1 + _np.exp(-z)) > rng.uniform()),
            "features": [
                {"name": str(int(j)), "term": "", "value": float(v)}
                for j, v in zip(ix, vs)
            ],
            "offset": 0.0, "weight": 1.0,
        })
    _wc(os.path.join(train, "part-poison.avro"),
        _schemas.TRAINING_EXAMPLE_AVRO, recs)
    out = os.path.join(base, "gate-refused")
    run(glm_publish_args(train, val, out, reg,
                         extra=["--gate-max-auc-drop", "0.02"]))
    m = json.load(open(os.path.join(out, "metrics.json")))
    verdict = m["registry"]["gates"]["verdict"]
    assert verdict == "AUC_REGRESSION", m["registry"]["gates"]
    assert m["registry"]["published_generation"] is None
    assert registry_generations(reg) == [1], (
        "refused candidate leaked into the loader listing"
    )
    from photon_ml_tpu.registry import ModelRegistry

    refusals = ModelRegistry(reg).refused_candidates()
    assert refusals and refusals[0]["gates"]["verdict"] == "AUC_REGRESSION"
    log(
        "gate refusal: AUC_REGRESSION recorded (driver exit 0), "
        "registry still serves generation 1 only"
    )


def auto_rollback_arm(base, game_train, model_dir, nt_dir, clean_scores):
    """Arm 13: registry-following frontend promotes generation 2 under
    traffic; a degraded-response health regression auto-rolls back to
    generation 1 BITWISE and quarantines generation 2."""
    from photon_ml_tpu.registry import ModelRegistry

    reg = os.path.join(base, "serving-registry")
    registry = ModelRegistry(reg)
    registry.publish(model_dir, data_ranges={"train": "arm4"})

    out = os.path.join(base, "serving-rollback-out")
    args = [
        sys.executable, "-m", "photon_ml_tpu.cli.serving_driver",
        "--registry-dir", reg,
        "--registry-poll-s", "0.3",
        "--rollback-window", "16",
        "--rollback-min-requests", "6",
        "--rollback-max-unhealthy", "0.5",
        "--output-dir", out,
        "--feature-shard-id-to-feature-section-keys-map",
        "globalShard:features|userShard:userFeatures",
        "--feature-name-and-term-set-path", nt_dir,
        "--request-nnz-width", "globalShard:6|userShard:4",
        "--ladder", "1,8,64",
        "--frontend-port", "0",
        "--drain-timeout", "20",
        "--delete-output-dir-if-exists", "true",
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PHOTON_RETRY_BASE_S": "0.002"}
    proc = subprocess.Popen(
        args, cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    try:
        fj = os.path.join(out, "frontend.json")
        deadline = time.time() + 240
        while not os.path.exists(fj):
            assert proc.poll() is None, proc.communicate()[0][-4000:]
            assert time.time() < deadline, "front-end never came up"
            time.sleep(0.1)
        front = json.load(open(fj))
        assert front["registry"] == os.path.abspath(reg), front
        port = front["port"]
        records = trace_json_records(game_train)[:60]
        c = _Wire(port)
        for rec in records[:20]:
            resp = c.ask(rec)
            assert resp["status"] == "ok" and not resp["degraded"]
            assert resp["score"] == clean_scores[rec["uid"]], resp
        status = c.ask({"op": "status"})
        assert status["registry"]["registry_generation"] == 1

        # publish generation 2 (same scores, distinct content) and wait
        # for the watcher to promote it
        gen2_src = os.path.join(base, "rollback-gen2")
        shutil.copytree(model_dir, gen2_src)
        with open(os.path.join(gen2_src, "model-spec"), "a") as f:
            f.write("\n# generation 2\n")
        registry.publish(gen2_src, parent=1)
        deadline = time.time() + 60
        while True:
            status = c.ask({"op": "status"})
            if status["registry"]["registry_generation"] == 2:
                break
            assert time.time() < deadline, f"gen 2 never promoted: {status}"
            time.sleep(0.1)
        for rec in records[:5]:
            resp = c.ask(rec)
            assert resp["status"] == "ok"
            assert resp["score"] == clean_scores[rec["uid"]], resp
        log("auto-rollback arm: generation 2 promoted under traffic")

        # health regression: quarantine the RE bank (the degraded-rate
        # signal a broken generation produces) and drive traffic until
        # the watcher rolls back
        resp = c.ask({"op": "quarantine_re", "re_type": "userId"})
        assert resp["status"] == "ok", resp
        deadline = time.time() + 60
        i = 0
        while True:
            rec = records[i % len(records)]
            i += 1
            resp = c.ask(rec)
            assert resp["status"] == "ok", resp
            status = c.ask({"op": "status"})
            if status["registry"]["registry_generation"] == 1:
                break
            assert time.time() < deadline, (
                f"auto-rollback never fired: {status}"
            )
        assert status["registry"]["last_swap"]["action"] == "rollback"
        # post-rollback traffic scores BITWISE the parent generation,
        # not degraded (the restored bank is a clean reload)
        for rec in records[:20]:
            resp = c.ask(rec)
            assert resp["status"] == "ok" and not resp["degraded"], resp
            assert resp["score"] == clean_scores[rec["uid"]], resp
        # the bad generation is quarantined in the registry
        assert registry_generations(reg) == [1]
        assert any(
            name.startswith("g000002")
            for name in os.listdir(os.path.join(reg, "quarantine"))
        )
        log(
            "auto-rollback: degraded window tripped, serving restored "
            "to generation 1 bitwise, generation 2 quarantined"
        )
        proc.send_signal(signal.SIGTERM)
        stdout, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0, stdout[-4000:]
        c.close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=60)
    m = json.load(open(os.path.join(out, "metrics.json")))
    actions = [h["action"] for h in m["registry"]["watcher_history"]]
    assert actions == ["swap", "rollback"], actions
    assert m["leaked_connections"] == 0
    log("auto-rollback arm: watcher history = swap -> rollback, 0 leaks")


def main():
    base = tempfile.mkdtemp(prefix="photon-chaos-")
    try:
        glm_train = os.path.join(base, "glm-train")
        game_train = os.path.join(base, "game-train")
        gen_glm_data(glm_train)
        gen_game_data(game_train)
        log(f"synthetic data under {base}")

        # -- GLM arms -----------------------------------------------------
        out1 = os.path.join(base, "glm-out-clean")
        out2 = os.path.join(base, "glm-out-faulted")
        out3 = os.path.join(base, "glm-out-warm")
        run(glm_args(glm_train, out1, os.path.join(base, "glm-ck1"),
                     os.path.join(base, "glm-cache1")))
        log("GLM clean arm completed")
        run(glm_args(glm_train, out2, os.path.join(base, "glm-ck2"),
                     os.path.join(base, "glm-cache2"), plan=GLM_PLAN_COLD))
        log("GLM faulted (cold-cache) arm completed")
        assert_accounting(
            os.path.join(out2, "metrics.json"), GLM_PLAN_COLD, "GLM cold"
        )
        for sub in ("models-text", "models"):
            # (no validate dir in the chaos arms, so there is no
            # best-model tree; the full grid's models ARE the output)
            assert_trees_bitwise_equal(
                os.path.join(out1, sub), os.path.join(out2, sub),
                f"GLM {sub}",
            )
        # warm arm: rerun over arm 2's populated tile cache with a
        # transient + a corrupting cache_load fault
        run(glm_args(glm_train, out3, os.path.join(base, "glm-ck3"),
                     os.path.join(base, "glm-cache2"), plan=GLM_PLAN_WARM))
        log("GLM faulted (warm-cache) arm completed")
        m = assert_accounting(
            os.path.join(out3, "metrics.json"), GLM_PLAN_WARM, "GLM warm"
        )
        quarantined = m["reliability"]["retries"]["quarantined"]
        qpaths = m["reliability"]["retries"]["quarantined_artifacts"]
        assert quarantined.get("cache_load", 0) >= 1, quarantined
        assert any(".corrupt" in p for p in qpaths), qpaths
        on_disk = [
            p for p in qpaths
            if os.path.exists(p) and ".corrupt" in p
        ]
        assert on_disk, f"quarantined artifacts not found on disk: {qpaths}"
        log(f"GLM warm: quarantine OK — {os.path.basename(on_disk[0])}")
        for sub in ("models-text", "models"):
            assert_trees_bitwise_equal(
                os.path.join(out1, sub), os.path.join(out3, sub),
                f"GLM warm {sub}",
            )

        # -- GAME arms ----------------------------------------------------
        gout1 = os.path.join(base, "game-out-clean")
        gout2 = os.path.join(base, "game-out-faulted")
        run(game_args(game_train, gout1, os.path.join(base, "game-ck1")))
        log("GAME clean arm completed")
        run(game_args(game_train, gout2, os.path.join(base, "game-ck2"),
                      plan=GAME_PLAN))
        log("GAME faulted arm completed")
        assert_accounting(
            os.path.join(gout2, "metrics.json"), GAME_PLAN, "GAME"
        )
        assert_trees_bitwise_equal(
            os.path.join(gout1, "best-model"),
            os.path.join(gout2, "best-model"),
            "GAME best-model",
        )
        m1 = json.load(open(os.path.join(gout1, "metrics.json")))
        m2 = json.load(open(os.path.join(gout2, "metrics.json")))
        assert m1["objective_history"] == m2["objective_history"], (
            m1["objective_history"], m2["objective_history"]
        )
        log("GAME: objective history identical across arms")

        # -- Serving arms -------------------------------------------------
        model_dir = os.path.join(gout1, "best-model")
        sout1 = os.path.join(base, "serving-out-clean")
        sout2 = os.path.join(base, "serving-out-faulted")
        sout3 = os.path.join(base, "serving-out-swap")
        run(serving_args(game_train, model_dir, sout1))
        log("serving clean arm completed")
        run(serving_args(game_train, model_dir, sout2, plan=SERVING_PLAN))
        log("serving faulted (transient model-load) arm completed")
        assert_accounting(
            os.path.join(sout2, "metrics.json"), SERVING_PLAN, "serving"
        )
        assert_trees_bitwise_equal(
            os.path.join(sout1, "scores"), os.path.join(sout2, "scores"),
            "serving scores",
        )
        # swap-corrupt arm: the staged generation is a COPY of the model
        # (the quarantine renames it; the served model must stay put)
        swap_copy = os.path.join(base, "serving-swap-gen2")
        shutil.copytree(model_dir, swap_copy)
        run(serving_args(game_train, model_dir, sout3,
                         plan=SERVING_SWAP_PLAN, swap_dir=swap_copy))
        log("serving swap-corrupt arm completed")
        m = json.load(open(os.path.join(sout3, "metrics.json")))
        swaps = m["swap_history"]
        assert len(swaps) == 1 and swaps[0]["rolled_back"], swaps
        assert swaps[0]["quarantined"] and os.path.exists(
            swaps[0]["quarantined"]
        ), swaps
        assert m["generation"] == 1, m["generation"]
        quarantined = m["reliability"]["retries"]["quarantined"]
        assert quarantined.get("serving.model_load", 0) >= 1, quarantined
        log(
            "serving swap: corrupt generation quarantined "
            f"({os.path.basename(swaps[0]['quarantined'])}), rolled back "
            "to generation 1"
        )
        assert_trees_bitwise_equal(
            os.path.join(sout1, "scores"), os.path.join(sout3, "scores"),
            "serving swap-rollback scores",
        )

        # -- serving-under-fire arms (ISSUE 8) ----------------------------
        nt_dir = os.path.join(base, "name-terms")
        write_name_term_lists(nt_dir)
        clean_scores = scores_by_uid(os.path.join(sout1, "scores"))
        serving_overload_arm(
            base, game_train, model_dir, nt_dir, clean_scores
        )
        # FE-only reference scores: the SAME model with its RE
        # coordinates removed, replayed clean — what a degraded
        # response must reproduce bitwise
        fe_model = fe_only_model_copy(
            model_dir, os.path.join(base, "fe-only-model")
        )
        fout = os.path.join(base, "serving-fe-only-out")
        run(serving_args(game_train, fe_model, fout))
        log("serving FE-only reference arm completed")
        fe_scores = scores_by_uid(os.path.join(fout, "scores"))
        frontend_under_fire_arm(
            base, game_train, model_dir, nt_dir, clean_scores, fe_scores
        )

        # -- continuous-retraining arms (ISSUE 10) ------------------------
        kill_mid_publish_arm(base, glm_train)
        gate_refusal_arm(base, glm_train)
        auto_rollback_arm(
            base, game_train, model_dir, nt_dir, clean_scores
        )

        # -- planet-scale serving arm (ISSUE 12) --------------------------
        shard_routing_arm(
            base, game_train, model_dir, fe_model, nt_dir, clean_scores
        )
        log("chaos matrix: PASS")
    finally:
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
