#!/usr/bin/env bash
# photon-wire bench (photon_ml_tpu/serving/wire, ISSUE 17): runs
# bench.py --wire — the SAME closed-loop routed request stream through
# a REAL 2-shard TCP fleet over the JSON-lines data plane vs the
# negotiated length-prefixed binary plane, paired-alternating passes —
# and gates the result.
#
# Host-class-aware gates:
#   - EVERYWHERE (the wire contract, host-independent):
#       * BITWISE PARITY: every pass of both arms reproduces the same
#         routed margins EXACTLY (float equality, no tolerance) — the
#         binary codec must not perturb one bit;
#       * negotiation: the binary router negotiated "binary", the JSON
#         router stayed "json";
#       * zero programs lowered on the request path in BOTH arms
#         (the wire plane must never compile anything);
#       * FLEET CONSERVATION over the shared ledger: router admitted
#         == Σ shard-attributed terminals, joined against each
#         shard's own book — across BOTH arms' full stream;
#       * binary trace drain COMPLETE: every traced request's
#         router.request root reached the FleetCollector over
#         MSG_TRACE_RESPONSE frames (roots == traced requests,
#         ring_dropped == 0, errors == 0);
#       * MARSHALLING: the binary codec round-trip (request
#         encode+decode + gather-answer encode+decode, best-of-reps,
#         measured pre+post the A/B) is cheaper than the JSON
#         round-trip on criteo-width rows.
#   - MULTI-CORE / CHIP ONLY: the paired A/B wall-clock speedup >=
#     PHOTON_WIRE_MIN_SPEEDUP (default 1.0 — binary must not lose).
#     A 1-core container timeshares router, both fleets, and writer
#     threads on one core, so its A/B ratio is noise-dominated;
#     recorded honestly, bounded only by a loose floor.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-wire-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --wire | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

# -- bitwise parity -----------------------------------------------------
assert d["bitwise_parity"] is True, "routed margins diverged between arms"
print(
    f"parity OK: {d['passes_per_arm']} passes x "
    f"{d['requests_per_pass']} requests bitwise-identical across "
    "JSON and binary arms"
)

# -- negotiation --------------------------------------------------------
assert d["negotiated"] == {"json": "json", "binary": "binary"}, (
    d["negotiated"]
)
print(f"negotiation OK: {d['negotiated']}")

# -- request-path contract ----------------------------------------------
assert d["request_path_lowerings"] == 0, d["request_path_lowerings"]
print("contract OK: 0 request-path lowerings across both arms")

# -- fleet conservation (both arms' stream in one ledger) ---------------
cons = d["conservation"]
assert cons["ok"], cons
assert cons["attribution_ok"], cons
for name, entry in cons["shards"].items():
    assert entry["join_ok"] is True, (name, entry)
print(
    f"fleet conservation OK: admitted {cons['admitted']} == "
    f"Σ attributed {sum(cons['terminal_by_attribution'].values())} "
    f"({cons['terminal_by_attribution']}), shard joins exact"
)

# -- binary trace drain completeness ------------------------------------
tr = d["trace"]
assert tr["router_request_roots"] == tr["traced_requests"], tr
assert tr["ring_dropped"] == 0, tr
assert tr["errors"] == 0, tr
print(
    f"trace drain OK: {tr['router_request_roots']} router.request "
    f"roots == {tr['traced_requests']} traced requests over binary "
    f"framing; collector dropped 0"
)

# -- marshalling micro (host-independent: deterministic, best-of-reps) --
mj, mb = d["micro_codec_us"]["json"], d["micro_codec_us"]["binary"]
assert mb < mj, (
    f"binary codec round-trip {mb}us is not cheaper than JSON {mj}us"
)
print(
    f"marshalling OK: binary {mb}us < JSON {mj}us per request+answer "
    f"round-trip ({(1 - mb / mj):.1%} cheaper; implied fraction of "
    f"request wall: {d['implied_marshalling_frac']})"
)

# -- writer coalescing (both protocols pipelined on one connection) -----
b = d["burst"]
assert b["coalesced_responses"] > 0, (
    "a pipelined burst produced no coalesced writes — the writer "
    "thread is flushing one response per sendall"
)
print(
    f"coalescing OK: {b['coalesced_responses']} responses shared a "
    f"sendall across {b['pipelined_requests']}-deep bursts "
    f"(pipelined best: json {b['json_best_us_per_req']}us/req, "
    f"binary {b['binary_best_us_per_req']}us/req)"
)

# -- wall-clock speedup (multi-core / chip only) ------------------------
multi_core = d["host"]["on_chip"] or (d["host"]["cpu_count"] or 1) > 1
ab = r["value"]
if multi_core:
    gate = float(os.environ.get("PHOTON_WIRE_MIN_SPEEDUP", "1.0"))
    assert ab >= gate, (
        f"JSON/binary wall ratio {ab:.4f} below the {gate:.2f}x gate"
    )
    print(f"A/B speedup OK: {ab:.4f}x >= {gate:.2f}x")
else:
    noise_floor = float(
        os.environ.get("PHOTON_WIRE_NOISE_FLOOR", "0.70")
    )
    assert ab > noise_floor, (
        f"JSON/binary wall ratio {ab:.4f} below even the 1-core noise "
        f"floor {noise_floor:.2f} — that is a regression, not jitter"
    )
    print(
        f"A/B recorded (1-core container, router + both shard fleets "
        f"timeshare one core): {ab:.4f}x (pairwise ratios "
        f"{d['pairwise_ratios']}); >=1.0x gate applies on "
        "multi-core/chip hosts"
    )
print("bench_wire: PASS")
EOF
