#!/usr/bin/env bash
# Serving-under-fire bench (photon_ml_tpu/serving, ISSUE 8): runs
# bench.py --overload — an open-loop flood PAST capacity (0-pacing
# submitter threads + a tight per-request deadline) through the
# admission-controlled micro-batcher — and gates the overload contract.
#
# Host-class-aware gates:
#   - EVERYWHERE (the overload contract is host-independent):
#       * every submitted request reached exactly one terminal outcome
#         (terminal == submitted; the drain burst too) — zero hangs;
#       * shed rate NONZERO (the flood is past capacity by
#         construction, so a zero shed rate means admission is not
#         engaging) and BOUNDED (<= PHOTON_OVERLOAD_MAX_SHED_RATE,
#         default 0.95 — the service must still do real work);
#       * zero programs lowered on the request path under flood
#         (request_path_lowerings == 0, cold_dispatch_compiles == 0);
#       * the parting-burst drain completes inside its budget with no
#         DRAIN_TIMEOUT failures and every burst future terminal;
#   - ADMITTED-p99 gate: <= PHOTON_OVERLOAD_MAX_P99_MS (default 250 ms
#     on CPU containers — scheduler jitter dominates; 50 ms
#     chip-attached). Shedding is what buys this bound: the queue is
#     never allowed to grow past what the deadline can absorb.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-overload-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

python bench.py --overload | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

f = d["flood"]

# -- exactly one terminal outcome per submitted request -----------------
assert f["terminal"] == f["submitted"], (f["terminal"], f["submitted"])
print(f"outcomes OK: {f['submitted']} submitted -> {f['terminal']} "
      f"terminal ({f['outcomes']})")

# -- shedding engaged, but bounded --------------------------------------
max_shed = float(os.environ.get("PHOTON_OVERLOAD_MAX_SHED_RATE", "0.95"))
assert f["refused"] > 0, (
    "flood past capacity produced ZERO sheds/deadline drops — "
    "admission control is not engaging"
)
assert f["shed_rate"] <= max_shed, (
    f"shed rate {f['shed_rate']} above {max_shed}: the service is "
    "refusing nearly everything"
)
assert f["ok"] > 0, "no admitted request completed"
print(f"shed OK: rate {f['shed_rate']} "
      f"(refused {f['refused']} = sheds {f['sheds_by_reason']} + "
      f"{f['deadline_expired_at_dispatch']} expired at dispatch), "
      f"{f['ok']} scored")

# -- fixed-shape contract under flood -----------------------------------
assert d["request_path_lowerings"] == 0, d["request_path_lowerings"]
assert d["recompiles_after_warmup"] == 0, d["recompiles_after_warmup"]
assert d["cold_dispatch_compiles"] == 0, d["cold_dispatch_compiles"]
print("contract OK: 0 request-path lowerings under flood")

# -- admitted-request latency stays bounded -----------------------------
default_p99 = 50.0 if d["host"]["on_chip"] else 250.0
max_p99 = float(os.environ.get("PHOTON_OVERLOAD_MAX_P99_MS", default_p99))
p99 = f["admitted_p99_ms"]
assert p99 is not None and p99 <= max_p99, (
    f"admitted p99 {p99}ms above {max_p99}ms — shedding failed to "
    "protect the latency of admitted work"
)
print(f"latency OK: admitted p50 {f['admitted_p50_ms']}ms / "
      f"p99 {p99}ms (gate <= {max_p99}ms)")

# -- bounded drain: zero hung futures -----------------------------------
dr = d["drain"]
assert dr["duration_s"] < dr["budget_s"], (dr["duration_s"], dr["budget_s"])
assert not dr["timed_out"], dr
assert dr["failed"] == 0, dr
assert dr["burst_terminal"] == dr["burst"], (
    f"drain left hung futures: {dr['burst_terminal']}/{dr['burst']}"
)
print(f"drain OK: {dr['burst']} pending -> all terminal in "
      f"{dr['duration_s']}s (budget {dr['budget_s']}s)")

print("bench_overload: PASS")
EOF
