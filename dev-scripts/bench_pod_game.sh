#!/usr/bin/env bash
# Pod-scale GAME A/B (game/pod.py): entity-hash-sharded RE banks +
# two-hop routed residuals vs the replicated bucket path
# (bench.py --pod-game) with host-class-aware gates.
#
# Gates applied EVERYWHERE (correctness-grade, device-count only needs
# the virtual CPU mesh):
#   - weak scaling: per-device bank+optimizer-state bytes stay ~flat
#     (<= 1.3x spread) while total coefficients grow with the shard
#     count, and the sharded bytes at N shards are <= 1/N of the
#     replicated path + hash-padding slack;
#   - parity: sharded bank and routed scores match the replicated
#     update within the fp32 envelope;
#   - zero host-side gathers on the routed path (the counted
#     overlap.device_get seam).
# The throughput-scaling gate is CHIP-ONLY: virtual CPU devices emulate
# every collective participant on one core, so sharded wall-clock here
# measures XLA's emulation, not ICI (PHOTON_POD_GAME_MIN_RATIO
# overrides the chip gate, default 0.9x at equal model size — the win
# this path buys is CAPACITY, per-device bytes, not single-model speed).
set -euo pipefail
cd "$(dirname "$0")/.."

# no accelerator -> force the 8-device virtual CPU mesh
if [ "${JAX_PLATFORMS:-}" = "" ] || [ "${JAX_PLATFORMS:-}" = "cpu" ]; then
  export JAX_PLATFORMS=cpu
  case "${XLA_FLAGS:-}" in
    *xla_force_host_platform_device_count*) ;;
    *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" ;;
  esac
fi

OUT=$(mktemp -t photon-pod-game-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

python bench.py --pod-game | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

n = d["n_shards"]
assert n >= 2, f"pod A/B needs >= 2 devices, got {n}"

# -- weak scaling: per-device bytes flat as coefficients grow ----------
per_dev = [row["per_device_state_bytes"] for row in d["weak_scaling"]]
spread = max(per_dev) / max(min(per_dev), 1)
assert spread <= 1.3, (
    f"per-device state bytes not flat across the weak-scaling table: "
    f"{per_dev} (spread {spread:.2f}x)"
)
coef = [row["coefficients"] for row in d["weak_scaling"]]
assert coef[-1] > coef[0], coef
print(f"weak scaling: coefficients {coef[0]} -> {coef[-1]}, "
      f"per-device state bytes {per_dev} (spread {spread:.2f}x)")

# -- sharded bytes <= 1/N of replicated + hash-padding slack -----------
ratio = d["bytes_ratio"]
assert ratio <= 1.0 / n * 1.25 + 1e-9, (
    f"sharded per-device state {ratio:.4f}x of replicated exceeds "
    f"1/{n} + 25% padding slack"
)
print(f"per-device state {d['sharded_per_device_state_bytes']} B = "
      f"{ratio:.4f}x of replicated {d['replicated_state_bytes']} B "
      f"(gate <= {1.0 / n * 1.25:.4f}x)")

# -- parity ------------------------------------------------------------
assert d["bank_max_abs_diff"] <= 1e-3, d["bank_max_abs_diff"]
assert d["score_max_abs_diff"] <= 1e-3, d["score_max_abs_diff"]
print(f"parity: bank diff {d['bank_max_abs_diff']}, "
      f"score diff {d['score_max_abs_diff']}")

# -- routed path crosses the host ZERO times ---------------------------
assert d["routed_readbacks"] == 0, (
    f"routed update/score path performed {d['routed_readbacks']} "
    "host readbacks (expected 0)"
)
print("routed path: 0 host readbacks")

# -- throughput gate (chip-only) ---------------------------------------
platform = d["host"]["platform"]
if platform == "cpu":
    print(f"cpu host-class: throughput ratio {d['throughput_ratio']}x "
          "recorded (chip-only gate; virtual devices emulate "
          "collectives on one core)")
else:
    gate = float(os.environ.get("PHOTON_POD_GAME_MIN_RATIO", "0.9"))
    ratio = d["throughput_ratio"]
    print(f"sharded step {d['sharded_step_s']}s vs replicated "
          f"{d['replicated_step_s']}s ({ratio}x; gate >= {gate}x)")
    assert ratio >= gate, f"throughput ratio {ratio}x below {gate}x"

print("bench_pod_game: PASS")
EOF
