#!/usr/bin/env bash
# Online scoring service bench (photon_ml_tpu/serving, ISSUE 7): runs
# bench.py --serving — a synthetic GAME bank at config-5-class shapes
# served through the device bank + AOT ladder + micro-batcher, under a
# single-request closed loop (latency floor) and a saturating open loop
# (QPS) — and gates the result.
#
# Host-class-aware gates:
#   - EVERYWHERE (the fixed-shape serving contract, host-independent):
#       * zero programs lowered on the request path after AOT warmup
#         (request_path_lowerings == 0, recompiles_after_warmup == 0,
#         cold_dispatch_compiles == 0);
#       * exactly ONE counted readback per dispatched micro-batch
#         (readbacks == dispatches, both phases);
#       * closed-loop p99 <= PHOTON_SERVING_MAX_P99_MS (default 25 ms —
#         generous on purpose: the container's scheduler jitter is the
#         ceiling here, not the dispatch path, measured p99 ~0.2 ms on
#         the 1-core image);
#   - CHIP-ATTACHED ONLY: open-loop QPS >= PHOTON_SERVING_MIN_QPS
#     (default 50000). A 1-core CPU host serializes the device program
#     under the submitters, so its QPS is recorded, not gated.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-serving-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

python bench.py --serving | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

# -- the fixed-shape / readback contract (host-independent) -------------
assert d["request_path_lowerings"] == 0, d["request_path_lowerings"]
assert d["recompiles_after_warmup"] == 0, d["recompiles_after_warmup"]
assert d["cold_dispatch_compiles"] == 0, d["cold_dispatch_compiles"]
for phase in ("closed", "open"):
    p = d[phase]
    assert p["readbacks"] == p["dispatches"], (phase, p)
print(
    f"contract OK: 0 request-path lowerings after warmup "
    f"({d['aot_programs']} AOT programs); 1 readback/dispatch "
    f"(closed {d['closed']['dispatches']}, open {d['open']['dispatches']})"
)

# -- latency gate (everywhere) ------------------------------------------
max_p99 = float(os.environ.get("PHOTON_SERVING_MAX_P99_MS", "25"))
p99 = d["closed"]["p99_ms"]
assert p99 <= max_p99, f"closed-loop p99 {p99}ms above {max_p99}ms"
print(
    f"latency OK: closed-loop p50 {d['closed']['p50_ms']}ms / "
    f"p99 {p99}ms (gate <= {max_p99}ms)"
)

# -- throughput gate (chip-attached only) -------------------------------
if d["host"]["on_chip"]:
    min_qps = float(os.environ.get("PHOTON_SERVING_MIN_QPS", "50000"))
    qps = d["open"]["qps"]
    assert qps >= min_qps, f"open-loop QPS {qps} below {min_qps}"
    print(f"throughput OK: {qps} QPS (gate >= {min_qps})")
else:
    print(
        f"CPU host: open-loop {d['open']['qps']} QPS at occupancy "
        f"{d['open']['batch_occupancy_mean']} recorded (QPS gate applies "
        "chip-attached)"
    )

print("bench_serving: PASS")
EOF
