#!/usr/bin/env bash
# Unified (grid x entity) mesh A/B (game/unified.py): the whole
# G-member λ-grid over an entity-sharded GAME model as ONE
# jitted/shard_mapped program vs G sequential pod CD sweeps
# (bench.py --unified-mesh) with host-class-aware gates.
#
# Gates applied EVERYWHERE (correctness-grade, device-count only needs
# the virtual CPU mesh):
#   - parity: per-λ objectives within 2e-4 relative and member banks
#     within 2e-3 max-abs of the sequential pod oracle;
#   - ONE batched readback per CD iteration for the WHOLE grid
#     (the overlap.device_get seam);
#   - ZERO relowerings on a warmed same-shape run with different λs
#     (λ values are data, not program structure).
# The wall-clock gate is MULTI-CORE/CHIP-ONLY: a 1-core host runs every
# virtual device sequentially, so the one-program win there is Python
# dispatch overhead only — the 1-core speedup is recorded honestly but
# not gated. On >= 4 cores or a real accelerator the unified sweep at
# G >= 4 must beat the sequential-composed legacy by >= 1.2x
# (PHOTON_UNIFIED_MIN_RATIO overrides).
set -euo pipefail
cd "$(dirname "$0")/.."

# no accelerator -> force the 8-device virtual CPU mesh
if [ "${JAX_PLATFORMS:-}" = "" ] || [ "${JAX_PLATFORMS:-}" = "cpu" ]; then
  export JAX_PLATFORMS=cpu
  case "${XLA_FLAGS:-}" in
    *xla_force_host_platform_device_count*) ;;
    *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" ;;
  esac
fi

OUT=$(mktemp -t photon-unified-mesh-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

python bench.py --unified-mesh | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

# -- parity vs the sequential pod oracle (everywhere) ------------------
assert d["objective_max_rel_diff"] <= 2e-4, (
    f"objective parity broke: {d['objective_max_rel_diff']}"
)
assert d["bank_max_abs_diff"] <= 2e-3, (
    f"bank parity broke: {d['bank_max_abs_diff']}"
)
print(f"parity: obj rel {d['objective_max_rel_diff']:.2e}, "
      f"bank abs {d['bank_max_abs_diff']:.2e}")

# -- one batched readback per CD iteration (everywhere) ----------------
assert d["unified_readbacks"] == d["cd_iterations"], (
    f"readbacks {d['unified_readbacks']} != "
    f"CD iterations {d['cd_iterations']}"
)
print(f"readbacks: {d['unified_readbacks']} for "
      f"{d['cd_iterations']} CD iterations")

# -- zero relowerings warm (everywhere) --------------------------------
assert d["relowerings_warm"] == 0, (
    f"warmed run relowered {d['relowerings_warm']} program(s)"
)
print("relowerings on warmed different-λ run: 0")

# -- wall-clock gate: multi-core / chip only ---------------------------
cpu = d["host"]["cpu_count"] or 1
chip = d["host"]["platform"] not in ("cpu",)
min_ratio = float(os.environ.get("PHOTON_UNIFIED_MIN_RATIO", "1.2"))
sp = d["speedup"]
if chip or cpu >= 4:
    assert d["grid_size"] >= 4, d["grid_size"]
    assert sp >= min_ratio, (
        f"unified sweep speedup {sp}x < {min_ratio}x on a "
        f"{cpu}-core/{d['host']['platform']} host"
    )
    print(f"speedup gate: {sp}x >= {min_ratio}x (G={d['grid_size']})")
else:
    print(f"speedup RECORDED (not gated, {cpu}-core host): {sp}x "
          f"(unified {d['unified_wall_s']}s vs "
          f"sequential {d['sequential_wall_s']}s)")

print("bench_unified_mesh: ALL GATES PASSED")
EOF
