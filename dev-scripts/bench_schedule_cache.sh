#!/usr/bin/env bash
# Tile-schedule cache A/B: run the GLM driver twice against one tmp
# --tile-cache-dir and assert the second (warm) run's schedule-build time
# is at least 10x lower than the first (cold) run's.
#
# Runs fully on CPU (JAX_PLATFORMS=cpu): the schedule build is host-side,
# so the cache win is measurable without a TPU. The fit itself runs the
# tiled kernels in interpret mode, so the dataset is kept small and the
# grid short — the metric under test is metrics.json's schedule_cache
# build_s/load_s, not fit throughput.
set -euo pipefail
cd "$(dirname "$0")/.."

TMP=$(mktemp -d -t photon-sched-cache-XXXXXX)
trap 'rm -rf "$TMP"' EXIT
export JAX_PLATFORMS=cpu

N_ROWS=98304
NNZ=8
DIM=12288

python - "$TMP/data" "$N_ROWS" "$NNZ" "$DIM" <<'EOF'
import os, sys
import numpy as np

out_dir, n, k, d = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
os.makedirs(out_dir, exist_ok=True)
rng = np.random.default_rng(0)
w = rng.normal(size=d).astype(np.float32) * 0.3
with open(os.path.join(out_dir, "part-00000.libsvm"), "w") as f:
    for _ in range(n):
        ix = rng.choice(d, size=k, replace=False)
        vs = rng.normal(size=k).astype(np.float32)
        z = float((w[ix] * vs).sum())
        y = int(rng.uniform() < 1.0 / (1.0 + np.exp(-z)))
        f.write(
            f"{y} " + " ".join(f"{i + 1}:{v:.4f}" for i, v in zip(ix, vs)) + "\n"
        )
print(f"wrote {n} LibSVM rows to {out_dir}")
EOF

run_driver() {
  python -m photon_ml_tpu.cli.glm_driver \
    --training-data-directory "$TMP/data" \
    --output-directory "$1" \
    --format LIBSVM \
    --feature-dimension "$DIM" \
    --kernel tiled \
    --distributed off \
    --optimizer LBFGS \
    --num-iterations 2 \
    --regularization-weights 1.0 \
    --data-validation-type VALIDATE_DISABLED \
    --tile-cache-dir "$TMP/cache"
}

echo "== cold run (cache empty) =="
run_driver "$TMP/out-cold"
echo "== warm run (cache populated) =="
run_driver "$TMP/out-warm"

python - "$TMP/out-cold/metrics.json" "$TMP/out-warm/metrics.json" <<'EOF'
import json, sys

cold = json.load(open(sys.argv[1]))["schedule_cache"]
warm = json.load(open(sys.argv[2]))["schedule_cache"]
# schedule time = what the cache replaces: build (+ artifact load on the
# warm side); keying/hash cost is reported separately in hash_s
cold_s = cold["build_s"] + cold["load_s"]
warm_s = warm["build_s"] + warm["load_s"]
print(f"cold: builds={cold['builds']} build_s={cold['build_s']:.3f} load_s={cold['load_s']:.4f}")
print(f"warm: hits={warm['hits']} build_s={warm['build_s']:.3f} load_s={warm['load_s']:.4f} hash_s={warm['hash_s']:.4f}")
assert cold["builds"] >= 2, f"cold run built {cold['builds']} schedules, expected z+g"
assert warm["builds"] == 0, f"warm run rebuilt {warm['builds']} schedules (cache missed)"
assert warm["hits"] >= 2, f"warm run hit {warm['hits']} artifacts, expected z+g"
speedup = cold_s / max(warm_s, 1e-9)
print(f"schedule time: cold {cold_s:.3f}s -> warm {warm_s:.4f}s ({speedup:.1f}x)")
assert speedup >= 10.0, f"warm schedule time only {speedup:.1f}x lower (need >= 10x)"
print("OK: warm schedule load >= 10x faster than cold build")
EOF
