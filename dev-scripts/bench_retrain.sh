#!/usr/bin/env bash
# Incremental-retrain A/B (ISSUE 10): bench.py --retrain measures full
# retrain (uncached scan + cold solve) vs incremental retrain
# (per-partition stats cache + registry warm start) at 1% and 10%
# appended data.
#
# Gates everywhere (any host):
#   - partitions_scanned == 1 in BOTH phases (the appended partition and
#     NOTHING else was re-read — the counted only-new-partitions claim);
#   - warm_start_bitwise == true (the no-drift alignment is bitwise the
#     parent coefficients);
#   - the parent publish landed as generation 1.
# Gate multi-core / chip-attached only:
#   - 1%-appended incremental retrain >= 1.2x faster than full (on the
#     1-core CPU container the solve is compute-bound and iteration-
#     count noise swamps the scan win; the counters above are the
#     correctness claim, measured everywhere).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

OUT=$(python bench.py --retrain)
echo "$OUT"

python - "$OUT" <<'EOF'
import json
import os
import sys

r = json.loads(sys.argv[1])
d = r["detail"]
for phase in ("1pct", "10pct"):
    p = d[phase]
    assert p["partitions_scanned"] == 1, (
        f"{phase}: scanned {p['partitions_scanned']} partitions, "
        "expected ONLY the appended one"
    )
    assert p["partitions_cached"] == p["partitions"] - 1, p
    print(
        f"{phase}: +{p['rows_appended']} rows, scanned 1/{p['partitions']} "
        f"partitions, full {p['full_s']}s vs incremental "
        f"{p['incremental_s']}s ({p['speedup']}x)"
    )
assert d["warm_start_bitwise"] is True, (
    "no-drift warm-start alignment must be bitwise the parent"
)
assert d["published_generation"] == 1
multi_core = (os.cpu_count() or 1) >= 4
chip = os.environ.get("JAX_PLATFORMS", "cpu") not in ("cpu", "")
if multi_core or chip:
    s = d["1pct"]["speedup"]
    assert s >= 1.2, f"1%-append incremental speedup {s}x < 1.2x gate"
    print(f"OK: speedup gate {s}x >= 1.2x (host class: multi-core/chip)")
else:
    print(
        "speedup gate skipped (1-core CPU host); counters + bitwise "
        "warm-start verified"
    )
print("OK: retrain bench gates passed")
EOF
