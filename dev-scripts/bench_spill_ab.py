"""A/B the spill-to-scatter hybrid kernel vs spill_cap=0 at the ads shape.

Run on the real TPU (no timeout-kill — launch in background and let it
exit). Protocol: in-jit fori_loop differencing (PERF_NOTES.md).
"""

import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from photon_ml_tpu.ops.losses import LOGISTIC
    from photon_ml_tpu.ops.tiled_sparse import (
        TileParams,
        TiledGLMObjective,
        build_tiled_batch,
    )

    rng = np.random.default_rng(0)
    n, k, d = 1 << 18, 64, 1 << 20
    indices = rng.integers(0, d, size=(n, k), dtype=np.int64)
    values = rng.normal(size=(n, k)).astype(np.float32)
    labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)

    obj = TiledGLMObjective(LOGISTIC, d)

    @jax.jit
    def loop(m, w0, tb):
        def body(i, carry):
            w, acc = carry
            v, g = obj.value_and_gradient(w, tb, 0.1)
            return (w - 1e-9 * g, acc + v)

        return lax.fori_loop(0, m, body, (w0, jnp.float32(0.0)))

    w0 = jnp.zeros((d,), jnp.float32)
    iters = 11

    def timed(tb, m):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = loop(m, w0, tb)
            _ = float(out[1])
            best = min(best, time.perf_counter() - t0)
        return best

    def measure(tb):
        _ = timed(tb, 1)  # compile + warm
        return (timed(tb, iters) - timed(tb, 1)) / (iters - 1)

    results = {}
    for name, cap, chunk in (
        ("spill4096", None, 4096),
        ("spill4224", None, 4224),
        ("spill4352", None, 4352),
    ):
        t0 = time.time()
        tb = build_tiled_batch(
            rows, indices.reshape(-1), values.reshape(-1), labels,
            np.zeros(n, np.float32), np.ones(n, np.float32), d,
            params=TileParams(spill_cap=cap, chunk=chunk),
        )
        build_s = time.time() - t0
        zs, gs = tb.z_sched.num_steps, tb.g_sched.num_steps
        sp_z = int(np.count_nonzero(np.asarray(tb.z_sched.spill_vals)))
        sp_g = int(np.count_nonzero(np.asarray(tb.g_sched.spill_vals)))
        dt = measure(tb)
        results[name] = dt
        print(
            f"{name}: {dt*1e3:.2f} ms/eval  {n/dt/1e6:.2f}M ex/s  "
            f"steps z/g {zs}/{gs}  spills z/g {sp_z}/{sp_g}  "
            f"build {build_s:.1f}s",
            flush=True,
        )
        del tb

    base = 23.12e-3  # nospill measured earlier this session
    for k, v in results.items():
        print(f"{k}: {base/v:.3f}x vs nospill", flush=True)


if __name__ == "__main__":
    main()
