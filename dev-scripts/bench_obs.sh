#!/usr/bin/env bash
# Unified-telemetry overhead bench (photon_ml_tpu/obs, ISSUE 13): runs
# bench.py --obs — the SAME closed-loop request stream through the real
# micro-batcher with the obs plane OFF (shipped default) vs ON (span
# tracing + metrics registry views + flight recorder), alternating
# passes — and gates the result.
#
# Host-class-aware gates:
#   - EVERYWHERE (the request-path contract, host-independent):
#       * zero programs lowered on the request path in BOTH arms
#         (request_path_lowerings == 0 — telemetry must never compile);
#       * exactly ONE counted readback per dispatch, unchanged by
#         tracing (readbacks == dispatches across both arms);
#       * trace COMPLETENESS: every dispatch of the traced arm filed a
#         serving.dispatch span, every traced request a serving.score
#         leaf (dispatch_spans == traced_dispatches, score_spans ==
#         traced_requests);
#       * conservation: admitted == terminal outcomes after the run;
#       * implied overhead < PHOTON_OBS_MAX_OVERHEAD (default 2%):
#         the obs plane's entire request-path addition is one
#         record_span per dispatch, measured deterministically in
#         isolation and divided by the measured per-request wall —
#         the noise-free twin of the A/B.
#   - MULTI-CORE / CHIP ONLY: the paired A/B itself < the same gate.
#     This 1-core container's scheduler jitter swings +-20% pass to
#     pass — far past the ~2us/dispatch effect — so its A/B number is
#     recorded honestly, bounded only by a loose noise ceiling.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -t photon-obs-XXXXXX.json)
trap 'rm -f "$OUT"' EXIT

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --obs | tail -1 > "$OUT"

python - "$OUT" <<'EOF'
import json, os, sys

r = json.load(open(sys.argv[1]))
d = r["detail"]
print(json.dumps(r, indent=2))

# -- request-path contract (host-independent) ---------------------------
assert d["request_path_lowerings"] == 0, d["request_path_lowerings"]
assert d["readbacks"] == d["dispatches"], (
    d["readbacks"], d["dispatches"],
)
print(
    f"contract OK: 0 request-path lowerings, "
    f"{d['readbacks']} readbacks == {d['dispatches']} dispatches "
    "(both arms)"
)

# -- trace completeness + conservation ----------------------------------
assert d["dispatch_spans"] == d["traced_dispatches"], (
    d["dispatch_spans"], d["traced_dispatches"],
)
assert d["score_spans"] == d["traced_requests"], (
    d["score_spans"], d["traced_requests"],
)
assert d["conservation"]["ok"], d["conservation"]
print(
    f"completeness OK: {d['dispatch_spans']} dispatch spans == "
    f"{d['traced_dispatches']} traced dispatches; "
    f"{d['score_spans']} score leaves == {d['traced_requests']} "
    f"traced requests; conservation admitted == terminal "
    f"({d['conservation']['admitted']})"
)

# -- overhead gates -----------------------------------------------------
gate = float(os.environ.get("PHOTON_OBS_MAX_OVERHEAD", "0.02"))
implied = d["implied_overhead_frac"]
assert implied < gate, (
    f"implied per-dispatch overhead {implied:.4f} "
    f"({d['span_record_us_per_dispatch']}us over "
    f"{d['per_request_us']}us/request) exceeds the {gate:.2%} gate"
)
print(
    f"implied overhead OK: {d['span_record_us_per_dispatch']}us/dispatch "
    f"over {d['per_request_us']}us/request = {implied:.4%} < {gate:.2%}"
)

multi_core = d["host"]["on_chip"] or (d["host"]["cpu_count"] or 1) > 1
ab = r["value"]
if multi_core:
    assert ab < gate, (
        f"paired A/B overhead {ab:.4f} exceeds the {gate:.2%} gate"
    )
    print(f"A/B overhead OK: {ab:.4%} < {gate:.2%}")
else:
    noise_ceiling = float(
        os.environ.get("PHOTON_OBS_NOISE_CEILING", "0.25")
    )
    assert ab < noise_ceiling, (
        f"paired A/B overhead {ab:.4f} exceeds even the 1-core noise "
        f"ceiling {noise_ceiling:.2%} — that is an effect, not jitter"
    )
    print(
        f"A/B recorded (1-core container, noise-dominated): {ab:.4%} "
        f"(pairwise ratios {d['pairwise_ratios']}); <{gate:.2%} gate "
        "applies on multi-core/chip hosts"
    )
print("bench_obs: PASS")
EOF
