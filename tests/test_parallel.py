"""Distributed-path tests on the virtual 8-device CPU mesh: data-parallel
objective == single-device objective, whole-fit-in-shard_map, feature-axis
sharding exactness (the multi-chip paths the driver dry-runs).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import minimize_lbfgs
from photon_ml_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    data_parallel_fit_lbfgs,
    data_parallel_value_and_grad,
    feature_sharded_fit,
    feature_sharded_value_and_grad,
    make_mesh,
    shard_batch,
)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh((8,), (DATA_AXIS,))


@pytest.fixture(scope="module")
def mesh4x2():
    return make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))


def sparse_problem(rng, n=256, d=32, k=8):
    rows = []
    labels = []
    w_true = rng.normal(size=d).astype(np.float32)
    for _ in range(n):
        ix = rng.choice(d, size=k, replace=False)
        vs = rng.normal(size=k).astype(np.float32)
        z = float(np.sum(w_true[ix] * vs))
        labels.append(float(1 / (1 + np.exp(-z)) > rng.uniform()))
        rows.append((ix.tolist(), vs.tolist()))
    return make_sparse_batch(rows, labels, pad_rows_to=8), w_true


class TestDataParallel:
    def test_matches_single_device(self, mesh8, rng):
        batch, _ = sparse_problem(rng)
        d = 32
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_local, g_local = obj.value_and_gradient(w, batch, 0.1)
        sharded = shard_batch(batch, mesh8)
        vg = data_parallel_value_and_grad(obj, mesh8)
        v_dist, g_dist = vg(w, sharded, jnp.float32(0.1))
        np.testing.assert_allclose(float(v_dist), float(v_local), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g_dist), np.asarray(g_local), atol=1e-4
        )

    def test_whole_fit_in_shard_map(self, mesh8, rng):
        batch, _ = sparse_problem(rng)
        d = 32
        obj = GLMObjective(LOGISTIC, d)
        fit = data_parallel_fit_lbfgs(obj, mesh8, max_iter=50)
        res = fit(jnp.zeros(d), shard_batch(batch, mesh8), jnp.float32(0.1))
        local = minimize_lbfgs(
            lambda w: obj.value_and_gradient(w, batch, 0.1),
            jnp.zeros(d), max_iter=50,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients), np.asarray(local.coefficients),
            atol=5e-3,
        )


class TestEntityAllToAll:
    """The shuffle analog: re-key rows to entity-owning devices in-jit."""

    def test_round_trip_lossless(self, mesh8, rng):
        from photon_ml_tpu.parallel.shuffle import (
            entity_all_to_all,
            reshard_capacity,
        )

        n, n_dev, k = 256, 8, 4
        codes = rng.integers(0, 40, size=n).astype(np.int32)
        codes[::17] = -1  # padding rows sprinkled in
        values = rng.normal(size=n).astype(np.float32)
        feats = rng.normal(size=(n, k)).astype(np.float32)
        cap = reshard_capacity(codes, n_dev)
        out = entity_all_to_all(
            mesh8,
            jnp.asarray(codes),
            {"v": jnp.asarray(values), "x": jnp.asarray(feats)},
            cap=cap,
        )
        assert int(np.asarray(out.dropped).sum()) == 0
        real = codes >= 0
        assert int(np.asarray(out.received).sum()) == int(real.sum())
        out_codes = np.asarray(out.entity_codes)
        out_v = np.asarray(out.payload["v"])
        got = out_codes >= 0
        # multiset of (code, value) pairs survives the re-shard
        sent = sorted(zip(codes[real].tolist(), values[real].tolist()))
        recv = sorted(zip(out_codes[got].tolist(), out_v[got].tolist()))
        assert sent == recv
        # each device block holds only entities it owns (code % n_dev)
        per_dev = out_codes.reshape(n_dev, -1)
        for d in range(n_dev):
            owned = per_dev[d][per_dev[d] >= 0]
            assert np.all(owned % n_dev == d)
        # payload rows stay aligned with their codes
        out_x = np.asarray(out.payload["x"])
        code_to_row = {}
        for i in range(n):
            if real[i]:
                code_to_row.setdefault(
                    (codes[i], round(float(values[i]), 5)), feats[i]
                )
        for j in np.nonzero(got)[0][:20]:
            key = (out_codes[j], round(float(out_v[j]), 5))
            np.testing.assert_allclose(out_x[j], code_to_row[key], rtol=1e-6)

    def test_overflow_is_reported(self, mesh8, rng):
        from photon_ml_tpu.parallel.shuffle import entity_all_to_all

        n = 64
        codes = np.zeros(n, np.int32)  # every row -> device 0
        out = entity_all_to_all(
            mesh8,
            jnp.asarray(codes),
            {"v": jnp.ones(n, jnp.float32)},
            cap=8,  # each source may send only 8 rows to device 0
        )
        # 8 sources x 8 rows each = 64 slots but only 8 rows per source fit
        assert int(np.asarray(out.received).sum()) == n - int(
            np.asarray(out.dropped).sum()
        )
        assert int(np.asarray(out.dropped).sum()) == 0  # 8 rows/src fit cap
        out2 = entity_all_to_all(
            mesh8,
            jnp.asarray(codes),
            {"v": jnp.ones(n, jnp.float32)},
            cap=4,
        )
        assert int(np.asarray(out2.dropped).sum()) == n - 8 * 4


class TestFeatureSharded:
    def test_value_and_grad_exact(self, mesh4x2, rng):
        n, d = 64, 16
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        batch = make_dense_batch(x, y)
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_local, g_local = obj.value_and_gradient(w, batch, 0.2)
        vg = feature_sharded_value_and_grad(obj, mesh4x2)
        v, g = vg(w, batch.features, batch.labels, batch.offsets,
                  batch.weights, jnp.float32(0.2))
        np.testing.assert_allclose(float(v), float(v_local), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_local), atol=1e-4)

    def test_sharded_fit_matches_replicated(self, mesh4x2, rng):
        n, d = 128, 16
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        batch = make_dense_batch(x, y)
        obj = GLMObjective(LOGISTIC, d)
        fit = feature_sharded_fit(obj, mesh4x2, max_iter=50)
        res = fit(jnp.zeros(d), batch.features, batch.labels, batch.offsets,
                  batch.weights, jnp.float32(0.1))
        local = minimize_lbfgs(
            lambda w_: obj.value_and_gradient(w_, batch, 0.1),
            jnp.zeros(d), max_iter=50,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients), np.asarray(local.coefficients),
            atol=5e-3,
        )
        # Shared optimizer => identical convergence bookkeeping shape.
        np.testing.assert_allclose(
            float(res.value), float(local.value), rtol=1e-5
        )
        assert int(res.iterations) > 0

    def test_sparse_sharded_fit_matches_replicated(self, mesh4x2, rng):
        from photon_ml_tpu.parallel import (
            feature_shard_sparse_batch,
            feature_sharded_sparse_fit,
        )

        # d chosen NOT to divide into equal blocks so d_pad > d and the
        # padded-slot assertion below is non-vacuous.
        batch, _ = sparse_problem(rng, n=128, d=45, k=8)
        d = 45
        obj = GLMObjective(LOGISTIC, d)
        sharded, block_dim = feature_shard_sparse_batch(
            batch, d, num_blocks=2, rows_multiple=4
        )
        d_pad = 2 * block_dim
        assert d_pad > d
        fit = feature_sharded_sparse_fit(obj, mesh4x2, max_iter=50)
        res = fit(jnp.zeros(d_pad), sharded, jnp.float32(0.1))
        local = minimize_lbfgs(
            lambda w_: obj.value_and_gradient(w_, batch, 0.1),
            jnp.zeros(d), max_iter=50,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients)[:d],
            np.asarray(local.coefficients), atol=5e-3,
        )
        # Padded vocabulary slots never see data => exactly zero.
        np.testing.assert_array_equal(np.asarray(res.coefficients)[d:], 0.0)

    def test_sparse_sharded_owlqn_matches_replicated(self, mesh4x2, rng):
        from photon_ml_tpu.optim.lbfgs import minimize_owlqn
        from photon_ml_tpu.parallel import feature_shard_sparse_batch
        from photon_ml_tpu.parallel.distributed import (
            feature_sharded_sparse_fit_owlqn,
        )

        batch, _ = sparse_problem(rng, n=128, d=45, k=8)
        d = 45
        obj = GLMObjective(LOGISTIC, d)
        sharded, block_dim = feature_shard_sparse_batch(
            batch, d, num_blocks=2, rows_multiple=4
        )
        fit = feature_sharded_sparse_fit_owlqn(obj, mesh4x2, max_iter=50)
        res = fit(
            jnp.zeros(2 * block_dim), sharded,
            jnp.float32(0.05), jnp.float32(0.2),
            jnp.ones(2 * block_dim, jnp.float32),
        )
        local = minimize_owlqn(
            lambda w_: obj.value_and_gradient(w_, batch, 0.05),
            jnp.zeros(d), 0.2, max_iter=50,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients)[:d],
            np.asarray(local.coefficients), atol=5e-3,
        )
        # L1 must produce sparsity, identically in both runs
        assert (np.asarray(res.coefficients)[:d] == 0).sum() == (
            np.asarray(local.coefficients) == 0
        ).sum()

    def test_sparse_sharded_value_and_grad_exact(self, mesh4x2, rng):
        from photon_ml_tpu.parallel import (
            feature_shard_sparse_batch,
            feature_sharded_sparse_fit,  # noqa: F401 (import check)
        )
        from photon_ml_tpu.parallel.distributed import (
            feature_sharded_sparse_value_and_grad,
        )

        batch, _ = sparse_problem(rng, n=64, d=40, k=8)
        d = 40
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_local, g_local = obj.value_and_gradient(w, batch, 0.2)
        sharded, block_dim = feature_shard_sparse_batch(
            batch, d, num_blocks=2, rows_multiple=4
        )
        w_pad = jnp.zeros(2 * block_dim).at[:d].set(w)
        vg = feature_sharded_sparse_value_and_grad(obj, mesh4x2)
        v, g = vg(w_pad, sharded, jnp.float32(0.2))
        np.testing.assert_allclose(float(v), float(v_local), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g)[:d], np.asarray(g_local), atol=1e-4
        )
