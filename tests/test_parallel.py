"""Distributed-path tests on the virtual 8-device CPU mesh: data-parallel
objective == single-device objective, whole-fit-in-shard_map, feature-axis
sharding exactness (the multi-chip paths the driver dry-runs).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import minimize_lbfgs
from photon_ml_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    data_parallel_fit_lbfgs,
    data_parallel_value_and_grad,
    feature_sharded_fit,
    feature_sharded_value_and_grad,
    make_mesh,
    shard_batch,
)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh((8,), (DATA_AXIS,))


@pytest.fixture(scope="module")
def mesh4x2():
    return make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))


def sparse_problem(rng, n=256, d=32, k=8):
    rows = []
    labels = []
    w_true = rng.normal(size=d).astype(np.float32)
    for _ in range(n):
        ix = rng.choice(d, size=k, replace=False)
        vs = rng.normal(size=k).astype(np.float32)
        z = float(np.sum(w_true[ix] * vs))
        labels.append(float(1 / (1 + np.exp(-z)) > rng.uniform()))
        rows.append((ix.tolist(), vs.tolist()))
    return make_sparse_batch(rows, labels, pad_rows_to=8), w_true


class TestDataParallel:
    def test_matches_single_device(self, mesh8, rng):
        batch, _ = sparse_problem(rng)
        d = 32
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_local, g_local = obj.value_and_gradient(w, batch, 0.1)
        sharded = shard_batch(batch, mesh8)
        vg = data_parallel_value_and_grad(obj, mesh8)
        v_dist, g_dist = vg(w, sharded, jnp.float32(0.1))
        np.testing.assert_allclose(float(v_dist), float(v_local), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g_dist), np.asarray(g_local), atol=1e-4
        )

    def test_whole_fit_in_shard_map(self, mesh8, rng):
        batch, _ = sparse_problem(rng)
        d = 32
        obj = GLMObjective(LOGISTIC, d)
        fit = data_parallel_fit_lbfgs(obj, mesh8, max_iter=50)
        res = fit(jnp.zeros(d), shard_batch(batch, mesh8), jnp.float32(0.1))
        local = minimize_lbfgs(
            lambda w: obj.value_and_gradient(w, batch, 0.1),
            jnp.zeros(d), max_iter=50,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients), np.asarray(local.coefficients),
            atol=5e-3,
        )


class TestEntityAllToAll:
    """The shuffle analog: re-key rows to entity-owning devices in-jit."""

    def test_round_trip_lossless(self, mesh8, rng):
        from photon_ml_tpu.parallel.shuffle import (
            entity_all_to_all,
            reshard_capacity,
        )

        n, n_dev, k = 256, 8, 4
        codes = rng.integers(0, 40, size=n).astype(np.int32)
        codes[::17] = -1  # padding rows sprinkled in
        values = rng.normal(size=n).astype(np.float32)
        feats = rng.normal(size=(n, k)).astype(np.float32)
        cap = reshard_capacity(codes, n_dev)
        out = entity_all_to_all(
            mesh8,
            jnp.asarray(codes),
            {"v": jnp.asarray(values), "x": jnp.asarray(feats)},
            cap=cap,
        )
        assert int(np.asarray(out.dropped).sum()) == 0
        real = codes >= 0
        assert int(np.asarray(out.received).sum()) == int(real.sum())
        out_codes = np.asarray(out.entity_codes)
        out_v = np.asarray(out.payload["v"])
        got = out_codes >= 0
        # multiset of (code, value) pairs survives the re-shard
        sent = sorted(zip(codes[real].tolist(), values[real].tolist()))
        recv = sorted(zip(out_codes[got].tolist(), out_v[got].tolist()))
        assert sent == recv
        # each device block holds only entities it owns (code % n_dev)
        per_dev = out_codes.reshape(n_dev, -1)
        for d in range(n_dev):
            owned = per_dev[d][per_dev[d] >= 0]
            assert np.all(owned % n_dev == d)
        # payload rows stay aligned with their codes
        out_x = np.asarray(out.payload["x"])
        code_to_row = {}
        for i in range(n):
            if real[i]:
                code_to_row.setdefault(
                    (codes[i], round(float(values[i]), 5)), feats[i]
                )
        for j in np.nonzero(got)[0][:20]:
            key = (out_codes[j], round(float(out_v[j]), 5))
            np.testing.assert_allclose(out_x[j], code_to_row[key], rtol=1e-6)

    def test_overflow_is_reported(self, mesh8, rng):
        from photon_ml_tpu.parallel.shuffle import entity_all_to_all

        n = 64
        codes = np.zeros(n, np.int32)  # every row -> device 0
        out = entity_all_to_all(
            mesh8,
            jnp.asarray(codes),
            {"v": jnp.ones(n, jnp.float32)},
            cap=8,  # each source may send only 8 rows to device 0
        )
        # 8 sources x 8 rows each = 64 slots but only 8 rows per source fit
        assert int(np.asarray(out.received).sum()) == n - int(
            np.asarray(out.dropped).sum()
        )
        assert int(np.asarray(out.dropped).sum()) == 0  # 8 rows/src fit cap
        out2 = entity_all_to_all(
            mesh8,
            jnp.asarray(codes),
            {"v": jnp.ones(n, jnp.float32)},
            cap=4,
        )
        assert int(np.asarray(out2.dropped).sum()) == n - 8 * 4


class TestFeatureSharded:
    def test_value_and_grad_exact(self, mesh4x2, rng):
        n, d = 64, 16
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        batch = make_dense_batch(x, y)
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_local, g_local = obj.value_and_gradient(w, batch, 0.2)
        vg = feature_sharded_value_and_grad(obj, mesh4x2)
        v, g = vg(w, batch.features, batch.labels, batch.offsets,
                  batch.weights, jnp.float32(0.2))
        np.testing.assert_allclose(float(v), float(v_local), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_local), atol=1e-4)

    def test_sharded_fit_matches_replicated(self, mesh4x2, rng):
        n, d = 128, 16
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.uniform(size=n) > 0.5).astype(np.float32)
        batch = make_dense_batch(x, y)
        obj = GLMObjective(LOGISTIC, d)
        fit = feature_sharded_fit(obj, mesh4x2, max_iter=50)
        res = fit(jnp.zeros(d), batch.features, batch.labels, batch.offsets,
                  batch.weights, jnp.float32(0.1))
        local = minimize_lbfgs(
            lambda w_: obj.value_and_gradient(w_, batch, 0.1),
            jnp.zeros(d), max_iter=50,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients), np.asarray(local.coefficients),
            atol=5e-3,
        )
        # Shared optimizer => identical convergence bookkeeping shape.
        np.testing.assert_allclose(
            float(res.value), float(local.value), rtol=1e-5
        )
        assert int(res.iterations) > 0

    def test_sparse_sharded_fit_matches_replicated(self, mesh4x2, rng):
        from photon_ml_tpu.parallel import (
            feature_shard_sparse_batch,
            feature_sharded_sparse_fit,
        )

        # d chosen NOT to divide into equal blocks so d_pad > d and the
        # padded-slot assertion below is non-vacuous.
        batch, _ = sparse_problem(rng, n=128, d=45, k=8)
        d = 45
        obj = GLMObjective(LOGISTIC, d)
        sharded, block_dim = feature_shard_sparse_batch(
            batch, d, num_blocks=2, rows_multiple=4
        )
        d_pad = 2 * block_dim
        assert d_pad > d
        fit = feature_sharded_sparse_fit(obj, mesh4x2, max_iter=50)
        res = fit(jnp.zeros(d_pad), sharded, jnp.float32(0.1))
        local = minimize_lbfgs(
            lambda w_: obj.value_and_gradient(w_, batch, 0.1),
            jnp.zeros(d), max_iter=50,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients)[:d],
            np.asarray(local.coefficients), atol=5e-3,
        )
        # Padded vocabulary slots never see data => exactly zero.
        np.testing.assert_array_equal(np.asarray(res.coefficients)[d:], 0.0)

    def test_sparse_sharded_owlqn_matches_replicated(self, mesh4x2, rng):
        from photon_ml_tpu.optim.lbfgs import minimize_owlqn
        from photon_ml_tpu.parallel import feature_shard_sparse_batch
        from photon_ml_tpu.parallel.distributed import (
            feature_sharded_sparse_fit_owlqn,
        )

        batch, _ = sparse_problem(rng, n=128, d=45, k=8)
        d = 45
        obj = GLMObjective(LOGISTIC, d)
        sharded, block_dim = feature_shard_sparse_batch(
            batch, d, num_blocks=2, rows_multiple=4
        )
        fit = feature_sharded_sparse_fit_owlqn(obj, mesh4x2, max_iter=50)
        res = fit(
            jnp.zeros(2 * block_dim), sharded,
            jnp.float32(0.05), jnp.float32(0.2),
            jnp.ones(2 * block_dim, jnp.float32),
        )
        local = minimize_owlqn(
            lambda w_: obj.value_and_gradient(w_, batch, 0.05),
            jnp.zeros(d), 0.2, max_iter=50,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients)[:d],
            np.asarray(local.coefficients), atol=5e-3,
        )
        # L1 must produce sparsity, identically in both runs
        assert (np.asarray(res.coefficients)[:d] == 0).sum() == (
            np.asarray(local.coefficients) == 0
        ).sum()

    def test_sparse_sharded_value_and_grad_exact(self, mesh4x2, rng):
        from photon_ml_tpu.parallel import (
            feature_shard_sparse_batch,
            feature_sharded_sparse_fit,  # noqa: F401 (import check)
        )
        from photon_ml_tpu.parallel.distributed import (
            feature_sharded_sparse_value_and_grad,
        )

        batch, _ = sparse_problem(rng, n=64, d=40, k=8)
        d = 40
        obj = GLMObjective(LOGISTIC, d)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v_local, g_local = obj.value_and_gradient(w, batch, 0.2)
        sharded, block_dim = feature_shard_sparse_batch(
            batch, d, num_blocks=2, rows_multiple=4
        )
        w_pad = jnp.zeros(2 * block_dim).at[:d].set(w)
        vg = feature_sharded_sparse_value_and_grad(obj, mesh4x2)
        v, g = vg(w_pad, sharded, jnp.float32(0.2))
        np.testing.assert_allclose(float(v), float(v_local), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g)[:d], np.asarray(g_local), atol=1e-4
        )


class TestFeatureShardedCompositions:
    """The reference composes normalization, variances, box constraints
    and per-iteration model tracking freely with distribution
    (NormalizationContext.scala:119-157, DistributedOptimizationProblem
    .scala:79-93, LBFGS.scala:77, Driver.scala:329-372); each combination
    must match the replicated path exactly (fp32 noise only)."""

    def _problem(self, rng, n=128, d=45, k=8):
        batch, _ = sparse_problem(rng, n=n, d=d, k=k)
        return batch, d

    def _norm(self, batch, d):
        from photon_ml_tpu.data.stats import compute_summary
        from photon_ml_tpu.ops.normalization import (
            NormalizationType,
            build_normalization,
        )

        s = compute_summary(batch, d)
        return build_normalization(
            NormalizationType.STANDARDIZATION,
            mean=s.mean, std=s.std, max_magnitude=s.max_magnitude,
        )

    @pytest.mark.parametrize("kernel", ["scatter", "tiled"])
    def test_normalization_matches_replicated(self, mesh4x2, rng, kernel):
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import (
            train_feature_sharded,
            train_generalized_linear_model,
        )
        from photon_ml_tpu.optim import RegularizationType

        batch, d = self._problem(rng)
        norm = self._norm(batch, d)
        kwargs = dict(
            regularization_type=RegularizationType.L2,
            regularization_weights=[0.5], max_iter=40,
        )
        m_rep, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, normalization=norm,
            kernel="scatter", **kwargs,
        )
        m_sh, _ = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d, mesh=mesh4x2,
            normalization=norm, kernel=kernel, **kwargs,
        )
        np.testing.assert_allclose(
            np.asarray(m_sh[0.5].means), np.asarray(m_rep[0.5].means),
            atol=5e-3,
        )

    def test_box_matches_replicated(self, mesh4x2, rng):
        from photon_ml_tpu.optim.common import BoxConstraints
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import (
            train_feature_sharded,
            train_generalized_linear_model,
        )
        from photon_ml_tpu.optim import RegularizationType

        batch, d = self._problem(rng)
        box = BoxConstraints(
            lower=jnp.full((d,), -0.2, jnp.float32),
            upper=jnp.full((d,), 0.2, jnp.float32),
        )
        kwargs = dict(
            regularization_type=RegularizationType.L2,
            regularization_weights=[0.1], max_iter=40,
        )
        m_rep, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, box=box,
            kernel="scatter", **kwargs,
        )
        m_sh, _ = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d, mesh=mesh4x2,
            box=box, kernel="scatter", **kwargs,
        )
        w = np.asarray(m_sh[0.1].means)
        assert np.all(w >= -0.2 - 1e-6) and np.all(w <= 0.2 + 1e-6)
        # the box must actually bind somewhere or this test is vacuous
        assert np.any(np.isclose(np.abs(w), 0.2, atol=1e-4))
        np.testing.assert_allclose(
            w, np.asarray(m_rep[0.1].means), atol=5e-3
        )

    @pytest.mark.parametrize("kernel", ["scatter", "tiled"])
    def test_variances_match_replicated(self, mesh4x2, rng, kernel):
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import (
            train_feature_sharded,
            train_generalized_linear_model,
        )
        from photon_ml_tpu.optim import RegularizationType

        batch, d = self._problem(rng)
        kwargs = dict(
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0], max_iter=40,
        )
        m_rep, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d,
            compute_variances=True, kernel="scatter", **kwargs,
        )
        m_sh, _ = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d, mesh=mesh4x2,
            compute_variances=True, kernel=kernel, **kwargs,
        )
        assert m_sh[1.0].coefficients.variances is not None
        np.testing.assert_allclose(
            np.asarray(m_sh[1.0].coefficients.variances),
            np.asarray(m_rep[1.0].coefficients.variances), rtol=2e-3,
        )

    def test_track_models_matches_replicated(self, mesh4x2, rng):
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import (
            train_feature_sharded,
            train_generalized_linear_model,
        )
        from photon_ml_tpu.optim import RegularizationType

        batch, d = self._problem(rng)
        kwargs = dict(
            regularization_type=RegularizationType.L2,
            regularization_weights=[0.5], max_iter=10,
        )
        _, r_rep = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, track_models=True,
            kernel="scatter", **kwargs,
        )
        _, r_sh = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d, mesh=mesh4x2,
            track_models=True, kernel="scatter", **kwargs,
        )
        rep, sh = r_rep[0.5], r_sh[0.5]
        assert sh.tracker.coefs is not None
        n_rep = int(rep.tracker.count)
        assert int(sh.tracker.count) == n_rep
        np.testing.assert_allclose(
            np.asarray(sh.tracker.coefs)[:n_rep],
            np.asarray(rep.tracker.coefs)[:n_rep], atol=5e-3,
        )

    def test_tron_normalization_matches_replicated(self, mesh4x2, rng):
        from photon_ml_tpu.optim import OptimizerType, RegularizationType
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import (
            train_feature_sharded,
            train_generalized_linear_model,
        )

        batch, d = self._problem(rng)
        norm = self._norm(batch, d)
        kwargs = dict(
            optimizer_type=OptimizerType.TRON,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0], max_iter=15,
        )
        m_rep, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, normalization=norm,
            kernel="scatter", **kwargs,
        )
        m_sh, _ = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d, mesh=mesh4x2,
            normalization=norm, kernel="scatter", **kwargs,
        )
        np.testing.assert_allclose(
            np.asarray(m_sh[1.0].means), np.asarray(m_rep[1.0].means),
            atol=5e-3,
        )

    def test_owlqn_box_norm_composed(self, mesh4x2, rng):
        # the full stack at once: elastic-net OWL-QN + box + intercept
        # exemption on the sharded path, vs the replicated problem layer
        from photon_ml_tpu.optim.common import BoxConstraints
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import (
            train_feature_sharded,
            train_generalized_linear_model,
        )
        from photon_ml_tpu.optim import RegularizationType

        batch, d = self._problem(rng)
        box = BoxConstraints(
            lower=jnp.full((d,), -0.3, jnp.float32),
            upper=jnp.full((d,), 0.3, jnp.float32),
        )
        kwargs = dict(
            regularization_type=RegularizationType.ELASTIC_NET,
            elastic_net_alpha=0.5,
            regularization_weights=[0.2], max_iter=40,
        )
        m_rep, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, box=box,
            kernel="scatter", **kwargs,
        )
        m_sh, _ = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d, mesh=mesh4x2,
            box=box, kernel="scatter", **kwargs,
        )
        w = np.asarray(m_sh[0.2].means)
        assert np.all(w >= -0.3 - 1e-6) and np.all(w <= 0.3 + 1e-6)
        np.testing.assert_allclose(
            w, np.asarray(m_rep[0.2].means), atol=5e-3
        )
