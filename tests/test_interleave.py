"""The interleaving harness + the concurrency defects ISSUE 11's rules
surfaced, each pinned by a replayable schedule.

Every regression test here encodes a schedule family that FAILS on the
pre-fix code (revert the named fix and the seed sweep reports the
violating seeds) and passes on the fixed code for every seed swept:

- frontend lost-response-at-close: ``_on_done`` must enqueue the
  response BEFORE decrementing the pending count;
- watcher double-rollback: a stale health observation from the bad
  generation must not re-arm ``_rollback_wanted`` after the rollback
  disarmed the watch;
- ServingModel swap serialization: concurrent stage/flip protocols
  must mint distinct, monotonic generations;
- ModelBank.quarantine_re: concurrent quarantines (operator op vs the
  dispatcher's auto-quarantine) must not lose updates, and readers see
  snapshot sets only;
- batcher shed accounting: metrics callbacks run OUTSIDE the
  Condition-backed queue lock (PL010's finding, verified dynamically).
"""

import json
import socket
import threading
import time
from concurrent.futures import Future

import pytest

from photon_ml_tpu.testing.interleave import (
    DeadlockError,
    InterleaveScheduler,
    explore,
)


# -- harness unit tests -------------------------------------------------------


class TestScheduler:
    def test_same_seed_same_trace(self):
        def scenario(sched):
            log = []
            lock = sched.Lock()

            def worker(tag):
                def body():
                    for _ in range(3):
                        with lock:
                            log.append(tag)
                return body

            sched.spawn(worker("a"), name="a")
            sched.spawn(worker("b"), name="b")
            sched.log = log
            return None

        s1 = InterleaveScheduler(seed=42)
        scenario(s1)
        s1.run()
        s2 = InterleaveScheduler(seed=42)
        scenario(s2)
        s2.run()
        assert s1.log == s2.log
        assert s1.trace == s2.trace
        # across a seed sweep, schedules actually differ (determinism
        # without diversity would make explore() a single test)
        traces = set()
        for seed in range(8):
            s = InterleaveScheduler(seed=seed)
            scenario(s)
            s.run()
            traces.add(tuple(s.trace))
        assert len(traces) > 1

    def test_lock_mutual_exclusion(self):
        def scenario(sched):
            lock = sched.Lock()
            state = {"in_cs": 0, "max_in_cs": 0, "count": 0}

            def body():
                for _ in range(5):
                    with lock:
                        state["in_cs"] += 1
                        state["max_in_cs"] = max(
                            state["max_in_cs"], state["in_cs"]
                        )
                        state["count"] += 1
                        state["in_cs"] -= 1

            for i in range(3):
                sched.spawn(body, name=f"w{i}")

            def verify():
                assert state["max_in_cs"] == 1
                assert state["count"] == 15

            return verify

        explore(scenario, seeds=range(10))

    def test_condition_wait_notify(self):
        def scenario(sched):
            lock = sched.Lock()
            cond = sched.Condition(lock)
            box = []

            def consumer():
                with lock:
                    # canonical timed-wait loop: the timeout may fire
                    # before the producer is scheduled (timeouts race
                    # runnable threads under the tick policy), so the
                    # predicate is re-checked, never the return value
                    while not box:
                        cond.wait(timeout=10.0)
                    box.append("consumed")

            def producer():
                with lock:
                    box.append("item")
                    cond.notify()

            sched.spawn(consumer, name="consumer")
            sched.spawn(producer, name="producer")
            return lambda: (
                None if box == ["item", "consumed"]
                else pytest.fail(box)
            )

        explore(scenario, seeds=range(10))

    def test_virtual_timeout_fires_without_real_waiting(self):
        sched = InterleaveScheduler(seed=0)
        ev = sched.Event()
        out = {}

        def waiter():
            t0 = sched.time()
            out["got"] = ev.wait(timeout=3600.0)  # an hour, virtually
            out["elapsed"] = sched.time() - t0

        sched.spawn(waiter, name="waiter")
        wall0 = time.monotonic()
        sched.run()
        assert time.monotonic() - wall0 < 5.0
        assert out["got"] is False
        assert out["elapsed"] >= 3600.0

    def test_deadlock_detection(self):
        sched = InterleaveScheduler(seed=1)
        a, b = sched.Lock(), sched.Lock()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        sched.spawn(t1, name="t1")
        sched.spawn(t2, name="t2")
        # the inversion deadlocks under SOME schedule; sweep seeds until
        # one manifests (deterministically — the sweep itself is fixed)
        saw_deadlock = False
        for seed in range(30):
            s = InterleaveScheduler(seed=seed)
            la, lb = s.Lock(), s.Lock()

            def mk(first, second):
                def body():
                    with first:
                        with second:
                            pass
                return body

            s.spawn(mk(la, lb), name="t1")
            s.spawn(mk(lb, la), name="t2")
            try:
                s.run()
            except DeadlockError:
                saw_deadlock = True
                break
        assert saw_deadlock, "no schedule manifested the inversion"

    def test_patched_queue_event_thread(self):
        import queue

        sched = InterleaveScheduler(seed=5)
        out = []
        with sched.patched():
            q = queue.Queue(maxsize=2)
            done = threading.Event()

            def worker():
                while True:
                    try:
                        item = q.get(timeout=0.25)
                    except queue.Empty:
                        if done.is_set():
                            return
                        continue
                    out.append(item)

            th = threading.Thread(target=worker)
            th.start()

            def producer():
                for i in range(5):
                    q.put(i, timeout=5.0)
                done.set()

            sched.spawn(producer, name="producer")
        sched.run()
        assert out == [0, 1, 2, 3, 4]


# -- defect 1: frontend lost response at close --------------------------------


class _FakeSocket:
    """Duck-typed socket for _Connection: recv yields scripted lines
    then virtual-sleeps (a preemption point) before timing out; sendall
    records every byte."""

    def __init__(self, sched, lines=()):
        self.sched = sched
        self.to_read = list(lines)
        self.sent = b""
        self.closed = False

    def settimeout(self, t):
        pass

    def recv(self, n):
        if self.to_read:
            return self.to_read.pop(0)
        self.sched.sleep(0.05)
        raise socket.timeout()

    def sendall(self, data):
        self.sched.sleep(0.001)
        self.sent += data

    def close(self):
        self.closed = True

    def responses(self):
        return [
            json.loads(line)
            for line in self.sent.decode("utf-8").splitlines()
            if line.strip()
        ]


class _FakeFrontend:
    """Just enough ServingFrontend surface for a _Connection."""

    max_line_bytes = 1 << 20
    writer_queue_max = 16
    metrics = None

    def __init__(self):
        self.notes = []

    def _note(self, event, n=1):
        self.notes.append(event)

    def _forget(self, conn):
        pass

    def _handle_score(self, conn, obj):
        pass


class TestFrontendResponseNotLostAtClose:
    """PRE-FIX: ``_on_done`` decremented ``pending`` BEFORE enqueueing
    the response; a closing writer that polled between the two steps
    saw pending==0 + empty queue, exited, and the final response was
    silently dropped. The fix enqueues first. This sweep replays
    schedules that include the exact bad window."""

    def _scenario(self, sched):
        from photon_ml_tpu.serving.frontend import (
            ServingFrontend,
            _Connection,
        )

        state = {}
        with sched.patched():
            fe = _FakeFrontend()
            sock = _FakeSocket(sched)
            conn = _Connection(fe, sock, "test:1")
            state["conn"], state["sock"] = conn, sock
            # one request in flight, exactly as _handle_score records it
            conn._note_pending(+1)
            fut = Future()
            fut.set_result(1.25)

            def dispatcher():
                # the dispatcher thread completing the last in-flight
                # request while the connection is draining
                ServingFrontend._on_done(_OnDoneHost(fe), conn, "u1", fut)

            def closer():
                conn.closing.set()

            sched.spawn(dispatcher, name="dispatcher")
            sched.spawn(closer, name="closer")
            sched.run()  # inside the window: bodies use time.*/queue

        def verify():
            resps = state["sock"].responses()
            uids = [r.get("uid") for r in resps]
            assert "u1" in uids, (
                f"final response dropped at close; wire got {resps}"
            )

        return verify

    def test_no_schedule_drops_the_final_response(self):
        explore(self._scenario, seeds=range(40))


class _OnDoneHost:
    """Binds ServingFrontend._on_done's self-surface onto the fake."""

    def __init__(self, fe):
        self.on_outcome = None
        self.on_completion = None
        self._completed = 0
        self._completed_lock = threading.Lock()
        self._fe = fe

    def _note(self, event, n=1):
        self._fe._note(event, n)


# -- defect 2: watcher double rollback ----------------------------------------


class _FakeGen:
    def __init__(self, generation, parent, model_dir="m"):
        self.generation = generation
        self.parent = parent
        self.model_dir = f"{model_dir}{generation}"


class _FakeRegistry:
    root = "<fake>"

    def __init__(self, gens):
        self._gens = {g.generation: g for g in gens}
        self.quarantined = []

    def latest(self):
        live = [
            g for n, g in self._gens.items()
            if n not in self.quarantined
        ]
        return max(live, key=lambda g: g.generation) if live else None

    def generation(self, n):
        return self._gens.get(n)

    def lineage(self, n):
        out = []
        while n is not None and n in self._gens:
            out.append(n)
            n = self._gens[n].parent
        return out

    def quarantine_generation(self, n, reason=""):
        self.quarantined.append(n)
        return f"quarantined-{n}"


class _FakeSwapModel:
    """stage_and_swap with a staging delay (a real preemption window)."""

    def __init__(self, sched):
        self.sched = sched
        self.swaps = []

    def stage_and_swap(self, model_dir, **kw):
        time.sleep(0.2)  # staging takes (virtual) time
        self.swaps.append(model_dir)

        class R:
            ok = True
            error = ""

        return R()


class TestWatcherSingleRollback:
    """PRE-FIX: ``_watching_swap``/``_rollback_wanted`` were bare;
    an observer preempted between the watch check and the flag write
    re-armed the trigger DURING the rollback, and the watcher rolled
    back a second time onto the grandparent (quarantining a healthy
    generation). The fix guards both flags and clears the trigger when
    the watch disarms."""

    def _scenario(self, sched):
        from photon_ml_tpu.registry.watcher import (
            RegistryWatcher,
            RollbackPolicy,
        )

        state = {}
        with sched.patched():
            registry = _FakeRegistry([
                _FakeGen(1, None), _FakeGen(2, 1), _FakeGen(3, 2),
            ])
            model = _FakeSwapModel(sched)
            watcher = RegistryWatcher(
                registry, model,
                poll_s=0.05,
                policy=RollbackPolicy(
                    window=8, min_requests=2, max_unhealthy_rate=0.4
                ),
            )
            state["watcher"], state["registry"] = watcher, registry
            watcher.start()

            def feeder():
                # unhealthy traffic against the promoted generation —
                # keeps feeding until a rollback lands (the stragglers
                # ARE the double-rollback window), bounded so a broken
                # watcher still terminates the schedule
                for _ in range(300):
                    watcher.observe_outcome(degraded=True)
                    time.sleep(0.01)
                    if any(
                        r.action == "rollback" for r in watcher.history
                    ):
                        break
                # a few stragglers AFTER the rollback, the exact
                # pre-fix re-arm window
                for _ in range(5):
                    watcher.observe_outcome(degraded=True)
                    time.sleep(0.01)

            def stopper():
                # wait until one rollback landed, let stragglers fire,
                # then stop the watcher
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    if any(
                        r.action == "rollback" for r in watcher.history
                    ):
                        break
                    time.sleep(0.05)
                time.sleep(1.0)  # straggler window
                watcher.stop(timeout_s=10.0)

            sched.spawn(feeder, name="feeder-a")
            sched.spawn(feeder, name="feeder-b")
            sched.spawn(stopper, name="stopper")
            sched.run()

        def verify():
            watcher, registry = state["watcher"], state["registry"]
            rollbacks = [
                r for r in watcher.history if r.action == "rollback"
            ]
            assert len(rollbacks) == 1, (
                f"double rollback: {[(r.action, r.registry_generation) for r in watcher.history]}"
            )
            # rolled back exactly one step: 3 -> 2, never to 1
            assert rollbacks[0].registry_generation == 2
            assert registry.quarantined == [3], registry.quarantined

        return verify

    def test_stale_window_never_rolls_back_twice(self):
        explore(self._scenario, seeds=range(25))


# -- defect 3: concurrent swap serialization ----------------------------------


class _FakePrograms:
    ladder = (1, 8)

    def __init__(self, sched):
        self.sched = sched

    def ensure_compiled(self, bank, partial=False):
        time.sleep(0.1)  # warmup takes (virtual) time
        return 0

    def executable(self, spec, B, partial=False):
        return object()


class _FakeBank:
    def __init__(self, spec):
        self.spec = spec
        self.arrays = {}
        self.generation = 1
        self.retired = False
        self.index_maps = {}
        self.shard_widths = {}


class TestSwapSerialization:
    """PRE-FIX: two threads in ``swap_to_bank``/``_flip`` both read the
    same ``prev`` and minted the same generation number (and on the
    donated path would both consume prev's buffers). The fix serializes
    whole stage/flip protocols under ``_stage_lock``."""

    def _scenario(self, sched):
        import photon_ml_tpu.serving.swap as swap_mod

        state = {}
        saved = swap_mod.place_on_device
        swap_mod.place_on_device = lambda arrays: arrays
        try:
            with sched.patched():
                sm = swap_mod.ServingModel(
                    _FakeBank(spec=("g1",)),
                    programs=_FakePrograms(sched),
                )
                state["sm"] = sm

                def swapper(tag):
                    def body():
                        sm.swap_to_bank(_FakeBank(spec=(tag,)))
                    return body

                sched.spawn(swapper("g2"), name="swap-a")
                sched.spawn(swapper("g3"), name="swap-b")
                sched.run()
        finally:
            swap_mod.place_on_device = saved

        def verify():
            sm = state["sm"]
            gens = [r.generation for r in sm.swap_history]
            assert sorted(gens) == [2, 3], (
                f"generations collided under concurrent swaps: {gens}"
            )
            assert sm.generation == 3

        return verify

    def test_concurrent_swaps_mint_distinct_generations(self):
        explore(self._scenario, seeds=range(20))


# -- defect 4: quarantine copy-on-write ---------------------------------------


class TestQuarantineCopyOnWrite:
    """PRE-FIX: ``quarantine_re`` mutated a plain ``set`` in place —
    a dispatcher reading the set between two reads saw it change size
    mid-use (this scenario fails on that code). The fix publishes a
    fresh frozenset under a writer lock: readers see the old snapshot
    or the new one, never a set mid-mutation, and racing writers
    cannot lose an update (the lock serializes the read-copy-write;
    that window sits between bytecodes, below the harness's preemption
    granularity, so it is pinned structurally by the lock + this
    no-lost-update assert rather than by a manifesting schedule)."""

    def _scenario(self, sched):
        from photon_ml_tpu.serving.model_bank import ModelBank

        state = {}
        with sched.patched():
            bank = ModelBank(
                generation=1,
                spec=(
                    ("re", "re-a", "memberId", "s1", 4, 2, 3),
                    ("re", "re-b", "jobId", "s1", 4, 2, 3),
                ),
                arrays={},
                entity_rows={},
                index_maps={},
                shard_widths={"s1": 3},
            )
            state["bank"] = bank
            seen = []
            state["seen"] = seen

            def q(re_type):
                def body():
                    bank.quarantine_re(re_type)
                return body

            def reader():
                for _ in range(6):
                    snap = bank.quarantined_re_types
                    # iterate the snapshot with preemption in between:
                    # an in-place-mutated set would change size mid-use
                    before = len(snap)
                    time.sleep(0.01)
                    assert len(snap) == before
                    seen.append(frozenset(snap))
                    time.sleep(0.01)

            sched.spawn(q("memberId"), name="op-quarantine")
            sched.spawn(q("jobId"), name="auto-quarantine")
            sched.spawn(reader, name="dispatcher-read")
            sched.run()

        def verify():
            bank = state["bank"]
            assert bank.quarantined_re_types == {"memberId", "jobId"}, (
                f"lost quarantine update: {bank.quarantined_re_types}"
            )

        return verify

    def test_no_lost_updates_and_snapshot_reads(self):
        explore(self._scenario, seeds=range(20))


# -- defect 5: batcher shed accounting outside the queue lock -----------------


class _LockProbeMetrics:
    """Asserts the batcher's Condition-backed queue lock is NOT held
    when the metrics callbacks run (PL010's finding, dynamically)."""

    def __init__(self):
        self.batcher = None
        self.sheds = []
        self.violations = []

    def _held_by_caller(self) -> bool:
        lock = self.batcher._lock
        owner = getattr(lock, "_owner", None)
        # cooperative world: the caller IS the scheduler's running task
        return owner is not None and owner is lock._sched._running

    def record_shed(self, reason):
        if self._held_by_caller():
            self.violations.append(f"record_shed({reason}) under lock")
        self.sheds.append(reason)

    def record_drain(self, report):
        if self._held_by_caller():
            self.violations.append("record_drain under lock")

    def __getattr__(self, name):
        if name.startswith("record_"):
            return lambda *a, **kw: None
        raise AttributeError(name)


class TestShedAccountingOutsideLock:
    """PRE-FIX: record_shed/record_drain ran inside ``with self._lock``
    — a foreign critical section under the Condition-backed queue lock
    (every parked submitter and the dispatcher wait out the metrics
    lock). The fix carries the shed reason on the exception and records
    after release."""

    def _scenario(self, sched):
        import numpy as np

        from photon_ml_tpu.serving.batcher import MicroBatcher, ScoreRequest
        from photon_ml_tpu.serving.admission import ServingError

        class SlowPrograms:
            ladder = (1, 2)

            def score(self, bank, batch):
                time.sleep(5.0)  # pins the dispatcher so the queue fills
                return np.zeros(batch.offsets.shape[0], np.float32)

        class Bank:
            generation = 1
            spec = ("fe",)
            used_shards = ()
            shard_widths = {}
            re_types = ()
            quarantined_re_types = frozenset()
            entity_rows = {}
            retired = False

        state = {}
        with sched.patched():
            metrics = _LockProbeMetrics()
            batcher = MicroBatcher(
                lambda: Bank(), SlowPrograms(), metrics, max_queue=1,
            )
            metrics.batcher = batcher
            state["metrics"] = metrics

            def req(uid, deadline_ms=None):
                return ScoreRequest(
                    uid=uid, indices={}, values={}, entity_ids={},
                    deadline_ms=deadline_ms,
                )

            def submitter(uid, deadline):
                def body():
                    try:
                        batcher.submit(req(uid, deadline))
                    except ServingError:
                        # shed / closed are named outcomes, not bugs —
                        # the probe only cares WHERE accounting runs
                        pass
                return body

            def closer():
                # give the flood time to shed, then shut down
                time.sleep(30.0)
                batcher.drain(timeout_s=30.0)

            # first fills the in-flight slot, the rest contend for the
            # 1-slot queue with tight budgets -> queue_full sheds
            sched.spawn(submitter("a", None), name="sub-a")
            for i in range(3):
                sched.spawn(
                    submitter(f"b{i}", 50.0), name=f"sub-b{i}"
                )

            sched.spawn(closer, name="closer")
            sched.run()

        def verify():
            metrics = state["metrics"]
            assert not metrics.violations, metrics.violations
            self.total_sheds += len(metrics.sheds)

        return verify

    def test_metrics_callbacks_never_run_under_queue_lock(self):
        self.total_sheds = 0
        explore(self._scenario, seeds=range(10), max_steps=500_000)
        # not every schedule sheds (the closer may drain first), but
        # the sweep as a whole must exercise the accounting path
        assert self.total_sheds > 0, "no schedule shed — not probative"
