"""Online scoring service tests (ISSUES 7+8): scoring parity with the
batch driver (bitwise), micro-batch demux under concurrent submitters,
hot-swap parity + rollback, padded-shape ladder selection, the
zero-recompile / one-readback-per-dispatch contract, and the
serving-under-fire layer — admission control (shed/deadline), graceful
FE-only degradation, and bounded shutdown (every future exactly one
terminal outcome under clean close, drain, and a KILL fault plan).
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np
import pytest
import jax.numpy as jnp

from photon_ml_tpu.game.config import FeatureShardConfiguration
from photon_ml_tpu.game.data import build_game_dataset
from photon_ml_tpu.game.model_io import LoadedGameModel
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.serving import (
    AdmissionController,
    BatcherClosed,
    DeadlineExceeded,
    DrainTimeout,
    EntityRowIndex,
    MicroBatcher,
    RequestShed,
    ScoreOutcome,
    ServingMetrics,
    ServingModel,
    ServingPrograms,
    build_model_bank,
    request_from_record,
    requests_from_dataset,
    select_shape,
)
from photon_ml_tpu.task import TaskType

SHARDS = [
    FeatureShardConfiguration("g", ["features"]),
    FeatureShardConfiguration("u", ["userFeatures"]),
]


def synth_records(rng, n=60, n_users=7, d_g=5, d_u=3):
    recs = []
    for i in range(n):
        u = int(rng.integers(0, n_users))
        recs.append({
            "uid": f"r{i}",
            "response": float(rng.integers(0, 2)),
            "offset": float(rng.normal() * 0.1),
            "weight": float(rng.uniform(0.5, 2.0)),
            "metadataMap": {"userId": f"user{u}"},
            "features": [
                {"name": f"g{j}", "term": "", "value": float(rng.normal())}
                for j in range(d_g)
            ],
            "userFeatures": [
                {"name": f"u{j}", "term": "", "value": float(rng.normal())}
                for j in range(d_u)
            ],
        })
    return recs


def synth_model(rng, n_users=7, d_g=5, d_u=3, *, scale=1.0, drop_user=True):
    """A LoadedGameModel with one FE + one per-user RE coordinate; one
    user deliberately has NO model (the unknown-entity path)."""
    lm = LoadedGameModel()
    lm.fixed_effects["global"] = (
        "g",
        {f"g{j}\t": float(rng.normal()) * scale for j in range(d_g)},
    )
    users = range(n_users - 1) if drop_user else range(n_users)
    lm.random_effects["per-user"] = (
        "userId",
        "u",
        {
            f"user{e}": {
                f"u{j}\t": float(rng.normal()) * scale for j in range(d_u)
            }
            for e in users
        },
    )
    return lm


def batch_reference_scores(lm, ds):
    """What the batch scoring driver writes: raw scores + offsets."""
    return np.asarray(
        lm.score(ds, TaskType.LOGISTIC_REGRESSION) + jnp.asarray(ds.offsets)
    )[: ds.num_real_rows]


def make_bank(lm, ds, **kw):
    imaps = {sid: sd.index_map for sid, sd in ds.shards.items()}
    widths = {sid: sd.indices.shape[1] for sid, sd in ds.shards.items()}
    return build_model_bank(lm, imaps, widths, **kw)


@pytest.fixture
def served(rng):
    recs = synth_records(rng)
    ds = build_game_dataset(recs, SHARDS, ["userId"])
    lm = synth_model(rng)
    bank = make_bank(lm, ds)
    programs = ServingPrograms((1, 8, 64))
    programs.ensure_compiled(bank)
    return recs, ds, lm, bank, programs


class TestScoringParity:
    def test_serving_scores_bitwise_match_batch_scorer(self, served):
        """The acceptance bar: the request path reproduces the batch
        scoring driver's scores BITWISE, including offsets, masked
        unknown entities, and weights-irrelevance."""
        _, ds, lm, bank, programs = served
        ref = batch_reference_scores(lm, ds)
        metrics = ServingMetrics()
        with MicroBatcher(lambda: bank, programs, metrics) as mb:
            futs = [mb.submit(r) for r in requests_from_dataset(ds, bank)]
            got = np.asarray([f.result() for f in futs], np.float32)
        assert np.array_equal(got, ref)

    def test_single_request_dispatches_shape_one(self, served):
        _, ds, lm, bank, programs = served
        ref = batch_reference_scores(lm, ds)
        metrics = ServingMetrics()
        reqs = requests_from_dataset(ds, bank)
        with MicroBatcher(lambda: bank, programs, metrics) as mb:
            for i in (0, 7, 23):
                assert mb.score(reqs[i]) == ref[i]
        snap = metrics.snapshot()
        assert snap["shape_counts"] == {"1": 3}
        assert snap["pad_waste_frac"] == 0.0

    def test_unknown_entity_scores_through_fe_only(self, served):
        """A request whose entity the model never saw gets code -1 and
        scores 0 through the RE coordinate — exactly the batch scorer's
        masked-code semantics (synth_model drops the last user)."""
        recs, ds, lm, bank, _ = served
        missing = f"user{6}"
        assert any(
            r["metadataMap"]["userId"] == missing for r in recs
        ), "fixture must exercise the unknown entity"
        assert bank.entity_row("userId", missing) == -1
        assert bank.entity_row("userId", "user0") >= 0

    def test_fe_only_model_under_multi_shard_config(self, served):
        """An FE-only model served with a multi-shard request config:
        requests carry features for shards the spec never scores — the
        batch must assemble (and the AOT program run) on exactly the
        spec's shards, scoring bitwise the FE-only batch path."""
        _, ds, lm, _bank, _ = served
        fe = LoadedGameModel()
        fe.fixed_effects = dict(lm.fixed_effects)
        bank = make_bank(fe, ds)  # widths cover BOTH shards
        assert set(bank.shard_widths) == {"g", "u"}
        assert bank.used_shards == ("g",)
        programs = ServingPrograms((1, 8))
        programs.ensure_compiled(bank)
        ref = batch_reference_scores(fe, ds)
        with MicroBatcher(lambda: bank, programs) as mb:
            got = np.asarray(
                [mb.score(r) for r in requests_from_dataset(ds, bank)],
                np.float32,
            )
        assert np.array_equal(got, ref)

    def test_record_assembly_matches_dataset_assembly(self, served):
        """The stdin path (request_from_record through index maps) and
        the Avro replay path (requests_from_dataset) produce identical
        scores for the same logical record."""
        recs, ds, lm, bank, programs = served
        ref = batch_reference_scores(lm, ds)
        with MicroBatcher(lambda: bank, programs) as mb:
            for i in (0, 11, 42):
                req = request_from_record(recs[i], bank, SHARDS)
                assert mb.score(req) == ref[i]

    def test_record_width_overflow_raises(self, served):
        recs, ds, lm, bank, _ = served
        fat = dict(recs[0])
        fat["features"] = [
            {"name": f"g{j % 5}", "term": "", "value": 1.0}
            for j in range(bank.shard_widths["g"] + 1)
        ]
        with pytest.raises(ValueError, match="exceeds shard"):
            request_from_record(fat, bank, SHARDS)

    def test_record_missing_id_omits_metadata_and_scores_fe_only(
        self, served
    ):
        """A record with no resolvable entity id scores FE-only (same as
        an unknown entity) and its metadataMap OMITS the key — never the
        literal string "None" — matching the dataset path's records."""
        recs, ds, lm, bank, programs = served
        bare = dict(recs[0])
        bare.pop("metadataMap")
        req = request_from_record(bare, bank, SHARDS)
        assert req.entity_ids == {"userId": None}
        assert req.metadata is None
        unknown = dict(recs[0])
        unknown["metadataMap"] = {"userId": "no-such-user"}
        req_unknown = request_from_record(unknown, bank, SHARDS)
        assert req_unknown.metadata == {"userId": "no-such-user"}
        with MicroBatcher(lambda: bank, programs) as mb:
            assert mb.score(req) == mb.score(req_unknown)


class TestEntityRowIndex:
    def test_dict_backend(self):
        idx = EntityRowIndex(["a", "b", "c"])
        assert idx.backend == "dict"
        assert [idx.row_of(e) for e in ("a", "c", "zz")] == [0, 2, -1]
        assert idx.rows_of(["b", "nope", "a"]).tolist() == [1, -1, 0]

    def test_native_backend_matches_dict(self):
        ids = [f"member-{i}" for i in range(257)]
        try:
            native = EntityRowIndex(ids, native_threshold=1)
        except Exception:
            pytest.skip("native toolchain unavailable")
        if native.backend != "native":
            pytest.skip("native store fell back")
        plain = EntityRowIndex(ids)
        probe = ids[::13] + ["member-9999", ""]
        assert native.rows_of(probe).tolist() == plain.rows_of(probe).tolist()


class TestLadder:
    def test_select_shape_picks_smallest_fit(self):
        ladder = (1, 8, 64, 256)
        assert select_shape(1, ladder) == 1
        assert select_shape(2, ladder) == 8
        assert select_shape(8, ladder) == 8
        assert select_shape(65, ladder) == 256
        with pytest.raises(ValueError):
            select_shape(257, ladder)

    def test_bad_ladder_rejected(self):
        with pytest.raises(ValueError):
            ServingPrograms((8, 1))
        with pytest.raises(ValueError):
            ServingPrograms(())

    def test_coalesced_batches_use_ladder_shapes(self, served):
        """Submitting a burst while the dispatcher is busy coalesces the
        backlog into the smallest fitting padded shape."""
        _, ds, lm, bank, programs = served
        metrics = ServingMetrics()
        reqs = requests_from_dataset(ds, bank)
        with MicroBatcher(lambda: bank, programs, metrics) as mb:
            futs = [mb.submit(r) for r in reqs]
            for f in futs:
                f.result()
        snap = metrics.snapshot()
        shapes = {int(s) for s in snap["shape_counts"]}
        assert shapes <= {1, 8, 64}
        assert snap["requests"] == len(reqs)
        # occupancy accounting is consistent with the shape counts
        padded = sum(
            int(s) * c for s, c in snap["shape_counts"].items()
        )
        assert snap["batch_occupancy_mean"] == pytest.approx(
            len(reqs) / padded
        )

    def test_max_wait_coalesces_trickled_requests(self, served):
        """With a linger window, requests trickling in one at a time
        still form a multi-row batch."""
        _, ds, lm, bank, programs = served
        metrics = ServingMetrics()
        reqs = requests_from_dataset(ds, bank)[:8]
        with MicroBatcher(
            lambda: bank, programs, metrics, max_wait_s=0.25
        ) as mb:
            futs = [mb.submit(r) for r in reqs]
            for f in futs:
                f.result()
        snap = metrics.snapshot()
        assert snap["dispatches"] < len(reqs)


class TestProgramCache:
    def _bank(self, rng, d):
        from photon_ml_tpu.serving import bank_from_arrays

        return bank_from_arrays(
            fixed=[(
                "global", "g",
                rng.standard_normal(d).astype(np.float32),
            )],
            shard_widths={"g": 4},
        )

    def test_eviction_is_lru_not_fifo(self, rng):
        """Eviction under spec churn drops the COLDEST entry: a rung
        the live bank just used survives insertions from another spec
        (FIFO would evict it and force a hot-path recompile)."""
        bank_a = self._bank(rng, 16)
        bank_b = self._bank(rng, 32)
        programs = ServingPrograms((1, 8), max_entries=3)
        programs.ensure_compiled(bank_a)
        # touch (spec_a, 1): now the most recently used entry
        assert programs.executable(bank_a.spec, 1) is not None
        programs.ensure_compiled(bank_b)  # 4th insert evicts ONE entry
        assert programs.executable(bank_a.spec, 1) is not None, (
            "LRU must keep the just-used rung"
        )
        assert programs.executable(bank_a.spec, 8) is None, (
            "the untouched rung is the eviction victim"
        )

    def test_concurrent_warmup_compiles_each_shape_once(self, rng):
        """ensure_compiled is single-flight per (spec, shape): racing
        threads never compile the same program twice."""
        bank = self._bank(rng, 16)
        programs = ServingPrograms((1, 8, 64))
        errors = []

        def warm():
            try:
                programs.ensure_compiled(bank)
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=warm) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert programs.stats()["compile_count"] == 3


class TestMicroBatchDemux:
    def test_concurrent_submitters_each_get_their_own_score(self, served):
        """The demux invariant under contention: N threads hammering
        submit() each receive exactly their request's row."""
        _, ds, lm, bank, programs = served
        ref = batch_reference_scores(lm, ds)
        reqs = requests_from_dataset(ds, bank)
        errors = []

        def worker(idx):
            try:
                for i in idx:
                    got = mb.score(reqs[i])
                    assert got == ref[i], (i, got, ref[i])
            except BaseException as e:
                errors.append(e)

        with MicroBatcher(lambda: bank, programs) as mb:
            threads = [
                threading.Thread(
                    target=worker, args=(range(t, len(reqs), 6),)
                )
                for t in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors, errors

    def test_submit_after_close_raises(self, served):
        _, ds, _, bank, programs = served
        reqs = requests_from_dataset(ds, bank)
        mb = MicroBatcher(lambda: bank, programs)
        mb.close()
        with pytest.raises(RuntimeError, match="closed"):
            mb.submit(reqs[0])


class TestCompileAndReadbackContract:
    def test_zero_recompiles_after_warmup(self, served):
        """After ensure_compiled walks the ladder, a replayed trace
        lowers NOTHING — every dispatch hits a precompiled executable
        (the AOT fixed-shape contract, pinned with jax's own counter)."""
        import jax._src.test_util as jtu

        _, ds, lm, bank, programs = served
        reqs = requests_from_dataset(ds, bank)
        before = programs.stats()
        with MicroBatcher(lambda: bank, programs) as mb:
            with jtu.count_jit_and_pmap_lowerings() as count:
                futs = [mb.submit(r) for r in reqs]
                for f in futs:
                    f.result()
        assert count[0] == 0, f"request path lowered {count[0]} program(s)"
        after = programs.stats()
        assert after["compile_count"] == before["compile_count"]
        assert after["cold_dispatch_compiles"] == 0

    def test_exactly_one_readback_per_dispatched_batch(self, served):
        _, ds, lm, bank, programs = served
        metrics = ServingMetrics()
        reqs = requests_from_dataset(ds, bank)
        with MicroBatcher(lambda: bank, programs, metrics) as mb:
            overlap.reset_readback_stats()
            futs = [mb.submit(r) for r in reqs]
            for f in futs:
                f.result()
            assert overlap.readback_stats() == metrics.snapshot()[
                "dispatches"
            ]


class TestHotSwap:
    def _save(self, lm, ds, path, rng):
        """Persist a LoadedGameModel-shaped model through the real
        artifact writer (reference directory layout)."""
        from photon_ml_tpu.game.model_io import save_game_model
        from photon_ml_tpu.game.model import (
            FixedEffectModel,
            GameModel,
        )
        from photon_ml_tpu.models.coefficients import Coefficients
        from photon_ml_tpu.models.glm import create_model

        shard_id, means = lm.fixed_effects["global"]
        imap = ds.shards[shard_id].index_map
        w = np.zeros((imap.size,), np.float32)
        for k, v in means.items():
            i = imap.get_index(k)
            if i >= 0:
                w[i] = v
        gm = GameModel({
            "global": FixedEffectModel(
                create_model(
                    TaskType.LOGISTIC_REGRESSION,
                    Coefficients(jnp.asarray(w)),
                ),
                shard_id,
            )
        })
        save_game_model(gm, ds, path)

    def _fe_only(self, rng, scale):
        lm = LoadedGameModel()
        lm.fixed_effects["global"] = (
            "g", {f"g{j}\t": float(rng.normal()) * scale for j in range(5)},
        )
        return lm

    @pytest.fixture
    def two_generations(self, rng, tmp_path):
        recs = synth_records(rng)
        ds = build_game_dataset(recs, [SHARDS[0]], [])
        gens = {}
        for name, scale in (("g1", 1.0), ("g2", -2.0)):
            lm = self._fe_only(rng, scale)
            self._save(lm, ds, str(tmp_path / name), rng)
            gens[name] = lm
        return ds, gens, tmp_path

    def _serving_model(self, ds, model_dir):
        imaps = {"g": ds.shards["g"].index_map}
        widths = {"g": ds.shards["g"].indices.shape[1]}
        return ServingModel.load(
            str(model_dir), imaps, widths, ladder=(1, 8)
        ), imaps, widths

    def test_swap_parity_mid_load(self, two_generations):
        """Requests completing before the flip score generation 1,
        requests after score generation 2, and the swapped bank is
        BITWISE the bank a fresh load of generation 2 builds — through
        the donating refresh path (same shapes)."""
        ds, gens, tmp = two_generations
        sm, imaps, widths = self._serving_model(ds, tmp / "g1")
        ref1 = batch_reference_scores(gens["g1"], ds)
        ref2 = batch_reference_scores(gens["g2"], ds)
        reqs = requests_from_dataset(ds, sm.current())
        with MicroBatcher(sm.current, sm.programs) as mb:
            for i in range(5):
                assert mb.score(reqs[i]) == ref1[i]
            res = sm.stage_and_swap(str(tmp / "g2"))
            assert res.ok and res.generation == 2
            assert res.donated, "same-shape swap must take the donated path"
            assert res.recompiled_programs == 0
            for i in range(5, 10):
                assert mb.score(reqs[i]) == ref2[i]
        fresh = build_model_bank(gens["g2"], imaps, widths)
        assert np.array_equal(
            overlap.device_get(sm.current().arrays["global"]),
            overlap.device_get(fresh.arrays["global"]),
        ), "donated refresh must be a bitwise move"
        assert sm.current().generation == 2

    def test_swap_under_concurrent_traffic(self, two_generations):
        """Flip while submitters hammer: every result is EITHER gen-1's
        or gen-2's score for its row (a flip lands on a batch boundary,
        never inside one), and after the swap only gen-2 scores appear."""
        ds, gens, tmp = two_generations
        sm, _, _ = self._serving_model(ds, tmp / "g1")
        ref1 = batch_reference_scores(gens["g1"], ds)
        ref2 = batch_reference_scores(gens["g2"], ds)
        reqs = requests_from_dataset(ds, sm.current())
        errors = []

        def worker(idx):
            try:
                for i in idx:
                    got = mb.score(reqs[i])
                    assert got in (ref1[i], ref2[i]), (i, got)
            except BaseException as e:
                errors.append(e)

        with MicroBatcher(sm.current, sm.programs) as mb:
            threads = [
                threading.Thread(
                    target=worker, args=(range(t, len(reqs), 4),)
                )
                for t in range(4)
            ]
            for t in threads:
                t.start()
            sm.stage_and_swap(str(tmp / "g2"))
            for t in threads:
                t.join()
            assert not errors, errors
            for i in range(4):
                assert mb.score(reqs[i]) == ref2[i]

    def test_batcher_autowires_the_dispatch_lock(self, two_generations):
        """A bound ServingModel.current bank_ref hands the batcher the
        swap/dispatch exclusion lock automatically: a DONATING flip
        (which invalidates generation N's buffers) can never overlap a
        dispatch that is executing against them."""
        ds, gens, tmp = two_generations
        sm, _, _ = self._serving_model(ds, tmp / "g1")
        mb = MicroBatcher(sm.current, sm.programs)
        try:
            assert mb._swap_lock is sm.dispatch_lock
        finally:
            mb.close()
        plain = MicroBatcher(lambda: sm.current(), sm.programs)
        try:
            assert plain._swap_lock is None
        finally:
            plain.close()

    def test_repeated_swaps_under_fire_never_break_a_dispatch(
        self, two_generations
    ):
        """Donation stress: flip generations repeatedly while
        submitters hammer — no dispatch may ever observe a donated
        (deleted) buffer, and every result matches one generation."""
        ds, gens, tmp = two_generations
        sm, _, _ = self._serving_model(ds, tmp / "g1")
        ref1 = batch_reference_scores(gens["g1"], ds)
        ref2 = batch_reference_scores(gens["g2"], ds)
        reqs = requests_from_dataset(ds, sm.current())
        errors = []
        stop = threading.Event()

        def submitter():
            try:
                i = 0
                while not stop.is_set():
                    got = mb.score(reqs[i % len(reqs)])
                    j = i % len(reqs)
                    assert got in (ref1[j], ref2[j]), (j, got)
                    i += 1
            except BaseException as e:
                errors.append(e)

        with MicroBatcher(sm.current, sm.programs) as mb:
            threads = [
                threading.Thread(target=submitter) for _ in range(3)
            ]
            for t in threads:
                t.start()
            try:
                for gen_dir in ("g2", "g1", "g2", "g1", "g2"):
                    res = sm.stage_and_swap(str(tmp / gen_dir))
                    assert res.ok and res.donated, res
            finally:
                stop.set()
                for t in threads:
                    t.join()
        assert not errors, errors
        assert sm.current().generation == 6

    def test_corrupt_swap_quarantines_and_rolls_back(
        self, two_generations
    ):
        """An injected CORRUPT at the serving.model_load seam during
        staging: the artifact moves to *.corrupt, the swap reports
        rolled_back, and generation 1 keeps serving bit-identically."""
        from photon_ml_tpu.reliability import install_plan
        from photon_ml_tpu.reliability.retry import (
            reset_retry_stats,
            retry_stats,
        )

        ds, gens, tmp = two_generations
        sm, _, _ = self._serving_model(ds, tmp / "g1")
        ref1 = batch_reference_scores(gens["g1"], ds)
        reqs = requests_from_dataset(ds, sm.current())
        victim = str(tmp / "g2-copy")
        shutil.copytree(str(tmp / "g2"), victim)
        reset_retry_stats()
        install_plan("serving.model_load:1:CORRUPT")
        try:
            res = sm.stage_and_swap(victim)
        finally:
            install_plan(None)
        assert not res.ok and res.rolled_back
        assert res.quarantined and os.path.exists(res.quarantined)
        assert not os.path.exists(victim)
        assert (
            retry_stats()["quarantined"].get("serving.model_load", 0) == 1
        )
        assert sm.current().generation == 1
        with MicroBatcher(sm.current, sm.programs) as mb:
            for i in range(3):
                assert mb.score(reqs[i]) == ref1[i]

    def test_transient_load_fault_retries(self, two_generations):
        """A once-EIO at the seam is absorbed by the retry budget: the
        swap still completes and the retry is accounted."""
        from photon_ml_tpu.reliability import install_plan
        from photon_ml_tpu.reliability.retry import (
            reset_retry_stats,
            retry_stats,
        )

        ds, gens, tmp = two_generations
        sm, _, _ = self._serving_model(ds, tmp / "g1")
        reset_retry_stats()
        install_plan("serving.model_load:1:EIO")
        try:
            res = sm.stage_and_swap(str(tmp / "g2"))
        finally:
            install_plan(None)
        assert res.ok and res.generation == 2
        assert retry_stats()["retries"].get("serving.model_load", 0) >= 1

    def test_entity_set_change_resolves_rows_at_dispatch(self, rng):
        """The case entity padding exists for: generation 2 adds an
        entity inside the same padded bucket, and the new id sorts
        BEFORE existing ones so every bank row shifts. The swap is
        donated (same spec), yet requests built BEFORE the swap — both
        the dataset-replay path and the stdin path — must score
        generation 2 bitwise: entity ids resolve to bank rows at
        dispatch time, never at request-build time."""
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm1 = synth_model(rng)
        lm2 = synth_model(rng, scale=-1.5)
        # "user00" sorts between "user0" and "user1": rows of
        # user1..user5 all shift by one in generation 2's bank
        lm2.random_effects["per-user"][2]["user00"] = {
            "u0\t": 3.0, "u1\t": -2.0, "u2\t": 1.0
        }
        bank1 = make_bank(lm1, ds)
        sm = ServingModel(bank1, ServingPrograms((1, 8, 64)))
        ref1 = batch_reference_scores(lm1, ds)
        ref2 = batch_reference_scores(lm2, ds)
        reqs = requests_from_dataset(ds, bank1)  # pre-built, gen 1
        stdin_reqs = [
            request_from_record(recs[i], bank1, SHARDS) for i in (1, 9)
        ]
        imaps = {sid: sd.index_map for sid, sd in ds.shards.items()}
        widths = {sid: sd.indices.shape[1] for sid, sd in ds.shards.items()}
        staged = build_model_bank(lm2, imaps, widths, device=False)
        with MicroBatcher(sm.current, sm.programs) as mb:
            for i in range(3):
                assert mb.score(reqs[i]) == ref1[i]
            res = sm.swap_to_bank(staged)
            assert res.ok and res.generation == 2
            assert res.donated, "same padded bucket must stay donated"
            assert res.recompiled_programs == 0
            got = np.asarray(
                [mb.score(r) for r in reqs], np.float32
            )
            assert np.array_equal(got, ref2), (
                "pre-swap requests scored stale bank rows"
            )
            for req, i in zip(stdin_reqs, (1, 9)):
                assert mb.score(req) == ref2[i]

    def test_second_donated_swap_lowers_nothing(self, rng):
        """After the first donating swap compiles the refresh program
        (during staging, OFF the request path), further same-shape swaps
        are all-cache-hit: zero lowerings, including the refresh."""
        import jax._src.test_util as jtu

        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        imaps = {sid: sd.index_map for sid, sd in ds.shards.items()}
        widths = {sid: sd.indices.shape[1] for sid, sd in ds.shards.items()}
        sm = ServingModel(
            make_bank(synth_model(rng), ds), ServingPrograms((1, 8))
        )
        sm.swap_to_bank(
            build_model_bank(synth_model(rng, scale=2.0), imaps, widths,
                             device=False)
        )
        staged = build_model_bank(
            synth_model(rng, scale=-3.0), imaps, widths, device=False
        )
        with jtu.count_jit_and_pmap_lowerings() as count:
            res = sm.swap_to_bank(staged)
        assert res.ok and res.donated
        assert count[0] == 0, (
            f"donated swap lowered {count[0]} program(s) after warmup"
        )

    def test_exhausted_load_budget_rolls_back(self, two_generations):
        from photon_ml_tpu.reliability import install_plan

        ds, gens, tmp = two_generations
        sm, _, _ = self._serving_model(ds, tmp / "g1")
        install_plan("serving.model_load:1:EIO:*")
        try:
            res = sm.stage_and_swap(str(tmp / "g2"))
        finally:
            install_plan(None)
        assert not res.ok and res.rolled_back
        assert sm.current().generation == 1
        # a transient give-up does NOT quarantine the (healthy) artifact
        assert os.path.isdir(str(tmp / "g2"))


class TestVectorizedScoreRecords:
    """Satellite: the batch scorer's record assembly is a vectorized,
    sliceable, re-iterable column view — same records as the old
    per-row loop, no per-cell Python casts, retry-safe."""

    def _rows(self, rng):
        from photon_ml_tpu.cli.game_scoring_driver import (
            GameScoringDriver,
            GameScoringParams,
        )

        recs = synth_records(rng, n=20)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        scores = np.asarray(rng.normal(size=ds.num_real_rows), np.float32)
        params = GameScoringParams.__new__(GameScoringParams)
        params.has_response = True
        params.model_id = "m7"
        fake = GameScoringDriver.__new__(GameScoringDriver)
        fake.params = params
        return ds, scores, GameScoringDriver._score_records(
            fake, ds, scores
        )

    def _expected(self, ds, scores):
        id_types = sorted(ds.entity_indexes)
        out = []
        for i in range(ds.num_real_rows):
            meta = {
                t: ds.entity_indexes[t].ids[int(ds.entity_codes[t][i])]
                for t in id_types
                if int(ds.entity_codes[t][i]) >= 0
            }
            out.append({
                "uid": ds.uids[i],
                "label": float(ds.labels[i]),
                "modelId": "m7",
                "predictionScore": float(scores[i]),
                "weight": float(ds.weights[i]),
                "metadataMap": meta or None,
            })
        return out

    def test_rows_match_reference_loop(self, rng):
        ds, scores, rows = self._rows(rng)
        assert len(rows) == ds.num_real_rows
        assert list(rows) == self._expected(ds, scores)

    def test_reiteration_and_split_slicing(self, rng):
        ds, scores, rows = self._rows(rng)
        first = list(rows)
        assert list(rows) == first, "view must re-iterate identically"
        expected = self._expected(ds, scores)
        n = 3
        split = [list(rows[i::n]) for i in range(n)]
        assert [r for part in split for r in part] != []
        for i in range(n):
            assert split[i] == expected[i::n]


def _wait_until(cond, timeout=5.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return False


class TestAdmissionControl:
    """ISSUE 8: deadlines, load shedding and bounded submit — every
    request reaches exactly one NAMED terminal outcome, fast."""

    def _blocked_batcher(self, served, **kw):
        """A batcher whose dispatcher is parked on a held lock — the
        deterministic way to build queue depth."""
        _, ds, lm, bank, programs = served
        gate = threading.Lock()
        gate.acquire()
        metrics = ServingMetrics()
        mb = MicroBatcher(
            lambda: bank, programs, metrics, swap_lock=gate, **kw
        )
        reqs = requests_from_dataset(ds, bank)
        return mb, metrics, reqs, gate

    def test_predicted_wait_sheds_immediately(self, served):
        """Admission refuses a deadlined request UP FRONT when the EWMA
        service model says the queue already costs more than its
        deadline — no queue slot, no device work, a named SHED."""
        admission = AdmissionController()
        admission.note_dispatch(rows=1, busy_s=10.0)  # 10s per row
        _, ds, lm, bank, programs = served
        gate = threading.Lock()
        gate.acquire()
        metrics = ServingMetrics()
        mb = MicroBatcher(
            lambda: bank, programs, metrics,
            swap_lock=gate, admission=admission,
        )
        reqs = requests_from_dataset(ds, bank)
        try:
            f1 = mb.submit(reqs[0])  # claimed by the blocked dispatcher
            assert _wait_until(lambda: not mb._queue and mb._inflight)
            f2 = mb.submit(reqs[1])  # no deadline: admitted, queued
            r3 = reqs[2]
            r3.deadline_ms = 50.0
            t0 = time.perf_counter()
            with pytest.raises(RequestShed, match="predicted queue wait"):
                mb.submit(r3)
            assert time.perf_counter() - t0 < 1.0, "shed must be instant"
        finally:
            gate.release()
        assert isinstance(f1.result(timeout=30), float)
        assert isinstance(f2.result(timeout=30), float)
        mb.close()
        assert metrics.snapshot()["sheds"] == {
            "predicted_wait": 1, "total": 1,
        }

    def test_full_queue_submit_sheds_after_bounded_wait(self, served):
        """The round-12 indefinite block is gone: a submitter facing a
        full queue waits at most its own deadline, then gets SHED."""
        mb, metrics, reqs, gate = self._blocked_batcher(
            served, max_queue=1
        )
        try:
            f1 = mb.submit(reqs[0])
            assert _wait_until(lambda: not mb._queue and mb._inflight)
            f2 = mb.submit(reqs[1])  # fills the queue
            r3 = reqs[2]
            r3.deadline_ms = 100.0
            t0 = time.perf_counter()
            with pytest.raises(RequestShed, match="queue full"):
                mb.submit(r3)
            elapsed = time.perf_counter() - t0
            assert 0.05 < elapsed < 5.0, elapsed
        finally:
            gate.release()
        assert isinstance(f1.result(timeout=30), float)
        assert isinstance(f2.result(timeout=30), float)
        mb.close()
        assert metrics.snapshot()["sheds"]["queue_full"] == 1

    def test_expired_request_dropped_before_dispatch(self, served):
        """A deadline that passes in the queue fails the future with
        DeadlineExceeded and the device NEVER scores the dead row (the
        dispatch count does not move)."""
        mb, metrics, reqs, gate = self._blocked_batcher(served)
        try:
            f1 = mb.submit(reqs[0])
            assert _wait_until(lambda: not mb._queue and mb._inflight)
            r2 = reqs[1]
            r2.deadline_ms = 20.0
            f2 = mb.submit(r2)
            time.sleep(0.1)  # let the deadline lapse while queued
        finally:
            gate.release()
        assert isinstance(f1.result(timeout=30), float)
        with pytest.raises(DeadlineExceeded, match="deadline"):
            f2.result(timeout=30)
        mb.close()
        snap = metrics.snapshot()
        assert snap["deadline_expired"] == 1
        assert snap["dispatches"] == 1, (
            "the expired request must never reach the device"
        )

    def test_default_deadline_applies_to_undeadlined_requests(
        self, served
    ):
        _, ds, lm, bank, programs = served
        reqs = requests_from_dataset(ds, bank)
        with MicroBatcher(
            lambda: bank, programs, default_deadline_ms=1234.0
        ) as mb:
            assert reqs[0].deadline_ms is None
            mb.score(reqs[0])
            assert reqs[0].deadline_ms == 1234.0

    def test_outcome_is_an_annotated_float(self, served):
        _, ds, lm, bank, programs = served
        ref = batch_reference_scores(lm, ds)
        reqs = requests_from_dataset(ds, bank)
        with MicroBatcher(lambda: bank, programs) as mb:
            out = mb.score(reqs[0])
        assert isinstance(out, ScoreOutcome)
        assert out == ref[0]  # still a float, still bitwise
        assert out.degraded is False
        assert out.generation == bank.generation

    def test_record_deadline_propagates(self, served):
        recs, ds, lm, bank, _ = served
        rec = dict(recs[0])
        rec["deadline_ms"] = 75.5
        req = request_from_record(rec, bank, SHARDS)
        assert req.deadline_ms == 75.5
        assert request_from_record(recs[0], bank, SHARDS).deadline_ms is None


class TestGracefulDegradation:
    """ISSUE 8: RE-bank trouble degrades to the FE-only score (bitwise
    the batch scorer's unknown-entity semantics) with a flag — never a
    failed request."""

    def _fe_only_reference(self, lm, ds):
        fe = LoadedGameModel()
        fe.fixed_effects = dict(lm.fixed_effects)
        return batch_reference_scores(fe, ds)

    def test_quarantined_re_scores_fe_only_bitwise(self, served):
        _, ds, lm, bank, programs = served
        ref_fe = self._fe_only_reference(lm, ds)
        ref_full = batch_reference_scores(lm, ds)
        assert not np.array_equal(ref_fe, ref_full), (
            "fixture must make degradation observable"
        )
        bank.quarantine_re("userId")
        metrics = ServingMetrics()
        reqs = requests_from_dataset(ds, bank)
        with MicroBatcher(lambda: bank, programs, metrics) as mb:
            outs = [mb.score(r) for r in reqs]
        got = np.asarray(outs, np.float32)
        assert np.array_equal(got, ref_fe), (
            "degraded scores must be bitwise the batch scorer's "
            "FE-only path"
        )
        assert all(o.degraded for o in outs)
        assert metrics.snapshot()["degraded_responses"] == len(reqs)

    def test_unknown_re_type_quarantine_rejected(self, served):
        _, ds, lm, bank, _ = served
        with pytest.raises(ValueError, match="unknown random-effect"):
            bank.quarantine_re("no-such-type")

    def test_row_resolution_failure_degrades_then_quarantines(
        self, served
    ):
        """A dying entity index (e.g. the native mmap store lost mid-
        swap) degrades affected rows FE-only; after RE_QUARANTINE_AFTER
        consecutive failures the type is quarantined so later requests
        stop paying the failing lookup."""
        from photon_ml_tpu.serving.batcher import RE_QUARANTINE_AFTER

        _, ds, lm, bank, programs = served
        ref_fe = self._fe_only_reference(lm, ds)

        class DyingIndex:
            calls = 0

            def rows_of(self, ids):
                DyingIndex.calls += 1
                raise RuntimeError("entity store died")

        bank.entity_rows["userId"] = DyingIndex()
        metrics = ServingMetrics()
        reqs = requests_from_dataset(ds, bank)
        n = RE_QUARANTINE_AFTER + 2
        with MicroBatcher(lambda: bank, programs, metrics) as mb:
            outs = [mb.score(reqs[i]) for i in range(n)]
        got = np.asarray(outs, np.float32)
        assert np.array_equal(got, ref_fe[:n])
        assert all(o.degraded for o in outs)
        assert "userId" in bank.quarantined_re_types
        # after quarantine the failing store is no longer consulted
        assert DyingIndex.calls == RE_QUARANTINE_AFTER
        snap = metrics.snapshot()
        assert snap["re_resolution_failures"] == {
            "userId": RE_QUARANTINE_AFTER
        }
        assert snap["re_quarantines"] == {"userId": 1}
        assert snap["degraded_responses"] == n

    def test_swap_installs_a_clean_bank(self, served, rng):
        """Quarantine is per-generation: a hot swap's fresh bank starts
        with no quarantined coordinates."""
        _, ds, lm, bank, programs = served
        bank.quarantine_re("userId")
        sm = ServingModel(bank, programs)
        imaps = {sid: sd.index_map for sid, sd in ds.shards.items()}
        widths = {sid: sd.indices.shape[1] for sid, sd in ds.shards.items()}
        staged = build_model_bank(
            synth_model(rng, scale=2.0), imaps, widths, device=False
        )
        res = sm.swap_to_bank(staged)
        assert res.ok
        assert sm.current().quarantined_re_types == set()


class TestShutdownAndDrain:
    """Satellites 1+3: close/drain semantics — blocked submitters wake
    and raise, every in-flight future reaches exactly one terminal
    state, and a bounded drain never leaves a hung future."""

    def test_close_under_saturated_queue_wakes_blocked_submitters(
        self, served
    ):
        """Satellite 1: a submitter parked on a FULL queue must wake
        and raise when another thread closes the batcher — not hang."""
        _, ds, lm, bank, programs = served
        gate = threading.Lock()
        gate.acquire()
        mb = MicroBatcher(
            lambda: bank, programs, swap_lock=gate, max_queue=1
        )
        reqs = requests_from_dataset(ds, bank)
        f1 = mb.submit(reqs[0])
        assert _wait_until(lambda: not mb._queue and mb._inflight)
        f2 = mb.submit(reqs[1])  # saturates the queue
        blocked_outcome = []

        def blocked_submitter():
            try:
                mb.submit(reqs[2])
                blocked_outcome.append("admitted")
            except BatcherClosed:
                blocked_outcome.append("closed")
            except BaseException as e:  # pragma: no cover
                blocked_outcome.append(e)

        t = threading.Thread(target=blocked_submitter)
        t.start()
        time.sleep(0.1)  # park it on the full queue
        closer = threading.Thread(target=mb.close)
        closer.start()
        t.join(timeout=10)
        assert not t.is_alive(), "blocked submitter hung across close()"
        assert blocked_outcome == ["closed"]
        gate.release()  # let the dispatcher finish the claimed work
        closer.join(timeout=10)
        assert not closer.is_alive()
        # the admitted requests still reached their terminal results
        assert isinstance(f1.result(timeout=10), float)
        assert isinstance(f2.result(timeout=10), float)

    def test_clean_close_resolves_every_future(self, served):
        _, ds, lm, bank, programs = served
        reqs = requests_from_dataset(ds, bank)
        mb = MicroBatcher(lambda: bank, programs)
        futs = [mb.submit(r) for r in reqs]
        mb.close()
        assert all(f.done() for f in futs)
        assert [f.result(timeout=0) for f in futs]

    def test_drain_serves_queue_inside_budget(self, served):
        _, ds, lm, bank, programs = served
        metrics = ServingMetrics()
        reqs = requests_from_dataset(ds, bank)
        mb = MicroBatcher(lambda: bank, programs, metrics)
        futs = [mb.submit(r) for r in reqs]
        report = mb.drain(30.0)
        assert report.failed == 0 and not report.timed_out
        assert all(f.done() for f in futs)
        assert [f.result(timeout=0) for f in futs]
        assert metrics.snapshot()["drain"]["failed"] == 0
        with pytest.raises(BatcherClosed):
            mb.submit(reqs[0])

    def test_drain_timeout_fails_leftovers_with_named_error(
        self, served
    ):
        """A wedged dispatcher cannot turn SIGTERM into a hang: at the
        budget, every still-pending future (queued AND in-flight) fails
        with DRAIN_TIMEOUT — exactly one terminal outcome each."""
        _, ds, lm, bank, programs = served
        gate = threading.Lock()
        gate.acquire()
        metrics = ServingMetrics()
        mb = MicroBatcher(lambda: bank, programs, metrics, swap_lock=gate)
        reqs = requests_from_dataset(ds, bank)
        futs = [mb.submit(r) for r in reqs[:5]]
        assert _wait_until(lambda: mb._inflight)
        report = mb.drain(0.3)
        assert report.timed_out and report.failed == len(futs)
        for f in futs:
            assert f.done(), "drain left a hung future"
            with pytest.raises(DrainTimeout):
                f.result(timeout=0)
        snap = metrics.snapshot()
        assert snap["drain"]["failed"] == len(futs)
        assert snap["drain"]["timed_out"] is True
        # un-wedge: the dispatcher finishes its claimed batch, finds
        # every future already terminal (no double resolution), exits
        gate.release()
        assert _wait_until(lambda: not mb.alive(), timeout=10)

    def test_drain_is_idempotent_after_close(self, served):
        _, ds, lm, bank, programs = served
        mb = MicroBatcher(lambda: bank, programs)
        mb.close()
        report = mb.drain(1.0)
        assert report.pending_at_start == 0 and report.failed == 0

    def test_heartbeat_beats_while_idle(self, served):
        _, ds, lm, bank, programs = served
        with MicroBatcher(lambda: bank, programs) as mb:
            assert mb.alive()
            time.sleep(0.6)  # > 2 heartbeat intervals, zero traffic
            assert mb.heartbeat_age_s() < 0.5, (
                "idle dispatcher must keep beating"
            )

    def test_kill_fault_plan_dies_instead_of_hanging(self, tmp_path):
        """Satellite 3, the KILL arm: a deterministic SIGKILL at the
        serving.dispatch crossing kills the process AT that crossing —
        promptly (no drain, no atexit, no hang), which is the crash the
        resume/ops machinery must assume."""
        script = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from photon_ml_tpu.reliability import install_plan
from photon_ml_tpu.serving import (
    MicroBatcher, ScoreRequest, ServingPrograms, bank_from_arrays,
)

bank = bank_from_arrays(
    fixed=[("global", "g", np.ones(8, np.float32))],
    shard_widths={"g": 2},
)
programs = ServingPrograms((1, 4))
programs.ensure_compiled(bank)
install_plan("serving.dispatch:1:KILL")
mb = MicroBatcher(lambda: bank, programs)
fut = mb.submit(ScoreRequest(
    uid="x",
    indices={"g": np.zeros(2, np.int32)},
    values={"g": np.zeros(2, np.float32)},
    entity_ids={},
))
import time
time.sleep(60)
print("SURVIVED")
"""
        r = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert r.returncode == -9, (r.returncode, r.stdout, r.stderr)
        assert "SURVIVED" not in r.stdout


class TestServingDriverValidation:
    def _params(self, **kw):
        from photon_ml_tpu.cli.serving_driver import ServingParams

        base = dict(
            game_model_input_dir="m",
            output_dir="o",
            request_paths=["trace"],
            feature_shards=[SHARDS[0]],
        )
        base.update(kw)
        return ServingParams(**base)

    def test_stdin_requires_prebuilt_maps_and_width(self):
        with pytest.raises(ValueError, match="prebuilt feature maps"):
            self._params(request_paths=["-"]).validate()
        with pytest.raises(ValueError, match="request-nnz-width"):
            self._params(
                request_paths=["-"], offheap_indexmap_dir="idx"
            ).validate()

    def test_swap_requires_threshold(self):
        with pytest.raises(ValueError, match="swap-after-requests"):
            self._params(swap_model_dir="m2").validate()

    def test_bad_ladder_and_mode(self):
        with pytest.raises(ValueError, match="ladder"):
            self._params(ladder=[8, 1]).validate()
        with pytest.raises(ValueError, match="mode"):
            self._params(mode="burst").validate()


@pytest.mark.slow
class TestServingDriverEndToEnd:
    def _train(self, tmp_path, rng):
        from tests.test_game_drivers import write_game_avro
        from photon_ml_tpu.cli.game_training_driver import (
            GameTrainingDriver,
            GameTrainingParams,
        )
        from photon_ml_tpu.game.config import (
            FixedEffectDataConfiguration,
            RandomEffectDataConfiguration,
        )

        train = tmp_path / "train"
        train.mkdir()
        write_game_avro(str(train / "p0.avro"), rng)
        params = GameTrainingParams(
            train_input_dirs=[str(train)],
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=[
                FeatureShardConfiguration("g", ["features"]),
                FeatureShardConfiguration("u", ["userFeatures"]),
            ],
            fixed_effect_data_configs={
                "global": FixedEffectDataConfiguration("g")
            },
            fixed_effect_opt_configs={"global": "10,1e-6,0.1,1,LBFGS,L2"},
            random_effect_data_configs={
                "per-user": RandomEffectDataConfiguration("userId", "u")
            },
            random_effect_opt_configs={"per-user": "10,1e-6,1.0,1,LBFGS,L2"},
            num_iterations=1,
        )
        GameTrainingDriver(params).run()
        return str(train), os.path.join(params.output_dir, "best-model")

    def test_replayed_trace_matches_batch_driver_bitwise(
        self, tmp_path, rng
    ):
        """Driver-level acceptance: the serving driver's score records
        equal the batch scoring driver's record for record, and its
        metrics.json carries the latency/occupancy/compile accounting."""
        from photon_ml_tpu.cli.game_scoring_driver import (
            GameScoringDriver,
            GameScoringParams,
        )
        from photon_ml_tpu.cli.serving_driver import (
            ServingDriver,
            params_from_args,
        )
        from photon_ml_tpu.io.avro_codec import read_avro_records

        train, model_dir = self._train(tmp_path, rng)
        shards = [
            FeatureShardConfiguration("g", ["features"]),
            FeatureShardConfiguration("u", ["userFeatures"]),
        ]
        GameScoringDriver(GameScoringParams(
            input_dirs=[train],
            game_model_input_dir=model_dir,
            output_dir=str(tmp_path / "batch"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=shards,
        )).run()
        driver = ServingDriver(params_from_args([
            "--game-model-input-dir", model_dir,
            "--output-dir", str(tmp_path / "serve"),
            "--request-paths", train,
            "--feature-shard-id-to-feature-section-keys-map",
            "g:features|u:userFeatures",
            "--mode", "open",
            "--concurrency", "4",
            "--evaluator-types", "AUC",
        ]))
        driver.run()
        batch = {
            r["uid"]: r
            for r in read_avro_records(str(tmp_path / "batch" / "scores"))
        }
        serve = {
            r["uid"]: r
            for r in read_avro_records(str(tmp_path / "serve" / "scores"))
        }
        assert batch == serve
        m = json.load(open(str(tmp_path / "serve" / "metrics.json")))
        assert m["programs"]["cold_dispatch_compiles"] == 0
        assert m["readbacks"] == m["serving"]["dispatches"]
        assert m["serving"]["latency_p99_ms"] > 0
        assert m["serving"]["qps"] > 0
        assert 0 < m["AUC"] <= 1

    def test_driver_hot_swap_mid_replay(self, tmp_path, rng):
        """--swap-model-dir flips generations mid-trace: both
        generations appear in the dispatch accounting and the swap
        history records a donated, non-recompiling flip."""
        from photon_ml_tpu.cli.serving_driver import (
            ServingDriver,
            params_from_args,
        )

        train, model_dir = self._train(tmp_path, rng)
        driver = ServingDriver(params_from_args([
            "--game-model-input-dir", model_dir,
            "--output-dir", str(tmp_path / "serve-swap"),
            "--request-paths", train,
            "--feature-shard-id-to-feature-section-keys-map",
            "g:features|u:userFeatures",
            "--swap-model-dir", model_dir,
            "--swap-after-requests", "40",
        ]))
        driver.run()
        m = json.load(
            open(str(tmp_path / "serve-swap" / "metrics.json"))
        )
        assert m["generation"] == 2
        swaps = m["swap_history"]
        assert len(swaps) == 1 and swaps[0]["ok"] and swaps[0]["donated"]
        assert swaps[0]["recompiled_programs"] == 0
        gens = m["serving"]["generation_dispatches"]
        assert set(gens) == {"1", "2"}
