"""Interop tests pinned on the reference's OWN JVM/Spark-written fixtures.

Every other Avro test in the suite is a self-round-trip; these read the
16 MB of artifacts the reference ships under integTest/resources — files
written by org.apache.avro's Java implementation and Spark — so a silent
wire-format divergence in our codec cannot pass. Mirrors the reference's
own correctness bar: DriverIntegTest.scala (heart data end-to-end) and
cli/game/scoring/DriverTest.scala (yahoo-music scoring against a saved
GAME model, RMSE pinned at 1.32106 from an assumed-correct 2016 capture).
"""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.io.avro_codec import read_avro_records, read_container

REF = "/root/reference/photon-ml/src/integTest/resources"
DRIVER_IN = os.path.join(REF, "DriverIntegTest", "input")
GAME_REF = os.path.join(REF, "GameIntegTest")

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not os.path.isdir(REF), reason="reference fixtures unavailable"
    ),
]


class TestHeartAvroDecode:
    """heart.avro: 250 records of the metronome TrainingExample schema —
    union-typed label/weight/offset/uid, written by the JVM."""

    def test_python_codec_reads_jvm_file(self):
        schema, it = read_container(os.path.join(DRIVER_IN, "heart.avro"))
        recs = list(it)
        assert schema["name"] == "TrainingExample"
        assert schema["namespace"] == "com.linkedin.metronome.avro.generated"
        assert len(recs) == 250
        labels = [r["label"] for r in recs]
        assert sorted(set(labels)) == [0, 1]
        assert labels.count(1) == 112
        r0 = recs[0]
        # optional union branches decode as None, not as missing keys
        assert r0["uid"] is None and r0["weight"] is None and r0["offset"] is None
        assert len(r0["features"]) == 13
        assert r0["features"][0] == {"name": "1", "value": 70.0, "term": ""}

    def test_validation_and_empty_files(self):
        val = list(
            read_avro_records(os.path.join(DRIVER_IN, "heart_validation.avro"))
        )
        assert len(val) == 20
        # "empty.avro" carries records whose feature bags are all empty
        empty = list(read_avro_records(os.path.join(DRIVER_IN, "empty.avro")))
        assert len(empty) == 250

    def test_native_decoder_matches_python_codec(self):
        from photon_ml_tpu.io import native_avro

        if not native_avro.available():
            pytest.skip("native avro build unavailable")
        path = os.path.join(DRIVER_IN, "heart.avro")
        recs = list(read_avro_records(path))
        plan = native_avro.plan_for_file(
            path,
            numeric_fields=["label", "weight", "offset"],
            string_fields=["uid"],
            bag_fields=["features"],
        )
        cols = native_avro.decode_columns(path, plan)
        assert cols.num_records == len(recs)
        np.testing.assert_array_equal(
            cols.f64("label"), np.asarray([r["label"] for r in recs], np.float64)
        )
        row_ptr, _key_ids, values = cols.bag("features")
        counts = np.diff(row_ptr)
        np.testing.assert_array_equal(
            counts, np.asarray([len(r["features"]) for r in recs])
        )
        flat = [f["value"] for r in recs for f in r["features"]]
        np.testing.assert_allclose(values, np.asarray(flat), rtol=0, atol=0)

    def test_glm_driver_end_to_end_on_heart(self, tmp_path):
        """DriverIntegTest analog: train logistic regression on heart.avro,
        validate on heart_validation.avro, model selected by held-out AUC."""
        from photon_ml_tpu.cli.glm_driver import GLMDriver, GLMParams
        from photon_ml_tpu.task import TaskType

        params = GLMParams(
            train_dir=os.path.join(DRIVER_IN, "heart.avro"),
            validate_dir=os.path.join(DRIVER_IN, "heart_validation.avro"),
            output_dir=str(tmp_path / "out"),
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[1.0],
        )
        GLMDriver(params).run()
        metrics = json.load(open(os.path.join(params.output_dir, "metrics.json")))
        # capture on this fixture: AUC 0.75, logloss 0.54 (20-row val split)
        assert metrics["validation"]["1.0"]["AUC"] >= 0.70
        assert metrics["validation"]["1.0"]["logistic_loss"] <= 0.60
        assert os.path.isfile(
            os.path.join(params.output_dir, "best-model", "model.avro")
        )


class TestReferenceGameModelLoad:
    """Saved-model interop: the reference's Spark-written GAME model
    directories load through game/model_io.py (ModelProcessingUtils
    layout parity, avro/Constants.scala:22-25)."""

    def test_fixed_effect_only_model(self):
        from photon_ml_tpu.game.model_io import load_game_model

        m = load_game_model(os.path.join(GAME_REF, "fixedEffectOnlyGAMEModel"))
        assert m.coordinate_names() == ["globalShard"]
        shard_id, means = m.fixed_effects["globalShard"]
        assert shard_id == "globalShard"
        assert len(means) == 14982
        # intercept value written by the JVM, decoded bit-exact
        assert means["(INTERCEPT)\t"] == pytest.approx(
            3.5525033712866567, abs=0
        )

    def test_full_game_model(self):
        from photon_ml_tpu.game.model_io import load_game_model

        m = load_game_model(os.path.join(GAME_REF, "gameModel"))
        assert sorted(m.coordinate_names()) == [
            "globalShard", "songId-songShard", "userId-userShard",
        ]
        re_type, shard_id, per_entity = m.random_effects["userId-userShard"]
        assert (re_type, shard_id) == ("userId", "userShard")
        # the shipped fixture has id-info but no RE part files (empty dirs
        # don't survive git): loads as an empty per-entity map
        assert per_entity == {}
        _, means = m.fixed_effects["globalShard"]
        assert len(means) == 14982


class TestYahooMusicScoring:
    """cli/game/scoring DriverTest analog on the shipped fixtures: score
    yahoo-music-test.avro with the reference's saved model through the
    scoring driver, evaluate RMSE.

    The reference pins RMSE 1.32106 (its testOffHeapIndexMap capture,
    LOW_PRECISION tolerance) on the uid-variant of this input; our run on
    input/test with the fixed-effect model lands 1.3217 — within 6e-4 of
    the JVM implementation's own anchor.
    """

    def _score(self, tmp_path, model_subdir):
        from photon_ml_tpu.cli.game_scoring_driver import (
            GameScoringDriver,
            GameScoringParams,
        )
        from photon_ml_tpu.evaluation import EvaluatorType
        from photon_ml_tpu.game.config import FeatureShardConfiguration
        from photon_ml_tpu.task import TaskType

        params = GameScoringParams(
            input_dirs=[os.path.join(GAME_REF, "input", "test")],
            game_model_input_dir=os.path.join(GAME_REF, model_subdir),
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LINEAR_REGRESSION,
            feature_shards=[
                FeatureShardConfiguration(
                    "globalShard", ["features", "songFeatures", "userFeatures"]
                ),
            ],
            feature_name_and_term_set_path=os.path.join(
                GAME_REF, "input", "feature-lists"
            ),
            evaluator_types=[EvaluatorType.parse("RMSE")],
            model_id="interop-test",
        )
        GameScoringDriver(params).run()
        return params.output_dir

    def test_streaming_scoring_matches_in_memory(self, tmp_path):
        """--streaming scores in bounded-memory chunks (the reference's
        partition-streamed profile): same scores, same RMSE, multiple
        part files."""
        from photon_ml_tpu.cli.game_scoring_driver import (
            GameScoringDriver,
            GameScoringParams,
        )
        from photon_ml_tpu.evaluation import EvaluatorType
        from photon_ml_tpu.game.config import FeatureShardConfiguration
        from photon_ml_tpu.task import TaskType

        outs = {}
        for label, streaming in (("mem", False), ("stream", True)):
            params = GameScoringParams(
                input_dirs=[os.path.join(GAME_REF, "input", "test")],
                game_model_input_dir=os.path.join(
                    GAME_REF, "fixedEffectOnlyGAMEModel"
                ),
                output_dir=str(tmp_path / label),
                task_type=TaskType.LINEAR_REGRESSION,
                feature_shards=[
                    FeatureShardConfiguration(
                        "globalShard",
                        ["features", "songFeatures", "userFeatures"],
                    ),
                ],
                feature_name_and_term_set_path=os.path.join(
                    GAME_REF, "input", "feature-lists"
                ),
                evaluator_types=[EvaluatorType.parse("RMSE")],
                streaming=streaming,
                rows_per_chunk=2500,
            )
            GameScoringDriver(params).run()
            # part files sort lexically = chunk order, so file order IS
            # the input row order on both paths (the fixture has no uid
            # field — row-index uids restart per chunk and cannot key a
            # cross-path sort)
            recs = list(
                read_avro_records(os.path.join(params.output_dir, "scores"))
            )
            metrics = json.load(
                open(os.path.join(params.output_dir, "metrics.json"))
            )
            outs[label] = (recs, metrics)
        mem_recs, mem_m = outs["mem"]
        st_recs, st_m = outs["stream"]
        assert len(st_recs) == len(mem_recs) == 9195
        # 9195 rows / 2500 per chunk -> 4 part files
        parts = os.listdir(os.path.join(tmp_path, "stream", "scores"))
        assert len(parts) == 4
        assert st_m["RMSE"] == pytest.approx(mem_m["RMSE"], rel=1e-6)
        np.testing.assert_allclose(
            [r["predictionScore"] for r in st_recs],
            [r["predictionScore"] for r in mem_recs],
            rtol=1e-5,
        )

    def test_streaming_scoring_guards(self, tmp_path):
        from photon_ml_tpu.cli.game_scoring_driver import (
            GameScoringDriver,
            GameScoringParams,
        )
        from photon_ml_tpu.evaluation import EvaluatorType
        from photon_ml_tpu.game.config import FeatureShardConfiguration
        from photon_ml_tpu.task import TaskType

        base = dict(
            input_dirs=[os.path.join(GAME_REF, "input", "test")],
            game_model_input_dir=os.path.join(
                GAME_REF, "fixedEffectOnlyGAMEModel"
            ),
            task_type=TaskType.LINEAR_REGRESSION,
            feature_shards=[
                FeatureShardConfiguration("globalShard", ["features"]),
            ],
            streaming=True,
        )
        # no prebuilt feature maps -> rejected
        with pytest.raises(ValueError, match="prebuilt feature maps"):
            GameScoringDriver(
                GameScoringParams(
                    output_dir=str(tmp_path / "a"), **base
                )
            ).run()
        # sharded evaluators -> rejected
        with pytest.raises(ValueError, match="sharded evaluator"):
            GameScoringDriver(
                GameScoringParams(
                    output_dir=str(tmp_path / "b"),
                    feature_name_and_term_set_path=os.path.join(
                        GAME_REF, "input", "feature-lists"
                    ),
                    evaluator_types=[
                        EvaluatorType.parse("precision@5:userId")
                    ],
                    **base,
                )
            ).run()

    def test_score_with_reference_model(self, tmp_path):
        out = self._score(tmp_path, "fixedEffectOnlyGAMEModel")
        metrics = json.load(open(os.path.join(out, "metrics.json")))
        assert metrics["RMSE"] == pytest.approx(1.32106, abs=2e-3)
        recs = list(read_avro_records(os.path.join(out, "scores")))
        assert len(recs) == 9195
        assert all(r["modelId"] == "interop-test" for r in recs[:50])
        assert np.isfinite([r["predictionScore"] for r in recs]).all()

    def test_input_fixture_shape(self):
        recs = list(
            read_avro_records(
                os.path.join(GAME_REF, "input", "test", "yahoo-music-test.avro")
            )
        )
        assert len(recs) == 9195
        r0 = recs[0]
        assert {"userId", "songId", "response", "features"} <= set(r0)

    def test_native_game_build_matches_python(self, monkeypatch):
        """The yahoo-music records (int id columns, union-typed fields) now
        decode through the native column path; labels and raw entity ids
        must match the Python-codec build exactly."""
        from photon_ml_tpu.game.config import FeatureShardConfiguration
        from photon_ml_tpu.game.data import build_game_dataset_from_files
        from photon_ml_tpu.io import native_avro

        if not native_avro.available():
            pytest.skip("native avro build unavailable")
        files = [
            os.path.join(GAME_REF, "input", "test", "yahoo-music-test.avro")
        ]
        shards = [FeatureShardConfiguration("globalShard", ["features"])]
        native_ds = build_game_dataset_from_files(
            files, shards, ["userId", "songId"]
        )
        monkeypatch.setattr(native_avro, "available", lambda: False)
        python_ds = build_game_dataset_from_files(
            files, shards, ["userId", "songId"]
        )
        assert native_ds.num_rows == python_ds.num_rows
        np.testing.assert_array_equal(native_ds.labels, python_ds.labels)
        for t in ("userId", "songId"):
            n_ids = native_ds.entity_indexes[t]
            p_ids = python_ds.entity_indexes[t]
            assert sorted(n_ids.ids) == sorted(p_ids.ids)
            n_raw = [n_ids.ids[c] for c in native_ds.entity_codes[t][: native_ds.num_real_rows]]
            p_raw = [p_ids.ids[c] for c in python_ds.entity_codes[t][: python_ds.num_real_rows]]
            assert n_raw == p_raw
