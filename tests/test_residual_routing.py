"""ResidualRouter: the entity_all_to_all consumer that re-keys per-row
residual offsets to entity-owning devices each iteration (the
addScoresToOffsets shuffle analog, RandomEffectDataSet.scala:55-74)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.game import build_game_dataset
from photon_ml_tpu.game.config import RandomEffectDataConfiguration
from photon_ml_tpu.game.coordinate import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
from photon_ml_tpu.game.random_effect import RandomEffectOptimizationProblem
from photon_ml_tpu.game.random_effect_data import build_random_effect_dataset
from photon_ml_tpu.game.residual_routing import ResidualRouter
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.task import TaskType

from tests.test_game import SHARDS, make_records


def _re_dataset(rng, n=220, n_users=13, cap=None):
    recs, _, _ = make_records(rng, n=n, n_users=n_users)
    ds = build_game_dataset(recs, SHARDS, ["userId"])
    red = build_random_effect_dataset(
        ds,
        RandomEffectDataConfiguration(
            "userId", "userShard", active_data_upper_bound=cap
        ),
    )
    return ds, red


class TestRouter:
    def test_routed_slabs_match_direct_gather(self, rng):
        ds, red = _re_dataset(rng)
        mesh = make_mesh()
        router = ResidualRouter(mesh, red)
        offsets = rng.normal(size=ds.num_rows).astype(np.float32)
        flat = router.route(jnp.asarray(offsets))
        for bi, b in enumerate(red.buckets):
            slab = np.asarray(router.bucket_slab(flat, bi, b.capacity))
            # oracle: direct host gather into the same padded layout
            e_loc = router.e_locs[bi]
            want = np.zeros((router.n_dev * e_loc, b.capacity), np.float32)
            safe = np.maximum(b.row_index, 0)
            got_rows = np.where(b.row_index >= 0, offsets[safe], 0.0)
            want[: b.num_entities] = got_rows
            np.testing.assert_allclose(slab, want, rtol=1e-6)

    def test_reservoir_capped_dataset_routes_losslessly(self, rng):
        ds, red = _re_dataset(rng, n=400, n_users=7, cap=8)
        mesh = make_mesh()
        router = ResidualRouter(mesh, red)
        offsets = rng.normal(size=ds.num_rows).astype(np.float32)
        flat = router.route(jnp.asarray(offsets))
        # every active row's offset must land exactly once
        total_active = sum(
            int((b.row_index >= 0).sum()) for b in red.buckets
        )
        nz = int(np.count_nonzero(np.asarray(flat)))
        # (offsets are continuous so exact zeros are measure-zero)
        assert nz == total_active

    def test_update_bank_mesh_uses_routed_offsets(self, rng):
        # mesh update_bank with residuals == single-device update_bank
        ds, red = _re_dataset(rng)
        offsets = jnp.asarray(rng.normal(size=ds.num_rows).astype(np.float32))
        bank0 = jnp.zeros((red.num_entities, red.local_dim), jnp.float32)

        def problem(mesh):
            return RandomEffectOptimizationProblem(
                LOGISTIC,
                OptimizerConfig(max_iter=15),
                RegularizationContext(RegularizationType.L2),
                reg_weight=1.0,
                mesh=mesh,
            )

        bank_single, _ = problem(None).update_bank(
            bank0, red, residual_offsets=offsets
        )
        bank_mesh, _ = problem(make_mesh()).update_bank(
            bank0, red, residual_offsets=offsets
        )
        # atol: mesh and single-device solves reduce in different float32
        # orders and stop at max_iter=15 (not fully converged), so the
        # optima differ by up to ~4e-4 on CPU hosts — the seed's 2e-4
        # tripped on 2/65 elements
        np.testing.assert_allclose(
            np.asarray(bank_mesh), np.asarray(bank_single), atol=1e-3
        )


@pytest.mark.slow
class TestMeshSteadyState:
    def test_mesh_cd_no_implicit_d2h_at_steady_state(self, rng):
        # VERDICT r2 items 5+6 done-criterion: CPU-mesh CoordinateDescent
        # under the transfer guard once caches/routers are warm
        recs, _, _ = make_records(rng, n=200, n_users=6)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        mesh = make_mesh()
        coords = {
            "global": FixedEffectCoordinate(
                name="global",
                dataset=ds,
                problem=create_glm_problem(
                    TaskType.LOGISTIC_REGRESSION,
                    ds.shards["globalShard"].dim,
                    config=OptimizerConfig(max_iter=5),
                    regularization=RegularizationContext(
                        RegularizationType.L2
                    ),
                ),
                feature_shard_id="globalShard",
                reg_weight=0.1,
                mesh=mesh,
            ),
            "per-user": RandomEffectCoordinate(
                name="per-user",
                dataset=ds,
                re_dataset=red,
                problem=RandomEffectOptimizationProblem(
                    LOGISTIC,
                    OptimizerConfig(max_iter=5),
                    RegularizationContext(RegularizationType.L2),
                    reg_weight=1.0,
                    mesh=mesh,
                ),
            ),
        }

        def make_cd():
            return CoordinateDescent(
                coords, ds, TaskType.LOGISTIC_REGRESSION,
                update_sequence=["global", "per-user"],
            )

        make_cd().run(1)  # warm caches, routers, compiled programs
        with jax.transfer_guard_device_to_host("disallow"):
            res = make_cd().run(1)
        assert np.isfinite(res.objective_history[-1])
