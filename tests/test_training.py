"""Model/problem/training tests: variance estimates, warm-started lambda
grids, normalization invariance, down-samplers.

Mirrors the reference's integration strategy (NormalizationIntegTest's
invariant "training with normalization == training on pre-transformed
data"; DistributedOptimizationProblemIntegTest variance checks) with
validator-style assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.data.sampler import (
    binary_classification_down_sample,
    default_down_sample,
)
from photon_ml_tpu.models import logistic_regression_model
from photon_ml_tpu.ops.normalization import (
    NormalizationType,
    build_normalization,
)
from photon_ml_tpu.optim import OptimizerType, RegularizationType
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.training import train_generalized_linear_model


def logistic_data(rng, n=512, d=6, intercept=True):
    x = rng.normal(size=(n, d)).astype(np.float32)
    if intercept:
        x[:, -1] = 1.0  # intercept column
    w = rng.normal(size=d).astype(np.float32)
    y = (1 / (1 + np.exp(-x @ w)) > rng.uniform(size=n)).astype(np.float32)
    return x, y


class TestProblem:
    def test_variances_linear_regression(self, rng):
        # For squared loss, H = X^T X (weights 1), so variances ~ 1/diag.
        n, d = 128, 4
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (x @ np.ones(d)).astype(np.float32)
        batch = make_dense_batch(x, y)
        problem = create_glm_problem(
            TaskType.LINEAR_REGRESSION, d, compute_variances=True
        )
        coefficients, _ = problem.run(batch)
        expect = 1.0 / np.sum(x**2, axis=0)
        np.testing.assert_allclose(
            np.asarray(coefficients.variances), expect, rtol=1e-4
        )

    def test_poisson_trains(self, rng):
        n, d = 4096, 4
        x = (0.3 * rng.normal(size=(n, d))).astype(np.float32)
        w = np.array([0.5, -0.3, 0.2, 0.1], np.float32)
        y = rng.poisson(np.exp(x @ w)).astype(np.float32)
        batch = make_dense_batch(x, y)
        problem = create_glm_problem(TaskType.POISSON_REGRESSION, d)
        coefficients, result = problem.run(batch, reg_weight=1e-3)
        assert np.all(np.isfinite(np.asarray(coefficients.means)))
        np.testing.assert_allclose(np.asarray(coefficients.means), w, atol=0.3)

    def test_svm_rejects_tron(self, rng):
        from photon_ml_tpu.optim import OptimizerConfig

        problem = create_glm_problem(
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
            4,
            config=OptimizerConfig(OptimizerType.TRON),
        )
        x, y = logistic_data(rng, n=64, d=4)
        with pytest.raises(ValueError):
            problem.run(make_dense_batch(x, y))

    def test_svm_trains_with_lbfgs(self, rng):
        x, y = logistic_data(rng, n=256, d=5)
        batch = make_dense_batch(x, y)
        problem = create_glm_problem(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM, 5)
        coefficients, _ = problem.run(batch, reg_weight=0.01)
        model = logistic_regression_model(coefficients)
        pred = np.asarray(model.predict_class(batch))
        w = np.asarray(batch.weights)
        acc = np.sum((pred == np.asarray(batch.labels)) * w) / w.sum()
        assert acc > 0.6


class TestTraining:
    def test_lambda_grid_shrinks_norms(self, rng):
        x, y = logistic_data(rng)
        batch = make_dense_batch(x, y)
        models, results = train_generalized_linear_model(
            batch,
            TaskType.LOGISTIC_REGRESSION,
            6,
            regularization_type=RegularizationType.L2,
            regularization_weights=[0.1, 10.0, 1000.0],
        )
        norms = {
            lam: float(jnp.linalg.norm(m.means)) for lam, m in models.items()
        }
        assert norms[1000.0] < norms[10.0] < norms[0.1]

    def test_warm_start_converges_faster(self, rng):
        x, y = logistic_data(rng)
        batch = make_dense_batch(x, y)
        _, warm = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, 6,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0, 10.0], warm_start=True,
        )
        _, cold = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, 6,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0, 10.0], warm_start=False,
        )
        assert int(warm[1.0].iterations) <= int(cold[1.0].iterations)

    def test_normalization_invariance(self, rng):
        """Training with standardization context == training on
        pre-standardized data (NormalizationIntegTest invariant)."""
        n, d = 256, 5
        x = (rng.normal(size=(n, d)) * np.array([5.0, 0.1, 2.0, 1.0, 1.0])
             + np.array([1.0, -3.0, 0.5, 0.0, 0.0])).astype(np.float32)
        x[:, -1] = 1.0  # intercept
        w = rng.normal(size=d).astype(np.float32)
        y = (1 / (1 + np.exp(-x @ w)) > rng.uniform(size=n)).astype(np.float32)

        mean = x.mean(axis=0)
        std = x.std(axis=0, ddof=0)
        norm = build_normalization(
            NormalizationType.STANDARDIZATION,
            mean=mean, std=std, max_magnitude=np.abs(x).max(axis=0),
            intercept_index=d - 1,
        )
        batch_raw = make_dense_batch(x, y)
        models_norm, _ = train_generalized_linear_model(
            batch_raw, TaskType.LOGISTIC_REGRESSION, d,
            regularization_weights=[0.0], normalization=norm,
            intercept_index=d - 1,
        )
        # Manually transformed data (intercept col untouched).
        x2 = (x - mean) / np.where(std > 0, std, 1.0)
        x2[:, -1] = 1.0
        models_pre, _ = train_generalized_linear_model(
            make_dense_batch(x2.astype(np.float32), y),
            TaskType.LOGISTIC_REGRESSION, d, regularization_weights=[0.0],
        )
        # models_norm is already back in original space; map the
        # pre-transformed model back by hand to compare.
        w_pre = np.asarray(models_pre[0.0].means)
        factor = 1.0 / np.where(std > 0, std, 1.0)
        w_back = w_pre * factor
        w_back[-1] = w_pre[-1] - np.sum((mean * factor)[:-1] * w_pre[:-1])
        np.testing.assert_allclose(
            np.asarray(models_norm[0.0].means), w_back, atol=2e-2
        )


class TestSamplers:
    def test_binary_keeps_positives(self, rng):
        x, y = logistic_data(rng, n=200, d=4)
        batch = make_dense_batch(x, y)
        key = jax.random.PRNGKey(0)
        out = binary_classification_down_sample(key, batch, 0.3)
        w = np.asarray(out.weights)
        lab = np.asarray(batch.labels)
        orig_w = np.asarray(batch.weights)
        # positives untouched
        np.testing.assert_allclose(w[lab > 0.5], orig_w[lab > 0.5])
        # kept negatives rescaled by 1/rate
        kept_neg = (lab <= 0.5) & (w > 0) & (orig_w > 0)
        np.testing.assert_allclose(w[kept_neg], orig_w[kept_neg] / 0.3)
        # expected weight mass approximately preserved
        assert w[lab <= 0.5].sum() == pytest.approx(
            orig_w[lab <= 0.5].sum(), rel=0.35
        )

    def test_default_unbiased_mass(self, rng):
        x, y = logistic_data(rng, n=400, d=4)
        batch = make_dense_batch(x, y)
        out = default_down_sample(jax.random.PRNGKey(1), batch, 0.5)
        assert float(np.asarray(out.weights).sum()) == pytest.approx(
            float(np.asarray(batch.weights).sum()), rel=0.2
        )


class TestKernelSwitch:
    """The tiled/scatter kernel switch must not change training results
    (task 'single construction switch' — optim.problem.create_glm_problem)."""

    def test_tiled_training_matches_scatter(self, rng):
        import numpy as np
        import jax.numpy as jnp
        from photon_ml_tpu.data.batch import make_sparse_batch
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import train_generalized_linear_model

        n, d, k = 120, 40, 5
        rows, labels = [], []
        w_true = rng.normal(size=d)
        for _ in range(n):
            ix = rng.choice(d, size=k, replace=False)
            vs = rng.normal(size=k)
            z = float((w_true[ix] * vs).sum())
            labels.append(float(rng.uniform() < 1 / (1 + np.exp(-z))))
            rows.append((ix.tolist(), vs.tolist()))
        batch = make_sparse_batch(rows, labels)

        kwargs = dict(
            regularization_weights=[1.0, 0.1],
            max_iter=25,
        )
        m_sc, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, kernel="scatter", **kwargs
        )
        m_ti, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, kernel="tiled", **kwargs
        )
        for lam in m_sc:
            # bf16x2 gradient noise (~1e-5/eval) compounds over the L-BFGS
            # trajectory; solutions agree to ~0.2% relative, which is well
            # inside statistical noise for a fitted GLM.
            np.testing.assert_allclose(
                np.asarray(m_ti[lam].coefficients.means),
                np.asarray(m_sc[lam].coefficients.means),
                rtol=0.02, atol=1e-2,
            )

    def test_auto_resolves_scatter_on_cpu(self):
        from photon_ml_tpu.optim.problem import resolve_kernel

        assert resolve_kernel("auto") == "scatter"  # tests run on CPU
        assert resolve_kernel("tiled") == "tiled"
        assert resolve_kernel("scatter") == "scatter"
