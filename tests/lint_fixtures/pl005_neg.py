"""PL005 negatives: submit_io scopes that reach their barrier."""

from photon_ml_tpu.parallel import overlap


def submit_then_drain(write, paths):
    for p in paths:
        overlap.submit_io(write, p)
    overlap.drain_io()  # barrier before return — fine


def drain_in_finally(write, path):
    try:
        overlap.submit_io(write, path)
    finally:
        overlap.drain_io()  # fine


def only_drains():
    overlap.drain_io()  # draining without submitting is always fine
