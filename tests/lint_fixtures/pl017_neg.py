"""PL017 negative: accumulation with the order pinned (or over ordered
containers to begin with)."""

import math

import numpy as np


def total_weight(weights):
    vals = set(weights)
    return sum(sorted(vals))


def exact_total(weights):
    vals = frozenset(weights)
    return math.fsum(sorted(vals))


def np_total(bucket_values):
    return np.sum(np.asarray(bucket_values))


def list_total(values):
    return sum(values)
