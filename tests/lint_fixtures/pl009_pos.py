"""PL009 positive: a two-lock acquisition-order inversion (one cycle,
reported at both participating edge sites)."""
import threading

_A = threading.Lock()
_B = threading.Lock()


def forward():
    with _A:
        with _B:  # acquires B while holding A
            pass


def backward():
    with _B:
        with _A:  # acquires A while holding B: the inversion
            pass
