"""The pl018_pos frontend: routes everything EXCEPT the orphan type
and maps only the 'malformed' error kind."""


def route(mtype, wire):
    if mtype == wire.MSG_JSON:
        return "json"
    if mtype == wire.MSG_SCORE:
        return "score"
    if mtype == wire.MSG_DUP:
        return "dup"
    return "refused"


def classify(err):
    if getattr(err, "kind", "") == "malformed":
        return "BAD_REQUEST"
    return "ERROR"
