"""PL003 negatives: static control flow inside jitted bodies."""

import jax
import jax.numpy as jnp
from functools import partial


@jax.jit
def static_metadata(x):
    if x.shape[0] > 4:  # shapes are static at trace time — fine
        return x[:4]
    if x.ndim == 1:  # fine
        return x
    return jnp.ravel(x)


@jax.jit
def none_and_isinstance(x, scale=None):
    if scale is None:  # identity test — fine
        scale = 1.0
    if isinstance(x, tuple):  # fine
        x = x[0]
    if len(x.shape) == 2:  # fine
        x = x[0]
    return x * scale


@partial(jax.jit, static_argnames=("flag",))
def static_arg_branch(x, flag):
    if flag:  # static argument — fine
        return x * 2.0
    return x


def not_jitted(x):
    if x > 0:  # plain python function — fine
        return x
    return -x


@jax.jit
def device_branching(x):
    return jnp.where(x > 0, x, -x)  # the jax-native branch — fine
