"""PL006 negatives: atomic publishes, seam-routed IO, teardown scopes."""

import json
import os

from photon_ml_tpu.reliability.artifacts import atomic_write_json, atomic_writer
from photon_ml_tpu.reliability.retry import io_call


def write_via_helper(path, payload):
    atomic_write_json(path, payload)  # the blessed path


def write_via_writer(path, lines):
    with atomic_writer(path) as f:  # helper in scope — fine
        f.write("\n".join(lines))


def write_with_explicit_replace(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:  # fine: os.replace publishes atomically
        json.dump(payload, f)
    os.replace(tmp, path)


def read_through_seam(path):
    def _load():
        with open(path) as f:
            return json.load(f)

    try:
        return io_call("cache_load", _load, detail=path)
    except Exception:
        pass  # fine: the operation already went through the retry layer
    return None


def reads_are_not_writes(path):
    with open(path) as f:  # read mode: not an artifact publish
        return f.read()


def appends_are_stream_writers(path, data):
    with open(path, "ab") as f:  # append: the spill-writer protocol
        f.write(data)


class Store:
    def close(self):
        try:
            os.remove(self._path)
        except OSError:
            pass  # teardown scope: best-effort cleanup is the contract

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
