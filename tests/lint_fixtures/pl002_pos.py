"""PL002 positives: recompile hazards."""

import jax
from functools import partial

import jax.numpy as jnp


def jit_of_lambda(dim):
    return jax.jit(lambda b: jnp.sum(b) * dim)  # violation: lambda


def jit_in_loop(fns, xs):
    out = []
    for f in fns:
        jf = jax.jit(f)  # violation: re-wrapped per iteration
        out.append(jf(xs))
    return out


def jit_def_in_loop(xs):
    outs = []
    for x in xs:
        @jax.jit  # violation: def re-created per iteration
        def step(v):
            return v * 2.0

        outs.append(step(x))
    return outs


def unhashable_static(f):
    return jax.jit(f, static_argnums=[0, 1])  # violation: list literal


def unhashable_static_partial(f):
    return partial(jax.jit, static_argnames=["dim"])(f)  # violation
