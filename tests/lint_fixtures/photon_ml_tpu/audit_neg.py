"""Seam-audit negatives: allowed raw fetches that stay accounted — the
scope either gates on the overlap-off serial switch or also feeds the
counted seam."""

import jax

from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.parallel.overlap import overlap_enabled


def serial_path_fetch(tree):
    if not overlap_enabled():
        return jax.device_get(tree)  # photon: allow(hidden-host-sync)
    return overlap.device_get(tree)


def counted_alongside(tree, other):
    host = overlap.device_get(other)  # the counted fetch
    raw = jax.device_get(tree)  # photon: allow(hidden-host-sync)
    return host, raw
