"""PL011 contract positives (package-scoped): a mesh entry point with
no sharding declaration, and declarations that drifted from the code."""

from functools import partial

import jax
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"


def undeclared_entry(mesh):
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    def vg(w, batch):  # no sharding declaration -> violation
        return lax.psum(batch.sum() * w.sum(), DATA_AXIS)

    return jax.jit(vg)


def typo_axis_declared(mesh):
    # photon: sharding(axes=[entiy], in=[r,data], out=[r])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    def vg(w, batch):  # declared axis is a typo AND misses 'data'
        return lax.psum(batch.sum() * w.sum(), DATA_AXIS)

    return jax.jit(vg)


def spec_drift_declared(mesh):
    # photon: sharding(axes=[data], in=[data,data], out=[r])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    def vg(w, batch):  # declared in= does not match the code's specs
        return lax.psum(batch.sum() * w.sum(), DATA_AXIS)

    return jax.jit(vg)
