"""PL012 positive (package-scoped): host gathers of sharded banks on
paths with no export/checkpoint declaration."""

import numpy as np

from photon_ml_tpu.parallel import overlap


class ShardedREBank:
    def __init__(self, mesh, spec, data):
        self.data = data

    @classmethod
    def zeros(cls, mesh, spec, dim) -> "ShardedREBank":
        return cls(mesh, spec, None)

    def to_global(self):
        return self.data


def undeclared_to_global(bank):
    if isinstance(bank, ShardedREBank):
        return bank.to_global()  # replicated [E, d] off the shards
    return bank


def undeclared_device_get(mesh, spec):
    bank = ShardedREBank.zeros(mesh, spec, 4)
    return overlap.device_get(bank.data)  # counted, but still a gather


class Holder:
    def __init__(self, sharded_bank):
        self.sharded_bank = sharded_bank

    def snapshot(self):
        return np.asarray(self.sharded_bank.data)  # host [E, d]
