"""Seam-audit positive: an allow(hidden-host-sync) in package-path code
whose scope never touches the seam — the readback routes around the
counter, so the allow itself is a violation (and unsuppressable)."""

import jax


def rogue_allowed_fetch(tree):
    return jax.device_get(tree)  # photon: allow(hidden-host-sync)
