"""PL012 negative (package-scoped): declared export/checkpoint scopes
gather legitimately; non-bank values are untouched."""

import numpy as np

from photon_ml_tpu.parallel import overlap


class ShardedREBank:
    def __init__(self, mesh, spec, data):
        self.data = data

    @classmethod
    def zeros(cls, mesh, spec, dim) -> "ShardedREBank":
        return cls(mesh, spec, None)

    def to_global(self):
        return self.data


# photon: sharding(export)
def export_model(bank):
    """Model artifacts are host-side by definition."""
    if isinstance(bank, ShardedREBank):
        return bank.to_global()
    return bank


# photon: sharding(checkpoint)
def checkpoint_bank(bank: ShardedREBank):
    return np.asarray(bank.data)


def scalar_readback(bank: ShardedREBank):
    # a device scalar derived from the bank is not a bank gather
    term = bank.data if False else None
    return overlap.device_get(compute_term(term))


def compute_term(data):
    return data


def unrelated_numpy(rows):
    return np.asarray(rows)
