"""PL018 negative: every message type has an encoder, a decoder branch
and a dispatch reference; every WireError kind is frontend-mapped."""

MAGIC = 0xF7
MSG_JSON = 0x01
MSG_SCORE = 0x02


class WireError(ValueError):
    def __init__(self, message, *, kind="malformed"):
        super().__init__(message)
        self.kind = kind


def append_frame(buf, msg_type, *parts):
    buf.append(msg_type)
    for p in parts:
        buf.extend(p)


def append_json(buf, obj):
    append_frame(buf, MSG_JSON, b"{}")


def append_score(buf):
    append_frame(buf, MSG_SCORE, b"")


def decode_message(msg_type, payload):
    if len(payload) > 1 << 20:
        raise WireError("frame too large", kind="oversized")
    if msg_type == MSG_JSON:
        return {}
    if msg_type == MSG_SCORE:
        return {}
    raise WireError("unknown message type")
