"""The pl018_neg frontend: every wire type routed, every error kind
named."""


def route(mtype, wire):
    if mtype == wire.MSG_JSON:
        return "json"
    if mtype == wire.MSG_SCORE:
        return "score"
    return "refused"


def classify(err):
    kind = getattr(err, "kind", "")
    if kind == "malformed":
        return "BAD_REQUEST"
    if kind == "oversized":
        return "PAYLOAD_TOO_LARGE"
    return "ERROR"
