"""PL009 negative: every path acquires in one global order."""
import threading

_A = threading.Lock()
_B = threading.Lock()


def one():
    with _A:
        with _B:
            pass


def two():
    with _A:
        with _B:
            pass


class Ordered:
    def __init__(self):
        self._outer = threading.Lock()
        self._inner = threading.Lock()

    def op(self):
        with self._outer:
            with self._inner:
                pass

    def other(self):
        with self._outer:
            with self._inner:
                pass
