"""PL001 positives: every statement here is a hidden host sync."""

import jax
import jax.numpy as jnp
import numpy as np


def raw_device_get(tree):
    return jax.device_get(tree)  # violation: raw fetch


def raw_block(x):
    x.block_until_ready()  # violation: hidden sync
    return x


def np_asarray_on_jax():
    device = jnp.ones((4,))
    return np.asarray(device)  # violation: host copy of a jax value


def scalar_casts():
    total = jnp.sum(jnp.arange(3))
    a = float(total)  # violation
    b = int(total)  # violation
    c = bool(total > 0)  # violation
    return a, b, c


def derived_taint():
    x = jnp.zeros((2,))
    y = x + 1.0  # taint flows through arithmetic
    return float(y[0])  # violation
