"""PL015 negative: the same writer shapes with the order pinned."""

import json
import os

from photon_ml_tpu.reliability import atomic_write_json


def dump_feature_names(path, names):
    uniq = set(names)
    atomic_write_json(path, {"features": sorted(uniq)})


def dump_listing(root):
    files = sorted(os.listdir(root))
    return json.dumps({"files": files})


def dump_union(path, a, b):
    merged = set(a).union(b)
    return json.dumps(sorted(merged))


def write_parts(path, parts):
    lines = []
    for p in sorted(set(parts)):
        lines.append(str(p))
    atomic_write_json(path, lines)


def count_only(path, parts):
    # order-erasing reductions are fine: the set never orders bytes
    atomic_write_json(path, {"n": len(set(parts))})


def membership_walk(parts):
    # iterating a set in a scope that writes NOTHING is not a finding
    total = 0
    for p in set(parts):
        total += 1
    return total
