"""PL013 positive: a replication claim with no reduction, and a psum
over an axis the specs never shard."""

from functools import partial

import jax
from jax import lax, shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def unreduced_replication(mesh):
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def body(w, batch):
        partial_sum = jnp.sum(batch * w)  # device-local partial
        total = lax.psum(partial_sum, DATA_AXIS)
        return total, partial_sum  # second output claims P() unreduced

    return jax.jit(body)


def unbound_axis_psum(mesh):
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS),),
        out_specs=P(),
        check_vma=False,
    )
    def body(batch):
        # MODEL_AXIS is not in this site's specs: the psum either
        # multiplies replicated values or binds a stale axis
        return lax.psum(jnp.sum(batch), MODEL_AXIS)

    return jax.jit(body)
