"""PL002 negatives: stable-identity jit usage."""

import jax
from functools import partial

import jax.numpy as jnp


def _moments(b, dim):
    return jnp.sum(b) * dim


_MOMENTS_JIT = jax.jit(_moments, static_argnums=(1,))  # tuple — fine


@jax.jit
def decorated(x):
    return x * 2.0


@partial(jax.jit, static_argnums=(1,))
def decorated_partial(x, flag):
    return x if flag else -x


def factory(dim):
    def fit(w):
        return jnp.sum(w) * dim

    return jax.jit(fit)  # named def, built once per factory call — fine


def loop_calls_prebuilt(xs):
    out = []
    for x in xs:
        out.append(decorated(x))  # calling a jitted fn in a loop — fine
    return out
