"""PL004 positives (path contains an io/ segment, so the rule applies)."""

import tempfile
from tempfile import TemporaryDirectory, mkdtemp


def unswept_scratch():
    return tempfile.mkdtemp(prefix="photon-spill-")  # violation


def unswept_bare():
    return mkdtemp(prefix="photon-spill-")  # violation


def unswept_tempdir():
    return TemporaryDirectory(prefix="photon-spill-")  # violation
