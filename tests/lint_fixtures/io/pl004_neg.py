"""PL004 negatives: registered scratch dirs."""

import tempfile

from photon_ml_tpu.io.streaming import make_spill_dir, register_spill_dir


def registered_scratch():
    path = tempfile.mkdtemp(prefix="photon-spill-")
    register_spill_dir(path)  # paired with the sweep — fine
    return path


def through_helper():
    return make_spill_dir("photon-spill-")  # the blessed factory — fine
