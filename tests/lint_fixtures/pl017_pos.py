"""PL017 positive: order-dependent float accumulation over unordered
iterables."""

import math

import numpy as np


def total_weight(weights):
    vals = set(weights)
    return sum(vals)


def exact_total(weights):
    vals = frozenset(weights)
    return math.fsum(vals)


def np_total(bucket_values):
    bucket = set(bucket_values)
    return np.sum([x for x in bucket])
