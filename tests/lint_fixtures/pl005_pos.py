"""PL005 positives: submitted IO with no drain barrier in scope."""

from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.parallel.overlap import submit_io


def fire_and_forget(write, path):
    overlap.submit_io(write, path)  # violation: nothing drains


def fire_and_forget_bare(write, path):
    submit_io(write, path)  # violation
    return path
