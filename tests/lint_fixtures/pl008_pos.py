"""PL008 positives: nine seeded unguarded-shared-state violations."""
import threading


class BareReadWrite:
    def __init__(self):
        self._lock = threading.Lock()
        self._flag = False

    def set_flag(self):
        with self._lock:
            self._flag = True  # guarded write: establishes the guard

    def bare_write(self):
        self._flag = False  # VIOLATION 1: bare write of guarded attr

    def bare_read(self):
        return self._flag  # VIOLATION 2: bare read of guarded attr


class AtomicMutation:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0  # photon: guarded-by(atomic)

    def bump(self):
        self._count += 1  # VIOLATION 3: read-modify-write on atomic


class DeclaredGuard:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"  # photon: guarded-by(_lock)

    def ok(self):
        with self._lock:
            self._state = "busy"

    def bad(self):
        return self._state  # VIOLATION 4: declared guard not held


class SharedFlag:
    def __init__(self):
        self._running = False
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._loop)
        self._worker.start()

    def _loop(self):
        while self._running:  # VIOLATION 5: thread-side bare read
            pass

    def stop(self):
        self._running = False  # VIOLATION 6: caller-side bare write


def lambda_target():
    t = threading.Thread(target=lambda: None)  # VIOLATION 7: lambda
    t.start()
    return t


def escaped_local():
    results = {}

    def worker():
        results["x"] = 1  # mutated bare inside the thread target

    t = threading.Thread(target=worker)
    t.start()
    results["y"] = 2  # VIOLATION 8: ...and by the spawning scope
    t.join()
    return results


class LockExpectedHelper:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def _get(self, k):  # photon: guarded-by(_lock)
        return self._items.get(k)

    def caller_ok(self, k):
        with self._lock:
            return self._get(k)

    def caller_bad(self, k):
        return self._get(k)  # VIOLATION 9: lock-expected helper, bare
