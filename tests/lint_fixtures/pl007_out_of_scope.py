"""PL007 scope check: the same untimed waits OUTSIDE serving/ are not
request-path code (driver replay loops may block on their own futures)."""

import threading
from concurrent.futures import Future


def untimed_wait_is_fine_here(cond: threading.Condition, fut: Future):
    with cond:
        cond.wait()
    return fut.result()
