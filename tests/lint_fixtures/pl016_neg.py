"""PL016 negative: declared entropy, durations, decisions, content-
derived seeds and hash-probe keys are all clean."""

import json
import os
import random
import time
import zlib

from photon_ml_tpu.reliability import atomic_write_json

_CACHE = {}


def write_discovery(path):
    atomic_write_json(path, {"pid": os.getpid()})  # photon: entropy(discovery artifact; pid names the live process)


def write_lease(path):  # photon: entropy(lease identity payload; uniqueness is the point)
    atomic_write_json(path, {"pid": os.getpid(), "token": "t"})


def elapsed(path, t0):
    # clock MINUS clock is a duration — content, not entropy
    dt = time.perf_counter() - t0
    return json.dumps({"elapsed_s": dt})


def expired(deadline):
    # a clock COMPARISON yields a decision, not entropy content
    return time.monotonic() >= deadline


def lookup_by_content(key):
    # builtin hash() as a hashability probe / dict key is the dict's
    # own business — only PYTHONHASHSEED-exposed ARTIFACTS are findings
    return _CACHE.get(hash(key))


def stable_draw(name):
    return random.Random(zlib.crc32(name.encode("utf-8"))).random()
