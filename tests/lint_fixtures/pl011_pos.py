"""PL011 positive: axis-name literals in every checked position."""

import jax
from jax import lax
from jax.sharding import PartitionSpec as P


def partition_spec_literal(mesh):
    return P("data")  # literal in P(...)


def collective_literal(x):
    return lax.psum(x, "data")  # literal collective axis


def stale_axis_literal(x):
    return lax.all_gather(x, "entiy")  # typo'd axis — binds nothing


def axis_param_default(batch, axis_name="model"):
    return jax.device_put(batch), axis_name


def boolop_fallback(axis=None):
    return axis or "data"  # literal fallback for an axis name
