"""PL014 negative: the rebind-the-result swap idiom, conditional
donation tuples, and defensive copies."""

from functools import partial

import jax
import jax.numpy as jnp


def _donate_args():
    return (0,) if jax.default_backend() != "cpu" else ()


@partial(jax.jit, donate_argnums=_donate_args())
def refresh(old, new):
    return jnp.where(jnp.bool_(True), new, old)


def rebind_swap(bank, new_values):
    bank = refresh(bank, new_values)  # donor replaced by the result
    return bank


def loop_rebind(bank, updates):
    for u in updates:
        bank = refresh(bank, u)
    return bank


def defensive_copy(bank, new_values):
    data = jnp.array(bank, copy=True)
    data = refresh(data, new_values)
    return data, bank  # the caller's bank was never donated


def non_donated_position(bank, new_values):
    out = refresh(jnp.array(bank, copy=True), new_values)
    return out, new_values  # position 1 is not donated
