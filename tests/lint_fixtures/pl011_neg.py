"""PL011 negative: constants everywhere, declarations that match."""

from functools import partial

import jax
from jax import lax, shard_map
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def collective_constant(x):
    return lax.psum(x, DATA_AXIS)


def axis_param_default(batch, axis_name=DATA_AXIS):
    return jax.device_put(batch), axis_name


def boolop_fallback(axis=None):
    return axis or DATA_AXIS


def empty_string_sentinel(axis=""):
    # an empty default is a sentinel, not an axis literal
    return axis


def data_parallel(mesh):
    # photon: sharding(axes=[data], in=[r,data], out=[r])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    def vg(w, batch):
        return lax.psum(batch.sum() * w.sum(), DATA_AXIS)

    return jax.jit(vg)


def two_axis(mesh, data_axis=DATA_AXIS, model_axis=MODEL_AXIS):
    # variadic tail + multi-axis spec tokens in the declaration
    # photon: sharding(axes=[data,model], in=[model,data+model,*], out=[r])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(model_axis), P(data_axis, model_axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    def vg(w_block, x_block, l2):
        z = lax.psum(x_block @ w_block, model_axis)
        return lax.psum(z.sum(), data_axis) + l2

    return jax.jit(vg)
