"""PL006 positives: torn-artifact writes and swallowed IO failures."""

import json
import os


def write_metrics_torn(path, payload):
    with open(path, "w") as f:  # violation: no atomic publish in scope
        json.dump(payload, f)


def write_blob_torn(path, data):
    f = open(path, mode="wb")  # violation: keyword mode, still a write
    f.write(data)
    f.close()


def swallow_io_failure(path):
    try:
        with open(path) as f:
            return f.read()
    except OSError:  # violation: IO failure silently swallowed
        pass


def swallow_in_loop(paths):
    out = []
    for p in paths:
        try:
            out.append(os.path.getsize(p) and open(p).read())
        except Exception:  # violation: blanket except-and-continue
            continue
    return out
