"""PL001 negatives: counted-seam fetches and genuinely-host values."""

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.parallel.overlap import device_get


def seam_fetch(tree):
    return overlap.device_get(tree)  # the counted seam — fine


def seam_fetch_bare(tree):
    return device_get(tree)  # imported FROM overlap — fine


def host_values_stay_host():
    xs = [1.0, 2.0]
    a = float(xs[0])  # plain python — fine
    b = np.asarray(xs)  # numpy on host data — fine
    return a, b


def jnp_asarray_is_not_a_sync():
    host = np.zeros((4,))
    return jnp.asarray(host)  # host->device, not a readback — fine


def metadata_is_host_side():
    devs = jax.devices()
    return np.asarray(devs), int(jax.device_count())  # metadata — fine
