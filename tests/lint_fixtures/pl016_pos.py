"""PL016 positive: ambient entropy reaching artifacts, cache keys and
seeds — plus one stale and one reasonless declaration."""

import json
import os
import random
import socket
import time

from photon_ml_tpu.reliability import atomic_write_json

_CACHE = {}


def write_summary(path):
    atomic_write_json(path, {"pid": os.getpid()})


def render_status():
    return json.dumps({"ts": time.time()})


def seeded_draw():
    return random.Random(time.time()).random()


def lookup(obj):
    return _CACHE.get(id(obj))


def store(obj, value):
    _CACHE[id(obj)] = value


def describe():
    return {"host": socket.gethostname()}


def stale_claim(path, payload):
    # photon: entropy(this line consumes nothing)
    atomic_write_json(path, payload)


def reasonless(path):  # photon: entropy()
    atomic_write_json(path, {"pid": os.getpid()})
