"""PL010 negatives: small, private, actually-atomic critical sections."""
import threading


class Disciplined:
    def __init__(self, on_done, metrics):
        self._serial = threading.Lock()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items = []
        self.on_done = on_done
        self._metrics = metrics

    def push(self, item):
        with self._lock:
            self._items.append(item)
            self._cond.notify()
        self.on_done(item)  # callback AFTER release
        self._metrics.record_thing()  # foreign lock AFTER release

    def wake(self):
        with self._cond:  # the condition's own lock is held
            self._cond.notify_all()

    def protocol(self):
        # read-then-write across two inner sections is fine when ONE
        # outer lock provably spans both (the serialize-the-protocol
        # idiom the watcher uses)
        with self._serial:
            with self._lock:
                n = list(self._items)
            with self._lock:
                self._items = []
            return n


class Foreign:
    def __init__(self):
        self._flock = threading.Lock()

    def record_thing(self):
        with self._flock:
            pass
