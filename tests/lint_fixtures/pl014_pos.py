"""PL014 positive: donated arguments referenced after the donating
call."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def refresh(old, new):
    return jnp.where(jnp.bool_(True), new, old)


def use_after_donate(old_bank, new_bank):
    out = refresh(old_bank, new_bank)
    return out, old_bank.shape, old_bank  # old_bank's buffer is gone


def _build_donating():
    @partial(jax.jit, donate_argnums=(0,))
    def step(state, grad):
        return state - grad

    return step


def builder_use_after_donate(state, grad):
    step = _build_donating()
    result = step(state, grad)
    return result + state  # donated through the builder-made callable
