"""PL013 negative: complete reductions, psum-through-helper one hop,
and unknown calls stay unflagged."""

from functools import partial

import jax
from jax import lax, shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"


def _psum_helper(value):
    return lax.psum(value, DATA_AXIS)


def reduced_replication(mesh):
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS)),
        check_vma=False,
    )
    def body(w, batch):
        scores = batch * w  # stays sharded -> sharded out_spec
        total = lax.psum(jnp.sum(scores), DATA_AXIS)
        return total, scores

    return jax.jit(body)


def psum_through_helper(mesh):
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    def body(w, batch):
        # the reduction lives one call away — still complete
        return _psum_helper(jnp.sum(batch * w))

    return jax.jit(body)


def unknown_call_is_not_flagged(optimize, mesh):
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    def body(w, batch):
        # `optimize` may reduce internally; the analyzer cannot prove
        # the absence of a psum, so it stays silent
        return optimize(w, batch)

    return jax.jit(body)
