"""PL004 scope negative: outside io// game streaming the rule is silent
(bench harnesses and tests own their own cleanup)."""

import tempfile


def bench_scratch():
    return tempfile.mkdtemp(prefix="bench-")  # out of scope — fine
