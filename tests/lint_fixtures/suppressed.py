"""Suppression fixtures: every violation here carries an allow comment.

(Not under a photon_ml_tpu/ segment, so the PL001 allow-site audit stays
informational — see the photon_ml_tpu/ fixture subtree for the audit.)
"""

import jax
import jax.numpy as jnp

from photon_ml_tpu.parallel import overlap


def same_line_id(tree):
    return jax.device_get(tree)  # photon: allow(PL001)


def same_line_slug(tree):
    return jax.device_get(tree)  # photon: allow(hidden-host-sync)


def standalone_comment(tree):
    # photon: allow(hidden-host-sync)
    return jax.device_get(tree)


def multi_rule(write, path):
    # photon: allow(undrained-io, recompile-hazard)
    return overlap.submit_io(write, path), jax.jit(lambda x: x)


def wrong_rule_does_not_suppress(tree):
    return jax.device_get(tree)  # photon: allow(recompile-hazard)
