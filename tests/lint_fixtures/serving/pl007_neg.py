"""PL007 negatives: bounded waits, done-callback reads, non-primitive
helpers."""

import threading
from concurrent.futures import Future


def timed_condition_wait(cond: threading.Condition, budget: float):
    with cond:
        while not cond.wait(timeout=budget):
            break


def timed_keyword_wait(ev: threading.Event):
    while not ev.wait(timeout=0.1):
        continue


def timed_future_result(fut: Future, timeout: float):
    return fut.result(timeout=timeout)


def done_callback_read(fut: Future):
    # inside a done-callback the future is terminal: timeout=0 cannot
    # block, and satisfies the bounded-wait contract
    return fut.result(timeout=0)


def bare_helper_named_result():
    def result():
        return 1

    return result()  # a local helper, not the stdlib primitive
