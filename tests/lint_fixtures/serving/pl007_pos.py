"""PL007 positives: untimed blocking waits on the request path."""

import threading
from concurrent.futures import Future


def untimed_condition_wait(cond: threading.Condition):
    with cond:
        cond.wait()  # PL007: unbounded — cannot observe shutdown


def untimed_event_wait(ev: threading.Event):
    ev.wait()  # PL007: unbounded park


def untimed_future_result(fut: Future):
    return fut.result()  # PL007: hangs forever on a lost wakeup
