"""PL003 positives: tracers escaping or steering jitted bodies."""

import jax
import jax.numpy as jnp

_LAST = None


class Holder:
    @jax.jit
    def store_on_self(self, x):
        self.cache = x  # violation: tracer stored on the instance
        return x * 2.0


@jax.jit
def branch_on_traced(x):
    if x > 0:  # violation: python branch on a tracer
        return x
    return -x


@jax.jit
def while_on_traced(x):
    while x < 10.0:  # violation: python loop on a tracer
        x = x * 2.0
    return x


@jax.jit
def leak_to_global(x):
    global _LAST  # violation: traced value written to module state
    _LAST = x
    return x


@jax.jit
def branch_on_derived(x):
    y = jnp.sum(x)
    if y.item() > 0:  # violation: .item() concretizes the tracer
        return x
    return -x
