"""PL015 positive: unordered iteration order reaching artifact bytes."""

import json
import os

from photon_ml_tpu.reliability import atomic_write_json


def dump_feature_names(path, names):
    uniq = set(names)
    atomic_write_json(path, {"features": [n for n in uniq]})


def dump_listing(root):
    files = os.listdir(root)
    return json.dumps({"files": files})


def dump_union(path, a, b):
    merged = set(a).union(b)
    return json.dumps(list(merged))


def write_parts(path, parts):
    lines = []
    for p in set(parts):
        lines.append(str(p))
    atomic_write_json(path, lines)
