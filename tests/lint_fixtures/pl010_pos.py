"""PL010 positives: five seeded atomicity-hygiene violations."""
import threading


class CallbackUnderLock:
    def __init__(self, on_done):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items = []
        self.on_done = on_done

    def push(self, item, sock):
        with self._lock:
            self._items.append(item)
            self.on_done(item)  # VIOLATION 1: callback under the lock
            sock.sendall(b"x")  # VIOLATION 2: blocking under the lock
            self._cond.notify()

    def wake_wrong(self):
        self._cond.notify_all()  # VIOLATION 3: notify without the lock

    def check_then_act(self):
        with self._lock:
            n = self._items  # read under the lock...
        count = len(n)
        with self._lock:
            self._items = []  # VIOLATION 4: ...stale write after release
        return count


class Foreign:
    def __init__(self):
        self._flock = threading.Lock()

    def record_thing(self):
        with self._flock:
            pass


class CallsForeign:
    def __init__(self, metrics):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._metrics = metrics

    def submit(self):
        with self._lock:
            self._metrics.record_thing()  # VIOLATION 5: foreign lock
