"""PL008 negatives: disciplined shared state — no violations."""
import queue
import threading


class Disciplined:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue = []
        self._closed = False
        self._beat = 0.0  # photon: guarded-by(atomic)
        self._out = queue.Queue()  # synchronized type: exempt
        self._stop = threading.Event()  # synchronized type: exempt
        self._worker = threading.Thread(target=self._loop)
        self._worker.start()

    def _loop(self):
        while True:
            self._beat = 1.0  # atomic publish: plain assignment
            with self._cond:  # the condition aliases self._lock
                while not self._queue and not self._closed:
                    self._cond.wait(timeout=0.1)
                if self._closed:
                    return
                item = self._queue.pop()
            self._out.put_nowait(item)

    def submit(self, item):
        with self._lock:
            self._queue.append(item)
            self._cond.notify()

    def close(self):
        with self._lock:
            self._closed = True
            self._cond.notify_all()

    def heartbeat(self):
        return self._beat  # atomic read: allowed anywhere


class NotConcurrent:
    """No locks, no threads: plain single-threaded state is exempt."""

    def __init__(self):
        self.value = 0

    def bump(self):
        self.value += 1


def handoff_via_queue():
    q = queue.Queue()

    def worker():
        q.put(1)  # results flow over the queue, nothing escapes bare

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    return q.get()


def guarded_escape():
    lock = threading.Lock()
    results = {}

    def worker():
        with lock:
            results["x"] = 1  # closure side holds the shared lock

    t = threading.Thread(target=worker)
    t.start()
    with lock:
        results["y"] = 2
    t.join()
    return results


class HelperDiscipline:
    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}

    def _lookup(self, k):  # photon: guarded-by(_lock)
        return self._cache.get(k)

    def get_value(self, k):
        with self._lock:
            return self._lookup(k)
