"""GAME layer tests: dataset building, entity grouping + reservoir cap,
Pearson filter, projections, vmapped RE solves vs direct per-entity
solves, coordinate descent objective decrease, factored RE + MF.

Mirrors GameIntegTest/GameTestUtils validator-style checks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_ml_tpu.game import (
    CoordinateDescent,
    FactoredRandomEffectConfiguration,
    FactoredRandomEffectCoordinate,
    FeatureShardConfiguration,
    FixedEffectCoordinate,
    MatrixFactorizationCoordinate,
    ProjectorType,
    RandomEffectCoordinate,
    RandomEffectDataConfiguration,
    RandomEffectOptimizationProblem,
    build_game_dataset,
    build_random_effect_dataset,
    score_random_effect,
)
from photon_ml_tpu.ops.losses import LINEAR, LOGISTIC
from photon_ml_tpu.optim import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
    minimize_lbfgs,
)
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.task import TaskType


def make_records(rng, n=200, n_users=10, d_global=6, d_user=4):
    """Synthetic GLMix data: global effect + per-user effect."""
    w_global = np.linspace(-1, 1, d_global)
    w_user = rng.normal(size=(n_users, d_user)).astype(np.float32)
    recs = []
    for i in range(n):
        u = int(rng.integers(0, n_users))
        xg = rng.normal(size=d_global).astype(np.float32)
        xu = rng.normal(size=d_user).astype(np.float32)
        z = float(xg @ w_global + xu @ w_user[u])
        y = float(1 / (1 + np.exp(-z)) > rng.uniform())
        recs.append({
            "uid": f"r{i}",
            "response": y,
            "userId": f"user{u:03d}",
            "features": [
                {"name": f"g{j}", "term": "", "value": float(xg[j])}
                for j in range(d_global)
            ],
            "userFeatures": [
                {"name": f"u{j}", "term": "", "value": float(xu[j])}
                for j in range(d_user)
            ],
        })
    return recs, w_global, w_user


SHARDS = [
    FeatureShardConfiguration("globalShard", ["features"], add_intercept=True),
    FeatureShardConfiguration("userShard", ["userFeatures"], add_intercept=True),
]


class TestGameDataset:
    def test_build(self, rng):
        recs, _, _ = make_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        assert ds.num_real_rows == 200
        assert ds.shards["globalShard"].dim == 7  # 6 + intercept
        assert ds.shards["userShard"].dim == 5
        assert ds.entity_indexes["userId"].num_entities == 10
        codes = ds.entity_codes["userId"]
        assert codes[:200].min() >= 0 and codes[:200].max() == 9
        # padding rows have weight 0 and code -1
        assert np.all(ds.weights[200:] == 0)

    def test_metadata_map_ids(self, rng):
        recs = [
            {"response": 1.0, "metadataMap": {"queryId": "q1"}, "features": []},
            {"response": 0.0, "metadataMap": {"queryId": "q2"}, "features": []},
        ]
        ds = build_game_dataset(
            recs, [FeatureShardConfiguration("g", ["features"])], ["queryId"]
        )
        assert ds.entity_indexes["queryId"].num_entities == 2

    def test_scoring_mode_no_response(self):
        recs = [{"features": [{"name": "a", "term": "", "value": 1.0}]}]
        with pytest.raises(ValueError):
            build_game_dataset(recs, [FeatureShardConfiguration("g", ["features"])])
        ds = build_game_dataset(
            recs, [FeatureShardConfiguration("g", ["features"])],
            is_response_required=False,
        )
        assert ds.labels[0] == 0.0


class TestRandomEffectDataset:
    def test_grouping_and_buckets(self, rng):
        recs, _, _ = make_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfiguration("userId", "userShard"),
        )
        assert red.num_entities == 10
        # every real row appears exactly once across buckets
        seen = []
        for b in red.buckets:
            seen.extend(b.row_index[b.row_index >= 0].tolist())
        assert sorted(seen) == sorted(
            np.nonzero((ds.weights > 0) & (ds.entity_codes["userId"] >= 0))[0].tolist()
        )
        # bucket capacities are powers of two and weights pad with 0
        for b in red.buckets:
            assert (b.capacity & (b.capacity - 1)) == 0
            assert np.all(b.weights[b.row_index < 0] == 0)

    def test_reservoir_cap_rescales_weights(self, rng):
        recs, _, _ = make_records(rng, n=300, n_users=3)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfiguration(
                "userId", "userShard", active_data_upper_bound=16
            ),
        )
        assert red.num_active_rows == 3 * 16
        assert red.num_passive_rows == 300 - 48
        # weight mass approximately preserved per entity
        for b in red.buckets:
            for e in range(b.num_entities):
                cnt_total = np.sum(
                    ds.entity_codes["userId"][:300] == b.entity_codes[e]
                )
                mass = b.weights[e].sum()
                assert mass == pytest.approx(cnt_total, rel=1e-5)

    def test_pearson_filter_bounds_dim(self, rng):
        recs, _, _ = make_records(rng, n=60, n_users=30)  # ~2 rows/user
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfiguration(
                "userId", "userShard", features_to_samples_ratio=1.0
            ),
        )
        # with ratio 1 and ~2 samples, local dims stay small (<= samples+icept)
        assert red.local_dim <= 8

    def test_random_projection(self, rng):
        recs, _, _ = make_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfiguration(
                "userId", "userShard",
                projector_type=ProjectorType.RANDOM,
                random_projection_dim=3,
            ),
        )
        assert red.local_dim == 3
        assert red.random_projection.shape == (5, 3)
        # intercept column preserved: last latent dim is the intercept slot
        icept = ds.shards["userShard"].intercept_index
        col = red.random_projection[:, 2]
        expect = np.zeros(5); expect[icept] = 1.0
        np.testing.assert_allclose(col, expect)


class TestRandomEffectSolver:
    def test_matches_direct_solves(self, rng):
        recs, _, _ = make_records(rng, n=120, n_users=5)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        problem = RandomEffectOptimizationProblem(
            LOGISTIC, OptimizerConfig(max_iter=100),
            RegularizationContext(RegularizationType.L2), reg_weight=1.0,
        )
        bank = jnp.zeros((red.num_entities, red.local_dim), jnp.float32)
        bank, tracker = problem.update_bank(bank, red)
        assert tracker.num_entities == 5

        # direct per-entity solve from the raw rows must agree
        codes = ds.entity_codes["userId"]
        for e in range(3):
            rows = np.nonzero((codes == e) & (ds.weights > 0))[0]
            proj = red.projection[e]
            D_e = int((proj >= 0).sum())
            gl2loc = {int(g): l for l, g in enumerate(proj[:D_e])}
            sd = ds.shards["userShard"]
            def vg(w):
                val = 0.0
                grad = jnp.zeros(D_e)
                for i in rows:
                    ix = [gl2loc[int(g)] for g, v in zip(sd.indices[i], sd.values[i]) if v != 0]
                    vs = [float(v) for v in sd.values[i] if v != 0]
                    z = sum(v * w[l] for l, v in zip(ix, vs)) + ds.offsets[i]
                    val = val + ds.weights[i] * LOGISTIC.value(z, ds.labels[i])
                    d1 = ds.weights[i] * LOGISTIC.d1(z, ds.labels[i])
                    for l, v in zip(ix, vs):
                        grad = grad.at[l].add(d1 * v)
                return val + 0.5 * jnp.vdot(w, w), grad + w
            direct = minimize_lbfgs(vg, jnp.zeros(D_e))
            np.testing.assert_allclose(
                np.asarray(bank[e][:D_e]), np.asarray(direct.coefficients),
                atol=5e-3,
            )

    def test_dense_layout_matches_sparse(self, rng):
        """The densified (batched-matmul) solver must agree with the
        gather/scatter solver entity for entity — same optimizer, two
        data layouts."""
        recs, _, _ = make_records(rng, n=160, n_users=6)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        banks = {}
        trackers = {}
        # ELASTIC_NET keeps both layouts on the SAME optimizer (OWL-QN) —
        # isolating the layout change (dense + pure L2 would auto-select
        # the Newton solver, covered by test_newton_solver_matches_lbfgs).
        for layout in ("sparse", "dense"):
            problem = RandomEffectOptimizationProblem(
                LOGISTIC, OptimizerConfig(max_iter=100),
                RegularizationContext(RegularizationType.ELASTIC_NET, 0.5),
                reg_weight=1.0, layout=layout,
            )
            bank = jnp.zeros((red.num_entities, red.local_dim), jnp.float32)
            banks[layout], trackers[layout] = problem.update_bank(bank, red)
        # atol: the two layouts reduce in different float32 orders, so the
        # OWL-QN optima land within convergence tolerance of each other,
        # not bitwise — on CPU hosts the worst element lands ~3e-4 apart
        # (the seed's 2e-4 tripped on exactly 2/30 elements)
        np.testing.assert_allclose(
            np.asarray(banks["dense"]), np.asarray(banks["sparse"]),
            atol=5e-4,
        )
        # Both layouts must actually converge (exact reason-for-reason
        # equality would be flaky: the two float32 reduction orders can
        # trip different tolerance tests at the boundary).
        for tracker in trackers.values():
            assert tracker.reason_counts.get("MaxIterations", 0) == 0

    def test_newton_solver_matches_lbfgs(self, rng):
        """The dual-space Newton path (auto-selected for dense + L2 + twice
        -differentiable loss) must reach the same optimum as L-BFGS — same
        convex objective, different algorithm."""
        recs, _, _ = make_records(rng, n=160, n_users=6)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        banks = {}
        # layout="dense" + L2 auto-selects Newton; layout="sparse" is LBFGS
        for layout in ("sparse", "dense"):
            problem = RandomEffectOptimizationProblem(
                LOGISTIC, OptimizerConfig(max_iter=100),
                RegularizationContext(RegularizationType.L2),
                reg_weight=1.0, layout=layout,
            )
            if layout == "dense":
                assert problem._use_dense(red.buckets[0], red.local_dim)
            bank = jnp.zeros((red.num_entities, red.local_dim), jnp.float32)
            banks[layout], tracker = problem.update_bank(bank, red)
        np.testing.assert_allclose(
            np.asarray(banks["dense"]), np.asarray(banks["sparse"]),
            atol=2e-3,
        )
        # Newton converges in far fewer iterations than L-BFGS
        assert tracker.iterations_max <= 20

    def test_bank_variances_match_direct(self, rng):
        """bank_variances = 1/(Hdiag + eps) per entity at the solution,
        Hdiag[j] = sum_i w_i l''(z_i) x_ij^2 + l2 (isComputingVariance,
        RandomEffectOptimizationProblem.scala:106-127)."""
        from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

        recs, _, _ = make_records(rng, n=120, n_users=5)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        problem = RandomEffectOptimizationProblem(
            LOGISTIC, OptimizerConfig(max_iter=100),
            RegularizationContext(RegularizationType.L2), reg_weight=1.0,
        )
        bank = jnp.zeros((red.num_entities, red.local_dim), jnp.float32)
        bank, _ = problem.update_bank(bank, red)
        variances = np.asarray(problem.bank_variances(bank, red))
        assert variances.shape == bank.shape
        assert (variances > 0).all()

        codes = ds.entity_codes["userId"]
        sd = ds.shards["userShard"]
        for e in range(3):
            rows = np.nonzero((codes == e) & (ds.weights > 0))[0]
            proj = red.projection[e]
            D_e = int((proj >= 0).sum())
            gl2loc = {int(g): l for l, g in enumerate(proj[:D_e])}
            hd = np.full(D_e, 1.0)  # l2 = reg_weight
            for i in rows:
                z = ds.offsets[i]
                for g, v in zip(sd.indices[i], sd.values[i]):
                    if v != 0:
                        z += v * float(bank[e, gl2loc[int(g)]])
                d2 = float(ds.weights[i]) * float(LOGISTIC.d2(z, ds.labels[i]))
                for g, v in zip(sd.indices[i], sd.values[i]):
                    if v != 0:
                        hd[gl2loc[int(g)]] += d2 * float(v) ** 2
            np.testing.assert_allclose(
                variances[e, :D_e], 1.0 / (hd + _VARIANCE_EPSILON), rtol=2e-4
            )

    def test_scores_cover_all_rows(self, rng):
        recs, _, _ = make_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard",
                                              active_data_upper_bound=8)
        )
        bank = jnp.ones((red.num_entities, red.local_dim), jnp.float32)
        s = np.asarray(score_random_effect(bank, red))
        # passive rows (beyond cap) must be scored too
        assert np.count_nonzero(s[:200]) > 150


@pytest.mark.slow
class TestCoordinateDescent:
    def _setup(self, rng, task=TaskType.LOGISTIC_REGRESSION):
        recs, _, _ = make_records(rng, n=300, n_users=8)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        fe = FixedEffectCoordinate(
            name="global",
            dataset=ds,
            problem=create_glm_problem(
                task, ds.shards["globalShard"].dim,
                config=OptimizerConfig(max_iter=30),
                regularization=RegularizationContext(RegularizationType.L2),
            ),
            feature_shard_id="globalShard",
            reg_weight=0.1,
        )
        re = RandomEffectCoordinate(
            name="per-user",
            dataset=ds,
            re_dataset=red,
            problem=RandomEffectOptimizationProblem(
                LOGISTIC if task == TaskType.LOGISTIC_REGRESSION else LINEAR,
                OptimizerConfig(max_iter=30),
                RegularizationContext(RegularizationType.L2),
                reg_weight=1.0,
            ),
        )
        return ds, {"global": fe, "per-user": re}

    def test_objective_decreases(self, rng):
        ds, coords = self._setup(rng)
        cd = CoordinateDescent(coords, ds, TaskType.LOGISTIC_REGRESSION)
        result = cd.run(num_iterations=3)
        obj = result.objective_history
        assert len(obj) == 3
        assert obj[-1] <= obj[0] + 1e-6, obj
        # mixed model beats fixed-effect-only on training loss
        cd_fe = CoordinateDescent(
            {"global": coords["global"]}, ds, TaskType.LOGISTIC_REGRESSION
        )
        fe_only = cd_fe.run(num_iterations=1)
        assert obj[-1] < fe_only.objective_history[-1]

    def test_warm_start_model(self, rng):
        ds, coords = self._setup(rng)
        cd = CoordinateDescent(coords, ds, TaskType.LOGISTIC_REGRESSION)
        r1 = cd.run(num_iterations=2)
        r2 = cd.run(num_iterations=1, initial_model=r1.model)
        assert r2.objective_history[-1] <= r1.objective_history[0] + 1e-6

    def test_update_sequence_validation(self, rng):
        ds, coords = self._setup(rng)
        with pytest.raises(ValueError, match="unknown"):
            CoordinateDescent(
                coords, ds, TaskType.LOGISTIC_REGRESSION,
                update_sequence=["global", "nope"],
            )


class TestFactoredRandomEffect:
    def test_trains_and_scores(self, rng):
        recs, _, _ = make_records(rng, n=200, n_users=6)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfiguration(
                "userId", "userShard", projector_type=ProjectorType.IDENTITY
            ),
        )
        fre = FactoredRandomEffectCoordinate(
            name="factored",
            dataset=ds,
            re_dataset=red,
            problem=RandomEffectOptimizationProblem(
                LOGISTIC, OptimizerConfig(max_iter=15),
                RegularizationContext(RegularizationType.L2), reg_weight=1.0,
            ),
            projection_problem=create_glm_problem(
                TaskType.LOGISTIC_REGRESSION,
                red.local_dim * 2,
                config=OptimizerConfig(max_iter=15),
                regularization=RegularizationContext(RegularizationType.L2),
            ),
            config=FactoredRandomEffectConfiguration(
                latent_space_dimension=2, num_inner_iterations=1
            ),
            reg_weight_projection=0.1,
        )
        model = fre.initialize_model()
        assert model.projection.shape == (red.local_dim, 2)
        new_model, _ = fre.update_model(model, None)
        s = fre.score(new_model)
        assert np.all(np.isfinite(np.asarray(s)))
        # training reduced the loss vs initial (zero bank scores 0)
        loss0 = float(jnp.sum(jnp.asarray(ds.weights) * LOGISTIC.value(
            jnp.zeros(ds.num_rows), jnp.asarray(ds.labels))))
        loss1 = float(jnp.sum(jnp.asarray(ds.weights) * LOGISTIC.value(
            s + jnp.asarray(ds.offsets), jnp.asarray(ds.labels))))
        assert loss1 < loss0


class TestMatrixFactorization:
    def test_als_reduces_loss(self, rng):
        # rating ~ user . item latent
        n_users, n_items, K = 12, 15, 3
        U = rng.normal(size=(n_users, K))
        V = rng.normal(size=(n_items, K))
        recs = []
        for i in range(400):
            u = int(rng.integers(0, n_users))
            it = int(rng.integers(0, n_items))
            recs.append({
                "response": float(U[u] @ V[it] + 0.1 * rng.normal()),
                "userId": f"u{u}",
                "itemId": f"i{it}",
                "features": [],
            })
        ds = build_game_dataset(
            recs, [FeatureShardConfiguration("g", ["features"])],
            ["userId", "itemId"],
        )
        mf = MatrixFactorizationCoordinate(
            name="mf",
            dataset=ds,
            row_effect_type="userId",
            col_effect_type="itemId",
            num_latent_factors=K,
            problem=RandomEffectOptimizationProblem(
                LINEAR, OptimizerConfig(max_iter=20),
                RegularizationContext(RegularizationType.L2), reg_weight=0.1,
            ),
            num_inner_iterations=3,
        )
        model = mf.initialize_model()
        lab = jnp.asarray(ds.labels); w = jnp.asarray(ds.weights)
        def mse(m):
            s = mf.score(m)
            return float(jnp.sum(w * (s - lab) ** 2) / jnp.sum(w))
        before = mse(model)
        model, _ = mf.update_model(model, None)
        after = mse(model)
        assert after < before * 0.5, (before, after)

    def test_identity_solvers_match_densify(self, rng):
        """The *_id solver variants (X = values, no densify broadcast —
        the MF latent-view fast path) must produce the same solves as
        the general densify path on identity-index data."""
        from photon_ml_tpu.game.random_effect import _bucket_solver
        from photon_ml_tpu.ops.losses import LOGISTIC as _LOG

        E, S, k = 50, 8, 4
        solvers = _bucket_solver(
            _LOG, OptimizerConfig(max_iter=50),
            RegularizationContext(RegularizationType.L2),
        )
        ix = np.tile(np.arange(k, dtype=np.int32)[None, None, :], (E, S, 1))
        v = rng.normal(size=(E, S, k)).astype(np.float32)
        lab = (rng.uniform(size=(E, S)) > 0.5).astype(np.float32)
        w = np.ones((E, S), np.float32)
        off = np.zeros((E, S), np.float32)
        bank = jnp.zeros((E, k), jnp.float32)
        args = (
            jnp.asarray(ix), jnp.asarray(v), jnp.asarray(lab),
            jnp.asarray(off), jnp.asarray(w),
            jnp.float32(0.0), jnp.float32(0.5),
        )
        for base, ident in (("dense", "dense_id"), ("newton", "newton_id")):
            out_b, _, _ = getattr(solvers, base)(bank, *args)
            out_i, _, _ = getattr(solvers, ident)(bank, *args)
            np.testing.assert_allclose(
                np.asarray(out_i), np.asarray(out_b), atol=1e-5,
                err_msg=base,
            )

    def test_cap_class_merge_bounds_padding(self, rng):
        """The MF bucket cap-class merge (fewer distinct solver programs)
        must never pad an entity's sample capacity more than 4x — a
        heavy-tailed count distribution where no class holds 25% of
        entities must not collapse everything onto the largest class."""
        # entity i gets ~2^(i mod 10) ratings: every cap class ~10%
        counts = [2 ** (i % 10) for i in range(40)]
        rows = np.repeat(np.arange(40, dtype=np.int32), counts)
        n = len(rows)
        cols = rng.integers(0, 5, size=n).astype(np.int32)
        recs = [
            {
                "uid": f"r{i}",
                "response": float(rng.normal()),
                "userId": f"u{rows[i]}",
                "itemId": f"i{cols[i]}",
                "features": [],
            }
            for i in range(n)
        ]
        ds = build_game_dataset(
            recs, [FeatureShardConfiguration("g", ["features"])],
            ["userId", "itemId"],
        )
        mf = MatrixFactorizationCoordinate(
            name="mf", dataset=ds, row_effect_type="userId",
            col_effect_type="itemId", num_latent_factors=2,
            problem=RandomEffectOptimizationProblem(
                LINEAR, OptimizerConfig(max_iter=5),
                RegularizationContext(RegularizationType.L2), reg_weight=1.0,
            ),
        )
        row_codes = ds.entity_codes["userId"]
        col_codes = ds.entity_codes["itemId"]
        view, _ = mf._side_structure("row", row_codes, col_codes, 40)
        per_entity = np.bincount(
            row_codes[(ds.weights > 0) & (row_codes >= 0)], minlength=40
        )
        for b in view.buckets:
            assert b.identity_indices
            S = b.row_index.shape[1]
            for e, code in enumerate(b.entity_codes):
                c = per_entity[code]
                cap = 1 << int(np.ceil(np.log2(max(c, 1))))
                assert S <= 4 * cap, (int(code), c, cap, S)
        # every entity appears in exactly one bucket
        all_codes = np.concatenate([b.entity_codes for b in view.buckets])
        assert sorted(all_codes.tolist()) == list(range(40))


@pytest.mark.slow
class TestMediumScaleGame:
    """Stress above toy size: 30k rows, 2k entities, full CD with residual
    passing — exercises bucketing, the dense/Newton auto-layout, device
    caching, and the fused bank updates at a size where a quadratic or
    per-entity-dispatch design would visibly blow up."""

    def test_coordinate_descent_30k_rows(self, rng):
        import time

        n, n_users = 30_000, 2_000
        recs, _, _ = make_records(rng, n=n, n_users=n_users,
                                  d_global=20, d_user=8)
        t0 = time.perf_counter()
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        build_s = time.perf_counter() - t0
        assert ds.num_real_rows == n
        assert red.num_entities == n_users

        coords = {
            "global": FixedEffectCoordinate(
                name="global", dataset=ds,
                problem=create_glm_problem(
                    TaskType.LOGISTIC_REGRESSION,
                    ds.shards["globalShard"].dim,
                    config=OptimizerConfig(max_iter=20),
                    regularization=RegularizationContext(
                        RegularizationType.L2
                    ),
                ),
                feature_shard_id="globalShard", reg_weight=0.1,
            ),
            "per-user": RandomEffectCoordinate(
                name="per-user", dataset=ds, re_dataset=red,
                problem=RandomEffectOptimizationProblem(
                    LOGISTIC, OptimizerConfig(max_iter=20),
                    RegularizationContext(RegularizationType.L2),
                    reg_weight=1.0,
                ),
            ),
        }
        t0 = time.perf_counter()
        res = CoordinateDescent(
            coords, ds, TaskType.LOGISTIC_REGRESSION,
            update_sequence=["global", "per-user"],
        ).run(2)
        cd_s = time.perf_counter() - t0
        # objective decreases monotonically across CD iterations
        hist = res.objective_history
        assert len(hist) == 2 and hist[1] <= hist[0]
        # per-entity solves actually converge at this scale
        tracker = res.trackers["per-user"][-1]
        assert tracker.num_entities == n_users
        assert (
            tracker.reason_counts.get("MaxIterations", 0) < n_users * 0.02
        )
        # design sanity: the whole thing stays minutes-free on 1 CPU device
        assert build_s < 120 and cd_s < 300, (build_s, cd_s)




_BUILD_TIMING_SCRIPT = """
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from photon_ml_tpu.game import (
    RandomEffectDataConfiguration, build_random_effect_dataset,
)
from photon_ml_tpu.game.data import EntityIndex, GameDataset, ShardData
from photon_ml_tpu.utils.index_map import IndexMap

rng = np.random.default_rng(42)
n, E, d, k = {n}, {E}, {d}, {k}
imap = IndexMap({{f"f{{i}}": i for i in range(d)}})
ds = GameDataset(
    uids=[str(i) for i in range(n)],
    labels=(rng.uniform(size=n) > 0.5).astype(np.float32),
    offsets=np.zeros(n, np.float32),
    weights=np.ones(n, np.float32),
    shards={{"userShard": ShardData(
        indices=rng.integers(0, d, size=(n, k)).astype(np.int32),
        values=rng.normal(size=(n, k)).astype(np.float32),
        index_map=imap, intercept_index=None)}},
    entity_codes={{"userId": rng.integers(0, E, size=n).astype(np.int32)}},
    entity_indexes={{"userId": EntityIndex(
        "userId", [f"u{{i}}" for i in range(E)], {{}})}},
    num_real_rows=n,
)
t0 = time.thread_time()
red = build_random_effect_dataset(
    ds, RandomEffectDataConfiguration(
        "userId", "userShard", active_data_upper_bound={cap}))
build_s = time.thread_time() - t0
caps_cover = all(
    int((b.row_index >= 0).sum(axis=1).max()) <= b.capacity
    and int((b.row_index >= 0).sum(axis=1).min()) >= 1
    for b in red.buckets
)
print(json.dumps({{
    "build_s": build_s,
    "num_entities": red.num_entities,
    "num_active_rows": red.num_active_rows,
    "num_passive_rows": red.num_passive_rows,
    "placed": sum(int((b.row_index >= 0).sum()) for b in red.buckets),
    "caps_cover": caps_cover,
    "total_weight_mass": sum(float(b.weights.sum()) for b in red.buckets),
}}))
"""


def _hermetic_build(n, E, d, k, cap=None):
    """Build the 1M-row RE dataset (and time it) in a FRESH interpreter:
    in the parent, the full suite's accumulated heap makes direct-reclaim
    page faults bill to the building thread's CPU time, flaking any
    in-process bound on a small box. The subprocess returns BOTH the
    hermetic thread-CPU build time and the correctness summaries, so the
    parent never constructs the 1M-row dataset at all."""
    import json as _json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = _BUILD_TIMING_SCRIPT.format(
        repo=repo, n=n, E=E, d=d, k=k, cap=cap if cap is not None else "None"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return _json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.mark.slow
class TestLargeScaleREBuild:
    """1M rows x 8 nnz with 100k entities through the REAL vectorized
    path (argsort + bincount + flat scatter, no per-row or per-entity
    Python loops), built and timed hermetically in a subprocess."""

    def test_million_row_build(self):
        r = _hermetic_build(n=1_000_000, E=100_000, d=50_000, k=8)
        assert r["num_entities"] == 100_000
        assert r["num_active_rows"] == 1_000_000
        # each bucket's capacity covers its members; every active row
        # landed in exactly one bucket slot
        assert r["caps_cover"]
        assert r["placed"] == 1_000_000
        # regression guard: a reintroduced per-row loop costs 17-77 s at
        # this scale (round 2); the fresh interpreter makes the bound
        # immune to suite-level memory pressure and host load
        assert r["build_s"] < 15.0, r["build_s"]

    def test_million_row_build_with_cap(self):
        r = _hermetic_build(n=1_000_000, E=100_000, d=30_000, k=8, cap=8)
        assert r["num_active_rows"] + r["num_passive_rows"] == 1_000_000
        # reservoir weight mass preserved per entity (sum over buckets)
        assert abs(r["total_weight_mass"] - 1_000_000) < 1e-3 * 1_000_000
        assert r["build_s"] < 15.0, r["build_s"]


@pytest.mark.slow
class TestDeviceResidentResiduals:
    """VERDICT r2 item 6: at steady state the coordinate-descent loop does
    no implicit device->host transfer — residuals, offsets, and scores
    stay jnp end-to-end (SURVEY §7.9 device-resident KeyValueScore); the
    tracker/objective readbacks are single EXPLICIT device_get calls."""

    def test_steady_state_no_implicit_d2h(self, rng):
        recs, _, _ = make_records(rng, n=200, n_users=6)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        coords = {
            "global": FixedEffectCoordinate(
                name="global",
                dataset=ds,
                problem=create_glm_problem(
                    TaskType.LOGISTIC_REGRESSION,
                    ds.shards["globalShard"].dim,
                    config=OptimizerConfig(max_iter=5),
                    regularization=RegularizationContext(
                        RegularizationType.L2
                    ),
                ),
                feature_shard_id="globalShard",
                reg_weight=0.1,
            ),
            "per-user": RandomEffectCoordinate(
                name="per-user",
                dataset=ds,
                re_dataset=red,
                problem=RandomEffectOptimizationProblem(
                    LOGISTIC,
                    OptimizerConfig(max_iter=5),
                    RegularizationContext(RegularizationType.L2),
                    reg_weight=1.0,
                ),
            ),
        }

        def make_cd():
            return CoordinateDescent(
                coords, ds, TaskType.LOGISTIC_REGRESSION,
                update_sequence=["global", "per-user"],
            )

        # iteration 1 warms every device cache (feature tables, row views)
        warm = make_cd().run(1)
        # steady state: the same coordinates must run with implicit
        # device->host transfers disallowed (explicit device_get is fine)
        with jax.transfer_guard_device_to_host("disallow"):
            res = make_cd().run(1)
        assert np.isfinite(res.objective_history[-1])


@pytest.mark.slow
class TestFilePathScale:
    """VERDICT r2 item 3, 'through the REAL path': Avro files -> native
    column decode -> vectorized GAME dataset assembly -> vectorized RE
    build, at a volume where any per-record Python loop in the chain
    would visibly blow up."""

    def test_200k_rows_from_avro_files(self, tmp_path, rng):
        import time

        from photon_ml_tpu.game.data import build_game_dataset_from_files
        from photon_ml_tpu.io import native_avro
        from photon_ml_tpu.io.avro_codec import write_container

        from conftest import game_example_schema

        if not native_avro.available():
            pytest.skip(
                "native avro decoder unavailable: the point of this test "
                "is the REAL (native-decode) load path"
            )
        n, n_users, d_g, d_u = 200_000, 20_000, 6, 4
        rows_per_file = 50_000
        schema = game_example_schema()
        u_codes = rng.integers(0, n_users, size=n)
        n_users_seen = len(np.unique(u_codes))
        for fi in range(n // rows_per_file):
            recs = []
            base = fi * rows_per_file
            for i in range(rows_per_file):
                u = int(u_codes[base + i])
                recs.append({
                    "uid": f"r{base + i}",
                    "response": float(rng.uniform() > 0.5),
                    "metadataMap": {"userId": f"user{u}"},
                    "features": [
                        {"name": f"g{j}", "term": "",
                         "value": float(rng.normal())}
                        for j in range(d_g)
                    ],
                    "userFeatures": [
                        {"name": f"u{j}", "term": "",
                         "value": float(rng.normal())}
                        for j in range(d_u)
                    ],
                })
            write_container(
                str(tmp_path / f"part-{fi}.avro"), schema, recs
            )
            del recs

        t0 = time.perf_counter()
        ds = build_game_dataset_from_files(
            [str(tmp_path)], SHARDS, ["userId"]
        )
        load_s = time.perf_counter() - t0
        assert ds.num_real_rows == n
        assert ds.entity_indexes["userId"].num_entities == n_users_seen

        t0 = time.perf_counter()
        red = build_random_effect_dataset(
            ds, RandomEffectDataConfiguration("userId", "userShard")
        )
        re_s = time.perf_counter() - t0
        assert red.num_entities == n_users_seen
        assert red.num_active_rows == n
        placed = sum(int((b.row_index >= 0).sum()) for b in red.buckets)
        assert placed == n
        # the whole chain is vectorized/native: generous 1-core CI bounds
        # that still catch any reintroduced per-record hot loop
        assert load_s < 120, load_s
        assert re_s < 10, re_s


class TestBucketScanFold:
    """Same-shape bucket groups fold into ONE lax.scan dispatch
    (round 5, PERF_NOTES RE-bank ceiling): the folded update must equal
    the per-bucket path exactly."""

    def _data(self, rng, n_buckets=4, E=64, S=8, K=6, D=32):
        from types import SimpleNamespace

        from photon_ml_tpu.game.random_effect_data import RandomEffectBucket

        buckets = []
        for b in range(n_buckets):
            idx = rng.integers(0, D, size=(E, S, K)).astype(np.int32)
            val = rng.normal(size=(E, S, K)).astype(np.float32)
            z = (val * 0.3).sum(axis=2)
            lab = (rng.uniform(size=(E, S)) < 1 / (1 + np.exp(-z))).astype(
                np.float32
            )
            buckets.append(RandomEffectBucket(
                entity_codes=np.arange(b * E, (b + 1) * E, dtype=np.int32),
                row_index=np.full((E, S), -1, np.int32),
                indices=idx, values=val, labels=lab,
                offsets=np.zeros((E, S), np.float32),
                weights=np.ones((E, S), np.float32),
            ))
        return SimpleNamespace(buckets=buckets), n_buckets * E, D

    def test_fold_matches_per_bucket(self, rng):
        import jax.numpy as jnp

        from photon_ml_tpu.game.random_effect import (
            RandomEffectOptimizationProblem,
        )
        from photon_ml_tpu.ops.losses import LOGISTIC
        from photon_ml_tpu.optim.config import (
            OptimizerConfig,
            RegularizationContext,
            RegularizationType,
        )

        data, n_e, D = self._data(rng)

        def run(with_variances):
            problem = RandomEffectOptimizationProblem(
                loss=LOGISTIC,
                config=OptimizerConfig(max_iter=20, tolerance=1e-6),
                regularization=RegularizationContext(RegularizationType.L2),
                reg_weight=1.0,
            )
            bank = jnp.zeros((n_e, D), jnp.float32)
            if with_variances:
                # variances disable the fold -> per-bucket oracle path
                bank, tracker, _ = problem.update_bank(
                    bank, data, with_variances=True
                )
            else:
                bank, tracker = problem.update_bank(bank, data)
            return np.asarray(bank), tracker

        bank_fold, tr_fold = run(False)
        bank_oracle, tr_oracle = run(True)
        np.testing.assert_allclose(bank_fold, bank_oracle, atol=1e-5)
        assert tr_fold.num_entities == tr_oracle.num_entities
        # differently-compiled XLA programs may flip a convergence check
        # by a rounding ulp; compare the stat with slack, not ==
        assert tr_fold.iterations_mean == pytest.approx(
            tr_oracle.iterations_mean, abs=0.1
        )

    def test_fold_with_residual_offsets(self, rng):
        import jax.numpy as jnp

        from photon_ml_tpu.game.random_effect import (
            RandomEffectOptimizationProblem,
        )
        from photon_ml_tpu.ops.losses import LOGISTIC
        from photon_ml_tpu.optim.config import (
            OptimizerConfig,
            RegularizationContext,
            RegularizationType,
        )
        from photon_ml_tpu.game.random_effect_data import RandomEffectBucket
        from types import SimpleNamespace

        # row_index >= 0 so residual offsets route through the fold's
        # stacked gather: rebuild the buckets with real row indices
        data, n_e, D = self._data(rng, n_buckets=3, E=32, S=4)
        n_rows = 512
        buckets = []
        for b in data.buckets:
            buckets.append(RandomEffectBucket(
                entity_codes=b.entity_codes,
                row_index=rng.integers(
                    0, n_rows, size=b.labels.shape
                ).astype(np.int32),
                indices=b.indices, values=b.values, labels=b.labels,
                offsets=b.offsets, weights=b.weights,
            ))
        data = SimpleNamespace(buckets=buckets)
        residual = jnp.asarray(
            rng.normal(size=n_rows).astype(np.float32) * 0.1
        )
        problem = RandomEffectOptimizationProblem(
            loss=LOGISTIC,
            config=OptimizerConfig(max_iter=15, tolerance=1e-6),
            regularization=RegularizationContext(RegularizationType.L2),
            reg_weight=1.0,
        )
        bank = jnp.zeros((3 * 32, D), jnp.float32)
        bank_fold, _ = problem.update_bank(
            bank, data, residual_offsets=residual
        )
        # oracle: per-bucket path (variances disable the fold)
        problem2 = RandomEffectOptimizationProblem(
            loss=LOGISTIC,
            config=OptimizerConfig(max_iter=15, tolerance=1e-6),
            regularization=RegularizationContext(RegularizationType.L2),
            reg_weight=1.0,
        )
        bank_oracle, _, _ = problem2.update_bank(
            jnp.zeros((3 * 32, D), jnp.float32), data,
            residual_offsets=residual, with_variances=True,
        )
        np.testing.assert_allclose(
            np.asarray(bank_fold), np.asarray(bank_oracle), atol=1e-5
        )
