"""End-to-end GLM driver tests (DriverIntegTest analog): full pipeline runs
on Avro + LibSVM fixtures, asserting stage history, outputs and failure
modes; interop test against the reference's Java-written heart.avro.
"""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu.cli.glm_driver import (
    DriverStage,
    GLMDriver,
    GLMParams,
    params_from_args,
)
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import read_container, write_container
from photon_ml_tpu.io.model_io import load_glm_models_avro
from photon_ml_tpu.optim import OptimizerType, RegularizationType
from photon_ml_tpu.ops.normalization import NormalizationType
from photon_ml_tpu.task import TaskType

# Driver end-to-end runs (full stage pipelines, file IO,
# multi-lambda fits): integration tier
pytestmark = pytest.mark.slow

REF_INPUT = "/root/reference/photon-ml/src/integTest/resources/DriverIntegTest/input"


def synth_avro(path, rng, n=200, d=8, seed_offset=0):
    w = np.linspace(-1, 1, d)
    recs = []
    for i in range(n):
        ix = rng.choice(d, size=4, replace=False)
        vs = rng.normal(size=4)
        z = float(np.sum(w[ix] * vs))
        label = float(1 / (1 + np.exp(-z)) > rng.uniform())
        recs.append({
            "uid": f"u{i}",
            "label": label,
            "features": [
                {"name": f"f{j}", "term": "", "value": float(v)}
                for j, v in zip(ix, vs)
            ],
            "metadataMap": None,
            "weight": None,
            "offset": None,
        })
    write_container(path, schemas.TRAINING_EXAMPLE_AVRO, recs)


@pytest.fixture
def avro_dirs(tmp_path, rng):
    train = tmp_path / "train"
    val = tmp_path / "val"
    train.mkdir(); val.mkdir()
    synth_avro(str(train / "part-0.avro"), rng, n=300)
    synth_avro(str(val / "part-0.avro"), rng, n=100)
    return str(train), str(val)


class TestGLMDriverEndToEnd:
    def test_full_pipeline_avro(self, tmp_path, avro_dirs):
        train, val = avro_dirs
        out = str(tmp_path / "out")
        params = GLMParams(
            train_dir=train,
            validate_dir=val,
            output_dir=out,
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[0.1, 1.0, 10.0],
            regularization_type=RegularizationType.L2,
            compute_variances=True,
            summarization_output_dir=str(tmp_path / "summary"),
        )
        driver = GLMDriver(params)
        driver.run()
        assert driver.stage_history == [
            DriverStage.PREPROCESSED, DriverStage.TRAINED, DriverStage.VALIDATED,
        ]
        assert set(driver.models) == {0.1, 1.0, 10.0}
        assert driver.best_model is not None
        # AUC on validation should beat random for all lambdas
        for lam, metrics in driver.validation_metrics.items():
            assert metrics["AUC"] > 0.6, (lam, metrics)
        # outputs on disk
        assert os.path.isfile(os.path.join(out, "models", "models.avro"))
        assert os.path.isfile(os.path.join(out, "best-model", "model.avro"))
        assert os.path.isfile(os.path.join(out, "metrics.json"))
        assert len(os.listdir(os.path.join(out, "models-text"))) == 3
        # model avro roundtrip with variances
        from photon_ml_tpu.utils.index_map import IndexMap
        imap = IndexMap.load(os.path.join(out, "feature-index", "index.json"))
        loaded = load_glm_models_avro(
            os.path.join(out, "models", "models.avro"), imap
        )
        assert set(loaded) == {"0.1", "1.0", "10.0"}
        m = loaded["0.1"]
        assert m.task == TaskType.LOGISTIC_REGRESSION
        np.testing.assert_allclose(
            np.asarray(m.means), np.asarray(driver.models[0.1].means), atol=1e-6
        )
        assert m.coefficients.variances is not None
        # summarization written
        schema, it = read_container(
            str(tmp_path / "summary" / "part-00000.avro")
        )
        summary = list(it)
        assert len(summary) == 9  # 8 features + intercept
        # metrics.json sane
        metrics = json.load(open(os.path.join(out, "metrics.json")))
        assert metrics["best_lambda"] is not None

    def test_grid_mode_batched_matches_sequential(self, tmp_path, avro_dirs):
        """--grid-mode batched: the whole λ grid trains as ONE vmapped
        program and the driver pipeline (validation, best-model
        selection, outputs) lands on the same answers as the sequential
        sweep within the fp32 envelope."""
        train, val = avro_dirs
        drivers = {}
        for mode in ("batched", "sequential"):
            params = GLMParams(
                train_dir=train,
                validate_dir=val,
                output_dir=str(tmp_path / f"out_{mode}"),
                task=TaskType.LOGISTIC_REGRESSION,
                regularization_weights=[0.1, 1.0, 10.0],
                regularization_type=RegularizationType.L2,
                grid_mode=mode,
            )
            drivers[mode] = GLMDriver(params)
            drivers[mode].run()
        b, s = drivers["batched"], drivers["sequential"]
        assert b.best_lambda == s.best_lambda
        for lam in (0.1, 1.0, 10.0):
            assert float(b.results[lam].value) == pytest.approx(
                float(s.results[lam].value), rel=2e-3
            )
            np.testing.assert_allclose(
                np.asarray(b.models[lam].means),
                np.asarray(s.models[lam].means), atol=5e-3,
            )
        assert os.path.isfile(
            os.path.join(str(tmp_path / "out_batched"), "metrics.json")
        )

    def test_grid_mode_auto_budget_falls_back(self, tmp_path, avro_dirs):
        """--grid-mode auto with a budget too small for the G×d bank must
        fall back to the warm-started sequential path and still complete
        the pipeline."""
        train, val = avro_dirs
        params = GLMParams(
            train_dir=train,
            validate_dir=val,
            output_dir=str(tmp_path / "out_auto"),
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[0.1, 1.0, 10.0],
            regularization_type=RegularizationType.L2,
            grid_mode="auto",
            grid_memory_budget=1,  # nothing fits: sequential fallback
        )
        driver = GLMDriver(params)
        driver.run()
        assert set(driver.models) == {0.1, 1.0, 10.0}
        assert driver.best_model is not None

    def test_grid_mode_batched_rejected_with_streaming(self, tmp_path,
                                                       avro_dirs):
        train, val = avro_dirs
        with pytest.raises(ValueError, match="incompatible with"):
            GLMParams(
                train_dir=train,
                output_dir=str(tmp_path / "out"),
                streaming=True,
                grid_mode="batched",
            ).validate()

    def test_output_dir_guard(self, tmp_path, avro_dirs):
        train, _ = avro_dirs
        out = tmp_path / "out"
        out.mkdir()
        (out / "junk.txt").write_text("x")
        params = GLMParams(
            train_dir=train, output_dir=str(out),
            regularization_weights=[1.0],
        )
        with pytest.raises(ValueError, match="exists"):
            GLMDriver(params).run()
        params.delete_output_dirs_if_exist = True
        GLMDriver(params).run()  # now succeeds

    def test_libsvm_pipeline_with_normalization(self, tmp_path, rng):
        # a1a-style libsvm input
        train = tmp_path / "a1a.txt"
        lines = []
        d = 20
        w = np.linspace(-2, 2, d)
        for _ in range(300):
            ix = np.sort(rng.choice(d, size=5, replace=False))
            z = float(np.sum(w[ix]))
            y = 1 if 1 / (1 + np.exp(-z)) > rng.uniform() else -1
            lines.append(
                f"{y:+d} " + " ".join(f"{i+1}:1" for i in ix)
            )
        train.write_text("\n".join(lines) + "\n")
        out = str(tmp_path / "out")
        params = GLMParams(
            train_dir=str(train), output_dir=out,
            input_format="LIBSVM",
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[0.5],
            normalization_type=NormalizationType.STANDARDIZATION,
        )
        driver = GLMDriver(params)
        driver.run()
        assert 0.5 in driver.models

    def test_cli_arg_parsing(self):
        params = params_from_args([
            "--training-data-directory", "/tmp/train",
            "--output-directory", "/tmp/out",
            "--task", "poisson_regression",
            "--format", "LIBSVM",
            "--regularization-weights", "0.1,1,10",
            "--regularization-type", "ELASTIC_NET",
            "--elastic-net-alpha", "0.5",
            "--optimizer", "LBFGS",
            "--num-iterations", "50",
            "--intercept", "false",
            "--normalization-type", "STANDARDIZATION",
        ])
        assert params.task == TaskType.POISSON_REGRESSION
        assert params.regularization_weights == [0.1, 1.0, 10.0]
        assert params.elastic_net_alpha == 0.5
        assert not params.add_intercept
        params.validate()

    def test_params_validation(self):
        p = GLMParams(train_dir="t", output_dir="o",
                      optimizer_type=OptimizerType.TRON,
                      regularization_type=RegularizationType.L1)
        with pytest.raises(ValueError, match="not allowed"):
            p.validate()

    def test_date_range_params_validation(self):
        p = GLMParams(train_dir="t", output_dir="o",
                      train_date_range="20160101-20160102",
                      train_date_range_days_ago="9-1")
        with pytest.raises(ValueError, match="at most one"):
            p.validate()
        p = GLMParams(train_dir="t", output_dir="o",
                      validate_per_iteration=True)
        with pytest.raises(ValueError, match="requires a validating"):
            p.validate()


class TestFeatureShardedDriver:
    def test_feature_sharded_mode_end_to_end(self, tmp_path, avro_dirs):
        """--distributed feature trains over a (data, model) mesh and
        matches the single-device model (the >HBM coefficient path made
        driver-reachable)."""
        train, val = avro_dirs
        results = {}
        for mode, out in (("feature", "out_fs"), ("off", "out_single")):
            params = GLMParams(
                train_dir=train,
                validate_dir=val,
                output_dir=str(tmp_path / out),
                task=TaskType.LOGISTIC_REGRESSION,
                regularization_weights=[0.1, 1.0],
                distributed=mode,
                model_shards=2,
            )
            driver = GLMDriver(params)
            driver.run()
            results[mode] = driver
        for lam in (0.1, 1.0):
            np.testing.assert_allclose(
                np.asarray(results["feature"].models[lam].means),
                np.asarray(results["off"].models[lam].means),
                atol=5e-3,
            )
        assert results["feature"].best_model is not None

    def test_feature_mode_composes_all_params(self):
        # Round 5 closed the feature-sharded combination guards: the
        # reference composes normalization, variances and box constraints
        # freely with distribution (NormalizationContext.scala:119-157,
        # DistributedOptimizationProblem.scala:79-93, LBFGS.scala:77) —
        # these now VALIDATE cleanly instead of raising.
        for kw in (
            dict(normalization_type=NormalizationType.STANDARDIZATION),
            dict(compute_variances=True),
            dict(constraint_string="[]"),
            dict(validate_per_iteration=True, validate_dir="v"),
        ):
            GLMParams(
                train_dir="t", output_dir="o", distributed="feature", **kw
            ).validate()
        # TRON + feature sharding validates cleanly, with either kernel
        # (tiled Hv schedules landed round 4)
        for kernel in ("auto", "tiled", "scatter"):
            GLMParams(
                train_dir="t", output_dir="o", distributed="feature",
                optimizer_type=OptimizerType.TRON, kernel=kernel,
            ).validate()

    def test_feature_sharded_norm_variances_validate_per_iter(
        self, tmp_path, avro_dirs
    ):
        """The previously-guarded combinations, driver end-to-end in
        --distributed feature mode: standardization + variances +
        validate-per-iteration must reproduce the single-device run."""
        train, val = avro_dirs
        results = {}
        for mode, out in (("feature", "out_fsn"), ("off", "out_sn")):
            params = GLMParams(
                train_dir=train,
                validate_dir=val,
                output_dir=str(tmp_path / out),
                task=TaskType.LOGISTIC_REGRESSION,
                regularization_weights=[1.0],
                normalization_type=NormalizationType.STANDARDIZATION,
                compute_variances=True,
                validate_per_iteration=True,
                distributed=mode,
                model_shards=2,
            )
            driver = GLMDriver(params)
            driver.run()
            results[mode] = driver
        np.testing.assert_allclose(
            np.asarray(results["feature"].models[1.0].means),
            np.asarray(results["off"].models[1.0].means),
            atol=5e-3,
        )
        np.testing.assert_allclose(
            np.asarray(results["feature"].models[1.0].coefficients.variances),
            np.asarray(results["off"].models[1.0].coefficients.variances),
            rtol=5e-3,
        )
        per_iter = results["feature"].per_iteration_metrics[1.0]
        assert len(per_iter) > 1
        # per-iteration metrics track the single-device run
        ref_iter = results["off"].per_iteration_metrics[1.0]
        assert abs(per_iter[-1]["AUC"] - ref_iter[-1]["AUC"]) < 1e-3

    def test_feature_sharded_tron_tiled_end_to_end(self, tmp_path, avro_dirs):
        """--distributed feature --optimizer TRON --kernel tiled: the
        hottest distributed loop (Hv per CG step) on the Pallas kernels,
        driver-reachable; matches the single-device TRON model."""
        train, val = avro_dirs
        results = {}
        for mode, kernel, out in (
            ("feature", "tiled", "out_fs_tron"),
            ("off", "auto", "out_single_tron"),
        ):
            params = GLMParams(
                train_dir=train,
                validate_dir=val,
                output_dir=str(tmp_path / out),
                task=TaskType.LOGISTIC_REGRESSION,
                regularization_weights=[1.0],
                optimizer_type=OptimizerType.TRON,
                distributed=mode,
                model_shards=2,
                kernel=kernel,
            )
            driver = GLMDriver(params)
            driver.run()
            results[mode] = driver
        np.testing.assert_allclose(
            np.asarray(results["feature"].models[1.0].means),
            np.asarray(results["off"].models[1.0].means),
            atol=5e-3,
        )


class TestProfilerHook:
    def test_profile_dir_writes_trace(self, tmp_path, avro_dirs):
        """--profile-dir captures a jax.profiler trace of the train stage
        (SURVEY §7.11): a TensorBoard-loadable .xplane.pb appears."""
        train, _ = avro_dirs
        prof = tmp_path / "profile"
        params = GLMParams(
            train_dir=train,
            output_dir=str(tmp_path / "out"),
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[1.0],
            profile_dir=str(prof),
        )
        GLMDriver(params).run()
        traces = list(prof.rglob("*.xplane.pb"))
        assert traces, f"no trace files under {prof}"


class TestDatedInputAndPerIterationValidation:
    def _make_daily(self, base, rng, days, n=120):
        import datetime

        from photon_ml_tpu.utils.date_range import daily_path

        for d in days:
            p = daily_path(str(base), datetime.date(2016, 1, d))
            os.makedirs(p)
            synth_avro(os.path.join(p, "part-0.avro"), rng, n=n)

    def test_dated_train_and_validate(self, tmp_path, rng):
        train = tmp_path / "train"
        val = tmp_path / "val"
        self._make_daily(train, rng, (1, 2, 3))
        self._make_daily(val, rng, (4,), n=80)
        out = str(tmp_path / "out")
        params = GLMParams(
            train_dir=str(train),
            validate_dir=str(val),
            output_dir=out,
            train_date_range="20160101-20160102",  # excludes day 3
            validate_date_range="20160104-20160104",
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[1.0],
        )
        driver = GLMDriver(params)
        driver.run()
        # two of the three daily train files -> 240 examples
        assert int(np.asarray(driver._data.batch.weights > 0).sum()) == 240
        assert driver.best_model is not None

    def test_validate_per_iteration(self, tmp_path, avro_dirs):
        train, val = avro_dirs
        out = str(tmp_path / "out")
        params = GLMParams(
            train_dir=train,
            validate_dir=val,
            output_dir=out,
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[0.1, 1.0],
            validate_per_iteration=True,
        )
        driver = GLMDriver(params)
        driver.run()
        assert set(driver.per_iteration_metrics) == {0.1, 1.0}
        for lam, per_iter in driver.per_iteration_metrics.items():
            iters = int(driver.results[lam].iterations)
            # slot 0 = initial model, then one per iteration
            assert len(per_iter) == iters + 1
            assert all("AUC" in m for m in per_iter)
            # final per-iteration metrics == the final-model metrics
            assert per_iter[-1] == driver.validation_metrics[lam]
        # surfaced in metrics.json
        with open(os.path.join(out, "metrics.json")) as f:
            metrics = json.load(f)
        assert "0.1" in metrics["per_iteration_validation"]


@pytest.mark.skipif(
    not os.path.isdir(REF_INPUT), reason="reference fixtures unavailable"
)
class TestReferenceFixtureInterop:
    def test_heart_dataset_trains(self, tmp_path):
        """Train on the reference's Java-written heart.avro and beat the
        majority baseline on its validation file."""
        out = str(tmp_path / "out")
        params = GLMParams(
            train_dir=os.path.join(REF_INPUT, "heart.avro"),
            validate_dir=os.path.join(REF_INPUT, "heart_validation.avro"),
            output_dir=out,
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_weights=[0.1, 1.0],
            normalization_type=NormalizationType.STANDARDIZATION,
        )
        driver = GLMDriver(params)
        driver.run()
        best = driver.validation_metrics[driver.best_lambda]
        assert best["AUC"] > 0.75, best


class TestStreamingDriver:
    def test_streaming_mode_matches_in_memory(self, avro_dirs, tmp_path):
        train, val = avro_dirs
        common = dict(
            train_dir=train,
            validate_dir=val,
            task=TaskType.LOGISTIC_REGRESSION,
            regularization_type=RegularizationType.L2,
            regularization_weights=[1.0, 0.1],
            max_num_iterations=40,
        )
        d1 = GLMDriver(GLMParams(
            output_dir=str(tmp_path / "mem"), **common
        ))
        d1.run()
        d2 = GLMDriver(GLMParams(
            output_dir=str(tmp_path / "stream"), streaming=True, **common
        ))
        d2.run()
        assert d2.stage_history[-1].name == d1.stage_history[-1].name
        assert d2.best_lambda == d1.best_lambda
        for lam in (1.0, 0.1):
            np.testing.assert_allclose(
                np.asarray(d2.models[lam].coefficients.means),
                np.asarray(d1.models[lam].coefficients.means),
                atol=5e-3,
            )
            # validation metrics agree
            a = d1.validation_metrics[lam]["AUC"]
            b = d2.validation_metrics[lam]["AUC"]
            assert abs(a - b) < 5e-3
        # model files written in streaming mode too
        assert os.path.isdir(os.path.join(str(tmp_path / "stream"), "models"))

    def test_streaming_guards_only_structural(self, avro_dirs, tmp_path):
        train, _ = avro_dirs
        # Round 5: every driver stage streams (TRON, normalization, box,
        # variances, summarization, diagnostics, validate-per-iteration)
        # — these all validate cleanly now
        for kw in (
            dict(regularization_type=RegularizationType.L1),
            dict(normalization_type=NormalizationType.STANDARDIZATION),
            dict(optimizer_type=OptimizerType.TRON),
            dict(compute_variances=True),
            dict(summarization_output_dir="s"),
            dict(constraint_string="[]"),
            dict(validate_per_iteration=True, validate_dir="v"),
        ):
            GLMParams(
                train_dir=train,
                output_dir=str(tmp_path / "x"),
                streaming=True,
                **kw,
            ).validate()
        # LibSVM streams line-at-a-time since round 5: validates cleanly
        GLMParams(
            train_dir=train,
            output_dir=str(tmp_path / "z"),
            streaming=True,
            input_format="LIBSVM",
        ).validate()
        # Round 8 deleted the streaming x feature-sharding exclusion:
        # plain streaming + feature-sharded validates cleanly too
        GLMParams(
            train_dir=train,
            output_dir=str(tmp_path / "y"),
            streaming=True,
            distributed="feature",
        ).validate()
        # what remains unsupported is structural: normalization's
        # shift/factor extras aren't threaded through the per-chunk
        # sharded programs
        with pytest.raises(ValueError, match="streaming training"):
            GLMParams(
                train_dir=train,
                output_dir=str(tmp_path / "y"),
                streaming=True,
                distributed="feature",
                normalization_type=NormalizationType.STANDARDIZATION,
            ).validate()
