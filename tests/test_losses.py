"""Pointwise loss kernels vs numeric oracles (reference:
photon-ml .../function/glm/*LossFunction* unit tests)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.ops import losses
from photon_ml_tpu.task import TaskType

ALL = [losses.LOGISTIC, losses.LINEAR, losses.POISSON, losses.SMOOTHED_HINGE]


def _num_d1(f, z, y, eps=1e-3):
    # eps large enough to dominate float32 quantization noise
    return (f(z + eps, y) - f(z - eps, y)) / (2 * eps)


@pytest.mark.parametrize("loss", ALL, ids=lambda l: l.name)
def test_d1_matches_finite_difference(loss):
    z = jnp.asarray(np.linspace(-4, 4, 41), dtype=jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    for y in (0.0, 1.0, 3.0) if loss.name in ("squared", "poisson") else (0.0, 1.0):
        yv = jnp.full_like(z, y)
        got = loss.d1(z, yv)
        want = _num_d1(loss.value, z, yv)
        np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("loss", [losses.LOGISTIC, losses.LINEAR, losses.POISSON], ids=lambda l: l.name)
def test_d2_matches_finite_difference(loss):
    z = jnp.asarray(np.linspace(-3, 3, 31))
    yv = jnp.ones_like(z)
    got = loss.d2(z, yv)
    want = _num_d1(loss.d1, z, yv)
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


def test_logistic_stability_extreme_margins():
    z = jnp.asarray([-500.0, -50.0, 0.0, 50.0, 500.0])
    y = jnp.asarray([0.0, 1.0, 1.0, 0.0, 1.0])
    v = losses.LOGISTIC.value(z, y)
    assert bool(jnp.all(jnp.isfinite(v)))
    # loss(z, y=1) ~ 0 for large positive margin; ~|z| for mismatched sign
    np.testing.assert_allclose(float(v[4]), 0.0, atol=1e-5)
    np.testing.assert_allclose(float(v[3]), 50.0, rtol=1e-5)
    d = losses.LOGISTIC.d1(z, y)
    assert bool(jnp.all(jnp.isfinite(d)))


def test_smoothed_hinge_regions():
    # label 1 -> s=+1: t=z. Regions: z>=1 -> 0 ; z<=0 -> 0.5 - z ; else quad
    y = jnp.ones((5,))
    z = jnp.asarray([-2.0, 0.0, 0.5, 1.0, 3.0])
    v = losses.SMOOTHED_HINGE.value(z, y)
    np.testing.assert_allclose(np.asarray(v), [2.5, 0.5, 0.125, 0.0, 0.0], atol=1e-6)


def test_mean_functions():
    z = jnp.asarray([0.0, 1.0])
    np.testing.assert_allclose(losses.LOGISTIC.mean(z), [0.5, 1 / (1 + np.exp(-1))], rtol=1e-6)
    np.testing.assert_allclose(losses.POISSON.mean(z), [1.0, np.e], rtol=1e-6)
    np.testing.assert_allclose(losses.LINEAR.mean(z), [0.0, 1.0], rtol=1e-6)


def test_loss_for_task():
    assert losses.loss_for_task(TaskType.LOGISTIC_REGRESSION) is losses.LOGISTIC
    assert not losses.loss_for_task(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM).has_hessian
