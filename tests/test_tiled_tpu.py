"""Real-TPU (non-interpret) test tier: the framework's hot paths on the
actual chip, each checked against a CPU oracle in the same process.

The pytest harness pins everything to virtual CPU devices
(tests/conftest.py), and the axon TPU backend can only be selected before
JAX initializes — so this tier drives the real chip from ONE SUBPROCESS
with the default (TPU) environment (module-scoped fixture; TPU init and
compiles are paid once), and each pytest test asserts its own section's
marker. Gated behind PHOTON_TPU_TESTS=1: the tunnel's first compile is
~20-40s and CI keeps the suite CPU-only.

Sections (SURVEY §4: test on the real execution target):
  1. tiled Pallas kernels (all mxu variants + spill hybrid + the
     MXU-packed one-hot expansion) vs scatter
  2. GLM driver-path fit at the a1a shape, tiled-on-TPU vs scatter-on-CPU
  3. random-effect bank update on TPU vs the same solve on CPU
  4. MF ALS warm step on TPU vs the same coordinate on CPU
  5. streaming cached evaluation (tiled chunk cache) vs in-memory scatter
  6. 1-device-mesh tiled fit (shard_map) vs the replicated fit
  7. FEATURE-SHARDED fit under a 1x1 (data, model) mesh vs the CPU oracle
  8. full GAME coordinate-descent step on chip vs the CPU oracle (the
     whole composition: FE solve + RE bank + residuals + objective,
     through the overlap layer's deferred readbacks)

Run with:  PHOTON_TPU_TESTS=1 python -m pytest tests/test_tiled_tpu.py -v
"""

import os
import subprocess
import sys

import pytest

_CHECK = r"""
import numpy as np, jax, jax.numpy as jnp
assert any(d.platform != "cpu" for d in jax.devices()), jax.devices()
from photon_ml_tpu.utils.backend import enable_compilation_cache
enable_compilation_cache()
cpu = jax.devices("cpu")[0]

# ---- 1. tiled Pallas kernels vs the scatter oracle (on chip) ----------
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.tiled_sparse import build_tiled_batch, TiledGLMObjective
from photon_ml_tpu.data.batch import SparseBatch

rng = np.random.default_rng(0)
n, k, d = 2048, 16, 20000
indices = rng.integers(0, d, size=(n, k), dtype=np.int64)
values = rng.normal(size=(n, k)).astype(np.float32)
labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
offsets = rng.normal(size=n).astype(np.float32) * 0.1
weights = rng.uniform(0.5, 1.5, size=n).astype(np.float32)
rows = np.repeat(np.arange(n, dtype=np.int64), k)
tb = build_tiled_batch(rows, indices.reshape(-1), values.reshape(-1),
                       labels, offsets, weights, d)
sb = SparseBatch(indices=jnp.asarray(indices.astype(np.int32)),
                 values=jnp.asarray(values), labels=jnp.asarray(labels),
                 offsets=jnp.asarray(offsets), weights=jnp.asarray(weights))
oobj = GLMObjective(LOGISTIC, d)
w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.01)
for mxu, tol in (("highest", 1e-4), ("bf16x2", 1e-3), ("bf16x2w", 1e-3)):
    tobj = TiledGLMObjective(LOGISTIC, d, mxu=mxu)
    v1, g1 = jax.jit(tobj.value_and_gradient)(w, tb, 0.1)
    v2, g2 = jax.jit(oobj.value_and_gradient)(w, sb, 0.1)
    ge = float(jnp.max(jnp.abs(g1 - g2)) / (jnp.max(jnp.abs(g2)) + 1e-9))
    hv1 = jax.jit(tobj.hessian_vector)(w, w * 0.5, tb, 0.1)
    hv2 = jax.jit(oobj.hessian_vector)(w, w * 0.5, sb, 0.1)
    he = float(jnp.max(jnp.abs(hv1 - hv2)) / (jnp.max(jnp.abs(hv2)) + 1e-9))
    hd1 = jax.jit(tobj.hessian_diagonal)(w, tb, 0.1)
    hd2 = jax.jit(oobj.hessian_diagonal)(w, sb, 0.1)
    de = float(jnp.max(jnp.abs(hd1 - hd2)) / (jnp.max(jnp.abs(hd2)) + 1e-9))
    assert max(ge, he, de) < tol, (mxu, ge, he, de)

# spill-to-scatter hybrid ON CHIP: force tile remainders through the
# spill path (cap > remainder) and hold the same tolerance
from photon_ml_tpu.ops.tiled_sparse import TileParams
tb_spill = build_tiled_batch(rows, indices.reshape(-1), values.reshape(-1),
                             labels, offsets, weights, d,
                             params=TileParams(chunk=4096, spill_cap=3000))
assert int(np.count_nonzero(np.asarray(tb_spill.z_sched.spill_vals))) > 0
assert int(np.count_nonzero(np.asarray(tb_spill.g_sched.spill_vals))) > 0
tobj = TiledGLMObjective(LOGISTIC, d, mxu="bf16x2w")
v1, g1 = jax.jit(tobj.value_and_gradient)(w, tb_spill, 0.1)
v2, g2 = jax.jit(oobj.value_and_gradient)(w, sb, 0.1)
ge = float(jnp.max(jnp.abs(g1 - g2)) / (jnp.max(jnp.abs(g2)) + 1e-9))
assert ge < 1e-3, ("spill", ge)

# MXU-packed one-hot expansion ON CHIP: bit-identical to the compare
# build (both produce exact 0/1 one-hots) — the Mosaic lowering of the
# distance-matmul route must not change numerics
tobj_moh = TiledGLMObjective(LOGISTIC, d, mxu="bf16x2w", onehot="mxu")
vm, gm = jax.jit(tobj_moh.value_and_gradient)(w, tb, 0.1)
vc, gc = jax.jit(TiledGLMObjective(LOGISTIC, d, mxu="bf16x2w")
                 .value_and_gradient)(w, tb, 0.1)
assert float(vm) == float(vc), ("mxu-onehot value", float(vm), float(vc))
assert bool(jnp.all(gm == gc)), "mxu-onehot grad differs from compare build"
print("TPU_TILED_OK")

# ---- 2. GLM training-path fit at the a1a shape: TPU tiled vs CPU ------
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.training import train_generalized_linear_model
from photon_ml_tpu.optim import RegularizationType

def a1a_batch():
    r = np.random.default_rng(1)
    na, da, ka = 1605, 123, 14
    ixa = np.stack([r.choice(da, size=ka, replace=False) for _ in range(na)])
    va = r.normal(size=(na, ka)).astype(np.float32)
    wt = r.normal(size=da).astype(np.float32)
    za = (wt[ixa] * va).sum(axis=1)
    ya = (r.uniform(size=na) < 1 / (1 + np.exp(-za))).astype(np.float32)
    return SparseBatch(
        indices=jnp.asarray(ixa.astype(np.int32)), values=jnp.asarray(va),
        labels=jnp.asarray(ya), offsets=jnp.zeros(na, jnp.float32),
        weights=jnp.ones(na, jnp.float32)), da

batch_a1a, d_a1a = a1a_batch()
kwargs = dict(regularization_type=RegularizationType.L2,
              regularization_weights=[1.0, 0.1], max_iter=50)
m_tpu, _ = train_generalized_linear_model(
    batch_a1a, TaskType.LOGISTIC_REGRESSION, d_a1a, kernel="tiled", **kwargs)
with jax.default_device(cpu):
    host = jax.device_get(batch_a1a)
    batch_cpu = SparseBatch(*(jnp.asarray(np.asarray(a)) for a in host))
    m_cpu, _ = train_generalized_linear_model(
        batch_cpu, TaskType.LOGISTIC_REGRESSION, d_a1a, kernel="scatter",
        **kwargs)
for lam in (1.0, 0.1):
    err = float(jnp.max(jnp.abs(
        jnp.asarray(np.asarray(m_tpu[lam].means))
        - jnp.asarray(np.asarray(m_cpu[lam].means)))))
    assert err < 5e-3, ("a1a", lam, err)
print("TPU_GLM_FIT_OK")

# ---- 3. random-effect bank update: TPU vs CPU oracle ------------------
from types import SimpleNamespace
from photon_ml_tpu.game.random_effect import RandomEffectOptimizationProblem
from photon_ml_tpu.game.random_effect_data import RandomEffectBucket
from photon_ml_tpu.optim.config import (OptimizerConfig, OptimizerType,
                                        RegularizationContext)

r = np.random.default_rng(2)
E, S, K2 = 256, 8, 16
idx = r.integers(0, 32, size=(E, S, K2), dtype=np.int32)
val = r.normal(size=(E, S, K2)).astype(np.float32)
w_ent = r.normal(size=(E, 1, 32)).astype(np.float32) * 0.5
z = np.take_along_axis(np.broadcast_to(w_ent, (E, S, 32)), idx, axis=2)
z = (z * val).sum(axis=2)
lab = (r.uniform(size=(E, S)) < 1 / (1 + np.exp(-z))).astype(np.float32)
bucket = RandomEffectBucket(
    entity_codes=np.arange(E, dtype=np.int32),
    row_index=np.full((E, S), -1, np.int32),
    indices=idx, values=val, labels=lab,
    offsets=np.zeros((E, S), np.float32),
    weights=np.ones((E, S), np.float32))
dataset = SimpleNamespace(buckets=[bucket])

def bank_update():
    problem = RandomEffectOptimizationProblem(
        loss=LOGISTIC,
        config=OptimizerConfig(OptimizerType.LBFGS, max_iter=20,
                               tolerance=1e-5, lbfgs_history=5),
        regularization=RegularizationContext(),
        reg_weight=1.0)
    bank0 = jnp.zeros((E, 32), jnp.float32)
    bank, _ = problem.update_bank(bank0, dataset)
    return np.asarray(bank)

bank_tpu = bank_update()
with jax.default_device(cpu):
    bank_cpu = bank_update()
err = float(np.max(np.abs(bank_tpu - bank_cpu)))
assert err < 5e-3, ("re_bank", err)
print("TPU_RE_BANK_OK")

# ---- 4. MF ALS warm step: TPU vs CPU oracle ---------------------------
from photon_ml_tpu.game.coordinate import MatrixFactorizationCoordinate
from photon_ml_tpu.game.data import EntityIndex, GameDataset
from photon_ml_tpu.ops.losses import LINEAR
from photon_ml_tpu.optim.config import RegularizationType as RT2

r = np.random.default_rng(3)
nr, nc, K3, nrat = 400, 300, 8, 4000
rws = r.integers(0, nr, size=nrat).astype(np.int32)
cls = r.integers(0, nc, size=nrat).astype(np.int32)
rt = r.normal(0, 0.4, size=(nr, K3)).astype(np.float32)
ct = r.normal(0, 0.4, size=(nc, K3)).astype(np.float32)
ratings = ((rt[rws] * ct[cls]).sum(axis=1)
           + 0.2 * r.normal(size=nrat)).astype(np.float32)

def eindex(prefix, count):
    ids = [f"{prefix}{i}" for i in range(count)]
    return EntityIndex(prefix, ids, {v: i for i, v in enumerate(ids)})

def mf_step():
    ds = GameDataset(
        uids=[""] * nrat, labels=ratings,
        offsets=np.zeros(nrat, np.float32),
        weights=np.ones(nrat, np.float32), shards={},
        entity_codes={"userId": rws, "itemId": cls},
        entity_indexes={"userId": eindex("u", nr),
                        "itemId": eindex("i", nc)},
        num_real_rows=nrat)
    coord = MatrixFactorizationCoordinate(
        name="mf", dataset=ds, row_effect_type="userId",
        col_effect_type="itemId", num_latent_factors=K3,
        problem=RandomEffectOptimizationProblem(
            loss=LINEAR,
            config=OptimizerConfig(OptimizerType.LBFGS, max_iter=15,
                                   tolerance=1e-5, lbfgs_history=5),
            regularization=RegularizationContext(),
            reg_weight=1.0))
    model = coord.initialize_model()
    model, _ = coord.update_model(model)   # structure build + compile
    model, _ = coord.update_model(model)   # the warm per-CD-iteration step
    return np.asarray(model.row_latent), np.asarray(model.col_latent)

row_tpu, col_tpu = mf_step()
with jax.default_device(cpu):
    row_cpu, col_cpu = mf_step()
err = max(float(np.max(np.abs(row_tpu - row_cpu))),
          float(np.max(np.abs(col_tpu - col_cpu))))
assert err < 5e-3, ("mf", err)
print("TPU_MF_OK")

# ---- 5. streaming cached evaluation (tiled chunk cache) on chip -------
import tempfile, shutil
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.avro_codec import write_container
from photon_ml_tpu.io.input_format import AvroInputDataFormat
from photon_ml_tpu.io.streaming import StreamingGLMObjective, scan_stream

tmp = tempfile.mkdtemp(prefix="photon-tpu-stream-")
try:
    r = np.random.default_rng(4)
    ds_d = 5000
    for fi in range(2):
        recs = []
        for i in range(400):
            ix = r.choice(ds_d, size=8, replace=False)
            vs = r.normal(size=8)
            recs.append({"uid": f"{fi}-{i}",
                         "label": float(r.uniform() > 0.5),
                         "features": [{"name": str(int(j)), "term": "",
                                       "value": float(v)}
                                      for j, v in zip(ix, vs)],
                         "offset": 0.0, "weight": 1.0})
        write_container(f"{tmp}/p{fi}.avro",
                        schemas.TRAINING_EXAMPLE_AVRO, recs)
    fmt = AvroInputDataFormat()
    index_map, stats = scan_stream([tmp], fmt)
    sobj = StreamingGLMObjective([tmp], fmt, index_map, stats,
                                 TaskType.LOGISTIC_REGRESSION,
                                 rows_per_chunk=256, kernel="tiled")
    ws = jnp.asarray(r.normal(size=sobj.dim).astype(np.float32) * 0.1)
    v1, g1 = sobj.value_and_gradient(ws, 0.3)   # populate (scatter)
    v2, g2 = sobj.value_and_gradient(ws, 0.3)   # cached (tiled Pallas)
    assert sobj._tiled_chunk_count, "tiled chunk cache was not built on TPU"
    assert abs(float(v2) - float(v1)) / abs(float(v1)) < 2e-4, (v1, v2)
    gerr = float(jnp.max(jnp.abs(g2 - g1)) / (jnp.max(jnp.abs(g1)) + 1e-9))
    assert gerr < 2e-3, gerr
finally:
    shutil.rmtree(tmp, ignore_errors=True)
print("TPU_STREAMING_OK")

# ---- 6. 1-device-mesh tiled fit (shard_map) vs replicated -------------
from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh
tpu_dev = [dd for dd in jax.devices() if dd.platform != "cpu"][0]
mesh = make_mesh((1,), (DATA_AXIS,), devices=[tpu_dev])
m_mesh, _ = train_generalized_linear_model(
    batch_a1a, TaskType.LOGISTIC_REGRESSION, d_a1a, kernel="tiled",
    mesh=mesh, **kwargs)
for lam in (1.0, 0.1):
    err = float(np.max(np.abs(np.asarray(m_mesh[lam].means)
                              - np.asarray(m_tpu[lam].means))))
    assert err < 5e-3, ("mesh", lam, err)
print("TPU_MESH_FIT_OK")

# ---- 7. feature-sharded fit under a 1x1 (data, model) mesh ------------
from photon_ml_tpu.parallel.mesh import MODEL_AXIS
from photon_ml_tpu.training import train_feature_sharded

mesh11 = make_mesh((1, 1), (DATA_AXIS, MODEL_AXIS), devices=[tpu_dev])
m_fs, _ = train_feature_sharded(
    batch_a1a, TaskType.LOGISTIC_REGRESSION, d_a1a, mesh=mesh11,
    kernel="tiled", **kwargs)
for lam in (1.0, 0.1):
    err = float(np.max(np.abs(np.asarray(m_fs[lam].means)
                              - np.asarray(m_cpu[lam].means))))
    assert err < 5e-3, ("feature-sharded", lam, err)
print("TPU_FEATURE_SHARDED_OK")

# ---- 8. full GAME coordinate-descent step on chip vs CPU oracle -------
from photon_ml_tpu.game import (
    CoordinateDescent, FeatureShardConfiguration, FixedEffectCoordinate,
    RandomEffectCoordinate, RandomEffectDataConfiguration,
    RandomEffectOptimizationProblem, build_game_dataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.optim.config import RegularizationContext as RC5
from photon_ml_tpu.optim.config import RegularizationType as RT5
from photon_ml_tpu.optim.problem import create_glm_problem

r = np.random.default_rng(5)
recs = []
for i in range(160):
    u = int(r.integers(0, 8))
    xg = r.normal(size=5); xu = r.normal(size=3)
    recs.append({
        "uid": f"r{i}", "response": float(r.uniform() > 0.5),
        "userId": f"u{u}",
        "features": [{"name": f"g{j}", "term": "", "value": float(xg[j])}
                     for j in range(5)],
        "userFeatures": [{"name": f"f{j}", "term": "", "value": float(xu[j])}
                         for j in range(3)],
    })
game_shards = [
    FeatureShardConfiguration("globalShard", ["features"], add_intercept=True),
    FeatureShardConfiguration("userShard", ["userFeatures"], add_intercept=True),
]

def game_cd_step():
    ds = build_game_dataset(recs, game_shards, ["userId"])
    red = build_random_effect_dataset(
        ds, RandomEffectDataConfiguration("userId", "userShard"))
    coords = {
        "fixed": FixedEffectCoordinate(
            name="fixed", dataset=ds,
            problem=create_glm_problem(
                TaskType.LOGISTIC_REGRESSION, ds.shards["globalShard"].dim,
                config=OptimizerConfig(max_iter=10),
                regularization=RC5(RT5.L2)),
            feature_shard_id="globalShard", reg_weight=0.5),
        "perUser": RandomEffectCoordinate(
            name="perUser", dataset=ds, re_dataset=red,
            problem=RandomEffectOptimizationProblem(
                LOGISTIC, OptimizerConfig(max_iter=10), RC5(RT5.L2),
                reg_weight=1.0)),
    }
    res = CoordinateDescent(
        coords, ds, TaskType.LOGISTIC_REGRESSION,
        update_sequence=["fixed", "perUser"],
    ).run(2)
    return (np.asarray(res.model.get_model("fixed").model.means),
            np.asarray(res.model.get_model("perUser").bank),
            np.asarray(res.objective_history))

from photon_ml_tpu.optim.config import OptimizerConfig
fe_t, bank_t, hist_t = game_cd_step()
with jax.default_device(cpu):
    fe_c, bank_c, hist_c = game_cd_step()
assert float(np.max(np.abs(fe_t - fe_c))) < 5e-3, "GAME CD FE means"
assert float(np.max(np.abs(bank_t - bank_c))) < 5e-3, "GAME CD RE bank"
np.testing.assert_allclose(hist_t, hist_c, atol=1e-3)
print("TPU_GAME_CD_OK")
"""

_MARKERS = {
    "tiled_kernels": "TPU_TILED_OK",
    "glm_fit_a1a": "TPU_GLM_FIT_OK",
    "re_bank_update": "TPU_RE_BANK_OK",
    "mf_warm_step": "TPU_MF_OK",
    "streaming_cached_eval": "TPU_STREAMING_OK",
    "one_device_mesh_fit": "TPU_MESH_FIT_OK",
    "feature_sharded_1x1_mesh_fit": "TPU_FEATURE_SHARDED_OK",
    "game_cd_step": "TPU_GAME_CD_OK",
}

pytestmark = pytest.mark.skipif(
    os.environ.get("PHOTON_TPU_TESTS") != "1",
    reason="real-TPU test; set PHOTON_TPU_TESTS=1 to run",
)


@pytest.fixture(scope="module")
def tpu_run():
    """One subprocess on the real chip executing every section; sections
    print a marker on success. TPU init + compiles are paid once."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    proc = subprocess.run(
        [sys.executable, "-c", _CHECK],
        env=env,
        capture_output=True,
        text=True,
        timeout=1100,
    )
    return proc


@pytest.mark.parametrize("section", sorted(_MARKERS))
def test_on_real_tpu(tpu_run, section):
    marker = _MARKERS[section]
    if marker not in tpu_run.stdout:
        raise AssertionError(
            f"section {section!r} did not reach {marker}; rc="
            f"{tpu_run.returncode}\nstdout tail: {tpu_run.stdout[-1500:]}"
            f"\nstderr tail: {tpu_run.stderr[-3000:]}"
        )
