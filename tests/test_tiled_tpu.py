"""Real-TPU (non-interpret) test for the tiled Pallas kernels.

The pytest harness pins everything to virtual CPU devices
(tests/conftest.py), and the axon TPU backend can only be selected before
JAX initializes — so this test drives the real chip from a SUBPROCESS with
the default (TPU) environment. Gated behind PHOTON_TPU_TESTS=1: the
tunnel's first compile is ~20-40s and CI keeps the suite CPU-only.

Run with:  PHOTON_TPU_TESTS=1 python -m pytest tests/test_tiled_tpu.py -v
"""

import os
import subprocess
import sys

import pytest

_CHECK = r"""
import numpy as np, jax, jax.numpy as jnp
assert any(d.platform != "cpu" for d in jax.devices()), jax.devices()
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.tiled_sparse import build_tiled_batch, TiledGLMObjective
from photon_ml_tpu.data.batch import SparseBatch

rng = np.random.default_rng(0)
n, k, d = 2048, 16, 20000
indices = rng.integers(0, d, size=(n, k), dtype=np.int64)
values = rng.normal(size=(n, k)).astype(np.float32)
labels = (rng.uniform(size=n) > 0.5).astype(np.float32)
offsets = rng.normal(size=n).astype(np.float32) * 0.1
weights = rng.uniform(0.5, 1.5, size=n).astype(np.float32)
rows = np.repeat(np.arange(n, dtype=np.int64), k)
tb = build_tiled_batch(rows, indices.reshape(-1), values.reshape(-1),
                       labels, offsets, weights, d)
sb = SparseBatch(indices=jnp.asarray(indices.astype(np.int32)),
                 values=jnp.asarray(values), labels=jnp.asarray(labels),
                 offsets=jnp.asarray(offsets), weights=jnp.asarray(weights))
oobj = GLMObjective(LOGISTIC, d)
w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.01)
for mxu, tol in (("highest", 1e-4), ("bf16x2", 1e-3), ("bf16x2w", 1e-3)):
    tobj = TiledGLMObjective(LOGISTIC, d, mxu=mxu)
    v1, g1 = jax.jit(tobj.value_and_gradient)(w, tb, 0.1)
    v2, g2 = jax.jit(oobj.value_and_gradient)(w, sb, 0.1)
    ge = float(jnp.max(jnp.abs(g1 - g2)) / (jnp.max(jnp.abs(g2)) + 1e-9))
    hv1 = jax.jit(tobj.hessian_vector)(w, w * 0.5, tb, 0.1)
    hv2 = jax.jit(oobj.hessian_vector)(w, w * 0.5, sb, 0.1)
    he = float(jnp.max(jnp.abs(hv1 - hv2)) / (jnp.max(jnp.abs(hv2)) + 1e-9))
    hd1 = jax.jit(tobj.hessian_diagonal)(w, tb, 0.1)
    hd2 = jax.jit(oobj.hessian_diagonal)(w, sb, 0.1)
    de = float(jnp.max(jnp.abs(hd1 - hd2)) / (jnp.max(jnp.abs(hd2)) + 1e-9))
    assert max(ge, he, de) < tol, (mxu, ge, he, de)

# spill-to-scatter hybrid ON CHIP: force tile remainders through the
# spill path (cap > remainder) and hold the same tolerance
from photon_ml_tpu.ops.tiled_sparse import TileParams
tb_spill = build_tiled_batch(rows, indices.reshape(-1), values.reshape(-1),
                             labels, offsets, weights, d,
                             params=TileParams(chunk=4096, spill_cap=3000))
assert int(np.count_nonzero(np.asarray(tb_spill.z_sched.spill_vals))) > 0
assert int(np.count_nonzero(np.asarray(tb_spill.g_sched.spill_vals))) > 0
tobj = TiledGLMObjective(LOGISTIC, d, mxu="bf16x2w")
v1, g1 = jax.jit(tobj.value_and_gradient)(w, tb_spill, 0.1)
v2, g2 = jax.jit(oobj.value_and_gradient)(w, sb, 0.1)
ge = float(jnp.max(jnp.abs(g1 - g2)) / (jnp.max(jnp.abs(g2)) + 1e-9))
assert ge < 1e-3, ("spill", ge)
print("TPU_TILED_OK")
"""


@pytest.mark.skipif(
    os.environ.get("PHOTON_TPU_TESTS") != "1",
    reason="real-TPU test; set PHOTON_TPU_TESTS=1 to run",
)
def test_tiled_kernels_on_real_tpu():
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    proc = subprocess.run(
        [sys.executable, "-c", _CHECK],
        env=env,
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "TPU_TILED_OK" in proc.stdout
