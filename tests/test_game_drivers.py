"""GAME driver end-to-end tests (cli/game DriverTest analog): train on
synthetic Avro, save with reference layout, reload, batch-score, evaluate;
interop run on the reference's yahoo-music fixture.
"""

import json
import os

import numpy as np
import pytest


from photon_ml_tpu.cli.game_scoring_driver import (
    GameScoringDriver,
    GameScoringParams,
)
from photon_ml_tpu.cli.game_training_driver import (
    GameTrainingDriver,
    GameTrainingParams,
    expand_config_grid,
    parse_keyed_map,
    parse_shard_map,
)
from photon_ml_tpu.evaluation import EvaluatorType
from photon_ml_tpu.game.config import (
    FeatureShardConfiguration,
    FixedEffectDataConfiguration,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.game.model_io import load_game_model
from photon_ml_tpu.io.avro_codec import read_avro_records, write_container
from photon_ml_tpu.task import TaskType

# Driver end-to-end runs (full stage pipelines, file IO,
# multi-lambda fits): integration tier
pytestmark = pytest.mark.slow

GAME_REF = "/root/reference/photon-ml/src/integTest/resources/GameIntegTest"


def write_game_avro(path, rng, n=240, n_users=8, d_g=5, d_u=3, seed_shift=0):
    w_g = np.linspace(-1, 1, d_g)
    w_u = np.random.default_rng(7).normal(size=(n_users, d_u))
    recs = []
    for i in range(n):
        u = int(rng.integers(0, n_users))
        xg = rng.normal(size=d_g)
        xu = rng.normal(size=d_u)
        z = float(xg @ w_g + xu @ w_u[u])
        recs.append({
            "uid": f"s{seed_shift}-{i}",
            "response": float(1 / (1 + np.exp(-z)) > rng.uniform()),
            "metadataMap": {"userId": f"user{u}"},
            "features": [
                {"name": f"g{j}", "term": "", "value": float(xg[j])}
                for j in range(d_g)
            ],
            "userFeatures": [
                {"name": f"u{j}", "term": "", "value": float(xu[j])}
                for j in range(d_u)
            ],
        })
    from conftest import game_example_schema

    schema = game_example_schema()
    write_container(path, schema, recs)


class TestConfigParsing:
    def test_keyed_map(self):
        m = parse_keyed_map("a:1,2|b:3,4")
        assert m == {"a": "1,2", "b": "3,4"}

    def test_shard_map(self):
        shards = parse_shard_map("global:features|user:userFeatures,extra")
        assert shards[0].shard_id == "global"
        assert list(shards[1].feature_bags) == ["userFeatures", "extra"]

    def test_grid_expansion(self):
        combos = expand_config_grid({
            "a": "10,1e-4,1.0,1,LBFGS,L2;10,1e-4,10.0,1,LBFGS,L2",
            "b": "5,1e-4,0.5,1,LBFGS,L2",
        })
        assert len(combos) == 2
        assert {c["a"].reg_weight for c in combos} == {1.0, 10.0}


class TestGameTrainingEndToEnd:
    def _params(self, tmp_path, rng, **kw):
        train = tmp_path / "train"; train.mkdir()
        val = tmp_path / "val"; val.mkdir()
        write_game_avro(str(train / "p0.avro"), rng)
        write_game_avro(str(val / "p0.avro"), rng, n=120, seed_shift=1)
        base = dict(
            train_input_dirs=[str(train)],
            validate_input_dirs=[str(val)],
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=[
                FeatureShardConfiguration("globalShard", ["features"]),
                FeatureShardConfiguration("userShard", ["userFeatures"]),
            ],
            fixed_effect_data_configs={
                "global": FixedEffectDataConfiguration("globalShard")
            },
            fixed_effect_opt_configs={"global": "30,1e-6,0.1,1,LBFGS,L2"},
            random_effect_data_configs={
                "per-user": RandomEffectDataConfiguration("userId", "userShard")
            },
            random_effect_opt_configs={"per-user": "30,1e-6,1.0,1,LBFGS,L2"},
            num_iterations=2,
            evaluator_types=[EvaluatorType.parse("AUC")],
        )
        base.update(kw)
        return GameTrainingParams(**base)

    def test_train_save_load_score(self, tmp_path, rng):
        params = self._params(tmp_path, rng)
        driver = GameTrainingDriver(params)
        driver.run()
        out = params.output_dir
        # objective decreased across CD iterations
        metrics = json.load(open(os.path.join(out, "metrics.json")))
        assert len(metrics["objective_history"]) == 2
        assert metrics["objective_history"][-1] <= metrics["objective_history"][0]
        assert metrics["validation_history"][-1]["AUC"] > 0.6
        # reference layout on disk
        model_dir = os.path.join(out, "best-model")
        assert os.path.isfile(
            os.path.join(model_dir, "fixed-effect", "global", "id-info")
        )
        assert os.path.isfile(
            os.path.join(model_dir, "random-effect", "per-user", "coefficients",
                         "part-00000.avro")
        )
        assert os.path.isfile(os.path.join(model_dir, "model-spec"))

        # scoring driver round-trip on the validation data
        sp = GameScoringParams(
            input_dirs=params.validate_input_dirs,
            game_model_input_dir=model_dir,
            output_dir=str(tmp_path / "scores"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=params.feature_shards,
            evaluator_types=[EvaluatorType.parse("AUC")],
        )
        sd = GameScoringDriver(sp)
        sd.run()
        assert sd.metrics["AUC"] > 0.6
        score_recs = list(read_avro_records(str(tmp_path / "scores" / "scores")))
        assert len(score_recs) == 120
        assert all(np.isfinite(r["predictionScore"]) for r in score_recs)
        # scoring metrics match training-side validation metric
        assert sd.metrics["AUC"] == pytest.approx(
            metrics["validation_history"][-1]["AUC"], abs=0.05
        )

    def test_checkpoint_dir_resume(self, tmp_path, rng):
        """--checkpoint-dir: iterations checkpoint; a rerun fast-forwards
        past completed steps instead of retraining."""
        from photon_ml_tpu.utils.checkpoint import TrainingCheckpointer

        params = self._params(
            tmp_path, rng, checkpoint_dir=str(tmp_path / "ckpt"),
        )
        GameTrainingDriver(params).run()
        combo_dir = next((tmp_path / "ckpt").glob("combo-*"))
        assert TrainingCheckpointer(str(combo_dir)).latest_step() == 2
        # restarted identical job: resumes at the final step, so no new CD
        # iterations run, and best selection comes from the meta sidecar
        import dataclasses

        params2 = dataclasses.replace(
            params, output_dir=str(tmp_path / "out2")
        )
        d2 = GameTrainingDriver(params2)
        d2.run()
        assert d2.results[0][1].objective_history == []
        assert d2.best_result[1] is not None  # metric restored, not re-judged

        # a changed input configuration must fail loudly, not silently
        # resume foreign weights
        (tmp_path / "rerun").mkdir()
        params3 = self._params(
            tmp_path / "rerun", rng, checkpoint_dir=str(tmp_path / "ckpt"),
        )
        with pytest.raises(ValueError, match="different run configuration"):
            GameTrainingDriver(params3).run()

    def test_fe_lambda_grid_batched_matches_sequential(self, tmp_path, rng):
        """A pure fixed-effect λ sweep (one FE coordinate, no REs, 1 CD
        iteration) collapses to ONE vmapped grid program under
        --grid-mode batched; per-combo objectives, validation metrics
        and best-combo selection match the sequential sweep."""
        import dataclasses

        base = self._params(
            tmp_path, rng,
            fixed_effect_opt_configs={
                "global": (
                    "30,1e-6,0.1,1,LBFGS,L2;30,1e-6,10.0,1,LBFGS,L2;"
                    "30,1e-6,1000.0,1,LBFGS,L2"
                )
            },
            random_effect_data_configs={},
            random_effect_opt_configs={},
            num_iterations=1,
            grid_mode="batched",
        )
        d_b = GameTrainingDriver(base)
        d_b.run()
        d_s = GameTrainingDriver(dataclasses.replace(
            base, grid_mode="sequential",
            output_dir=str(tmp_path / "out_seq"),
        ))
        d_s.run()
        assert len(d_b.results) == 3
        assert (
            d_b.best_config["global"].reg_weight
            == d_s.best_config["global"].reg_weight
        )
        by_lam_s = {
            c["global"].reg_weight: r for c, r, _ in d_s.results
        }
        for combo, result, _ci in d_b.results:
            lam = combo["global"].reg_weight
            ref = by_lam_s[lam]
            assert result.objective_history[-1] == pytest.approx(
                ref.objective_history[-1], rel=2e-3
            )
            assert result.best_metric == pytest.approx(
                ref.best_metric, abs=5e-3
            )
        # batched path still writes the reference model layout
        assert os.path.isfile(os.path.join(
            base.output_dir, "best-model", "fixed-effect", "global",
            "id-info",
        ))

    def test_fe_grid_not_batchable_with_random_effects(self, tmp_path, rng):
        """Grids that are NOT pure FE λ sweeps (here: an RE coordinate in
        the model) always run the sequential warm-started sweep, even
        under --grid-mode batched."""
        params = self._params(
            tmp_path, rng,
            fixed_effect_opt_configs={
                "global": "30,1e-6,0.1,1,LBFGS,L2;30,1e-6,1000.0,1,LBFGS,L2"
            },
            num_iterations=1,
            grid_mode="batched",
        )
        driver = GameTrainingDriver(params)
        assert driver._fe_grid_lambdas(expand_config_grid({
            **params.fixed_effect_opt_configs,
            **params.random_effect_opt_configs,
        })) is None
        driver.run()
        assert len(driver.results) == 2
        # sequential sweep trains strongest-λ first (warm-start order)
        assert driver.results[0][0]["global"].reg_weight == 1000.0

    def test_grid_picks_best(self, tmp_path, rng):
        params = self._params(
            tmp_path, rng,
            fixed_effect_opt_configs={
                "global": "30,1e-6,0.1,1,LBFGS,L2;30,1e-6,1000.0,1,LBFGS,L2"
            },
            num_iterations=1,
        )
        driver = GameTrainingDriver(params)
        driver.run()
        assert len(driver.results) == 2
        # strongest regularization trains first so later combos warm-start
        # from the previous fit
        assert driver.results[0][0]["global"].reg_weight == 1000.0
        assert driver.best_config["global"].reg_weight == 0.1

    def test_dated_train_inputs(self, tmp_path, rng):
        import datetime

        from photon_ml_tpu.utils.date_range import daily_path

        dated = tmp_path / "dated"
        for d in (1, 2, 3):
            p = daily_path(str(dated), datetime.date(2016, 1, d))
            os.makedirs(p)
            write_game_avro(os.path.join(p, "p0.avro"), rng, n=80,
                            seed_shift=d)
        params = self._params(
            tmp_path, rng,
            train_input_dirs=[str(dated)],
            train_date_range="20160101-20160102",  # excludes day 3
        )
        from photon_ml_tpu.cli.game_training_driver import GameTrainingDriver

        driver = GameTrainingDriver(params)
        driver.run()
        assert driver._train_dataset.num_real_rows == 160
        assert driver.best_result is not None

    def test_missing_opt_config_rejected(self, tmp_path, rng):
        with pytest.raises(ValueError, match="missing optimization"):
            self._params(tmp_path, rng, fixed_effect_opt_configs={}).validate()

    def test_feature_sharded_accepts_down_sampling(self, tmp_path, rng):
        """Down-sampling now COMPOSES with --distributed feature: the
        sampler is pure row re-weighting whose per-draw weights ride the
        cached sharded layout as traced arguments
        (FixedEffectCoordinate._update_model_feature_sharded), so the
        round-5 parse-time rejection is gone — singly and in grids."""
        p = self._params(
            tmp_path, rng,
            distributed="feature",
            fixed_effect_opt_configs={"global": "30,1e-6,0.1,0.5,LBFGS,L2"},
        )
        p.validate()
        p.fixed_effect_opt_configs = {
            "global": "30,1e-6,0.1,1,LBFGS,L2;30,1e-6,1.0,0.9,LBFGS,L2"
        }
        p.validate()


    def test_model_output_modes(self, tmp_path, rng):
        """ALL writes best-model plus all/<i> per combo; BEST only the
        best; NONE nothing (ModelOutputMode.scala,
        cli/game/training/Driver.scala:620-635, :706)."""
        params = self._params(
            tmp_path, rng,
            fixed_effect_opt_configs={
                "global": "10,1e-6,0.1,1,LBFGS,L2;10,1e-6,100.0,1,LBFGS,L2"
            },
            num_iterations=1,
        )
        GameTrainingDriver(params).run()
        out = params.output_dir
        assert os.path.isdir(os.path.join(out, "best-model"))
        assert os.path.isdir(os.path.join(out, "all", "0"))
        assert os.path.isdir(os.path.join(out, "all", "1"))
        # all/<i> is the USER's grid index (combo 0 = reg 0.1), not the
        # warm-start training order (which runs reg 100 first)
        spec0 = open(os.path.join(out, "all", "0", "model-spec")).read()
        spec1 = open(os.path.join(out, "all", "1", "model-spec")).read()
        assert "0.1" in spec0 and "100" not in spec0
        assert "100" in spec1

        for mode, best_exists, all_exists in (
            ("BEST", True, False), ("NONE", False, False),
        ):
            (tmp_path / mode).mkdir()
            params2 = self._params(
                (tmp_path / mode), rng, model_output_mode=mode,
            )
            GameTrainingDriver(params2).run()
            out2 = params2.output_dir
            assert os.path.isdir(os.path.join(out2, "best-model")) == best_exists
            assert os.path.isdir(os.path.join(out2, "all")) == all_exists

    def test_bad_model_output_mode_rejected(self, tmp_path, rng):
        params = self._params(tmp_path, rng, model_output_mode="SOME")
        with pytest.raises(ValueError):
            GameTrainingDriver(params)


@pytest.mark.skipif(
    not os.path.isdir(GAME_REF), reason="reference fixtures unavailable"
)
class TestYahooMusicInterop:
    def test_train_on_reference_fixture(self, tmp_path):
        """GLMix (global + per-user + per-song) on the reference's
        yahoo-music fixture — linear regression on ratings."""
        # the fixture ships only a test split; train and validate on it
        # (interop check, not a generalization claim)
        params = GameTrainingParams(
            train_input_dirs=[os.path.join(GAME_REF, "input", "test")],
            validate_input_dirs=[os.path.join(GAME_REF, "input", "test")],
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LINEAR_REGRESSION,
            feature_shards=[
                FeatureShardConfiguration("globalShard", ["features"]),
                FeatureShardConfiguration("userShard", ["userFeatures"]),
                FeatureShardConfiguration("songShard", ["songFeatures"]),
            ],
            fixed_effect_data_configs={
                "global": FixedEffectDataConfiguration("globalShard")
            },
            fixed_effect_opt_configs={"global": "20,1e-5,10.0,1,LBFGS,L2"},
            random_effect_data_configs={
                "per-user": RandomEffectDataConfiguration("userId", "userShard"),
                "per-song": RandomEffectDataConfiguration("songId", "songShard"),
            },
            random_effect_opt_configs={
                "per-user": "10,1e-5,1.0,1,LBFGS,L2",
                "per-song": "10,1e-5,10.0,1,LBFGS,L2",
            },
            num_iterations=2,
            evaluator_types=[EvaluatorType.parse("RMSE")],
        )
        driver = GameTrainingDriver(params)
        driver.run()
        metrics = json.load(
            open(os.path.join(params.output_dir, "metrics.json"))
        )
        # mixed model must improve training objective monotonically and
        # beat the label-variance RMSE baseline on validation
        hist = metrics["objective_history"]
        assert hist[-1] <= hist[0]
        rmse = metrics["validation_history"][-1]["RMSE"]
        assert rmse < 1.4, metrics["validation_history"]


class TestPerEntityVariances:
    def test_variances_round_trip(self, tmp_path, rng):
        """--compute-variance writes per-entity variances into the saved
        BayesianLinearModelAvro records and they load back
        (RandomEffectOptimizationProblem.scala:106-127,
        ModelProcessingUtils.scala:44-189)."""
        train = tmp_path / "train"; train.mkdir()
        write_game_avro(str(train / "p0.avro"), rng, n=200)
        tparams = GameTrainingParams(
            train_input_dirs=[str(train)],
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=[
                FeatureShardConfiguration("g", ["features"]),
                FeatureShardConfiguration("u", ["userFeatures"]),
            ],
            fixed_effect_data_configs={
                "global": FixedEffectDataConfiguration("g")
            },
            fixed_effect_opt_configs={"global": "10,1e-6,0.1,1,LBFGS,L2"},
            random_effect_data_configs={
                "per-user": RandomEffectDataConfiguration("userId", "u")
            },
            random_effect_opt_configs={"per-user": "10,1e-6,1.0,1,LBFGS,L2"},
            num_iterations=1,
            compute_variance=True,
        )
        GameTrainingDriver(tparams).run()
        model_dir = os.path.join(tparams.output_dir, "best-model")
        recs = list(read_avro_records(
            os.path.join(model_dir, "random-effect", "per-user", "coefficients")
        ))
        assert len(recs) == 8
        for rec in recs:
            if not rec["means"]:
                continue
            assert rec["variances"] is not None
            # variance entries align with means, all positive
            assert [(m["name"], m["term"]) for m in rec["variances"]] == [
                (m["name"], m["term"]) for m in rec["means"]
            ]
            assert all(m["value"] > 0 for m in rec["variances"])
        model = load_game_model(model_dir)
        per_entity_vars = model.random_effect_variances["per-user"]
        _, _, per_entity = model.random_effects["per-user"]
        populated = [k for k, m in per_entity.items() if m]
        assert populated and set(per_entity_vars) >= set(populated)
        # fixed-effect side carries variances too (GLM compute path)
        fe = list(read_avro_records(
            os.path.join(model_dir, "fixed-effect", "global", "coefficients")
        ))
        assert fe[0]["variances"] is not None


class TestScoringOptionParity:
    def test_score_output_ids_num_files_and_model_id(self, tmp_path, rng):
        """random-effect-id-set ids ride along in metadataMap, --num-files
        splits the output, --game-model-id stamps every record
        (cli/game/scoring/Driver.scala:42,152; Params numOutputFilesForScores)."""
        train = tmp_path / "train"; train.mkdir()
        write_game_avro(str(train / "p0.avro"), rng, n=200)
        tparams = GameTrainingParams(
            train_input_dirs=[str(train)],
            output_dir=str(tmp_path / "out"),
            task_type=TaskType.LOGISTIC_REGRESSION,
            feature_shards=[
                FeatureShardConfiguration("g", ["features"]),
                FeatureShardConfiguration("u", ["userFeatures"]),
            ],
            fixed_effect_data_configs={
                "global": FixedEffectDataConfiguration("g")
            },
            fixed_effect_opt_configs={"global": "10,1e-6,0.1,1,LBFGS,L2"},
            random_effect_data_configs={
                "per-user": RandomEffectDataConfiguration("userId", "u")
            },
            random_effect_opt_configs={"per-user": "10,1e-6,1.0,1,LBFGS,L2"},
            num_iterations=1,
            num_output_files_for_random_effect_model=3,
        )
        GameTrainingDriver(tparams).run()
        model_dir = os.path.join(tparams.output_dir, "best-model")
        # RE coefficients split across 3 part files, loadable as one model
        parts = os.listdir(
            os.path.join(model_dir, "random-effect", "per-user", "coefficients")
        )
        assert sorted(parts) == [
            "part-00000.avro", "part-00001.avro", "part-00002.avro"
        ]
        model = load_game_model(model_dir)
        _, _, per_entity = model.random_effects["per-user"]
        assert len(per_entity) == 8  # all users survive the split

        from photon_ml_tpu.cli.game_scoring_driver import params_from_args

        sp = params_from_args([
            "--input-data-dirs", str(train),
            "--game-model-input-dir", model_dir,
            "--output-dir", str(tmp_path / "scores"),
            "--feature-shard-id-to-feature-section-keys-map",
            "g:features|u:userFeatures",
            "--game-model-id", "my-model-7",
            "--random-effect-id-set", "userId",
            "--num-files", "2",
        ])
        GameScoringDriver(sp).run()
        score_dir = tmp_path / "scores" / "scores"
        assert sorted(os.listdir(score_dir)) == [
            "part-00000.avro", "part-00001.avro"
        ]
        recs = list(read_avro_records(str(score_dir)))
        assert len(recs) == 200
        assert all(r["modelId"] == "my-model-7" for r in recs)
        assert all(
            r["metadataMap"]["userId"].startswith("user") for r in recs
        )


class TestFeatureShardedGameDriver:
    def test_distributed_feature_matches_off(self, tmp_path, rng):
        """--distributed feature: the GAME fixed effect trains
        feature-sharded over a (data, model) mesh inside coordinate
        descent and reproduces the single-device run (the reference's
        huge-dimension FE path, Driver.scala:357-363,717-719)."""
        import numpy as _np

        helper = TestGameTrainingEndToEnd()
        results = {}
        for mode, sub in (("feature", "fs"), ("off", "single")):
            root = tmp_path / sub
            root.mkdir()
            params = helper._params(
                root, _np.random.default_rng(7),  # same data both modes
                distributed=mode,
                model_shards=2 if mode == "feature" else None,
            )
            driver = GameTrainingDriver(params)
            driver.run()
            metrics = json.load(
                open(os.path.join(params.output_dir, "metrics.json"))
            )
            results[mode] = metrics
        h_fs = results["feature"]["objective_history"]
        h_off = results["off"]["objective_history"]
        _np.testing.assert_allclose(h_fs, h_off, rtol=1e-3)
        assert results["feature"]["validation_history"][-1]["AUC"] > 0.6
