"""Multi-device == single-device end-to-end equality.

The reference's distribution simulator is partitioned local-mode Spark
(SparkTestUtils.scala:27-70); ours is the 8-virtual-CPU-device mesh from
tests/conftest.py. Every test trains the same problem with and without the
mesh and asserts the results agree (fp32 reduction-order noise only).
"""

import numpy as np

import jax.numpy as jnp

from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.game import build_game_dataset
from photon_ml_tpu.game.coordinate import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
from photon_ml_tpu.game.random_effect import RandomEffectOptimizationProblem
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optim.problem import create_glm_problem
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.training import train_generalized_linear_model

from tests.test_game import SHARDS, make_records


def _logistic_batch(rng, n=203, d=40, k=5):
    w_true = rng.normal(size=d)
    rows, labels = [], []
    for _ in range(n):
        ix = rng.choice(d, size=k, replace=False)
        vs = rng.normal(size=k)
        z = float((w_true[ix] * vs).sum())
        labels.append(float(rng.uniform() < 1 / (1 + np.exp(-z))))
        rows.append((ix.tolist(), vs.tolist()))
    return make_sparse_batch(rows, labels), d


class TestDistributedGLMTraining:
    def test_mesh_matches_single_device(self, rng):
        batch, d = _logistic_batch(rng)
        kwargs = dict(regularization_weights=[1.0, 0.1], max_iter=30)
        m1, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, **kwargs
        )
        m2, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, mesh=make_mesh(), **kwargs
        )
        for lam in m1:
            np.testing.assert_allclose(
                np.asarray(m2[lam].coefficients.means),
                np.asarray(m1[lam].coefficients.means),
                atol=5e-3,
            )

    def test_mesh_row_padding_not_divisible(self, rng):
        # 203 rows over 8 devices exercises the pad-to-multiple path; the
        # single-device result is the oracle
        batch, d = _logistic_batch(rng, n=203)
        m1, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d,
            regularization_weights=[0.5],
        )
        m2, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d,
            regularization_weights=[0.5], mesh=make_mesh(),
        )
        np.testing.assert_allclose(
            np.asarray(m2[0.5].coefficients.means),
            np.asarray(m1[0.5].coefficients.means),
            atol=5e-3,
        )


class TestDistributedGame:
    def _coords(self, ds, mesh):
        fe_problem = create_glm_problem(
            TaskType.LOGISTIC_REGRESSION,
            ds.shards["globalShard"].dim,
            config=OptimizerConfig(max_iter=20),
            regularization=RegularizationContext(RegularizationType.L2),
        )
        re_problem = RandomEffectOptimizationProblem(
            LOGISTIC,
            OptimizerConfig(max_iter=20),
            RegularizationContext(RegularizationType.L2),
            reg_weight=1.0,
            mesh=mesh,
        )
        from photon_ml_tpu.game.random_effect_data import build_random_effect_dataset
        from photon_ml_tpu.game.config import RandomEffectDataConfiguration

        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfiguration(
                random_effect_type="userId", feature_shard_id="userShard"
            ),
        )
        return {
            "fixed": FixedEffectCoordinate(
                name="fixed",
                dataset=ds,
                problem=fe_problem,
                feature_shard_id="globalShard",
                reg_weight=0.5,
                mesh=mesh,
            ),
            "perUser": RandomEffectCoordinate(
                name="perUser", dataset=ds, re_dataset=red, problem=re_problem
            ),
        }

    def test_game_coordinate_descent_matches_single_device(self, rng):
        recs, _, _ = make_records(rng, n=150, n_users=8)
        ds = build_game_dataset(recs, SHARDS, ["userId"])

        results = {}
        for label, mesh in (("single", None), ("mesh", make_mesh())):
            cd = CoordinateDescent(
                self._coords(ds, mesh),
                ds,
                TaskType.LOGISTIC_REGRESSION,
                update_sequence=["fixed", "perUser"],
            )
            res = cd.run(2)
            results[label] = (
                np.asarray(res.model.get_model("fixed").model.means),
                np.asarray(res.model.get_model("perUser").bank),
                res.objective_history,
            )

        np.testing.assert_allclose(
            results["mesh"][0], results["single"][0], atol=5e-3
        )
        np.testing.assert_allclose(
            results["mesh"][1], results["single"][1], atol=5e-3
        )

    def test_entity_bank_sharding_exact(self, rng):
        """The RE bank solve is embarrassingly parallel: sharded and
        unsharded banks must agree per entity up to fp32 compilation noise
        (GSPMD partitions reductions differently; ~1e-4 after 15 L-BFGS
        iterations)."""
        recs, _, _ = make_records(rng, n=150, n_users=9)  # 9 % 8 != 0
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        from photon_ml_tpu.game.config import RandomEffectDataConfiguration
        from photon_ml_tpu.game.random_effect_data import build_random_effect_dataset

        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfiguration(
                random_effect_type="userId", feature_shard_id="userShard"
            ),
        )
        banks = {}
        variances = {}
        for label, mesh in (("single", None), ("mesh", make_mesh())):
            problem = RandomEffectOptimizationProblem(
                LOGISTIC,
                OptimizerConfig(max_iter=15),
                RegularizationContext(RegularizationType.L2),
                reg_weight=0.7,
                mesh=mesh,
            )
            bank0 = jnp.zeros((red.num_entities, red.local_dim), jnp.float32)
            bank, tracker, var = problem.update_bank(
                bank0, red, with_variances=True
            )
            assert tracker.num_entities == red.num_entities
            banks[label] = np.asarray(bank)
            variances[label] = np.asarray(var)
        np.testing.assert_allclose(banks["mesh"], banks["single"], atol=1e-3)
        # per-entity variances ride the same sharding (isComputingVariance
        # under the mesh): entity-for-entity agreement, all positive
        assert (variances["single"] > 0).all()
        np.testing.assert_allclose(
            variances["mesh"], variances["single"], rtol=2e-3, atol=1e-5
        )


class TestFeatureShardedGameFE:
    """The GAME fixed effect under a 2-D (data, model) mesh: the
    reference's huge-dimension FE (Driver.scala:357-363,717-719;
    "hundreds of billions of coefficients", README.md:73) composed into
    coordinate descent — must match the single-device CD exactly."""

    def _coords(self, ds, fe_mesh, re_mesh):
        from photon_ml_tpu.game.config import RandomEffectDataConfiguration
        from photon_ml_tpu.game.random_effect_data import (
            build_random_effect_dataset,
        )
        from photon_ml_tpu.optim.config import OptimizerType

        fe_problem = create_glm_problem(
            TaskType.LOGISTIC_REGRESSION,
            ds.shards["globalShard"].dim,
            config=OptimizerConfig(max_iter=20),
            regularization=RegularizationContext(RegularizationType.L2),
        )
        re_problem = RandomEffectOptimizationProblem(
            LOGISTIC,
            OptimizerConfig(max_iter=20),
            RegularizationContext(RegularizationType.L2),
            reg_weight=1.0,
            mesh=re_mesh,
        )
        red = build_random_effect_dataset(
            ds,
            RandomEffectDataConfiguration(
                random_effect_type="userId", feature_shard_id="userShard"
            ),
        )
        return {
            "fixed": FixedEffectCoordinate(
                name="fixed",
                dataset=ds,
                problem=fe_problem,
                feature_shard_id="globalShard",
                reg_weight=0.5,
                mesh=fe_mesh,
            ),
            "perUser": RandomEffectCoordinate(
                name="perUser", dataset=ds, re_dataset=red, problem=re_problem
            ),
        }

    def test_game_cd_with_sharded_fe_matches_single_device(self, rng):
        from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        recs, _, _ = make_records(rng, n=150, n_users=8)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        mesh2d = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))

        results = {}
        for label, fe_mesh, re_mesh in (
            ("single", None, None),
            ("sharded", mesh2d, make_mesh()),
        ):
            cd = CoordinateDescent(
                self._coords(ds, fe_mesh, re_mesh),
                ds,
                TaskType.LOGISTIC_REGRESSION,
                update_sequence=["fixed", "perUser"],
            )
            res = cd.run(2)
            results[label] = (
                np.asarray(res.model.get_model("fixed").model.means),
                np.asarray(res.model.get_model("perUser").bank),
                res.objective_history,
            )
        np.testing.assert_allclose(
            results["sharded"][0], results["single"][0], atol=5e-3
        )
        np.testing.assert_allclose(
            results["sharded"][1], results["single"][1], atol=5e-3
        )
        assert np.all(np.isfinite(results["sharded"][2]))

    def test_sharded_fe_tron_in_cd(self, rng):
        """TRON on the feature-sharded GAME fixed effect (the tiled/sparse
        Hv factory inside CD) matches the single-device TRON solve."""
        from photon_ml_tpu.optim.config import OptimizerType
        from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        recs, _, _ = make_records(rng, n=150, n_users=8)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        mesh2d = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        results = {}
        for label, mesh in (("single", None), ("sharded", mesh2d)):
            problem = create_glm_problem(
                TaskType.LOGISTIC_REGRESSION,
                ds.shards["globalShard"].dim,
                config=OptimizerConfig(
                    optimizer_type=OptimizerType.TRON, max_iter=15
                ),
                regularization=RegularizationContext(RegularizationType.L2),
            )
            coord = FixedEffectCoordinate(
                name="fixed", dataset=ds, problem=problem,
                feature_shard_id="globalShard", reg_weight=0.5, mesh=mesh,
            )
            model, _ = coord.update_model(coord.initialize_model())
            # second update from the first's warm start exercises the
            # cached layout + offsets-replacement path
            model, _ = coord.update_model(model)
            results[label] = np.asarray(model.model.means)
        np.testing.assert_allclose(
            results["sharded"], results["single"], atol=5e-3
        )

    def test_sharded_fe_down_sampling_matches_replicated(self, rng):
        """Down-sampling on the FEATURE-SHARDED fixed effect: the per-draw
        sampling weights are traced arguments against the cached sharded
        layout, so (same RNG key) sampled-sharded reproduces
        sampled-replicated — the round-5 guard and driver rejection are
        gone."""
        from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        recs, _, _ = make_records(rng, n=160, n_users=8)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        mesh2d = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        results = {}
        for label, mesh in (("single", None), ("sharded", mesh2d)):
            coord = FixedEffectCoordinate(
                name="fixed",
                dataset=ds,
                problem=create_glm_problem(
                    TaskType.LOGISTIC_REGRESSION,
                    ds.shards["globalShard"].dim,
                    config=OptimizerConfig(max_iter=25),
                    regularization=RegularizationContext(
                        RegularizationType.L2
                    ),
                ),
                feature_shard_id="globalShard",
                reg_weight=0.5,
                down_sampling_rate=0.6,
                sampler_seed=7,
                mesh=mesh,
            )
            model, _ = coord.update_model(coord.initialize_model())
            # second update exercises the cached-layout re-weighting path
            model, _ = coord.update_model(model)
            results[label] = np.asarray(model.model.means)
        # the dropped rows differ from the full-data fit — only an
        # identical draw sequence can make these match
        np.testing.assert_allclose(
            results["sharded"], results["single"], atol=5e-3
        )

    def test_layout_cached_across_coordinates(self, rng):
        """A combo grid builds fresh coordinates over the same dataset;
        the feature-sharded LAYOUT (the multi-second host re-layout) must
        be built once and shared, with results unchanged."""
        from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        recs, _, _ = make_records(rng, n=120, n_users=6)
        ds = build_game_dataset(recs, SHARDS, ["userId"])

        def coord(reg_weight):
            # a FRESH (content-identical) mesh per combo, exactly like
            # the driver's per-combo _fe_mesh() — the cache must hit on
            # mesh CONTENT, not object identity
            return FixedEffectCoordinate(
                name="fixed",
                dataset=ds,
                problem=create_glm_problem(
                    TaskType.LOGISTIC_REGRESSION,
                    ds.shards["globalShard"].dim,
                    config=OptimizerConfig(max_iter=15),
                    regularization=RegularizationContext(
                        RegularizationType.L2
                    ),
                ),
                feature_shard_id="globalShard",
                reg_weight=reg_weight,
                mesh=make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS)),
            )

        c1, c2 = coord(0.5), coord(2.0)
        m1, _ = c1.update_model(c1.initialize_model())
        m2, _ = c2.update_model(c2.initialize_model())
        cache = ds.__dict__["_fs_layout_cache"]
        assert len(cache) == 1  # one layout shared by both combos
        st1 = c1.__dict__["_fs_state"]
        st2 = c2.__dict__["_fs_state"]
        # same underlying per-entry arrays (identity, not equality) —
        # the layout was built once and shared
        assert (
            st1["sharded"].indices is st2["sharded"].indices
        )
        # stronger reg shrinks the solution
        w1 = np.asarray(m1.model.means)
        w2 = np.asarray(m2.model.means)
        assert np.linalg.norm(w2) < np.linalg.norm(w1)
