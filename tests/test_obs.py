"""Unified telemetry plane tests (ISSUE 13).

The acceptance bar: a routed request over a real 2-shard TCP fleet
produces ONE connected trace (router -> both shards' frontends ->
batcher dispatch) with exact parent/child nesting, exportable as Chrome
trace-event JSON; the frontend's ``{"op": "metrics"}`` serves a live
registry snapshot whose counters reconcile with the exit metrics.json;
the flight recorder's ring is bounded, dumps atomically on SIGTERM and
on swap/rollback transitions, and ``check_conservation()`` passes on a
fully-served batcher run and fails on an injected drop. The interleave
schedule family drives concurrent span emission + swap events + dumps:
no deadlocks, no torn dumps, sequence numbers strictly increasing.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from photon_ml_tpu.game.data import build_game_dataset
from photon_ml_tpu.obs import ObsSession
from photon_ml_tpu.obs.flight_recorder import (
    FlightRecorder,
    flight_recorder,
    reset_flight_recorder,
)
from photon_ml_tpu.obs.registry import (
    MetricsRegistry,
    SnapshotWriter,
)
from photon_ml_tpu.obs.trace import (
    PARENT_KEY,
    TRACE_KEY,
    NULL_SPAN,
    Tracer,
    chrome_trace_events,
    expand_spans,
    export_chrome_trace,
    start_span,
    tracer,
    tracing_enabled,
    tracing_scope,
    wire_context,
)
from photon_ml_tpu.serving import (
    MicroBatcher,
    ServingFrontend,
    ServingMetrics,
    ServingModel,
    ServingPrograms,
    requests_from_dataset,
)
from photon_ml_tpu.testing.interleave import InterleaveScheduler, explore
from tests.test_serving import (
    SHARDS,
    batch_reference_scores,
    make_bank,
    synth_model,
    synth_records,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- trace core ---------------------------------------------------------------


class TestTraceCore:
    def test_ring_is_bounded_and_drops_are_counted(self):
        t = Tracer(max_spans=8)
        for i in range(20):
            t.start(f"s{i}").end()
        assert len(t) == 8
        assert t.dropped == 12
        names = [s.name for s in t.snapshot()]
        assert names == [f"s{i}" for i in range(12, 20)]  # oldest evicted

    def test_disabled_tracing_is_free_and_silent(self):
        assert not tracing_enabled()
        t0 = len(tracer())
        s = start_span("noop")
        assert s is NULL_SPAN
        s.end()
        assert len(tracer()) == t0

    def test_span_nesting_ids_and_wire_context(self):
        t = Tracer()
        root = t.start("root")
        child = t.start(
            "child", trace_id=root.trace_id, parent_id=root.span_id
        )
        child.end()
        root.end()
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
        assert child.span_id != root.span_id
        obj = {TRACE_KEY: root.trace_id, PARENT_KEY: root.span_id}
        assert wire_context(obj) == (root.trace_id, root.span_id)
        assert wire_context({}) == (None, None)

    def test_chrome_export_is_atomic_valid_and_complete(self, tmp_path):
        t = Tracer()
        root = t.start("router.request", attrs={"uid": "r1"})
        t.start(
            "frontend.request",
            trace_id=root.trace_id,
            parent_id=root.span_id,
        ).end()
        root.end()
        path = str(tmp_path / "trace.json")
        n = export_chrome_trace(path, t.snapshot())
        assert n == 2
        data = json.load(open(path))
        evs = data["traceEvents"]
        assert all(e["ph"] == "X" for e in evs)
        assert all(e["dur"] > 0 for e in evs)
        by_name = {e["name"]: e for e in evs}
        assert (
            by_name["frontend.request"]["args"]["parent_span"]
            == by_name["router.request"]["args"]["span_id"]
        )
        assert (
            by_name["frontend.request"]["args"]["trace_id"]
            == by_name["router.request"]["args"]["trace_id"]
        )
        # an unfinished span never exports (no torn events)
        open_span = t.start("open")
        assert len(chrome_trace_events(t.snapshot())) == 2
        open_span.end()


# -- trace propagation over a real 2-shard TCP fleet -------------------------


class TestFleetTracePropagation:
    def test_one_connected_trace_per_routed_request(self, rng):
        """frontend-minted ids, carried on the wire, propagated by the
        router into every sub-request and by the shard's batcher into
        dispatch spans: every routed request yields ONE trace whose
        parent/child nesting is exactly router.request ->
        router.subrequest -> frontend.request -> serving.score."""
        from tests.test_shard_routing import (
            build_fleet,
            build_router,
            close_fleet,
        )

        recs = synth_records(rng, n=24)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        with tracing_scope(True):
            tracer().clear()
            servers = build_fleet(lm, ds, 2)
            router = None
            try:
                router = build_router(servers, lm, cache_entries=0)
                for rec in recs[:10]:
                    out = router.score_record(rec)
                    assert not out.degraded
            finally:
                close_fleet(servers, router)
            # expand batch-level dispatch spans into their per-request
            # serving.score leaves (the hot path records one span per
            # dispatch; the leaves materialize at export)
            spans = expand_spans(tracer().snapshot())
        by_trace = {}
        for s in spans:
            by_trace.setdefault(s.trace_id, []).append(s)
        roots = [s for s in spans if s.name == "router.request"]
        assert len(roots) == 10
        uids = {s.attrs["uid"] for s in roots}
        assert uids == {r["uid"] for r in recs[:10]}
        for root in roots:
            family = by_trace[root.trace_id]
            names = sorted(s.name for s in family)
            by_id = {s.span_id: s for s in family}
            # exactly one root, and it is parentless
            assert [s for s in family if s.parent_id is None] == [root]
            subs = [s for s in family if s.name == "router.subrequest"]
            fronts = [s for s in family if s.name == "frontend.request"]
            scores = [s for s in family if s.name == "serving.score"]
            assert subs and fronts and scores, names
            # nesting exact: sub -> root, front -> sub, score -> front
            for s in subs:
                assert s.parent_id == root.span_id
            for f in fronts:
                assert by_id[f.parent_id].name == "router.subrequest"
            for sc in scores:
                assert by_id[sc.parent_id].name == "frontend.request"
                assert sc.attrs["dispatch_span"]
            assert len(fronts) == len(subs)
            assert len(scores) == len(fronts)
            # every span in the family is reachable from the root
            for s in family:
                hop, seen = s, 0
                while hop.parent_id is not None and seen < 10:
                    hop = by_id[hop.parent_id]
                    seen += 1
                assert hop is root

    def test_every_dispatch_has_a_span(self, rng):
        """Trace completeness: dispatches counted by ServingMetrics ==
        serving.dispatch spans recorded by the batcher."""
        recs = synth_records(rng, n=16)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        bank = make_bank(synth_model(rng), ds)
        programs = ServingPrograms((1, 8))
        programs.ensure_compiled(bank)
        metrics = ServingMetrics()
        with tracing_scope(True):
            tracer().clear()
            with MicroBatcher(lambda: bank, programs, metrics) as mb:
                for r in requests_from_dataset(ds, bank):
                    mb.score(r)
            dispatch_spans = [
                s for s in tracer().snapshot()
                if s.name == "serving.dispatch"
            ]
        assert len(dispatch_spans) == metrics.snapshot()["dispatches"]
        assert all(
            s.attrs["generation"] == 1 and s.attrs["shape"] in (1, 8)
            for s in dispatch_spans
        )


# -- metrics registry ---------------------------------------------------------


class TestMetricsRegistry:
    def test_counters_gauges_histograms(self):
        r = MetricsRegistry()
        c = r.counter("reqs")
        c.inc()
        c.inc(2, shard="1")
        assert c.value() == 1
        assert c.value(shard="1") == 2
        assert c.total() == 3
        g = r.gauge("depth")
        g.set(4)
        g.set(7)
        assert g.value() == 7
        h = r.histogram("lat", bounds=(0.01, 0.1))
        h.observe(0.005)
        h.observe(0.05)
        h.observe(5.0)
        assert h.count() == 3
        snap = r.snapshot()["metrics"]
        assert snap["reqs"]["kind"] == "counter"
        assert snap["lat"]["values"][""]["buckets"] == [1, 1, 1]

    def test_same_name_same_instrument_kind_clash_raises(self):
        r = MetricsRegistry()
        assert r.counter("x") is r.counter("x")
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("x")

    def test_label_cardinality_is_capped(self):
        r = MetricsRegistry(max_label_sets=4)
        c = r.counter("leaky")
        for i in range(50):
            c.inc(uid=f"u{i}")  # a uid smuggled into a label
        series = c.series()
        assert len(series) <= 5  # 4 real + the overflow slot
        assert series[("__overflow__",)] == 46
        assert c.total() == 50  # nothing lost, resolution degraded

    def test_views_merge_and_failing_view_is_isolated(self):
        r = MetricsRegistry()
        r.register_view("ok_view", lambda: {"a": 1})

        def bad():
            raise RuntimeError("wedged subsystem")

        r.register_view("bad_view", bad)
        snap = r.snapshot()
        assert snap["ok_view"] == {"a": 1}
        assert snap["bad_view"] == {"error": "wedged subsystem"}

    def test_prometheus_text_exposition(self):
        r = MetricsRegistry()
        r.counter("reqs").inc(3, shard="0")
        r.histogram("lat", bounds=(0.5,)).observe(0.1)
        r.register_view("serving", lambda: {"dispatches": 7, "qps": 1.5})
        text = r.prometheus()
        assert "# TYPE reqs counter" in text
        assert 'reqs{shard="0"} 3' in text
        assert 'lat_bucket{le="0.5"} 1' in text
        assert 'lat_bucket{le="+Inf"} 1' in text
        assert "serving_dispatches 7" in text

    def test_histogram_bucket_lines_keep_their_label_set(self):
        """Satellite (ISSUE 15): two label sets of one histogram used
        to emit colliding unlabeled {le=...} bucket samples — buckets
        must merge the series labels with le, consistent with
        _count/_sum."""
        r = MetricsRegistry()
        h = r.histogram("lat", bounds=(0.5,))
        h.observe(0.1, shard="0")
        h.observe(0.9, shard="1")
        text = r.prometheus()
        assert 'lat_bucket{shard="0",le="0.5"} 1' in text
        assert 'lat_bucket{shard="0",le="+Inf"} 1' in text
        assert 'lat_bucket{shard="1",le="0.5"} 0' in text
        assert 'lat_bucket{shard="1",le="+Inf"} 1' in text
        # no unlabeled bucket line survives
        assert 'lat_bucket{le="' not in text

    def test_prometheus_exposition_is_well_formed(self):
        """Strict line-grammar check over a POPULATED registry (labeled
        histograms included): every sample parses, no duplicate sample
        name per label set, buckets cumulative and monotone, +Inf
        bucket == _count, _count/_sum label-consistent with their
        buckets."""
        import re

        r = MetricsRegistry()
        c = r.counter("reqs")
        c.inc(3)
        c.inc(2, shard="0")
        c.inc(7, shard="1", route="a")
        g = r.gauge("depth")
        g.set(4.5)
        g.set(2.0, shard="0")
        h = r.histogram("lat", bounds=(0.01, 0.1, 1.0))
        for v, n in ((0.005, 3), (0.05, 2), (0.5, 4), (5.0, 1)):
            for _ in range(n):
                h.observe(v)
                h.observe(v * 2, shard="1")
        r.register_view(
            "serving", lambda: {"dispatches": 7, "nested": {"qps": 1.5}}
        )
        text = r.prometheus()
        line_re = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
            r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")"
            r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*)\})?"
            r" (-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|inf))$"
        )
        samples = {}
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            m = line_re.match(line)
            assert m, f"malformed exposition line: {line!r}"
            name, labels, value = m.group(1), m.group(2) or "", m.group(3)
            key = (name, tuple(sorted(labels.split(","))) if labels
                   else ())
            assert key not in samples, (
                f"duplicate sample for {name} with labels {labels!r}"
            )
            samples[key] = float(value)
        # histogram invariants per label set: buckets cumulative and
        # monotone, +Inf == _count, _count/_sum present with the SAME
        # label set as their buckets
        by_series = {}
        for (name, labels), v in samples.items():
            if not name.startswith("lat_bucket"):
                continue
            le = next(p for p in labels if p.startswith('le="'))
            rest = tuple(p for p in labels if not p.startswith('le="'))
            by_series.setdefault(rest, []).append((le, v))
        assert len(by_series) == 2  # unlabeled + shard="1"
        for rest, buckets in by_series.items():
            order = {f'le="{b}"': i for i, b in
                     enumerate(("0.01", "0.1", "1.0", "+Inf"))}
            buckets.sort(key=lambda bv: order[bv[0]])
            values = [v for _le, v in buckets]
            assert values == sorted(values), (rest, values)
            count = samples[("lat_count", rest)]
            assert values[-1] == count, (rest, values, count)
            assert ("lat_sum", rest) in samples
        assert samples[("serving_dispatches", ())] == 7
        assert samples[("serving_nested_qps", ())] == 1.5

    def test_snapshot_writer_writes_atomically(self, tmp_path):
        r = MetricsRegistry()
        r.counter("n").inc(5)
        w = SnapshotWriter(r, str(tmp_path), period_s=0.05).start()
        time.sleep(0.2)
        w.stop()
        assert w.writes >= 1
        snap = json.load(open(tmp_path / "metrics_snapshot.json"))
        assert snap["metrics"]["n"]["values"][""] == 5


# -- the {"op": "metrics"} wire exposition ------------------------------------


class _Client:
    def __init__(self, port, timeout=15.0):
        self.sock = socket.create_connection(
            ("127.0.0.1", port), timeout=timeout
        )
        self.reader = self.sock.makefile("rb")

    def ask(self, obj):
        self.sock.sendall((json.dumps(obj) + "\n").encode())
        return json.loads(self.reader.readline())

    def close(self):
        try:
            self.reader.close()
            self.sock.close()
        except OSError:
            pass


@pytest.fixture
def obs_stack(rng):
    """frontend + batcher with a live metrics registry and a fresh
    flight recorder, torn down in drain order."""
    recs = synth_records(rng)
    ds = build_game_dataset(recs, SHARDS, ["userId"])
    lm = synth_model(rng)
    bank = make_bank(lm, ds)
    sm = ServingModel(bank, ServingPrograms((1, 8)))
    metrics = ServingMetrics()
    registry = MetricsRegistry()
    registry.register_view("serving", metrics.snapshot)
    rec = reset_flight_recorder()
    registry.register_view(
        "flight", lambda: {"conservation": rec.check_conservation()}
    )
    batcher = MicroBatcher(sm.current, sm.programs, metrics)
    fe = ServingFrontend(
        batcher, sm, SHARDS, metrics=metrics, port=0,
        metrics_registry=registry,
    ).start()
    yield recs, ds, lm, metrics, registry, fe
    fe.stop_accepting()
    batcher.drain(10.0)
    fe.close()
    batcher.close()


class TestMetricsOp:
    def test_live_snapshot_reconciles_with_exit_metrics_json(
        self, obs_stack, tmp_path
    ):
        recs, ds, lm, metrics, registry, fe = obs_stack
        ref = batch_reference_scores(lm, ds)
        c = _Client(fe.port)
        try:
            for i in range(8):
                resp = c.ask(recs[i])
                assert resp["status"] == "ok"
                assert np.float32(resp["score"]) == ref[i]
            live = c.ask({"op": "metrics", "uid": "m1"})
        finally:
            c.close()
        assert live["status"] == "ok" and live["uid"] == "m1"
        serving_live = live["metrics"]["serving"]
        assert serving_live["requests"] == 8
        assert live["metrics"]["flight"]["conservation"]["ok"]
        # the live op and the exit artifact are the SAME accumulator:
        # traffic has stopped, so every counter reconciles exactly
        # response accounting happens on the connection writer thread
        # AFTER the bytes go out — wait for it to settle (8 score
        # responses + the metrics-op reply) before comparing artifacts
        from tests.test_serving import _wait_until

        _wait_until(
            lambda: metrics.snapshot().get("responses", {}).get("ok", 0)
            >= 9,
        )
        out = str(tmp_path / "metrics.json")
        metrics.write(out)
        final = json.load(open(out))["serving"]
        for key in ("requests", "dispatches", "sheds",
                    "generation_dispatches"):
            assert final[key] == serving_live[key], key
        # the metrics-op reply is one more wire response than whatever
        # the live snapshot had seen at op time
        assert final["responses"]["ok"] >= serving_live.get(
            "responses", {}
        ).get("ok", 0)
        assert final["responses"]["ok"] == 9

    def test_prometheus_format_and_fallback(self, obs_stack, rng):
        recs, ds, lm, metrics, registry, fe = obs_stack
        c = _Client(fe.port)
        try:
            resp = c.ask({"op": "metrics", "format": "prometheus"})
            assert resp["status"] == "ok"
            assert "serving_requests" in resp["text"]
        finally:
            c.close()
        # a frontend WITHOUT a registry still answers (accumulator
        # fallback) — the op is always available
        bank = make_bank(lm, ds)
        sm2 = ServingModel(bank, ServingPrograms((1,)))
        m2 = ServingMetrics()
        b2 = MicroBatcher(sm2.current, sm2.programs, m2)
        fe2 = ServingFrontend(b2, sm2, SHARDS, metrics=m2, port=0).start()
        c2 = _Client(fe2.port)
        try:
            resp = c2.ask({"op": "metrics"})
            assert resp["status"] == "ok"
            assert "serving" in resp["metrics"]
            bad = c2.ask({"op": "metrics", "format": "prometheus"})
            assert bad["status"] == "error"
            assert bad["error"] == "BAD_REQUEST"
        finally:
            c2.close()
            fe2.stop_accepting()
            b2.drain(5.0)
            fe2.close()
            b2.close()

    def test_flight_op_serves_ring_and_conservation(self, obs_stack):
        recs, ds, lm, metrics, registry, fe = obs_stack
        flight_recorder().record("swap.commit", generation=2)
        c = _Client(fe.port)
        try:
            resp = c.ask({"op": "flight", "uid": "f1"})
        finally:
            c.close()
        assert resp["status"] == "ok" and resp["uid"] == "f1"
        kinds = [e["kind"] for e in resp["flight"]["events"]]
        assert "swap.commit" in kinds
        assert resp["conservation"]["ok"]
        # dump_flight without a configured path is a named refusal
        c = _Client(fe.port)
        try:
            resp = c.ask({"op": "dump_flight"})
        finally:
            c.close()
        assert resp["status"] == "error"
        assert resp["error"] == "BAD_REQUEST"


# -- flight recorder ----------------------------------------------------------


class TestFlightRecorder:
    def test_ring_bounds_and_counters_survive_eviction(self):
        rec = FlightRecorder(capacity=16)
        for i in range(100):
            rec.record("request.shed", i=i)
        snap = rec.snapshot()
        assert snap["retained"] == 16
        assert snap["recorded"] == 100
        assert snap["dropped"] == 84
        seqs = [e["seq"] for e in snap["events"]]
        assert seqs == list(range(85, 101))  # newest 16, ordered

    def test_conservation_positive_over_a_real_batcher_run(self, rng):
        recs = synth_records(rng, n=20)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        bank = make_bank(synth_model(rng), ds)
        programs = ServingPrograms((1, 8))
        programs.ensure_compiled(bank)
        rec = reset_flight_recorder()
        with MicroBatcher(lambda: bank, programs) as mb:
            for r in requests_from_dataset(ds, bank):
                mb.score(r)
        cons = rec.check_conservation()
        assert cons["ok"], cons
        assert cons["admitted"] == 20
        assert cons["terminal"] == {"ok": 20}
        assert cons["terminal_by_generation"] == {"1": 20}

    def test_conservation_negative_on_injected_drop(self, rng):
        recs = synth_records(rng, n=6)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        bank = make_bank(synth_model(rng), ds)
        programs = ServingPrograms((1, 8))
        programs.ensure_compiled(bank)
        rec = reset_flight_recorder()
        with MicroBatcher(lambda: bank, programs) as mb:
            for r in requests_from_dataset(ds, bank):
                mb.score(r)
        # the injected drop: an admitted request whose terminal outcome
        # never happened (the exact bug class the invariant exists for)
        rec.note_admitted()
        cons = rec.check_conservation()
        assert not cons["ok"]
        assert cons["in_flight"] == 1

    def test_conservation_conserved_across_swaps(self, rng):
        """Generation flips mid-traffic must not lose requests: the
        per-generation terminal split re-sums to admitted."""
        recs = synth_records(rng, n=16)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        bank = make_bank(lm, ds)
        sm = ServingModel(bank, ServingPrograms((1, 8)))
        rec = reset_flight_recorder()
        reqs = requests_from_dataset(ds, bank)
        with MicroBatcher(sm.current, sm.programs) as mb:
            for r in reqs[:8]:
                mb.score(r)
            sm.swap_to_bank(make_bank(lm, ds, device=False))
            for r in reqs[8:]:
                mb.score(r)
        cons = rec.check_conservation()
        assert cons["ok"], cons
        assert cons["admitted"] == 16
        assert cons["terminal_by_generation"] == {"1": 8, "2": 8}
        # the swap transition itself is on the ring
        kinds = [e["kind"] for e in rec.events("swap.")]
        assert "swap.commit" in kinds

    def test_auto_dump_on_swap_transition(self, tmp_path):
        rec = FlightRecorder(capacity=32)
        path = str(tmp_path / "flight.json")
        rec.set_auto_dump(path)
        rec.record("request.shed", reason="x")  # not a transition
        assert not os.path.exists(path)
        rec.record("swap.commit", generation=2)
        dump = json.load(open(path))
        kinds = [e["kind"] for e in dump["events"]]
        assert kinds == ["request.shed", "swap.commit"]

    def test_sigterm_dumps_atomically_then_terminates(self, tmp_path):
        """install_signal_dump chains the dump ONTO SIGTERM: the dump
        lands (valid, complete JSON) and the default disposition still
        terminates the process."""
        dump = str(tmp_path / "flight.json")
        script = (
            "import sys, time\n"
            "from photon_ml_tpu.obs.flight_recorder import ("
            "flight_recorder, install_signal_dump)\n"
            "rec = flight_recorder()\n"
            "rec.record('swap.commit', generation=2)\n"
            "rec.note_admitted(3)\n"
            "rec.note_terminal('ok', generation=2, n=3)\n"
            "install_signal_dump(sys.argv[1])\n"
            "print('READY', flush=True)\n"
            "while True:\n"
            "    time.sleep(0.05)\n"
        )
        p = subprocess.Popen(
            [sys.executable, "-c", script, dump],
            cwd=REPO, stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        try:
            assert p.stdout.readline().strip() == "READY"
            p.send_signal(signal.SIGTERM)
            p.wait(timeout=30)
        finally:
            if p.poll() is None:
                p.kill()
        assert p.returncode == -signal.SIGTERM
        data = json.load(open(dump))
        assert data["reason"] == f"signal {signal.SIGTERM}"
        kinds = [e["kind"] for e in data["events"]]
        assert "swap.commit" in kinds and "signal" in kinds
        assert data["conservation"]["ok"]

    def test_event_emitter_folds_into_the_recorder(self):
        """Satellite: ONE structured-event path — the legacy emitter's
        sends land on the flight ring, and the compat shim still
        exports everything."""
        from photon_ml_tpu import events as shim
        from photon_ml_tpu.obs import events as folded

        assert shim.EventEmitter is folded.EventEmitter
        assert shim.ScheduleCacheEvent is folded.ScheduleCacheEvent
        rec = reset_flight_recorder()
        seen = []

        class L(shim.EventListener):
            def on_event(self, e):
                seen.append(e)

        em = shim.EventEmitter()
        em.register(L())
        em.send(shim.TrainingStartEvent("job-1"))
        em.send(shim.PhotonOptimizationLogEvent(reg_weight=0.5))
        assert len(seen) == 2
        kinds = [e["kind"] for e in rec.events("event.")]
        assert kinds == [
            "event.TrainingStartEvent",
            "event.PhotonOptimizationLogEvent",
        ]
        ev = rec.events("event.TrainingStart")[0]
        assert ev["fields"]["job_name"] == "job-1"
        em.close()


# -- ObsSession ---------------------------------------------------------------


class TestObsSession:
    def test_disabled_session_noops(self):
        sess = ObsSession(None)
        assert not sess.enabled
        sess.record("swap.commit")
        assert sess.finish() is None

    def test_session_wires_views_and_exports_on_finish(self, tmp_path):
        from photon_ml_tpu.obs.registry import reset_default_registry
        from photon_ml_tpu.obs.trace import set_tracing, span

        reset_default_registry()
        reset_flight_recorder()
        obs_dir = str(tmp_path / "obs")
        sess = ObsSession(obs_dir, snapshot_period_s=60, signal_dump=False)
        try:
            assert tracing_enabled()
            with span("cd.iteration", iteration=1):
                pass
            sess.record("swap.commit", generation=2)
            summary = sess.finish()
        finally:
            set_tracing(False)
        assert summary["conservation"]["ok"]
        trace = json.load(open(summary["trace_path"]))
        assert any(
            e["name"] == "cd.iteration" for e in trace["traceEvents"]
        )
        flight = json.load(open(summary["flight_path"]))
        assert any(e["kind"] == "swap.commit" for e in flight["events"])
        snap = json.load(open(os.path.join(obs_dir, "metrics_snapshot.json")))
        for view in ("host_timings", "reliability", "readbacks", "flight"):
            assert view in snap, view
        assert sess.finish() is None  # idempotent


# -- interleave schedule family: span emit x swap x dump ---------------------


class TestObsInterleave:
    def _scenario(self, sched):
        rec = None
        t = None
        dumps = []

        def emitter(tag):
            def body():
                for i in range(10):
                    s = t.start(f"req.{tag}")
                    rec.record("request.shed", tag=tag, i=i)
                    s.end()
            return body

        def swapper():
            for g in (2, 3):
                rec.record("swap.commit", generation=g)
                rec.note_admitted(2)
                rec.note_terminal("ok", generation=g, n=2)

        def dumper():
            for _ in range(4):
                snap = rec.snapshot()
                dumps.append(snap)

        with sched.patched():
            # recorder/tracer constructed in the patched window: their
            # locks are cooperative, so the scheduler owns every
            # preemption point
            rec = FlightRecorder(capacity=64)
            t = Tracer(max_spans=256)
            sched.spawn(emitter("a"), name="emit-a")
            sched.spawn(emitter("b"), name="emit-b")
            sched.spawn(swapper, name="swap")
            sched.spawn(dumper, name="dump")

        def verify():
            # no torn dumps: every snapshot's sequence numbers are
            # strictly increasing and consistent with its own count
            for snap in dumps:
                seqs = [e["seq"] for e in snap["events"]]
                assert seqs == sorted(seqs)
                assert len(set(seqs)) == len(seqs)
                assert snap["retained"] == len(snap["events"])
            final = rec.snapshot()
            assert final["recorded"] == 22  # 2x10 sheds + 2 swaps
            assert rec.check_conservation()["ok"]
            assert len(t) == 20

        return verify

    def test_span_emit_swap_dump_schedules(self):
        explore(self._scenario, seeds=range(25))
