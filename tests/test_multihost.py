"""Multi-host scaffold tests: single-process semantics inline, plus a
subprocess smoke test that actually joins a 1-process jax.distributed
coordination service and runs the GLM driver under it (the CPU analog of
SparkContextConfiguration.asYarnClient boot, SURVEY §7.11)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from photon_ml_tpu.parallel.multihost import (
    coordinator_only,
    initialize_multihost,
    is_coordinator,
    process_count,
    process_shard,
    sync_processes,
)


class TestSingleProcessSemantics:
    def test_no_coordinator_is_noop(self):
        assert initialize_multihost(None) is False

    def test_single_process_identity(self):
        assert process_count() == 1
        assert is_coordinator()
        assert process_shard([1, 2, 3]) == [1, 2, 3]
        sync_processes("noop")  # must not hang or require a service

    def test_coordinator_only_runs(self):
        calls = []

        @coordinator_only
        def write(x):
            calls.append(x)
            return x

        assert write(7) == 7
        assert calls == [7]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
class TestOneProcessDistributedSmoke:
    def test_glm_driver_under_coordination_service(self, tmp_path, rng):
        """Boot jax.distributed with num_processes=1 in a subprocess and run
        the full GLM driver with --coordinator-address; output must appear
        exactly as in the plain single-process run."""
        sys.path.insert(0, os.path.dirname(__file__))
        from test_glm_driver import synth_avro

        train = tmp_path / "train"
        train.mkdir()
        synth_avro(str(train / "p0.avro"), rng, n=150)
        out = tmp_path / "out"
        port = _free_port()
        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            from photon_ml_tpu.cli.glm_driver import main
            main([
                "--training-data-directory", {str(train)!r},
                "--output-directory", {str(out)!r},
                "--regularization-weights", "1.0",
                "--coordinator-address", "127.0.0.1:{port}",
                "--num-processes", "1",
                "--process-id", "0",
            ])
            import photon_ml_tpu.parallel.multihost as mh
            assert mh.process_count() == 1 and mh.is_coordinator()
            assert mh._initialized
        """)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "-c", script],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env,
            capture_output=True,
            text=True,
            timeout=420,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        with open(out / "metrics.json") as f:
            metrics = json.load(f)
        assert "timers" in metrics
        assert (out / "models-text").is_dir()
