"""Multi-host scaffold tests: single-process semantics inline, plus a
subprocess smoke test that actually joins a 1-process jax.distributed
coordination service and runs the GLM driver under it (the CPU analog of
SparkContextConfiguration.asYarnClient boot, SURVEY §7.11)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from photon_ml_tpu.parallel.multihost import (
    coordinator_only,
    initialize_multihost,
    is_coordinator,
    process_count,
    process_shard,
    sync_processes,
)


class TestSingleProcessSemantics:
    def test_no_coordinator_is_noop(self):
        assert initialize_multihost(None) is False

    def test_single_process_identity(self):
        assert process_count() == 1
        assert is_coordinator()
        assert process_shard([1, 2, 3]) == [1, 2, 3]
        sync_processes("noop")  # must not hang or require a service

    def test_coordinator_only_runs(self):
        calls = []

        @coordinator_only
        def write(x):
            calls.append(x)
            return x

        assert write(7) == 7
        assert calls == [7]


class TestShardAssignmentContract:
    """process_shard's assignment must be CONTENT-keyed: stable under
    item reordering across processes (a filesystem listing order that
    differs between hosts must not change any item's owner), disjoint,
    and covering. Entity-hash sharding (game/pod.py) and the streaming
    input split both rely on exactly this contract."""

    def test_stable_under_reordering(self):
        from photon_ml_tpu.parallel.multihost import shard_assignment

        items = [f"part-{i:05d}.avro" for i in range(64)]
        n = 4
        owners = {x: shard_assignment(x, n) for x in items}
        import random

        shuffled = list(items)
        random.Random(123).shuffle(shuffled)
        assert {x: shard_assignment(x, n) for x in shuffled} == owners

    def test_disjoint_and_covering(self, monkeypatch):
        import photon_ml_tpu.parallel.multihost as mh

        items = [f"day={d}/part-{i}.avro" for d in range(4) for i in range(8)]
        n = 3
        monkeypatch.setattr(mh, "process_count", lambda: n)
        shards = []
        for pid in range(n):
            monkeypatch.setattr(mh, "process_index", lambda pid=pid: pid)
            shards.append(mh.process_shard(items))
        flat = [x for s in shards for x in s]
        assert sorted(flat) == sorted(items)  # covering, no double-reads
        assert len(set(flat)) == len(items)  # disjoint

    def test_reordered_lists_agree_per_process(self, monkeypatch):
        """The actual multi-host failure mode the fix closes: process 0
        enumerates the list in one order, process 1 in another. Every
        item must still have exactly one owner."""
        import random

        import photon_ml_tpu.parallel.multihost as mh

        items = [f"f{i}" for i in range(40)]
        reordered = list(items)
        random.Random(7).shuffle(reordered)
        monkeypatch.setattr(mh, "process_count", lambda: 2)
        monkeypatch.setattr(mh, "process_index", lambda: 0)
        shard0 = set(mh.process_shard(items))
        monkeypatch.setattr(mh, "process_index", lambda: 1)
        shard1 = set(mh.process_shard(reordered))  # DIFFERENT order
        assert shard0 | shard1 == set(items)
        assert not (shard0 & shard1)

    def test_stability_as_the_list_grows(self):
        """Appending new items never re-homes existing ones (the daily
        incremental-input case): owners are per-item, not positional."""
        from photon_ml_tpu.parallel.multihost import shard_assignment

        base = [f"part-{i}" for i in range(20)]
        owners = {x: shard_assignment(x, 4) for x in base}
        grown = base + [f"part-{i}" for i in range(20, 40)]
        assert {x: shard_assignment(x, 4) for x in grown if x in owners} == owners


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
class TestOneProcessDistributedSmoke:
    def test_glm_driver_under_coordination_service(self, tmp_path, rng):
        """Boot jax.distributed with num_processes=1 in a subprocess and run
        the full GLM driver with --coordinator-address; output must appear
        exactly as in the plain single-process run."""
        sys.path.insert(0, os.path.dirname(__file__))
        from test_glm_driver import synth_avro

        train = tmp_path / "train"
        train.mkdir()
        synth_avro(str(train / "p0.avro"), rng, n=150)
        out = tmp_path / "out"
        port = _free_port()
        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            from photon_ml_tpu.cli.glm_driver import main
            main([
                "--training-data-directory", {str(train)!r},
                "--output-directory", {str(out)!r},
                "--regularization-weights", "1.0",
                "--coordinator-address", "127.0.0.1:{port}",
                "--num-processes", "1",
                "--process-id", "0",
            ])
            import photon_ml_tpu.parallel.multihost as mh
            assert mh.process_count() == 1 and mh.is_coordinator()
            assert mh._initialized
        """)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "-c", script],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env,
            capture_output=True,
            text=True,
            timeout=420,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        with open(out / "metrics.json") as f:
            metrics = json.load(f)
        assert "timers" in metrics
        assert (out / "models-text").is_dir()


def _run_two_processes(script_fn, timeout=420):
    """Spawn both ranks, reap them even on timeout/failure, and assert
    both exited 0. ``script_fn(pid)`` -> the python source for one rank."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script_fn(pid)],
            cwd=cwd, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=timeout) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, (_, se) in zip(procs, outs):
        assert p.returncode == 0, se[-3000:]


@pytest.mark.slow
class TestTwoProcessDistributed:
    def test_glm_driver_two_processes(self, tmp_path, rng):
        """A REAL 2-process run: both processes join the coordination
        service, the mesh spans both hosts' CPU devices, the data-parallel
        fit psums across the process boundary, and only the coordinator
        writes outputs. Trained coefficients must match the plain
        single-process fit (same data, same lambda)."""
        sys.path.insert(0, os.path.dirname(__file__))
        from test_glm_driver import synth_avro

        train = tmp_path / "train"
        train.mkdir()
        synth_avro(str(train / "p0.avro"), rng, n=160)
        out = tmp_path / "out"
        port = _free_port()

        def script(pid):
            return textwrap.dedent(f"""
                import jax
                jax.config.update("jax_platforms", "cpu")
                from photon_ml_tpu.cli.glm_driver import main
                main([
                    "--training-data-directory", {str(train)!r},
                    "--output-directory", {str(out)!r},
                    "--regularization-weights", "1.0",
                    "--coordinator-address", "127.0.0.1:{port}",
                    "--num-processes", "2",
                    "--process-id", "{pid}",
                ])
                import jax as j
                assert j.process_count() == 2, j.process_count()
            """)

        _run_two_processes(script)

        # coordinator wrote the outputs exactly once
        with open(out / "metrics.json") as f:
            json.load(f)
        assert (out / "models-text").is_dir()

        # 2-process coefficients match a plain single-process fit
        from photon_ml_tpu.cli.glm_driver import GLMDriver, GLMParams
        from photon_ml_tpu.io.model_io import load_glm_models_avro
        from photon_ml_tpu.utils.index_map import IndexMap

        single_out = tmp_path / "single"
        GLMDriver(GLMParams(
            train_dir=str(train),
            output_dir=str(single_out),
            regularization_weights=[1.0],
            distributed="off",
        )).run()
        imap = IndexMap.load(str(single_out / "feature-index" / "index.json"))
        two = load_glm_models_avro(str(out / "models" / "models.avro"), imap)
        one = load_glm_models_avro(
            str(single_out / "models" / "models.avro"), imap
        )
        import numpy as np

        w2 = np.asarray(two["1.0"].means)
        w1 = np.asarray(one["1.0"].means)
        np.testing.assert_allclose(w2, w1, rtol=2e-3, atol=2e-4)

    def test_game_driver_two_processes(self, tmp_path, rng):
        """2-process GAME training: fixed-effect solves psum across the
        process boundary and entity banks shard over the global mesh;
        the coordinate-descent objective must decrease and the saved
        model must be written once."""
        sys.path.insert(0, os.path.dirname(__file__))
        from test_game_drivers import write_game_avro

        train = tmp_path / "train"
        train.mkdir()
        write_game_avro(str(train / "p0.avro"), rng, n=160)
        out = tmp_path / "out"
        port = _free_port()

        def script(pid):
            return textwrap.dedent(f"""
                import jax
                jax.config.update("jax_platforms", "cpu")
                from photon_ml_tpu.cli.game_training_driver import main
                main([
                    "--train-input-dirs", {str(train)!r},
                    "--output-dir", {str(out)!r},
                    "--feature-shard-id-to-feature-section-keys-map",
                    "g:features|u:userFeatures",
                    "--fixed-effect-data-configurations", "global:g",
                    "--fixed-effect-optimization-configurations",
                    "global:10,1e-6,0.1,1,LBFGS,L2",
                    "--random-effect-data-configurations",
                    "per-user:userId,u,1,none,none,none,index_map",
                    "--random-effect-optimization-configurations",
                    "per-user:10,1e-6,1.0,1,LBFGS,L2",
                    "--updating-sequence", "global,per-user",
                    "--num-iterations", "2",
                    "--coordinator-address", "127.0.0.1:{port}",
                    "--num-processes", "2",
                    "--process-id", "{pid}",
                ])
            """)

        _run_two_processes(script)
        with open(out / "metrics.json") as f:
            metrics = json.load(f)
        hist = metrics["objective_history"]
        assert len(hist) == 2 and hist[-1] <= hist[0]
        assert os.path.isdir(out / "best-model" / "random-effect" / "per-user")


@pytest.mark.slow
class TestTwoProcessStreaming:
    def test_streaming_glm_two_processes(self, tmp_path, rng):
        """Multi-host >RAM streaming: the input FILES split across the two
        processes (process_shard) and every evaluation's (value, gradient)
        partials reduce across hosts, so each rank only reads its shard.
        Coefficients must match a single-process streaming fit over the
        full file set."""
        sys.path.insert(0, os.path.dirname(__file__))
        from test_streaming import _write_files

        train = tmp_path / "train"
        train.mkdir()
        _write_files(train, rng, n_files=4, rows_per_file=90)
        port = _free_port()

        def script(pid):
            return textwrap.dedent(f"""
                import jax
                jax.config.update("jax_platforms", "cpu")
                import numpy as np
                from photon_ml_tpu.parallel.multihost import (
                    initialize_multihost,
                )
                initialize_multihost("127.0.0.1:{port}", 2, {pid})
                assert jax.process_count() == 2
                from photon_ml_tpu.io.input_format import AvroInputDataFormat
                from photon_ml_tpu.io.streaming import scan_stream
                from photon_ml_tpu.optim.config import RegularizationType
                from photon_ml_tpu.task import TaskType
                from photon_ml_tpu.training import train_streaming_glm

                fmt = AvroInputDataFormat()
                # shared vocabulary: both ranks scan the full file set
                # (stands in for the offheap FeatureIndexingJob store)
                index_map, _ = scan_stream([{str(train)!r}], fmt)
                models, results, _ = train_streaming_glm(
                    [{str(train)!r}], TaskType.LOGISTIC_REGRESSION,
                    regularization_type=RegularizationType.L2,
                    regularization_weights=[0.5],
                    max_iter=25,
                    fmt=fmt,
                    index_map=index_map,
                )
                if jax.process_index() == 0:
                    np.save(
                        {str(tmp_path / "w2proc.npy")!r},
                        np.asarray(models[0.5].coefficients.means),
                    )
            """)

        _run_two_processes(script)

        from photon_ml_tpu.optim.config import RegularizationType
        from photon_ml_tpu.task import TaskType
        from photon_ml_tpu.training import train_streaming_glm

        models, _, _ = train_streaming_glm(
            [str(train)], TaskType.LOGISTIC_REGRESSION,
            regularization_type=RegularizationType.L2,
            regularization_weights=[0.5],
            max_iter=25,
        )
        import numpy as np

        w2 = np.load(tmp_path / "w2proc.npy")
        w1 = np.asarray(models[0.5].coefficients.means)
        np.testing.assert_allclose(w2, w1, rtol=2e-3, atol=2e-4)


@pytest.mark.slow
class TestTwoProcessStreamingSummary:
    def test_streamed_summary_two_processes(self, tmp_path, rng):
        """Multi-host streamed colStats: each process scans only ITS file
        shard and moments all-reduce — the result must equal the
        single-process summary over the full set (double-counting every
        moment by the process count is the failure this pins)."""
        sys.path.insert(0, os.path.dirname(__file__))
        from test_streaming import _write_files

        train = tmp_path / "train"
        train.mkdir()
        _write_files(train, rng, n_files=4, rows_per_file=60)
        port = _free_port()

        def script(pid):
            return textwrap.dedent(f"""
                import jax
                jax.config.update("jax_platforms", "cpu")
                import numpy as np
                from photon_ml_tpu.parallel.multihost import (
                    initialize_multihost, process_shard,
                )
                initialize_multihost("127.0.0.1:{port}", 2, {pid})
                from photon_ml_tpu.io.input_format import AvroInputDataFormat
                from photon_ml_tpu.io.streaming import (
                    scan_stream, shard_avro_files, streaming_summary,
                )

                fmt = AvroInputDataFormat()
                index_map, stats = scan_stream([{str(train)!r}], fmt)
                files = shard_avro_files([{str(train)!r}])
                summary, _ = streaming_summary(
                    files, fmt, index_map, stats
                )
                if jax.process_index() == 0:
                    np.savez(
                        {str(tmp_path / "summary2.npz")!r},
                        mean=np.asarray(summary.mean),
                        variance=np.asarray(summary.variance),
                        count=np.asarray(summary.count),
                        nnz=np.asarray(summary.num_nonzeros),
                        mx=np.asarray(summary.max),
                        mn=np.asarray(summary.min),
                    )
            """)

        _run_two_processes(script)

        from photon_ml_tpu.io.input_format import AvroInputDataFormat
        from photon_ml_tpu.io.streaming import scan_stream, streaming_summary

        fmt = AvroInputDataFormat()
        index_map, stats = scan_stream([str(train)], fmt)
        ref, _ = streaming_summary([str(train)], fmt, index_map, stats)
        import numpy as np

        got = np.load(tmp_path / "summary2.npz")
        assert int(got["count"]) == int(ref.count)
        np.testing.assert_allclose(got["mean"], np.asarray(ref.mean), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got["variance"], np.asarray(ref.variance), rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(got["nnz"], np.asarray(ref.num_nonzeros))
        np.testing.assert_allclose(got["mx"], np.asarray(ref.max), atol=1e-6)
        np.testing.assert_allclose(got["mn"], np.asarray(ref.min), atol=1e-6)
