"""Tiled kernel x mesh composition: the fast kernel and data parallelism
run TOGETHER (the reference's hot loop is simultaneously fast and
distributed — ValueAndGradientAggregator.scala:235-250; round 2 fell back
to the scatter objective under a mesh).

All tests run the Pallas kernels in interpret mode on the virtual 8-device
CPU mesh from tests/conftest.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from photon_ml_tpu.data.batch import make_sparse_batch
from photon_ml_tpu.ops.losses import LOGISTIC
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.tiled_sparse import (
    TileParams,
    TiledGLMObjective,
    build_sharded_tiled_batch,
    ensure_tiled_sharded,
)
from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh
from photon_ml_tpu.task import TaskType
from photon_ml_tpu.training import train_generalized_linear_model

PARAMS = TileParams(s_hi=8, s_lo=8, chunk=32)  # window 64, tiny for tests


def random_problem(rng, n=203, d=150, k=6):
    rows, labels = [], []
    for _ in range(n):
        nnz = int(rng.integers(1, k + 1))
        ix = rng.choice(d, size=nnz, replace=False).tolist()
        vs = rng.normal(size=nnz).tolist()
        labels.append(float(rng.uniform() > 0.5))
        rows.append((ix, vs))
    return make_sparse_batch(rows, labels, weights=rng.uniform(0.5, 2.0, n)), d


class TestShardedTiledBatch:
    def test_leaf_shapes_stack_per_shard(self, rng):
        batch, d = random_problem(rng)
        n_shards = 4
        tb = build_sharded_tiled_batch(
            batch, d, n_shards, params=PARAMS
        )
        assert tb.meta.data_shards == n_shards
        # per-shard static views divide every leaf's leading axis
        assert tb.labels.shape[0] == n_shards * tb.meta.num_rows
        assert tb.z_sched.step_out.shape[0] % n_shards == 0
        assert tb.g_sched.step_out.shape[0] % n_shards == 0
        assert tb.z_sched.out_pos.shape[0] % n_shards == 0
        # every nonzero entry appears once per schedule (chunk slots +
        # spill tail), across all shards
        nnz = int(np.count_nonzero(np.asarray(batch.values)))
        assert (
            np.count_nonzero(np.asarray(tb.z_sched.vals))
            + np.count_nonzero(np.asarray(tb.z_sched.spill_vals))
        ) == nnz
        assert (
            np.count_nonzero(np.asarray(tb.g_sched.vals))
            + np.count_nonzero(np.asarray(tb.g_sched.spill_vals))
        ) == nnz

    def test_per_shard_blocks_monotone(self, rng):
        batch, d = random_problem(rng)
        n_shards = 4
        tb = build_sharded_tiled_batch(batch, d, n_shards, params=PARAMS)
        gz = tb.z_sched.step_out.shape[0] // n_shards
        gg = tb.g_sched.step_out.shape[0] // n_shards
        for s in range(n_shards):
            z_out = np.asarray(tb.z_sched.step_out[s * gz:(s + 1) * gz])
            g_out = np.asarray(tb.g_sched.step_out[s * gg:(s + 1) * gg])
            assert np.all(np.diff(z_out) >= 0)
            assert np.all(np.diff(g_out) >= 0)

    def test_value_and_gradient_matches_scatter(self, rng):
        batch, d = random_problem(rng)
        mesh = make_mesh()
        n_shards = int(mesh.shape[DATA_AXIS])
        tb = build_sharded_tiled_batch(
            batch, d, n_shards, params=PARAMS, mesh=mesh
        )
        obj = TiledGLMObjective(
            LOGISTIC, d, axis_name=DATA_AXIS, interpret=True
        )
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))

        @jax.jit
        @lambda f: shard_map(
            f, mesh=mesh, in_specs=(P(), P(DATA_AXIS), P()),
            out_specs=(P(), P()), check_vma=False,
        )
        def vg(w, b, l2):
            return obj.value_and_gradient(w, b, l2)

        value, grad = vg(w, tb, jnp.float32(0.3))
        oracle = GLMObjective(LOGISTIC, d)
        ov, og = oracle.value_and_gradient(w, batch, jnp.float32(0.3))
        np.testing.assert_allclose(float(value), float(ov), rtol=2e-4)
        np.testing.assert_allclose(
            np.asarray(grad), np.asarray(og), rtol=3e-3, atol=3e-5
        )

    def test_hessian_vector_matches_scatter(self, rng):
        batch, d = random_problem(rng, n=97)
        mesh = make_mesh()
        tb = ensure_tiled_sharded(batch, d, mesh, params=PARAMS)
        obj = TiledGLMObjective(
            LOGISTIC, d, axis_name=DATA_AXIS, interpret=True
        )
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v = jnp.asarray(rng.normal(size=d).astype(np.float32))

        @jax.jit
        @lambda f: shard_map(
            f, mesh=mesh, in_specs=(P(), P(), P(DATA_AXIS), P()),
            out_specs=P(), check_vma=False,
        )
        def hv(w, v, b, l2):
            return obj.hessian_vector(w, v, b, l2)

        got = hv(w, v, tb, jnp.float32(0.1))
        oracle = GLMObjective(LOGISTIC, d)
        want = oracle.hessian_vector(w, v, batch, jnp.float32(0.1))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=3e-3, atol=3e-5
        )

    def test_ensure_idempotent(self, rng):
        batch, d = random_problem(rng)
        mesh = make_mesh()
        tb = ensure_tiled_sharded(batch, d, mesh, params=PARAMS)
        tb2 = ensure_tiled_sharded(tb, d, mesh, params=PARAMS)
        assert tb2 is tb

    def test_schedule_cache_across_fresh_wrappers(self, rng):
        """A fresh SparseBatch sharing indices/values/weights with a prior
        call (the GAME CD pattern: only offsets change per sweep) reuses
        the cached schedules — no rebuild — while the new offsets land in
        the returned batch."""
        batch, d = random_problem(rng)
        mesh = make_mesh()
        tb = ensure_tiled_sharded(batch, d, mesh, params=PARAMS)
        shifted = batch._replace(offsets=batch.offsets + 1.0)
        tb2 = ensure_tiled_sharded(shifted, d, mesh, params=PARAMS)
        assert tb2.z_sched.vals is tb.z_sched.vals  # schedules reused
        n = batch.labels.shape[0]
        np.testing.assert_allclose(
            np.asarray(tb2.offsets)[:n], np.asarray(batch.offsets) + 1.0
        )
        # different values array -> genuine rebuild
        scaled = batch._replace(values=batch.values * 2.0)
        tb3 = ensure_tiled_sharded(scaled, d, mesh, params=PARAMS)
        assert tb3.z_sched.vals is not tb.z_sched.vals

    def test_shard_count_mismatch_raises(self, rng):
        batch, d = random_problem(rng)
        mesh = make_mesh()
        tb = build_sharded_tiled_batch(batch, d, 2, params=PARAMS)
        with pytest.raises(ValueError, match="laid out for 2"):
            ensure_tiled_sharded(tb, d, mesh)


class TestFeatureShardedTiled:
    def test_matches_replicated_lbfgs(self, rng):
        # 10B-coef layout on the fast kernel: 2-D (data=4, model=2) mesh,
        # tiled block-local schedules vs the plain replicated fit
        from photon_ml_tpu.ops.tiled_sparse import feature_shard_tiled_batch
        from photon_ml_tpu.parallel.distributed import (
            feature_sharded_tiled_fit,
        )
        from photon_ml_tpu.parallel.mesh import MODEL_AXIS

        n, d, k = 120, 100, 5
        w_true = rng.normal(size=d)
        rows, labels = [], []
        for _ in range(n):
            ix = rng.choice(d, size=k, replace=False)
            vs = rng.normal(size=k)
            z = float((w_true[ix] * vs).sum())
            labels.append(float(rng.uniform() < 1 / (1 + np.exp(-z))))
            rows.append((ix.tolist(), vs.tolist()))
        batch = make_sparse_batch(rows, labels)
        mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        sharded, block_dim = feature_shard_tiled_batch(
            batch, d, 4, 2, params=PARAMS, mesh=mesh
        )
        obj = GLMObjective(LOGISTIC, d)
        fit = feature_sharded_tiled_fit(
            obj, mesh, sharded.meta, max_iter=25, interpret=True
        )
        res = fit(
            jnp.zeros(2 * block_dim, jnp.float32), sharded, jnp.float32(0.5)
        )
        # oracle: plain single-device L-BFGS on the scatter objective
        from photon_ml_tpu.optim.lbfgs import minimize_lbfgs

        oracle = minimize_lbfgs(
            lambda w: obj.value_and_gradient(w, batch, jnp.float32(0.5)),
            jnp.zeros(d, jnp.float32), max_iter=25,
        )
        np.testing.assert_allclose(
            np.asarray(res.coefficients)[:d],
            np.asarray(oracle.coefficients),
            atol=5e-3,
        )
        np.testing.assert_allclose(
            float(res.value), float(oracle.value), rtol=1e-4
        )

    @pytest.mark.parametrize("kernel", ["scatter", "tiled"])
    def test_feature_sharded_tron_matches_replicated(self, rng, kernel):
        # sharded trust-region Newton: every CG inner product psums over
        # the model axis (the treeAggregate-per-CG-iteration loop on ICI).
        # kernel="tiled" runs the Pallas z/g schedules for BOTH the
        # objective and the Hv factory (tiled_block_local_hvp_factory).
        from photon_ml_tpu.optim.config import OptimizerType, RegularizationType
        from photon_ml_tpu.optim.tron import minimize_tron
        from photon_ml_tpu.ops.objective import GLMObjective as _G
        from photon_ml_tpu.parallel.mesh import MODEL_AXIS
        from photon_ml_tpu.training import train_feature_sharded

        n, d, k = 120, 64, 5
        w_true = rng.normal(size=d)
        rows, labels = [], []
        for _ in range(n):
            ix = rng.choice(d, size=k, replace=False)
            vs = rng.normal(size=k)
            z = float((w_true[ix] * vs).sum())
            labels.append(float(rng.uniform() < 1 / (1 + np.exp(-z))))
            rows.append((ix.tolist(), vs.tolist()))
        batch = make_sparse_batch(rows, labels)
        mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        models, results = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d,
            mesh=mesh,
            regularization_type=RegularizationType.L2,
            regularization_weights=[0.5],
            max_iter=12,
            tolerance=1e-5,
            optimizer_type=OptimizerType.TRON,
            kernel=kernel,
        )
        obj = _G(LOGISTIC, d)
        oracle = minimize_tron(
            lambda w: obj.value_and_gradient(w, batch, jnp.float32(0.5)),
            lambda w, dd: obj.hessian_vector(w, dd, batch, jnp.float32(0.5)),
            jnp.zeros(d, jnp.float32), max_iter=12, tol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(models[0.5].coefficients.means),
            np.asarray(oracle.coefficients),
            atol=5e-3,
        )
        np.testing.assert_allclose(
            float(results[0.5].value), float(oracle.value), rtol=1e-4
        )

    def test_feature_sharded_tron_guards(self, rng):
        from photon_ml_tpu.optim.config import OptimizerType, RegularizationType
        from photon_ml_tpu.parallel.mesh import MODEL_AXIS
        from photon_ml_tpu.training import train_feature_sharded

        batch, d = random_problem(rng, n=32, d=16, k=3)
        mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        with pytest.raises(ValueError, match="twice-differentiable"):
            train_feature_sharded(
                batch, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM, d,
                mesh=mesh, optimizer_type=OptimizerType.TRON,
            )
        with pytest.raises(ValueError, match="L1/ELASTIC_NET"):
            train_feature_sharded(
                batch, TaskType.LOGISTIC_REGRESSION, d,
                mesh=mesh, optimizer_type=OptimizerType.TRON,
                regularization_type=RegularizationType.L1,
            )

    def test_train_feature_sharded_tiled_owlqn(self, rng):
        # elastic-net grid through the public entry point, tiled kernel
        from photon_ml_tpu.parallel.mesh import MODEL_AXIS
        from photon_ml_tpu.training import train_feature_sharded
        from photon_ml_tpu.optim.config import RegularizationType

        n, d, k = 96, 60, 4
        w_true = rng.normal(size=d)
        rows, labels = [], []
        for _ in range(n):
            ix = rng.choice(d, size=k, replace=False)
            vs = rng.normal(size=k)
            z = float((w_true[ix] * vs).sum())
            labels.append(float(rng.uniform() < 1 / (1 + np.exp(-z))))
            rows.append((ix.tolist(), vs.tolist()))
        batch = make_sparse_batch(rows, labels)
        mesh = make_mesh((4, 2), (DATA_AXIS, MODEL_AXIS))
        kwargs = dict(
            mesh=mesh,
            regularization_type=RegularizationType.ELASTIC_NET,
            elastic_net_alpha=0.5,
            regularization_weights=[0.3],
            max_iter=25,
        )
        m_scatter, _ = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d, kernel="scatter", **kwargs
        )
        m_tiled, _ = train_feature_sharded(
            batch, TaskType.LOGISTIC_REGRESSION, d, kernel="tiled", **kwargs
        )
        np.testing.assert_allclose(
            np.asarray(m_tiled[0.3].coefficients.means),
            np.asarray(m_scatter[0.3].coefficients.means),
            atol=5e-3,
        )


class TestTiledMeshTraining:
    def test_mesh_matches_single_device_tiled(self, rng):
        # end-to-end lambda grid: tiled+mesh vs scatter single-device agree
        # (no silent fallback anywhere). Labels come from a planted model so
        # the optimum is well-conditioned (separable data would amplify fp
        # reduction-order noise into large coefficient differences).
        n, d, k = 157, 40, 5
        w_true = rng.normal(size=d)
        rows, labels = [], []
        for _ in range(n):
            ix = rng.choice(d, size=k, replace=False)
            vs = rng.normal(size=k)
            z = float((w_true[ix] * vs).sum())
            labels.append(float(rng.uniform() < 1 / (1 + np.exp(-z))))
            rows.append((ix.tolist(), vs.tolist()))
        batch = make_sparse_batch(rows, labels)
        kwargs = dict(regularization_weights=[1.0, 0.1], max_iter=25)
        m_scatter, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d, **kwargs
        )
        m_mesh, _ = train_generalized_linear_model(
            batch, TaskType.LOGISTIC_REGRESSION, d,
            kernel="tiled", mesh=make_mesh(), **kwargs
        )
        for lam in m_scatter:
            np.testing.assert_allclose(
                np.asarray(m_mesh[lam].coefficients.means),
                np.asarray(m_scatter[lam].coefficients.means),
                atol=5e-3,
            )
