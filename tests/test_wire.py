"""photon-wire tests (ISSUE 17): the length-prefixed binary wire plane.

The acceptance bar: binary-framed scores are BITWISE the JSON-lines
path's and the batch scorer's at N in {1, 2, 4} shards; the frontend
sniffs the connection's first byte so JSON and binary clients coexist
on one port; the router NEGOTIATES the data plane from the topology
advertisement and a binary-pinned router refuses a JSON-only shard
with a named error; every malformed-binary-frame shape in the fuzz
corpus (lying lengths, truncated frames, giant lengths, mid-frame
disconnects, unknown types, bad versions) is a named BAD_REQUEST —
never a crash or a stuck reader; the framing cap is ONE rule enforced
identically for JSON lines and binary frames; and the cursor-keyed
trace drain rides MSG_TRACE_RESPONSE frames into a FleetCollector
with an exact merge.
"""

import json
import math
import socket
import struct
import threading

import numpy as np
import pytest

from photon_ml_tpu.game.data import build_game_dataset
from photon_ml_tpu.obs.fleet import FleetCollector
from photon_ml_tpu.obs.trace import tracer, tracing_scope
from photon_ml_tpu.serving import (
    MicroBatcher,
    PartialScore,
    ServingFrontend,
    ServingMetrics,
    ServingModel,
    ServingPrograms,
    ShardRouter,
)
from photon_ml_tpu.serving import wire
from tests.test_serving import (
    SHARDS,
    batch_reference_scores,
    make_bank,
    synth_model,
    synth_records,
)
from tests.test_serving_frontend import Client
from tests.test_shard_routing import (
    build_fleet,
    build_router,
    close_fleet,
)


def _hdr(obj, tail=b""):
    """A header-prefixed payload in the shape every non-JSON decoder
    splits: 4-byte little-endian header length, JSON header, float tail."""
    hj = json.dumps(obj).encode("utf-8")
    return struct.pack("<I", len(hj)) + hj + tail


# The malformed-payload fuzz corpus, keyed by message type. This dict is
# half of a machine-checked contract: photon-lint PL018 cross-checks its
# keys against wire.py's MSG_* inventory (a new message type without a
# corpus entry fails lint, package-wide), and TestFuzzCorpus proves every
# payload here is REFUSED by decode_message with a named WireError —
# never a crash, never a silent partial decode.
WIRE_FUZZ_CORPUS = {
    wire.MSG_JSON: [
        b"{",  # truncated JSON
        b"[1, 2]",  # not an object
        b"\xff\xfe\x00",  # not UTF-8
    ],
    wire.MSG_SCORE_REQUEST: [
        struct.pack("<I", 999) + b"{}",  # header length overruns frame
        _hdr({"_wire_bags": "nope"}),  # _wire_bags must be an object
        _hdr(
            {"features": [{"name": "a"}], "_wire_bags": {"features": 1}},
            b"\x00" * 4,
        ),  # float tail shorter than the bag counts promise
    ],
    wire.MSG_SCORE_RESPONSE: [
        b"\x00",  # too short for the header-length word
        _hdr({}),  # no f32 score tail
    ],
    wire.MSG_PARTIAL_RESPONSE: [
        _hdr({}),  # header lacks names
        _hdr({"names": ["a", "b"]}, b"\x00" * 4),  # tail < 1 + len(names)
    ],
    wire.MSG_TRACE_RESPONSE: [
        _hdr({}),  # header lacks spans
        _hdr({"spans": [{}]}),  # no span-times tail
        _hdr({"spans": [1]}, b"\x00" * 16),  # span is not an object
    ],
}


class BinClient:
    """One binary-framing client connection: frames out, frames in."""

    def __init__(self, port, timeout=15.0, max_frame_bytes=None):
        self.sock = socket.create_connection(
            ("127.0.0.1", port), timeout=timeout
        )
        self.dec = wire.FrameDecoder(
            wire.resolve_max_frame_bytes(max_frame_bytes)
        )
        self.pending = []

    def send_raw(self, data: bytes):
        self.sock.sendall(data)

    def send(self, obj, *, score=False):
        buf = bytearray()
        if score:
            wire.append_score_request(buf, obj)
        else:
            wire.append_json(buf, obj)
        self.sock.sendall(buf)

    def recv_frame(self):
        """The next raw (msg_type, payload), or None on EOF."""
        while not self.pending:
            try:
                chunk = self.sock.recv(1 << 16)
            except OSError:
                return None
            if not chunk:
                return None
            self.pending.extend(self.dec.feed(chunk))
        return self.pending.pop(0)

    def recv(self):
        frame = self.recv_frame()
        if frame is None:
            return None
        return wire.decode_message(*frame)

    def ask(self, obj, *, score=False):
        self.send(obj, score=score)
        return self.recv()

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


@pytest.fixture
def front(rng):
    """Full-margin serving stack on an ephemeral port + its records."""
    recs = synth_records(rng)
    ds = build_game_dataset(recs, SHARDS, ["userId"])
    lm = synth_model(rng)
    bank = make_bank(lm, ds)
    sm = ServingModel(bank, ServingPrograms((1, 8)))
    metrics = ServingMetrics()
    batcher = MicroBatcher(sm.current, sm.programs, metrics)
    fe = ServingFrontend(
        batcher, sm, SHARDS, metrics=metrics, port=0
    ).start()
    yield recs, ds, lm, metrics, fe
    fe.stop_accepting()
    batcher.drain(10.0)
    fe.close()
    batcher.close()


# -- the codec in isolation ---------------------------------------------------


class TestCodec:
    def test_score_request_roundtrip_matches_json(self):
        rng = np.random.default_rng(7)
        rec = {
            "uid": "q1",
            "deadline_ms": 250.0,
            "metadataMap": {"userId": "user3"},
            "features": [
                {"name": f"g{i}", "term": "", "value": float(v)}
                for i, v in enumerate(rng.standard_normal(8))
            ],
            "userFeatures": [
                {"name": "u0", "term": "t", "value": -1.5},
            ],
        }
        buf = bytearray()
        wire.append_score_request(buf, rec)
        frames = wire.FrameDecoder().feed(bytes(buf))
        assert len(frames) == 1
        assert frames[0][0] == wire.MSG_SCORE_REQUEST
        got = wire.decode_message(*frames[0])
        # the binary round-trip must reproduce EXACTLY what a JSON
        # round-trip of the same record produces — same doubles
        assert got == json.loads(json.dumps(rec))

    @pytest.mark.parametrize("bag", [
        # generic fallback shapes: extra key, missing term, the column
        # separator inside a name, non-string name, bool/str/nested
        # values, an un-listable value — all must still round-trip
        [{"name": "a", "term": "", "value": 1.5, "extra": 2}],
        [{"name": "a", "value": 1.5}],
        [{"name": "a\x1fb", "term": "", "value": 1.5}],
        [{"name": 3, "term": "", "value": 1.5}],
        [{"name": "a", "term": "", "value": True}],
        [{"name": "a", "term": "", "value": "str"}],
        [{"name": "a", "term": "", "value": [1.0, 2.0]}],
        [{"name": "a", "term": "", "value": 2 ** 400}],
        ["not-a-dict"],
        [],
    ])
    def test_nonstandard_bags_roundtrip(self, bag):
        rec = {"uid": "q", "features": bag}
        buf = bytearray()
        wire.append_score_request(buf, rec)
        got = wire.decode_message(*wire.FrameDecoder().feed(bytes(buf))[0])
        assert got == json.loads(json.dumps(rec))

    def test_int_values_ride_as_doubles(self):
        # the existing strip contract: numeric values ride the f64
        # tail, so ints come back as the equal float (score-identical:
        # the batcher floats every value anyway)
        rec = {"uid": "q", "features": [
            {"name": "a", "term": "", "value": 7},
        ]}
        buf = bytearray()
        wire.append_score_request(buf, rec)
        got = wire.decode_message(*wire.FrameDecoder().feed(bytes(buf))[0])
        assert got["features"][0]["value"] == 7.0
        assert isinstance(got["features"][0]["value"], float)

    def test_score_response_roundtrip_exact_f32(self):
        score = float(np.float32(0.1))  # long shortest-round-trip repr
        resp = {"uid": "q", "status": "ok", "score": score,
                "degraded": False, "generation": 3}
        buf = bytearray()
        wire.append_response(buf, resp)
        (mtype, payload), = wire.FrameDecoder().feed(bytes(buf))
        assert mtype == wire.MSG_SCORE_RESPONSE
        assert wire.decode_message(mtype, payload) == resp

    def test_partial_response_matches_json_form(self):
        names = ("per-user", "per-item")
        vec = np.asarray([0.25, -1.125], dtype=np.float32)
        ps = PartialScore.from_vector(
            float(np.float32(0.7)), names, vec, generation=2
        )
        head = {"uid": "q", "status": "ok", "partial": True,
                "generation": 2, "degraded": False}
        json_form = dict(head)
        json_form["fe"] = ps.fe
        json_form["terms"] = dict(ps.terms)
        resp = dict(head)
        resp["_wire_partial"] = ps
        buf = bytearray()
        wire.append_response(buf, resp)
        (mtype, payload), = wire.FrameDecoder().feed(bytes(buf))
        assert mtype == wire.MSG_PARTIAL_RESPONSE
        # decoded binary == what the JSON path would have produced,
        # double for double
        assert wire.decode_message(mtype, payload) == json.loads(
            json.dumps(json_form)
        )

    def test_trace_response_roundtrip_with_unfinished_span(self):
        resp = {
            "uid": "t", "status": "ok", "op": "trace", "cursor": 9,
            "dropped": 0,
            "spans": [
                {"seq": 1, "name": "a", "t0": 1.25, "t1": 2.5},
                {"seq": 2, "name": "b", "t0": 3.125, "t1": None},
            ],
        }
        buf = bytearray()
        wire.append_response(buf, resp)
        (mtype, payload), = wire.FrameDecoder().feed(bytes(buf))
        assert mtype == wire.MSG_TRACE_RESPONSE
        assert wire.decode_message(mtype, payload) == resp

    def test_control_responses_ride_msg_json(self):
        resp = {"uid": "q", "status": "error", "error": "BAD_REQUEST",
                "message": "nope"}
        buf = bytearray()
        wire.append_response(buf, resp)
        (mtype, payload), = wire.FrameDecoder().feed(bytes(buf))
        assert mtype == wire.MSG_JSON
        assert wire.decode_message(mtype, payload) == resp

    def test_decoder_streams_partial_frames(self):
        buf = bytearray()
        wire.append_json(buf, {"op": "status"})
        wire.append_json(buf, {"op": "metrics"})
        dec = wire.FrameDecoder()
        out = []
        for i in range(len(buf)):  # one byte at a time
            out.extend(dec.feed(bytes(buf[i:i + 1])))
        assert [m for m, _p in out] == [wire.MSG_JSON, wire.MSG_JSON]
        assert dec.pending_bytes == 0

    def test_decoder_named_failures(self):
        dec = wire.FrameDecoder(max_frame_bytes=1024)
        with pytest.raises(wire.WireError, match="framing lost"):
            dec.feed(b"\x00" * 7)
        dec = wire.FrameDecoder(max_frame_bytes=1024)
        bad_version = struct.pack("<BBBI", wire.MAGIC, 99, wire.MSG_JSON, 0)
        with pytest.raises(wire.WireError, match="wire version"):
            dec.feed(bad_version)
        dec = wire.FrameDecoder(max_frame_bytes=1024)
        giant = struct.pack(
            "<BBBI", wire.MAGIC, wire.WIRE_VERSION, wire.MSG_JSON, 1 << 30
        )
        with pytest.raises(wire.WireError, match="exceeds") as ei:
            dec.feed(giant)  # refused from the HEADER — nothing buffered
        assert ei.value.kind == "oversized"
        with pytest.raises(wire.WireError, match="unknown message type"):
            wire.decode_message(0x7F, b"")

    def test_lying_payload_lengths_are_named_errors(self):
        # inner header length overruns the frame
        payload = struct.pack("<I", 999) + b"{}"
        with pytest.raises(wire.WireError, match="overruns"):
            wire.decode_score_request(payload)
        # float tail shorter than _wire_bags promises
        head = json.dumps(
            {"features": [{"name": "a"}], "_wire_bags": {"features": 1}}
        ).encode()
        payload = struct.pack("<I", len(head)) + head + b"\x00" * 4
        with pytest.raises(wire.WireError, match="float buffer"):
            wire.decode_score_request(payload)
        # _wire_cols without a matching count
        head = json.dumps(
            {"_wire_bags": {}, "_wire_cols": {"features": ["a", ""]}}
        ).encode()
        payload = struct.pack("<I", len(head)) + head
        with pytest.raises(wire.WireError, match="_wire"):
            wire.decode_score_request(payload)
        # column entry count disagrees with the bag count
        head = json.dumps({
            "_wire_bags": {"features": 2},
            "_wire_cols": {"features": ["a", ""]},
        }).encode()
        payload = (
            struct.pack("<I", len(head)) + head + b"\x00" * 16
        )
        with pytest.raises(wire.WireError, match="promised 2"):
            wire.decode_score_request(payload)

    def test_resolve_max_frame_bytes(self, monkeypatch):
        monkeypatch.delenv(wire.MAX_FRAME_BYTES_ENV, raising=False)
        assert wire.resolve_max_frame_bytes() == wire.DEFAULT_MAX_FRAME_BYTES
        monkeypatch.setenv(wire.MAX_FRAME_BYTES_ENV, "4096")
        assert wire.resolve_max_frame_bytes() == 4096
        # explicit beats env
        assert wire.resolve_max_frame_bytes(512) == 512
        with pytest.raises(ValueError, match="positive"):
            wire.resolve_max_frame_bytes(0)


class TestFuzzCorpus:
    """The WIRE_FUZZ_CORPUS contract, runtime half. Lint (PL018) proves
    the corpus KEYS track wire.py's MSG_* inventory; these tests prove
    the corpus VALUES are live ammunition — every payload refused with
    a named WireError, through the bare codec and the stream decoder."""

    def test_corpus_covers_every_message_type(self):
        # the same inventory derivation PL018 performs: module-level
        # MSG_* integer constants in wire.py
        inventory = {
            v
            for k, v in vars(wire).items()
            if k.startswith("MSG_") and isinstance(v, int)
        }
        assert set(WIRE_FUZZ_CORPUS) == inventory
        # and no message type shares a wire value with another
        assert len(inventory) == sum(
            1 for k in vars(wire) if k.startswith("MSG_")
        )

    def test_every_corpus_payload_is_a_named_refusal(self):
        for mtype, payloads in WIRE_FUZZ_CORPUS.items():
            assert payloads, f"empty corpus list for 0x{mtype:02x}"
            for payload in payloads:
                with pytest.raises(wire.WireError):
                    wire.decode_message(mtype, payload)

    def test_corpus_payloads_survive_framing_then_refuse(self):
        # framing is content-blind: every corpus payload rides a frame
        # intact and still dies as a WireError at decode_message — the
        # refusal happens at the codec, never as a stream wedge
        for mtype, payloads in WIRE_FUZZ_CORPUS.items():
            for payload in payloads:
                buf = bytearray()
                wire.append_frame(buf, mtype, payload)
                frames = wire.FrameDecoder().feed(bytes(buf))
                assert len(frames) == 1
                got_type, got_payload = frames[0]
                assert got_type == mtype
                assert bytes(got_payload) == payload
                with pytest.raises(wire.WireError):
                    wire.decode_message(got_type, got_payload)


# -- first-byte sniffing: both protocols on ONE port --------------------------


class TestFrontendSniffing:
    def test_binary_scores_bitwise_match_json_clients(self, front):
        recs, ds, lm, metrics, fe = front
        ref = batch_reference_scores(lm, ds)
        jc, bc = Client(fe.port), BinClient(fe.port)
        try:
            for i in (0, 7, 23):
                jr = jc.ask(recs[i])
                bc.send(recs[i], score=True)
                mtype, payload = bc.recv_frame()
                # the hot-path response codec, not a JSON fallback
                assert mtype == wire.MSG_SCORE_RESPONSE
                br = wire.decode_message(mtype, payload)
                assert br == jr, "binary response must equal JSON's"
                assert np.float32(br["score"]) == ref[i]
        finally:
            jc.close()
            bc.close()

    def test_mixed_protocol_clients_concurrently(self, front):
        recs, ds, lm, metrics, fe = front
        ref = batch_reference_scores(lm, ds)
        errors = []

        def json_worker(idx):
            c = Client(fe.port)
            try:
                for i in idx:
                    r = c.ask(recs[i])
                    assert r["status"] == "ok", r
                    assert np.float32(r["score"]) == ref[i], i
            except BaseException as e:  # noqa: BLE001 - collected
                errors.append(e)
            finally:
                c.close()

        def bin_worker(idx):
            c = BinClient(fe.port)
            try:
                for i in idx:
                    r = c.ask(recs[i], score=True)
                    assert r["status"] == "ok", r
                    assert np.float32(r["score"]) == ref[i], i
            except BaseException as e:  # noqa: BLE001 - collected
                errors.append(e)
            finally:
                c.close()

        threads = [
            threading.Thread(target=json_worker, args=(range(0, 30),)),
            threading.Thread(target=bin_worker, args=(range(30, 60),)),
            threading.Thread(target=json_worker, args=(range(15, 45),)),
            threading.Thread(target=bin_worker, args=(range(0, 60, 2),)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

    def test_control_ops_and_status_advertise_wire(self, front):
        recs, ds, lm, metrics, fe = front
        bc = BinClient(fe.port)
        jc = Client(fe.port)
        try:
            for status in (
                bc.ask({"op": "status"}),
                jc.ask({"op": "status"}),
            ):
                assert status["status"] == "ok"
                assert status["wire"]["protocols"] == ["json", "binary"]
                assert status["wire"]["version"] == wire.WIRE_VERSION
                assert (
                    status["wire"]["max_frame_bytes"] == fe.max_frame_bytes
                )
            m = bc.ask({"op": "metrics"})
            assert m["status"] == "ok"
        finally:
            bc.close()
            jc.close()

    def test_pipelined_burst_coalesces_and_demuxes(self, front):
        """Writer coalescing: a pipelined burst on one connection gets
        every response exactly once (uids demux), for BOTH protocols,
        and backlog drains through batched sendalls."""
        recs, ds, lm, metrics, fe = front
        ref = {r["uid"]: s for r, s in zip(
            recs, batch_reference_scores(lm, ds)
        )}
        bc = BinClient(fe.port)
        try:
            buf = bytearray()
            for r in recs[:40]:
                wire.append_score_request(buf, r)
            bc.send_raw(bytes(buf))
            got = {}
            for _ in range(40):
                r = bc.recv()
                assert r["status"] == "ok", r
                got[r["uid"]] = np.float32(r["score"])
            assert got == {r["uid"]: ref[r["uid"]] for r in recs[:40]}
        finally:
            bc.close()
        jc = Client(fe.port)
        try:
            jc.send_line(
                ("\n".join(json.dumps(r) for r in recs[:40]) + "\n")
                .encode()
            )
            got = {}
            for _ in range(40):
                r = jc.recv()
                assert r["status"] == "ok", r
                got[r["uid"]] = np.float32(r["score"])
            assert got == {r["uid"]: ref[r["uid"]] for r in recs[:40]}
        finally:
            jc.close()


# -- malformed-binary-frame fuzz corpus ---------------------------------------


class TestBinaryFuzz:
    def _score_ok(self, fe, rec):
        """The server is still alive: a fresh connection scores."""
        c = BinClient(fe.port)
        try:
            r = c.ask(rec, score=True)
            assert r is not None and r["status"] == "ok", r
        finally:
            c.close()

    def test_giant_announced_length_is_named_refusal(self, front):
        recs, ds, lm, metrics, fe = front
        c = BinClient(fe.port)
        try:
            c.send_raw(struct.pack(
                "<BBBI", wire.MAGIC, wire.WIRE_VERSION, wire.MSG_JSON,
                1 << 30,
            ))
            r = c.recv()
            assert r["status"] == "error" and r["error"] == "BAD_REQUEST"
            assert "exceeds" in r["message"]
            assert c.recv() is None  # framing lost -> connection closed
        finally:
            c.close()
        assert metrics.snapshot()["frontend"]["oversized"] >= 1
        self._score_ok(fe, recs[0])

    def test_bad_version_is_named_refusal(self, front):
        recs, ds, lm, metrics, fe = front
        c = BinClient(fe.port)
        try:
            c.send_raw(struct.pack(
                "<BBBI", wire.MAGIC, 99, wire.MSG_JSON, 0
            ))
            r = c.recv()
            assert r["status"] == "error" and r["error"] == "BAD_REQUEST"
            assert "wire version" in r["message"]
            assert c.recv() is None
        finally:
            c.close()
        self._score_ok(fe, recs[0])

    def test_framing_lost_mid_stream_is_named_refusal(self, front):
        recs, ds, lm, metrics, fe = front
        c = BinClient(fe.port)
        try:
            r = c.ask({"op": "status"})
            assert r["status"] == "ok"  # the connection served traffic
            c.send_raw(b"garbage-after-a-valid-frame")
            r = c.recv()
            assert r["status"] == "error" and r["error"] == "BAD_REQUEST"
            assert "framing lost" in r["message"]
            assert c.recv() is None
        finally:
            c.close()
        self._score_ok(fe, recs[0])

    def test_lying_inner_length_keeps_connection_alive(self, front):
        """Payload-level lies are per-REQUEST errors: the frame
        boundary is intact, so the connection survives and the next
        frame answers normally."""
        recs, ds, lm, metrics, fe = front
        c = BinClient(fe.port)
        try:
            payload = struct.pack("<I", 999) + b"{}"
            frame = struct.pack(
                "<BBBI", wire.MAGIC, wire.WIRE_VERSION,
                wire.MSG_SCORE_REQUEST, len(payload),
            ) + payload
            c.send_raw(frame)
            r = c.recv()
            assert r["status"] == "error" and r["error"] == "BAD_REQUEST"
            assert "overruns" in r["message"]
            # same connection, next frame: a real score
            r2 = c.ask(recs[0], score=True)
            assert r2["status"] == "ok"
        finally:
            c.close()
        assert metrics.snapshot()["frontend"]["malformed"] >= 1

    def test_unknown_message_type_keeps_connection_alive(self, front):
        recs, ds, lm, metrics, fe = front
        c = BinClient(fe.port)
        try:
            c.send_raw(struct.pack(
                "<BBBI", wire.MAGIC, wire.WIRE_VERSION, 0x7F, 0
            ))
            r = c.recv()
            assert r["status"] == "error" and r["error"] == "BAD_REQUEST"
            assert "unexpected message type" in r["message"]
            r2 = c.ask(recs[0], score=True)
            assert r2["status"] == "ok"
        finally:
            c.close()

    def test_response_types_refused_on_request_side(self, front):
        recs, ds, lm, metrics, fe = front
        c = BinClient(fe.port)
        try:
            resp = bytearray()
            wire.append_response(resp, {
                "uid": "q", "status": "ok", "score": 0.5,
            })
            c.send_raw(bytes(resp))  # MSG_SCORE_RESPONSE at the server
            r = c.recv()
            assert r["status"] == "error" and r["error"] == "BAD_REQUEST"
            assert "request side" in r["message"]
        finally:
            c.close()

    def test_mid_frame_disconnect_never_wedges_the_server(self, front):
        recs, ds, lm, metrics, fe = front
        whole = bytearray()
        wire.append_score_request(whole, recs[0])
        for cut in (3, 7, len(whole) // 2, len(whole) - 1):
            c = BinClient(fe.port)
            c.send_raw(bytes(whole[:cut]))
            c.close()  # mid-frame EOF: the tail is just dropped
        self._score_ok(fe, recs[0])
        # no reader thread is stuck: the frontend drains to zero conns
        deadline = 50
        while fe.open_connections() > 0 and deadline:
            import time

            time.sleep(0.1)
            deadline -= 1
        assert fe.open_connections() == 0

    def test_non_magic_garbage_takes_the_json_lane(self, front):
        recs, ds, lm, metrics, fe = front
        c = Client(fe.port)
        try:
            c.send_line(b"\x02not json either\n")
            r = c.recv()
            assert r["status"] == "error" and r["error"] == "BAD_REQUEST"
        finally:
            c.close()
        self._score_ok(fe, recs[0])


# -- the ONE framing cap, both protocols --------------------------------------


class TestFrameCap:
    @pytest.fixture
    def capped(self, rng):
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        bank = make_bank(synth_model(rng), ds)
        sm = ServingModel(bank, ServingPrograms((1, 8)))
        metrics = ServingMetrics()
        batcher = MicroBatcher(sm.current, sm.programs, metrics)
        fe = ServingFrontend(
            batcher, sm, SHARDS, metrics=metrics, port=0,
            max_frame_bytes=2048,
        ).start()
        yield recs, metrics, fe
        fe.stop_accepting()
        batcher.drain(10.0)
        fe.close()
        batcher.close()

    def test_cap_refuses_json_line_and_binary_frame_alike(self, capped):
        recs, metrics, fe = capped
        assert fe.max_frame_bytes == 2048
        jc = Client(fe.port)
        try:
            jc.send_line(b"{" + b" " * 4096)  # no newline before cap
            r = jc.recv()
            assert r["error"] == "BAD_REQUEST"
            assert "exceeds 2048 bytes" in r["message"]
        finally:
            jc.close()
        bc = BinClient(fe.port)
        try:
            bc.send_raw(struct.pack(
                "<BBBI", wire.MAGIC, wire.WIRE_VERSION, wire.MSG_JSON,
                4096,
            ))
            r = bc.recv()
            assert r["error"] == "BAD_REQUEST"
            assert "exceeds 2048" in r["message"]
        finally:
            bc.close()
        assert metrics.snapshot()["frontend"]["oversized"] >= 2
        # the cap is published where operators look
        c = Client(fe.port)
        try:
            assert c.ask({"op": "status"})["wire"]["max_frame_bytes"] == 2048
        finally:
            c.close()

    def test_env_cap_applies_when_unset(self, rng, monkeypatch):
        monkeypatch.setenv(wire.MAX_FRAME_BYTES_ENV, "8192")
        recs = synth_records(rng, n=4)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        bank = make_bank(synth_model(rng), ds)
        sm = ServingModel(bank, ServingPrograms((1, 8)))
        batcher = MicroBatcher(sm.current, sm.programs, ServingMetrics())
        fe = ServingFrontend(batcher, sm, SHARDS, port=0)
        try:
            assert fe.max_frame_bytes == 8192
            assert fe.max_line_bytes == 8192  # legacy alias, same rule
        finally:
            fe.close()
            batcher.close()

    def test_driver_flags(self):
        from photon_ml_tpu.cli.serving_driver import params_from_args

        p = params_from_args([
            "--game-model-input-dir", "m",
            "--output-dir", "o",
            "--feature-shard-id-to-feature-section-keys-map",
            "g:features",
            "--wire", "binary",
            "--max-frame-bytes", "65536",
        ])
        assert p.wire == "binary"
        assert p.max_frame_bytes == 65536
        p2 = params_from_args([
            "--game-model-input-dir", "m",
            "--output-dir", "o",
            "--feature-shard-id-to-feature-section-keys-map",
            "g:features",
        ])
        assert p2.wire == "auto"
        assert p2.max_frame_bytes is None
        bad = params_from_args([
            "--game-model-input-dir", "m",
            "--output-dir", "o",
            "--feature-shard-id-to-feature-section-keys-map",
            "g:features",
            "--max-frame-bytes", "0",
        ])
        with pytest.raises(ValueError, match="max-frame-bytes"):
            bad.validate()


# -- negotiation + routed parity ----------------------------------------------


def _strip_wire_advertisement(server):
    """Make one shard LOOK like a pre-wire build: its topology answer
    loses the ``wire`` block (the negotiation treats that as
    JSON-only)."""
    orig = server.frontend.extra_ops["topology"]

    def legacy_topology(obj):
        out = orig(obj)
        out.pop("wire", None)
        return out

    server.frontend.extra_ops["topology"] = legacy_topology


class TestNegotiation:
    def test_binary_router_refuses_json_only_shard_by_name(self, rng):
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 2)
        _strip_wire_advertisement(servers[1])
        try:
            with pytest.raises(
                ValueError, match=r"wire-protocol mismatch.*\[1\]"
            ):
                build_router(servers, lm, wire="binary")
        finally:
            close_fleet(servers)

    def test_auto_falls_back_to_json_on_mixed_fleet(self, rng):
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        ref = batch_reference_scores(lm, ds)
        servers = build_fleet(lm, ds, 2)
        _strip_wire_advertisement(servers[0])
        router = None
        try:
            router = build_router(servers, lm, wire="auto")
            st = router.status()["wire"]
            assert st == {"requested": "auto", "negotiated": "json"}
            got = [router.score_record(r) for r in recs[:16]]
            assert np.array_equal(
                np.asarray(got, np.float32), ref[:16]
            )
        finally:
            close_fleet(servers, router)

    def test_auto_negotiates_binary_on_uniform_fleet(self, rng):
        recs = synth_records(rng, n=8)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 2)
        router = None
        try:
            router = build_router(servers, lm, wire="auto")
            assert router.status()["wire"] == {
                "requested": "auto", "negotiated": "binary",
            }
        finally:
            close_fleet(servers, router)

    def test_topology_advertises_protocols(self, rng):
        recs = synth_records(rng, n=8)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 1)
        try:
            c = Client(servers[0].port)
            topo = c.ask({"op": "topology", "uid": "t"})
            assert topo["wire"]["protocols"] == ["json", "binary"]
            assert topo["wire"]["version"] == wire.WIRE_VERSION
            c.close()
        finally:
            close_fleet(servers)


class TestRoutedParityBinary:
    @pytest.mark.parametrize("n_shards", [1, 2, 4])
    def test_binary_routed_bitwise_vs_json_and_batch(self, rng, n_shards):
        """The acceptance bar: binary-wire routed margins are BITWISE
        the JSON-wire router's AND the batch scorer's at N in
        {1, 2, 4} shards."""
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        ref = batch_reference_scores(lm, ds)
        servers = build_fleet(lm, ds, n_shards)
        r_bin = r_json = None
        try:
            r_bin = build_router(servers, lm, wire="binary")
            r_json = build_router(servers, lm, wire="json")
            assert r_bin.status()["wire"]["negotiated"] == "binary"
            assert r_json.status()["wire"]["negotiated"] == "json"
            got_b = [float(r_bin.score_record(r)) for r in recs]
            got_j = [float(r_json.score_record(r)) for r in recs]
            assert got_b == got_j, (
                "binary and JSON data planes must agree bitwise"
            )
            assert np.array_equal(np.asarray(got_b, np.float32), ref)
        finally:
            close_fleet(servers)
            for r in (r_bin, r_json):
                if r is not None:
                    r.close()


# -- binary trace drain -------------------------------------------------------


class TestBinaryTraceDrain:
    def test_trace_op_over_binary_and_collector_merge_exact(self, front):
        recs, ds, lm, metrics, fe = front
        with tracing_scope(True):
            tracer().clear()
            collector = FleetCollector(
                [("m0", "127.0.0.1", fe.port)],
                poll_s=0.05,
                wire="binary",
            ).start()
            jc = Client(fe.port)
            try:
                for r in recs[:6]:
                    assert jc.ask(r)["status"] == "ok"
            finally:
                jc.close()
            collector.stop(final_poll=True)
            # cursor-keyed drain over MSG_TRACE_RESPONSE, by hand: the
            # drained spans carry their float timestamps losslessly
            bc = BinClient(fe.port)
            try:
                bc.send({"op": "trace", "cursor": 0, "uid": "t1"})
                mtype, payload = bc.recv_frame()
                assert mtype == wire.MSG_TRACE_RESPONSE
                drained = wire.decode_message(mtype, payload)
                assert drained["status"] == "ok"
                assert drained["uid"] == "t1"
                assert drained["dropped"] == 0
                spans = drained["spans"]
                assert spans, "trace drain must return the recorded spans"
                for s in spans:
                    assert isinstance(s["t0"], float)
                    assert s["t1"] is None or isinstance(s["t1"], float)
                    assert not (
                        isinstance(s["t1"], float) and math.isnan(s["t1"])
                    )
                roots = [
                    s for s in spans if s["name"] == "frontend.request"
                ]
                assert len(roots) == 6
            finally:
                bc.close()
        # the live collector's merge is EXACT: every request root
        # arrived, nothing dropped, no poll errors
        status = collector.member_status()["m0"]
        assert status["errors"] == 0
        assert status["ring_dropped"] == 0
        stitched = collector.stitched_spans()
        assert len([
            s for s in stitched if s["name"] == "frontend.request"
        ]) == 6


# -- the shard data plane, end to end over binary -----------------------------


class TestShardDataPlane:
    def test_partial_responses_ride_partial_frames(self, rng):
        """A shard-server answers the router's score sub-requests with
        MSG_PARTIAL_RESPONSE frames on a binary connection — the
        vectorized codec, not a JSON fallback — and the payload equals
        the JSON path's, double for double."""
        recs = synth_records(rng)
        ds = build_game_dataset(recs, SHARDS, ["userId"])
        lm = synth_model(rng)
        servers = build_fleet(lm, ds, 1)
        try:
            jc = Client(servers[0].port)
            bc = BinClient(servers[0].port)
            try:
                jr = jc.ask(recs[0])
                assert jr["status"] == "ok" and jr["partial"] is True
                bc.send(recs[0], score=True)
                mtype, payload = bc.recv_frame()
                assert mtype == wire.MSG_PARTIAL_RESPONSE
                br = wire.decode_message(mtype, payload)
                assert br == jr
            finally:
                jc.close()
                bc.close()
        finally:
            close_fleet(servers)
