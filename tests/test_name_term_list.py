"""Feature name-and-term list files (NameAndTermFeatureSetContainer
analog) and the per-shard intercept map."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))


@pytest.fixture
def rng():
    return np.random.default_rng(5)


class TestListFiles:
    def test_roundtrip(self, tmp_path):
        from photon_ml_tpu.io.name_term_list import (
            read_name_and_term_feature_sets,
            save_name_and_term_feature_sets,
        )
        from photon_ml_tpu.utils.index_map import feature_key

        sets = {
            "features": {feature_key("a", "t1"), feature_key("b")},
            "userFeatures": {feature_key("u0"), feature_key("u1", "x")},
        }
        save_name_and_term_feature_sets(sets, str(tmp_path))
        back = read_name_and_term_feature_sets(
            str(tmp_path), ["features", "userFeatures"]
        )
        assert back == sets

    def test_bare_name_line_means_empty_term(self, tmp_path):
        from photon_ml_tpu.io.name_term_list import read_name_and_term_set
        from photon_ml_tpu.utils.index_map import feature_key

        d = tmp_path / "features"
        d.mkdir()
        (d / "part-00000").write_text("plain\nwith\ttermed\n")
        assert read_name_and_term_set(str(d)) == {
            feature_key("plain"), feature_key("with", "termed")
        }

    def test_missing_section_raises(self, tmp_path):
        from photon_ml_tpu.io.name_term_list import (
            read_name_and_term_feature_sets,
        )

        with pytest.raises(OSError, match="no feature list"):
            read_name_and_term_feature_sets(str(tmp_path), ["nope"])

    def test_index_map_union_and_intercept(self, tmp_path):
        from photon_ml_tpu.io.name_term_list import index_map_from_sections
        from photon_ml_tpu.utils.index_map import feature_key, intercept_key

        sets = {
            "a": {feature_key("x"), feature_key("y")},
            "b": {feature_key("y"), feature_key("z")},
        }
        m = index_map_from_sections(sets, ["a", "b"], add_intercept=True)
        assert m.size == 4  # x, y, z + intercept
        assert m.get_index(intercept_key()) == 3
        m2 = index_map_from_sections(sets, ["a"], add_intercept=False)
        assert m2.size == 2

    def test_generate_from_avro(self, tmp_path, rng):
        from test_game_drivers import write_game_avro
        from photon_ml_tpu.io.name_term_list import (
            generate_name_and_term_lists,
            read_name_and_term_feature_sets,
        )

        data = tmp_path / "data"
        data.mkdir()
        write_game_avro(str(data / "p.avro"), rng, n=50)
        out = tmp_path / "lists"
        sets = generate_name_and_term_lists(
            [str(data)], ["features", "userFeatures"], str(out)
        )
        assert len(sets["features"]) == 5
        assert len(sets["userFeatures"]) == 3
        back = read_name_and_term_feature_sets(
            str(out), ["features", "userFeatures"]
        )
        assert back == sets


class TestInterceptMap:
    def test_apply(self):
        from photon_ml_tpu.cli.game_training_driver import (
            apply_intercept_map,
            parse_shard_map,
        )

        shards = parse_shard_map("g:features|u:userFeatures")
        out = apply_intercept_map(shards, "g:true|u:false")
        assert out[0].add_intercept is True
        assert out[1].add_intercept is False
        # bare shard id means true; unspecified keeps default
        out2 = apply_intercept_map(shards, "u")
        assert out2[1].add_intercept is True
        with pytest.raises(ValueError, match="unknown feature shards"):
            apply_intercept_map(shards, "ghost:false")


class TestDriverIntegration:
    def test_game_training_with_list_files_and_intercept_map(
        self, tmp_path, rng
    ):
        from test_game_drivers import write_game_avro
        from photon_ml_tpu.cli.game_training_driver import (
            GameTrainingDriver,
            params_from_args,
        )
        from photon_ml_tpu.io.name_term_list import (
            generate_name_and_term_lists,
        )

        train = tmp_path / "train"
        train.mkdir()
        write_game_avro(str(train / "p.avro"), rng, n=160)
        lists = tmp_path / "lists"
        generate_name_and_term_lists(
            [str(train)], ["features", "userFeatures"], str(lists)
        )

        params = params_from_args([
            "--train-input-dirs", str(train),
            "--output-dir", str(tmp_path / "out"),
            "--feature-shard-id-to-feature-section-keys-map",
            "g:features|u:userFeatures",
            "--feature-shard-id-to-intercept-map", "g:true|u:false",
            "--feature-name-and-term-set-path", str(lists),
            "--fixed-effect-data-configurations", "global:g",
            "--fixed-effect-optimization-configurations",
            "global:10,1e-6,0.1,1,LBFGS,L2",
            "--random-effect-data-configurations",
            "per-user:userId,u,1,none,none,none,index_map",
            "--random-effect-optimization-configurations",
            "per-user:10,1e-6,1.0,1,LBFGS,L2",
            "--updating-sequence", "global,per-user",
            "--num-iterations", "2",
            "--distributed", "off",
        ])
        driver = GameTrainingDriver(params)
        driver.run()
        ds = driver._train_dataset
        # g: 5 features + intercept; u: 3 features, NO intercept
        assert ds.shards["g"].dim == 6
        assert ds.shards["u"].dim == 3
        assert ds.shards["u"].intercept_index is None
        hist = driver.results[0][1].objective_history
        assert hist[-1] <= hist[0]


class TestStrictness:
    def test_bad_intercept_value_rejected(self):
        from photon_ml_tpu.cli.game_training_driver import (
            apply_intercept_map,
            parse_shard_map,
        )

        shards = parse_shard_map("g:features")
        with pytest.raises(ValueError, match="must be true/false"):
            apply_intercept_map(shards, "g:ture")
