"""Network front-end tests (ISSUE 8): the TCP JSON-lines request path —
bitwise parity over a real socket, malformed/oversized input guards,
readiness vs liveness status ops, the serving.frontend.read fault seam,
shed/deadline semantics on the wire, the SIGTERM drain protocol (zero
hung futures, zero leaked connections), and the driver's front-end mode
end to end (SIGTERM -> drained exit 0 + interrupted metrics.json).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from photon_ml_tpu.serving import (
    AdmissionController,
    MicroBatcher,
    ServingFrontend,
    ServingMetrics,
    ServingModel,
    ServingPrograms,
)
from tests.test_serving import (
    SHARDS,
    _wait_until,
    batch_reference_scores,
    make_bank,
    synth_model,
    synth_records,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Client:
    """One JSON-lines client connection with bounded reads."""

    def __init__(self, port, timeout=15.0):
        self.sock = socket.create_connection(
            ("127.0.0.1", port), timeout=timeout
        )
        self.reader = self.sock.makefile("rb")

    def send_line(self, obj_or_bytes):
        data = (
            obj_or_bytes
            if isinstance(obj_or_bytes, bytes)
            else (json.dumps(obj_or_bytes) + "\n").encode()
        )
        self.sock.sendall(data)

    def recv(self):
        line = self.reader.readline()
        if not line:
            return None  # EOF
        return json.loads(line)

    def ask(self, obj):
        self.send_line(obj)
        return self.recv()

    def close(self):
        try:
            self.reader.close()
            self.sock.close()
        except OSError:
            pass


@pytest.fixture
def stack(rng):
    """bank + batcher + frontend on an ephemeral port, torn down in
    drain order."""
    recs = synth_records(rng)
    from photon_ml_tpu.game.data import build_game_dataset

    ds = build_game_dataset(recs, SHARDS, ["userId"])
    lm = synth_model(rng)
    bank = make_bank(lm, ds)
    sm = ServingModel(bank, ServingPrograms((1, 8)))
    metrics = ServingMetrics()
    batcher = MicroBatcher(sm.current, sm.programs, metrics)
    fe = ServingFrontend(
        batcher, sm, SHARDS, metrics=metrics, port=0
    ).start()
    yield recs, ds, lm, sm, batcher, metrics, fe
    fe.stop_accepting()
    batcher.drain(10.0)
    fe.close()


class TestFrontendScoring:
    def test_socket_scores_bitwise_match_batch_scorer(self, stack):
        """The acceptance bar extends to the wire: a record scored over
        TCP returns the batch scoring driver's float, bit for bit."""
        recs, ds, lm, sm, batcher, metrics, fe = stack
        ref = batch_reference_scores(lm, ds)
        c = Client(fe.port)
        try:
            for i in (0, 7, 23, 42):
                resp = c.ask(recs[i])
                assert resp["status"] == "ok", resp
                assert resp["uid"] == recs[i]["uid"]
                assert np.float32(resp["score"]) == ref[i]
                assert resp["degraded"] is False
                assert resp["generation"] == 1
        finally:
            c.close()

    def test_concurrent_connections_each_get_their_rows(self, stack):
        recs, ds, lm, sm, batcher, metrics, fe = stack
        ref = batch_reference_scores(lm, ds)
        errors = []

        def client_worker(idx):
            c = Client(fe.port)
            try:
                for i in idx:
                    resp = c.ask(recs[i])
                    assert resp["status"] == "ok", resp
                    assert np.float32(resp["score"]) == ref[i], i
            except BaseException as e:
                errors.append(e)
            finally:
                c.close()

        threads = [
            threading.Thread(target=client_worker, args=(range(t, 30, 3),))
            for t in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors

    def test_malformed_lines_get_named_error_and_connection_survives(
        self, stack
    ):
        recs, ds, lm, sm, batcher, metrics, fe = stack
        c = Client(fe.port)
        try:
            resp = c.ask(b"this is not json\n")
            assert resp["status"] == "error"
            assert resp["error"] == "BAD_REQUEST"
            resp = c.ask(b'["a", "json", "array"]\n')
            assert resp["error"] == "BAD_REQUEST"
            resp = c.ask({"op": "no-such-op"})
            assert resp["error"] == "BAD_REQUEST"
            # the connection still serves real requests afterwards
            resp = c.ask(recs[0])
            assert resp["status"] == "ok", resp
        finally:
            c.close()
        snap = metrics.snapshot()
        assert snap["frontend"]["malformed"] >= 2

    def test_oversized_line_is_refused_and_closed(self, rng, stack):
        recs, ds, lm, sm, batcher, metrics, fe = stack
        small = ServingFrontend(
            batcher, sm, SHARDS, metrics=metrics, port=0,
            max_line_bytes=512,
        ).start()
        try:
            c = Client(small.port)
            c.send_line(b"x" * 2048)  # no newline: an unframed flood
            resp = c.recv()
            assert resp["error"] == "BAD_REQUEST"
            assert "exceeds" in resp["message"]
            assert c.recv() is None, "connection must close after refusal"
            c.close()
        finally:
            small.stop_accepting()
            small.close()
        assert metrics.snapshot()["frontend"]["oversized"] == 1

    def test_read_fault_seam_yields_named_error(self, stack):
        """A planned fault at serving.frontend.read surfaces as a
        READ_FAULT response on that connection — deterministic, crash-
        free, accounted."""
        from photon_ml_tpu.reliability import install_plan

        recs, ds, lm, sm, batcher, metrics, fe = stack
        ref = batch_reference_scores(lm, ds)
        install_plan("serving.frontend.read:2:EIO")
        c = Client(fe.port)
        try:
            assert c.ask(recs[0])["status"] == "ok"
            faulted = c.ask(recs[1])
            assert faulted["status"] == "error"
            assert faulted["error"] == "READ_FAULT"
            ok = c.ask(recs[2])  # the connection keeps serving
            assert ok["status"] == "ok"
            assert np.float32(ok["score"]) == ref[2]
        finally:
            c.close()
            install_plan(None)
        assert metrics.snapshot()["frontend"]["read_faults"] == 1


class TestFrontendLifecycle:
    def test_status_reports_ready_and_alive(self, stack):
        recs, ds, lm, sm, batcher, metrics, fe = stack
        c = Client(fe.port)
        try:
            for op in ("status", "ready", "live"):
                resp = c.ask({"op": op})
                assert resp["status"] == "ok"
                assert resp["ready"] is True
                assert resp["alive"] is True
                assert resp["draining"] is False
                assert resp["generation"] == 1
                assert resp["heartbeat_age_s"] < 5.0
        finally:
            c.close()

    def test_not_ready_when_ladder_cold(self, rng):
        """Readiness is 'bank + ladder warm', not 'process up': a model
        whose programs were never compiled must answer not-ready."""
        recs = synth_records(rng, n=5)
        from photon_ml_tpu.game.data import build_game_dataset

        ds = build_game_dataset(recs, SHARDS, ["userId"])
        bank = make_bank(synth_model(rng), ds)
        sm = ServingModel(bank, ServingPrograms((1, 8)))
        assert sm.ready()
        # evict by warming a different spec through a tiny cache
        sm.programs._max_entries = 1
        from photon_ml_tpu.serving import bank_from_arrays

        other = bank_from_arrays(
            fixed=[("global", "g", np.ones(16, np.float32))],
            shard_widths={"g": 4},
        )
        sm.programs.ensure_compiled(other)
        assert not sm.ready()

    def test_drain_refuses_new_work_finishes_old_zero_leaks(self, stack):
        """The SIGTERM protocol over a live socket: stop accepting ->
        in-flight work completes -> new score lines get CLOSED -> drain
        -> close -> zero open connections, client sees EOF."""
        recs, ds, lm, sm, batcher, metrics, fe = stack
        c = Client(fe.port)
        assert c.ask(recs[0])["status"] == "ok"
        fe.stop_accepting()
        # new connections are refused outright
        with pytest.raises(OSError):
            Client(fe.port, timeout=2.0)
        # score lines on the surviving connection get the named refusal
        resp = c.ask(recs[1])
        assert resp["status"] == "error" and resp["error"] == "CLOSED"
        report = batcher.drain(5.0)
        assert report.failed == 0 and not report.timed_out
        fe.close()
        assert fe.open_connections() == 0, "leaked connections"
        assert c.recv() is None, "client must observe EOF after close"
        c.close()
        snap = metrics.snapshot()
        assert snap["frontend"]["connections_opened"] >= 1
        assert snap["drain"]["failed"] == 0

    def test_quarantine_re_op_degrades_scores_on_the_wire(self, stack):
        """The operator's degradation lever: after the quarantine op,
        the same record answers ok + degraded=true with the FE-only
        score (bitwise the batch scorer's FE-only path)."""
        recs, ds, lm, sm, batcher, metrics, fe = stack
        fe_only = type(lm)()
        fe_only.fixed_effects = dict(lm.fixed_effects)
        ref_full = batch_reference_scores(lm, ds)
        ref_fe = batch_reference_scores(fe_only, ds)
        c = Client(fe.port)
        try:
            before = c.ask(recs[0])
            assert before["status"] == "ok" and not before["degraded"]
            assert np.float32(before["score"]) == ref_full[0]
            bad = c.ask({"op": "quarantine_re", "re_type": "nope"})
            assert bad["error"] == "BAD_REQUEST"
            resp = c.ask({"op": "quarantine_re", "re_type": "userId"})
            assert resp["status"] == "ok" and resp["re_type"] == "userId"
            after = c.ask(recs[0])
            assert after["status"] == "ok" and after["degraded"] is True
            assert np.float32(after["score"]) == ref_fe[0]
        finally:
            c.close()

    def test_shed_and_deadline_surface_on_the_wire(self, rng):
        """Wire mapping of the admission outcomes: a deadlined request
        against a saturated queue answers status=shed; one that expires
        in the queue answers status=deadline_exceeded."""
        recs = synth_records(rng)
        from photon_ml_tpu.game.data import build_game_dataset

        ds = build_game_dataset(recs, SHARDS, ["userId"])
        bank = make_bank(synth_model(rng), ds)
        sm = ServingModel(bank, ServingPrograms((1, 8)))
        admission = AdmissionController()
        admission.note_dispatch(rows=1, busy_s=10.0)
        gate = threading.Lock()
        gate.acquire()
        metrics = ServingMetrics()
        batcher = MicroBatcher(
            sm.current, sm.programs, metrics,
            swap_lock=gate, admission=admission,
        )
        fe = ServingFrontend(
            batcher, sm, SHARDS, metrics=metrics, port=0
        ).start()
        c = Client(fe.port)
        try:
            # r0: claimed by the blocked dispatcher; r1 queues behind it
            c.send_line(recs[0])
            assert _wait_until(
                lambda: not batcher._queue and batcher._inflight
            )
            c.send_line(recs[1])
            assert _wait_until(lambda: len(batcher._queue) == 1)
            shed_req = dict(recs[2])
            shed_req["deadline_ms"] = 40.0
            resp = c.ask(shed_req)
            assert resp["status"] == "shed", resp
            assert resp["error"] == "SHED"
            expire_req = dict(recs[3])
            expire_req["deadline_ms"] = 1e9  # admitted…
            c.send_line(expire_req)
            assert _wait_until(lambda: len(batcher._queue) == 2)
            # …but its deadline (rewritten to the past) lapses in queue
            with batcher._lock:
                for q_req, _f in batcher._queue:
                    if q_req.uid == expire_req["uid"]:
                        q_req.deadline_ms = 0.5
            time.sleep(0.05)
            gate.release()
            got = {}
            for _ in range(3):
                r = c.recv()
                got[r["uid"]] = r
            assert got[recs[0]["uid"]]["status"] == "ok"
            assert got[recs[1]["uid"]]["status"] == "ok"
            assert got[expire_req["uid"]]["status"] == "deadline_exceeded"
        finally:
            c.close()
            batcher.drain(5.0)
            fe.stop_accepting()
            fe.close()
        snap = metrics.snapshot()
        assert snap["sheds"]["total"] == 1
        assert snap["deadline_expired"] == 1


def _save_fe_model(rng, tmp_path, recs):
    """A real on-disk GAME model dir + name-term lists WITHOUT training:
    an FE-only model over the trace's vocabulary."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.data import build_game_dataset
    from photon_ml_tpu.game.model import FixedEffectModel, GameModel
    from photon_ml_tpu.game.model_io import save_game_model
    from photon_ml_tpu.io.name_term_list import (
        save_name_and_term_feature_sets,
    )
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.glm import create_model
    from photon_ml_tpu.task import TaskType

    ds = build_game_dataset(recs, [SHARDS[0]], [])
    imap = ds.shards["g"].index_map
    w = np.asarray(
        np.linspace(-1.0, 1.0, imap.size), np.float32
    )
    gm = GameModel({
        "global": FixedEffectModel(
            create_model(
                TaskType.LOGISTIC_REGRESSION, Coefficients(jnp.asarray(w))
            ),
            "g",
        )
    })
    model_dir = str(tmp_path / "model")
    save_game_model(gm, ds, model_dir)
    nt_dir = str(tmp_path / "name-terms")
    save_name_and_term_feature_sets(
        {"features": {f"g{j}\t" for j in range(5)}}, nt_dir
    )
    return model_dir, nt_dir, ds, w


class TestReplayInterrupt:
    def test_sigterm_mid_replay_drains_and_keeps_partial_accounting(
        self, tmp_path, rng
    ):
        """Satellite 2, replay mode: SIGTERM mid-trace used to lose ALL
        accounting. Now the driver drains the batcher, writes the
        scores it completed, and metrics.json lands with
        interrupted=true + the outcome counts + the drain report."""
        from tests.conftest import game_example_schema

        from photon_ml_tpu.cli.serving_driver import (
            ServingDriver,
            params_from_args,
        )
        from photon_ml_tpu.io.avro_codec import (
            read_avro_records,
            write_container,
        )

        n = 3000
        recs = synth_records(rng, n=n)
        model_dir, _nt, _ds, _w = _save_fe_model(rng, tmp_path, recs)
        trace = tmp_path / "trace"
        trace.mkdir()
        write_container(
            str(trace / "part-0.avro"),
            game_example_schema(),
            [
                {
                    "uid": r["uid"],
                    "response": r["response"],
                    "metadataMap": r["metadataMap"],
                    "features": r["features"],
                    "userFeatures": r["userFeatures"],
                }
                for r in recs
            ],
        )
        out_dir = str(tmp_path / "out")
        driver = ServingDriver(params_from_args([
            "--game-model-input-dir", model_dir,
            "--output-dir", out_dir,
            "--request-paths", str(trace),
            "--feature-shard-id-to-feature-section-keys-map", "g:features",
            "--ladder", "1,8",
            "--drain-timeout", "10",
        ]))

        def killer():
            # fire once the replay is demonstrably mid-flight: the
            # latency counter only moves while requests complete
            assert _wait_until(
                lambda: driver.metrics is not None
                and driver.metrics.snapshot()["requests"] >= 20,
                timeout=60,
            )
            os.kill(os.getpid(), signal.SIGTERM)

        t = threading.Thread(target=killer)
        t.start()
        driver.run()
        t.join(timeout=10)
        assert driver.interrupted, "SIGTERM must mark the run interrupted"
        m = json.load(open(os.path.join(out_dir, "metrics.json")))
        assert m["interrupted"] is True
        ok = m["outcomes"]["ok"]
        assert 20 <= ok < n, (
            "partial accounting must cover exactly the completed slice"
        )
        assert m["drain"]["timed_out"] is False
        # the interrupt can land between a dispatch completing (latency
        # recorded) and the replay loop appending its outcome — at most
        # one request sits in that gap
        assert ok <= m["serving"]["requests"] <= ok + 1
        scored = list(
            read_avro_records(os.path.join(out_dir, "scores"))
        )
        assert len(scored) == ok


@pytest.mark.slow
class TestFrontendDriverEndToEnd:
    def test_sigterm_drains_and_writes_interrupted_metrics(
        self, tmp_path, rng
    ):
        """The full operating story, as ops would see it: boot the
        driver in front-end mode, read the published port, score real
        traffic over TCP (bitwise vs the model's margins), check
        status, then SIGTERM — the process drains within budget, exits
        0, and metrics.json records the interrupted run, the drain
        report, response counts and zero leaked connections."""
        recs = synth_records(rng, n=20)
        model_dir, nt_dir, ds, w = _save_fe_model(rng, tmp_path, recs)
        out_dir = str(tmp_path / "serve-out")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "photon_ml_tpu.cli.serving_driver",
                "--game-model-input-dir", model_dir,
                "--output-dir", out_dir,
                "--feature-shard-id-to-feature-section-keys-map",
                "g:features",
                "--feature-name-and-term-set-path", nt_dir,
                "--request-nnz-width", "g:6",
                "--frontend-port", "0",
                "--drain-timeout", "10",
                "--ladder", "1,8",
            ],
            cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            fj = os.path.join(out_dir, "frontend.json")
            assert _wait_until(
                lambda: os.path.exists(fj), timeout=120
            ), "front-end never published its port"
            port = json.load(open(fj))["port"]
            c = Client(port, timeout=30)
            status = c.ask({"op": "status"})
            assert status["ready"] is True and status["alive"] is True
            # margins through the same dataset the model was saved
            # against: the wire score must match w·x bitwise
            got = {}
            for i in range(10):
                resp = c.ask(recs[i])
                assert resp["status"] == "ok", resp
                got[resp["uid"]] = np.float32(resp["score"])
            # the bitwise reference is the BATCH scorer over the saved
            # artifact (numpy reductions differ from XLA's by a ulp)
            from photon_ml_tpu.game.model_io import load_game_model

            ref = batch_reference_scores(load_game_model(model_dir), ds)
            for i in range(10):
                assert got[recs[i]["uid"]] == np.float32(ref[i]), i
            c.close()
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=60)
            assert proc.returncode == 0, out[-4000:]
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        m = json.load(open(os.path.join(out_dir, "metrics.json")))
        assert m["interrupted"] is True
        assert m["mode"] == "frontend"
        assert m["leaked_connections"] == 0
        assert m["drain"]["timed_out"] is False
        assert m["frontend_completed"] == 10
        assert m["serving"]["responses"]["ok"] >= 10
        assert m["serving"]["frontend"]["connections_opened"] >= 1
        assert m["serving"]["dispatches"] >= 1
        assert "reliability" in m
